name := "spark-rapids-tpu-plugin"
version := "0.3.0-SNAPSHOT"
scalaVersion := "2.12.18"

libraryDependencies ++= Seq(
  "org.apache.spark" %% "spark-sql" % "3.5.1" % "provided",
  "org.apache.arrow" % "arrow-vector" % "14.0.2",
  "org.apache.arrow" % "arrow-memory-netty" % "14.0.2"
)
