/*
 * The JVM side of the JVM⇄TPU-worker boundary (SURVEY §7): a framed
 * socket client speaking the protocol spark_rapids_tpu/plugin/worker.py
 * serves — [4-byte big-endian length][payload] frames, a token
 * handshake as the first frame, one JSON request frame followed by one
 * Arrow IPC frame per shipped table, then a JSON reply (+ one Arrow
 * frame for execute results).
 *
 * Reference role: the JNI boundary of the CUDA plugin (device calls
 * into libcudf); here the "device" is a long-lived worker process that
 * owns the chip, so the boundary is a local socket instead of JNI.
 * The executable contract is tests/test_plugin.py (Python worker +
 * client) plus the golden fixtures under jvm-plugin/fixtures/ that pin
 * this client's and PlanSerializer's wire bytes.
 */
package org.tpurapids

import java.io.{BufferedInputStream, BufferedOutputStream, DataInputStream, DataOutputStream}
import java.net.Socket
import java.nio.charset.StandardCharsets

object ProtocolVersion {
  val Current: Long = 1L
}

case class Pong(version: Long)

case class WorkerException(errorClass: String, message: String)
  extends RuntimeException(s"$errorClass: $message")

object WorkerClient {
  /** The executor-wide shared client (set by TpuExecutorPlugin.init). */
  @volatile var shared: WorkerClient = _
}

class WorkerClient(host: String, port: Int, token: String) {
  private val sock = new Socket(host, port)
  sock.setTcpNoDelay(true)
  private val in = new DataInputStream(
    new BufferedInputStream(sock.getInputStream))
  private val out = new DataOutputStream(
    new BufferedOutputStream(sock.getOutputStream))
  // the worker unconditionally reads the first frame as the auth token
  // (plugin/worker.py _serve_conn) — a missing token must fail HERE with
  // a clear message, not as a silent desync on the first request
  require(token != null && token.nonEmpty,
    s"${TpuPluginConf.WorkerToken} is not set — the worker prints its " +
      "token at startup; pass it via spark conf")
  sendFrame(token.getBytes(StandardCharsets.UTF_8))

  // -- framing ------------------------------------------------------------

  private def sendFrame(payload: Array[Byte]): Unit = synchronized {
    out.writeInt(payload.length)
    out.write(payload)
    out.flush()
  }

  private def recvFrame(): Array[Byte] = {
    val n = in.readInt()
    val buf = new Array[Byte](n)
    in.readFully(buf)
    buf
  }

  private def jsonReply(): Json.V = {
    val head = Json.parse(new String(recvFrame(), StandardCharsets.UTF_8))
    head match {
      case Json.O(fields) if fields.toMap.get("type").contains(Json.S("error")) =>
        val m = fields.toMap
        throw WorkerException(
          m.get("error_class").collect { case Json.S(s) => s }.getOrElse("?"),
          m.get("message").collect { case Json.S(s) => s }.getOrElse(""))
      case v => v
    }
  }

  // -- requests -----------------------------------------------------------

  def ping(): Pong = synchronized {
    sendFrame("""{"type":"ping"}""".getBytes(StandardCharsets.UTF_8))
    jsonReply() match {
      case Json.O(fields) =>
        fields.toMap.get("version") match {
          case Some(Json.I(v)) => Pong(v)
          case _ => throw WorkerException("ProtocolError", "pong without version")
        }
      case _ => throw WorkerException("ProtocolError", "malformed pong")
    }
  }

  /** Execute a serialized plan against named Arrow IPC table payloads.
    * Returns (result IPC stream bytes, metrics).  The request's
    * "tables" list orders the Arrow frames that follow the header —
    * sorted by name, matching the Python reference client. */
  def execute(planJson: String, tables: Seq[(String, Array[Byte])],
              conf: Map[String, String] = Map.empty)
      : (Array[Byte], Map[String, Double]) = synchronized {
    sendRequest("execute", planJson, tables, conf)
    val head = jsonReply()
    val result = recvFrame()
    val metrics = head match {
      case Json.O(fields) =>
        fields.toMap.get("metrics") match {
          case Some(Json.O(ms)) => ms.collect {
            case (k, Json.I(v)) => k -> v.toDouble
            case (k, Json.D(v)) => k -> v
          }.toMap
          case _ => Map.empty[String, Double]
        }
      case _ => Map.empty[String, Double]
    }
    (result, metrics)
  }

  /** Ask the worker to run the overrides pipeline without executing:
    * returns (explain text, whole plan lands on device?). */
  def explain(planJson: String, tables: Seq[(String, Array[Byte])],
              conf: Map[String, String] = Map.empty)
      : (String, Boolean) = synchronized {
    sendRequest("explain", planJson, tables, conf)
    jsonReply() match {
      case Json.O(fields) =>
        val m = fields.toMap
        val text = m.get("text").collect { case Json.S(s) => s }.getOrElse("")
        val device = m.get("device").collect { case Json.B(b) => b }
          .getOrElse(false)
        (text, device)
      case _ => throw WorkerException("ProtocolError", "malformed explained")
    }
  }

  private def sendRequest(kind: String, planJson: String,
                          tables: Seq[(String, Array[Byte])],
                          conf: Map[String, String]): Unit = {
    val sorted = tables.sortBy(_._1)
    val header = Json.obj(
      "type" -> Json.s(kind),
      // the plan is already rendered JSON: splice it through verbatim
      "plan" -> Json.Raw(planJson),
      "tables" -> Json.arr(sorted.map(t => Json.s(t._1)): _*),
      "conf" -> Json.O(conf.toSeq.sortBy(_._1)
        .map { case (k, v) => k -> Json.s(v) })
    ).render
    sendFrame(header.getBytes(StandardCharsets.UTF_8))
    sorted.foreach { case (_, ipc) => sendFrame(ipc) }
  }

  def close(): Unit = {
    try sock.close() catch { case _: java.io.IOException => () }
  }
}
