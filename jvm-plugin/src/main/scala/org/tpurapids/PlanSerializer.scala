/*
 * Catalyst physical plan/expression -> the engine's JSON wire schema
 * (spark_rapids_tpu/plugin/protocol.py).  The encodable surface mirrors
 * the worker's expr_from_json/plan_from_json decoders; anything outside
 * it returns Left(reason) so TpuOverrideRule leaves that operator on
 * Spark with the reason logged (the RapidsMeta willNotWorkOnGpu
 * contract).
 */
package org.tpurapids

import scala.collection.mutable

import org.apache.spark.sql.catalyst.expressions._
import org.apache.spark.sql.catalyst.expressions.aggregate._
import org.apache.spark.sql.execution._
import org.apache.spark.sql.execution.aggregate.HashAggregateExec
import org.apache.spark.sql.execution.joins.{BroadcastHashJoinExec, ShuffledHashJoinExec, SortMergeJoinExec}
import org.apache.spark.sql.types._

/** Serialized subtree: protocol JSON + the leaf scans to ship as Arrow. */
case class SerializedPlan(json: String, inputs: Seq[SparkPlan])

object PlanSerializer {

  def trySerialize(plan: SparkPlan): Either[String, SerializedPlan] = {
    try {
      val inputs = mutable.ArrayBuffer[SparkPlan]()
      val json = planJson(plan, inputs)
      Right(SerializedPlan(Json.render(json), inputs.toSeq))
    } catch {
      case e: UnsupportedPlan => Left(e.getMessage)
    }
  }

  private final class UnsupportedPlan(msg: String) extends Exception(msg)
  private def bail(msg: String): Nothing = throw new UnsupportedPlan(msg)

  // ---- plans ----------------------------------------------------------

  private def planJson(p: SparkPlan,
                       inputs: mutable.ArrayBuffer[SparkPlan]): Json.V =
    p match {
      case ProjectExec(exprs, child) =>
        Json.obj(
          "op" -> Json.s("Project"),
          "exprs" -> Json.arr(exprs.map(e => exprJson(stripAlias(e))): _*),
          "names" -> Json.arr(exprs.map(e => Json.s(e.name)): _*),
          "child" -> planJson(child, inputs))
      case FilterExec(cond, child) =>
        Json.obj("op" -> Json.s("Filter"),
          "condition" -> exprJson(cond),
          "child" -> planJson(child, inputs))
      case agg: HashAggregateExec =>
        Json.obj("op" -> Json.s("Aggregate"),
          "keys" -> Json.arr(agg.groupingExpressions.map(exprJson): _*),
          "key_names" -> Json.arr(
            agg.groupingExpressions.map(e => Json.s(e.name)): _*),
          "aggs" -> Json.arr(agg.aggregateExpressions.map(aggJson): _*),
          "child" -> planJson(agg.child, inputs))
      case j: ShuffledHashJoinExec =>
        joinJson(j.joinType.sql, j.leftKeys, j.rightKeys, j.left, j.right,
                 broadcast = null, inputs)
      case j: SortMergeJoinExec =>
        // SMJ converts to the worker's hash join, as the reference's
        // GpuSortMergeJoinMeta does
        joinJson(j.joinType.sql, j.leftKeys, j.rightKeys, j.left, j.right,
                 broadcast = null, inputs)
      case j: BroadcastHashJoinExec =>
        joinJson(j.joinType.sql, j.leftKeys, j.rightKeys, j.left, j.right,
                 broadcast = "right", inputs)
      case s: SortExec =>
        Json.obj("op" -> Json.s("Sort"),
          "orders" -> Json.arr(s.sortOrder.map { so =>
            Json.arr(exprJson(so.child),
                     Json.b(so.direction == Ascending),
                     Json.b(so.nullOrdering == NullsFirst))
          }: _*),
          "global" -> Json.b(s.global),
          "child" -> planJson(s.child, inputs))
      case l: LocalLimitExec =>
        Json.obj("op" -> Json.s("Limit"), "n" -> Json.i(l.limit),
          "child" -> planJson(l.child, inputs))
      case g: GlobalLimitExec =>
        Json.obj("op" -> Json.s("Limit"), "n" -> Json.i(g.limit),
          "child" -> planJson(g.child, inputs))
      case u: UnionExec =>
        Json.obj("op" -> Json.s("Union"),
          "children" -> Json.arr(u.children.map(planJson(_, inputs)): _*))
      case leaf: LeafExecNode =>
        // any leaf (file scan, in-memory relation, reused exchange
        // output) ships as an Arrow table: record it and reference by
        // position (matches protocol.py's "t0", "t1", ... naming)
        val idx = inputs.indexWhere(_ eq leaf) match {
          case -1 => inputs += leaf; inputs.length - 1
          case i => i
        }
        Json.obj("op" -> Json.s("Scan"), "table" -> Json.s(s"t$idx"))
      case other =>
        bail(s"operator ${other.nodeName} has no TPU wire encoding")
    }

  private def joinJson(how: String, lk: Seq[Expression], rk: Seq[Expression],
                       left: SparkPlan, right: SparkPlan, broadcast: String,
                       inputs: mutable.ArrayBuffer[SparkPlan]): Json.V = {
    val howNorm = how.toLowerCase.replace(" ", "_") match {
      case "inner" => "inner"
      case "left_outer" | "leftouter" => "left_outer"
      case "right_outer" | "rightouter" => "right_outer"
      case "full_outer" | "fullouter" => "full_outer"
      case "left_semi" | "leftsemi" => "left_semi"
      case "left_anti" | "leftanti" => "left_anti"
      case "cross" => "cross"
      case o => bail(s"join type $o not supported")
    }
    Json.obj("op" -> Json.s("Join"), "how" -> Json.s(howNorm),
      "left_keys" -> Json.arr(lk.map(exprJson): _*),
      "right_keys" -> Json.arr(rk.map(exprJson): _*),
      "broadcast" -> (if (broadcast == null) Json.nul else Json.s(broadcast)),
      "left" -> planJson(left, inputs),
      "right" -> planJson(right, inputs))
  }

  // ---- expressions ----------------------------------------------------

  private def stripAlias(e: Expression): Expression = e match {
    case Alias(child, _) => child
    case other => other
  }

  /** Catalyst class name -> the worker's children-only class name. */
  private val childOnly: Map[Class[_], String] = Map(
    classOf[Add] -> "Add", classOf[Subtract] -> "Subtract",
    classOf[Multiply] -> "Multiply", classOf[Divide] -> "Divide",
    classOf[Remainder] -> "Remainder", classOf[UnaryMinus] -> "UnaryMinus",
    classOf[Abs] -> "Abs", classOf[EqualTo] -> "EqualTo",
    classOf[LessThan] -> "LessThan",
    classOf[LessThanOrEqual] -> "LessThanOrEqual",
    classOf[GreaterThan] -> "GreaterThan",
    classOf[GreaterThanOrEqual] -> "GreaterThanOrEqual",
    classOf[EqualNullSafe] -> "EqualNullSafe",
    classOf[And] -> "And", classOf[Or] -> "Or", classOf[Not] -> "Not",
    classOf[IsNull] -> "IsNull", classOf[IsNotNull] -> "IsNotNull",
    classOf[IsNaN] -> "IsNaN", classOf[Coalesce] -> "Coalesce",
    classOf[If] -> "If", classOf[Sqrt] -> "Sqrt", classOf[Exp] -> "Exp",
    classOf[Log] -> "Log", classOf[Floor] -> "Floor",
    classOf[Ceil] -> "Ceil", classOf[Pow] -> "Pow",
    classOf[Greatest] -> "Greatest", classOf[Least] -> "Least",
    classOf[Upper] -> "Upper", classOf[Lower] -> "Lower",
    classOf[Length] -> "Length", classOf[Concat] -> "Concat",
    classOf[Year] -> "Year", classOf[Month] -> "Month",
    classOf[DayOfMonth] -> "DayOfMonth", classOf[Hour] -> "Hour",
    classOf[Minute] -> "Minute", classOf[Second] -> "Second",
    classOf[DateAdd] -> "DateAdd", classOf[DateSub] -> "DateSub",
    classOf[DateDiff] -> "DateDiff")

  def exprJson(e: Expression): Json.V = e match {
    case a: AttributeReference =>
      Json.obj("e" -> Json.s("ColumnRef"), "name" -> Json.s(a.name))
    case Alias(child, _) => exprJson(child)
    case lit: Literal => literalJson(lit)
    case c: Cast =>
      Json.obj("e" -> Json.s("Cast"),
        "dtype" -> Json.s(typeString(c.dataType)),
        "child" -> exprJson(c.child))
    case in: In if in.list.forall(_.isInstanceOf[Literal]) =>
      Json.obj("e" -> Json.s("In"), "child" -> exprJson(in.value),
        "items" -> Json.arr(in.list.map(l =>
          literalValue(l.asInstanceOf[Literal])): _*))
    case cw: CaseWhen =>
      Json.obj("e" -> Json.s("CaseWhen"),
        "branches" -> Json.arr(cw.branches.map { case (c, v) =>
          Json.arr(exprJson(c), exprJson(v)) }: _*),
        "else" -> cw.elseValue.map(exprJson).getOrElse(Json.nul))
    case ss: Substring =>
      // the worker decodes pos/length as plain JSON numbers
      // (protocol.py expr_from_json "Substring"), not expression objects
      (ss.pos, ss.len) match {
        case (Literal(p, _: IntegralType), Literal(l, _: IntegralType))
            if p != null && l != null =>
          Json.obj("e" -> Json.s("Substring"), "child" -> exprJson(ss.str),
            "pos" -> Json.i(p.toString.toLong),
            "length" -> Json.i(l.toString.toLong))
        case _ => bail("Substring pos/length must be integer literals")
      }
    case sw: StartsWith =>
      needleJson("StartsWith", sw.left, sw.right)
    case ew: EndsWith => needleJson("EndsWith", ew.left, ew.right)
    case ct: Contains => needleJson("Contains", ct.left, ct.right)
    case other =>
      childOnly.get(other.getClass) match {
        case Some(name) =>
          Json.obj("e" -> Json.s(name),
            "children" -> Json.arr(other.children.map(exprJson): _*))
        case None =>
          bail(s"expression ${other.prettyName} has no TPU wire encoding")
      }
  }

  private def needleJson(name: String, subject: Expression,
                         needle: Expression): Json.V = needle match {
    case Literal(v, StringType) =>
      Json.obj("e" -> Json.s(name), "child" -> exprJson(subject),
        "needle" -> Json.s(v.toString))
    case _ => bail(s"$name needle must be a literal")
  }

  private def aggJson(ae: AggregateExpression): Json.V = {
    val (fn, child) = ae.aggregateFunction match {
      case Sum(c, _) => ("Sum", Some(c))
      case Count(Seq(Literal(1, _))) | Count(Nil) => ("Count", None)
      case Count(Seq(c)) => ("Count", Some(c))
      case Min(c) => ("Min", Some(c))
      case Max(c) => ("Max", Some(c))
      case Average(c, _) => ("Average", Some(c))
      case First(c, ignoreNulls) => ("First", Some(c))
      case Last(c, ignoreNulls) => ("Last", Some(c))
      case other => bail(s"aggregate ${other.prettyName} not encodable")
    }
    Json.obj("fn" -> Json.s(fn),
      "name" -> Json.s(ae.resultAttribute.name),
      "child" -> child.map(exprJson).getOrElse(Json.nul))
  }

  private def literalJson(lit: Literal): Json.V =
    Json.obj("e" -> Json.s("Literal"), "value" -> literalValue(lit),
      "dtype" -> Json.s(typeString(lit.dataType)))

  private def literalValue(lit: Literal): Json.V = lit.dataType match {
    case _ if lit.value == null => Json.nul
    case StringType => Json.s(lit.value.toString)
    case BooleanType => Json.b(lit.value.asInstanceOf[Boolean])
    case _: DecimalType =>
      // exact decimal transport (protocol.py: {"decimal": "<str>"});
      // a double here would silently round 38-digit values
      Json.obj("decimal" -> Json.s(lit.value.toString))
    case _: IntegralType => Json.i(lit.value.toString.toLong)
    case _: FractionalType => Json.d(lit.value.toString.toDouble)
    case DateType => Json.i(lit.value.toString.toLong)  // days since epoch
    case dt => bail(s"literal of type $dt not encodable")
  }

  private def typeString(dt: DataType): String = dt match {
    case BooleanType => "boolean"
    case ByteType => "tinyint"
    case ShortType => "smallint"
    case IntegerType => "int"
    case LongType => "bigint"
    case FloatType => "float"
    case DoubleType => "double"
    case StringType => "string"
    case DateType => "date"
    case TimestampType => "timestamp"
    case d: DecimalType => s"decimal(${d.precision},${d.scale})"
    case other => bail(s"type $other not encodable")
  }
}

/** Dependency-free minimal JSON rendering (the plugin shades nothing). */
object Json {
  sealed trait V { def render: String }
  case class S(v: String) extends V {
    def render: String = "\"" + v.flatMap {
      case '"' => "\\\""
      case '\\' => "\\\\"
      case '\n' => "\\n"
      case c if c < ' ' => f"\\u${c.toInt}%04x"
      case c => c.toString
    } + "\""
  }
  case class I(v: Long) extends V { def render: String = v.toString }
  case class D(v: Double) extends V { def render: String = v.toString }
  case class B(v: Boolean) extends V { def render: String = v.toString }
  case object Null extends V { def render: String = "null" }
  case class A(items: Seq[V]) extends V {
    def render: String = items.map(_.render).mkString("[", ",", "]")
  }
  case class O(fields: Seq[(String, V)]) extends V {
    def render: String =
      fields.map { case (k, v) => S(k).render + ":" + v.render }
        .mkString("{", ",", "}")
  }
  /** Pre-rendered JSON spliced through verbatim (e.g. a SerializedPlan's
    * payload embedded in a request header). */
  case class Raw(v: String) extends V { def render: String = v }
  def s(v: String): V = S(v)
  def i(v: Long): V = I(v)
  def d(v: Double): V = D(v)
  def b(v: Boolean): V = B(v)
  def nul: V = Null
  def arr(items: V*): V = A(items)
  def obj(fields: (String, V)*): V = O(fields)
  def render(v: V): String = v.render

  /** Minimal recursive-descent parser for worker replies (flat JSON of
    * strings/numbers/bools/objects — no dependency, mirrors render). */
  def parse(text: String): V = {
    val p = new Parser(text)
    val v = p.value()
    p.skipWs()
    require(p.eof, s"trailing JSON content at ${p.pos}")
    v
  }

  private final class Parser(s: String) {
    var pos = 0
    def eof: Boolean = pos >= s.length
    def skipWs(): Unit = {
      while (!eof && Character.isWhitespace(s.charAt(pos))) pos += 1
    }
    private def expect(c: Char): Unit = {
      skipWs()
      require(!eof && s.charAt(pos) == c,
        s"expected '$c' at $pos in ${s.take(80)}")
      pos += 1
    }
    def value(): V = {
      skipWs()
      require(!eof, "unexpected end of JSON")
      s.charAt(pos) match {
        case '{' => obj()
        case '[' => arr()
        case '"' => S(string())
        case 't' => lit("true", B(true))
        case 'f' => lit("false", B(false))
        case 'n' => lit("null", Null)
        case _ => number()
      }
    }
    private def lit(word: String, v: V): V = {
      require(s.regionMatches(pos, word, 0, word.length),
        s"bad literal at $pos")
      pos += word.length
      v
    }
    private def obj(): V = {
      expect('{')
      val fields = scala.collection.mutable.ArrayBuffer[(String, V)]()
      skipWs()
      if (!eof && s.charAt(pos) == '}') { pos += 1; return O(fields.toSeq) }
      while (true) {
        skipWs()
        val k = string()
        expect(':')
        fields += (k -> value())
        skipWs()
        if (!eof && s.charAt(pos) == ',') pos += 1
        else { expect('}'); return O(fields.toSeq) }
      }
      O(fields.toSeq)
    }
    private def arr(): V = {
      expect('[')
      val items = scala.collection.mutable.ArrayBuffer[V]()
      skipWs()
      if (!eof && s.charAt(pos) == ']') { pos += 1; return A(items.toSeq) }
      while (true) {
        items += value()
        skipWs()
        if (!eof && s.charAt(pos) == ',') pos += 1
        else { expect(']'); return A(items.toSeq) }
      }
      A(items.toSeq)
    }
    private def string(): String = {
      expect('"')
      val sb = new StringBuilder
      while (true) {
        require(!eof, "unterminated string")
        val c = s.charAt(pos)
        pos += 1
        c match {
          case '"' => return sb.toString
          case '\\' =>
            require(!eof, "unterminated escape")
            val e = s.charAt(pos); pos += 1
            e match {
              case '"' => sb += '"'
              case '\\' => sb += '\\'
              case '/' => sb += '/'
              case 'n' => sb += '\n'
              case 't' => sb += '\t'
              case 'r' => sb += '\r'
              case 'b' => sb += '\b'
              case 'f' => sb += '\f'
              case 'u' =>
                sb += Integer.parseInt(s.substring(pos, pos + 4), 16).toChar
                pos += 4
              case other => sb += other
            }
          case other => sb += other
        }
      }
      sb.toString
    }
    private def number(): V = {
      val start = pos
      while (!eof && "+-0123456789.eE".indexOf(s.charAt(pos)) >= 0) pos += 1
      val text = s.substring(start, pos)
      require(text.nonEmpty, s"bad JSON value at $start")
      if (text.exists(c => c == '.' || c == 'e' || c == 'E')) D(text.toDouble)
      else I(text.toLong)
    }
  }
}
