/*
 * The executed TPU operator: a SparkPlan node standing in for a claimed
 * subtree (reference role: GpuExec + the transition execs,
 * GpuTransitionOverrides) — its children are the claimed subtree's
 * leaves (which Spark executes normally), its runtime ships the
 * serialized plan + the children's output as Arrow to the executor's
 * TPU worker and decodes the result stream back into rows.
 *
 * Execution shape (v1 data plane): the shipped subtree runs on ONE
 * worker, so the input partitions gather onto a single partition first
 * (coalesce(1)) — the Spark-side scale-out story is the worker's own
 * distributed mesh (SURVEY §2.7: the engine shards one plan over the
 * chip mesh), not many workers per query.  Output partitioning is
 * therefore SinglePartition.
 */
package org.tpurapids

import org.apache.spark.rdd.RDD
import org.apache.spark.sql.catalyst.InternalRow
import org.apache.spark.sql.catalyst.expressions.Attribute
import org.apache.spark.sql.catalyst.plans.physical.{Partitioning, SinglePartition}
import org.apache.spark.sql.execution.SparkPlan
import org.apache.spark.sql.types.StructType

case class TpuExec(original: SparkPlan, payload: SerializedPlan)
    extends SparkPlan {

  override def output: Seq[Attribute] = original.output

  override def children: Seq[SparkPlan] = payload.inputs

  override def outputPartitioning: Partitioning = SinglePartition

  override def nodeName: String = "TpuExec"

  override def simpleString(maxFields: Int): String =
    s"TpuExec [${original.nodeName}] (${payload.inputs.length} inputs)"

  override protected def withNewChildrenInternal(
      newChildren: IndexedSeq[SparkPlan]): SparkPlan =
    copy(payload = payload.copy(inputs = newChildren))

  override protected def doExecute(): RDD[InternalRow] = {
    val planJson = payload.json
    val schemas: Seq[StructType] = children.map(_.schema)
    val confMap: Map[String, String] = {
      val c = conf
      Seq(TpuPluginConf.SqlEnabled, TpuPluginConf.Explain)
        .flatMap(k => c.getAllConfs.get(k).map(k -> _)).toMap
    }

    // each input partition encodes itself to one (inputIdx, ipcBytes)
    val frames: Seq[RDD[(Int, Array[Byte])]] =
      children.zipWithIndex.map { case (child, idx) =>
        val schema = schemas(idx)
        child.execute().mapPartitions { rows =>
          Iterator((idx, ArrowCodec.toIpc(rows, schema)))
        }
      }

    sparkContext.union(frames).coalesce(1).mapPartitions { it =>
      val byInput = scala.collection.mutable.Map[
        Int, scala.collection.mutable.ArrayBuffer[Array[Byte]]]()
      it.foreach { case (i, b) =>
        byInput.getOrElseUpdate(
          i, scala.collection.mutable.ArrayBuffer[Array[Byte]]()) += b
      }
      val tables = schemas.indices.map { i =>
        val parts = byInput.get(i).map(_.toSeq).getOrElse(Seq.empty)
        (s"t$i", ArrowCodec.concatIpc(parts, schemas(i)))
      }
      val client = WorkerClient.shared
      require(client != null,
        "TPU worker client not initialized on this executor " +
          "(TpuExecutorPlugin.init did not run?)")
      val (resultIpc, _) = client.execute(planJson, tables, confMap)
      ArrowCodec.fromIpc(resultIpc)
    }
  }
}
