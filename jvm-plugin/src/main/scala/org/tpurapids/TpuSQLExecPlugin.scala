/*
 * SparkSessionExtensions injection (reference SQLExecPlugin.scala:27-43):
 * install the columnar override rule so every physical plan (and every
 * AQE query stage) passes through the TPU overrides.
 */
package org.tpurapids

import org.apache.spark.internal.Logging
import org.apache.spark.sql.SparkSessionExtensions
import org.apache.spark.sql.catalyst.rules.Rule
import org.apache.spark.sql.execution.{ColumnarRule, SparkPlan}

class TpuSQLExecPlugin extends (SparkSessionExtensions => Unit) with Logging {
  override def apply(ext: SparkSessionExtensions): Unit = {
    ext.injectColumnar(_ => new TpuColumnarRule)
    logInfo("spark-rapids-tpu columnar rule injected")
  }
}

class TpuColumnarRule extends ColumnarRule {
  // pre-columnar-transitions: the wrap/tag/convert pass
  override def preColumnarTransitions: Rule[SparkPlan] = new TpuOverrideRule
  // post-columnar-transitions: nothing extra — TpuExec produces rows
  // directly (the worker returns Arrow; row conversion happens at the
  // exec boundary), so Spark's own transitions suffice.
  override def postColumnarTransitions: Rule[SparkPlan] =
    new Rule[SparkPlan] { override def apply(p: SparkPlan): SparkPlan = p }
}
