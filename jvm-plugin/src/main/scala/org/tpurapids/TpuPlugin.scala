/*
 * The SparkPlugin pair: driver + executor lifecycle for the TPU engine.
 *
 * Reference roles: RapidsDriverPlugin (sql-plugin Plugin.scala:426-491,
 * config fixups + conf broadcast) and RapidsExecutorPlugin
 * (Plugin.scala:496-576, device init / health checks / fatal-error
 * executor self-termination).  The CUDA-era device bring-up maps to
 * launching (or attaching to) the long-lived TPU worker process that
 * owns the chip for this executor; the JNI boundary maps to the framed
 * socket protocol in WorkerClient.scala.
 */
package org.tpurapids

import java.util.{Map => JMap}
import scala.collection.JavaConverters._

import org.apache.spark.SparkContext
import org.apache.spark.api.plugin.{DriverPlugin, ExecutorPlugin, PluginContext, SparkPlugin}
import org.apache.spark.internal.Logging
import org.apache.spark.sql.internal.StaticSQLConf

class TpuPlugin extends SparkPlugin {
  override def driverPlugin(): DriverPlugin = new TpuDriverPlugin
  override def executorPlugin(): ExecutorPlugin = new TpuExecutorPlugin
}

object TpuPluginConf {
  val WorkerAddress = "spark.tpurapids.worker.address"
  val WorkerToken = "spark.tpurapids.worker.token"
  val WorkerLaunch = "spark.tpurapids.worker.autoLaunch"
  val SqlEnabled = "spark.tpurapids.sql.enabled"
  val Explain = "spark.tpurapids.sql.explain"
}

class TpuDriverPlugin extends DriverPlugin with Logging {
  override def init(sc: SparkContext, ctx: PluginContext): JMap[String, String] = {
    // fixupConfigsOnDriver role (Plugin.scala:457): force the SQL
    // extension in so the ColumnarRule is installed for every session.
    val extKey = StaticSQLConf.SPARK_SESSION_EXTENSIONS.key
    val ext = sc.conf.getOption(extKey)
    val ours = classOf[TpuSQLExecPlugin].getName
    ext match {
      case Some(v) if v.contains(ours) => ()
      case Some(v) => sc.conf.set(extKey, s"$v,$ours")
      case None => sc.conf.set(extKey, ours)
    }
    logInfo(s"spark-rapids-tpu driver plugin initialized; extensions=$ours")
    // broadcast the worker coordinates to executors (the conf-map hop
    // RapidsDriverPlugin.init returns, Plugin.scala:480)
    Map(
      TpuPluginConf.WorkerAddress ->
        sc.conf.get(TpuPluginConf.WorkerAddress, "127.0.0.1:9779"),
      TpuPluginConf.WorkerToken ->
        sc.conf.get(TpuPluginConf.WorkerToken, "")
    ).asJava
  }
}

class TpuExecutorPlugin extends ExecutorPlugin with Logging {
  @volatile private var client: WorkerClient = _

  override def init(ctx: PluginContext, extraConf: JMap[String, String]): Unit = {
    val addr = extraConf.get(TpuPluginConf.WorkerAddress)
    val token = extraConf.get(TpuPluginConf.WorkerToken)
    val Array(host, port) = addr.split(":")
    // Device bring-up (GpuDeviceManager.initializeGpuAndMemory role):
    // attach to the executor's TPU worker and health-check it.  A worker
    // that cannot be reached is the CudaException analogue — fail fast
    // so Spark replaces the executor (Plugin.scala:566-575).
    client = new WorkerClient(host, port.toInt, token)
    val pong = client.ping()
    require(pong.version == ProtocolVersion.Current,
      s"worker protocol ${pong.version} != ${ProtocolVersion.Current}")
    WorkerClient.shared = client
    logInfo(s"attached to TPU worker at $addr (protocol v${pong.version})")
  }

  override def shutdown(): Unit = {
    if (client != null) client.close()
  }
}
