/*
 * InternalRow ⇄ Arrow IPC stream conversion at the Spark boundary — the
 * data plane of the plugin (reference role: GpuColumnVector.java:1-1105
 * ColumnarBatch⇄device-column bridging + JCudfSerialization).  The
 * worker speaks whole Arrow IPC streams per table, so the JVM side
 * encodes each claimed subtree's input partitions into one stream and
 * decodes the result stream back into rows.
 *
 * Deliberately dependency-light: plain arrow-vector (the only non-
 * provided dependency), no private[sql] Spark internals, covering the
 * flat type surface PlanSerializer encodes (bool, 8/16/32/64-bit ints,
 * float/double, string, date, timestamp, decimal128).
 */
package org.tpurapids

import java.io.{ByteArrayInputStream, ByteArrayOutputStream}
import java.nio.channels.Channels
import java.nio.charset.StandardCharsets

import scala.collection.JavaConverters._
import scala.collection.mutable.ArrayBuffer

import org.apache.arrow.memory.{BufferAllocator, RootAllocator}
import org.apache.arrow.vector._
import org.apache.arrow.vector.ipc.{ArrowStreamReader, ArrowStreamWriter}
import org.apache.arrow.vector.types.{DateUnit, FloatingPointPrecision, TimeUnit => ArrowTimeUnit}
import org.apache.arrow.vector.types.pojo.{ArrowType, Field, FieldType, Schema}

import org.apache.spark.sql.catalyst.InternalRow
import org.apache.spark.sql.catalyst.expressions.GenericInternalRow
import org.apache.spark.sql.types._
import org.apache.spark.unsafe.types.UTF8String

object ArrowCodec {

  lazy val allocator: BufferAllocator =
    new RootAllocator(Long.MaxValue)

  // -- schema mapping -----------------------------------------------------

  def arrowField(name: String, dt: DataType, nullable: Boolean): Field = {
    val at: ArrowType = dt match {
      case BooleanType => ArrowType.Bool.INSTANCE
      case ByteType => new ArrowType.Int(8, true)
      case ShortType => new ArrowType.Int(16, true)
      case IntegerType => new ArrowType.Int(32, true)
      case LongType => new ArrowType.Int(64, true)
      case FloatType =>
        new ArrowType.FloatingPoint(FloatingPointPrecision.SINGLE)
      case DoubleType =>
        new ArrowType.FloatingPoint(FloatingPointPrecision.DOUBLE)
      case StringType => ArrowType.Utf8.INSTANCE
      case DateType => new ArrowType.Date(DateUnit.DAY)
      case TimestampType =>
        new ArrowType.Timestamp(ArrowTimeUnit.MICROSECOND, "UTC")
      case d: DecimalType => new ArrowType.Decimal(d.precision, d.scale, 128)
      case other =>
        throw new UnsupportedOperationException(
          s"type $other has no Arrow wire mapping")
    }
    new Field(name, new FieldType(nullable, at, null), null)
  }

  def arrowSchema(schema: StructType): Schema =
    new Schema(schema.fields.map(f =>
      arrowField(f.name, f.dataType, f.nullable)).toList.asJava)

  // -- rows -> IPC stream -------------------------------------------------

  /** Encode rows into one Arrow IPC stream (schema + batches). */
  def toIpc(rows: Iterator[InternalRow], schema: StructType,
            batchRows: Int = 1 << 16): Array[Byte] = {
    val root = VectorSchemaRoot.create(arrowSchema(schema), allocator)
    val out = new ByteArrayOutputStream()
    val writer = new ArrowStreamWriter(root, null, Channels.newChannel(out))
    try {
      writer.start()
      val fields = schema.fields
      while (rows.hasNext) {
        var n = 0
        while (rows.hasNext && n < batchRows) {
          val row = rows.next()
          var c = 0
          while (c < fields.length) {
            writeValue(root.getVector(c), n, row, c, fields(c).dataType)
            c += 1
          }
          n += 1
        }
        root.setRowCount(n)
        writer.writeBatch()
        root.allocateNew()
      }
      writer.end()
    } finally {
      root.close()
    }
    out.toByteArray
  }

  private def writeValue(v: FieldVector, i: Int, row: InternalRow,
                         c: Int, dt: DataType): Unit = {
    if (row.isNullAt(c)) {
      v match {
        case x: BitVector => x.setNull(i)
        case x: TinyIntVector => x.setNull(i)
        case x: SmallIntVector => x.setNull(i)
        case x: IntVector => x.setNull(i)
        case x: BigIntVector => x.setNull(i)
        case x: Float4Vector => x.setNull(i)
        case x: Float8Vector => x.setNull(i)
        case x: VarCharVector => x.setNull(i)
        case x: DateDayVector => x.setNull(i)
        case x: TimeStampMicroTZVector => x.setNull(i)
        case x: DecimalVector => x.setNull(i)
        case other => throw new UnsupportedOperationException(
          s"null write for ${other.getClass}")
      }
      return
    }
    (v, dt) match {
      case (x: BitVector, BooleanType) =>
        x.setSafe(i, if (row.getBoolean(c)) 1 else 0)
      case (x: TinyIntVector, ByteType) => x.setSafe(i, row.getByte(c))
      case (x: SmallIntVector, ShortType) => x.setSafe(i, row.getShort(c))
      case (x: IntVector, IntegerType) => x.setSafe(i, row.getInt(c))
      case (x: BigIntVector, LongType) => x.setSafe(i, row.getLong(c))
      case (x: Float4Vector, FloatType) => x.setSafe(i, row.getFloat(c))
      case (x: Float8Vector, DoubleType) => x.setSafe(i, row.getDouble(c))
      case (x: VarCharVector, StringType) =>
        x.setSafe(i, row.getUTF8String(c).getBytes)
      case (x: DateDayVector, DateType) => x.setSafe(i, row.getInt(c))
      case (x: TimeStampMicroTZVector, TimestampType) =>
        x.setSafe(i, row.getLong(c))
      case (x: DecimalVector, d: DecimalType) =>
        x.setSafe(i, row.getDecimal(c, d.precision, d.scale)
          .toJavaBigDecimal)
      case (other, t) => throw new UnsupportedOperationException(
        s"write of $t into ${other.getClass}")
    }
  }

  // -- IPC stream -> rows -------------------------------------------------

  /** Decode one Arrow IPC stream into rows (column order positional).
    *
    * STREAMING: rows decode batch-by-batch as the iterator drains, so a
    * large result never materializes twice in memory (each value is
    * copied out of the Arrow vectors into its row before the next batch
    * overwrites them; VarCharVector.get already returns fresh bytes).
    * The reader closes itself at exhaustion. */
  def fromIpc(bytes: Array[Byte]): Iterator[InternalRow] = {
    val reader = new ArrowStreamReader(
      new ByteArrayInputStream(bytes), allocator)
    val root = reader.getVectorSchemaRoot
    val it = new Iterator[InternalRow] {
      private[ArrowCodec] var closed = false
      private var vectors: Array[FieldVector] = Array.empty
      private var count = 0
      private var i = 0

      private[ArrowCodec] def closeNow(): Unit =
        if (!closed) { closed = true; reader.close() }

      private def advance(): Unit = {
        while (!closed && i >= count) {
          val loaded = try reader.loadNextBatch() catch {
            case e: Throwable => closeNow(); throw e
          }
          if (loaded) {
            vectors = root.getFieldVectors.asScala.toArray
            count = root.getRowCount
            i = 0
          } else {
            closeNow()
          }
        }
      }

      override def hasNext: Boolean = { advance(); !closed }

      override def next(): InternalRow = {
        advance()
        if (closed) throw new NoSuchElementException("drained IPC stream")
        val vals = new Array[Any](vectors.length)
        var c = 0
        while (c < vectors.length) {
          vals(c) = readValue(vectors(c), i)
          c += 1
        }
        i += 1
        new GenericInternalRow(vals)
      }
    }
    // a partially consumed iterator (limit/take, downstream exception)
    // must not leak the reader's direct memory: inside a task, close at
    // task end; outside one (driver/tests) keep the old eager-drain
    // contract so abandonment can never leak
    Option(org.apache.spark.TaskContext.get()) match {
      case Some(tc) =>
        tc.addTaskCompletionListener[Unit](_ => it.closeNow())
        it
      case None =>
        try it.toArray.iterator finally it.closeNow()
    }
  }

  private def readValue(v: FieldVector, i: Int): Any = {
    if (v.isNull(i)) return null
    v match {
      case x: BitVector => x.get(i) != 0
      case x: TinyIntVector => x.get(i)
      case x: SmallIntVector => x.get(i)
      case x: IntVector => x.get(i)
      case x: BigIntVector => x.get(i)
      case x: Float4Vector => x.get(i)
      case x: Float8Vector => x.get(i)
      case x: VarCharVector => UTF8String.fromBytes(x.get(i))
      case x: DateDayVector => x.get(i)
      case x: TimeStampMicroTZVector => x.get(i)
      case x: DecimalVector =>
        val bd = x.getObject(i).asInstanceOf[java.math.BigDecimal]
        Decimal(bd)
      case other => throw new UnsupportedOperationException(
        s"read from ${other.getClass}")
    }
  }

  // -- stream concat ------------------------------------------------------

  /** Merge several IPC streams that share `schema` into one stream (the
    * per-partition payloads of one input gathered on the exec's single
    * partition); zero streams produce a schema-only empty stream. */
  def concatIpc(parts: Seq[Array[Byte]], schema: StructType): Array[Byte] = {
    if (parts.length == 1) return parts.head
    if (parts.isEmpty) return toIpc(Iterator.empty, schema)
    // concatenate at the RECORD-BATCH level: each part's batches are
    // unloaded and re-framed into one stream without a row-object round
    // trip (the previous decode+re-encode doubled memory and CPU at the
    // single-partition gather — ADVICE r4)
    import org.apache.arrow.vector.{VectorLoader, VectorUnloader}
    val out = new ByteArrayOutputStream()
    val outRoot = VectorSchemaRoot.create(arrowSchema(schema), allocator)
    try {
      val writer = new ArrowStreamWriter(
        outRoot, null, Channels.newChannel(out))
      writer.start()
      val loader = new VectorLoader(outRoot)
      parts.foreach { bytes =>
        val reader = new ArrowStreamReader(
          new ByteArrayInputStream(bytes), allocator)
        try {
          val inRoot = reader.getVectorSchemaRoot
          val unloader = new VectorUnloader(inRoot)
          while (reader.loadNextBatch()) {
            val rb = unloader.getRecordBatch
            try {
              loader.load(rb)
              writer.writeBatch()
            } finally {
              rb.close()
            }
          }
        } finally {
          reader.close()
        }
      }
      writer.end()
    } finally {
      outRoot.close()
    }
    out.toByteArray
  }
}
