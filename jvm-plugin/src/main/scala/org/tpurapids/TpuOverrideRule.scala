/*
 * The plan-walking override rule (reference GpuOverrides.scala:4563-4720
 * applyWithContext/applyOverrides + RapidsMeta tagging).
 *
 * Strategy: find the LARGEST subtrees whose every operator and
 * expression PlanSerializer can encode, replace each with a TpuExec leaf
 * that ships the serialized subtree (plus its input tables as Arrow) to
 * the worker, and leave everything else on Spark with a logged reason —
 * per-operator fallback, never whole-query.
 */
package org.tpurapids

import org.apache.spark.internal.Logging
import org.apache.spark.sql.catalyst.rules.Rule
import org.apache.spark.sql.execution._
import org.apache.spark.sql.execution.exchange.Exchange

class TpuOverrideRule extends Rule[SparkPlan] with Logging {

  override def apply(plan: SparkPlan): SparkPlan = {
    val conf = plan.conf
    if (!conf.getConfString(TpuPluginConf.SqlEnabled, "true").toBoolean) {
      return plan
    }
    val explain = conf.getConfString(TpuPluginConf.Explain, "NONE")
    convert(plan, explain)
  }

  /** Bottom-up: children first, then try to claim this node.  A node is
    * claimable when PlanSerializer encodes it AND all its children were
    * claimed (contiguous device subtrees, the doConvertPlan rule). */
  private def convert(plan: SparkPlan, explain: String): SparkPlan = {
    plan match {
      case _: Exchange =>
        // exchanges stay on Spark: the shuffle boundary is where the
        // worker's own distributed exchange takes over (SURVEY §2.7)
        plan.withNewChildren(plan.children.map(convert(_, explain)))
      case _ =>
        PlanSerializer.trySerialize(plan) match {
          case Right(payload) =>
            TpuExec(plan, payload)
          case Left(reason) =>
            if (explain != "NONE") {
              logWarning(s"!Exec <${plan.nodeName}> cannot run on TPU " +
                s"because $reason")
            }
            plan.withNewChildren(plan.children.map(convert(_, explain)))
        }
    }
  }
}
