#!/usr/bin/env python
"""Decompose a query's warm wall time: device execute vs host fetch.

Usage: python scripts/decompose.py q16 [scale]
Prints: warm wall, execute-only (dispatch+device, synced via scalar),
fetch-only, output capacities/rows/bytes — the numbers docs/PERF.md
needs to attribute tunnel cost vs device cost.
"""
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_compilation_cache_dir", _REPO + "/.jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

qname = sys.argv[1] if len(sys.argv) > 1 else "q16"
scale = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0

from spark_rapids_tpu import tpch
from spark_rapids_tpu.exec.compiled import CompiledPlan, _find_split_seams, SplitCompiledPlan
from spark_rapids_tpu.exec.plan import ExecContext
from spark_rapids_tpu.session import TpuSession

tables = tpch.gen_tables(scale=scale)
dev = TpuSession()
q = tpch.QUERIES[qname](dev, tables).physical()
ctx = ExecContext(dev.conf)

t0 = time.perf_counter()
out = q.collect(ctx)
print(f"cold+upload: {time.perf_counter()-t0:.1f}s", flush=True)
for i in range(2):
    t0 = time.perf_counter()
    out = q.collect(ctx)
    print(f"warm wall{i}: {time.perf_counter()-t0:.2f}s ({out.num_rows} rows)",
          flush=True)

plan = getattr(q, "_compiled_plan", None)
print(f"plan type: {type(plan).__name__}")
if isinstance(plan, CompiledPlan):
    t0 = time.perf_counter()
    outs = plan.execute(ctx)
    # force device completion with ONE tiny fetch
    first = outs[0]
    _ = jax.device_get(first.columns[0].data.ravel()[0])
    t_exec = time.perf_counter() - t0
    tot = 0
    for db in outs:
        cap = db.capacity
        nb = db.nbytes() if hasattr(db, "nbytes") else -1
        n = db.num_rows if isinstance(db.num_rows, int) else "dev"
        print(f"  out batch: cap={cap} rows={n} bytes={nb}")
        tot += nb
    t0 = time.perf_counter()
    from spark_rapids_tpu.columnar.device import to_host
    hbs = [to_host(db) for db in outs]
    t_fetch = time.perf_counter() - t0
    print(f"execute+sync: {t_exec:.2f}s  fetch: {t_fetch:.2f}s  "
          f"out_bytes={tot/1e6:.1f}MB", flush=True)
elif isinstance(plan, SplitCompiledPlan):
    # time each segment
    import spark_rapids_tpu.exec.compiled as C
    t0 = time.perf_counter()
    out = plan.collect(ctx)
    print(f"split collect: {time.perf_counter()-t0:.2f}s; "
          f"segments={len(plan.seams)+1}")
