#!/usr/bin/env python
"""Profile one TPC-H query's warm whole-plan run on the real chip.

Usage: python scripts/profile_q3.py [query] [scale]
Writes a profiler trace to /tmp/jaxprof (open the xplane.pb with
tensorboard_plugin_profile, or parse it directly — see git history for
a snippet) and prints cold/warm timings.
"""
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_compilation_cache_dir", _REPO + "/.jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

qname = sys.argv[1] if len(sys.argv) > 1 else "q3"
scale = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0

from spark_rapids_tpu import tpch
from spark_rapids_tpu.exec.plan import ExecContext
from spark_rapids_tpu.session import TpuSession

t0 = time.perf_counter()
tables = tpch.gen_tables(scale=scale)
print(f"datagen {time.perf_counter()-t0:.1f}s", file=sys.stderr)

dev = TpuSession()
dfq = tpch.QUERIES[qname](dev, tables)
q = dfq.physical()

t0 = time.perf_counter()
out = q.collect(ExecContext(dev.conf))
print(f"cold: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

for i in range(2):
    t0 = time.perf_counter()
    out = q.collect(ExecContext(dev.conf))
    print(f"warm{i}: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

import shutil
shutil.rmtree("/tmp/jaxprof", ignore_errors=True)
with jax.profiler.trace("/tmp/jaxprof"):
    t0 = time.perf_counter()
    out = q.collect(ExecContext(dev.conf))
    wall = time.perf_counter() - t0
print(f"profiled warm: {wall:.2f}s", file=sys.stderr)
print(out.to_pydict() if out.num_rows < 5 else f"{out.num_rows} rows",
      file=sys.stderr)
