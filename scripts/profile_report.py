#!/usr/bin/env python
"""Offline query-profile reports from JSONL event logs — the RAPIDS
profiling-tool analogue (SURVEY §5).

Input: one `query_<id>.jsonl` written under `spark.rapids.tpu.eventLog.dir`,
or a directory of them.  For each log it renders the QueryProfile: the
compile/execute/transition/shuffle wall split, the per-node-id operator
table (top operators by self time), per-SEGMENT measured device time
(runs with `spark.rapids.tpu.profile.segments` on), data-movement bytes,
memory high-water, runtime incidents (OOM retries / splits / spills) and
the fallback summary.  The sibling `query_<id>.trace.json` opens directly
in perfetto (https://ui.perfetto.dev) or chrome://tracing.

MULTICHIP/BENCH records (`MULTICHIP_r*.json`, bench final lines, driver
wrappers — including legacy dry-run tails whose last line is a python
repr) are rendered too: the `mc:`-keyed timings, the embedded per-round
exchange timelines and per-query mesh records.

`--mesh` expands the per-round mesh exchange timeline (round quotas,
wire bytes pre/post compress, per-device arrivals, staging vs
collective ms) for every input that carries one.

Usage:
    python scripts/profile_report.py <event_log.jsonl | record.json | dir>
                                     [--json] [--mesh]
"""
import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log_paths(target: str) -> list:
    if os.path.isdir(target):
        paths = sorted(glob.glob(os.path.join(target, "*.jsonl")) +
                       glob.glob(os.path.join(target, "*.json")))
        paths = [p for p in paths if not p.endswith(".trace.json")]
        if not paths:
            raise SystemExit(f"no *.jsonl / *.json records under {target}")
        return paths
    if not os.path.exists(target):
        raise SystemExit(f"no such file: {target}")
    return [target]


def render_mesh_timeline(tl: dict, indent: str = "  ") -> list:
    """Expanded per-round mesh timeline lines (--mesh)."""
    lines = []
    for ex in tl.get("exchanges", []):
        if ex.get("kind") == "dict_gather":
            lines.append(f"{indent}dict_gather t={ex.get('t_ms', 0)}ms "
                         f"bytes={ex.get('bytes', 0)}")
            continue
        hbm = ""
        if ex.get("slab_bytes") or ex.get("recv_buffer_bytes"):
            hbm = (f" slab={ex.get('slab_bytes', 0)}B "
                   f"recv_buf={ex.get('recv_buffer_bytes', 0)}B")
        lines.append(
            f"{indent}exchange t={ex.get('t_ms', 0)}ms "
            f"rounds={ex.get('rounds', 0)} quota={ex.get('quota', 0)} "
            f"wire={ex.get('bytes', 0)}B "
            f"(pre-compress {ex.get('bytes_pre_compress', 0)}B) "
            f"recv_cap={ex.get('recv_cap', 0)}{hbm} "
            f"arrivals={ex.get('arrivals', '?')}")
        for r in ex.get("round_events", []):
            lines.append(
                f"{indent}  round {r.get('r')}: "
                f"stage={r.get('stage_ms', '?')}ms "
                f"collective={r.get('collective_ms', '?')}ms")
    for sp in tl.get("skew_splits", []):
        lines.append(f"{indent}skew_split t={sp.get('t_ms', 0)}ms "
                     f"per_shard_in={sp.get('per_shard_in', '?')}")
    if tl.get("ici_exchange_bytes"):
        lines.append(f"{indent}ici bytes attributed: "
                     f"{tl['ici_exchange_bytes']}")
    return lines


def kernel_section(registry: dict) -> list:
    """Rendered lines for the Pallas kernel-tier metric families
    (`tpu_kernel_dispatch_total` / `tpu_kernel_fallback_total`) found
    in a compact registry snapshot — PR 11 added the metrics; this is
    the offline report that surfaces them."""
    disp = {k: v for k, v in (registry or {}).items()
            if k.startswith("tpu_kernel_dispatch_total")}
    fb = {k: v for k, v in (registry or {}).items()
          if k.startswith("tpu_kernel_fallback_total")}
    if not disp and not fb:
        return []
    lines = ["-- kernel tier (Pallas dispatch/fallback) --"]
    for k, v in sorted(disp.items(), key=lambda kv: -kv[1]):
        lines.append(f"  dispatch {k.split('{', 1)[-1].rstrip('}'):<40}"
                     f" {v}")
    for k, v in sorted(fb.items(), key=lambda kv: -kv[1]):
        lines.append(f"  fallback {k.split('{', 1)[-1].rstrip('}'):<40}"
                     f" {v}")
    return lines


def kernel_plan_section(meta: dict) -> list:
    """Rendered per-query kernel-tier DECISIONS (PhysicalQuery.
    kernel_plan(), embedded in the event-log meta when tracing is on
    and the tier resolved): which operator elected which kernel and
    why the sorted tier kept the rest."""
    kp = (meta or {}).get("kernel_plan")
    if not kp:
        return []
    return ["-- kernel tier decisions (this query) --"] + \
        [f"  {line}" for line in kp]


def try_bench_record(path: str):
    """Parse a .json file as a bench final record (no multichip
    timings) -> (per-suite query dict, full doc) or (None, None)."""
    if path.endswith(".jsonl"):
        return None, None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None, None
    inner = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else doc
    if not isinstance(inner, dict):
        return None, None
    suites = {k: v for k, v in inner.items()
              if k.endswith("_suite_queries") and isinstance(v, dict)}
    if not suites and not inner.get("kernel_timings_ms"):
        return None, None
    return suites, inner


def render_bench(path: str, suites: dict, inner: dict,
                 as_json: bool) -> None:
    reg = inner.get("registry") if isinstance(inner.get("registry"),
                                              dict) else {}
    if as_json:
        out = {"log": path,
               "kernel_metrics": {k: v for k, v in reg.items()
                                  if k.startswith("tpu_kernel_")}}
        if inner.get("kernel_timings_ms"):
            out["kernel_timings_ms"] = inner["kernel_timings_ms"]
        print(json.dumps(out))
        return
    print(f"### {path}")
    print("== bench record ==")
    meta = [f"{k}={inner[k]}" for k in ("backend", "suite",
                                        "queries_measured") if k in inner]
    if meta:
        print("  " + " ".join(meta))
    tim = inner.get("kernel_timings_ms")
    if isinstance(tim, dict):
        print("-- kernel A/B timings (pallas vs sorted) --")
        for k in sorted(tim):
            print(f"  {k:<44} {tim[k]:>10.1f} ms")
    for line in kernel_section(reg):
        print(line)
    if not tim and not kernel_section(reg):
        print("  (no kernel-tier data in this record)")
    print()


def try_heartbeat_log(path: str):
    """Parse a .jsonl file as a metrics-heartbeat log (obs/export.py
    Heartbeat) -> list of beat records, or None.  A supervised pool
    writes one such log PER PROCESS into a shared directory (the
    `-w<id>` suffix from `worker_suffixed_path`), so a pool report dir
    mixes query event logs with supervisor + worker heartbeat logs —
    these must render as fleet summaries, not as unreadable queries."""
    if not path.endswith(".jsonl"):
        return None
    beats = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if not isinstance(rec, dict) or \
                        rec.get("type") != "heartbeat":
                    return None
                beats.append(rec)
    except (OSError, json.JSONDecodeError):
        return None
    return beats or None


def render_heartbeat_log(path: str, beats: list, as_json: bool) -> None:
    first, last = beats[0], beats[-1]
    role = last.get("role") or "process"
    worker = last.get("worker")
    who = f"{role} {worker}" if worker else role
    span_s = max(0.0, float(last.get("ts", 0)) - float(first.get("ts", 0)))
    reg = last.get("registry") if isinstance(last.get("registry"),
                                             dict) else {}
    fleet = last.get("fleet") if isinstance(last.get("fleet"), dict) else {}
    if as_json:
        print(json.dumps({"log": path, "heartbeats": len(beats),
                          "role": role, "worker": worker,
                          "span_s": round(span_s, 3),
                          "registry_series": len(reg),
                          "fleet_series": len(fleet)}))
        return
    print(f"### {path}")
    print("== metrics heartbeat log ==")
    print(f"  {who}: {len(beats)} beat(s) over {span_s:.1f}s, "
          f"last registry {len(reg)} series"
          + (f", fleet view {len(fleet)} series" if fleet else ""))
    workers = sorted({k.split("worker=", 1)[1].split(",", 1)[0]
                      .split("}", 1)[0] for k in fleet if "worker=" in k})
    if workers:
        print(f"  fleet workers seen: {', '.join(workers)}")
    print()


def try_multichip_record(path: str):
    """Parse a .json file as a multichip/bench record -> (mc timings
    dict, full doc) or (None, None).  Reuses the regression gate's
    extractor, so driver wrappers and legacy python-repr dry-run tails
    all render."""
    if path.endswith(".jsonl"):
        return None, None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None, None
    from check_regression import extract_multichip
    mc, _backend = extract_multichip(doc)
    if not mc:
        return None, None
    return mc, doc


def render_multichip(path: str, mc: dict, doc: dict, mesh: bool,
                     as_json: bool) -> None:
    inner = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else doc
    if as_json:
        out = {"log": path, "multichip_timings_ms": mc}
        for k in ("n_devices", "backend", "multichip_sf", "pcache",
                  "exchange", "primitives_mesh_timeline"):
            if k in inner:
                out[k] = inner[k]
        print(json.dumps(out))
        return
    print(f"### {path}")
    print("== multichip record ==")
    meta = [f"{k}={inner[k]}" for k in ("n_devices", "backend",
                                        "multichip_sf") if k in inner]
    if meta:
        print("  " + " ".join(meta))
    for k in sorted(mc, key=lambda s: (len(s), s)):
        print(f"  {k:<44} {mc[k]:>12.1f} ms")
    prim = inner.get("primitives_mesh_timeline") or {}
    for name, tl in prim.items():
        nex = len(tl.get("exchanges", []))
        print(f"  -- {name}: {nex} exchange call(s)")
        if mesh:
            for line in render_mesh_timeline(tl, indent="     "):
                print(line)
    per_q = inner.get("multichip_suite_queries") or {}
    with_tl = {q: r for q, r in per_q.items()
               if isinstance(r, dict) and r.get("mesh_timeline")}
    for q, r in sorted(with_tl.items()):
        tl = r["mesh_timeline"]
        print(f"  -- {q}: {len(tl.get('exchanges', []))} exchange "
              f"call(s), ici bytes={r.get('ici_exchange_bytes', 0)}")
        if mesh:
            for line in render_mesh_timeline(tl, indent="     "):
                print(line)
    print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", help="event-log .jsonl file, multichip/"
                                   "bench .json record, or directory")
    ap.add_argument("--json", action="store_true",
                    help="emit the full profile dict as JSON instead of "
                         "the text report")
    ap.add_argument("--mesh", action="store_true",
                    help="expand the per-round mesh exchange timeline "
                         "(round quotas, wire bytes pre/post compress, "
                         "arrivals, staging vs collective ms)")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.obs.profile import QueryProfile

    for path in log_paths(args.target):
        # multichip/bench .json records render their own section (the
        # mc:-keyed timings + embedded exchange timelines)
        mc, doc = try_multichip_record(path)
        if mc:
            render_multichip(path, mc, doc, args.mesh, args.json)
            continue
        # bench final records (incl. --kernels A/B rounds): the suite
        # summary + the tpu_kernel_* dispatch/fallback families
        suites, inner = try_bench_record(path)
        if inner is not None:
            render_bench(path, suites, inner, args.json)
            continue
        # pool heartbeat logs (supervisor + per-worker) share the dir
        # with query event logs; render their fleet summary instead of
        # failing them through the query-profile path
        beats = try_heartbeat_log(path)
        if beats:
            render_heartbeat_log(path, beats, args.json)
            continue
        # a directory can hold non-query JSONL (metrics heartbeats),
        # truncated crash-time logs, or logs from fallback-only queries
        # with no spans — none of those may take the report down
        try:
            prof = QueryProfile.from_event_log(path)
        except Exception as e:                   # noqa: BLE001
            if args.json:
                print(json.dumps({"log": path, "error":
                                  f"{type(e).__name__}: {e}"}))
            else:
                print(f"### {path}")
                print(f"  unreadable as a query event log "
                      f"({type(e).__name__}: {e})")
                print()
            continue
        if not prof.spans and not prof.metrics and not prof.events:
            if args.json:
                print(json.dumps({"log": path, "skipped":
                                  "no query trace data"}))
            else:
                print(f"### {path}")
                print("  no query trace data (not an event log, or a "
                      "fallback-only query with tracing off)")
                print()
            continue
        if args.json:
            out = {"log": path, **prof.to_dict()}
            if prof.meta.get("kernel_plan"):
                out["kernel_plan"] = prof.meta["kernel_plan"]
            print(json.dumps(out))
        else:
            print(f"### {path}")
            print(prof.render())
            for line in kernel_plan_section(prof.meta):
                print(line)
            for line in kernel_section(prof.registry):
                print(line)
            if args.mesh:
                tl = prof.mesh_timeline()
                if tl["exchanges"] or tl["skew_splits"]:
                    print("-- mesh timeline (per round) --")
                    for line in render_mesh_timeline(tl):
                        print(line)
                else:
                    print("(no mesh exchange events in this log)")
            trace = path.removesuffix(".jsonl") + ".trace.json"
            if os.path.exists(trace):
                print(f"perfetto trace: {trace}")
            else:
                print("(no perfetto trace file for this query)")
            print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
