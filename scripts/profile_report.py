#!/usr/bin/env python
"""Offline query-profile reports from JSONL event logs — the RAPIDS
profiling-tool analogue (SURVEY §5).

Input: one `query_<id>.jsonl` written under `spark.rapids.tpu.eventLog.dir`,
or a directory of them.  For each log it renders the QueryProfile: the
compile/execute/transition/shuffle wall split, the per-node-id operator
table (top operators by self time), data-movement bytes, memory
high-water, runtime incidents (OOM retries / splits / spills) and the
fallback summary.  The sibling `query_<id>.trace.json` opens directly in
perfetto (https://ui.perfetto.dev) or chrome://tracing.

Usage:
    python scripts/profile_report.py <event_log.jsonl | dir> [--json]
"""
import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log_paths(target: str) -> list:
    if os.path.isdir(target):
        paths = sorted(glob.glob(os.path.join(target, "*.jsonl")))
        if not paths:
            raise SystemExit(f"no *.jsonl event logs under {target}")
        return paths
    if not os.path.exists(target):
        raise SystemExit(f"no such file: {target}")
    return [target]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", help="event-log .jsonl file or directory")
    ap.add_argument("--json", action="store_true",
                    help="emit the full profile dict as JSON instead of "
                         "the text report")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.obs.profile import QueryProfile

    for path in log_paths(args.target):
        # a directory can hold non-query JSONL (metrics heartbeats),
        # truncated crash-time logs, or logs from fallback-only queries
        # with no spans — none of those may take the report down
        try:
            prof = QueryProfile.from_event_log(path)
        except Exception as e:                   # noqa: BLE001
            if args.json:
                print(json.dumps({"log": path, "error":
                                  f"{type(e).__name__}: {e}"}))
            else:
                print(f"### {path}")
                print(f"  unreadable as a query event log "
                      f"({type(e).__name__}: {e})")
                print()
            continue
        if not prof.spans and not prof.metrics and not prof.events:
            if args.json:
                print(json.dumps({"log": path, "skipped":
                                  "no query trace data"}))
            else:
                print(f"### {path}")
                print("  no query trace data (not an event log, or a "
                      "fallback-only query with tracing off)")
                print()
            continue
        if args.json:
            print(json.dumps({"log": path, **prof.to_dict()}))
        else:
            print(f"### {path}")
            print(prof.render())
            trace = path.removesuffix(".jsonl") + ".trace.json"
            if os.path.exists(trace):
                print(f"perfetto trace: {trace}")
            else:
                print("(no perfetto trace file for this query)")
            print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
