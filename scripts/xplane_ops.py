#!/usr/bin/env python
"""Summarize a jax.profiler trace: per-op device time from the xplane.

Usage: python scripts/xplane_ops.py /tmp/jaxprof [topN]
Aggregates XLA op events on the device plane by op category (the HLO
fingerprint up to the numeric suffix) and prints total us + count,
descending.  This is the measured per-op breakdown docs/PERF.md cites.
"""
import collections
import glob
import sys

from tensorflow.tsl.profiler.protobuf import xplane_pb2

path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jaxprof"
topn = int(sys.argv[2]) if len(sys.argv) > 2 else 40

files = glob.glob(path + "/plugins/profile/*/*.xplane.pb")
assert files, f"no xplane under {path}"
sp = xplane_pb2.XSpace()
with open(files[-1], "rb") as f:
    sp.ParseFromString(f.read())

for plane in sp.planes:
    is_dev = ("TPU" in plane.name or "/device" in plane.name.lower()
              or "Accelerator" in plane.name)
    if not is_dev:
        continue
    evmeta = {m.id: m.name for m in plane.event_metadata.values()}
    agg = collections.Counter()
    cnt = collections.Counter()
    total = 0
    for line in plane.lines:
        for ev in line.events:
            name = evmeta.get(ev.metadata_id, "?")
            dur = ev.duration_ps / 1e6  # -> us
            key = name.split(".")[0].rstrip("0123456789_")
            agg[key] += dur
            cnt[key] += 1
            total += dur
    print(f"== plane: {plane.name}  lines={len(plane.lines)} "
          f"total={total/1e3:.1f}ms")
    for k, us in agg.most_common(topn):
        print(f"  {us/1e3:9.2f}ms  n={cnt[k]:5d}  {k}")
