#!/usr/bin/env python
"""Chaos-coverage lint: every registered fault-injection site must be
exercised by at least one chaos test.

The site registry is the source of truth
(spark_rapids_tpu.runtime.faults.SITES — the names the conf grammar
accepts); tests/test_chaos.py is the chaos suite.  A site that gains an
injection point in the engine but no chaos test is an UNTESTED recovery
path — exactly the gap this PR exists to close — so this lint fails the
build on it.  Runs in tier-1 via tests/test_chaos.py.

A site counts as covered when the chaos suite arms a fault spec at it
(`"<site>:<kind>"`) or fires it directly (`fire("<site>")` /
`fire_active("<site>")`).

Usage:
    python scripts/check_fault_sites.py      # exit 1 + list when gaps
"""
import os
import re
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def uncovered_sites() -> list:
    from spark_rapids_tpu.runtime.faults import KINDS, SITES
    src = open(os.path.join(_ROOT, "tests", "test_chaos.py")).read()
    missing = []
    kind_alt = "|".join(KINDS)
    for site in sorted(SITES):
        armed = re.search(rf"\b{site}:(?:{kind_alt}):", src)
        fired = re.search(rf"fire(?:_active)?\(\s*['\"]{site}['\"]", src)
        if not armed and not fired:
            missing.append(site)
    return missing


def main() -> int:
    missing = uncovered_sites()
    if missing:
        print("fault sites registered in runtime/faults.py with NO chaos "
              "test in tests/test_chaos.py:")
        for site in missing:
            print(f"  {site}")
        print("add a chaos case arming '<site>:<kind>:<trigger>' (or a "
              "direct fire()) for each.")
        return 1
    print("every registered fault site is exercised by the chaos suite")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
