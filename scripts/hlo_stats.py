#!/usr/bin/env python
"""Trace a TPC-H query's whole-plan program and report HLO size stats
WITHOUT the device: runs on the CPU backend, so trace time and program
shape are visible locally (compile on the tunnel-attached chip scales
with the same program).

Usage: python scripts/hlo_stats.py q16 [scale]
Prints: trace seconds, jaxpr eqn count, stablehlo op histogram (top 20),
sort op count/operand widths, total lowered text size.
"""
import collections
import os
import re
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

qname = sys.argv[1] if len(sys.argv) > 1 else "q16"
scale = float(sys.argv[2]) if len(sys.argv) > 2 else 1.0

from spark_rapids_tpu import tpch
from spark_rapids_tpu.exec.compiled import (CompiledPlan, _find_split_seams,
                                            SplitCompiledPlan, _flatten_batch,
                                            _trace_context)
from spark_rapids_tpu.exec.plan import ExecContext
from spark_rapids_tpu.session import TpuSession

t0 = time.perf_counter()
tables = tpch.gen_tables(scale=scale)
print(f"datagen {time.perf_counter()-t0:.1f}s", file=sys.stderr)

dev = TpuSession()
dfq = tpch.QUERIES[qname](dev, tables)
q = dfq.physical()
root = q.root
ctx = ExecContext(dev.conf)

seams = _find_split_seams(root)
print(f"split seams: {[type(s).__name__ for s in seams]}")

plan = CompiledPlan(root, ctx.conf)
pairs = plan._leaf_batches(ctx)
flat_in = []
in_specs = []
for node, dbs in pairs:
    node_specs = []
    for db in dbs:
        arrays, spec = _flatten_batch(db)
        flat_in.extend(arrays)
        node_specs.append(spec)
    in_specs.append((node, node_specs))
print(f"leaf arrays: {len(flat_in)}; "
      f"total in bytes: {sum(a.nbytes for a in flat_in)/1e6:.1f}MB")

from spark_rapids_tpu.exec.compiled import _rebuild_batch

def run(flat):
    i = 0
    for node, node_specs in in_specs:
        batches = []
        for spec in node_specs:
            db, i = _rebuild_batch(flat, spec, i)
            batches.append(db)
        node._trace_batches = batches
    try:
        trace_ctx = _trace_context(ctx)
        outs = list(root.execute(trace_ctx))
    finally:
        for node, _ in in_specs:
            node._trace_batches = None
    flat_out = []
    for db in outs:
        arrays, _ = _flatten_batch(db)
        flat_out.extend(arrays)
    return flat_out

t0 = time.perf_counter()
traced = jax.make_jaxpr(run)(flat_in)
trace_s = time.perf_counter() - t0
n_eqns = len(traced.eqns)

def count_all(jaxpr, ctr):
    for e in jaxpr.eqns:
        ctr[e.primitive.name] += 1
        for sub in e.params.values():
            if hasattr(sub, "jaxpr"):
                count_all(sub.jaxpr, ctr)
ctr = collections.Counter()
count_all(traced.jaxpr, ctr)
print(f"trace: {trace_s:.1f}s, top-level eqns: {n_eqns}, "
      f"total (nested): {sum(ctr.values())}")
print("top prims:", ctr.most_common(25))

t0 = time.perf_counter()
lowered = jax.jit(run).lower(flat_in)
low_s = time.perf_counter() - t0
txt = lowered.as_text()
print(f"lower: {low_s:.1f}s, stablehlo text: {len(txt)/1e6:.1f}MB")
ops = collections.Counter(re.findall(r"stablehlo\.(\w+)", txt))
print("top stablehlo:", ops.most_common(25))
sorts = re.findall(r'"stablehlo.sort"\(([^)]*)\)', txt)
widths = [s.count("%") for s in sorts]
print(f"sort ops: {len(sorts)}, operand widths: "
      f"{collections.Counter(widths).most_common()}")

t0 = time.perf_counter()
comp = lowered.compile()
print(f"CPU compile: {time.perf_counter()-t0:.1f}s", file=sys.stderr)
