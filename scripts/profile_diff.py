#!/usr/bin/env python
"""Profile diff: compare two profiling records per SEGMENT and per
metric family, and emit the top regressed / improved entries.

The regression gate (check_regression.py) answers "did a query get
slower"; this tool answers the next question — *where*.  It diffs any
two of:

  * query event logs (`query_<id>.jsonl`, written under
    `spark.rapids.tpu.eventLog.dir`) — per-segment measured device ms
    (runs with `spark.rapids.tpu.profile.segments` on), per-node
    operator self time, the compile/execute/transition/shuffle split,
    data-movement counters and incident counts;
  * bench / multichip result JSONs (BENCH_r*/MULTICHIP_r*, raw final
    lines, driver wrappers, legacy python-repr dry-run tails) —
    per-query net device ms, `mc:`-prefixed multichip timings, embedded
    per-query segment summaries and cold compile ms.

Typical uses: A/B two confs from their event logs; r(N) vs r(N-1) from
the committed trajectory (`profile_diff.py MULTICHIP_r05.json
MULTICHIP_r08.json` reproduces the PR 8 fused-groupby win as a
segment-level diff).

Exit codes: 0 ok, 2 usage/no comparable data.

Usage:
    python scripts/profile_diff.py A B [--top N] [--min-ms MS] [--json]
    python scripts/profile_diff.py --self-test
"""
import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# record loading: every input becomes {family: {entry: float}}
# ---------------------------------------------------------------------------

def _eventlog_families(path: str) -> dict:
    from spark_rapids_tpu.obs.profile import QueryProfile
    prof = QueryProfile.from_event_log(path)
    fams = {}
    segs = {s["node"]: float(s.get("device_ms", 0.0))
            for s in prof.segments()}
    if segs:
        fams["segments"] = segs
    ops = {o["node"]: float(o.get("self_time_ms", 0.0))
           for o in prof.operators()}
    if ops:
        fams["operators"] = ops
    split = {k: float(v) for k, v in prof.time_split().items() if v}
    if split:
        fams["time_split"] = split
    dm = {k: float(v) for k, v in prof.data_movement().items()}
    if dm:
        fams["data_movement"] = dm
    inc = {k: float(v) for k, v in prof.incidents().items()}
    if inc:
        fams["incidents"] = inc
    # the wall-decomposition plane: fixed-overhead tail per category
    # (seam wall, dispatch floor x launches, padding waste) — a diff
    # here names the overhead a refactor added or removed even when
    # device ms held still
    bd = prof.wall_breakdown()
    ov = {k: float(bd[k]) for k in ("seam_ms", "dispatch_ms",
                                    "pad_waste_ms", "seam_count")
          if bd.get(k)}
    if ov:
        fams["overhead"] = ov
    return fams


def _bench_families(path: str) -> dict:
    from check_regression import (extract_compile_ms, extract_hbm,
                                  extract_kernels, extract_multichip,
                                  extract_overheads, extract_queries,
                                  extract_segments, extract_serving)
    with open(path) as f:
        doc = json.load(f)
    fams = {}
    qs, _backend = extract_queries(doc)
    mc, _ = extract_multichip(doc)
    queries = {**qs, **mc}
    if queries:
        fams["queries"] = queries
    # kernel A/B (KERNELS_r*.json kn: entries) and serving-latency
    # (SERVING_r*.json sv: entries) records diff with the same tool —
    # the regression gate already mines them, so reuse its extractors
    kn, _ = extract_kernels(doc)
    if kn:
        fams["kernels"] = kn
    sv, _ = extract_serving(doc)
    if sv:
        fams["serving"] = sv
    segs = extract_segments(doc)
    flat_segs = {f"{q}/{node}": ms for q, per in segs.items()
                 for node, ms in per.items()}
    if flat_segs:
        fams["segments"] = flat_segs
    # per-query measured HBM peaks (memory-attribution plane): diff
    # working sets across bench rounds the same way device time diffs
    hbm = extract_hbm(doc)
    if hbm:
        fams["hbm"] = hbm
    # per-query overhead tails (wall_breakdown embeds): seam/dispatch/
    # pad-waste ms keyed q/field, so "q4 gained a seam" reads directly
    ovs = extract_overheads(doc)
    flat_ov = {f"{q}/{k}": float(v) for q, per in ovs.items()
               for k, v in per.items()
               if k != "pad_waste_share" and v}
    if flat_ov:
        fams["overhead"] = flat_ov
    cms = extract_compile_ms(doc)
    if cms:
        fams["compile"] = {"median_compile_ms":
                           float(sorted(cms)[len(cms) // 2])}
    return fams


def load_families(path: str) -> dict:
    """-> {family: {entry: value}} for an event log or bench record."""
    if path.endswith(".jsonl"):
        return _eventlog_families(path)
    return _bench_families(path)


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------

def diff_families(a: dict, b: dict, min_abs: float = 1.0) -> dict:
    """Per-family entry diff of record A (baseline) vs B (current):
    rows {entry, a, b, delta, ratio}, split into regressed (B worse,
    ratio desc) and improved (B better, improvement desc).  Entries
    below `min_abs` on BOTH sides are noise and skipped."""
    out = {}
    for fam in sorted(set(a) & set(b)):
        rows = []
        ea, eb = a[fam], b[fam]
        for k in sorted(set(ea) & set(eb)):
            va, vb = float(ea[k]), float(eb[k])
            if abs(va) < min_abs and abs(vb) < min_abs:
                continue
            rows.append({"entry": k, "a": round(va, 3),
                         "b": round(vb, 3),
                         "delta": round(vb - va, 3),
                         "ratio": round(vb / va, 4) if va else
                         float("inf")})
        regressed = sorted([r for r in rows if r["delta"] > 0],
                           key=lambda r: -r["delta"])
        improved = sorted([r for r in rows if r["delta"] < 0],
                          key=lambda r: r["delta"])
        out[fam] = {"regressed": regressed, "improved": improved,
                    "only_a": sorted(set(ea) - set(eb)),
                    "only_b": sorted(set(eb) - set(ea))}
    return out


def render(res: dict, name_a: str, name_b: str, top: int) -> str:
    lines = [f"A (baseline): {name_a}", f"B (current):  {name_b}"]
    for fam, d in res.items():
        lines.append(f"-- {fam} --")
        for r in d["regressed"][:top]:
            lines.append(f"  REGRESSED {r['entry']:<44} "
                         f"{r['a']:>12.1f} -> {r['b']:>12.1f}  "
                         f"(x{r['ratio']:.2f}, +{r['delta']:.1f})")
        for r in d["improved"][:top]:
            lines.append(f"  improved  {r['entry']:<44} "
                         f"{r['a']:>12.1f} -> {r['b']:>12.1f}  "
                         f"(x{r['ratio']:.2f}, {r['delta']:.1f})")
        if not d["regressed"] and not d["improved"]:
            lines.append("  (no change above the noise floor)")
        extra = len(d["regressed"]) + len(d["improved"]) - 2 * top
        if extra > 0:
            lines.append(f"  ... {extra} more changed entr"
                         f"{'y' if extra == 1 else 'ies'}")
        if d["only_a"] or d["only_b"]:
            lines.append(f"  (only in A: {len(d['only_a'])}, "
                         f"only in B: {len(d['only_b'])})")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self test (tier-1 via tests/test_explain_analyze.py)
# ---------------------------------------------------------------------------

def self_test() -> int:
    """Built-in proof the diff works end to end: (1) a synthetic A/B
    orders regressions and improvements correctly; (2) a synthetic
    event-log pair diffs per segment; (3) kernel (kn:) and serving
    (sv:) records load and diff as their own families; (3c) a
    seam-elimination win surfaces in the overhead family; (4) the
    committed MULTICHIP trajectory reproduces the PR 8 fused-groupby
    win (119.4s -> 11.1s) as an `mc:`-keyed improvement, and the
    committed KERNELS record loads as a kernels family."""
    import tempfile
    # 1: synthetic family diff
    a = {"segments": {"agg": 100.0, "join": 500.0, "sort": 50.0}}
    b = {"segments": {"agg": 300.0, "join": 50.0, "sort": 55.0}}
    res = diff_families(a, b)
    seg = res["segments"]
    assert seg["regressed"][0]["entry"] == "agg", seg
    assert seg["improved"][0]["entry"] == "join", seg
    assert abs(seg["regressed"][0]["ratio"] - 3.0) < 1e-9

    # 2: event-log pair round trip (segment.* metrics -> segments family)
    def log_lines(join_ms):
        return "\n".join([
            json.dumps({"type": "query_start", "query_id": 1,
                        "wall_start_unix": 0.0}),
            json.dumps({"type": "span", "id": 0, "parent": None,
                        "name": "query", "cat": "query", "t0_ms": 0.0,
                        "dur_ms": join_ms + 10.0}),
            json.dumps({"type": "query_end", "query_id": 1,
                        "metrics": {
                            "segment.HashJoinExec#1.device_ms": join_ms,
                            "segment.HashAggregateExec#0.device_ms": 5.0},
                        "counters": {}, "meta": {}})])
    with tempfile.TemporaryDirectory() as td:
        pa = os.path.join(td, "a.jsonl")
        pb = os.path.join(td, "b.jsonl")
        open(pa, "w").write(log_lines(200.0) + "\n")
        open(pb, "w").write(log_lines(20.0) + "\n")
        res = diff_families(load_families(pa), load_families(pb))
        imp = res["segments"]["improved"]
        assert imp and imp[0]["entry"] == "HashJoinExec#1", res

    # 3: kernel + serving records diff through the same loader (the
    # kn:/sv: families the regression gate mines)
    def kn_sv_doc(probe_ms, p99_ms):
        return {"backend": "cpu",
                "kernel_timings_ms": {"probe_1m_pallas": probe_ms,
                                      "compact_1m_pallas": 40.0},
                "serving_latency_ms": {"c8_p99": p99_ms,
                                       "c8_mean": p99_ms / 2.0}}
    with tempfile.TemporaryDirectory() as td:
        ka = os.path.join(td, "KERNELS_a.json")
        kb = os.path.join(td, "KERNELS_b.json")
        json.dump(kn_sv_doc(100.0, 800.0), open(ka, "w"))
        json.dump(kn_sv_doc(30.0, 2400.0), open(kb, "w"))
        res = diff_families(load_families(ka), load_families(kb))
        assert res["kernels"]["improved"][0]["entry"] == \
            "kn:probe_1m_pallas", res["kernels"]
        assert res["serving"]["regressed"][0]["entry"] == "sv:c8_p99", \
            res["serving"]
        assert abs(res["serving"]["regressed"][0]["ratio"] - 3.0) < 1e-9

    # 3b: per-query HBM peaks diff as their own family (the memattr
    # plane's bench fields — check_regression gates them, this names
    # the query whose working set moved)
    def hbm_doc(q3_bytes):
        return {"backend": "cpu", "tpch_suite_queries": {
            "q3": {"device_ms_net": 100.0, "hbm_peak_bytes": q3_bytes},
            "q6": {"device_ms_net": 50.0, "hbm_peak_bytes": 1 << 20}}}
    with tempfile.TemporaryDirectory() as td:
        ha = os.path.join(td, "BENCH_a.json")
        hb = os.path.join(td, "BENCH_b.json")
        json.dump(hbm_doc(2 << 20), open(ha, "w"))
        json.dump(hbm_doc(8 << 20), open(hb, "w"))
        res = diff_families(load_families(ha), load_families(hb))
        reg = res["hbm"]["regressed"]
        assert reg and reg[0]["entry"] == "q3", res["hbm"]
        assert abs(reg[0]["ratio"] - 4.0) < 1e-9

    # 3c: seam-elimination win (wall-decomposition plane): B fuses the
    # plan so one row-collapse seam disappears — the overhead family
    # must show q4's seam wall and seam count improving even though
    # net device ms is unchanged
    def seam_doc(seam_count, seam_ms):
        return {"backend": "cpu", "tpch_suite_queries": {
            "q4": {"device_ms_net": 80.0, "wall_breakdown": {
                "wall_ms": 200.0, "seam_ms": seam_ms,
                "seam_count": seam_count, "dispatch_ms": 3.0,
                "pad_waste_ms": 2.0}}}}
    with tempfile.TemporaryDirectory() as td:
        sa = os.path.join(td, "BENCH_a.json")
        sb = os.path.join(td, "BENCH_b.json")
        json.dump(seam_doc(2, 24.0), open(sa, "w"))
        json.dump(seam_doc(1, 6.0), open(sb, "w"))
        res = diff_families(load_families(sa), load_families(sb))
        imp = res["overhead"]["improved"]
        assert imp and imp[0]["entry"] == "q4/seam_ms", res["overhead"]
        assert abs(imp[0]["ratio"] - 0.25) < 1e-9
        assert any(r["entry"] == "q4/seam_count" for r in imp), imp
        assert not res["overhead"]["regressed"], res["overhead"]

    # 4: the committed trajectory reproduces the PR 8 groupby win
    r05 = os.path.join(_ROOT, "MULTICHIP_r05.json")
    r08 = os.path.join(_ROOT, "MULTICHIP_r08.json")
    if os.path.exists(r05) and os.path.exists(r08):
        res = diff_families(load_families(r05), load_families(r08))
        imp = res["queries"]["improved"]
        assert imp, "no improvements between MULTICHIP r05 and r08"
        top = imp[0]
        assert top["entry"] == "mc:groupby_1048576_rows_per_device", imp
        assert top["ratio"] < 0.15, top   # 119.4s -> 11.1s is ~0.093x
    else:
        print("# self-test: committed MULTICHIP records absent, "
              "trajectory leg skipped", file=sys.stderr)
    r11 = os.path.join(_ROOT, "KERNELS_r11.json")
    if os.path.exists(r11):
        fams = load_families(r11)
        assert fams.get("kernels"), "KERNELS_r11 yields no kn: family"
        assert all(k.startswith("kn:") for k in fams["kernels"])
    print("profile_diff self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("a", nargs="?", help="baseline record "
                                         "(.jsonl event log or .json)")
    ap.add_argument("b", nargs="?", help="current record")
    ap.add_argument("--top", type=int, default=5,
                    help="entries shown per direction per family")
    ap.add_argument("--min-ms", type=float, default=1.0,
                    help="noise floor: entries below this on both "
                         "sides are skipped")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in self test (tier-1 wired)")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()
    if not args.a or not args.b:
        ap.print_usage()
        return 2
    try:
        fa = load_families(args.a)
        fb = load_families(args.b)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read records: {e}", file=sys.stderr)
        return 2
    res = diff_families(fa, fb, args.min_ms)
    if not res:
        print("no comparable metric families between the two records",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"a": args.a, "b": args.b, **res}))
    else:
        print(render(res, args.a, args.b, args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
