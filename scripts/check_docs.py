#!/usr/bin/env python
"""Docs lint: every public `conf(...)` entry must appear in
docs/configs.md, and every metric family registered in the always-on
`MetricsRegistry` must appear in docs/METRICS.md.

The registries are the source of truth (config.py `_REGISTRY` plus the
entries modules register at import — runtime/failure.py; and
obs/registry.py's central metric catalog); docs are generated/curated
but can silently drift when a knob or metric lands without a doc.  This
lint fails on any non-internal conf key missing from docs/configs.md
and any `REGISTRY.family_names()` entry missing from docs/METRICS.md,
and runs in tier-1 (tests/test_tracing.py, tests/test_metrics_plane.py)
so neither can ship undocumented.

Usage:
    python scripts/check_docs.py          # exit 1 + list when stale
"""
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def missing_keys() -> list:
    """Non-internal registered conf keys absent from docs/configs.md."""
    from spark_rapids_tpu import config
    # modules that register conf entries at import time must be imported
    # so the registry is complete (same set as config.__main__)
    from spark_rapids_tpu.runtime import failure  # noqa: F401
    doc = open(os.path.join(_ROOT, "docs", "configs.md")).read()
    return [e.key for e in config.all_entries()
            if not e.internal and f"`{e.key}`" not in doc]


def missing_metric_docs() -> list:
    """Registry metric family names absent from docs/METRICS.md (the
    metric catalog — obs/registry.py declares every family at import,
    so importing the module yields the complete name set)."""
    from spark_rapids_tpu.obs.registry import REGISTRY
    path = os.path.join(_ROOT, "docs", "METRICS.md")
    try:
        doc = open(path).read()
    except OSError:
        return list(REGISTRY.family_names())
    return [n for n in REGISTRY.family_names() if f"`{n}`" not in doc]


def missing_attribution() -> list:
    """Registered exec node classes in neither the attribution plane's
    covered set nor its explicit exemption list (obs/attribution.py).
    A new operator must be added to one of them DELIBERATELY, so plan
    time can never silently fall outside EXPLAIN ANALYZE."""
    from spark_rapids_tpu.obs.attribution import attribution_coverage_gaps
    return attribution_coverage_gaps()


def main() -> int:
    rc = 0
    missing = missing_keys()
    if missing:
        print("docs/configs.md is missing documented conf entries "
              "(run `python -m spark_rapids_tpu.config` to regenerate):")
        for k in missing:
            print(f"  {k}")
        rc = 1
    else:
        print("docs/configs.md covers every public conf entry")
    missing_m = missing_metric_docs()
    if missing_m:
        print("docs/METRICS.md is missing registered metric families "
              "(document each name in the catalog table):")
        for n in missing_m:
            print(f"  {n}")
        rc = 1
    else:
        print("docs/METRICS.md covers every registered metric family")
    missing_a = missing_attribution()
    if missing_a:
        print("attribution coverage gaps: exec classes in neither "
              "ATTRIBUTION_COVERED nor ATTRIBUTION_EXEMPT "
              "(obs/attribution.py):")
        for n in missing_a:
            print(f"  {n}")
        rc = 1
    else:
        print("every registered exec class is attribution-covered or "
              "explicitly exempted")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
