#!/usr/bin/env python
"""Docs lint: every public `conf(...)` entry must appear in docs/configs.md.

The config registry is the source of truth (config.py `_REGISTRY`, plus
the entries modules register at import — runtime/failure.py); docs are
generated (`python -m spark_rapids_tpu.config`) but can silently drift
when a knob lands without a regen.  This lint fails on any non-internal
key missing from docs/configs.md, and runs in tier-1 via
tests/test_tracing.py so new knobs can't ship undocumented.

Usage:
    python scripts/check_docs.py          # exit 1 + list when stale
"""
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def missing_keys() -> list:
    """Non-internal registered conf keys absent from docs/configs.md."""
    from spark_rapids_tpu import config
    # modules that register conf entries at import time must be imported
    # so the registry is complete (same set as config.__main__)
    from spark_rapids_tpu.runtime import failure  # noqa: F401
    doc = open(os.path.join(_ROOT, "docs", "configs.md")).read()
    return [e.key for e in config.all_entries()
            if not e.internal and f"`{e.key}`" not in doc]


def main() -> int:
    missing = missing_keys()
    if missing:
        print("docs/configs.md is missing documented conf entries "
              "(run `python -m spark_rapids_tpu.config` to regenerate):")
        for k in missing:
            print(f"  {k}")
        return 1
    print("docs/configs.md covers every public conf entry")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
