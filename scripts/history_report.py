#!/usr/bin/env python
"""Performance-history report: the cost oracle's offline triage surface.

Reads the persistent performance-history store (the JSONL file under
`spark.rapids.tpu.history.dir`, obs/history.py) and renders:

  * TOP STRUCTURES by cumulative measured device time — where this
    deployment's device seconds actually go, with per-structure run
    counts, warm decayed device-us, compile ms and labels (bench runs
    stamp query names);
  * the CALIBRATION CURVE — per estimate basis (exact_history /
    static_cost), how far admission-time predictions landed from the
    measured runs (log2-bucketed error-ratio histogram + mean), the
    offline twin of `tpu_history_prediction_error_ratio`;
  * DRIFT DETECTION — structures whose newest WARM measurement shifted
    more than the threshold (default 2x) from their own decayed warm
    history, the regression-triage entry point: a structure drifting
    slower is a perf regression with a named, reproducible plan shape
    (`check_regression.py --history-dir` cites these when a gate
    fails).

Exit codes: 0 ok, 1 drift found with --fail-on-drift, 2 usage/no data.

Usage:
    python scripts/history_report.py <history dir | perf_history.jsonl>
                                     [--top N] [--drift-threshold R]
                                     [--json] [--fail-on-drift]
    python scripts/history_report.py --self-test
"""
import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def load_store(target: str):
    """PerfHistoryStore over a history dir or a direct .jsonl path."""
    from spark_rapids_tpu.obs.history import HISTORY_FILE, PerfHistoryStore
    path = target
    if os.path.isdir(target):
        path = os.path.join(target, HISTORY_FILE)
    if not os.path.exists(path):
        raise SystemExit(f"no history file at {path}")
    return PerfHistoryStore(path)


def report_data(store, top: int = 10, drift_threshold: float = 2.0
                ) -> dict:
    """The structured report: top structures, calibration, drift."""
    rows = []
    for key, agg in store.aggregates().items():
        rows.append({"key": key, "label": agg.label, "kind": agg.kind,
                     "backend": agg.backend, "runs": agg.runs,
                     "warm_runs": agg.warm_runs,
                     "total_device_ms": round(agg.total_device_us / 1e3,
                                              1),
                     "device_us": round(agg.predicted_us(), 1),
                     "compile_ms": round(agg.compile_ms, 1),
                     "src_bytes": int(agg.src_bytes),
                     "ws_bytes": int(agg.ws_bytes),
                     "ws_runs": agg.ws_runs,
                     "segments": {n: round(v, 2)
                                  for n, v in agg.segments.items()},
                     "drift_ratio": agg.drift_ratio()})
    rows.sort(key=lambda r: -r["total_device_ms"])
    return {"stats": store.stats(),
            "top_structures": rows[:top],
            "structures": len(rows),
            "calibration": store.calibration(),
            "ws_calibration": store.ws_calibration(),
            "drift": store.drifted(drift_threshold)}


def render(data: dict, drift_threshold: float) -> str:
    st = data["stats"]
    lines = ["== performance history =="]
    lines.append(f"store            {st['path']}")
    lines.append(f"structures       {data['structures']} "
                 f"({st['records_loaded']} records loaded, "
                 f"{st['corrupt_lines']} corrupt line(s) tolerated, "
                 f"{st['compactions']} compaction(s))")
    if st.get("us_per_byte"):
        lines.append(f"fitted static    {st['us_per_byte']:.6f} us/byte "
                     f"(the static_cost fallback coefficient)")
    lines.append("-- top structures by cumulative device time --")
    for r in data["top_structures"]:
        name = r["label"] or r["key"]
        lines.append(
            f"  {name:<28} {r['total_device_ms']:>10.1f} ms total  "
            f"runs={r['runs']}({r['warm_runs']} warm) "
            f"warm={r['device_us'] / 1e3:.1f}ms "
            f"compile={r['compile_ms']:.0f}ms"
            + (f"  [{r['key']}]" if r["label"] else ""))
        for node, ms in sorted(r["segments"].items(),
                               key=lambda kv: -kv[1])[:3]:
            lines.append(f"      seg {node:<30} {ms:>8.1f} ms")
    calib = data["calibration"]
    if calib:
        lines.append("-- calibration (prediction vs measured) --")
        for basis, c in sorted(calib.items()):
            curve = " ".join(f"<=2^{b}:{n}" for b, n in
                             sorted(c["buckets"].items()))
            lines.append(f"  {basis:<16} n={c['n']} "
                         f"mean_error=x{c['mean_ratio']}  {curve}")
    else:
        lines.append("-- calibration: no predictions recorded yet "
                     "(serving admission stamps them) --")
    ws_calib = data.get("ws_calibration") or {}
    if ws_calib:
        lines.append("-- working-set calibration (reservation vs "
                     "measured HBM) --")
        for basis, c in sorted(ws_calib.items()):
            curve = " ".join(f"<=2^{b}:{n}" for b, n in
                             sorted(c["buckets"].items()))
            lines.append(f"  {basis:<16} n={c['n']} "
                         f"mean_error=x{c['mean_ratio']}  {curve}")
    drift = data["drift"]
    lines.append(f"-- drift (> x{drift_threshold:g} vs own warm "
                 f"history) --")
    if not drift:
        lines.append("  none — every structure tracks its history")
    for d in drift:
        name = d["label"] or d["key"]
        direction = "SLOWER" if d["slower"] else "faster"
        lines.append(
            f"  DRIFT {name:<24} x{d['ratio']:<7g} {direction}: "
            f"history {d['history_us'] / 1e3:.1f}ms -> last "
            f"{d['last_us'] / 1e3:.1f}ms over {d['runs']} runs "
            f"[{d['key']}]")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self test (tier-1 via tests/test_history.py; synthetic fixtures only)
# ---------------------------------------------------------------------------

def self_test() -> int:
    """Built-in proof on synthetic fixtures: (1) a drifted structure is
    flagged and a clean one is not; (2) corrupt/truncated lines are
    tolerated on load; (3) compaction enforces the entry cap with LRU
    order; (4) calibration records aggregate into the per-basis
    curve."""
    import tempfile
    from spark_rapids_tpu.obs.history import PerfHistoryStore

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "perf_history.jsonl")

        # 1: drift fixture — "steady" holds ~100ms, "drifty" jumps 5x
        st = PerfHistoryStore(path, decay=0.3)
        for i in range(6):
            st.record("steady", {"device_us": 100_000.0 + i * 500,
                                 "wall_ms": 101.0, "compile_ms": 0.0,
                                 "src_bytes": 1 << 20,
                                 "label": "steady_q"})
        for us in (100_000.0, 101_000.0, 99_500.0, 500_000.0):
            st.record("drifty", {"device_us": us, "wall_ms": us / 1e3,
                                 "compile_ms": 0.0,
                                 "src_bytes": 1 << 20,
                                 "label": "drifty_q"})
        data = report_data(st, drift_threshold=2.0)
        flagged = {d["key"] for d in data["drift"]}
        assert flagged == {"drifty"}, \
            f"drift fixture mis-flagged: {flagged}"
        assert data["drift"][0]["slower"] is True
        assert data["drift"][0]["ratio"] > 2.0
        # the clean fixture is genuinely clean, not just unmeasured
        steady = next(r for r in data["top_structures"]
                      if r["key"] == "steady")
        assert steady["warm_runs"] == 6 and steady["drift_ratio"] is not None

        # 2: corrupt + truncated tail tolerated on reload
        with open(path, "a") as f:
            f.write("%% not json at all %%\n")
            f.write('{"k": "steady", "device_us": 12')   # truncated
        st2 = PerfHistoryStore(path)
        assert st2.corrupt_lines == 2, st2.corrupt_lines
        assert st2.get("steady").runs == 6
        assert st2.get("drifty").runs == 4

        # 3: entry-capped LRU compaction — 5 keys into a 2-entry store
        path3 = os.path.join(td, "cap.jsonl")
        st3 = PerfHistoryStore(path3, max_entries=2, decay=0.5)
        for i in range(5):
            st3.record(f"k{i}", {"device_us": 1000.0 + i,
                                 "wall_ms": 1.0, "compile_ms": 0.0})
        assert st3.compactions >= 1
        keys = set(st3.aggregates())
        assert keys == {"k3", "k4"}, keys          # newest survive
        st3b = PerfHistoryStore(path3)             # and reload intact
        assert set(st3b.aggregates()) == {"k3", "k4"}
        assert st3b.get("k4").runs == 1

        # 4: calibration curve from predicted records
        path4 = os.path.join(td, "cal.jsonl")
        st4 = PerfHistoryStore(path4)
        for _ in range(4):
            st4.record("c", {"device_us": 200_000.0, "wall_ms": 200.0,
                             "compile_ms": 0.0,
                             "predicted_us": 100_000.0,
                             "basis": "exact_history"})
        cal = st4.calibration()["exact_history"]
        assert cal["n"] == 4 and abs(cal["mean_ratio"] - 2.0) < 1e-6

        # 5: working-set calibration — a measured-ws record carrying a
        # working-set prediction folds the reservation-vs-actual curve
        # and the aggregate serves a measured-basis working set
        path5 = os.path.join(td, "ws.jsonl")
        st5 = PerfHistoryStore(path5)
        for _ in range(3):
            st5.record("w", {"device_us": 50_000.0, "wall_ms": 50.0,
                             "compile_ms": 0.0,
                             "ws_bytes": 1 << 20, "ws_basis": "measured",
                             "predicted_ws": float(1 << 22),
                             "ws_pred_basis": "source",
                             "label": "ws_q"})
        ws_cal = st5.ws_calibration()["source"]
        assert ws_cal["n"] == 3 and abs(ws_cal["mean_ratio"] - 4.0) < 1e-6
        agg = st5.get("w")
        assert agg.ws_runs == 3 and abs(agg.ws_bytes - (1 << 20)) < 1
        data5 = report_data(st5)
        row = data5["top_structures"][0]
        assert row["ws_bytes"] == 1 << 20 and row["ws_runs"] == 3
        assert data5["ws_calibration"]["source"]["n"] == 3
        # and the curve survives a compaction round trip
        st5._compact()
        st5b = PerfHistoryStore(path5)
        assert st5b.ws_calibration()["source"]["n"] == 3
        assert st5b.get("w").ws_runs == 3

    print("history_report self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", nargs="?",
                    help="history dir (spark.rapids.tpu.history.dir) "
                         "or perf_history.jsonl path")
    ap.add_argument("--top", type=int, default=10,
                    help="structures shown in the cumulative-time table")
    ap.add_argument("--drift-threshold", type=float, default=2.0,
                    help="flag structures whose newest warm measurement "
                         "shifted more than this factor from their "
                         "history (default 2.0)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--fail-on-drift", action="store_true",
                    help="exit 1 when any structure drifted SLOWER "
                         "(CI guard)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in synthetic self test "
                         "(tier-1 wired)")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()
    if not args.target:
        ap.print_usage()
        return 2
    store = load_store(args.target)
    data = report_data(store, args.top, args.drift_threshold)
    if args.json:
        print(json.dumps(data, default=str))
    else:
        print(render(data, args.drift_threshold))
    if args.fail_on_drift and any(d["slower"] for d in data["drift"]):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
