#!/usr/bin/env python
"""Bench regression gate: compare a bench.py result against the
committed BENCH_r*/MULTICHIP_r* trajectory and fail on per-query
`device_ms` regressions.

The perf trajectory is the product (ROADMAP north star: as fast as the
hardware allows); a PR that silently doubles a query's device time must
fail CI, not wait for a human to eyeball BENCH_r{N}.json.  This gate:

  1. loads every trajectory file (three accepted shapes: the driver
     wrapper `{parsed, tail, ...}`, a raw bench.py final-line dict, or
     a `{"<suite>_suite_queries": ...}` fragment).  Wrapper files whose
     `parsed` is null still contribute: per-query records are recovered
     from the truncated `tail` text (the last stdout line is a complete
     JSON result, but the driver keeps only its tail — individual
     `"qN": {...}` objects inside it are intact and parse alone);
  2. normalizes every timing to NET-OF-FLOOR milliseconds — the
     emitted `device_ms_net` when present, else `device_ms` minus that
     result's own `tunnel_rtt_ms` — so the ~121ms harness round trip
     can neither hide nor manufacture a regression, and builds the
     per-query baseline as the MINIMUM each query ever achieved across
     baseline files from the SAME backend (a cpu-backend run never
     gates against tunneled-TPU numbers; files predating the `backend`
     field count as the tunnel's 'axon' platform);
  3. compares the current result: a query REGRESSES when its net ms
     exceeds baseline * (1 + threshold) — default threshold 0.25 —
     and exceeds the absolute noise floor (--min-ms, default 50 ms, so
     sub-frame jitter cannot fail the gate).

With no --current, the newest trajectory file that carries per-query
data is the "current" result and the older files are the baseline, so
running the script bare answers "did the latest round regress?" and
exits 0 on a healthy trajectory.

Queries only present on one side are reported but never fail the gate
(coverage growth must not look like a regression).  Exit codes: 0 ok,
1 regressions found, 2 usage/no-data.

Usage:
    python scripts/check_regression.py                  # gate the trajectory
    python scripts/check_regression.py --current out.json [traj.json ...]
    python scripts/check_regression.py --threshold 0.25 --min-ms 50
"""
import argparse
import ast
import glob
import json
import os
import re
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-query records inside a (possibly head-truncated) bench JSON line
_QREC_RE = re.compile(r'"(q\d+[a-z]?)":\s*(\{[^{}]*\})')
_RTT_RE = re.compile(r'"tunnel_rtt_ms":\s*([0-9.]+)')
_BACKEND_RE = re.compile(r'"backend":\s*"(\w+)"')

#: files predating the "backend" field all came from the tunneled-TPU
#: harness ('axon' platform) — tag them so timings are only ever
#: compared against runs on the SAME hardware
_DEFAULT_BACKEND = "axon"

#: multichip dry runs force the CPU backend (8 virtual devices) — files
#: predating the "backend" field compare against cpu-backend rounds
_MULTICHIP_BACKEND = "cpu"


def extract_multichip(doc):
    """-> ({'mc:<timing key>': ms}, backend or None) from a multichip
    result: the fused-groupby / ragged / window / mesh-query seconds in
    `multichip_timings_s` become gate-able millisecond entries under an
    `mc:` prefix (never colliding with single-chip qN names).  Accepts
    the suite runner's JSON line, the driver wrapper, and the legacy
    dryrun tail (a python-repr dict — ast.literal_eval parses it)."""
    if not isinstance(doc, dict):
        return {}, None
    tim = doc.get("multichip_timings_s")
    if isinstance(tim, dict):
        out = {f"mc:{k}": float(v) * 1e3 for k, v in tim.items()
               if isinstance(v, (int, float))}
        return out, str(doc.get("backend") or _MULTICHIP_BACKEND)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_multichip(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str) and "multichip_timings_s" in tail:
        for line in reversed(tail.splitlines()):
            if "multichip_timings_s" not in line:
                continue
            try:
                rec = ast.literal_eval(line.strip())
            except (ValueError, SyntaxError):
                try:
                    rec = json.loads(line.strip())
                except json.JSONDecodeError:
                    continue
            if isinstance(rec, dict):
                out, backend = extract_multichip(rec)
                if out:
                    return out, backend
    return {}, None


def extract_serving(doc):
    """-> ({'sv:<entry>': ms}, backend or None) from a bench.py
    --serving result: the `serving_latency_ms` gate dict (per-level
    p99/mean client-observed latency, lower = better) becomes `sv:`-
    prefixed entries that gate like per-query device_ms under the same
    backend-separation rule (never colliding with qN / mc: names).
    Accepts the runner's JSON line, the driver wrapper, and a tail."""
    if not isinstance(doc, dict):
        return {}, None
    lat = doc.get("serving_latency_ms")
    fleet = doc.get("serving_fleet")
    if (isinstance(lat, dict) and lat) or \
            (isinstance(fleet, dict) and fleet):
        out = {f"sv:{k}": float(v)
               for k, v in (lat if isinstance(lat, dict) else {}).items()
               if isinstance(v, (int, float))}
        # cross-process utilization skew from the federated fleet
        # registry (mp levels): gates under the same sv: rules — a skew
        # regression means dispatch stopped spreading work.  Scaled
        # x100 (1.0 -> 100) so a real imbalance clears the --min-ms
        # noise floor, which raw max/min ratios (~1-3) never would.
        out.update({f"sv:{k}": float(v) * 100.0
                    for k, v in (fleet if isinstance(fleet, dict)
                                 else {}).items()
                    if isinstance(v, (int, float))})
        return out, str(doc.get("backend") or _DEFAULT_BACKEND)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_serving(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str) and ("serving_latency_ms" in tail
                                  or "serving_fleet" in tail):
        for line in reversed(tail.splitlines()):
            if "serving_latency_ms" not in line and \
                    "serving_fleet" not in line:
                continue
            try:
                rec = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out, backend = extract_serving(rec)
                if out:
                    return out, backend
    return {}, None


def extract_kernels(doc):
    """-> ({'kn:<entry>': ms}, backend or None) from a bench.py
    --kernels result: the `kernel_timings_ms` A/B dict (pallas vs
    sorted per kernel family / size / skew, lower = better) becomes
    `kn:`-prefixed entries that gate like per-query device_ms under
    the same backend-separation rule (never colliding with qN / mc: /
    sv: names).  Accepts the runner's JSON line, the driver wrapper,
    and a tail."""
    if not isinstance(doc, dict):
        return {}, None
    tim = doc.get("kernel_timings_ms")
    if isinstance(tim, dict) and tim:
        out = {f"kn:{k}": float(v) for k, v in tim.items()
               if isinstance(v, (int, float))}
        return out, str(doc.get("backend") or _DEFAULT_BACKEND)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_kernels(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str) and "kernel_timings_ms" in tail:
        for line in reversed(tail.splitlines()):
            if "kernel_timings_ms" not in line:
                continue
            try:
                rec = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out, backend = extract_kernels(rec)
                if out:
                    return out, backend
    return {}, None


def extract_encodings(doc):
    """-> ({'en:<entry>': ms}, backend or None) from a bench.py
    --encodings result: the `encoding_timings_ms` A/B dict (encoded vs
    decode-first per encoding family / operator / selectivity, lower =
    better) becomes `en:`-prefixed entries that gate like per-query
    device_ms under the same backend-separation rule (never colliding
    with qN / mc: / sv: / kn: names).  Accepts the runner's JSON line,
    the driver wrapper, and a tail."""
    if not isinstance(doc, dict):
        return {}, None
    tim = doc.get("encoding_timings_ms")
    if isinstance(tim, dict) and tim:
        out = {f"en:{k}": float(v) for k, v in tim.items()
               if isinstance(v, (int, float))}
        return out, str(doc.get("backend") or _DEFAULT_BACKEND)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_encodings(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str) and "encoding_timings_ms" in tail:
        for line in reversed(tail.splitlines()):
            if "encoding_timings_ms" not in line:
                continue
            try:
                rec = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out, backend = extract_encodings(rec)
                if out:
                    return out, backend
    return {}, None


def extract_ooc(doc):
    """-> ({'oc:<entry>': ms}, backend or None) from a bench.py --ooc
    result: the `ooc_timings_ms` dict ({qN}_capped = memory-capped wall
    through the out-of-core tier, {qN}_uncapped = the resident
    baseline, lower = better) becomes `oc:`-prefixed entries that gate
    like per-query device_ms under the same backend-separation rule
    (never colliding with qN / mc: / sv: / kn: / en: names).  Accepts
    the runner's JSON line, the driver wrapper, and a tail."""
    if not isinstance(doc, dict):
        return {}, None
    tim = doc.get("ooc_timings_ms")
    if isinstance(tim, dict) and tim:
        out = {f"oc:{k}": float(v) for k, v in tim.items()
               if isinstance(v, (int, float))}
        return out, str(doc.get("backend") or _DEFAULT_BACKEND)
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_ooc(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str) and "ooc_timings_ms" in tail:
        for line in reversed(tail.splitlines()):
            if "ooc_timings_ms" not in line:
                continue
            try:
                rec = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out, backend = extract_ooc(rec)
                if out:
                    return out, backend
    return {}, None


def _rec_ms(rec: dict, rtt_ms: float):
    """Net-of-floor milliseconds for one per-query record: the explicit
    `device_ms_net` when the bench emitted it, else `device_ms` minus
    the result's own tunnel RTT (older trajectory files) — so a ~121ms
    harness round trip can neither hide a regression in a fast query
    nor manufacture one when the tunnel changes."""
    if rec.get("device_ms_net"):
        return float(rec["device_ms_net"])
    if rec.get("device_ms"):
        return max(float(rec["device_ms"]) - rtt_ms, 0.001)
    return None


def extract_compile_ms(doc) -> list:
    """Per-query COLD compile milliseconds (compile_ms_cold) of a
    result document — [] for documents predating the field.  The gate
    compares the MEDIAN, so one pathological query cannot fail it and
    coverage growth cannot hide a fleet-wide compile regression."""
    out = []
    if not isinstance(doc, dict):
        return out
    for key, val in doc.items():
        if key.endswith("_suite_queries") and isinstance(val, dict):
            for rec in val.values():
                if isinstance(rec, dict) and \
                        rec.get("compile_ms_cold") is not None:
                    out.append(float(rec["compile_ms_cold"]))
    if out:
        return out
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return extract_compile_ms(parsed)
    return out


def extract_segments(doc) -> dict:
    """-> {query: {segment node: device_ms}} from the per-query profile
    summaries bench embeds (profile.segments runs, PR 9) — {} for
    records predating the attribution plane.  When the gate fails a
    query, the worst-regressed SEGMENT is cited from these."""
    out = {}
    if not isinstance(doc, dict):
        return out
    for key, val in doc.items():
        if key.endswith("_suite_queries") and isinstance(val, dict):
            for q, rec in val.items():
                prof = rec.get("profile") if isinstance(rec, dict) \
                    else None
                segs = (prof or {}).get("segments") \
                    if isinstance(prof, dict) else None
                if segs:
                    out[q] = {s["node"]: float(s.get("device_ms", 0.0))
                              for s in segs
                              if isinstance(s, dict) and "node" in s}
    if out:
        return out
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return extract_segments(parsed)
    return out


def extract_hbm(doc) -> dict:
    """-> {query: hbm_peak_bytes} from the per-query measured-HBM
    fields bench/multichip records embed (the memory-attribution
    plane, ISSUE 14) — {} for records predating it.  Gated like
    device_ms under the same backend-separation rule: a PR that
    silently doubles a query's working set fails CI even when its
    wall time holds."""
    out = {}
    if not isinstance(doc, dict):
        return out
    for key, val in doc.items():
        if key.endswith("_suite_queries") and isinstance(val, dict):
            for q, rec in val.items():
                if isinstance(rec, dict) and rec.get("hbm_peak_bytes"):
                    out[q] = float(rec["hbm_peak_bytes"])
    if out:
        return out
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return extract_hbm(parsed)
    return out


def load_hbm(path: str) -> dict:
    """{query: hbm_peak_bytes} of one trajectory file ({} on any read
    problem — like segments, absence never fails the gate by itself)."""
    try:
        with open(path) as f:
            return extract_hbm(json.load(f))
    except (OSError, json.JSONDecodeError, ValueError):
        return {}


def extract_overheads(doc) -> dict:
    """-> {query: {seam_count, seam_ms, dispatch_ms, pad_waste_ms,
    pad_waste_share}} from the per-query wall_breakdown embeds bench
    records carry (the wall-decomposition plane, ISSUE 18) — {} for
    records predating it.  seam_count and pad_waste_share gate like
    device_ms under the same backend-separation rule: a PR that adds a
    seam round-trip or blows up bucket padding fails CI even when its
    wall time holds at this scale."""
    out = {}
    if not isinstance(doc, dict):
        return out
    for key, val in doc.items():
        if key.endswith("_suite_queries") and isinstance(val, dict):
            for q, rec in val.items():
                bd = rec.get("wall_breakdown") \
                    if isinstance(rec, dict) else None
                if not isinstance(bd, dict) or not bd.get("wall_ms"):
                    continue
                wall = float(bd["wall_ms"])
                pad = float(bd.get("pad_waste_ms") or 0.0)
                out[q] = {
                    "seam_count": int(bd.get("seam_count") or 0),
                    "seam_ms": float(bd.get("seam_ms") or 0.0),
                    "dispatch_ms": float(bd.get("dispatch_ms") or 0.0),
                    "pad_waste_ms": pad,
                    "pad_waste_share": pad / wall if wall else 0.0,
                }
    if out:
        return out
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return extract_overheads(parsed)
    return out


def load_overheads(path: str) -> dict:
    """{query: overhead fields} of one trajectory file ({} on any read
    problem — like hbm, absence never fails the gate by itself)."""
    try:
        with open(path) as f:
            return extract_overheads(json.load(f))
    except (OSError, json.JSONDecodeError, ValueError):
        return {}


def extract_queries(doc):
    """-> (query name -> net device_ms, backend tag) from any accepted
    result shape; ({}, backend) when the document carries no per-query
    timings."""
    out = {}
    if not isinstance(doc, dict):
        return out, _DEFAULT_BACKEND
    rtt_ms = float(doc.get("tunnel_rtt_ms") or 0.0)
    for key, val in doc.items():
        if key.endswith("_suite_queries") and isinstance(val, dict):
            for q, rec in val.items():
                if isinstance(rec, dict):
                    ms = _rec_ms(rec, rtt_ms)
                    if ms is not None:
                        out[q] = ms
    if out:
        return out, str(doc.get("backend") or _DEFAULT_BACKEND)
    # driver wrapper: prefer the parsed final line, else mine the tail
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out, backend = extract_queries(parsed)
        if out:
            return out, backend
    tail = doc.get("tail")
    if isinstance(tail, str):
        m_rtt = None
        for m_rtt in _RTT_RE.finditer(tail):
            pass                      # last match wins (final line)
        rtt_ms = float(m_rtt.group(1)) if m_rtt else 0.0
        for m in _QREC_RE.finditer(tail):
            try:
                rec = json.loads(m.group(2))
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                ms = _rec_ms(rec, rtt_ms)
                if ms is not None:
                    # later matches win: the FINAL summary line is
                    # printed last and covers every query measured
                    out[m.group(1)] = ms
        m_b = None
        for m_b in _BACKEND_RE.finditer(tail):
            pass
        return out, (m_b.group(1) if m_b else _DEFAULT_BACKEND)
    return out, _DEFAULT_BACKEND


def load_file(path: str):
    with open(path) as f:
        doc = json.load(f)
    qs, backend = extract_queries(doc)
    mc, mc_backend = extract_multichip(doc)
    if mc:
        # multichip timings gate alongside per-query device_ms under
        # their mc: prefix; a pure-multichip file takes the multichip
        # backend tag (cpu for pre-backend dryrun rounds)
        qs = {**qs, **mc}
        if not backend or backend == _DEFAULT_BACKEND:
            backend = mc_backend
    sv, sv_backend = extract_serving(doc)
    if sv:
        # serving latency entries gate under their sv: prefix; a pure
        # serving record carries its own backend tag
        qs = {**qs, **sv}
        if (not backend or backend == _DEFAULT_BACKEND) and sv_backend:
            backend = sv_backend
    kn, kn_backend = extract_kernels(doc)
    if kn:
        # kernel-microbench entries gate under their kn: prefix; a pure
        # kernels record carries its own backend tag
        qs = {**qs, **kn}
        if (not backend or backend == _DEFAULT_BACKEND) and kn_backend:
            backend = kn_backend
    en, en_backend = extract_encodings(doc)
    if en:
        # encoded-execution microbench entries gate under their en:
        # prefix; a pure encodings record carries its own backend tag
        qs = {**qs, **en}
        if (not backend or backend == _DEFAULT_BACKEND) and en_backend:
            backend = en_backend
    oc, oc_backend = extract_ooc(doc)
    if oc:
        # memory-capped out-of-core leg entries gate under their oc:
        # prefix; a pure ooc record carries its own backend tag
        qs = {**qs, **oc}
        if (not backend or backend == _DEFAULT_BACKEND) and oc_backend:
            backend = oc_backend
    return qs, backend, extract_compile_ms(doc)


def load_segments(path: str) -> dict:
    """{query: {segment: device_ms}} of one trajectory file ({} on any
    read problem — segment citation is best-effort color, never a gate
    failure of its own)."""
    try:
        with open(path) as f:
            return extract_segments(json.load(f))
    except (OSError, json.JSONDecodeError, ValueError):
        return {}


def worst_segment_line(q: str, cur_segs: dict, base_segs: dict):
    """The segment-level citation for one regressed query: the segment
    with the largest device_ms growth vs baseline (or the dominant
    current segment when the baseline has no segment data)."""
    cur = cur_segs.get(q) or {}
    if not cur:
        return None
    base = base_segs.get(q) or {}
    shared = set(cur) & set(base)
    if shared:
        node = max(shared, key=lambda n: cur[n] - base[n])
        return (f"    worst segment: {node} "
                f"{base[node]:.1f} -> {cur[node]:.1f} ms "
                f"(+{cur[node] - base[node]:.1f})")
    node = max(cur, key=cur.get)
    return (f"    dominant segment: {node} {cur[node]:.1f} ms "
            f"(no baseline segment data)")


def _median(vals: list):
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def default_trajectory() -> list:
    return (sorted(glob.glob(os.path.join(_ROOT, "BENCH_r*.json"))) +
            sorted(glob.glob(os.path.join(_ROOT, "MULTICHIP_r*.json"))) +
            sorted(glob.glob(os.path.join(_ROOT, "SERVING_r*.json"))) +
            sorted(glob.glob(os.path.join(_ROOT, "KERNELS_r*.json"))) +
            sorted(glob.glob(os.path.join(_ROOT, "ENCODINGS_r*.json"))) +
            sorted(glob.glob(os.path.join(_ROOT, "OOC_r*.json"))))


def compare(current: dict, baseline: dict, threshold: float,
            min_ms: float) -> dict:
    """-> {regressions, improved, ok, only_current, only_baseline}."""
    regressions, improved, ok = [], [], []
    for q in sorted(set(current) & set(baseline),
                    key=lambda s: (len(s), s)):
        cur, base = current[q], baseline[q]
        ratio = cur / base if base else float("inf")
        row = {"query": q, "device_ms": cur, "baseline_ms": base,
               "ratio": round(ratio, 3)}
        if cur > base * (1.0 + threshold) and cur > min_ms:
            regressions.append(row)
        elif ratio < 1.0:
            improved.append(row)
        else:
            ok.append(row)
    return {"regressions": regressions, "improved": improved, "ok": ok,
            "only_current": sorted(set(current) - set(baseline)),
            "only_baseline": sorted(set(baseline) - set(current))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trajectory", nargs="*",
                    help="baseline result files (default: the committed "
                         "BENCH_r*/MULTICHIP_r* trajectory)")
    ap.add_argument("--current",
                    help="bench result to gate (default: the newest "
                         "trajectory file with per-query data)")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="fractional device_ms regression that fails "
                         "(default 0.25 = +25%%)")
    ap.add_argument("--min-ms", type=float, default=50.0,
                    help="absolute floor below which timings are noise, "
                         "never regressions (default 50)")
    ap.add_argument("--compile-threshold", type=float, default=0.5,
                    help="fractional MEDIAN compile_ms_cold regression "
                         "that fails (default 0.5 = +50%%; compile wall "
                         "is noisier than device wall)")
    ap.add_argument("--compile-min-ms", type=float, default=1000.0,
                    help="median compile floor below which compile "
                         "timings never regress (default 1000)")
    ap.add_argument("--hbm-threshold", type=float, default=0.25,
                    help="fractional per-query hbm_peak_bytes growth "
                         "that fails (default 0.25 = +25%%; the "
                         "memory-attribution plane's measured peaks)")
    ap.add_argument("--hbm-min-bytes", type=float, default=float(1 << 20),
                    help="absolute floor below which HBM peaks are "
                         "noise, never regressions (default 1 MiB)")
    ap.add_argument("--seam-threshold", type=float, default=0.25,
                    help="fractional per-query seam-count growth that "
                         "fails (default 0.25 = +25%%; the "
                         "wall-decomposition plane's seam brackets)")
    ap.add_argument("--seam-min", type=float, default=2,
                    help="seam-count floor below which growth is noise, "
                         "never a regression (default 2: 1 -> 1 never "
                         "fails, 1 -> 2 does)")
    ap.add_argument("--pad-threshold", type=float, default=0.25,
                    help="fractional per-query pad-waste-share growth "
                         "that fails (default 0.25 = +25%%; share = "
                         "pad_waste_ms / profiled wall)")
    ap.add_argument("--pad-min-share", type=float, default=0.05,
                    help="pad-waste share floor below which growth is "
                         "noise, never a regression (default 0.05)")
    ap.add_argument("--history-dir",
                    help="performance-history dir "
                         "(spark.rapids.tpu.history.dir): when the "
                         "gate fails, cite the drifted plan "
                         "STRUCTURES and their measured history "
                         "(scripts/history_report.py drift detection) "
                         "next to the regressed queries")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    args = ap.parse_args(argv)

    paths = args.trajectory or default_trajectory()
    per_file = {}
    backends = {}
    compile_ms = {}
    for p in paths:
        try:
            qs, backend, cms = load_file(p)
        except (OSError, json.JSONDecodeError) as e:
            print(f"# skipping unreadable {p}: {e}", file=sys.stderr)
            continue
        per_file[p] = qs
        backends[p] = backend
        compile_ms[p] = cms
    with_data = [p for p in per_file if per_file[p]]

    if args.current:
        try:
            current, cur_backend, cur_compile = load_file(args.current)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read --current {args.current}: {e}",
                  file=sys.stderr)
            return 2
        current_name = args.current
        baseline_files = with_data
    else:
        if not with_data:
            print("no trajectory file carries per-query device_ms — "
                  "nothing to gate", file=sys.stderr)
            return 2
        current_name = with_data[-1]
        current = per_file[current_name]
        cur_backend = backends[current_name]
        cur_compile = compile_ms[current_name]
        baseline_files = with_data[:-1]
    if not current:
        print(f"{current_name} carries no per-query device_ms",
              file=sys.stderr)
        return 2

    # milliseconds only compare on the SAME hardware: a cpu-backend CI
    # run gating against tunneled-TPU baselines (or vice versa) would
    # manufacture regressions/improvements out of the platform change
    same_hw = [p for p in baseline_files if backends[p] == cur_backend]
    skipped_hw = [p for p in baseline_files if backends[p] != cur_backend]
    if skipped_hw:
        print(f"# backend={cur_backend}: skipping "
              f"{len(skipped_hw)} baseline file(s) from other backends "
              f"({', '.join(sorted({backends[p] for p in skipped_hw}))})",
              file=sys.stderr)
    baseline_files = same_hw

    baseline = {}
    for p in baseline_files:
        for q, v in per_file[p].items():
            baseline[q] = min(baseline.get(q, v), v)

    # segment-level attribution (best-effort): when a query regresses,
    # cite the worst-regressed SEGMENT from the embedded profiles
    cur_segs = load_segments(current_name) \
        if os.path.exists(current_name) else {}
    base_segs = {}
    for p in baseline_files:
        for q, per in load_segments(p).items():
            tgt = base_segs.setdefault(q, {})
            for n, v in per.items():
                tgt[n] = min(tgt.get(n, v), v)

    res = compare(current, baseline, args.threshold, args.min_ms)
    for row in res["regressions"]:
        cite = worst_segment_line(row["query"], cur_segs, base_segs)
        if cite:
            row["worst_segment"] = cite.strip()
    if args.json:
        print(json.dumps({"current": current_name,
                          "baseline_files": baseline_files,
                          "threshold": args.threshold, **res}))
    else:
        print(f"current:  {current_name} ({len(current)} queries)")
        print(f"baseline: best-of {len(baseline_files)} file(s), "
              f"{len(baseline)} queries; threshold "
              f"+{args.threshold:.0%}, noise floor {args.min_ms:g} ms")
        for row in res["regressions"]:
            print(f"  REGRESSION {row['query']}: {row['device_ms']:.1f} ms"
                  f" vs {row['baseline_ms']:.1f} ms "
                  f"(x{row['ratio']:.2f})")
            if row.get("worst_segment"):
                print(f"    {row['worst_segment']}")
        for row in res["improved"]:
            print(f"  improved   {row['query']}: {row['device_ms']:.1f} ms"
                  f" vs {row['baseline_ms']:.1f} ms "
                  f"(x{row['ratio']:.2f})")
        for row in res["ok"]:
            print(f"  ok         {row['query']}: {row['device_ms']:.1f} ms"
                  f" vs {row['baseline_ms']:.1f} ms "
                  f"(x{row['ratio']:.2f})")
        if res["only_current"]:
            print(f"  new (no baseline): {', '.join(res['only_current'])}")
        if not baseline:
            print("  (empty baseline — nothing to regress against)")
    # -- compile-latency gate: median cold compile_ms, same backend rule
    compile_reg = False
    cur_med = _median(cur_compile)
    base_meds = [_median(compile_ms.get(p) or []) for p in baseline_files]
    base_meds = [m for m in base_meds if m is not None]
    if cur_med is not None and base_meds:
        base_med = min(base_meds)
        if cur_med > base_med * (1.0 + args.compile_threshold) and \
                cur_med > args.compile_min_ms:
            compile_reg = True
            print(f"  COMPILE REGRESSION: median compile_ms_cold "
                  f"{cur_med:.0f} vs {base_med:.0f} "
                  f"(x{cur_med / base_med:.2f}, threshold "
                  f"+{args.compile_threshold:.0%})")
        else:
            print(f"  compile ok: median compile_ms_cold {cur_med:.0f} "
                  f"vs baseline {base_med:.0f}")
    elif cur_med is not None:
        print(f"  compile: median compile_ms_cold {cur_med:.0f} "
              f"(no baseline carries compile data)")

    # -- HBM-peak gate: per-query measured working-set peaks (the
    # memory-attribution plane), best-of baseline, same backend rule
    hbm_regs = []
    cur_hbm = load_hbm(current_name) if os.path.exists(current_name) \
        else {}
    if cur_hbm:
        base_hbm = {}
        for p in baseline_files:
            for q, v in load_hbm(p).items():
                base_hbm[q] = min(base_hbm.get(q, v), v)
        for q in sorted(set(cur_hbm) & set(base_hbm),
                        key=lambda s: (len(s), s)):
            cur_b, base_b = cur_hbm[q], base_hbm[q]
            if cur_b > base_b * (1.0 + args.hbm_threshold) and \
                    cur_b > args.hbm_min_bytes:
                hbm_regs.append((q, cur_b, base_b))
                print(f"  HBM REGRESSION {q}: peak {cur_b:.0f} bytes "
                      f"vs {base_b:.0f} (x{cur_b / base_b:.2f}, "
                      f"threshold +{args.hbm_threshold:.0%})")
        if not hbm_regs and base_hbm:
            print(f"  hbm ok: {len(set(cur_hbm) & set(base_hbm))} "
                  f"query peak(s) within +{args.hbm_threshold:.0%} of "
                  f"baseline")

    # -- overhead gates: per-query seam count and pad-waste share (the
    # wall-decomposition plane), best-of baseline, same backend rule
    overhead_regs = []
    cur_ov = load_overheads(current_name) \
        if os.path.exists(current_name) else {}
    if cur_ov:
        base_ov = {}
        for p in baseline_files:
            for q, rec in load_overheads(p).items():
                tgt = base_ov.get(q)
                if tgt is None:
                    base_ov[q] = dict(rec)
                else:
                    for fk in ("seam_count", "pad_waste_share"):
                        tgt[fk] = min(tgt[fk], rec[fk])
        shared = sorted(set(cur_ov) & set(base_ov),
                        key=lambda s: (len(s), s))
        for q in shared:
            cur_n = cur_ov[q]["seam_count"]
            base_n = base_ov[q]["seam_count"]
            if cur_n > base_n * (1.0 + args.seam_threshold) and \
                    cur_n >= args.seam_min:
                overhead_regs.append((q, "seam_count", cur_n, base_n))
                print(f"  SEAM REGRESSION {q}: {cur_n} seam(s) vs "
                      f"{base_n} baseline (each seam is a host "
                      f"round-trip + re-bucket; threshold "
                      f"+{args.seam_threshold:.0%})")
            cur_s = cur_ov[q]["pad_waste_share"]
            base_s = base_ov[q]["pad_waste_share"]
            if cur_s > base_s * (1.0 + args.pad_threshold) and \
                    cur_s > args.pad_min_share:
                overhead_regs.append((q, "pad_waste_share", cur_s,
                                      base_s))
                print(f"  PAD-WASTE REGRESSION {q}: "
                      f"{cur_s:.1%} of profiled wall vs {base_s:.1%} "
                      f"baseline (bucket-quantization tax; threshold "
                      f"+{args.pad_threshold:.0%})")
        if not overhead_regs and shared:
            print(f"  overhead ok: {len(shared)} query breakdown(s) "
                  f"within +{args.seam_threshold:.0%} seams / "
                  f"+{args.pad_threshold:.0%} pad share of baseline")

    if res["regressions"] or compile_reg or hbm_regs or overhead_regs:
        if res["regressions"]:
            print(f"{len(res['regressions'])} per-query regression(s) "
                  f"beyond +{args.threshold:.0%}")
        _cite_history_drift(args.history_dir)
        return 1
    print("no per-query device_ms regressions")
    return 0


def _cite_history_drift(history_dir) -> None:
    """Gate-failure color from the performance-history plane: name the
    plan structures whose own measured history drifted — a regressed
    query almost always means one of these, and the structure key is
    reproducible triage (best-effort: a missing/empty history never
    changes the exit code)."""
    if not history_dir:
        return
    try:
        sys.path.insert(0, _ROOT)
        from spark_rapids_tpu.obs.history import (HISTORY_FILE,
                                                  PerfHistoryStore)
        path = history_dir if not os.path.isdir(history_dir) \
            else os.path.join(history_dir, HISTORY_FILE)
        if not os.path.exists(path):
            print(f"  (no history file at {path} — drift citation "
                  f"skipped)")
            return
        drifted = PerfHistoryStore(path).drifted(2.0)
        slower = [d for d in drifted if d["slower"]]
        if not slower:
            print("  history: no structure drifted slower than 2x its "
                  "own measured history (regression may be "
                  "environmental)")
            return
        print("  history drift (structures measured >2x slower than "
              "their own history — scripts/history_report.py):")
        for d in slower[:5]:
            name = d["label"] or d["key"]
            print(f"    {name}: {d['history_us'] / 1e3:.1f}ms -> "
                  f"{d['last_us'] / 1e3:.1f}ms (x{d['ratio']:g}, "
                  f"{d['runs']} runs) [{d['key']}]")
    except Exception as e:                   # noqa: BLE001
        print(f"  (history drift citation unavailable: "
              f"{type(e).__name__}: {e})")


if __name__ == "__main__":
    raise SystemExit(main())
