#!/usr/bin/env python
"""Headline benchmark: TPC-H q6 (SF1-sized lineitem) through the framework.

Prints ONE json line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = CPU time / TPU per-query time (>1 means the TPU path wins)
against an in-process vectorized pyarrow baseline — a *stronger* stand-in
for CPU Spark than Spark itself (columnar C++ kernels, no JVM/task
overhead), so the reported speedup is conservative vs the BASELINE.md
north-star.

Methodology.  The TPU number is device-resident *throughput*: K independent
query executions are dispatched back-to-back and every result is fetched in
ONE batched D2H transfer; per-query time = wall / K.  This mirrors how both
the reference and Spark itself actually run — many concurrent tasks per
device (GpuSemaphore concurrentGpuTasks, RapidsConf.scala:544-551) with
per-task result latency hidden by the pipeline.  It matters doubly here
because this chip sits behind a tunnel with ~60 ms round-trip latency: a
single-query sync measures the tunnel, not the engine (round-1's 66 ms
"q6 time" was ~64 ms of RTT + ~2 ms of compute).  Single-shot latency and
cold end-to-end (host upload included) times are reported on stderr for
transparency.
"""
import json
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

SF1_ROWS = 6_001_215
DATE_LO = 8766    # 1994-01-01 in days since epoch
DATE_HI = 9131    # 1995-01-01
PIPELINE_DEPTH = 64


def gen_lineitem(n: int) -> pa.Table:
    rng = np.random.default_rng(20240706)
    return pa.table({
        "l_quantity": pa.array(rng.integers(1, 51, n), pa.int64()),
        "l_extendedprice": pa.array(rng.uniform(900.0, 105000.0, n).round(2)),
        "l_discount": pa.array(rng.integers(0, 11, n) / 100.0),
        "l_shipdate": pa.array(rng.integers(8035, 10592, n).astype(np.int32),
                               pa.int32()),
    })


def build_plan(scan):
    from spark_rapids_tpu.plan import expressions as E
    from spark_rapids_tpu.plan.aggregates import Sum
    from spark_rapids_tpu.exec.plan import FilterExec, HashAggregateExec

    c = E.ColumnRef
    cond = E.And(
        E.And(E.GreaterThanOrEqual(c("l_shipdate"), E.Literal(DATE_LO)),
              E.LessThan(c("l_shipdate"), E.Literal(DATE_HI))),
        E.And(E.And(E.GreaterThanOrEqual(c("l_discount"), E.Literal(0.05)),
                    E.LessThanOrEqual(c("l_discount"), E.Literal(0.07))),
              E.LessThan(c("l_quantity"), E.Literal(24))))
    revenue = E.Multiply(c("l_extendedprice"), c("l_discount"))
    return HashAggregateExec([], [], [(Sum(revenue), "revenue")],
                             FilterExec(cond, scan))


def time_runs(fn, iters=5):
    fn()  # warm (compile + caches)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def make_device_scan(table: pa.Table, batch_rows: int):
    """Upload once; return a PlanNode replaying device-resident batches
    (buffer-cache analogue of a hot scan)."""
    import jax
    from spark_rapids_tpu.columnar.device import to_device
    from spark_rapids_tpu.exec.plan import HostScanExec, PlanNode

    src = HostScanExec.from_table(table, batch_rows)
    cached = [to_device(hb) for hb in src.batches]
    jax.block_until_ready([c.data for b in cached for c in b.columns])

    class DeviceScan(PlanNode):
        output_schema = src.output_schema

        def execute(self, ctx):
            return iter(cached)

    return DeviceScan()


def run_tpu_throughput(scan, depth: int):
    """Pipelined device-resident execution: dispatch `depth` independent
    query runs, one batched fetch at the end."""
    import jax
    plan = build_plan(scan)

    def once():
        runs = [plan.collect_device() for _ in range(depth)]
        flat = [buf for outs, _fin in runs for pair in outs for buf in pair]
        fetched = jax.device_get(flat)
        results = []
        it = iter(fetched)
        for outs, fin in runs:
            pairs = [(next(it), next(it)) for _ in outs]
            results.append(fin(pairs).column("revenue").to_pylist()[0])
        return results

    results = once()
    assert all(abs(r - results[0]) < 1e-9 for r in results)
    return time_runs(once, iters=3) / depth, results[0]


def run_tpu_single(scan):
    plan = build_plan(scan)

    def once():
        return plan.collect().column("revenue").to_pylist()[0]

    result = once()
    return time_runs(once, iters=3), result


def run_tpu_e2e(table: pa.Table, batch_rows: int):
    from spark_rapids_tpu.exec.plan import HostScanExec

    def once():
        plan = build_plan(HostScanExec.from_table(table, batch_rows))
        return plan.collect().column("revenue").to_pylist()[0]

    result = once()
    return time_runs(once, iters=2), result


def run_cpu(table: pa.Table):
    def once():
        m = pc.and_(
            pc.and_(pc.greater_equal(table["l_shipdate"], DATE_LO),
                    pc.less(table["l_shipdate"], DATE_HI)),
            pc.and_(pc.and_(pc.greater_equal(table["l_discount"], 0.05),
                            pc.less_equal(table["l_discount"], 0.07)),
                    pc.less(table["l_quantity"], 24)))
        ft = table.filter(m)
        return pc.sum(pc.multiply(ft["l_extendedprice"],
                                  ft["l_discount"])).as_py()

    result = once()
    return time_runs(once), result


# Scan->filter->aggregate shapes only: join-shaped queries make several
# data-dependent shape decisions (join output capacity, coalesce sizing),
# each a host sync that costs the full ~60ms tunnel RTT in THIS harness —
# they measure the tunnel, not the engine (single_shot note).  On locally
# attached chips those syncs are ~10us.
SUITE_QUERIES = ("q1", "q6")


def run_tpch_suite(scale: float = 0.005):
    """Secondary breadth metric: the TPC-H query subset end-to-end
    (scan->joins->aggs->sort, transitions included) on the device path vs
    the SAME queries on the engine's CPU fallback engine (pyarrow
    kernels).  Single-shot wall times — includes the ~60ms tunnel RTT per
    device query, so these speedups UNDERSTATE the engine (see the
    headline methodology note)."""
    from spark_rapids_tpu import tpch
    from spark_rapids_tpu.session import TpuSession, DataFrame

    tables = tpch.gen_tables(scale=scale)
    dev_s = TpuSession()
    cpu_s = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    per_q = {}
    for name in SUITE_QUERIES:
        df = tpch.QUERIES[name](dev_s, tables)

        def dev_once(df=df):
            return df.collect()

        def cpu_once(df=df):
            return DataFrame(df._plan, cpu_s).collect()

        dt = time_runs(dev_once, iters=1)
        ct = time_runs(cpu_once, iters=1)
        per_q[name] = {"device_ms": round(dt * 1e3, 1),
                       "cpu_ms": round(ct * 1e3, 1),
                       "speedup": round(ct / dt, 2)}
    speedups = [v["speedup"] for v in per_q.values()]
    geomean = float(np.exp(np.mean(np.log(speedups))))
    return {"tpch_suite_scale": scale,
            "tpch_suite_geomean_speedup": round(geomean, 2),
            "tpch_suite_queries": per_q,
            "tpch_suite_note": "single-shot wall times incl. one full "
            "tunnel RTT per host sync; scan/agg shapes only (joins are "
            "RTT-bound in this harness, not engine-bound)"}


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else SF1_ROWS
    batch_rows = 1 << 23   # single fused batch: fewest dispatches wins
    table = gen_lineitem(n)

    cpu_t, cpu_r = run_cpu(table)
    scan = make_device_scan(table, batch_rows)
    thr_t, thr_r = run_tpu_throughput(scan, PIPELINE_DEPTH)
    lat_t, lat_r = run_tpu_single(scan)
    e2e_t, e2e_r = run_tpu_e2e(table, batch_rows)

    for r in (thr_r, lat_r, e2e_r):
        assert abs(r - cpu_r) / abs(cpu_r) < 1e-6, (r, cpu_r)

    print(f"# rows={n} cpu(pyarrow)={cpu_t*1e3:.1f}ms "
          f"tpu_resident_per_query={thr_t*1e3:.3f}ms (depth={PIPELINE_DEPTH}) "
          f"tpu_single_shot={lat_t*1e3:.1f}ms (tunnel RTT ~60ms) "
          f"tpu_e2e_cold={e2e_t*1e3:.1f}ms (tunnel H2D ~50MB/s)",
          file=sys.stderr)
    out = {
        "metric": "tpch_q6_sf1_device_resident_per_query_ms",
        "value": round(thr_t * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_t / thr_t, 3),
        "pipeline_depth": PIPELINE_DEPTH,
        "single_shot_ms": round(lat_t * 1e3, 3),
        "e2e_cold_ms": round(e2e_t * 1e3, 3),
        "cpu_baseline_ms": round(cpu_t * 1e3, 3),
        "note": "per-query time with K executions batched into one D2H "
                "fetch; single_shot is dominated by the ~60ms test-harness "
                "tunnel RTT, not engine time",
    }
    try:
        out.update(run_tpch_suite())
    except Exception as e:                       # noqa: BLE001
        print(f"# tpch suite sweep skipped: {e!r}", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
