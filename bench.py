#!/usr/bin/env python
"""Headline benchmark: the TPC-H suite (default) or the TPC-DS tranche
(--suite tpcds) at SF>=1.

Prints a running JSON summary line after EVERY query (flushed), so a
timeout kill at any point still leaves a complete, parseable result as
the last stdout line — a perf harness that can fail to report is itself
a defect (VERDICT r3).  The final line covers every query measured.

Headline metric: geometric-mean speedup of per-query WARM wall time
(device engine / whole-plan XLA compilation) over the SAME queries on
the engine's CPU fallback (vectorized pyarrow kernels — a stronger
stand-in for CPU Spark than Spark itself: columnar C++ kernels, no
JVM/task overhead, so the reported speedup is conservative vs the
BASELINE.md north star).

Methodology.
  * Every query runs BOTH engines from the same in-memory tables and
    results are cross-checked (float tails to 1e-6 relative — reduction
    order differs, as the reference documents for GPU float aggs).
  * Device timing is single-shot warm wall time: one whole-plan XLA
    dispatch + one result fetch, measured after the one-time costs
    (compile — persisted to the jax compilation cache; H2D upload —
    tables are device-resident across queries, the buffer-cache role).
    It INCLUDES the test harness tunnel's ~60ms round-trip per query;
    the RTT is also reported separately so the engine-time floor is
    visible.  CPU timing is the same warm single-shot discipline.
  * Cold numbers (first-run compile or cache load, upload) are reported
    per query and as a median; a persistent-cache hit shows up as a
    small cold time.
  * Time budgets: BENCH_BUDGET_S (default 1800, TOTAL_BUDGET_S below)
    total; queries that don't fit are listed in "skipped" rather than
    silently absent.

Every per-query record embeds a "profile" summary from one extra traced
(untimed) collect — the compile/execute/transition/shuffle wall split,
top operators by self time, data-movement bytes, memory high-water and
runtime incidents (obs/profile.py) — so the JSON explains where each
query's time goes, not just how much there is.

--suite tpcds additionally reports the operator-coverage matrix the
BASELINE.md staged config #2 asks for: per-query fallback reasons (from
the overrides tagger), sort_operand_max and scatter_op_count (jaxpr
lints, testing.py), and a top-level coverage summary splitting queries
into device-clean / with-fallbacks / not-whole-plan-traceable.

Run: python bench.py [scale] [--queries q1,q6,...] [--suite tpch|tpcds]
"""
import json
import os
import sys
import time

import numpy as np

import jax

# Persistent compile cache: cold compiles (minutes/query over the
# tunnel) are paid once per (plan, shape); later runs trace + load with
# ZERO XLA compiles (the hit/miss counters below prove it per run).
# Routed through the ENGINE's conf (spark.rapids.tpu.compile.cacheDir)
# rather than raw jax config: the engine scopes entries under a
# topology-hashed subdirectory, which is what makes one directory safe
# across the bench's 1-chip topology and the tests' forced 8-device CPU
# mesh — XLA's own cache key does NOT hash topology, and sharing a flat
# dir let one topology's executables segfault the other's deserializer.
BENCH_CACHE_DIR = __file__.rsplit("/", 1)[0] + "/.jax_cache_bench"

# With a primed compile cache (same disk), 22 queries need ~10-20 min
# (cache loads + warm timing + the CPU oracle, which alone costs ~70s on
# q21); the incremental JSON emit makes an external kill lossless, so a
# generous default just maximizes what gets measured.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1800"))
_T0 = time.perf_counter()


def left() -> float:
    return TOTAL_BUDGET_S - (time.perf_counter() - _T0)


def measure_rtt() -> float:
    """Median device round-trip (a 4-byte fetch) — the per-sync tax this
    harness adds; on a locally attached chip it is ~10us."""
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((1,), jnp.int32)
    jax.device_get(f(x))
    times = []
    for _ in range(11):
        t0 = time.perf_counter()
        # a fresh device-computed value: the fetch must round-trip
        jax.device_get(f(x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def approx_equal(a, b) -> bool:
    da, db = a.to_pydict(), b.to_pydict()
    if set(da) != set(db):
        return False
    for k in da:
        if len(da[k]) != len(db[k]):
            return False
        for x, y in zip(da[k], db[k]):
            if x == y:
                continue
            if isinstance(x, float) and isinstance(y, float) and \
                    abs(x - y) <= 1e-6 * max(1.0, abs(x), abs(y)):
                continue
            return False
    return True


def time_warm(fn, iters=3):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def query_profile(q, conf) -> dict:
    """One traced (untimed) collect -> the compact QueryProfile summary
    embedded per query, so BENCH_*.json explains its own numbers: the
    compile/execute/transition/shuffle split, top operators by self
    time, PER-SEGMENT measured device ms (profile.segments forced on
    for this collect — the attribution check_regression/profile_diff
    cite), data-movement bytes and memory high-water.  Runs AFTER the
    warm timing so span collection can't perturb the headline number."""
    from spark_rapids_tpu.config import (PROFILE_SEGMENTS, TRACE_ENABLED,
                                         TpuConf)
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.obs.profile import QueryProfile
    pctx = ExecContext(TpuConf({**conf._raw, TRACE_ENABLED.key: "true",
                                PROFILE_SEGMENTS.key: "true"}))
    q.collect(pctx)
    return QueryProfile.from_context(pctx).summary()


class Suite:
    def __init__(self, name: str, scale: float, rtt: float):
        self.name = name
        self.scale = scale
        self.rtt = rtt
        self.per_q = {}
        self.skipped = []
        self.compiled_ct = 0
        self.extra_conf = {}
        # metrics-plane A/B: q6 warm wall with the always-on registry +
        # flight recorder active vs spark.rapids.tpu.metrics.enabled=false
        # (the overhead bound the metrics plane claims — docs/METRICS.md)
        self.metrics_overhead = None
        # performance-history store stats when --history-dir recorded
        # this run (structures, records, calibration)
        self.history = None
        # measured per-backend dispatch floor (exec/compiled.py), the
        # irreducible ms one compiled-program launch costs here
        self.dispatch_floor_ms = None

    def overhead_share(self):
        """Suite-level fixed-overhead fraction: dispatch + seam + pad
        waste over the summed profiled walls of queries that carried a
        wall_breakdown embed; None before any did."""
        ov = wall = 0.0
        for v in self.per_q.values():
            bd = v.get("wall_breakdown")
            if isinstance(bd, dict) and bd.get("wall_ms"):
                wall += float(bd["wall_ms"])
                ov += float(bd.get("dispatch_ms", 0.0)) \
                    + float(bd.get("seam_ms", 0.0)) \
                    + float(bd.get("pad_waste_ms", 0.0))
        return round(ov / wall, 4) if wall else None

    def coverage(self) -> dict:
        """Operator-coverage matrix: which queries run device-clean,
        which carry fallbacks (and why), which cannot trace as one
        whole-plan program (per-query stats stay None for those)."""
        clean, with_fb, untraceable = [], {}, []
        for name, v in self.per_q.items():
            fb = v.get("fallback_reasons") or []
            if fb:
                with_fb[name] = fb
            else:
                clean.append(name)
            if v.get("sort_operand_max") is None and "error" not in v:
                untraceable.append(name)
        return {"device_clean": sorted(clean),
                "with_fallbacks": with_fb,
                "not_whole_plan_traceable": sorted(untraceable)}

    def emit(self, final: bool = False):
        speedups = [v["speedup"] for v in self.per_q.values()
                    if v["speedup"] is not None]
        geomean = float(np.exp(np.mean(np.log(speedups)))) \
            if speedups else 0.0
        net = [v["speedup_net"] for v in self.per_q.values()
               if v.get("speedup_net")]
        geomean_net = float(np.exp(np.mean(np.log(net)))) if net else 0.0
        errors = sum(1 for v in self.per_q.values() if "error" in v)
        colds = sorted(v["cold_s"] for v in self.per_q.values()
                       if "error" not in v)
        med_cold = colds[len(colds) // 2] if colds else None
        cms = sorted(v.get("compile_ms_cold") for v in self.per_q.values()
                     if v.get("compile_ms_cold") is not None)
        med_compile_ms = cms[len(cms) // 2] if cms else None
        try:
            from spark_rapids_tpu.exec.compiled import \
                persistent_cache_stats
            pcache = persistent_cache_stats()
        except Exception:                    # noqa: BLE001
            pcache = None
        scale = self.scale
        out = {
            "metric": f"{self.name}_sf{scale:g}_suite_geomean_speedup"
                      f"_vs_cpu",
            "value": round(geomean, 3),
            "unit": "x",
            "vs_baseline": round(geomean, 3),
            "suite": self.name,
            f"{self.name}_suite_scale": scale,
            f"{self.name}_suite_queries": self.per_q,
            f"{self.name}_suite_geomean_speedup": round(geomean, 3),
            f"{self.name}_suite_geomean_speedup_net": round(geomean_net, 3),
            "backend": jax.default_backend(),
            "extra_conf": self.extra_conf,
            "coverage": self.coverage(),
            "queries_measured": len(self.per_q),
            "errors": errors,
            "skipped": self.skipped,
            "final": final,
            "whole_plan_compiled": self.compiled_ct,
            "sort_operand_max": max(
                (v.get("sort_operand_max") or 0
                 for v in self.per_q.values()), default=0),
            "scatter_op_total": sum(
                v.get("scatter_op_count") or 0
                for v in self.per_q.values()),
            "median_cold_s": med_cold,
            "median_compile_ms": med_compile_ms,
            "pcache": pcache,
            "tunnel_rtt_ms": round(self.rtt * 1e3, 1),
            "metrics_overhead": self.metrics_overhead,
            "dispatch_floor_ms": self.dispatch_floor_ms,
            "overhead_share": self.overhead_share(),
            "history": self.history,
            "elapsed_s": round(time.perf_counter() - _T0, 1),
            "note": "warm single-shot wall per query (one whole-plan XLA "
                    "dispatch + one fetch, device-resident tables, compile "
                    "cached); INCLUDES one tunnel RTT per query — "
                    "tunnel_rtt_ms is the harness floor and device_ms_net/"
                    "speedup_net subtract it (the engine-controllable "
                    "time; the regression gate compares net values). "
                    "CPU baseline = "
                    "same queries on the engine's vectorized pyarrow "
                    "fallback, warm (arrow decimal128 kernels, no python "
                    "row loops). Incremental line: last stdout line is "
                    "always the complete current result.",
        }
        if final:
            # the always-on metrics-plane snapshot: process-wide data
            # movement / spill / retry / skew telemetry accumulated over
            # the whole run rides with the result (obs/registry.py)
            try:
                from spark_rapids_tpu.obs.export import registry_snapshot
                out["registry"] = registry_snapshot(compact=True)
            except Exception as e:               # noqa: BLE001
                out["registry"] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(out), flush=True)


#: --conf key=value session overrides (applied to the DEVICE session
#: only; the CPU oracle baseline never sees them) — how the committed
#: kernel-tier bench rounds flip spark.rapids.tpu.sql.kernels.pallas.*
EXTRA_CONF = {}


def run_suite(suite_name: str, scale: float, query_names):
    import importlib
    workload = importlib.import_module(f"spark_rapids_tpu.{suite_name}")
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.session import DataFrame, TpuSession

    rtt = measure_rtt()
    print(f"# backend={jax.default_backend()} tunnel RTT ~{rtt*1e3:.0f}ms "
          f"per host sync", file=sys.stderr)
    # the measured per-backend dispatch floor: header context for every
    # per-query wall_breakdown embed below (fail-soft — its absence
    # loses one report line, never the run)
    try:
        from spark_rapids_tpu.exec.compiled import dispatch_floor_ms
        floor = round(dispatch_floor_ms(), 4)
        print(f"# dispatch floor ~{floor:.3f}ms per compiled-program "
              f"launch on {jax.default_backend()}", file=sys.stderr)
    except Exception:                        # noqa: BLE001
        floor = None

    t0 = time.perf_counter()
    tables = workload.gen_tables(scale=scale)
    gen_s = time.perf_counter() - t0
    biggest = max(tables, key=lambda k: tables[k].num_rows)
    print(f"# datagen {suite_name} SF{scale}: {gen_s:.1f}s "
          f"{biggest}={tables[biggest].num_rows}", file=sys.stderr)

    # whole-plan compile forced ON: the bench methodology IS "one XLA
    # dispatch + one fetch" (docstring), and AUTO would silently fall
    # back to the eager batch engine on non-TPU backends — a different
    # engine than the one the headline number claims to measure
    from spark_rapids_tpu.config import COMPILE_CACHE_DIR, WHOLE_PLAN_COMPILE
    dev = TpuSession({WHOLE_PLAN_COMPILE.key: "ON",
                      COMPILE_CACHE_DIR.key: BENCH_CACHE_DIR,
                      **EXTRA_CONF})
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})

    suite = Suite(suite_name, scale, rtt)
    suite.extra_conf = dict(EXTRA_CONF)
    suite.dispatch_floor_ms = floor
    for name in query_names:
        if left() < 20:
            suite.skipped.append(name)
            continue
        try:
            from spark_rapids_tpu.exec.compiled import \
                persistent_cache_stats
            dfq = workload.QUERIES[name](dev, tables)
            q = dfq.physical()
            # cold: compile (or cache load) + device upload + first run;
            # the persistent-cache counter DELTA across it is the proof
            # of a warmed replay (0 misses = zero XLA compiles)
            pc0 = persistent_cache_stats()
            cctx = ExecContext(dev.conf)
            # history-plane label: the recorded structure carries the
            # query name so history_report / drift citations read qN,
            # not a bare digest (no-op when the plane is off)
            cctx.metrics["history.label"] = name
            t0 = time.perf_counter()
            out = q.collect(cctx)
            cold_s = time.perf_counter() - t0
            pc1 = persistent_cache_stats()
            compile_ms_cold = round(cctx.metrics.get("compile_ms", 0.0), 1)
            iters = 3 if left() > 120 else 1
            dt = time_warm(lambda: q.collect(ExecContext(dev.conf)),
                           iters=iters)
            ctx = ExecContext(dev.conf)
            out = q.collect(ctx)
            compile_ms_warm = round(ctx.metrics.get("compile_ms", 0.0), 1)
            compiled = ctx.metrics.get("whole_plan_compiled_queries", 0)
            suite.compiled_ct += compiled

            cq = DataFrame(dfq._plan, cpu).physical()
            oracle = cq.collect()
            ct = time_warm(lambda: cq.collect(), iters=2)

            # regression-surface metrics from the emitted program: the
            # widest sort (compile-time cliff) and the scatter count
            # (runtime cliff) — docs/PERF.md §1.  Tracked per query so
            # the perf trajectory sees the cause, not just wall time.
            try:
                from spark_rapids_tpu.testing import plan_program_stats
                pstats = plan_program_stats(q, ExecContext(dev.conf))
            except Exception:                # noqa: BLE001
                pstats = {"sort_operand_max": None,
                          "scatter_op_count": None}
            # the traced profile run is untimed and budget-gated: its
            # absence loses explanation, never measurement
            try:
                profile = query_profile(q, dev.conf) if left() > 30 \
                    else None
            except Exception as e:           # noqa: BLE001
                profile = {"error": f"{type(e).__name__}: {e}"[:200]}
            match = approx_equal(out, oracle)
            # device_ms_net: the warm wall minus ONE harness tunnel RTT
            # (the single dispatch+fetch round trip every query pays on
            # this harness, ~121ms over the tunnel, ~10us locally).  A
            # 546ms q11 is ~425ms of engine time — the floor-subtracted
            # number is what the engine can actually influence, and the
            # regression gate compares it (scripts/check_regression.py).
            dt_net = max(dt - suite.rtt, 1e-6)
            suite.per_q[name] = {"device_ms": round(dt * 1e3, 1),
                                 "device_ms_net": round(dt_net * 1e3, 1),
                                 "cpu_ms": round(ct * 1e3, 1),
                                 "speedup": round(ct / dt, 2),
                                 "speedup_net": round(ct / dt_net, 2),
                                 "cold_s": round(cold_s, 1),
                                 "compile_ms_cold": compile_ms_cold,
                                 "compile_ms_warm": compile_ms_warm,
                                 "pcache_hits": pc1["hits"] - pc0["hits"],
                                 "pcache_misses":
                                     pc1["misses"] - pc0["misses"],
                                 "compiled": bool(compiled),
                                 "match": match,
                                 "fallback_reasons":
                                     q.fallback_reasons(),
                                 "profile": profile, **pstats}
            # per-query HBM attribution (memattr plane, measured during
            # the profiled collect): top-level so check_regression.py
            # can gate >25% HBM-peak regressions next to device_ms
            if isinstance(profile, dict):
                for hk in ("hbm_peak_bytes", "hbm_measured_working_set"):
                    if profile.get(hk):
                        suite.per_q[name][hk] = int(profile[hk])
                # the wall-decomposition embed: top-level per query so
                # check_regression.py can gate seam-count and
                # pad-waste-share growth next to device_ms and hbm
                bd = profile.get("wall_breakdown")
                if isinstance(bd, dict) and bd.get("wall_ms"):
                    suite.per_q[name]["wall_breakdown"] = bd
            print(f"# {name}: device={dt*1e3:.0f}ms cpu={ct*1e3:.0f}ms "
                  f"x{ct/dt:.2f} cold={cold_s:.1f}s "
                  f"compiled={bool(compiled)} match={match}",
                  file=sys.stderr)
            if not match:
                print(f"# WARNING {name}: device != cpu oracle",
                      file=sys.stderr)
        except Exception as e:               # noqa: BLE001
            # a broken query must not take the whole suite's report down
            print(f"# ERROR {name}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            suite.per_q[name] = {"device_ms": None, "cpu_ms": None,
                                 "speedup": None, "cold_s": 0.0,
                                 "compiled": False, "match": False,
                                 "error": f"{type(e).__name__}: {e}"[:200]}
        suite.emit()
    suite.metrics_overhead = measure_metrics_overhead(workload, tables,
                                                      suite, dev)
    try:
        from spark_rapids_tpu.obs.history import get_store
        store = get_store(dev.conf)
        if store is not None:
            suite.history = store.stats()
    except Exception:                        # noqa: BLE001
        pass
    return suite


def run_compile_only(suite_name: str, scale: float, query_names):
    """--compile-only: pre-populate the compile caches WITHOUT timing
    anything — the CI warmup mode.  Every query's whole-plan program is
    AOT-compiled (PhysicalQuery.prewarm: trace + lower().compile(), no
    execution) on the background compile service's thread pool, so the
    suite's cold compile wall is max-over-threads instead of a serial
    sum, and the persistent cache ends up holding every program a
    subsequent timed run replays with zero XLA compiles."""
    import importlib
    workload = importlib.import_module(f"spark_rapids_tpu.{suite_name}")
    from spark_rapids_tpu.config import (COMPILE_CACHE_DIR,
                                         WHOLE_PLAN_COMPILE)
    from spark_rapids_tpu.exec.compiled import persistent_cache_stats
    from spark_rapids_tpu.runtime.compile_service import get_service
    from spark_rapids_tpu.session import TpuSession

    tables = workload.gen_tables(scale=scale)
    dev = TpuSession({WHOLE_PLAN_COMPILE.key: "ON",
                      COMPILE_CACHE_DIR.key: BENCH_CACHE_DIR,
                      **EXTRA_CONF})
    service = get_service(dev.conf)
    tasks = []
    for name in query_names:
        q = workload.QUERIES[name](dev, tables).physical()

        def thunk(q=q):
            t0 = time.perf_counter()
            ok = q.prewarm()
            return ok, time.perf_counter() - t0

        tasks.append((name, service.submit(
            ("compile-only", suite_name, name), thunk)))
    per_q = {}
    for name, task in tasks:
        try:
            ok, secs = task.wait(timeout=None)
            per_q[name] = {"compiled": bool(ok),
                           "compile_s": round(secs, 2)}
        except Exception as e:               # noqa: BLE001
            per_q[name] = {"compiled": False,
                           "error": f"{type(e).__name__}: {e}"[:200]}
        print(f"# {name}: {per_q[name]}", file=sys.stderr)
    out = {"mode": "compile-only",
           "suite": suite_name,
           f"{suite_name}_suite_scale": scale,
           "backend": jax.default_backend(),
           "queries": per_q,
           "compiled": sum(1 for v in per_q.values() if v["compiled"]),
           "pcache": persistent_cache_stats(),
           "elapsed_s": round(time.perf_counter() - _T0, 1),
           "final": True}
    print(json.dumps(out), flush=True)


#: --kernels microbench sizes (rows) and skew levels
KERNEL_SIZES = {"256k": 1 << 18, "1m": 1 << 20, "4m": 1 << 22}
KERNEL_SKEWS = ("uniform", "skewed")


def run_kernels():
    """--kernels: Pallas-vs-sorted A/B microbenchmarks of the three
    kernel families (ISSUE 11) at 3 sizes x 2 skew levels, emitting
    `kernel_timings_ms` entries scripts/check_regression.py gates under
    the `kn:` prefix (same backend-separation rule as qN device_ms).

    Shapes: probe = hash-probe join primitive (build table + aligned
    probe of N rows against an N/8-row build side) vs the sorted-lane
    merge-rank probe; segagg = 32-bucket segmented int64 sums (the
    block-accumulate matmul kernel vs jax.ops.segment_sum); compact =
    10%-selectivity compaction order (rank search vs keep-mask
    argsort).  'skewed' concentrates 90% of probe/segment rows on 1%
    of the key space — the collision/hot-bucket regime.  Pallas
    kernels run interpreted off-TPU (the same discharged bodies the
    query path dispatches)."""
    import numpy as np
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.join import _merge_rank
    from spark_rapids_tpu.ops.pallas import hashjoin as HK
    from spark_rapids_tpu.ops.pallas.compact import \
        compaction_order as pallas_order
    from spark_rapids_tpu.ops.pallas.segagg import _seg_matmul_sums
    from spark_rapids_tpu.ops.filter import compaction_order
    interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(17)
    out = {}

    def timed(name, fn):
        jax.block_until_ready(fn())                      # compile+warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        out[name] = round(min(times) * 1e3, 2)
        print(f"# {name}: {out[name]}ms", file=sys.stderr)

    for sname, n in KERNEL_SIZES.items():
        if left() < 60:
            print(f"# budget: skipping kernel size {sname}",
                  file=sys.stderr)
            continue
        b = n // 8
        for skew in KERNEL_SKEWS:
            if skew == "uniform":
                pk = rng.integers(0, b, n)
            else:
                hot = rng.integers(0, max(b // 100, 1), n)
                cold = rng.integers(0, b, n)
                pk = np.where(rng.random(n) < 0.9, hot, cold)
            bkeys = jnp.asarray(np.arange(b) * 7 + 3, jnp.int64)
            pkeys = jnp.asarray(pk * 7 + 3, jnp.int64)
            bvalid = jnp.ones((b,), bool)
            pvalid = jnp.ones((n,), bool)

            def probe_pallas():
                tbl = HK.build_table(bkeys, bvalid, interpret)
                return HK.probe_first(tbl, pkeys, pvalid)

            @jax.jit
            def probe_sorted(bkeys, pkeys):
                sh = jnp.sort(HK.mix64(bkeys))
                return _merge_rank(sh, HK.mix64(pkeys), side="left")

            timed(f"probe_{sname}_{skew}_pallas", probe_pallas)
            timed(f"probe_{sname}_{skew}_sorted",
                  lambda: probe_sorted(bkeys, pkeys))

            seg = jnp.asarray(pk % 32, jnp.int32)
            lanes = [jnp.asarray(rng.integers(-(10 ** 12), 10 ** 12, n),
                                 jnp.int64) for _ in range(4)]

            def segagg_pallas():
                return _seg_matmul_sums(seg, lanes, [], 32, n, interpret)

            @jax.jit
            def segagg_scatter(seg, stacked):
                return jax.ops.segment_sum(stacked, seg, num_segments=32)
            stacked = jnp.stack(lanes, axis=1)
            timed(f"segagg_{sname}_{skew}_pallas", segagg_pallas)
            timed(f"segagg_{sname}_{skew}_scatter",
                  lambda: segagg_scatter(seg, stacked))

            keep = jnp.asarray(rng.random(n) < 0.1)
            timed(f"compact_{sname}_{skew}_pallas",
                  lambda: pallas_order(keep, interpret))
            timed(f"compact_{sname}_{skew}_sorted",
                  lambda: compaction_order(keep))

    ratios = {}
    for k in sorted(out):
        if k.endswith("_pallas"):
            base = out.get(k.replace("_pallas", "_sorted"),
                           out.get(k.replace("_pallas", "_scatter")))
            if base:
                ratios[k[:-7]] = round(out[k] / base, 3)
    print(json.dumps({
        "mode": "kernels",
        "metric": "kernel_microbench_pallas_vs_sorted",
        "value": round(float(np.exp(np.mean(np.log(
            [max(r, 1e-6) for r in ratios.values()])))), 3)
        if ratios else None,
        "unit": "x (pallas/sorted, lower is better)",
        "backend": jax.default_backend(),
        "interpret": interpret,
        "kernel_timings_ms": out,
        "pallas_over_sorted_ratio": ratios,
        "elapsed_s": round(time.perf_counter() - _T0, 1),
        "final": True}), flush=True)


#: --encodings microbench sizes (rows) and selectivities
ENCODING_ROWS = 1 << 20
ENCODING_SELECTIVITIES = {"sel1": 0.01, "sel50": 0.5}


def run_encodings():
    """--encodings: encoded-vs-decode-first A/B microbenchmarks of the
    compressed device-resident execution layer (ISSUE 13) over
    predicate/join/agg x dict/RLE/FOR x 2 selectivities, emitting
    `encoding_timings_ms` entries scripts/check_regression.py gates
    under the `en:` prefix (same backend-separation rule as qN
    device_ms).

    Shapes per encoding:
      * dict — predicate: code-space equality (one scalar compare) vs
        the decode-first per-row remap-table gather; join: probe of
        dictionary-coded keys on codes vs probing decoded rank lanes;
        agg: 32-group code-keyed segment sums vs rank-decoded keys.
      * RLE  — predicate evaluated per RUN + rank-search mask expansion
        (ops/encodings.rle_predicate_mask) vs rle_decode-then-compare.
      * FOR  — predicate/arith on the value-preserving narrow lane
        (range-guarded compare, exact-width add) vs widen-then-compute.
    Selectivity levels move the predicate cut point (sel1 ~1% true,
    sel50 ~50% true) — code/narrow compares are selectivity-invariant,
    the decode-first gathers are too, so the ratio isolates the decode
    cost itself."""
    import numpy as np
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.bitpack import rle_decode
    from spark_rapids_tpu.ops.encodings import (narrow_compare,
                                                rle_predicate_mask)
    rng = np.random.default_rng(23)
    n = ENCODING_ROWS
    out = {}

    def timed(name, fn):
        jax.block_until_ready(fn())                      # compile+warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        out[name] = round(min(times) * 1e3, 2)
        print(f"# {name}: {out[name]}ms", file=sys.stderr)

    dict_size = 1024
    codes = jnp.asarray(rng.integers(0, dict_size, n), jnp.int32)
    remap = jnp.asarray(rng.permutation(dict_size).astype(np.int32))

    for sname, sel in ENCODING_SELECTIVITIES.items():
        if left() < 45:
            print(f"# budget: skipping encodings level {sname}",
                  file=sys.stderr)
            continue
        cut = max(int(dict_size * sel), 1)

        # -- dict: predicate (code-space vs remap-decode-first)
        @jax.jit
        def dict_pred_encoded(codes):
            return codes < cut                    # ordered dict: code IS rank

        @jax.jit
        def dict_pred_decoded(codes, remap):
            ranks = remap[jnp.clip(codes, 0, remap.shape[0] - 1)]
            return ranks < cut

        timed(f"dict_pred_{sname}_encoded", lambda: dict_pred_encoded(codes))
        timed(f"dict_pred_{sname}_decoded",
              lambda: dict_pred_decoded(codes, remap))

        # -- dict: join probe on codes vs on decoded rank lanes
        from spark_rapids_tpu.ops.join import _merge_rank
        bkeys = jnp.asarray(np.arange(dict_size), jnp.int64)

        @jax.jit
        def dict_join_encoded(codes):
            return _merge_rank(bkeys.astype(jnp.uint64),
                               codes.astype(jnp.uint64), side="left")

        @jax.jit
        def dict_join_decoded(codes, remap):
            lane = remap[jnp.clip(codes, 0, remap.shape[0] - 1)]
            return _merge_rank(jnp.sort(remap.astype(jnp.uint64)),
                               lane.astype(jnp.uint64), side="left")

        timed(f"dict_join_{sname}_encoded", lambda: dict_join_encoded(codes))
        timed(f"dict_join_{sname}_decoded",
              lambda: dict_join_decoded(codes, remap))

        # -- dict: 32-group segment sums keyed by codes vs decoded ranks
        vals = jnp.asarray(rng.integers(0, 1000, n), jnp.int64)

        @jax.jit
        def dict_agg_encoded(codes, vals):
            return jax.ops.segment_sum(vals, codes % 32, num_segments=32)

        @jax.jit
        def dict_agg_decoded(codes, remap, vals):
            lane = remap[jnp.clip(codes, 0, remap.shape[0] - 1)]
            return jax.ops.segment_sum(vals, lane % 32, num_segments=32)

        timed(f"dict_agg_{sname}_encoded",
              lambda: dict_agg_encoded(codes, vals))
        timed(f"dict_agg_{sname}_decoded",
              lambda: dict_agg_decoded(codes, remap, vals))

        # -- RLE: run-domain predicate vs decode-then-compare
        n_runs = n // 64
        run_vals = jnp.asarray(rng.integers(0, 1000, n_runs), jnp.int64)
        run_lens = jnp.asarray(np.full(n_runs, 64), jnp.int32)
        thr = int(1000 * sel)

        @jax.jit
        def rle_encoded(run_vals, run_lens):
            return rle_predicate_mask(run_vals, run_lens, n,
                                      lambda v: v < thr)

        @jax.jit
        def rle_decoded(run_vals, run_lens):
            return rle_decode(run_vals, run_lens, n) < thr

        timed(f"rle_pred_{sname}_encoded",
              lambda: rle_encoded(run_vals, run_lens))
        timed(f"rle_pred_{sname}_decoded",
              lambda: rle_decoded(run_vals, run_lens))

        # -- FOR: narrow-lane predicate + exact-width add vs widened
        narrow = jnp.asarray(rng.integers(-1000, 1000, n), jnp.int16)
        thr16 = jnp.asarray(int(2000 * sel) - 1000, jnp.int64)

        @jax.jit
        def for_encoded(narrow):
            keep = narrow_compare("<", narrow, thr16)
            s = narrow.astype(jnp.int32) + narrow.astype(jnp.int32)
            return keep, s

        @jax.jit
        def for_decoded(narrow):
            wide = narrow.astype(jnp.int64)
            return wide < thr16, wide + wide

        timed(f"for_pred_{sname}_encoded", lambda: for_encoded(narrow))
        timed(f"for_pred_{sname}_decoded", lambda: for_decoded(narrow))

    ratios = {}
    for k in sorted(out):
        if k.endswith("_encoded"):
            base = out.get(k.replace("_encoded", "_decoded"))
            if base:
                ratios[k[:-8]] = round(out[k] / base, 3)
    print(json.dumps({
        "mode": "encodings",
        "metric": "encoding_microbench_encoded_vs_decoded",
        "value": round(float(np.exp(np.mean(np.log(
            [max(r, 1e-6) for r in ratios.values()])))), 3)
        if ratios else None,
        "unit": "x (encoded/decode-first, lower is better)",
        "backend": jax.default_backend(),
        "encoding_timings_ms": out,
        "encoded_over_decoded_ratio": ratios,
        "elapsed_s": round(time.perf_counter() - _T0, 1),
        "final": True}), flush=True)


#: --ooc leg queries: the join+aggregation classes whose working sets
#: the out-of-core tier must carry (ISSUE 15; --queries overrides)
OOC_QUERIES = ["q3", "q9", "q18"]

#: HBM budget for the capped leg = measured peak / this divisor (the
#: budget lands well below the per-operator working sets — a gentler
#: divisor only pressures the staging spill path, never the
#: partition-tier gates)
OOC_CAP_DIVISOR = 16


def run_ooc(suite_name: str, scale: float, query_names):
    """--ooc: memory-capped out-of-core leg (ISSUE 15).  Each query runs
    once UNCAPPED (the resident baseline and the oracle — its measured
    budget peak is the working-set reference) and once with the HBM
    budget forced to peak/OOC_CAP_DIVISOR, floored so a single target
    batch still fits (a budget below one batch is unsatisfiable by ANY
    tier).  The capped run must oracle-match, engage the out-of-core
    tier (`ooc.*` ctx counters / tpu_ooc_* families) and never reach
    the query-level replay rung.  Emits `ooc_timings_ms` entries
    ({qN}_capped / {qN}_uncapped, lower = better) that
    scripts/check_regression.py gates under the `oc:` prefix with the
    same backend-separation rule as qN device_ms."""
    import importlib
    workload = importlib.import_module(f"spark_rapids_tpu.{suite_name}")
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.session import TpuSession

    tables = workload.gen_tables(scale=scale)
    names = [n for n in (query_names or OOC_QUERIES)
             if n in workload.QUERIES]
    # the capped leg runs SMALLER batches (scale-aware) so the
    # unsatisfiable floor — one staged batch must fit the budget —
    # stays far below the cap; the uncapped baseline keeps the default
    base = TpuSession(dict(EXTRA_CONF))
    bsr = base.conf.batch_size_rows
    rows_max = max(t.num_rows for t in tables.values())
    bsr_capped = min(bsr, max(4096, rows_max // 64))
    row_w = max(t.nbytes // max(t.num_rows, 1) for t in tables.values())
    batch_floor = 2 * bsr_capped * max(row_w, 8)
    out = {}
    timings = {}
    all_match = True
    for name in names:
        if left() < 120:
            print(f"# budget: skipping ooc query {name}", file=sys.stderr)
            continue
        # -- uncapped baseline + working-set reference
        s0 = TpuSession(dict(EXTRA_CONF))
        df0 = workload.QUERIES[name](s0, tables)
        t0 = time.perf_counter()
        oracle = df0.collect()
        un_ms = (time.perf_counter() - t0) * 1e3
        m0 = df0.metrics()
        peak = int(m0.get("memory.peak_bytes") or 0)
        src = sum(t.nbytes for t in tables.values())
        cap = max(max(peak, src // 4) // OOC_CAP_DIVISOR, batch_floor)
        # -- capped run: the OOC tier must carry it
        s1 = TpuSession({**EXTRA_CONF,
                         "spark.rapids.tpu.memory.tpu.budgetBytes":
                             str(cap),
                         "spark.rapids.tpu.sql.batchSizeRows":
                             str(bsr_capped)})
        df1 = workload.QUERIES[name](s1, tables)
        t0 = time.perf_counter()
        try:
            capped = df1.collect()
            err = None
        except Exception as e:                       # noqa: BLE001
            capped, err = None, f"{type(e).__name__}: {e}"[:200]
        cap_ms = (time.perf_counter() - t0) * 1e3
        m1 = df1.metrics() if capped is not None else {}
        ooc = {k[4:]: v for k, v in m1.items() if k.startswith("ooc.")}
        match = capped is not None and approx_equal(oracle, capped)
        all_match = all_match and match
        timings[f"{name}_uncapped"] = round(un_ms, 1)
        timings[f"{name}_capped"] = round(cap_ms, 1)
        out[name] = {
            "uncapped_ms": round(un_ms, 1),
            "capped_ms": round(cap_ms, 1),
            "degradation_x": round(cap_ms / un_ms, 2) if un_ms else None,
            "budget_bytes": cap,
            "working_set_peak_bytes": peak,
            "match": match,
            "error": err,
            "ooc": ooc,
            "ooc_engaged": any(k.endswith("_elections") for k in ooc),
            "spilled_batches": m1.get("memory.spilled_batches"),
            "query_oom_replays": m1.get("query_oom_replays", 0),
            "query_ooc_escalations": m1.get("query_ooc_escalations", 0),
        }
        print(f"# ooc {name}: uncapped={un_ms:.0f}ms capped={cap_ms:.0f}ms"
              f" budget={cap} match={match} ooc={ooc}", file=sys.stderr)
        _emit_ooc(suite_name, scale, out, timings, all_match, final=False)
    _emit_ooc(suite_name, scale, out, timings, all_match, final=True)


def _emit_ooc(suite_name, scale, out, timings, all_match, final):
    """Running JSON line after every --ooc query (same lossless-kill
    discipline as the suite runner: the last stdout line is always a
    complete, parseable record covering everything measured)."""
    print(json.dumps({
        "mode": "ooc",
        "metric": f"{suite_name}_sf{scale:g}_ooc_capped_geomean_x",
        "value": round(float(np.exp(np.mean(np.log(
            [max(v["degradation_x"], 1e-6) for v in out.values()
             if v.get("degradation_x")])))), 3)
        if any(v.get("degradation_x") for v in out.values()) else None,
        "unit": "x (capped/uncapped wall, lower is better)",
        "suite": suite_name,
        f"{suite_name}_suite_scale": scale,
        "backend": jax.default_backend(),
        "queries": out,
        "ooc_timings_ms": timings,
        "all_match": all_match,
        "all_engaged": all(v.get("ooc_engaged") for v in out.values())
        if out else False,
        "zero_replay_rung": all(
            not v.get("query_oom_replays") for v in out.values()),
        "extra_conf": dict(EXTRA_CONF),
        "elapsed_s": round(time.perf_counter() - _T0, 1),
        "final": final}), flush=True)


#: default serving mix: a fast, join/agg-diverse TPC-H tranche (clients
#: rotate through it; --queries overrides)
SERVING_MIX = ["q1", "q3", "q6", "q12", "q14", "q19"]

#: closed-loop concurrency levels --serving sweeps
SERVING_LEVELS = (1, 2, 4, 8)


def _pctl(vals, p):
    vs = sorted(vals)
    if not vs:
        return None
    k = max(0, min(len(vs) - 1, int(round(p / 100.0 * (len(vs) - 1)))))
    return vs[k]


def _rows_key(table):
    d = table.to_pydict()
    names = sorted(d)
    return sorted(zip(*(d[n] for n in names))) if names else []


def _fleet_worker_skew(fleet):
    """Utilization skew across pool workers: max/min ratio of summed
    per-worker device-us mined from the federated fleet registry
    (`tpu_fleet_serving_tenant_device_us_total{worker=..,tenant=..}`).
    1.0 = perfectly even dispatch; None when fewer than two workers
    reported work (nothing to compare)."""
    per = {}
    for key, v in (fleet or {}).items():
        if not key.startswith(
                "tpu_fleet_serving_tenant_device_us_total{"):
            continue
        labels = key.split("{", 1)[1].rstrip("}")
        wid = next((p.split("=", 1)[1] for p in labels.split(",")
                    if p.startswith("worker=")), None)
        if wid is not None:
            per[wid] = per.get(wid, 0) + float(v)
    if len(per) < 2 or min(per.values()) <= 0:
        return None
    return round(max(per.values()) / min(per.values()), 3)


def run_serving(suite_name: str, scale: float, query_names):
    """--serving: N concurrent closed-loop clients over a query mix
    through the ServingRuntime, vs the SAME query multiset run serially
    through today's single-query path.

    Per concurrency level: every client is its own tenant and runs the
    mix once (rotated by client index), so level c issues c*len(mix)
    queries — closed-loop repeated dashboard traffic.  The serial
    baseline runs the level-8 multiset sequentially through
    `PhysicalQuery.collect` exactly as today's path would serve it
    (replan per request, no result reuse).  Levels run with the result
    cache ON (it IS the serving architecture for this traffic); a
    `c8_nocache` level isolates pure phase overlap.  NOTE on reading
    the two: on an accelerator the nocache level shows the real
    compile/upload/host-tail overlap win; on a CPU-backend container
    the "device" shares the host cores (this harness runs on ONE core),
    so compute overlap cannot add throughput there by construction and
    nocache QPS ~= serial is the expected reading, with the serving win
    carried by the cache + structure-shared compiles.  Latency is
    client-observed submit->result wall (admission waits included).
    `mp2` / `mp4` levels run the same mix through the SUPERVISED
    WORKER POOL (`serving.pool.processes`, docs/SERVING.md): device
    execution in 2/4 worker processes — the fault-isolation
    architecture's throughput cost (dispatch serialization + per-worker
    warmup; the result cache is bypassed by construction).  `mp2_kill`
    additionally SIGKILLs one worker mid-query (`worker:kill:nth=1`)
    and must stay oracle-matching: the lost query redrives on the
    survivor (docs/ROBUSTNESS.md).
    Gate entries: `serving_latency_ms` (sv:-prefixed in
    scripts/check_regression.py, lower = better, same-backend rule)."""
    import importlib
    import threading
    workload = importlib.import_module(f"spark_rapids_tpu.{suite_name}")
    from spark_rapids_tpu.config import (COMPILE_CACHE_DIR,
                                         WHOLE_PLAN_COMPILE)
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.serving.runtime import ServingRuntime
    from spark_rapids_tpu.session import DataFrame, TpuSession

    rtt = measure_rtt()
    tables = workload.gen_tables(scale=scale)
    dev = TpuSession({WHOLE_PLAN_COMPILE.key: "ON",
                      COMPILE_CACHE_DIR.key: BENCH_CACHE_DIR})
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    mix = [n for n in (query_names or SERVING_MIX)
           if n in workload.QUERIES]

    # warm every mix query once (compile + upload) and oracle-check it
    per_q = {}
    expected = {}
    for name in mix:
        dfq = workload.QUERIES[name](dev, tables)
        q = dfq.physical()
        t0 = time.perf_counter()
        out = q.collect(ExecContext(dev.conf))
        cold_s = time.perf_counter() - t0
        oracle = DataFrame(dfq._plan, cpu).physical().collect()
        expected[name] = _rows_key(out)
        per_q[name] = {"cold_s": round(cold_s, 1),
                       "match": approx_equal(out, oracle)}
        print(f"# warm {name}: cold={cold_s:.1f}s "
              f"match={per_q[name]['match']}", file=sys.stderr)

    # serial baseline: the level-8 multiset through the single-query path
    serial_n = 8 * len(mix)
    t0 = time.perf_counter()
    for i in range(serial_n):
        name = mix[i % len(mix)]
        q = workload.QUERIES[name](dev, tables).physical()
        q.collect(ExecContext(dev.conf))
    serial_s = time.perf_counter() - t0
    serial_qps = serial_n / serial_s
    print(f"# serial baseline: {serial_n} queries in {serial_s:.1f}s "
          f"({serial_qps:.2f} QPS)", file=sys.stderr)

    def run_level(c: int, cache_on: bool, procs: int = 0,
                  faults: str = "") -> dict:
        # workers: 3 pipelines keep one query in a host phase while
        # another executes; more just multiplies GIL-bound planners
        # contending with the executing query (measured — the worker
        # sweep in docs/SERVING.md)
        ov = {
            "spark.rapids.tpu.serving.workers": str(min(3, max(2, c))),
            "spark.rapids.tpu.serving.resultCache.bytes":
                "0" if not cache_on else str(256 << 20)}
        if procs:
            # multi-process pool level: device execution moves into
            # `procs` supervised worker processes (docs/SERVING.md);
            # the result cache is bypassed by construction there
            ov["spark.rapids.tpu.serving.pool.processes"] = str(procs)
        if faults:
            # chaos leg: e.g. worker:kill:nth=1 SIGKILLs one worker
            # mid-query — the level must stay oracle-matching (redrive)
            ov["spark.rapids.tpu.test.faults"] = faults
        rt = ServingRuntime(dev, ov)
        lats, errs, mismatches = [], [], []
        lock = threading.Lock()

        def client(idx: int):
            tenant = rt.tenant(f"client{idx}")
            for j in range(len(mix)):
                name = mix[(j + idx) % len(mix)]
                df = workload.QUERIES[name](dev, tables)
                t0 = time.perf_counter()
                try:
                    out = tenant.collect(df)
                except Exception as e:           # noqa: BLE001
                    with lock:
                        errs.append(f"{name}: {type(e).__name__}: {e}")
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    lats.append(dt)
                    if _rows_key(out) != expected[name]:
                        mismatches.append(name)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(c)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        stats = rt.stats()
        rt.close()
        n = len(lats)
        level = {"clients": c, "queries": n, "errors": errs,
                 "mismatches": sorted(set(mismatches)),
                 "wall_s": round(wall, 2),
                 "qps": round(n / wall, 3) if wall else None,
                 "p50_ms": round(_pctl(lats, 50) * 1e3, 1) if n else None,
                 "p99_ms": round(_pctl(lats, 99) * 1e3, 1) if n else None,
                 "mean_ms": round(sum(lats) / n * 1e3, 1) if n else None,
                 "device_utilization": stats["device_utilization"],
                 "overlap_observed": stats["overlap_observed"],
                 "max_skips": stats["max_skips"],
                 "result_cache": stats["result_cache"],
                 "cache_on": cache_on}
        if procs:
            pool = stats.get("pool") or {}
            # the federated fleet registry (per-worker-labeled series the
            # supervisor folded from heartbeat telemetry) rides the level
            # record so regression mining sees cross-process utilization
            fleet = stats.get("fleet") or {}
            level.update(
                pool_processes=procs,
                worker_restarts=pool.get("restarts"),
                redrives=pool.get("redrives"),
                faults=faults or None,
                fleet=fleet or None,
                worker_skew=_fleet_worker_skew(fleet))
        print(f"# serving c={c} cache={'on' if cache_on else 'off'}: "
              f"{n} queries {wall:.1f}s qps={level['qps']} "
              f"p50={level['p50_ms']}ms p99={level['p99_ms']}ms "
              f"util={level['device_utilization']}", file=sys.stderr)
        return level

    levels = {}
    for c in SERVING_LEVELS:
        if left() < 45:
            print(f"# budget: skipping serving level c={c}",
                  file=sys.stderr)
            continue
        levels[f"c{c}"] = run_level(c, cache_on=True)
    if left() > 45:
        levels["c8_nocache"] = run_level(8, cache_on=False)
    # multi-process pool levels (docs/SERVING.md): same mix through the
    # supervised worker pool — the fault-isolation architecture's
    # throughput cost vs the in-process path — plus a chaos leg that
    # SIGKILLs one worker mid-query and must stay oracle-matching via
    # redrive.  Pool levels ship source tables over the dispatch socket
    # and pay per-worker session warmup, so they are budget-gated
    # harder than the in-process levels.
    for procs in (2, 4):
        if left() < 150:
            print(f"# budget: skipping serving level mp{procs}",
                  file=sys.stderr)
            continue
        levels[f"mp{procs}"] = run_level(4, cache_on=True, procs=procs)
    if left() > 150:
        levels["mp2_kill"] = run_level(4, cache_on=True, procs=2,
                                       faults="worker:kill:nth=1")
    else:
        print("# budget: skipping serving level mp2_kill",
              file=sys.stderr)

    c8 = levels.get("c8") or {}
    c8_nc = levels.get("c8_nocache") or {}
    gate = {}
    for key, lvl in levels.items():
        if lvl.get("p99_ms"):
            gate[f"{key}_p99"] = lvl["p99_ms"]
        if lvl.get("mean_ms"):
            gate[f"{key}_mean"] = lvl["mean_ms"]
    out = {"mode": "serving",
           "metric": f"{suite_name}_sf{scale:g}_serving_c8_qps",
           "value": c8.get("qps"),
           "unit": "qps",
           "suite": suite_name,
           f"{suite_name}_suite_scale": scale,
           "backend": jax.default_backend(),
           "mix": mix,
           "queries": per_q,
           "serial_n": serial_n,
           "serial_s": round(serial_s, 2),
           "serial_qps": round(serial_qps, 3),
           "serving_levels": levels,
           "serving_latency_ms": gate,
           # per-level worker utilization skew from the federated fleet
           # registry — mined by check_regression under the sv: rules
           "serving_fleet": {f"{key}_skew": lvl["worker_skew"]
                             for key, lvl in levels.items()
                             if lvl.get("worker_skew")},
           "qps_vs_serial": round(c8["qps"] / serial_qps, 3)
           if c8.get("qps") else None,
           "qps_nocache_vs_serial": round(c8_nc["qps"] / serial_qps, 3)
           if c8_nc.get("qps") else None,
           "serving_beats_serial": bool(c8.get("qps") and
                                        c8["qps"] > serial_qps),
           # the crash-containment headline: the kill leg lost one
           # worker mid-query and still matched the oracle everywhere
           "mp_kill_contained": bool(
               (kl := levels.get("mp2_kill"))
               and not kl["errors"] and not kl["mismatches"]
               and (kl.get("worker_restarts") or {}).get("crash")),
           "overlap_observed": bool(c8_nc.get("overlap_observed") or
                                    c8.get("overlap_observed")),
           "all_match": all(v["match"] for v in per_q.values()),
           "tunnel_rtt_ms": round(rtt * 1e3, 1),
           "elapsed_s": round(time.perf_counter() - _T0, 1),
           "final": True,
           "note": "closed-loop clients, one tenant each, mix rotated "
                   "per client (repeated dashboard traffic); levels run "
                   "the full serving architecture (result cache ON), "
                   "c8_nocache isolates pure phase overlap — on a "
                   "cpu-backend container the engine shares the host "
                   "cores with itself, so nocache ~= serial is the "
                   "expected reading there and the serving win is "
                   "cache + structure-shared compiles; latency = "
                   "client-observed submit->result wall incl. admission "
                   "waits; serial baseline = the same multiset through "
                   "the single-query path (replan per request, no "
                   "result reuse)."}
    print(json.dumps(out), flush=True)
    dev.close()


def measure_metrics_overhead(workload, tables, suite, dev, name="q6"):
    """Re-time one already-measured query with the metrics plane OFF and
    report the delta — the proof the always-on registry + flight
    recorder cost stays within the claimed bound (docs/METRICS.md).
    Budget-gated and fail-soft: its absence loses the overhead line,
    never the benchmark."""
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.session import TpuSession
    on_ms = (suite.per_q.get(name) or {}).get("device_ms")
    if on_ms is None or left() < 60:
        return None
    try:
        from spark_rapids_tpu.config import METRICS_ENABLED
        from spark_rapids_tpu.obs.export import configure_plane
        try:
            off = TpuSession({METRICS_ENABLED.key: "false"})
            q = workload.QUERIES[name](off, tables).physical()
            q.collect(ExecContext(off.conf))         # warm
            t_off = time_warm(lambda: q.collect(ExecContext(off.conf)))
        finally:
            configure_plane(dev.conf)                # plane back ON
        off_ms = t_off * 1e3
        return {"query": name, "on_ms": on_ms,
                "off_ms": round(off_ms, 1),
                "overhead_pct": round((on_ms - off_ms) / off_ms * 100, 2)
                if off_ms else None}
    except Exception as e:                           # noqa: BLE001
        return {"query": name, "error": f"{type(e).__name__}: {e}"[:200]}


def main():
    scale = 1.0
    names = None
    suite_name = "tpch"
    compile_only = False
    serving = False
    kernels = False
    encodings = False
    ooc = False
    multichip = False
    multichip_sf = 10.0
    args = list(sys.argv[1:])
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("--conf"):
            if a.startswith("--conf="):
                kv = a[len("--conf="):]
            else:
                i += 1
                kv = args[i]
            k, _, v = kv.partition("=")
            EXTRA_CONF[k] = v
        elif a == "--kernels":
            kernels = True
        elif a == "--encodings":
            encodings = True
        elif a == "--ooc":
            ooc = True
        elif a.startswith("--history-dir"):
            # persistent performance-history plane: every measured query
            # records its structure-keyed device time (obs/history.py)
            # so later rounds/admissions estimate from measured cost —
            # scripts/history_report.py renders the dir
            if "=" in a:
                hd = a.split("=", 1)[1]
            else:
                i += 1
                hd = args[i]
            EXTRA_CONF["spark.rapids.tpu.history.dir"] = hd
        elif a.startswith("--queries"):
            if "=" in a:
                names = a.split("=", 1)[1].split(",")
            else:
                i += 1
                names = args[i].split(",")
        elif a.startswith("--suite"):
            if "=" in a:
                suite_name = a.split("=", 1)[1]
            else:
                i += 1
                suite_name = args[i]
        elif a == "--compile-only":
            compile_only = True
        elif a == "--serving":
            serving = True
        elif a == "--multichip-suite":
            multichip = True
        elif a.startswith("--multichip-sf"):
            if "=" in a:
                multichip_sf = float(a.split("=", 1)[1])
            else:
                i += 1
                multichip_sf = float(args[i])
        else:
            scale = float(a)
        i += 1
    if multichip:
        # 8-virtual-device mesh + sharded TPC-H at --multichip-sf: must
        # run before any jax backend init (device-count config), so it
        # owns the whole process — spark_rapids_tpu/multichip.py
        from spark_rapids_tpu.multichip import run_multichip_suite
        run_multichip_suite(sf=multichip_sf, queries=names,
                            budget_s=TOTAL_BUDGET_S)
        return
    if suite_name not in ("tpch", "tpcds"):
        raise SystemExit(f"unknown suite {suite_name!r} "
                         f"(expected tpch or tpcds)")
    import importlib
    workload = importlib.import_module(f"spark_rapids_tpu.{suite_name}")
    query_names = names or sorted(workload.QUERIES,
                                  key=lambda q: int(q[1:]))

    if kernels:
        # Pallas-vs-sorted kernel microbench A/B (KERNELS_r*.json)
        run_kernels()
        return
    if encodings:
        # encoded-vs-decode-first microbench A/B (ENCODINGS_r*.json)
        run_encodings()
        return
    if ooc:
        # memory-capped out-of-core leg (OOC_r*.json, oc: gate entries)
        run_ooc(suite_name, scale, names)
        return
    if serving:
        # concurrent closed-loop serving sweep (names = the mix)
        run_serving(suite_name, scale, names)
        return
    if compile_only:
        run_compile_only(suite_name, scale, query_names)
        return
    suite = run_suite(suite_name, scale, query_names)
    suite.emit(final=True)


if __name__ == "__main__":
    main()
