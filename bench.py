#!/usr/bin/env python
"""Headline benchmark: the full 22-query TPC-H suite at SF>=1.

Prints ONE json line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Headline metric: geometric-mean speedup of per-query WARM wall time
(device engine / whole-plan XLA compilation) over the SAME queries on
the engine's CPU fallback (vectorized pyarrow kernels — a stronger
stand-in for CPU Spark than Spark itself: columnar C++ kernels, no
JVM/task overhead, so the reported speedup is conservative vs the
BASELINE.md north star).

Methodology.
  * Every query runs BOTH engines from the same in-memory tables and
    results are cross-checked (float tails to 1e-9 relative — reduction
    order differs, as the reference documents for GPU float aggs).
  * Device timing is single-shot warm wall time: one whole-plan XLA
    dispatch + one result fetch, measured after the one-time costs
    (compile — persisted to the jax compilation cache; H2D upload —
    tables are device-resident across queries, the buffer-cache role).
    It INCLUDES the test harness tunnel's ~60ms round-trip per query;
    the RTT is also reported separately so the engine-time floor is
    visible.  CPU timing is the same warm single-shot discipline.
  * Cold numbers (first-run compile, upload) are reported on stderr.

Run: python bench.py [scale] [--queries q1,q6,...]
"""
import json
import sys
import time

import numpy as np

import jax

# persistent compile cache: cold compiles (minutes/query over the
# tunnel) are paid once per (plan, shape); later runs trace + load
jax.config.update("jax_compilation_cache_dir",
                  __file__.rsplit("/", 1)[0] + "/.jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def measure_rtt() -> float:
    """Median device round-trip (a 4-byte fetch) — the per-sync tax this
    harness adds; on a locally attached chip it is ~10us."""
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((1,), jnp.int32)
    jax.device_get(f(x))
    times = []
    for _ in range(11):
        t0 = time.perf_counter()
        # a fresh device-computed value: the fetch must round-trip
        jax.device_get(f(x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def approx_equal(a, b) -> bool:
    da, db = a.to_pydict(), b.to_pydict()
    if set(da) != set(db):
        return False
    for k in da:
        if len(da[k]) != len(db[k]):
            return False
        for x, y in zip(da[k], db[k]):
            if x == y:
                continue
            if isinstance(x, float) and isinstance(y, float) and \
                    abs(x - y) <= 1e-6 * max(1.0, abs(x), abs(y)):
                continue
            return False
    return True


def time_warm(fn, iters=3):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def run_suite(scale: float, query_names):
    from spark_rapids_tpu import tpch
    from spark_rapids_tpu.exec.plan import ExecContext
    from spark_rapids_tpu.session import DataFrame, TpuSession

    t0 = time.perf_counter()
    tables = tpch.gen_tables(scale=scale)
    gen_s = time.perf_counter() - t0
    print(f"# datagen SF{scale}: {gen_s:.1f}s "
          f"lineitem={tables['lineitem'].num_rows}", file=sys.stderr)

    dev = TpuSession()          # wholePlan AUTO -> on for the TPU backend
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})

    per_q = {}
    compiled_ct = 0
    for name in query_names:
        dfq = tpch.QUERIES[name](dev, tables)
        q = dfq.physical()
        # cold: compile (or cache load) + device upload + first run
        t0 = time.perf_counter()
        out = q.collect(ExecContext(dev.conf))
        cold_s = time.perf_counter() - t0
        dt = time_warm(lambda: q.collect(ExecContext(dev.conf)))
        ctx = ExecContext(dev.conf)
        out = q.collect(ctx)
        compiled = ctx.metrics.get("whole_plan_compiled_queries", 0)
        compiled_ct += compiled

        cq = DataFrame(dfq._plan, cpu).physical()
        oracle = cq.collect()
        ct = time_warm(lambda: cq.collect(), iters=2)

        match = approx_equal(out, oracle)
        per_q[name] = {"device_ms": round(dt * 1e3, 1),
                       "cpu_ms": round(ct * 1e3, 1),
                       "speedup": round(ct / dt, 2),
                       "compiled": bool(compiled),
                       "match": match}
        print(f"# {name}: device={dt*1e3:.0f}ms cpu={ct*1e3:.0f}ms "
              f"x{ct/dt:.2f} cold={cold_s:.1f}s "
              f"compiled={bool(compiled)} match={match}", file=sys.stderr)
        if not match:
            print(f"# WARNING {name}: device != cpu oracle", file=sys.stderr)
    speedups = [v["speedup"] for v in per_q.values()]
    geomean = float(np.exp(np.mean(np.log(speedups)))) if speedups else 0.0
    return per_q, geomean, compiled_ct


def main():
    scale = 1.0
    names = None
    args = list(sys.argv[1:])
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("--queries"):
            if "=" in a:
                names = a.split("=", 1)[1].split(",")
            else:
                i += 1
                names = args[i].split(",")
        else:
            scale = float(a)
        i += 1
    from spark_rapids_tpu import tpch
    query_names = names or sorted(tpch.QUERIES, key=lambda q: int(q[1:]))

    rtt = measure_rtt()
    print(f"# backend={jax.default_backend()} tunnel RTT ~{rtt*1e3:.0f}ms "
          f"per host sync", file=sys.stderr)

    per_q, geomean, compiled_ct = run_suite(scale, query_names)

    q6 = per_q.get("q6", {})
    out = {
        "metric": f"tpch_sf{scale:g}_suite_geomean_speedup_vs_cpu",
        "value": round(geomean, 3),
        "unit": "x",
        "vs_baseline": round(geomean, 3),
        "tpch_suite_scale": scale,
        "tpch_suite_queries": per_q,
        "tpch_suite_geomean_speedup": round(geomean, 3),
        "queries_measured": len(per_q),
        "whole_plan_compiled": compiled_ct,
        "tunnel_rtt_ms": round(rtt * 1e3, 1),
        "q6_device_ms": q6.get("device_ms"),
        "note": "warm single-shot wall per query (one whole-plan XLA "
                "dispatch + one fetch, device-resident tables, compile "
                "cached); INCLUDES one tunnel RTT per query — "
                "tunnel_rtt_ms is the harness floor. CPU baseline = "
                "same queries on the engine's vectorized pyarrow "
                "fallback, warm.",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
