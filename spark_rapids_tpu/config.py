"""Typed configuration system for the TPU-native engine.

Plays the role of the reference's RapidsConf (sql-plugin/.../RapidsConf.scala:
3156 LoC, 225 `spark.rapids.*` entries): a registry of typed, documented
config entries with defaults, validated setters, `startup_only`/`internal`
markers and markdown doc generation (`python -m spark_rapids_tpu.config`
mirrors RapidsConf.main writing docs/configs.md).

Keys use the `spark.rapids.tpu.*` prefix.  Per-operator enable keys are
generated automatically from rule names by the plan-rewrite engine
(`spark.rapids.tpu.sql.expression.Abs=false` pattern, reference
RapidsMeta.scala:301-316).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: Dict[str, "ConfEntry"] = {}


def _parse_bool(raw: Any) -> bool:
    if isinstance(raw, bool):
        return raw
    return str(raw).strip().lower() in ("true", "1", "yes")


@dataclasses.dataclass
class ConfEntry:
    key: str
    default: Any
    doc: str
    conf_type: type
    checker: Optional[Callable[[Any], Optional[str]]] = None
    internal: bool = False
    startup_only: bool = False
    commonly_used: bool = False

    def convert(self, raw: Any) -> Any:
        if self.conf_type is bool:
            val = _parse_bool(raw)
        elif self.conf_type is int:
            val = int(str(raw).strip())
        elif self.conf_type is float:
            val = float(str(raw).strip())
        else:
            val = str(raw)
        if self.checker is not None:
            err = self.checker(val)
            if err:
                raise ValueError(f"{self.key}: {err}")
        return val


def _register(entry: ConfEntry) -> ConfEntry:
    if entry.key in _REGISTRY:
        raise ValueError(f"duplicate conf key {entry.key}")
    _REGISTRY[entry.key] = entry
    return entry


def conf(key, default, doc, conf_type=None, checker=None, internal=False,
         startup_only=False, commonly_used=False) -> ConfEntry:
    if conf_type is None:
        conf_type = type(default) if default is not None else str
    return _register(ConfEntry(key, default, doc, conf_type, checker,
                               internal, startup_only, commonly_used))


def _enum_checker(*allowed):
    def check(v):
        if str(v).upper() not in allowed:
            return f"must be one of {allowed}, got {v}"
        return None
    return check


def _non_negative(v):
    return None if v >= 0 else "must be >= 0"


def _positive(v):
    return None if v > 0 else "must be positive"


# --------------------------------------------------------------------------
# Core entries (subset mirroring the commonly-used reference entries; grows).
# --------------------------------------------------------------------------

SQL_ENABLED = conf(
    "spark.rapids.tpu.sql.enabled", True,
    "Master kill-switch: when false, no operator is placed on the TPU.",
    commonly_used=True)

EXPLAIN = conf(
    "spark.rapids.tpu.sql.explain", "NONE",
    "Explain mode: NONE, ALL (log every placement decision), or NOT_ON_TPU "
    "(log only operators that fell back to CPU with their reasons).",
    checker=_enum_checker("NONE", "ALL", "NOT_ON_TPU"), commonly_used=True)

MODE = conf(
    "spark.rapids.tpu.sql.mode", "executeOnTPU",
    "executeOnTPU runs supported operators on the TPU; explainOnly runs the "
    "whole planning pipeline (tagging + reasons) but executes fully on CPU.",
    checker=_enum_checker("EXECUTEONTPU", "EXPLAINONLY"))

BATCH_SIZE_ROWS = conf(
    "spark.rapids.tpu.sql.batchSizeRows", 1 << 22,
    "Target maximum rows per device batch (reference batchSizeBytes analogue; "
    "rows rather than bytes because XLA static shapes are row-bucketed).",
    checker=_positive, commonly_used=True)

BATCH_SIZE_BYTES = conf(
    "spark.rapids.tpu.sql.batchSizeBytes", 1 << 30,
    "Target maximum bytes per device batch when coalescing host batches.",
    checker=_positive)

WHOLE_PLAN_COMPILE = conf(
    "spark.rapids.tpu.sql.compile.wholePlan", "AUTO",
    "Compile an entire device plan into ONE XLA program (tracing is the "
    "whole-plan analogue of the reference's cudf AST compiled "
    "expressions). AUTO enables it on the TPU backend only (CPU test "
    "meshes keep the eager batch engine); ON/OFF force it. Plans that "
    "need host-side decisions (sized join expansion, out-of-core sort) "
    "automatically fall back to the eager engine.",
    checker=_enum_checker("AUTO", "ON", "OFF"), commonly_used=True)

PYTHON_WORKER_CONCURRENCY = conf(
    "spark.rapids.tpu.python.concurrentPythonWorkers", 4,
    "Concurrent pandas-UDF worker processes (the reference's "
    "spark.rapids.python.concurrentPythonWorkers / "
    "PythonWorkerSemaphore role).", checker=_positive)

STRING_TRANSFORM_DEVICE_MIN = conf(
    "spark.rapids.tpu.sql.string.transformDeviceMinUnique", 8192,
    "Dictionary size above which string transforms (upper/lower/trim/"
    "substring) rewrite their byte tensors ON DEVICE (one packed-range "
    "kernel + one fetch) instead of the per-entry host loop. Small "
    "dictionaries stay host-side (kernel+fetch overhead dominates).",
    checker=_positive)

SESSION_TIMEZONE = conf(
    "spark.sql.session.timeZone", "UTC",
    "Session timezone for timestamp field extraction, truncation and "
    "date<->timestamp casts. Non-UTC zones convert on device through a "
    "precomputed IANA transition table (ops/timezone.py — the "
    "GpuTimeZoneDB role).", commonly_used=True)

MESH_ENABLED = conf(
    "spark.rapids.tpu.sql.mesh.enabled", False,
    "Execute device plans SPMD over ALL addressable chips: leaf scans "
    "shard row-wise across a jax.sharding.Mesh and the whole-plan XLA "
    "program is GSPMD-partitioned, with cross-chip exchanges (groupby, "
    "sort, join) riding ICI collectives inserted by XLA. The "
    "multi-chip execution fabric (reference RapidsShuffleManager/UCX "
    "role). Requires >=2 addressable devices; single-device sessions "
    "ignore it.", commonly_used=True)

MESH_DEVICES = conf(
    "spark.rapids.tpu.sql.mesh.devices", 0,
    "Number of mesh devices for SPMD execution (0 = all addressable).",
    checker=lambda v: None if v >= 0 else "must be >= 0")

CONCURRENT_TPU_TASKS = conf(
    "spark.rapids.tpu.sql.concurrentTpuTasks", 2,
    "Number of concurrent tasks allowed to hold device memory at once "
    "(reference GpuSemaphore concurrentGpuTasks default 2).",
    checker=_positive, commonly_used=True)

BUCKET_MIN_ROWS = conf(
    "spark.rapids.tpu.sql.shape.minBucketRows", 1024,
    "Smallest static-shape row bucket. Device batches are padded up to a "
    "bounded geometric set of row capacities so XLA's jit cache stays small.",
    checker=_positive, internal=True)

BUCKET_GROWTH = conf(
    "spark.rapids.tpu.sql.shape.bucketGrowth", 4,
    "Geometric growth factor between static-shape row buckets.",
    checker=lambda v: None if v >= 2 else "must be >= 2", internal=True)

ANSI_ENABLED = conf(
    "spark.rapids.tpu.sql.ansi.enabled", False,
    "ANSI mode: overflow/invalid-cast raise instead of returning null.")

IMPROVED_FLOAT_OPS = conf(
    "spark.rapids.tpu.sql.variableFloatAgg.enabled", True,
    "Allow floating-point aggregations whose result can differ from CPU "
    "Spark in last-ulp due to parallel reduction ordering (reference "
    "docs/compatibility.md float semantics).")

HASH_SUBPARTITION_FALLBACK = conf(
    "spark.rapids.tpu.sql.join.subPartition.enabled", True,
    "Re-hash-partition oversized join build sides into sub-joins "
    "(reference GpuSubPartitionHashJoin).")

ADAPTIVE_ENABLED = conf(
    "spark.rapids.tpu.sql.adaptive.enabled", True,
    "Runtime-statistics re-planning (the AQE analogue, reference "
    "GpuOverrides.scala:496-564): joins measure both materialized inputs "
    "and build on the smaller side; shuffle reads coalesce partitions to "
    "the advisory size from real map-output stats.")

ADAPTIVE_ADVISORY_PARTITION_BYTES = conf(
    "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes",
    64 * 1024 * 1024,
    "Target bytes per coalesced shuffle-read group "
    "(spark.sql.adaptive.advisoryPartitionSizeInBytes role).",
    checker=_positive)

ADAPTIVE_SKEW_FACTOR = conf(
    "spark.rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor", 5.0,
    "A shuffle partition whose stored bytes exceed this factor times the "
    "median partition size (and the advisory size) splits into multiple "
    "independent sub-reads (spark.sql.adaptive.skewJoin."
    "skewedPartitionFactor / GpuCustomShuffleReaderExec skew-read role). "
    "Set <= 0 to disable splitting.")

RUNTIME_FILTER_ENABLED = conf(
    "spark.rapids.tpu.sql.join.runtimeFilter.enabled", True,
    "Bloom-filter the probe side of large adaptive joins with the "
    "materialized build side's keys before probing (the reference's "
    "BloomFilter JNI / bloom_filter_might_contain role).")

RUNTIME_FILTER_RATIO = conf(
    "spark.rapids.tpu.sql.join.runtimeFilter.sizeRatio", 4.0,
    "Apply the runtime filter only when probe bytes exceed build bytes "
    "by this factor (below it the filter pass costs more than it saves).",
    checker=_positive, internal=True)

AGG_FALLBACK_PARTITIONS = conf(
    "spark.rapids.tpu.sql.agg.fallbackPartitions", 8,
    "Bucket count for the high-cardinality aggregation fallback: when "
    "merged partial results exceed one target batch, partials are "
    "re-hash-partitioned into this many independently-merged buckets "
    "(reference GpuAggregateExec repartition-based fallback).",
    checker=_positive, internal=True)

CBO_ENABLED = conf(
    "spark.rapids.tpu.sql.optimizer.enabled", False,
    "Cost-based placement pass: un-tag isolated cheap device operators "
    "whose two host<->device transitions outweigh the device win "
    "(reference CostBasedOptimizer, also off by default).")

RETRY_ENABLED = conf(
    "spark.rapids.tpu.sql.retry.enabled", True,
    "Retry device work with halved batches on HBM RESOURCE_EXHAUSTED "
    "(reference RmmRapidsRetryIterator withSplitAndRetry analogue).")

RETRY_MAX_SPLITS = conf(
    "spark.rapids.tpu.sql.retry.maxSplits", 8,
    "Maximum times a batch may be halved before the OOM is rethrown.",
    checker=_positive)

RETRY_MAX_ATTEMPTS = conf(
    "spark.rapids.tpu.sql.retry.maxAttempts", 2,
    "Attempt-ladder depth of the OOM retry framework: how many times one "
    "unit of device work runs (spilling everything between attempts) "
    "before the ladder escalates — with_split_retry halves the batch, "
    "with_retry rethrows. The reference replays exactly once; raising "
    "this trades replay work for survival under sustained pressure.",
    checker=lambda v: None if v >= 1 else "must be >= 1")

RETRY_IO_ATTEMPTS = conf(
    "spark.rapids.tpu.retry.io.maxAttempts", 3,
    "Bounded retry for transient host-IO failures (spill block "
    "read/write, shuffle fetch, host<->device transfers): total attempts "
    "per IO unit before the OSError propagates (classified as class "
    "'io' by runtime.failure.classify). 1 disables retry.",
    checker=lambda v: None if v >= 1 else "must be >= 1")

RETRY_IO_BACKOFF_MS = conf(
    "spark.rapids.tpu.retry.io.backoffMs", 10,
    "Initial backoff before the first IO retry, in milliseconds; each "
    "further retry multiplies it by retry.io.backoffMultiplier.",
    checker=_non_negative)

RETRY_IO_BACKOFF_MULT = conf(
    "spark.rapids.tpu.retry.io.backoffMultiplier", 2.0,
    "Multiplier applied to the IO retry backoff after every attempt.",
    checker=_positive)

RETRY_IO_JITTER = conf(
    "spark.rapids.tpu.retry.io.jitterFraction", 0.25,
    "Deterministic seeded jitter applied to every IO-retry backoff "
    "sleep: each sleep is scaled by a factor in [1-f, 1+f] drawn from a "
    "splitmix64 stream seeded by (pid, site) — N worker processes "
    "replaying the same transient host-IO fault desynchronize instead "
    "of thundering-herding the spill disk, while any single process's "
    "backoff sequence stays exactly reproducible. 0 disables jitter.",
    checker=lambda v: None if 0.0 <= v <= 1.0 else "must be in [0, 1]")

TEST_INJECT_RETRY_OOM = conf(
    "spark.rapids.tpu.sql.test.injectRetryOOM", 0,
    "Test-only: force a synthetic device OOM on the Nth retryable block "
    "(reference spark.rapids.sql.test.injectRetryOOM).", internal=True)

TEST_FAULTS = conf(
    "spark.rapids.tpu.test.faults", "",
    "Site-addressable deterministic fault injection (chaos harness, "
    "runtime/faults.py): a ';'-separated list of `site:kind:trigger` "
    "rules, e.g. `spill_read:corrupt:nth=2`, `reserve:oom:every=3`, "
    "`shuffle_fetch:ioerror:p=0.1,seed=7`. Sites name the layer that "
    "fails (reserve, compile, execute, h2d, d2h, spill_write, "
    "spill_read, shuffle_write, shuffle_fetch, exchange); kinds pick "
    "the failure (oom, ioerror, corrupt, fatal, error); triggers are "
    "nth=N (once, on the Nth hit), every=N, p=F[,seed=N], or always. "
    "Every injection and recovery emits an obs instant. Empty disables "
    "injection (the default path is a no-op).",
    checker=lambda v: _check_fault_spec(v))


def _check_fault_spec(v):
    # deferred: the grammar lives with the injector (runtime/faults.py);
    # the checker only runs at conf.get() time, after imports settle
    from .runtime.faults import check_spec
    return check_spec(v)

SHUFFLE_MODE = conf(
    "spark.rapids.tpu.shuffle.mode", "MULTITHREADED",
    "MULTITHREADED: host-side threaded Arrow-IPC shuffle (reference mode 1). "
    "ICI: collective all-to-all exchange over the device mesh for co-located "
    "partitions (reference UCX-mode analogue). CACHE_ONLY: in-process, tests.",
    checker=_enum_checker("MULTITHREADED", "ICI", "CACHE_ONLY"))

SHUFFLE_WRITER_THREADS = conf(
    "spark.rapids.tpu.shuffle.multiThreaded.writer.threads", 8,
    "Thread pool size for the multithreaded shuffle writer.", checker=_positive)

SHUFFLE_READER_THREADS = conf(
    "spark.rapids.tpu.shuffle.multiThreaded.reader.threads", 8,
    "Thread pool size for the multithreaded shuffle reader.", checker=_positive)

SHUFFLE_COMPRESSION = conf(
    "spark.rapids.tpu.shuffle.compression.codec", "zstd",
    "Codec for shuffle Arrow IPC buffers: zstd, lz4, or none — applied "
    "inside the IPC layer (the nvcomp codec role, "
    "TableCompressionCodec.scala:42), so readers are codec-agnostic.",
    checker=_enum_checker("ZSTD", "LZ4", "NONE"))

EXCHANGE_COMPRESS = conf(
    "spark.rapids.tpu.exchange.compress.enabled", True,
    "Compress lanes on-device BEFORE the mesh all_to_all collective "
    "(the nvcomp-before-UCX analog): validity/flag lanes pack to 1 bit "
    "per row, integer lanes narrow to frame-of-reference uint8/16/32 "
    "words when their global live range allows (the range rides the "
    "exchange's own count fetch), and all narrow lanes fuse into one "
    "wide byte-word collective per round.  The "
    "tpu_exchange_wire_bytes_{pre,post}_compress metric families "
    "report the achieved ratio.", commonly_used=True)

EXCHANGE_QUOTA_AUTO = conf(
    "spark.rapids.tpu.exchange.quota.auto", True,
    "Derive each ragged-exchange round's slab quota from the exchanged "
    "per-destination count matrix (pow2-quantized): a uniform exchange "
    "finishes in one small round, and a hot destination widens the "
    "quota (bounded by the receive-buffer commitment) instead of "
    "forcing max_count/quota rounds on every chip.  false restores the "
    "fixed 2*cap/P fudge quota.")

EXCHANGE_QUOTA_ROWS = conf(
    "spark.rapids.tpu.exchange.quota.rows", 0,
    "Fixed per-round slab quota (rows per destination) for the ragged "
    "exchange; 0 sizes it from capacity (2*cap/P, pow2-rounded). "
    "Explicit values are pow2-rounded so compiled round variants stay "
    "bounded.", checker=_non_negative)

EXCHANGE_DONATE = conf(
    "spark.rapids.tpu.exchange.donate", "AUTO",
    "Donate the ragged exchange's receive buffers through each round "
    "program (double-buffering: rounds update the buffers in place "
    "instead of allocating and round-tripping fresh copies).  AUTO "
    "enables it on backends with buffer donation (TPU); ON/OFF force.",
    checker=_enum_checker("AUTO", "ON", "OFF"))

EXCHANGE_SPLIT_RETRY = conf(
    "spark.rapids.tpu.exchange.skew.splitRetry", True,
    "Skew mitigation for the distributed groupby: when the planned "
    "exchange would GROW a receive buffer (one hot hash partition), "
    "salt rows across destination pairs, merge, and re-exchange the "
    "(small) merged groups to their true owners — receive memory stays "
    "bounded by actual groups instead of the hot key's row count. "
    "Applies only when every merge kind is order-insensitive "
    "(sum/min/max/any/every; first/last keep the direct path).")

HOST_SPILL_LIMIT_BYTES = conf(
    "spark.rapids.tpu.memory.host.spillStorageSize", 8 << 30,
    "Host spill store byte limit before batches overflow to disk "
    "(reference RapidsHostMemoryStore limit).", checker=_positive)

HBM_BUDGET_BYTES = conf(
    "spark.rapids.tpu.memory.tpu.budgetBytes", 0,
    "Absolute HBM byte budget for operator-held batches; 0 derives it from "
    "allocFraction x discovered device memory (unlimited when memory stats "
    "are unavailable).  Exceeding the budget spills LRU batches to host.",
    checker=lambda v: None if v >= 0 else "must be >= 0")

HBM_BUDGET_FRACTION = conf(
    "spark.rapids.tpu.memory.tpu.allocFraction", 0.85,
    "Fraction of per-chip HBM the engine budgets for batches; exceeding the "
    "budget triggers spill-to-host before new device work is admitted.",
    checker=lambda v: None if 0 < v <= 1 else "must be in (0, 1]")

PARQUET_READER_TYPE = conf(
    "spark.rapids.tpu.sql.format.parquet.reader.type", "AUTO",
    "AUTO, PERFILE, COALESCING, or MULTITHREADED (reference 3 strategies).",
    checker=_enum_checker("AUTO", "PERFILE", "COALESCING", "MULTITHREADED"))

PARQUET_MT_THREADS = conf(
    "spark.rapids.tpu.sql.format.parquet.multiThreadedRead.numThreads", 8,
    "Thread pool for the multithreaded parquet reader.", checker=_positive)

ENABLED_FORMATS = {
    fmt: conf(
        f"spark.rapids.tpu.sql.format.{fmt}.enabled", True,
        f"Enable accelerated {fmt} scan.")
    for fmt in ("parquet", "csv", "json", "orc", "avro", "iceberg",
                "hivetext")
}

SPARK_VERSION = conf(
    "spark.rapids.tpu.spark.version", "3.5.0",
    "Spark line whose semantics the engine emulates; selects the shim "
    "(shims.py, the ShimLoader/SparkShimServiceProvider role).")

CPU_ORACLE_VALIDATE = conf(
    "spark.rapids.tpu.sql.test.validateWithCpu", False,
    "Test-only: run every device operator's CPU fallback too and compare.",
    internal=True)

METRICS_LEVEL = conf(
    "spark.rapids.tpu.sql.metrics.level", "MODERATE",
    "ESSENTIAL, MODERATE, or DEBUG metric collection per operator.",
    checker=_enum_checker("ESSENTIAL", "MODERATE", "DEBUG"))

PROFILE_PATH = conf(
    "spark.rapids.tpu.profile.path", "",
    "When set, wrap query execution in a jax-profiler trace written to "
    "this directory (the NVTX/CUPTI Profiler analogue; open in "
    "XProf/perfetto).")

PROFILE_SEGMENTS = conf(
    "spark.rapids.tpu.profile.segments", False,
    "Per-segment DEVICE-TIME attribution (exec/compiled.py): every "
    "compiled program execution blocks until its outputs are ready and "
    "records measured device wall, output rows and bytes against the "
    "segment's stable plan-node-id range — tracer `segment` spans, the "
    "tpu_segment_* registry families, and the explain_analyze() plan "
    "annotations all read it.  Whole-plan programs additionally "
    "RE-SPLIT at the seam boundaries the split compiler already knows "
    "(ignoring compile.seamSplitMinRows) so join subtrees and "
    "aggregates time separately.  Off by default: the disabled path is "
    "one conf check per program dispatch (no sync, no re-split).",
    commonly_used=True)

PROFILE_COST_ANALYSIS = conf(
    "spark.rapids.tpu.profile.costAnalysis", True,
    "Capture XLA cost_analysis()/memory_analysis() (FLOPs, bytes "
    "accessed, peak temp allocation) per compiled segment at COMPILE "
    "time and surface it next to measured device time "
    "(explain_analyze(), segment.* metrics) so predicted-vs-actual "
    "skew flags mis-fused segments.  Compile-time only: zero cost on "
    "the execute path.")

PROFILE_MEMORY = conf(
    "spark.rapids.tpu.profile.memory", True,
    "Device-MEMORY attribution (obs/memattr.py), active when "
    "profile.segments is on: every compiled segment dispatch is "
    "bracketed by a MemoryBudget census (resident, naked and "
    "spillable-resident bytes, peak delta across the window) and its "
    "XLA memory_analysis() bytes, building the per-query HBM timeline "
    "(reserve/release/spill/OOM watermarks with plan-node attribution) "
    "the EXPLAIN ANALYZE `hbm=` column, the segment.*.hbm_* metrics, "
    "tpu_segment_hbm_peak_bytes and the crash-dump forensics read "
    "from.  With profile.segments off this knob is never consulted — "
    "the execute path stays one conf check per dispatch.")

PROFILE_MEMORY_TIMELINE_EVENTS = conf(
    "spark.rapids.tpu.profile.memoryTimelineEvents", 512,
    "Bound on the per-query HBM-timeline event list (obs/memattr.py): "
    "past it further watermark samples are dropped and counted, so a "
    "reserve storm cannot grow query memory.", checker=_positive)

TRACE_ENABLED = conf(
    "spark.rapids.tpu.trace.enabled", False,
    "Collect query-lifecycle spans in memory (plan/compile/execute/"
    "transition/shuffle ranges, runtime incident events, data-movement "
    "counters) for TpuSession.last_query_profile() / DataFrame.metrics() "
    "without writing files. Off by default; the disabled path is a "
    "no-op tracer (obs/tracer.py).", commonly_used=True)

EVENT_LOG_DIR = conf(
    "spark.rapids.tpu.eventLog.dir", "",
    "When set, every query writes a structured JSONL event log "
    "(query_<id>.jsonl — the Spark history-server event-log analogue) "
    "and a Chrome trace-event JSON (query_<id>.trace.json, openable in "
    "perfetto — the NVTX/nsys analogue) into this directory. Implies "
    "span tracing for the query. Render reports with "
    "scripts/profile_report.py.", commonly_used=True)

METRICS_ENABLED = conf(
    "spark.rapids.tpu.metrics.enabled", True,
    "The always-on metrics plane (obs/registry.py + obs/recorder.py): "
    "process-wide counters/gauges/log2-histograms every runtime "
    "subsystem publishes into, plus the flight-recorder ring embedded "
    "in crash dumps. False turns every publish call into one attribute "
    "check (the A/B overhead knob bench.py reports against).",
    commonly_used=True)

METRICS_PORT = conf(
    "spark.rapids.tpu.metrics.port", -1,
    "TCP port for the on-demand Prometheus text-format endpoint "
    "(stdlib http.server thread, obs/export.py): GET /metrics for the "
    "exposition text, /metrics.json for the structured snapshot, "
    "/flight for the flight-recorder tail. 0 binds an EPHEMERAL port — "
    "N serving worker processes on one host cannot race a fixed port — "
    "and the bound port is reported by obs.export.bound_metrics_port(), "
    "ServingRuntime.stats() and every heartbeat line. -1 (default) "
    "disables the server.",
    checker=lambda v: None if v >= -1 else "must be >= -1")

METRICS_REPORT_INTERVAL_S = conf(
    "spark.rapids.tpu.metrics.reportIntervalS", 10.0,
    "Seconds between JSONL heartbeat snapshots of the metrics registry "
    "(obs/export.py Heartbeat) appended to metrics.heartbeatPath — the "
    "always-on metrics-sink cadence.", checker=_positive)

METRICS_HEARTBEAT_PATH = conf(
    "spark.rapids.tpu.metrics.heartbeatPath", "",
    "File the metrics heartbeat appends one JSON line to every "
    "reportIntervalS seconds ({ts, registry, flight_len}). Empty "
    "disables the heartbeat thread.")

METRICS_FLIGHT_EVENTS = conf(
    "spark.rapids.tpu.metrics.flightRecorderEvents", 1024,
    "Capacity of the always-on flight-recorder ring buffer (last N "
    "spans/instants across all queries, embedded in crash dumps).",
    checker=_positive)

RESULT_HEAD_ROWS = conf(
    "spark.rapids.tpu.sql.fetch.headRows", 4096,
    "Result-fetch head size: one speculative round trip ships the row "
    "count plus this many rows; only a larger result pays a second "
    "exactly-sized trip. Size for your link: ~RTT*bandwidth worth of "
    "rows (the tunnel harness measures ~125ms RTT at ~2MB/s).",
    checker=_positive)

RESULT_BOUND_FETCH_FACTOR = conf(
    "spark.rapids.tpu.sql.fetch.boundFactor", 4,
    "A static row bound up to boundFactor*headRows fetches exactly-sized "
    "in one trip; looser bounds fall back to the speculative head so a "
    "4M-row dense-domain bound cannot defeat the protocol.",
    checker=_positive)

SEAM_SPLIT_MIN_ROWS = conf(
    "spark.rapids.tpu.sql.compile.seamSplitMinRows", 2 << 20,
    "Minimum leaf-scan bucket size before a whole-plan program splits at "
    "row-collapse seams (join subtrees under aggregates). Each seam "
    "costs one host count sync (a full link RTT) plus an extra program "
    "dispatch; below this scale the trimmed padding is worth less than "
    "the round trips.", checker=_positive)

DENSE_AGG_DOMAIN_MAX = conf(
    "spark.rapids.tpu.sql.agg.denseDomainMax", 4096,
    "Largest combined key-domain product the no-sort dense group-by "
    "(direct bucket addressing over dictionary/boolean domains) will "
    "use; beyond it the sort-segment group-by runs instead.",
    checker=_positive)

AGG_INPUT_NARROWING = conf(
    "spark.rapids.tpu.sql.agg.inputNarrowing", True,
    "Gather int64 aggregate-input lanes as int32 when exact plan range "
    "statistics prove the values fit (row gathers are latency-bound per "
    "pass; half-width lanes halve the dominant group-by cost). Sums "
    "re-widen exactly.")

JOIN_LAZY_SELECTION = conf(
    "spark.rapids.tpu.sql.join.lazySelection", True,
    "Let a join whose parent consumes liveness as a mask (aggregation "
    "live lanes, a parent join's probe validity) emit a selection "
    "vector instead of compacting its output — skips a full "
    "argsort+gather pass per join output.")

APPROX_PERCENTILE_SKETCH_K = conf(
    "spark.rapids.tpu.sql.agg.approxPercentile.sketchSize", 129,
    "Order statistics kept per group by the mergeable approx_percentile "
    "summary (the t-digest delta analogue): rank error <= 1/(2(K-1)) "
    "per merge level.", checker=_positive)

REGEX_MAX_DFA_STATES = conf(
    "spark.rapids.tpu.sql.regexp.maxStates", 96,
    "DFA state budget for device regular expressions; patterns whose "
    "determinized automaton exceeds it fall back to CPU (the reference "
    "gates by RegexComplexityEstimator memory instead).",
    checker=_positive)

OOC_SORT_WINDOW_ROWS = conf(
    "spark.rapids.tpu.sql.sort.outOfCore.windowRows", 0,
    "Row budget per resident window of the out-of-core sorter; 0 sizes "
    "from the HBM budget (the GpuOutOfCoreSortIterator splitUntilSmaller "
    "role).", checker=_non_negative)

OOC_ENABLED = conf(
    "spark.rapids.tpu.sql.ooc.enabled", True,
    "Out-of-core execution tier: budget-driven graceful degradation for "
    "hash join and aggregation.  When an operator's measured working set "
    "exceeds the resident window (ooc.residentFraction x the HBM "
    "budget), both sides stream through budget-registered spillable "
    "partitions instead of materializing on device; the "
    "TpuSplitAndRetryOOM ladder also escalates into this tier before "
    "the query-level replay rung (docs/ROBUSTNESS.md).")

OOC_RESIDENT_FRACTION = conf(
    "spark.rapids.tpu.sql.ooc.residentFraction", 0.5,
    "Fraction of the HBM budget one out-of-core operator may hold "
    "resident at a time (the Theseus-style byte-budgeted window the "
    "spill-partition count is derived from).",
    checker=lambda v: None if 0.0 < v <= 1.0 else "must be in (0, 1]")

OOC_MAX_PARTITIONS = conf(
    "spark.rapids.tpu.sql.ooc.maxPartitions", 64,
    "Upper bound on spill partitions one out-of-core join/aggregation "
    "pass fans out to (partition count = measured bytes / resident "
    "window, pow2-rounded; skewed buckets re-partition recursively "
    "instead of widening past this).", checker=_positive)

OOC_MAX_DEPTH = conf(
    "spark.rapids.tpu.sql.ooc.maxDepth", 3,
    "Maximum recursive re-partition depth for an out-of-core bucket "
    "that still exceeds the resident window (re-salted hash per level "
    "so key skew cannot map a bucket onto itself); past it the "
    "split-retry ladder owns the remainder.", checker=_positive)

OOC_FORCE = conf(
    "spark.rapids.tpu.sql.ooc.force", False,
    "Force the out-of-core tier for every eligible hash join and "
    "aggregation regardless of measured bytes (test/ops knob; the "
    "bench --ooc leg and the chaos suite pin behavior with it).")

DELTA_OPTIMIZE_TARGET_ROWS = conf(
    "spark.rapids.tpu.delta.optimize.targetFileRows", 1 << 20,
    "Row target per output file for Delta OPTIMIZE / ZORDER compaction "
    "(the reference's optimize.maxFileSize analogue, rows not bytes "
    "because device buckets are row-shaped).", checker=_positive)

COLLECT_DEVICE_ENABLED = conf(
    "spark.rapids.tpu.sql.agg.collect.enabled", True,
    "Run collect_list/collect_set as the device sorted group-by "
    "emitting ragged columns; off forces the CPU aggregation path.")

RUNTIME_FILTER_FPP = conf(
    "spark.rapids.tpu.sql.runtimeFilter.fpp", 0.01,
    "Target false-positive probability sizing the join runtime bloom "
    "filter (the reference's BloomFilter JNI sizing role); lower = "
    "bigger filter, fewer wasted probe rows.", conf_type=float)

SEG_SCATTER_FREE = conf(
    "spark.rapids.tpu.sql.segments.scatterFree.enabled", True,
    "Run segmented reductions over sorted runs (group-by MIN/MAX, "
    "ignore-null FIRST/LAST, ANY/EVERY, f64 sums, count-distinct and "
    "percentile counts, window frames) as blocked segmented scans plus "
    "boundary gathers instead of jax.ops.segment_* scatters — scatters "
    "cost ~70ms per 1M rows on this platform and land in slow S(1) "
    "buffers (ops/segments.py). Off restores the scatter reductions "
    "for A/B comparison.")

MAX_SORT_OPERANDS = conf(
    "spark.rapids.tpu.sql.sort.maxSortOperands", 2,
    "Widest sort (key lanes + payload) any device kernel may emit; "
    "wider orderings chain stable sorts through a running permutation "
    "(ops/segments.py lexsort_capped). TPU sort COMPILE time scales "
    "brutally with operand count (2-op 31s, 3xi64 164s, 10-op ~10min "
    "at 1M), so 2 is the platform sweet spot; raise it only on "
    "backends whose sort compile is cheap.",
    checker=lambda v: None if v >= 2 else "must be >= 2")

DENSE_AGG_VIA_SORT = conf(
    "spark.rapids.tpu.sql.agg.denseDomainViaSort", False,
    "Route bounded-domain group-bys (dictionary/boolean keys) through "
    "the packed single-sort-lane kernel instead of the no-sort dense "
    "bucket scatters — trades one cheap 2-operand sort (~5ms/1M) for "
    "the direct segment scatters (~70ms/1M). Off keeps the dense "
    "no-sort path; each is flip-testable against the other.")

JOIN_DENSE_BUILD_VIA_SORT = conf(
    "spark.rapids.tpu.sql.join.denseBuildViaSort", True,
    "Build dense join direct-address tables (per-key offsets, "
    "unique-key slots) from a sorted key lane + merge-rank instead of "
    "scatters: scatter-built tables land in S(1)-space buffers whose "
    "probe-side gathers run ~200MB/s, while sort outputs stay in fast "
    "memory. Off restores the scatter builders.")

JOIN_MATCHED_VIA_PRESENCE = conf(
    "spark.rapids.tpu.sql.join.matchedViaPresence", True,
    "Answer semi/anti-join matched flags over a dense key domain from a "
    "PRESENCE bitmap (one bool scatter over build rows + a 1-byte "
    "gather per probe row) instead of the sorted per-key offs table — "
    "the flag needs key existence only, so the build-sized sort + "
    "merge-rank behind the table never pays for itself (q21/q22-class "
    "anti joins against a 2M-row build drop ~10x on the cpu backend). "
    "Off restores the sorted offs path (the all-scatter-free "
    "configuration, with agg.denseDomainViaSort).")

JOIN_MATCHED_VIA_MERGE = conf(
    "spark.rapids.tpu.sql.join.matchedViaMerge", True,
    "Derive per-build/per-probe matched flags for outer and expanded "
    "joins from a sorted index lane + merge-rank difference instead of "
    "segment_max scatters (ops/segments.py matched_flags). Off "
    "restores the scatter reductions.")

COMPILE_CONST_LIFT = conf(
    "spark.rapids.tpu.sql.compile.constantLifting", True,
    "Lift plan literals (filter constants, projection scalars) out of "
    "traced device programs into runtime arguments, and key compiled "
    "programs on expression STRUCTURE instead of literal values — two "
    "queries differing only in literals (the dashboard / parameterized "
    "traffic shape) share one XLA executable instead of each paying a "
    "cold compile. Applies to both the per-operator jit cache and the "
    "whole-plan program cache (exec/compiled.py). Literals in positions "
    "whose kernels specialize on the host value (string patterns, IN "
    "lists, array lambdas) stay baked into the program and keyed by "
    "value.", commonly_used=True)

COMPILE_CACHE_DIR = conf(
    "spark.rapids.tpu.compile.cacheDir", "",
    "Directory for the engine-level PERSISTENT compile cache: XLA "
    "executables are AOT-serialized here (jax compilation cache) so a "
    "fresh process replays warmed queries with zero XLA compiles. The "
    "engine scopes entries under a topology-hashed subdirectory "
    "(backend, device count/kinds, process count, XLA_FLAGS) because "
    "XLA's own cache key does NOT hash the device topology — sharing "
    "one directory across topologies can crash the executable "
    "deserializer. Empty disables the engine-managed cache (jax's own "
    "jax_compilation_cache_dir, if set, still applies).",
    commonly_used=True)

COMPILE_BG_ENABLED = conf(
    "spark.rapids.tpu.compile.background.enabled", True,
    "Compile downstream whole-plan SEGMENTS ahead of time on the "
    "background compile service (runtime/compile_service.py) while "
    "earlier segments execute: when a split plan compiles segment i, "
    "candidate programs for segment i+1 are speculatively AOT-compiled "
    "(lower().compile() over placeholder shapes) for the predicted "
    "seam output buckets, so the seam sync usually finds the next "
    "program ready. Mispredicted candidates are dropped; injected "
    "`compile` faults from background tasks surface on the consuming "
    "thread with the same recovery ladder as inline compiles.")

COMPILE_BG_THREADS = conf(
    "spark.rapids.tpu.compile.background.threads", 2,
    "Thread-pool size of the background compile service (XLA compiles "
    "release the GIL, so threads overlap real compile work — also the "
    "concurrency of bench.py --compile-only cache warmup).",
    checker=_positive)

COMPILE_BG_SPECULATE = conf(
    "spark.rapids.tpu.compile.background.speculateBuckets", 2,
    "Maximum candidate output buckets speculatively compiled per plan "
    "seam (the aggregate/join row-collapse points). Each candidate "
    "costs one background compile; a hit hides the next segment's "
    "compile behind the current segment's execution.",
    checker=_positive, internal=True)

PLAN_CACHE_ENTRIES = conf(
    "spark.rapids.tpu.compile.planCacheEntries", 256,
    "Bound on the process-wide whole-plan executable cache (canonical "
    "structure key -> compiled XLA program). LRU beyond it.",
    checker=_positive, internal=True)

SHAPE_BUCKETS = conf(
    "spark.rapids.tpu.sql.shape.buckets", "",
    "Explicit static-shape row-bucket set as ascending comma-separated "
    "capacities (e.g. `4096,65536,1048576,4194304`): device batches pad "
    "to the smallest listed bucket >= their row count (doubling past "
    "the largest), REPLACING the geometric minBucketRows/bucketGrowth "
    "ladder. A small coarse set quantizes many input sizes onto few "
    "compiled programs — the cross-scale-factor compile-cache hit — at "
    "the price of more padding. Empty keeps the geometric ladder.",
    checker=lambda v: _check_bucket_set(v))

SCAN_UPLOAD_CACHE_BYTES = conf(
    "spark.rapids.tpu.sql.scan.uploadCacheBytes", 4 << 30,
    "Byte cap on the shared scan-upload cache (one device copy per hot "
    "source table, exec/compiled.py): past it, least-recently-used "
    "table uploads evict (tpu_scan_upload_evictions_total counts them) "
    "so long multi-table sessions cannot grow device-pinned uploads "
    "without bound. 0 disables the cache entirely.",
    checker=_non_negative)


def _check_bucket_set(v):
    s = str(v).strip()
    if not s:
        return None
    try:
        caps = [int(x) for x in s.split(",")]
    except ValueError:
        return f"must be comma-separated integers, got {v!r}"
    if any(c <= 0 for c in caps):
        return "bucket capacities must be positive"
    if caps != sorted(caps) or len(set(caps)) != len(caps):
        return "bucket capacities must be strictly ascending"
    return None


def parse_bucket_set(raw: str):
    """Parsed ascending bucket list of a shape.buckets value ([] when
    unset) — shared by the conf checker and columnar.device."""
    s = str(raw or "").strip()
    return [int(x) for x in s.split(",")] if s else []


# --------------------------------------------------------------------------
# Concurrent serving plane (serving/runtime.py + serving/cache.py)
# --------------------------------------------------------------------------

SERVING_WORKERS = conf(
    "spark.rapids.tpu.serving.workers", 8,
    "Pipeline worker threads of the ServingRuntime: each admitted query "
    "runs its plan / result-cache probe / compile / device-execute "
    "phases on one worker, so up to this many queries are in SOME phase "
    "concurrently (XLA compiles release the GIL — one query's compile "
    "overlaps another's device execution).", checker=_positive)

SERVING_QUEUE_DEPTH = conf(
    "spark.rapids.tpu.serving.queueDepth", 64,
    "Bound on admitted-but-unfinished queries across all tenants. At "
    "the bound, submit() blocks (backpressure) up to "
    "serving.admitTimeoutMs and then raises AdmissionTimeout — load "
    "sheds at admission with a clean signal instead of a device OOM "
    "mid-query.", checker=_positive, commonly_used=True)

SERVING_ADMIT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serving.admitTimeoutMs", 10000,
    "Longest one submit() blocks for an admission slot when the queue "
    "is at queueDepth before AdmissionTimeout is raised (the "
    "backpressure signal; TenantSession.collect retries it once).",
    checker=_positive)

SERVING_DEVICE_SLOTS = conf(
    "spark.rapids.tpu.serving.deviceSlots", 0,
    "Concurrent device-execute grants the fair-share scheduler hands "
    "out. 0 (default) = auto: sql.concurrentTpuTasks (the GpuSemaphore "
    "sizing — one query's host tail overlaps another's device compute) "
    "on accelerator backends, but 1 on the CPU backend, where 'device "
    "compute' shares the host cores and concurrent XLA programs thrash "
    "each other's intra-op thread pools. Each grant still holds a "
    "semaphore permit inside the query, so the HBM story is unchanged.",
    checker=_non_negative)

SERVING_STARVATION_BOUND = conf(
    "spark.rapids.tpu.serving.starvationBound", 4,
    "Starvation bound of the weighted-deficit scheduler: a tenant with "
    "a runnable query is never passed over more than this many "
    "consecutive device grants — after that it is scheduled regardless "
    "of its deficit (the fairness invariant tests/test_serving.py's "
    "hammer asserts).", checker=_positive)

SERVING_RESULT_CACHE_BYTES = conf(
    "spark.rapids.tpu.serving.resultCache.bytes", 256 << 20,
    "Byte cap of the serving plan+result cache (LRU past it): repeated "
    "dashboard-style queries — same canonical plan STRUCTURE, same "
    "lifted literal values, same live source tables — return the cached "
    "result without touching the device. Entries are checksummed Arrow "
    "IPC payloads, invalidated the moment a source-table anchor is "
    "garbage collected. 0 disables the cache.",
    checker=_non_negative, commonly_used=True)

SERVING_DEADLINE_MS = conf(
    "spark.rapids.tpu.serving.deadlineMs", 0.0,
    "Per-query wall-clock deadline for serving queries, in milliseconds "
    "(0 disables). The clock starts when execution begins (queue wait "
    "is bounded separately by admitTimeoutMs); execution checks it "
    "at cooperative cancellation checkpoints — the compiled-plan seam "
    "brackets, the per-batch result stream, out-of-core partition/merge "
    "passes, exchange rounds and spill-all sweeps — and past the "
    "deadline raises QueryDeadlineExceeded, releasing the ticket's full "
    "device reservation (DeviceCensus shows zero residual). Per-submit "
    "override: TenantSession.submit(df, deadline_ms=...).",
    checker=_non_negative, commonly_used=True)

SERVING_POOL_PROCS = conf(
    "spark.rapids.tpu.serving.pool.processes", 0,
    "Fault-isolated multi-process serving (serving/workers.py): when "
    "> 0, the ServingRuntime supervises this many WORKER PROCESSES, "
    "each owning its own TpuSession / MemoryBudget / device slice, and "
    "dispatches admitted queries to them over an authenticated local "
    "socket. A fatal XLA error, SIGKILL or segfault in one worker loses "
    "only its in-flight queries — they redrive on a surviving worker "
    "(serving.redrive.maxAttempts) while other tenants' queries "
    "complete uninterrupted. Workers share the persistent compile "
    "cache and history store; their budgets reconcile through "
    "heartbeat-reported DeviceCensus totals so admission gates on the "
    "truthful cross-process HBM picture. 0 (default) keeps the "
    "single-process thread pipeline.",
    checker=_non_negative, commonly_used=True)

SERVING_REDRIVE_MAX = conf(
    "spark.rapids.tpu.serving.redrive.maxAttempts", 2,
    "How many times one serving query may REDRIVE onto a surviving "
    "worker after losing its worker process mid-flight (crash, "
    "SIGKILL, heartbeat-timeout hang, fatal device dump). Queries are "
    "read-only and deterministic, so a redriven result is bit-identical "
    "to an undisturbed run; past the bound the ticket fails with the "
    "worker-loss error (the Spark task-retry bound analogue).",
    checker=_non_negative)

SERVING_POOL_HEARTBEAT_MS = conf(
    "spark.rapids.tpu.serving.pool.heartbeatMs", 250,
    "Interval at which each serving worker process heartbeats the "
    "supervisor (pid, in-flight query, DeviceCensus live/peak bytes, "
    "bound metrics port).", checker=_positive)

SERVING_POOL_HEARTBEAT_MISSES = conf(
    "spark.rapids.tpu.serving.pool.heartbeatMisses", 12,
    "A worker whose last heartbeat is older than this many heartbeat "
    "intervals is declared HUNG: the supervisor SIGKILLs it, redrives "
    "its in-flight queries on surviving workers and (pool.restart) "
    "spawns a replacement.", checker=_positive)

SERVING_POOL_RESTART = conf(
    "spark.rapids.tpu.serving.pool.restart", True,
    "Supervised restart: replace a dead serving worker process (crash, "
    "kill, hang, fatal self-termination) with a fresh one so the pool "
    "holds its size. False leaves the pool smaller after each death "
    "(drain/teardown mode).")

SERVING_POOL_TELEMETRY_ENABLED = conf(
    "spark.rapids.tpu.serving.pool.telemetry.enabled", True,
    "Fleet observability federation: worker heartbeat frames piggyback "
    "a cumulative metrics-registry snapshot and a rolling flight-"
    "recorder tail, which the supervisor folds into the fleet-view "
    "registry (per-worker-labeled tpu_fleet_* families on the single "
    "Prometheus endpoint / stats()['fleet']) and into WorkerLost "
    "black-box forensics dumps. False keeps heartbeats bare "
    "(pid + census only, the PR 17 wire shape).")

SERVING_POOL_TELEMETRY_FLIGHT_EVENTS = conf(
    "spark.rapids.tpu.serving.pool.telemetry.flightEvents", 64,
    "How many of the newest in-worker flight-recorder events ride each "
    "heartbeat frame as the worker's black-box snapshot. The supervisor "
    "keeps only the latest snapshot per worker and embeds it into the "
    "WorkerLost dump when that worker dies by kill/hang — the cases "
    "where no in-worker dump is possible.", checker=_positive)

SERVING_POOL_TELEMETRY_MAX_FRAME_BYTES = conf(
    "spark.rapids.tpu.serving.pool.telemetry.maxFrameBytes", 262144,
    "Byte bound on one heartbeat frame's telemetry payload. Liveness "
    "beats observability: when a frame would exceed this, the flight "
    "snapshot is trimmed oldest-first, then dropped, then the registry "
    "snapshot is dropped — the bare heartbeat always goes out.",
    checker=_positive)

SERVING_ADMIT_WORKING_SET_FACTOR = conf(
    "spark.rapids.tpu.serving.admitWorkingSetFactor", 3.0,
    "HBM admission estimate: a query's device working set is assumed "
    "to be this factor times its source-table bytes, and the scheduler "
    "only overlaps device phases whose summed estimates fit the HBM "
    "budget (memory.tpu.budgetBytes / allocFraction) — queueing instead "
    "of betting on the OOM retry ladder. A query too big to ever fit "
    "still runs, alone.", checker=_positive, internal=True)


# --------------------------------------------------------------------------
# Hand-written Pallas kernel tier (ops/pallas/ — the libcudf-equivalent
# layer; the sort-based kernels stay the portable fallback)
# --------------------------------------------------------------------------

PALLAS_ENABLED = conf(
    "spark.rapids.tpu.sql.kernels.pallas.enabled", False,
    "Master switch for the hand-written Pallas kernel tier (ops/pallas/): "
    "hash-probe joins (murmur3 open addressing instead of sorted-build + "
    "merge-rank probes), bounded-domain segmented aggregation "
    "(block-local accumulate + single-pass combine instead of sort or "
    "scatter group-bys), and selection compaction (prefix-sum + rank "
    "search instead of the keep-mask argsort). Off keeps every query on "
    "the sort-based portable tier, bit-identical to main; on, each "
    "kernel family still negotiates per-operator legality (single exact "
    "key lane, domain bounds, backend support) and falls back to the "
    "sort tier where it loses — dispatch/fallback decisions are counted "
    "in the tpu_kernel_* metric families.", commonly_used=True)

PALLAS_JOIN = conf(
    "spark.rapids.tpu.sql.kernels.pallas.join", "AUTO",
    "Hash-probe join kernel family: open-addressing murmur3 table "
    "(hash-ordered layout, duplicates consecutive) built once per build "
    "side, probed by a Pallas kernel gridded over probe blocks — "
    "replaces the sorted-build + merge-rank probe (two 2-operand sorts "
    "of build+probe rows per probe op). AUTO enables it on every "
    "backend (the interpreted kernel beats the sort path on the CPU "
    "test mesh too); ON/OFF force. Requires kernels.pallas.enabled.",
    checker=_enum_checker("AUTO", "ON", "OFF"))

PALLAS_SEGAGG = conf(
    "spark.rapids.tpu.sql.kernels.pallas.segagg", "AUTO",
    "Segmented-aggregation kernel family: group-bys whose packed key "
    "domain fits kernels.pallas.segagg.maxDomain accumulate block-local "
    "per-bucket partials (one-hot MXU matmuls for the sum/count family "
    "— int64 sums ride exact split-f64 dot products — masked VPU "
    "reductions for MIN/MAX/FIRST/LAST/ANY/EVERY) and combine once, "
    "operating directly on dictionary codes / FOR-narrowed lanes with "
    "no sort and no scatter. AUTO enables it only where Pallas "
    "compiles natively (the TPU backend; XLA-CPU scatters are fast and "
    "the interpreted kernel loses there); ON forces it everywhere "
    "(tier-1 exercises the kernel bodies this way), OFF disables.",
    checker=_enum_checker("AUTO", "ON", "OFF"))

PALLAS_COMPACT = conf(
    "spark.rapids.tpu.sql.kernels.pallas.compact", "AUTO",
    "Selection-compaction kernel family: filter/compaction order from a "
    "blocked prefix sum + per-output-slot rank search (log2(capacity) "
    "vectorized gathers) instead of the stable keep-mask argsort. AUTO "
    "enables it on every backend; ON/OFF force. Requires "
    "kernels.pallas.enabled.",
    checker=_enum_checker("AUTO", "ON", "OFF"))

PALLAS_INTERPRET = conf(
    "spark.rapids.tpu.sql.kernels.pallas.interpret", "AUTO",
    "Run Pallas kernels through the interpreter (pl.pallas_call "
    "interpret=True): the kernel bodies execute as discharged XLA ops "
    "inside the same traced program, so non-TPU backends run the REAL "
    "kernel logic — tier-1 and the CPU container exercise the actual "
    "probe/accumulate/compact bodies, not a shadow implementation. "
    "AUTO interprets on every backend without native Pallas lowering "
    "(everything but TPU); ON forces interpretation even on TPU "
    "(debugging); OFF never interprets (the tier disables itself "
    "off-TPU).", checker=_enum_checker("AUTO", "ON", "OFF"))

PALLAS_SEGAGG_MAX_DOMAIN = conf(
    "spark.rapids.tpu.sql.kernels.pallas.segagg.maxDomain", 1024,
    "Largest packed key-domain product the block-accumulate segmented "
    "aggregation kernel will hold as a live accumulator (VMEM bound: "
    "domain x aggregate lanes x 8B per block); larger domains keep the "
    "sort/scatter group-by paths.", checker=_positive)

PALLAS_JOIN_DENSE_REPLACE = conf(
    "spark.rapids.tpu.sql.kernels.pallas.join.denseReplace", "AUTO",
    "When the hash-probe kernel is elected and the join ALSO qualifies "
    "for a dense direct-address table: AUTO replaces the dense table "
    "only when the key span exceeds 4x the build capacity — the regime "
    "where the dense build's span-sized offs sorts dominate; below it "
    "the dense table's one-gather probes beat the hash walk (measured: "
    "q4/q19/q22-class probe-bound joins regress ~1.3-1.5x under full "
    "replacement on the cpu backend, while q3/q9-class span-heavy "
    "builds win ~1.5-3x).  ON always replaces (scatter-free builds on "
    "backends where dense tables land in slow S(1) buffers; the sort-"
    "budget lint runs this way), OFF never does (the kernel only takes "
    "the no-domain sorted-probe shape).",
    checker=_enum_checker("AUTO", "ON", "OFF"))

PALLAS_JOIN_MAX_BUILD = conf(
    "spark.rapids.tpu.sql.kernels.pallas.join.maxBuildRows", 1 << 23,
    "Largest build-side row capacity the hash-probe join kernel will "
    "table (the open-addressing table holds ~3 slots per build row at "
    "load factor 0.5 plus the overflow tail); larger builds keep the "
    "sorted-lane fallback.", checker=_positive)


# --------------------------------------------------------------------------
# Compressed device-resident execution (ops/encodings.py): operators run
# directly on dictionary codes and FOR-narrowed integer lanes instead of
# decoding to full-width materialized columns first
# --------------------------------------------------------------------------

ENCODED_EXECUTION = conf(
    "spark.rapids.tpu.sql.encoded.execution.enabled", True,
    "Master switch for compressed device-resident execution "
    "(ops/encodings.py): equality/IN/range predicates on dictionary "
    "columns rewrite to CODE-SPACE predicates (the literal translates "
    "through the dictionary once at prepare time — no per-row remap "
    "gather), scan dictionaries upload ORDER-PRESERVING (sorted) so "
    "range predicates and ORDER BY compare codes directly, integer scan "
    "lanes FOR-narrow to the smallest value-preserving dtype (decode is "
    "a fused widen sunk to the consumer that truly needs width), and "
    "joins/group-bys keep hashing/accumulating codes. Off disables "
    "every encoded path — plans and results are bit-identical to the "
    "pre-encoding engine. Dispatch/fallback decisions are counted in "
    "tpu_encoded_dispatch_total / tpu_decode_bytes_total.",
    commonly_used=True)

ENCODED_DICT_PREDICATES = conf(
    "spark.rapids.tpu.sql.encoded.dict.predicates", "AUTO",
    "Code-space predicate rewrites on dictionary columns (needs "
    "encoded.execution.enabled): a literal comparison translates the "
    "literal through the column's dictionary at prepare time and "
    "compares codes (equality/IN: always; </<= ranges: against a rank "
    "bound when the dictionary is order-preserving, else through a "
    "per-dictionary rank table — the decode fallback, still on "
    "device). AUTO/ON behave the same today; OFF keeps the legacy "
    "unified-remap gathers.", checker=_enum_checker("AUTO", "ON", "OFF"))

ENCODED_DICT_SORT_SCAN = conf(
    "spark.rapids.tpu.sql.encoded.dict.sortOnScan", True,
    "Upload string dictionaries in SORTED (order-preserving) order at "
    "the host->device boundary (needs encoded.execution.enabled): codes "
    "then ARE ranks, so ORDER BY on dictionary columns skips its "
    "per-row rank-table gather and range predicates compare codes "
    "against one scalar bound. Pure representation change — decoded "
    "values are identical.")

ENCODED_NARROW_LANES = conf(
    "spark.rapids.tpu.sql.encoded.narrow.lanes", "AUTO",
    "FOR-narrow integer/date scan lanes to the smallest VALUE-PRESERVING "
    "signed dtype their live range fits (needs "
    "encoded.execution.enabled; the _negotiate_encoded legality pass "
    "approves columns per consumer chain): uploads ship fewer bytes, "
    "comparisons/arithmetic evaluate in the narrow dtype with "
    "overflow-checked promotion only when the exact result needs width, "
    "and sinks that need full width widen inside the fused program. "
    "AUTO/ON enable, OFF keeps full-width lanes.",
    checker=_enum_checker("AUTO", "ON", "OFF"))

ENCODED_IN_MAX_CODES = conf(
    "spark.rapids.tpu.sql.encoded.dict.inMaxCodes", 16,
    "Largest IN-list size rewritten to per-code equality comparisons "
    "(zero gathers); larger lists keep the per-dictionary membership "
    "mask gather.", checker=_positive)


# --------------------------------------------------------------------------
# Persistent performance-history plane (obs/history.py + obs/estimator.py)
# --------------------------------------------------------------------------

HISTORY_DIR = conf(
    "spark.rapids.tpu.history.dir", "",
    "Directory for the persistent performance-history store "
    "(obs/history.py): every completed query appends one JSONL record — "
    "measured device wall, per-segment device ms, compile ms, source "
    "bytes, peak HBM reservation — keyed by the canonical plan "
    "STRUCTURE (PR 7 constant-lifted structure key + resolved kernel "
    "tier + leaf shape bucket), so a fresh process serves calibrated "
    "cost estimates (obs/estimator.py, serving admission prediction) "
    "with zero re-measurement. Corrupt/truncated lines are tolerated "
    "on load; the file is byte/entry-capped with LRU compaction "
    "(history.maxBytes / history.maxEntries). Empty disables the plane "
    "(the disabled path is one cached conf check per query).",
    commonly_used=True)

HISTORY_MAX_BYTES = conf(
    "spark.rapids.tpu.history.maxBytes", 16 << 20,
    "Byte cap on the on-disk performance-history file: past it the "
    "store compacts — per-structure decay-weighted aggregates replace "
    "raw records and least-recently-updated structures drop first "
    "(the LRU half of the cap).", checker=_positive)

HISTORY_MAX_ENTRIES = conf(
    "spark.rapids.tpu.history.maxEntries", 4096,
    "Bound on distinct plan structures the history store tracks; "
    "beyond it, compaction drops least-recently-updated structures.",
    checker=_positive)

HISTORY_DECAY = conf(
    "spark.rapids.tpu.history.decay", 0.3,
    "Weight of the NEWEST observation in the store's exponentially "
    "decayed aggregates (device us, compile ms, working set): higher "
    "adapts faster to drift, lower smooths noise. In (0, 1].",
    checker=lambda v: None if 0 < v <= 1 else "must be in (0, 1]",
    internal=True)


JOIN_LATE_MATERIALIZATION = conf(
    "spark.rapids.tpu.sql.join.lateMaterialization.enabled", True,
    "Let equi-joins emit THIN batches: payload columns ride as per-side "
    "row-id selection lanes (the gather indices the join computed "
    "anyway) and materialize only at a pipeline sink (aggregate build, "
    "sort, exchange, collect) via one composed gather per source batch "
    "— row gathers are the dominant device cost on TPU, and a join "
    "chain otherwise re-gathers every payload column per join. Columns "
    "a mid-pipeline condition or projection needs are materialized "
    "early, and only those (plan/overrides.py legality pass).")


class TpuConf:
    """An immutable-ish view over a dict of raw settings with typed access.

    Like the reference, a fresh TpuConf is constructed from the session conf
    at plan time so per-query overrides take effect (GpuOverrides.scala:4571).
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._raw = dict(settings or {})
        self._cache: Dict[str, Any] = {}
        for k in self._raw:
            if (k.startswith("spark.rapids.tpu.") and k not in _REGISTRY
                    and not self._is_dynamic_key(k)):
                raise ValueError(f"unknown config key: {k}")

    _DYNAMIC_RE = re.compile(
        r"^spark\.rapids\.tpu\.sql\.(expression|exec|partitioning|command)\.\w+$")

    @classmethod
    def _is_dynamic_key(cls, key: str) -> bool:
        return cls._DYNAMIC_RE.match(key) is not None

    def get(self, entry: ConfEntry):
        if entry.key not in self._cache:
            raw = self._raw.get(entry.key, entry.default)
            self._cache[entry.key] = entry.convert(raw) if raw is not None else None
        return self._cache[entry.key]

    def get_raw(self, key: str, default=None):
        return self._raw.get(key, default)

    def is_op_enabled(self, kind: str, name: str) -> bool:
        """Per-operator auto-generated enable keys, default on."""
        raw = self._raw.get(f"spark.rapids.tpu.sql.{kind}.{name}")
        if raw is None:
            return True
        return _parse_bool(raw)

    def with_overrides(self, **kv) -> "TpuConf":
        merged = dict(self._raw)
        merged.update({k.replace("__", "."): v for k, v in kv.items()})
        return TpuConf(merged)

    # Convenience typed accessors used widely by the engine.
    @property
    def sql_enabled(self):
        return self.get(SQL_ENABLED)

    @property
    def explain(self):
        return str(self.get(EXPLAIN)).upper()

    @property
    def explain_only(self):
        return str(self.get(MODE)).upper() == "EXPLAINONLY"

    @property
    def batch_size_rows(self):
        return self.get(BATCH_SIZE_ROWS)

    @property
    def ansi(self):
        # explicit session setting wins; otherwise the pinned Spark
        # version's default (false through 3.x, true in 4.0 — shims.py)
        if ANSI_ENABLED.key in self._raw:
            return self.get(ANSI_ENABLED)
        return self.shims.ansi_default

    @property
    def shims(self):
        """Version shims for `spark.rapids.tpu.spark.version`
        (ShimLoader role, shims.py)."""
        from .shims import get_shims
        return get_shims(str(self.get(SPARK_VERSION)))

    @property
    def bucket_set(self):
        """Explicit shape.buckets capacities ([] = geometric ladder),
        parsed once per conf."""
        if "__bucket_set" not in self._cache:
            self._cache["__bucket_set"] = parse_bucket_set(
                self.get(SHAPE_BUCKETS))
        return self._cache["__bucket_set"]

    @property
    def bucket_min_rows(self):
        return self.get(BUCKET_MIN_ROWS)

    @property
    def bucket_growth(self):
        return self.get(BUCKET_GROWTH)


DEFAULT_CONF = TpuConf()


def generate_docs() -> str:
    """Markdown config reference (reference RapidsConf.help / docs/configs.md)."""
    lines = ["# spark-rapids-tpu configuration", "",
             "| key | default | meaning |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        doc = e.doc.replace("|", "\\|").replace("\n", " ")
        lines.append(f"| `{e.key}` | `{e.default}` | {doc} |")
    lines += [
        "", "## Benchmark harness (bench.py)", "",
        "`python bench.py [scale] [--queries q1,q6,...] "
        "[--suite tpch|tpcds]`", "",
        "| flag / env | default | meaning |", "|---|---|---|",
        "| `--suite` | `tpch` | Workload: the 22-query TPC-H suite or "
        "the TPC-DS tranche (spark_rapids_tpu/tpcds.py). The tpcds "
        "report adds the operator-coverage matrix: per-query fallback "
        "reasons plus the sort_operand_max / scatter_op_count jaxpr "
        "lints, and a summary splitting queries into device-clean / "
        "with-fallbacks / not-whole-plan-traceable. |",
        "| `--queries` | all registered | Comma-separated subset of the "
        "suite's QUERIES registry. |",
        "| `--serving` | off | Concurrent serving sweep: closed-loop "
        "clients (one tenant each) over the query mix at concurrency "
        "1/2/4/8 through the ServingRuntime, vs the same multiset "
        "serially through the single-query path; reports p50/p99 "
        "latency, QPS, device utilization and result-cache outcomes "
        "(docs/SERVING.md; gated via check_regression sv: entries). "
        "Adds mp2/mp4 multi-process pool levels "
        "(serving.pool.processes) plus an mp2_kill chaos leg that "
        "SIGKILLs one worker mid-query and must stay oracle-matching "
        "via redrive (docs/ROBUSTNESS.md). |",
        "| `scale` | `1.0` | Linear datagen scale factor (SF1-ish row "
        "counts at 1.0; fixed-size dimensions never scale). |",
        "| `BENCH_BUDGET_S` | `1800` | Total wall budget; queries that "
        "do not fit are listed in `skipped`, and the last stdout line "
        "is always a complete parseable JSON result. |",
        "",
    ]
    return "\n".join(lines)


def all_entries() -> List[ConfEntry]:
    return list(_REGISTRY.values())


if __name__ == "__main__":
    import pathlib
    # regenerate through the CANONICAL module: running `-m ...config`
    # executes this file as __main__ with its own empty _REGISTRY, while
    # imported modules (runtime/failure.py) register their entries into
    # the sys.modules copy — generating from __main__'s registry would
    # silently drop them (scripts/check_docs.py guards this)
    from spark_rapids_tpu import config as _cfg
    from spark_rapids_tpu.runtime import failure as _failure  # noqa: F401
    out = pathlib.Path(__file__).resolve().parent.parent / "docs"
    out.mkdir(exist_ok=True)
    (out / "configs.md").write_text(_cfg.generate_docs())
    print(f"wrote {out / 'configs.md'}")
