"""TPC-H data generation + query builders over the DataFrame API.

Role of the reference's integration_tests TPC-H/TPC-DS suites + datagen
(SURVEY §2.13): deterministic scaled tables with the spec's column types
(money = decimal(12,2), dates = date32) and the query shapes used by the
test suite (tests/test_tpch.py asserts device results against a pyarrow/
python oracle) and bench.py.

Row counts scale linearly with `scale` (scale=1.0 -> SF1-ish counts); the
value distributions follow the TPC-H spec shapes (uniform ranges, date
windows) without the full dbgen text grammar.
"""
from __future__ import annotations

import datetime as pydt
from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from .plan import datetime as DT
from .plan import expressions as E
from .plan.aggregates import Average, Count, Sum
from .session import DataFrame, TpuSession, col, lit


def money_from_cents(cents: np.ndarray, precision=12, scale=2) -> pa.Array:
    """Exact decimal(p,s) from integer unscaled values (no float trip).

    Vectorized: the unscaled int64 cents ARE the decimal128 low lane;
    build the array straight from buffers (a Python-Decimal loop takes
    minutes at SF1's 6M rows)."""
    unscaled = cents.astype(np.int64)
    lanes = np.empty((len(unscaled), 2), dtype=np.uint64)
    lanes[:, 0] = unscaled.view(np.uint64)
    lanes[:, 1] = np.where(unscaled < 0,
                           np.uint64(0xFFFFFFFFFFFFFFFF), np.uint64(0))
    return pa.Array.from_buffers(
        pa.decimal128(precision, scale), len(unscaled),
        [None, pa.py_buffer(lanes.tobytes())])


_DATE0 = pydt.date(1970, 1, 1)


def _days(d: pydt.date) -> int:
    return (d - _DATE0).days


def gen_tables(scale: float = 0.01, seed: int = 20240706
               ) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_li = max(int(6_001_215 * scale), 100)
    n_ord = max(int(1_500_000 * scale), 40)
    n_cust = max(int(150_000 * scale), 20)
    n_supp = max(int(10_000 * scale), 5)
    n_part = max(int(200_000 * scale), 20)

    nations = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT",
               "ETHIOPIA", "FRANCE", "GERMANY", "INDIA", "INDONESIA",
               "IRAN", "IRAQ", "JAPAN", "JORDAN", "KENYA", "MOROCCO",
               "MOZAMBIQUE", "PERU", "CHINA", "ROMANIA", "SAUDI ARABIA",
               "VIETNAM", "RUSSIA", "UNITED KINGDOM", "UNITED STATES"]
    region_of = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1, 2,
                 3, 4, 2, 3, 3, 1]
    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

    region = pa.table({
        "r_regionkey": pa.array(range(5), pa.int64()),
        "r_name": pa.array(regions),
    })
    nation = pa.table({
        "n_nationkey": pa.array(range(25), pa.int64()),
        "n_name": pa.array(nations),
        "n_regionkey": pa.array(region_of, pa.int64()),
    })
    c_nation = rng.integers(0, 25, n_cust)
    customer = pa.table({
        "c_custkey": pa.array(range(n_cust), pa.int64()),
        "c_name": pa.array([f"Customer#{i:09d}" for i in range(n_cust)]),
        "c_nationkey": pa.array(c_nation, pa.int64()),
        # spec: phone country code = nationkey + 10 (TPC-H 4.2.2.9)
        "c_phone": pa.array([
            f"{k + 10}-{a}-{b}-{c}" for k, a, b, c in zip(
                c_nation, rng.integers(100, 1000, n_cust),
                rng.integers(100, 1000, n_cust),
                rng.integers(1000, 10000, n_cust))]),
        "c_mktsegment": pa.array(rng.choice(
            ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
             "HOUSEHOLD"], n_cust)),
        "c_acctbal": money_from_cents(
            rng.integers(-99999, 999999, n_cust), 12, 2),
    })
    supplier = pa.table({
        "s_suppkey": pa.array(range(n_supp), pa.int64()),
        "s_name": pa.array([f"Supplier#{i:09d}" for i in range(n_supp)]),
        "s_address": pa.array([f"addr {i} lane" for i in range(n_supp)]),
        "s_phone": pa.array([f"{11 + i % 25}-{i % 900 + 100}-55"
                             for i in range(n_supp)]),
        "s_nationkey": pa.array(rng.integers(0, 25, n_supp), pa.int64()),
        "s_acctbal": money_from_cents(
            rng.integers(-99999, 999999, n_supp), 12, 2),
        "s_comment": pa.array(rng.choice(
            ["reliable and fast", "slow Customer Complaints recorded",
             "usually on time", "pending Customer Complaints review",
             "excellent record"], n_supp)),
    })
    colors = ["green", "blue", "red", "ivory", "khaki"]
    part = pa.table({
        "p_partkey": pa.array(range(n_part), pa.int64()),
        "p_mfgr": pa.array([f"Manufacturer#{m}" for m in
                            rng.integers(1, 6, n_part)]),
        "p_name": pa.array([f"{c} polished item{i}" for i, c in
                            enumerate(rng.choice(colors, n_part))]),
        "p_type": pa.array(rng.choice(
            ["ECONOMY ANODIZED STEEL", "LARGE BRUSHED BRASS",
             "STANDARD POLISHED TIN", "SMALL PLATED COPPER",
             "PROMO BURNISHED NICKEL"], n_part)),
        "p_brand": pa.array([f"Brand#{b}" for b in
                             rng.integers(11, 56, n_part)]),
        "p_container": pa.array(rng.choice(
            ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE",
             "LG BOX", "JUMBO PKG"], n_part)),
        "p_size": pa.array(rng.integers(1, 51, n_part), pa.int32()),
    })

    n_ps = n_part * 2
    # spec: (ps_partkey, ps_suppkey) is the table's primary key — each
    # part's supplier copies use distinct stride offsets (TPC-H 4.2.3's
    # supplier-of-part formula shape)
    ps_pk = np.concatenate([np.arange(n_part), np.arange(n_part)])
    ps_sk = np.concatenate(
        [np.arange(n_part) % n_supp,
         (np.arange(n_part) + max(1, n_supp // 4 + 1)) % n_supp])
    partsupp = pa.table({
        "ps_partkey": pa.array(ps_pk, pa.int64()),
        "ps_suppkey": pa.array(ps_sk, pa.int64()),
        "ps_availqty": pa.array(rng.integers(1, 10000, n_ps), pa.int32()),
        "ps_supplycost": money_from_cents(
            rng.integers(1_00, 1000_00, n_ps), 12, 2),
    })

    o_date_lo = _days(pydt.date(1992, 1, 1))
    o_date_hi = _days(pydt.date(1998, 8, 2))
    orders = pa.table({
        "o_orderkey": pa.array(range(n_ord), pa.int64()),
        # spec 4.2.3: orders reference only custkeys that are not a
        # multiple of 3 (a third of customers have no orders -> q13/q22
        # anti-join paths see real misses)
        "o_custkey": pa.array(
            np.array([k for k in range(n_cust) if k % 3 != 0], np.int64)[
                rng.integers(0, n_cust - (n_cust + 2) // 3, n_ord)],
            pa.int64()),
        "o_orderdate": pa.array(
            rng.integers(o_date_lo, o_date_hi, n_ord).astype(np.int32),
            pa.int32()).cast(pa.date32()),
        "o_shippriority": pa.array(np.zeros(n_ord, np.int32), pa.int32()),
        "o_orderstatus": pa.array(rng.choice(["F", "O", "P"], n_ord)),
        "o_orderpriority": pa.array(rng.choice(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
             "5-LOW"], n_ord)),
        "o_comment": pa.array(rng.choice(
            ["fast delivery", "special requests pending",
             "nothing unusual", "pending special requests now",
             "routine order"], n_ord)),
        "o_totalprice": money_from_cents(
            rng.integers(100_00, 500_000_00, n_ord), 12, 2),
    })

    l_ship = rng.integers(o_date_lo, o_date_hi + 122, n_li).astype(np.int32)
    l_commit = l_ship + rng.integers(-30, 61, n_li).astype(np.int32)
    l_receipt = l_ship + rng.integers(1, 31, n_li).astype(np.int32)
    rf = rng.choice(["A", "N", "R"], n_li)
    lineitem = pa.table({
        "l_orderkey": pa.array(rng.integers(0, n_ord, n_li), pa.int64()),
        "l_partkey": pa.array(rng.integers(0, n_part, n_li), pa.int64()),
        "l_suppkey": pa.array(rng.integers(0, n_supp, n_li), pa.int64()),
        "l_quantity": money_from_cents(
            rng.integers(1, 51, n_li) * 100, 12, 2),
        "l_extendedprice": money_from_cents(
            rng.integers(900_00, 10_500_000, n_li), 12, 2),
        "l_discount": money_from_cents(rng.integers(0, 11, n_li), 12, 2),
        "l_tax": money_from_cents(rng.integers(0, 9, n_li), 12, 2),
        "l_returnflag": pa.array(rf),
        "l_linestatus": pa.array(np.where(
            l_ship > _days(pydt.date(1995, 6, 17)), "O", "F")),
        "l_shipdate": pa.array(l_ship, pa.int32()).cast(pa.date32()),
        "l_commitdate": pa.array(l_commit, pa.int32()).cast(pa.date32()),
        "l_receiptdate": pa.array(l_receipt, pa.int32()).cast(pa.date32()),
        "l_shipmode": pa.array(rng.choice(
            ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP",
             "TRUCK"], n_li)),
    })
    return {"lineitem": lineitem, "orders": orders, "customer": customer,
            "supplier": supplier, "part": part, "partsupp": partsupp,
            "nation": nation, "region": region}


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------

def q1(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Pricing summary report."""
    cutoff = _days(pydt.date(1998, 12, 1)) - 90
    li = s.from_arrow(t["lineitem"])
    disc_price = E.Multiply(col("l_extendedprice"),
                            E.Subtract(E.Literal(1), col("l_discount")))
    charge = E.Multiply(disc_price,
                        E.Add(E.Literal(1), col("l_tax")))
    return (li.filter(E.LessThanOrEqual(col("l_shipdate"),
                                        E.Literal(cutoff, DTYPE_DATE)))
            .group_by("l_returnflag", "l_linestatus")
            .agg((Sum(col("l_quantity")), "sum_qty"),
                 (Sum(col("l_extendedprice")), "sum_base_price"),
                 (Sum(disc_price), "sum_disc_price"),
                 (Sum(charge), "sum_charge"),
                 (Average(col("l_quantity")), "avg_qty"),
                 (Average(col("l_extendedprice")), "avg_price"),
                 (Average(col("l_discount")), "avg_disc"),
                 (Count(None), "count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q3(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Shipping priority."""
    date = _days(pydt.date(1995, 3, 15))
    cust = s.from_arrow(t["customer"]).filter(
        E.EqualTo(col("c_mktsegment"), E.Literal("BUILDING")))
    orders = s.from_arrow(t["orders"]).filter(
        E.LessThan(col("o_orderdate"), E.Literal(date, DTYPE_DATE)))
    li = s.from_arrow(t["lineitem"]).filter(
        E.GreaterThan(col("l_shipdate"), E.Literal(date, DTYPE_DATE)))
    j = cust.join(orders, left_on=["c_custkey"], right_on=["o_custkey"]) \
        .join(li, left_on=["o_orderkey"], right_on=["l_orderkey"])
    revenue = E.Multiply(col("l_extendedprice"),
                         E.Subtract(E.Literal(1), col("l_discount")))
    return (j.group_by("o_orderkey", "o_orderdate", "o_shippriority")
            .agg((Sum(revenue), "revenue"))
            .sort(("revenue", False, False), ("o_orderdate", True, True))
            .limit(10))


def q5(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Local supplier volume: ASIA, 1994."""
    d_lo = _days(pydt.date(1994, 1, 1))
    d_hi = _days(pydt.date(1995, 1, 1))
    region = s.from_arrow(t["region"]).filter(
        E.EqualTo(col("r_name"), E.Literal("ASIA")))
    nation = s.from_arrow(t["nation"])
    cust = s.from_arrow(t["customer"])
    supp = s.from_arrow(t["supplier"])
    orders = s.from_arrow(t["orders"]).filter(
        E.And(E.GreaterThanOrEqual(col("o_orderdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("o_orderdate"), E.Literal(d_hi, DTYPE_DATE))))
    li = s.from_arrow(t["lineitem"])
    j = (region.join(nation, left_on=["r_regionkey"],
                     right_on=["n_regionkey"])
         .join(cust, left_on=["n_nationkey"], right_on=["c_nationkey"])
         .join(orders, left_on=["c_custkey"], right_on=["o_custkey"])
         .join(li, left_on=["o_orderkey"], right_on=["l_orderkey"]))
    # l_suppkey must match a supplier in the same nation:
    j = j.join(supp, left_on=["l_suppkey"], right_on=["s_suppkey"]) \
        .filter(E.EqualTo(col("s_nationkey"), col("n_nationkey")))
    revenue = E.Multiply(col("l_extendedprice"),
                         E.Subtract(E.Literal(1), col("l_discount")))
    return (j.group_by("n_name").agg((Sum(revenue), "revenue"))
            .sort(("revenue", False, False)))


def q6(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Forecast revenue change."""
    d_lo = _days(pydt.date(1994, 1, 1))
    d_hi = _days(pydt.date(1995, 1, 1))
    li = s.from_arrow(t["lineitem"])
    import decimal as pydec
    cond = E.And(
        E.And(E.GreaterThanOrEqual(col("l_shipdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("l_shipdate"), E.Literal(d_hi, DTYPE_DATE))),
        E.And(E.And(E.GreaterThanOrEqual(col("l_discount"),
                                         E.Literal(pydec.Decimal("0.05"))),
                    E.LessThanOrEqual(col("l_discount"),
                                      E.Literal(pydec.Decimal("0.07")))),
              E.LessThan(col("l_quantity"),
                         E.Literal(pydec.Decimal("24")))))
    revenue = E.Multiply(col("l_extendedprice"), col("l_discount"))
    return li.filter(cond).agg((Sum(revenue), "revenue"))


def q4(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Order priority checking: EXISTS ≡ left-semi join."""
    d_lo = _days(pydt.date(1993, 7, 1))
    d_hi = _days(pydt.date(1993, 10, 1))
    orders = s.from_arrow(t["orders"]).filter(
        E.And(E.GreaterThanOrEqual(col("o_orderdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("o_orderdate"), E.Literal(d_hi, DTYPE_DATE))))
    late = s.from_arrow(t["lineitem"]).filter(
        E.LessThan(col("l_commitdate"), col("l_receiptdate")))
    j = orders.join(late, how="left_semi",
                    left_on=["o_orderkey"], right_on=["l_orderkey"])
    return (j.group_by("o_orderpriority")
            .agg((Count(None), "order_count"))
            .sort("o_orderpriority"))


def q10(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Returned item reporting (top 20 customers by lost revenue)."""
    d_lo = _days(pydt.date(1993, 10, 1))
    d_hi = _days(pydt.date(1994, 1, 1))
    cust = s.from_arrow(t["customer"])
    orders = s.from_arrow(t["orders"]).filter(
        E.And(E.GreaterThanOrEqual(col("o_orderdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("o_orderdate"), E.Literal(d_hi, DTYPE_DATE))))
    li = s.from_arrow(t["lineitem"]).filter(
        E.EqualTo(col("l_returnflag"), E.Literal("R")))
    nation = s.from_arrow(t["nation"])
    j = (cust.join(orders, left_on=["c_custkey"], right_on=["o_custkey"])
         .join(li, left_on=["o_orderkey"], right_on=["l_orderkey"])
         .join(nation, left_on=["c_nationkey"], right_on=["n_nationkey"]))
    revenue = E.Multiply(col("l_extendedprice"),
                         E.Subtract(E.Literal(1), col("l_discount")))
    return (j.group_by("c_custkey", "n_name")
            .agg((Sum(revenue), "revenue"))
            .sort(("revenue", False, False), ("c_custkey", True, True))
            .limit(20))


def q12(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Shipping modes and order priority (CASE WHEN sums + IN)."""
    d_lo = _days(pydt.date(1994, 1, 1))
    d_hi = _days(pydt.date(1995, 1, 1))
    li = s.from_arrow(t["lineitem"]).filter(E.And(
        E.And(E.In(col("l_shipmode"), ["MAIL", "SHIP"]),
              E.And(E.LessThan(col("l_commitdate"), col("l_receiptdate")),
                    E.LessThan(col("l_shipdate"), col("l_commitdate")))),
        E.And(E.GreaterThanOrEqual(col("l_receiptdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("l_receiptdate"),
                         E.Literal(d_hi, DTYPE_DATE)))))
    orders = s.from_arrow(t["orders"])
    j = orders.join(li, left_on=["o_orderkey"], right_on=["l_orderkey"])
    high = E.CaseWhen(
        [(E.In(col("o_orderpriority"), ["1-URGENT", "2-HIGH"]),
          E.Literal(1))], E.Literal(0))
    low = E.CaseWhen(
        [(E.In(col("o_orderpriority"), ["1-URGENT", "2-HIGH"]),
          E.Literal(0))], E.Literal(1))
    return (j.group_by("l_shipmode")
            .agg((Sum(high), "high_line_count"),
                 (Sum(low), "low_line_count"))
            .sort("l_shipmode"))


def q14(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Promotion effect: 100 * promo revenue / total revenue."""
    from .plan.strings import StartsWith
    d_lo = _days(pydt.date(1995, 9, 1))
    d_hi = _days(pydt.date(1995, 10, 1))
    li = s.from_arrow(t["lineitem"]).filter(
        E.And(E.GreaterThanOrEqual(col("l_shipdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("l_shipdate"), E.Literal(d_hi, DTYPE_DATE))))
    part = s.from_arrow(t["part"])
    j = li.join(part, left_on=["l_partkey"], right_on=["p_partkey"])
    revenue = E.Multiply(col("l_extendedprice"),
                         E.Subtract(E.Literal(1), col("l_discount")))
    promo = E.CaseWhen([(StartsWith(col("p_type"), "PROMO"), revenue)],
                       E.Literal(pydec_zero()))
    agg = j.agg((Sum(promo), "promo"), (Sum(revenue), "total"))
    ratio = E.Divide(E.Multiply(E.Literal(100.0),
                                E.Cast(col("promo"), _t.DOUBLE)),
                     E.Cast(col("total"), _t.DOUBLE))
    return agg.select(ratio, names=["promo_revenue"])


def pydec_zero():
    import decimal as pydec
    return pydec.Decimal("0.00")


def q17(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Small-quantity-order revenue: correlated avg subquery as a join."""
    part = s.from_arrow(t["part"]).filter(
        E.EqualTo(col("p_type"), E.Literal("PROMO BURNISHED NICKEL")))
    li = s.from_arrow(t["lineitem"])
    per_part = (li.group_by("l_partkey")
                .agg((Average(col("l_quantity")), "avg_qty")))
    per_part = per_part.select(
        col("l_partkey"), E.Multiply(E.Literal(0.2),
                                     E.Cast(col("avg_qty"), _t.DOUBLE)),
        names=["ap_partkey", "qty_limit"])
    j = (li.join(part, left_on=["l_partkey"], right_on=["p_partkey"])
         .join(per_part, left_on=["l_partkey"], right_on=["ap_partkey"])
         .filter(E.LessThan(E.Cast(col("l_quantity"), _t.DOUBLE),
                            col("qty_limit"))))
    total = j.agg((Sum(col("l_extendedprice")), "s"))
    return total.select(
        E.Divide(E.Cast(col("s"), _t.DOUBLE), E.Literal(7.0)),
        names=["avg_yearly"])


def q18(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Large-volume customers (HAVING sum(qty) > threshold via join)."""
    li = s.from_arrow(t["lineitem"])
    big = (li.group_by("l_orderkey")
           .agg((Sum(col("l_quantity")), "total_qty"))
           .filter(E.GreaterThan(E.Cast(col("total_qty"), _t.DOUBLE),
                                 E.Literal(7200.0))))
    big = big.select(col("l_orderkey"), col("total_qty"),
                     names=["big_orderkey", "total_qty"])
    orders = s.from_arrow(t["orders"])
    cust = s.from_arrow(t["customer"])
    j = (orders.join(big, left_on=["o_orderkey"], right_on=["big_orderkey"])
         .join(cust, left_on=["o_custkey"], right_on=["c_custkey"]))
    return (j.select(col("c_custkey"), col("o_orderkey"), col("o_orderdate"),
                     col("o_totalprice"), col("total_qty"))
            .sort(("o_totalprice", False, False), ("o_orderdate", True, True))
            .limit(100))


def q7(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Volume shipping between FRANCE and GERMANY (nation joined twice
    under renames)."""
    d_lo = _days(pydt.date(1995, 1, 1))
    d_hi = _days(pydt.date(1996, 12, 31))
    supp_nation = s.from_arrow(t["nation"]).select(
        col("n_nationkey"), col("n_name"),
        names=["sn_key", "supp_nation"]).filter(
        E.In(col("supp_nation"), ["FRANCE", "GERMANY"]))
    cust_nation = s.from_arrow(t["nation"]).select(
        col("n_nationkey"), col("n_name"),
        names=["cn_key", "cust_nation"]).filter(
        E.In(col("cust_nation"), ["FRANCE", "GERMANY"]))
    li = s.from_arrow(t["lineitem"]).filter(
        E.And(E.GreaterThanOrEqual(col("l_shipdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThanOrEqual(col("l_shipdate"),
                                E.Literal(d_hi, DTYPE_DATE))))
    j = (li.join(s.from_arrow(t["supplier"]),
                 left_on=["l_suppkey"], right_on=["s_suppkey"])
         .join(supp_nation, left_on=["s_nationkey"], right_on=["sn_key"])
         .join(s.from_arrow(t["orders"]),
               left_on=["l_orderkey"], right_on=["o_orderkey"])
         .join(s.from_arrow(t["customer"]),
               left_on=["o_custkey"], right_on=["c_custkey"])
         .join(cust_nation, left_on=["c_nationkey"], right_on=["cn_key"])
         .filter(E.Not(E.EqualTo(col("supp_nation"),
                                 col("cust_nation")))))
    volume = E.Multiply(col("l_extendedprice"),
                        E.Subtract(E.Literal(1), col("l_discount")))
    year = DT.Year(col("l_shipdate"))
    return (j.group_by(col("supp_nation"), col("cust_nation"),
                       E.Alias(year, "l_year"))
            .agg((Sum(volume), "revenue"))
            .sort("supp_nation", "cust_nation", "l_year"))


def q9(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Product type profit measure: the spec's 6-table join with
    ps_supplycost (profit = price*(1-disc) - supplycost*qty)."""
    from .plan.strings import Contains
    part = s.from_arrow(t["part"]).filter(
        Contains(col("p_name"), "green"))
    li = s.from_arrow(t["lineitem"])
    ps = s.from_arrow(t["partsupp"])
    j = (li.join(part, left_on=["l_partkey"], right_on=["p_partkey"])
         .join(s.from_arrow(t["supplier"]),
               left_on=["l_suppkey"], right_on=["s_suppkey"])
         .join(ps, left_on=["l_partkey", "l_suppkey"],
               right_on=["ps_partkey", "ps_suppkey"])
         .join(s.from_arrow(t["orders"]),
               left_on=["l_orderkey"], right_on=["o_orderkey"])
         .join(s.from_arrow(t["nation"]),
               left_on=["s_nationkey"], right_on=["n_nationkey"]))
    amount = E.Subtract(
        E.Multiply(col("l_extendedprice"),
                   E.Subtract(E.Literal(1), col("l_discount"))),
        E.Multiply(col("ps_supplycost"), col("l_quantity")))
    year = DT.Year(col("o_orderdate"))
    return (j.group_by(col("n_name"), E.Alias(year, "o_year"))
            .agg((Sum(amount), "sum_profit"))
            .sort(("n_name", True, True), ("o_year", False, False)))


def q13(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Customer distribution: two-level aggregation over a left outer
    join with a NOT-LIKE filtered order side."""
    from .plan.strings import Contains
    orders = s.from_arrow(t["orders"]).filter(
        E.Not(E.And(Contains(col("o_comment"), "special"),
                    Contains(col("o_comment"), "requests"))))
    cust = s.from_arrow(t["customer"])
    j = cust.join(orders, how="left_outer",
                  left_on=["c_custkey"], right_on=["o_custkey"])
    per_cust = (j.group_by("c_custkey")
                .agg((Count(col("o_orderkey")), "c_count")))
    return (per_cust.group_by("c_count")
            .agg((Count(None), "custdist"))
            .sort(("custdist", False, False), ("c_count", False, False)))


def q19(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Discounted revenue: disjunction of brand/container/quantity
    conjuncts (the OR-of-ANDs predicate shape)."""
    import decimal as pydec
    li = s.from_arrow(t["lineitem"]).filter(
        E.And(E.In(col("l_shipmode"), ["AIR", "REG AIR"]),
              E.EqualTo(col("l_returnflag"), E.Literal("N"))))
    part = s.from_arrow(t["part"])
    j = li.join(part, left_on=["l_partkey"], right_on=["p_partkey"])

    def qty_between(lo, hi):
        return E.And(
            E.GreaterThanOrEqual(col("l_quantity"),
                                 E.Literal(pydec.Decimal(lo))),
            E.LessThanOrEqual(col("l_quantity"),
                              E.Literal(pydec.Decimal(hi))))
    branch1 = E.And(E.And(E.EqualTo(col("p_brand"), E.Literal("Brand#12")),
                          E.In(col("p_container"),
                               ["SM CASE", "SM BOX"])),
                    E.And(qty_between("1", "11"),
                          E.LessThanOrEqual(col("p_size"), E.Literal(5))))
    branch2 = E.And(E.And(E.EqualTo(col("p_brand"), E.Literal("Brand#23")),
                          E.In(col("p_container"),
                               ["MED BAG", "MED BOX"])),
                    E.And(qty_between("10", "20"),
                          E.LessThanOrEqual(col("p_size"), E.Literal(10))))
    branch3 = E.And(E.And(E.EqualTo(col("p_brand"), E.Literal("Brand#34")),
                          E.In(col("p_container"),
                               ["LG CASE", "LG BOX", "JUMBO PKG"])),
                    E.And(qty_between("20", "30"),
                          E.LessThanOrEqual(col("p_size"), E.Literal(15))))
    revenue = E.Multiply(col("l_extendedprice"),
                         E.Subtract(E.Literal(1), col("l_discount")))
    return (j.filter(E.Or(E.Or(branch1, branch2), branch3))
            .agg((Sum(revenue), "revenue")))


def q2(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Minimum-cost supplier: correlated MIN subquery as a self-join on
    (partkey, min cost)."""
    from .plan.strings import EndsWith
    part = s.from_arrow(t["part"]).filter(
        E.And(E.EqualTo(col("p_size"), E.Literal(15)),
              EndsWith(col("p_type"), "BRASS")))
    europe = (s.from_arrow(t["region"])
              .filter(E.EqualTo(col("r_name"), E.Literal("EUROPE")))
              .join(s.from_arrow(t["nation"]),
                    left_on=["r_regionkey"], right_on=["n_regionkey"]))
    esupp = europe.join(s.from_arrow(t["supplier"]),
                        left_on=["n_nationkey"], right_on=["s_nationkey"])
    ps = s.from_arrow(t["partsupp"])
    eps = ps.join(esupp, left_on=["ps_suppkey"], right_on=["s_suppkey"]) \
        .join(part, left_on=["ps_partkey"], right_on=["p_partkey"])
    from .plan.aggregates import Min
    mins = (eps.group_by("ps_partkey")
            .agg((Min(col("ps_supplycost")), "min_cost"))
            .select(col("ps_partkey"), col("min_cost"),
                    names=["mc_partkey", "min_cost"]))
    j = eps.join(mins, left_on=["ps_partkey", "ps_supplycost"],
                 right_on=["mc_partkey", "min_cost"])
    return (j.select(col("s_acctbal"), col("s_name"), col("n_name"),
                     col("p_partkey"), col("p_mfgr"), col("s_address"),
                     col("s_phone"))
            .sort(("s_acctbal", False, False), ("n_name", True, True),
                  ("s_name", True, True), ("p_partkey", True, True))
            .limit(100))


def q8(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """National market share: BRAZIL's share of AMERICA's ECONOMY
    ANODIZED STEEL volume per year."""
    d_lo = _days(pydt.date(1995, 1, 1))
    d_hi = _days(pydt.date(1996, 12, 31))
    part = s.from_arrow(t["part"]).filter(
        E.EqualTo(col("p_type"), E.Literal("ECONOMY ANODIZED STEEL")))
    orders = s.from_arrow(t["orders"]).filter(
        E.And(E.GreaterThanOrEqual(col("o_orderdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThanOrEqual(col("o_orderdate"),
                                E.Literal(d_hi, DTYPE_DATE))))
    n1 = (s.from_arrow(t["region"])
          .filter(E.EqualTo(col("r_name"), E.Literal("AMERICA")))
          .join(s.from_arrow(t["nation"]),
                left_on=["r_regionkey"], right_on=["n_regionkey"])
          .select(col("n_nationkey"), names=["cn_key"]))
    n2 = s.from_arrow(t["nation"]).select(
        col("n_nationkey"), col("n_name"), names=["sn_key", "supp_nation"])
    j = (s.from_arrow(t["lineitem"])
         .join(part, left_on=["l_partkey"], right_on=["p_partkey"])
         .join(s.from_arrow(t["supplier"]),
               left_on=["l_suppkey"], right_on=["s_suppkey"])
         .join(orders, left_on=["l_orderkey"], right_on=["o_orderkey"])
         .join(s.from_arrow(t["customer"]),
               left_on=["o_custkey"], right_on=["c_custkey"])
         .join(n1, left_on=["c_nationkey"], right_on=["cn_key"])
         .join(n2, left_on=["s_nationkey"], right_on=["sn_key"]))
    volume = E.Multiply(
        E.Cast(col("l_extendedprice"), _t.DOUBLE),
        E.Subtract(E.Literal(1.0), E.Cast(col("l_discount"), _t.DOUBLE)))
    brazil = E.CaseWhen(
        [(E.EqualTo(col("supp_nation"), E.Literal("BRAZIL")), volume)],
        E.Literal(0.0))
    year = DT.Year(col("o_orderdate"))
    g = (j.group_by(E.Alias(year, "o_year"))
         .agg((Sum(brazil), "brazil_vol"), (Sum(volume), "total_vol")))
    share = E.Divide(col("brazil_vol"), col("total_vol"))
    return (g.select(col("o_year"), share, names=["o_year", "mkt_share"])
            .sort("o_year"))


def q11(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Important stock identification: HAVING against a scalar subquery
    (total value fraction) via a 1-row cross join."""
    germany = (s.from_arrow(t["partsupp"])
               .join(s.from_arrow(t["supplier"]),
                     left_on=["ps_suppkey"], right_on=["s_suppkey"])
               .join(s.from_arrow(t["nation"]).filter(
                   E.EqualTo(col("n_name"), E.Literal("GERMANY"))),
                   left_on=["s_nationkey"], right_on=["n_nationkey"]))
    value = E.Multiply(E.Cast(col("ps_supplycost"), _t.DOUBLE),
                       E.Cast(col("ps_availqty"), _t.DOUBLE))
    per_part = (germany.group_by("ps_partkey")
                .agg((Sum(value), "value")))
    total = (germany.agg((Sum(value), "tv"))
             .select(E.Multiply(col("tv"), E.Literal(0.0001)),
                     names=["threshold"]))
    j = per_part.join(total, how="cross")
    return (j.filter(E.GreaterThan(col("value"), col("threshold")))
            .select(col("ps_partkey"), col("value"))
            .sort(("value", False, False), ("ps_partkey", True, True)))


def q15(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Top supplier: revenue view + MAX scalar subquery."""
    from .plan.aggregates import Max
    d_lo = _days(pydt.date(1996, 1, 1))
    d_hi = _days(pydt.date(1996, 4, 1))
    li = s.from_arrow(t["lineitem"]).filter(
        E.And(E.GreaterThanOrEqual(col("l_shipdate"),
                                   E.Literal(d_lo, DTYPE_DATE)),
              E.LessThan(col("l_shipdate"), E.Literal(d_hi, DTYPE_DATE))))
    revenue = E.Multiply(
        E.Cast(col("l_extendedprice"), _t.DOUBLE),
        E.Subtract(E.Literal(1.0), E.Cast(col("l_discount"), _t.DOUBLE)))
    rev = (li.group_by("l_suppkey")
           .agg((Sum(revenue), "total_revenue")))
    top = rev.agg((Max(col("total_revenue")), "max_revenue"))
    j = (rev.join(top, how="cross")
         .filter(E.EqualTo(col("total_revenue"), col("max_revenue")))
         .join(s.from_arrow(t["supplier"]),
               left_on=["l_suppkey"], right_on=["s_suppkey"]))
    return (j.select(col("s_suppkey"), col("s_name"), col("s_address"),
                     col("s_phone"), col("total_revenue"))
            .sort("s_suppkey"))


def q16(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Parts/supplier relationship: NOT IN subquery as anti join +
    count(distinct)."""
    from .plan.aggregates import CountDistinct
    from .plan.strings import Contains, StartsWith
    bad_supp = s.from_arrow(t["supplier"]).filter(
        E.And(Contains(col("s_comment"), "Customer"),
              Contains(col("s_comment"), "Complaints")))
    part = s.from_arrow(t["part"]).filter(
        E.And(E.Not(E.EqualTo(col("p_brand"), E.Literal("Brand#45"))),
              E.And(E.Not(StartsWith(col("p_type"), "MEDIUM POLISHED")),
                    E.In(E.Cast(col("p_size"), _t.INT),
                         [49, 14, 23, 45, 19, 3, 36, 9]))))
    ps = (s.from_arrow(t["partsupp"])
          .join(bad_supp, how="left_anti",
                left_on=["ps_suppkey"], right_on=["s_suppkey"])
          .join(part, left_on=["ps_partkey"], right_on=["p_partkey"]))
    return (ps.group_by("p_brand", "p_type", "p_size")
            .agg((CountDistinct(col("ps_suppkey")), "supplier_cnt"))
            .sort(("supplier_cnt", False, False), ("p_brand", True, True),
                  ("p_type", True, True), ("p_size", True, True)))


def q20(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Potential part promotion: nested IN subqueries as semi joins over
    a half-of-shipped-quantity threshold."""
    from .plan.strings import StartsWith
    d_lo = _days(pydt.date(1994, 1, 1))
    d_hi = _days(pydt.date(1995, 1, 1))
    green = s.from_arrow(t["part"]).filter(
        StartsWith(col("p_name"), "green"))
    shipped = (s.from_arrow(t["lineitem"])
               .filter(E.And(
                   E.GreaterThanOrEqual(col("l_shipdate"),
                                        E.Literal(d_lo, DTYPE_DATE)),
                   E.LessThan(col("l_shipdate"),
                              E.Literal(d_hi, DTYPE_DATE))))
               .group_by("l_partkey", "l_suppkey")
               .agg((Sum(col("l_quantity")), "sum_qty")))
    shipped = shipped.select(
        col("l_partkey"), col("l_suppkey"),
        E.Multiply(E.Literal(0.5), E.Cast(col("sum_qty"), _t.DOUBLE)),
        names=["sh_partkey", "sh_suppkey", "half_qty"])
    ps = (s.from_arrow(t["partsupp"])
          .join(green, how="left_semi",
                left_on=["ps_partkey"], right_on=["p_partkey"])
          .join(shipped, left_on=["ps_partkey", "ps_suppkey"],
                right_on=["sh_partkey", "sh_suppkey"])
          .filter(E.GreaterThan(E.Cast(col("ps_availqty"), _t.DOUBLE),
                                col("half_qty"))))
    supp = (s.from_arrow(t["supplier"])
            .join(s.from_arrow(t["nation"]).filter(
                E.EqualTo(col("n_name"), E.Literal("CANADA"))),
                left_on=["s_nationkey"], right_on=["n_nationkey"])
            .join(ps, how="left_semi",
                  left_on=["s_suppkey"], right_on=["ps_suppkey"]))
    return supp.select(col("s_name"), col("s_address")).sort("s_name")


def q21(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Suppliers who kept orders waiting: EXISTS/NOT-EXISTS pair rewritten
    as per-order distinct-supplier counts (total > 1, late == 1)."""
    from .plan.aggregates import CountDistinct
    li = s.from_arrow(t["lineitem"])
    late = li.filter(E.GreaterThan(col("l_receiptdate"),
                                   col("l_commitdate")))
    total_supp = (li.group_by("l_orderkey")
                  .agg((CountDistinct(col("l_suppkey")), "n_supp"))
                  .select(col("l_orderkey"), col("n_supp"),
                          names=["ts_orderkey", "n_supp"]))
    late_supp = (late.group_by("l_orderkey")
                 .agg((CountDistinct(col("l_suppkey")), "n_late"))
                 .select(col("l_orderkey"), col("n_late"),
                         names=["ls_orderkey", "n_late"]))
    fails = s.from_arrow(t["orders"]).filter(
        E.EqualTo(col("o_orderstatus"), E.Literal("F")))
    saudi = (s.from_arrow(t["supplier"])
             .join(s.from_arrow(t["nation"]).filter(
                 E.EqualTo(col("n_name"), E.Literal("SAUDI ARABIA"))),
                 left_on=["s_nationkey"], right_on=["n_nationkey"]))
    j = (late.join(saudi, left_on=["l_suppkey"], right_on=["s_suppkey"])
         .join(fails, left_on=["l_orderkey"], right_on=["o_orderkey"])
         .join(total_supp, left_on=["l_orderkey"], right_on=["ts_orderkey"])
         .join(late_supp, left_on=["l_orderkey"], right_on=["ls_orderkey"])
         .filter(E.And(E.GreaterThan(col("n_supp"), E.Literal(1)),
                       E.EqualTo(col("n_late"), E.Literal(1)))))
    return (j.group_by("s_name")
            .agg((Count(None), "numwait"))
            .sort(("numwait", False, False), ("s_name", True, True))
            .limit(100))


def q22(s: TpuSession, t: Dict[str, pa.Table]) -> DataFrame:
    """Global sales opportunity: phone-prefix IN + scalar AVG subquery +
    NOT EXISTS anti join."""
    from .plan.strings import Substring
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = s.from_arrow(t["customer"]).select(
        col("c_custkey"), col("c_acctbal"),
        Substring(col("c_phone"), 1, 2),
        names=["c_custkey", "c_acctbal", "cntrycode"])
    cust = cust.filter(E.In(col("cntrycode"), codes))
    pos = cust.filter(E.GreaterThan(
        E.Cast(col("c_acctbal"), _t.DOUBLE), E.Literal(0.0)))
    avg_bal = pos.agg(
        (Average(E.Cast(col("c_acctbal"), _t.DOUBLE)), "avg_bal"))
    cand = (cust.join(avg_bal, how="cross")
            .filter(E.GreaterThan(E.Cast(col("c_acctbal"), _t.DOUBLE),
                                  col("avg_bal")))
            .join(s.from_arrow(t["orders"]), how="left_anti",
                  left_on=["c_custkey"], right_on=["o_custkey"]))
    return (cand.group_by("cntrycode")
            .agg((Count(None), "numcust"),
                 (Sum(E.Cast(col("c_acctbal"), _t.DOUBLE)), "totacctbal"))
            .sort("cntrycode"))


from . import types as _t           # noqa: E402
DTYPE_DATE = _t.DATE

QUERIES = {"q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6,
           "q7": q7, "q8": q8, "q9": q9, "q10": q10, "q11": q11,
           "q12": q12, "q13": q13, "q14": q14, "q15": q15, "q16": q16,
           "q17": q17, "q18": q18, "q19": q19, "q20": q20, "q21": q21,
           "q22": q22}
