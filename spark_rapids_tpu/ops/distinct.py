"""Device count(DISTINCT) via the sort-segment machinery.

Role of the reference's count-distinct planning (SURVEY §2.5: 'per-key
dedupe' — Spark rewrites to a two-level aggregation; the reference runs
the dedupe as a cuDF drop_duplicates).  TPU formulation: sort rows by
(group keys, value) and count value-change boundaries among valid rows
per segment — no materialized dedupe table, one fused program.

Value equality uses the storage lanes (int64 f64-bit-patterns for
DOUBLE are bit-exact; string codes must be dictionary-unified by the
caller).  Nulls are excluded (Spark count(DISTINCT) semantics); NaN
counts as one distinct value (all NaN bit patterns canonicalize).

When the VALUE lane carries exact static bounds (`val_range`: scan
statistics for int lanes, dictionary size for string codes) it packs
into the same single sort lane as the group keys (ops/segments.py
sorted_segments minor_spec), so the whole count-distinct order is ONE
2-operand sort — the q16-class multi-operand lexsort whose XLA compile
ran minutes disappears.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import types as t
from .groupby import _CANON_NAN, _EXP_MASK, _MANT_MASK, _eq_prev
from .kernels import compute_view
from .segments import seg_sums_sorted, sorted_segments


_NEG_ZERO_BITS = jnp.int64(-2 ** 63)        # 0x8000000000000000


def _value_eq_lanes(data: jax.Array, dt: t.DataType):
    """Lanes whose rowwise equality == value equality (NaN canonical,
    -0.0 == 0.0 per Spark's distinct/grouping normalization)."""
    if isinstance(dt, t.DoubleType) and data.dtype == jnp.int64:
        is_nan = ((data & _EXP_MASK) == _EXP_MASK) & \
            ((data & _MANT_MASK) != 0)
        d = jnp.where(is_nan, jnp.int64(_CANON_NAN), data)
        return [jnp.where(d == _NEG_ZERO_BITS, jnp.int64(0), d)]
    v = compute_view(data, dt)
    if t.is_floating(dt):
        isnan = jnp.isnan(v)
        return [jnp.where(isnan, 0, v), isnan.astype(jnp.int8)]
    return [v]


def distinct_count_trace(key_lanes_info, num_segments: int,
                         capacity: int, pack_spec=None, val_range=None,
                         scatter_free=True, max_sort_operands=2):
    """Traced fn: (keys, keys_valid, val_data, val_valid, live,
    val_dtype static via closure list) -> (out_keys, (count, valid),
    num_groups).

    val_range: exact (lo, hi) bound on the value's int lane (scan stats
    / dictionary size) — lets the value ride the packed key sort lane.
    """

    def build(val_dtype: t.DataType):
        def run(keys, keys_valid, val, val_valid, live):
            vlive = live & val_valid
            vlanes = _value_eq_lanes(val, val_dtype)
            # minor order within group: values grouped (asc), nulls last
            minor = list(vlanes) + [(~vlive).astype(jnp.int8)]
            minor_spec = None
            if val_range is not None and len(vlanes) == 1:
                lo, hi = int(val_range[0]), int(val_range[1])
                minor_spec = [(lo, hi - lo + 1), (0, 2)]
            runs = sorted_segments(
                key_lanes_info, keys, keys_valid, live, minor, capacity,
                num_segments, pack_spec=pack_spec,
                minor_spec=minor_spec,
                max_sort_operands=max_sort_operands)
            perm, seg_ids = runs.perm, runs.seg_ids
            s_vlive = vlive[perm]
            s_vlanes = [l[perm] for l in vlanes]

            # first occurrence of each distinct valid value in a group:
            # segment start OR any value lane changed from prev row
            changed = _eq_prev(seg_ids)
            for lane in s_vlanes:
                changed = changed | _eq_prev(lane)
            first = s_vlive & changed
            if scatter_free:
                # per-segment boundary counts = stacked-cumsum diff at
                # the run ends — no segment_sum scatter
                cnt = seg_sums_sorted([first.astype(jnp.int64)],
                                      runs.start_idx,
                                      runs.end_idx)[:, 0]
            else:
                cnt = jax.ops.segment_sum(first.astype(jnp.int64),
                                          seg_ids,
                                          num_segments=num_segments)
            return runs.out_keys, (cnt, runs.group_live), runs.num_groups

        return run

    return build
