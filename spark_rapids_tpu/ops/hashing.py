"""Spark-exact Murmur3 hashing on device (jnp) and host (bytes).

Bit-compatibility with Spark's Murmur3_x86_32 (seed 42) matters because hash
partitioning decides shuffle placement: CPU-fallback operators and device
operators must agree on row placement, exactly as the reference computes
Spark-exact murmur3 on the GPU (reference: spark-rapids-jni `Hash`,
GpuHashPartitioningBase.scala:28, HashFunctions.scala).

Fixed-width values hash on device in uint32 lanes; strings hash on host over
the (small) per-batch dictionary, and rows pick up `dict_hashes[code]` on
device — the dictionary-encoding dividend of the TPU columnar layout.

Spark semantics reproduced here:
  * null field: hash unchanged (the running seed passes through)
  * boolean -> hashInt(0/1); byte/short/int/date -> hashInt(sign-extended)
  * long/timestamp -> hashLong; float/double -> bits with -0.0 -> +0.0
  * string -> hashUnsafeBytes: 4-byte LE words, then per-byte tail rounds
    (signed bytes), fmix with total byte length
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import types as t

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
SPARK_SEED = 42


# ---------------------------------------------------------------------------
# Device (jnp, uint32 lanes)
# ---------------------------------------------------------------------------

def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = (k1 * jnp.uint32(_C1)).astype(jnp.uint32)
    k1 = _rotl32(k1, 15)
    return (k1 * jnp.uint32(_C2)).astype(jnp.uint32)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return (h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)).astype(jnp.uint32)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * jnp.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * jnp.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def hash_int32(x, seed):
    """Murmur3 hashInt: x int32 array, seed uint32 array/scalar -> uint32."""
    k1 = _mix_k1(x.astype(jnp.uint32))
    h1 = _mix_h1(seed.astype(jnp.uint32), k1)
    return _fmix(h1, 4)


def hash_int64(x, seed):
    x = x.astype(jnp.int64)
    low = x.astype(jnp.uint32)
    high = (x >> 32).astype(jnp.uint32)
    k1 = _mix_k1(low)
    h1 = _mix_h1(seed.astype(jnp.uint32), k1)
    k1 = _mix_k1(high)
    h1 = _mix_h1(h1, k1)
    return _fmix(h1, 8)


def hash_column(data, validity, dt: t.DataType, seed, dict_hashes=None):
    """Fold one column into a running uint32 hash lane (Spark semantics).

    `data` is the *storage* lane (DOUBLE = f64 bits as int64). `dict_hashes`
    is a precomputed uint32 device array of per-dictionary-entry hashes for
    STRING columns, computed on host against the SAME seed chain only when
    the column is the first key; for multi-key chains string hashing needs
    per-row seeds, so dict_hashes holds murmur3 of the utf8 bytes with each
    possible seed — instead we pass raw bytes hashing via a two-level scheme:
    dict_hashes maps code -> hashUnsafeBytes(entry, seed_chain) computed on
    host per batch when seeds are scalar.  See StringHashPlan in
    exec/hashing for the general case.
    """
    if isinstance(dt, t.BooleanType):
        h = hash_int32(data.astype(jnp.int32), seed)
    elif isinstance(dt, (t.ByteType, t.ShortType, t.IntegerType, t.DateType)):
        h = hash_int32(data.astype(jnp.int32), seed)
    elif isinstance(dt, (t.LongType, t.TimestampType)):
        h = hash_int64(data, seed)
    elif isinstance(dt, t.FloatType):
        import jax
        x = jnp.where(data == 0.0, jnp.float32(0.0), data)  # -0.0 -> +0.0
        bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
        h = hash_int32(bits, seed)
    elif isinstance(dt, t.DoubleType):
        # Requires the int64 f64-bits storage lane (host pass-through
        # columns).  Computed-f64 lanes can't be bitcast on this TPU
        # (f64->s64 unimplemented); callers must tag such keys unsupported.
        if data.dtype != jnp.int64:
            raise TypeError("hashing computed f64 values is not supported on "
                            "device; route through host or disallow")
        neg_zero = jnp.int64(np.int64(-2**63))  # 0x8000_0000_0000_0000
        bits = jnp.where(data == neg_zero, jnp.int64(0), data)
        h = hash_int64(bits, seed)
    elif isinstance(dt, t.StringType):
        if dict_hashes is None:
            raise ValueError("string hashing requires precomputed dict hashes")
        h = dict_hashes[jnp.clip(data, 0, dict_hashes.shape[0] - 1)]
    elif isinstance(dt, t.DecimalType) and not dt.is_wide:
        # Spark hashes small decimals as the unscaled long when precision<=18
        h = hash_int64(data, seed)
    else:
        raise TypeError(f"unsupported hash type {dt}")
    if validity is not None:
        h = jnp.where(validity, h, seed.astype(jnp.uint32))
    return h


# ---------------------------------------------------------------------------
# Host (numpy over raw bytes — used for string dictionaries)
# ---------------------------------------------------------------------------

def _np_u32(x):
    return np.uint32(x & 0xFFFFFFFF)


def _np_mix_k1(k1):
    k1 = np.uint32((int(k1) * _C1) & 0xFFFFFFFF)
    k1 = np.uint32(((int(k1) << 15) | (int(k1) >> 17)) & 0xFFFFFFFF)
    return np.uint32((int(k1) * _C2) & 0xFFFFFFFF)


def _np_mix_h1(h1, k1):
    h1 = np.uint32(int(h1) ^ int(k1))
    h1 = np.uint32(((int(h1) << 13) | (int(h1) >> 19)) & 0xFFFFFFFF)
    return np.uint32((int(h1) * 5 + 0xE6546B64) & 0xFFFFFFFF)


def _np_fmix(h1, length):
    h = int(h1) ^ length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return np.uint32(h)


def murmur3_bytes(data: bytes, seed: int) -> int:
    """Spark Murmur3_x86_32.hashUnsafeBytes over `data` (per-byte tail)."""
    h1 = _np_u32(seed)
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        word = int.from_bytes(data[i:i + 4], "little", signed=True)
        h1 = _np_mix_h1(h1, _np_mix_k1(_np_u32(word)))
    for i in range(aligned, n):
        byte = int.from_bytes(data[i:i + 1], "little", signed=True)
        h1 = _np_mix_h1(h1, _np_mix_k1(_np_u32(byte)))
    return int(_np_fmix(h1, n))


def murmur3_utf8(s, seed: int) -> int:
    return murmur3_bytes(s.encode("utf-8"), seed)


def dict_hash_array(dictionary, seed: int) -> np.ndarray:
    """uint32 hashes of every dictionary entry (host; dictionaries are small)."""
    out = np.empty(max(len(dictionary), 1), dtype=np.uint32)
    out[:] = np.uint32(seed)
    for i, v in enumerate(dictionary):
        s = v.as_py() if hasattr(v, "as_py") else v
        if s is not None:
            out[i] = np.uint32(murmur3_utf8(s, seed))
    return out


def murmur3_int32_host(x: int, seed: int) -> int:
    h1 = _np_mix_h1(_np_u32(seed), _np_mix_k1(_np_u32(x)))
    return int(_np_fmix(h1, 4))


def murmur3_int64_host(x: int, seed: int) -> int:
    x &= 0xFFFFFFFFFFFFFFFF
    low = x & 0xFFFFFFFF
    high = (x >> 32) & 0xFFFFFFFF
    h1 = _np_mix_h1(_np_u32(seed), _np_mix_k1(_np_u32(low)))
    h1 = _np_mix_h1(h1, _np_mix_k1(_np_u32(high)))
    return int(_np_fmix(h1, 8))


# ---------------------------------------------------------------------------
# xxhash64 (reference spark-rapids-jni Hash.xxhash64 / Spark XXH64)
# ---------------------------------------------------------------------------

_XXP1 = 0x9E3779B185EBCA87
_XXP2 = 0xC2B2AE3D27D4EB4F
_XXP3 = 0x165667B19E3779F9
_XXP4 = 0x85EBCA77C2B2AE63
_XXP5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _xx_fmix(h: int) -> int:
    h ^= h >> 33
    h = (h * _XXP2) & _M64
    h ^= h >> 29
    h = (h * _XXP3) & _M64
    h ^= h >> 32
    return h


def xxhash64_long_host(l: int, seed: int) -> int:
    """Spark XXH64.hashLong, exact (host ints)."""
    h = (seed + _XXP5 + 8) & _M64
    k1 = (_rotl64((l & _M64) * _XXP2 & _M64, 31) * _XXP1) & _M64
    h ^= k1
    h = (_rotl64(h, 27) * _XXP1 + _XXP4) & _M64
    return _xx_fmix(h)


def xxhash64_int_host(i: int, seed: int) -> int:
    """Spark XXH64.hashInt, exact (host ints)."""
    h = (seed + _XXP5 + 4) & _M64
    h ^= ((i & 0xFFFFFFFF) * _XXP1) & _M64
    h = (_rotl64(h, 23) * _XXP2 + _XXP3) & _M64
    return _xx_fmix(h)


def xxhash64_bytes_host(data: bytes, seed: int) -> int:
    """Spark XXH64.hashUnsafeBytes (strings), exact."""
    length = len(data)
    if length >= 32:
        v1 = (seed + _XXP1 + _XXP2) & _M64
        v2 = (seed + _XXP2) & _M64
        v3 = seed & _M64
        v4 = (seed - _XXP1) & _M64
        off = 0
        while off + 32 <= length:
            for vi in range(4):
                w = int.from_bytes(data[off + 8 * vi: off + 8 * vi + 8],
                                   "little")
                if vi == 0:
                    v1 = (_rotl64((v1 + w * _XXP2) & _M64, 31) * _XXP1) \
                        & _M64
                elif vi == 1:
                    v2 = (_rotl64((v2 + w * _XXP2) & _M64, 31) * _XXP1) \
                        & _M64
                elif vi == 2:
                    v3 = (_rotl64((v3 + w * _XXP2) & _M64, 31) * _XXP1) \
                        & _M64
                else:
                    v4 = (_rotl64((v4 + w * _XXP2) & _M64, 31) * _XXP1) \
                        & _M64
            off += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) +
             _rotl64(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h ^= (_rotl64((v * _XXP2) & _M64, 31) * _XXP1) & _M64
            h = (h * _XXP1 + _XXP4) & _M64
    else:
        off = 0
        h = (seed + _XXP5) & _M64
    h = (h + length) & _M64
    while off + 8 <= length:
        w = int.from_bytes(data[off:off + 8], "little")
        h ^= (_rotl64((w * _XXP2) & _M64, 31) * _XXP1) & _M64
        h = (_rotl64(h, 27) * _XXP1 + _XXP4) & _M64
        off += 8
    if off + 4 <= length:
        w = int.from_bytes(data[off:off + 4], "little")
        h ^= (w * _XXP1) & _M64
        h = (_rotl64(h, 23) * _XXP2 + _XXP3) & _M64
        off += 4
    while off < length:
        h ^= ((data[off] & 0xFF) * _XXP5) & _M64
        h = (_rotl64(h, 11) * _XXP1) & _M64
        off += 1
    return _xx_fmix(h)


def xxhash64_utf8(s, seed: int) -> int:
    return xxhash64_bytes_host(s.encode("utf-8"), seed)


def dict_xxhash_array(dictionary, seed: int) -> np.ndarray:
    """uint64 xxhash64 of every dictionary entry (host)."""
    out = np.empty(max(len(dictionary), 1), dtype=np.uint64)
    out[:] = np.uint64(seed)
    for i, v in enumerate(dictionary):
        s = v.as_py() if hasattr(v, "as_py") else v
        if s is not None:
            out[i] = np.uint64(xxhash64_utf8(s, seed))
    return out


def _jx_rotl64(x, r: int):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _jx_fmix(h):
    import jax.numpy as jnp
    h = h ^ (h >> np.uint64(33))
    h = h * jnp.uint64(_XXP2)
    h = h ^ (h >> np.uint64(29))
    h = h * jnp.uint64(_XXP3)
    return h ^ (h >> np.uint64(32))


def xxhash64_long_lane(lane, seed):
    """Device Spark XXH64.hashLong over a uint64 lane; `seed` is a
    uint64 lane (per-row chaining across columns)."""
    import jax.numpy as jnp
    h = seed + jnp.uint64((_XXP5 + 8) & _M64)
    k1 = _jx_rotl64(lane * jnp.uint64(_XXP2), 31) * jnp.uint64(_XXP1)
    h = h ^ k1
    h = _jx_rotl64(h, 27) * jnp.uint64(_XXP1) + jnp.uint64(_XXP4)
    return _jx_fmix(h)


def xxhash64_int_lane(lane, seed):
    """Device Spark XXH64.hashInt over a uint64 lane holding the
    zero-extended 32-bit value."""
    import jax.numpy as jnp
    h = seed + jnp.uint64((_XXP5 + 4) & _M64)
    h = h ^ (lane * jnp.uint64(_XXP1))
    h = _jx_rotl64(h, 23) * jnp.uint64(_XXP2) + jnp.uint64(_XXP3)
    return _jx_fmix(h)
