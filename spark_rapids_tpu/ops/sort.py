"""Multi-key sort kernels (the cuDF Table.orderBy analogue).

Reference: GpuSortExec / GpuSortOrder (GpuSortExec.scala) sorts via cuDF's
radix/merge sort with per-key ascending/descending + null ordering.

TPU-first realization: every key column is mapped to an *order lane* — an
integer (or float) lane whose ascending order equals the requested logical
order — and an operand-capped lexsort chain (ops/segments.py) produces
the permutation, so no emitted sort exceeds the configured operand
budget (TPU sort compile time scales with operand count):

  * ints/dates/timestamps/bools: the lane is the value itself (descending =
    bitwise negation on the unsigned view, exact for all values incl. MIN).
  * DOUBLE (int64-bits storage): IEEE-754 total-order bit trick
    (groupby._bits_total_order) makes NaN sort above +inf, matching Spark.
  * strings: dictionary codes are unordered, so the host computes each
    dictionary's rank permutation (tiny) and the lane is `ranks[code]`.
  * nulls-first/last: an int8 null lane ordered before its value lane.
  * padding rows always sink to the end (liveness is the primary lane).

The permutation gather is the expensive part on TPU; sort is only used
where the plan truly needs order (SortExec, sort-merge structures, window
partitioning) — filters and aggregations never pay it (see groupby_trace).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..config import TpuConf, DEFAULT_CONF
from .groupby import _bits_total_order
from .kernels import compute_view


class SortKey(NamedTuple):
    """Ordering spec for one key column (Spark SortOrder analogue)."""
    col_index: int
    ascending: bool = True
    nulls_first: bool = True     # Spark default: NULLS FIRST for ASC


def dictionary_ranks(dictionary: Optional[pa.Array]) -> np.ndarray:
    """rank lane table: ranks[code] = position of the code's string in the
    sorted dictionary (unicode code point order, Spark's string order)."""
    if dictionary is None or len(dictionary) == 0:
        return np.zeros(1, np.int32)
    order = pc.sort_indices(dictionary).to_numpy(zero_copy_only=False)
    ranks = np.empty(len(dictionary), np.int32)
    ranks[order] = np.arange(len(dictionary), dtype=np.int32)
    return ranks


def _to_unsigned_comparable(lane: jax.Array) -> jax.Array:
    """Int lane -> unsigned lane with the same order (so descending can be
    exact bitwise negation, incl. at the type's MIN value)."""
    if lane.dtype == jnp.bool_:
        return lane.astype(jnp.uint8)
    w = np.dtype(lane.dtype).itemsize
    ubits = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[w]
    if not np.issubdtype(np.dtype(lane.dtype), np.signedinteger):
        return lane.astype(ubits)
    sign = 1 << (8 * w - 1)
    return lane.astype(ubits) ^ jnp.asarray(sign, ubits)


def order_lanes(col: DeviceColumn, asc: bool, nulls_first: bool,
                rank_table: Optional[jax.Array] = None) -> List[jax.Array]:
    """[null lane, value lane] both ascending-comparable for the requested
    order."""
    dt = col.dtype
    data = col.data
    if isinstance(dt, t.StringType):
        if rank_table is None:
            # ORDER-PRESERVING dictionary (ops/encodings.py): codes ARE
            # ranks, so the per-row rank-table gather disappears
            lane = _to_unsigned_comparable(data)
        else:
            lane = rank_table[jnp.clip(data, 0, rank_table.shape[0] - 1)]
            lane = _to_unsigned_comparable(lane)
    elif isinstance(dt, t.DoubleType):
        cv = compute_view(data, dt)
        if cv.dtype == jnp.float64:
            # computed f64 lane: total-order via bit tricks on the bitcast
            # is unavailable (no f64->i64 bitcast on TPU); order by value
            # with NaN pushed to the top explicitly
            isnan = jnp.isnan(cv)
            lane = jnp.where(isnan, jnp.float64(np.inf), cv)
            nan_lane = isnan.astype(jnp.uint8)
            lanes = [nan_lane, lane]
            if not asc:
                lanes = [1 - nan_lane, -lane]
            null = _null_lane(col.validity, nulls_first)
            return [null] + lanes
        lane = _to_unsigned_comparable(_bits_total_order(data))
    elif isinstance(dt, t.FloatType):
        isnan = jnp.isnan(data)
        lane = jnp.where(isnan, jnp.float32(np.inf), data)
        nan_lane = isnan.astype(jnp.uint8)
        lanes = [nan_lane, lane] if asc else [1 - nan_lane, -lane]
        return [_null_lane(col.validity, nulls_first)] + lanes
    elif isinstance(dt, t.DecimalType) and dt.is_wide and \
            col.data_hi is not None:
        # two-lane host decimal128: int128 total order == lexicographic
        # (signed hi, unsigned lo)
        hi_lane = _to_unsigned_comparable(col.data_hi)
        lo_lane = data.astype(jnp.int64).astype(jnp.uint64)
        if not asc:
            hi_lane, lo_lane = ~hi_lane, ~lo_lane
        return [_null_lane(col.validity, nulls_first), hi_lane, lo_lane]
    else:
        lane = _to_unsigned_comparable(data)
    if not asc:
        lane = ~lane
    return [_null_lane(col.validity, nulls_first), lane]


def _null_lane(validity: jax.Array, nulls_first: bool) -> jax.Array:
    # ascending-comparable: smaller sorts earlier
    return jnp.where(validity, jnp.uint8(1 if nulls_first else 0),
                     jnp.uint8(0 if nulls_first else 1))


_SORT_CACHE = {}


def sort_permutation(db: DeviceBatch, keys: Sequence[SortKey],
                     conf: TpuConf = DEFAULT_CONF) -> jax.Array:
    """Permutation putting live rows in key order, padding at the end.

    Emitted as a chain of <= spark.rapids.tpu.sql.sort.maxSortOperands
    stable sorts (segments.lexsort_capped): a k-key ORDER BY used to
    lower to ONE variadic lexsort whose XLA compile time scales
    brutally with operand count (3xi64 at 1M: 164s)."""
    from ..config import MAX_SORT_OPERANDS
    from .segments import lexsort_capped
    max_ops = conf.get(MAX_SORT_OPERANDS)
    from .encodings import count_dispatch, encoding_policy, is_ordered_dict
    pol = encoding_policy(conf)
    rank_tables = {}
    for k in keys:
        col = db.columns[k.col_index]
        if isinstance(col.dtype, t.StringType):
            if pol.enabled and pol.dict_sort_scan and \
                    is_ordered_dict(col.dictionary):
                # order-preserving dictionary: order by CODES, no table
                count_dispatch("sort_codes")
                continue
            rank_tables[k.col_index] = jnp.asarray(
                dictionary_ranks(col.dictionary))
    sig = ("sortperm", db.capacity, tuple(keys), max_ops,
           tuple((str(c.data.dtype), c.dtype.simple_string,
                  c.data_hi is not None) for c in db.columns),
           tuple((i, rt.shape) for i, rt in rank_tables.items()))
    fn = _SORT_CACHE.get(sig)
    if fn is None:
        keys_t = tuple(keys)
        dtypes = [c.dtype for c in db.columns]

        def run(col_data, col_valid, live, ranks):
            lanes: List[jax.Array] = []
            for k in keys_t:
                d = col_data[k.col_index]
                hi = None
                if isinstance(d, tuple):
                    d, hi = d
                col = DeviceColumn(d, col_valid[k.col_index],
                                   dtypes[k.col_index], None, hi)
                lanes.extend(order_lanes(col, k.ascending, k.nulls_first,
                                         ranks.get(k.col_index)))
            # lexsort: last key is primary -> [minor..., major, liveness]
            sort_keys = list(reversed(lanes)) + [(~live).astype(jnp.int8)]
            return lexsort_capped(sort_keys, max_ops)

        fn = jax.jit(run)
        _SORT_CACHE[sig] = fn
    return fn(tuple(c.data if c.data_hi is None else (c.data, c.data_hi)
                    for c in db.columns),
              tuple(c.validity for c in db.columns),
              db.row_mask(), rank_tables)


def permute_batch(db: DeviceBatch, perm: jax.Array) -> DeviceBatch:
    """Gather every lane of every column through a row permutation —
    ONE stacked pass per dtype class (TPU gathers pay per-row descriptor
    latency, ~80ms per 4M-row pass; per-lane takes multiply it)."""
    from .filter import grouped_take
    lanes = []
    slots = []
    for ci, c in enumerate(db.columns):
        lanes.append(c.data)
        slots.append((ci, "d"))
        lanes.append(c.validity)
        slots.append((ci, "v"))
        if c.data_hi is not None:
            lanes.append(c.data_hi)
            slots.append((ci, "h"))
    moved = dict(zip(slots, grouped_take(lanes, perm)))
    cols = []
    for ci, c in enumerate(db.columns):
        cols.append(DeviceColumn(moved[(ci, "d")], moved[(ci, "v")],
                                 c.dtype, c.dictionary,
                                 moved.get((ci, "h"))))
    return DeviceBatch(cols, db.num_rows, list(db.names), db.origin_file)


def sort_batch(db: DeviceBatch, keys: Sequence[SortKey],
               conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    """Fully sort one device batch by the given keys."""
    if db.thin is not None:
        # sort is a pipeline SINK for late-materialized join output:
        # resolve deferred columns (one composed gather per lane source)
        # before permuting
        from .batch_ops import ensure_prefix
        db = ensure_prefix(db, conf)
    return permute_batch(db, sort_permutation(db, keys, conf))
