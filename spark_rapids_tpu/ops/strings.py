"""Byte-level string kernels over (offsets, bytes) tensors.

Reference: cuDF strings columns are first-class device (offsets, chars)
buffers and stringFunctions.scala composes ~4k LoC of kernels over them.
TPU-first re-design: row strings stay dictionary-encoded (columnar/
device.py), and byte-level kernels run over the *dictionary's* byte
tensors — O(unique) device work instead of O(rows) — with per-row results
materialized by a code gather.  This makes predicates (startswith /
endswith / contains / LIKE) fully device-evaluated while transforms
(upper/trim/substr/...) rewrite the dictionary host-side (plan/strings.py).

Byte tensors are padded to the same geometric capacity buckets as row
batches so the jit cache stays bounded; the evaluator's content-keyed aux
cache means each distinct dictionary uploads once.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar.device import bucket_capacity
from ..config import TpuConf, DEFAULT_CONF


def dict_byte_tensors(dictionary: Optional[pa.Array],
                      conf: TpuConf = DEFAULT_CONF
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(offsets int32[cap_n+1], bytes uint8[cap_b]) of a string dictionary.

    offsets[i]..offsets[i+1] bound entry i's utf-8 bytes; offsets beyond the
    dictionary repeat the total so padded entries read as empty strings.
    """
    if dictionary is None or len(dictionary) == 0:
        return (np.zeros(2, np.int32), np.zeros(1, np.uint8))
    arr = dictionary.cast(pa.string())
    joined = "".join((v.as_py() or "") for v in arr)
    raw = joined.encode("utf-8")
    lens = np.array([len(((v.as_py()) or "").encode("utf-8")) for v in arr],
                    np.int32)
    offs = np.zeros(len(arr) + 1, np.int32)
    np.cumsum(lens, out=offs[1:])
    cap_n = bucket_capacity(len(arr) + 1, conf)
    cap_b = bucket_capacity(max(len(raw), 1), conf)
    offsets = np.full(cap_n + 1, offs[-1], np.int32)
    offsets[:len(offs)] = offs
    bytes_ = np.zeros(cap_b, np.uint8)
    bytes_[:len(raw)] = np.frombuffer(raw, np.uint8)
    return offsets, bytes_


# ---------------------------------------------------------------------------
# Device kernels (traced): per-dictionary-entry boolean / int results
# ---------------------------------------------------------------------------

def char_lengths(offsets: jax.Array, bytes_: jax.Array) -> jax.Array:
    """Unicode character count per entry (Spark length()).  A char starts
    at every byte that is not a UTF-8 continuation byte (0b10xxxxxx)."""
    lead = ((bytes_ & 0xC0) != 0x80).astype(jnp.int32)
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(lead)])
    n = offsets.shape[0] - 1
    lo = jnp.clip(offsets[:n], 0, bytes_.shape[0])
    hi = jnp.clip(offsets[1:], 0, bytes_.shape[0])
    return csum[hi] - csum[lo]


def byte_lengths(offsets: jax.Array) -> jax.Array:
    return offsets[1:] - offsets[:-1]


def _entry_bounds(offsets: jax.Array):
    n = offsets.shape[0] - 1
    return offsets[:n], offsets[1:]


def match_prefix(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    """bool[n]: entry starts with `pat` (byte-wise; exact for UTF-8)."""
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    ok = (hi - lo) >= p
    cap = bytes_.shape[0]
    for k, b in enumerate(pat):
        idx = jnp.clip(lo + k, 0, cap - 1)
        ok = ok & (bytes_[idx] == np.uint8(b))
    return ok


def match_suffix(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    ok = (hi - lo) >= p
    cap = bytes_.shape[0]
    for k, b in enumerate(pat):
        idx = jnp.clip(hi - p + k, 0, cap - 1)
        ok = ok & (bytes_[idx] == np.uint8(b))
    return ok


def match_contains(offsets: jax.Array, bytes_: jax.Array,
                   pat: bytes) -> jax.Array:
    """bool[n]: `pat` occurs in entry.  Sliding window match over the byte
    lane, then a per-entry any() via prefix sums — one pass, no loops over
    entries."""
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    if p == 0:
        return jnp.ones(lo.shape, bool)
    cap = bytes_.shape[0]
    window = jnp.ones((cap,), bool)
    for k, b in enumerate(pat):
        shifted = jnp.roll(bytes_, -k) if k else bytes_
        window = window & (shifted == np.uint8(b))
    # window[j] = bytes[j:j+p] == pat (rolled bytes wrap; guard via bounds)
    wsum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(window.astype(jnp.int32))])
    # valid starts for entry i: j in [lo, hi - p]
    start_lo = jnp.clip(lo, 0, cap)
    start_hi = jnp.clip(hi - p + 1, 0, cap)
    start_hi = jnp.maximum(start_hi, start_lo)
    return (wsum[start_hi] - wsum[start_lo]) > 0


def match_equals(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    lo, hi = _entry_bounds(offsets)
    return match_prefix(offsets, bytes_, pat) & ((hi - lo) == len(pat))


# ---------------------------------------------------------------------------
# LIKE compilation (reference GpuLike via cudf regex; RegexParser.scala
# rejects untranslatable patterns — same pattern here: simple shapes run as
# device byte kernels, the general case evaluates host-side per dictionary)
# ---------------------------------------------------------------------------

class LikePlan:
    """Compiled LIKE pattern: either a device kernel composition or None
    (host fallback)."""

    def __init__(self, kind: str, parts: List[bytes]):
        self.kind = kind      # equals|prefix|suffix|contains|prefix_suffix
        self.parts = parts

    def eval_device(self, offsets, bytes_) -> jax.Array:
        if self.kind == "equals":
            return match_equals(offsets, bytes_, self.parts[0])
        if self.kind == "prefix":
            return match_prefix(offsets, bytes_, self.parts[0])
        if self.kind == "suffix":
            return match_suffix(offsets, bytes_, self.parts[0])
        if self.kind == "contains":
            return match_contains(offsets, bytes_, self.parts[0])
        if self.kind == "prefix_suffix":
            pre, suf = self.parts
            lo, hi = _entry_bounds(offsets)
            return (match_prefix(offsets, bytes_, pre) &
                    match_suffix(offsets, bytes_, suf) &
                    ((hi - lo) >= (len(pre) + len(suf))))
        raise AssertionError(self.kind)


def compile_like(pattern: str, escape: str = "\\") -> Optional[LikePlan]:
    """Device plan for simple LIKE shapes; None -> host regex fallback."""
    # tokenize honoring the escape character
    literal: List[str] = []
    tokens: List[object] = []      # str literal chunks | "%" | "_"
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            literal.append(pattern[i + 1])
            i += 2
            continue
        if c in ("%", "_"):
            if literal:
                tokens.append("".join(literal))
                literal = []
            tokens.append("%" if c == "%" else "_")
            i += 1
            continue
        literal.append(c)
        i += 1
    if literal:
        tokens.append("".join(literal))
    if any(tk == "_" for tk in tokens):
        return None
    # collapse runs of %
    coll: List[object] = []
    for tk in tokens:
        if tk == "%" and coll and coll[-1] == "%":
            continue
        coll.append(tk)
    lits = [tk for tk in coll if tk != "%"]
    enc = [s.encode("utf-8") for s in lits]
    if not coll:
        return LikePlan("equals", [b""])
    if len(lits) == 0:      # only %
        return LikePlan("contains", [b""])
    if len(lits) == 1:
        s = enc[0]
        starts = coll[0] == "%"
        ends = coll[-1] == "%"
        if not starts and not ends:
            return LikePlan("equals", [s])
        if not starts and ends:
            return LikePlan("prefix", [s])
        if starts and not ends:
            return LikePlan("suffix", [s])
        return LikePlan("contains", [s])
    if len(lits) == 2 and coll[0] != "%" and coll[-1] != "%" \
            and len(coll) == 3:
        return LikePlan("prefix_suffix", enc)
    return None


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """Full-match regex equivalent of a LIKE pattern (host fallback path)."""
    import re as _re
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    return "".join(out)
