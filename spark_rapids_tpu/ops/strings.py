"""Byte-level string kernels over (offsets, bytes) tensors.

Reference: cuDF strings columns are first-class device (offsets, chars)
buffers and stringFunctions.scala composes ~4k LoC of kernels over them.
TPU-first re-design: row strings stay dictionary-encoded (columnar/
device.py), and byte-level kernels run over the *dictionary's* byte
tensors — O(unique) device work instead of O(rows) — with per-row results
materialized by a code gather.  This makes predicates (startswith /
endswith / contains / LIKE) fully device-evaluated while transforms
(upper/trim/substr/...) rewrite the dictionary host-side (plan/strings.py).

Byte tensors are padded to the same geometric capacity buckets as row
batches so the jit cache stays bounded; the evaluator's content-keyed aux
cache means each distinct dictionary uploads once.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar.device import bucket_capacity
from ..config import TpuConf, DEFAULT_CONF


def dict_byte_tensors(dictionary: Optional[pa.Array],
                      conf: TpuConf = DEFAULT_CONF
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(offsets int32[cap_n+1], bytes uint8[cap_b]) of a string dictionary.

    offsets[i]..offsets[i+1] bound entry i's utf-8 bytes; offsets beyond the
    dictionary repeat the total so padded entries read as empty strings.
    """
    if dictionary is None or len(dictionary) == 0:
        return (np.zeros(2, np.int32), np.zeros(1, np.uint8))
    # ZERO-COPY: a pyarrow string array IS (validity, int32 offsets, utf-8
    # bytes) buffers — read them directly instead of a per-entry python
    # join (the round-2 O(unique)-interpreted-python hot path).
    arr = dictionary.cast(pa.string())
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if arr.null_count:
        arr = arr.fill_null("")
    n = len(arr)
    bufs = arr.buffers()
    raw_offs = np.frombuffer(bufs[1], np.int32)[arr.offset: arr.offset
                                                + n + 1]
    base = int(raw_offs[0])
    offs = (raw_offs.astype(np.int64) - base).astype(np.int32)
    nbytes = int(offs[-1])
    data = np.frombuffer(bufs[2], np.uint8)[base: base + nbytes]
    cap_n = bucket_capacity(n + 1, conf)
    cap_b = bucket_capacity(max(nbytes, 1), conf)
    offsets = np.full(cap_n + 1, offs[-1], np.int32)
    offsets[:n + 1] = offs
    bytes_ = np.zeros(cap_b, np.uint8)
    bytes_[:nbytes] = data
    return offsets, bytes_


# ---------------------------------------------------------------------------
# Device kernels (traced): per-dictionary-entry boolean / int results
# ---------------------------------------------------------------------------

def char_lengths(offsets: jax.Array, bytes_: jax.Array) -> jax.Array:
    """Unicode character count per entry (Spark length()).  A char starts
    at every byte that is not a UTF-8 continuation byte (0b10xxxxxx)."""
    lead = ((bytes_ & 0xC0) != 0x80).astype(jnp.int32)
    csum = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(lead)])
    n = offsets.shape[0] - 1
    lo = jnp.clip(offsets[:n], 0, bytes_.shape[0])
    hi = jnp.clip(offsets[1:], 0, bytes_.shape[0])
    return csum[hi] - csum[lo]


def byte_lengths(offsets: jax.Array) -> jax.Array:
    return offsets[1:] - offsets[:-1]


def _entry_bounds(offsets: jax.Array):
    n = offsets.shape[0] - 1
    return offsets[:n], offsets[1:]


def match_prefix(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    """bool[n]: entry starts with `pat` (byte-wise; exact for UTF-8)."""
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    ok = (hi - lo) >= p
    cap = bytes_.shape[0]
    for k, b in enumerate(pat):
        idx = jnp.clip(lo + k, 0, cap - 1)
        ok = ok & (bytes_[idx] == np.uint8(b))
    return ok


def match_suffix(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    ok = (hi - lo) >= p
    cap = bytes_.shape[0]
    for k, b in enumerate(pat):
        idx = jnp.clip(hi - p + k, 0, cap - 1)
        ok = ok & (bytes_[idx] == np.uint8(b))
    return ok


def match_contains(offsets: jax.Array, bytes_: jax.Array,
                   pat: bytes) -> jax.Array:
    """bool[n]: `pat` occurs in entry.  Sliding window match over the byte
    lane, then a per-entry any() via prefix sums — one pass, no loops over
    entries."""
    lo, hi = _entry_bounds(offsets)
    p = len(pat)
    if p == 0:
        return jnp.ones(lo.shape, bool)
    cap = bytes_.shape[0]
    window = jnp.ones((cap,), bool)
    for k, b in enumerate(pat):
        shifted = jnp.roll(bytes_, -k) if k else bytes_
        window = window & (shifted == np.uint8(b))
    # window[j] = bytes[j:j+p] == pat (rolled bytes wrap; guard via bounds)
    wsum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(window.astype(jnp.int32))])
    # valid starts for entry i: j in [lo, hi - p]
    start_lo = jnp.clip(lo, 0, cap)
    start_hi = jnp.clip(hi - p + 1, 0, cap)
    start_hi = jnp.maximum(start_hi, start_lo)
    return (wsum[start_hi] - wsum[start_lo]) > 0


def match_equals(offsets: jax.Array, bytes_: jax.Array,
                 pat: bytes) -> jax.Array:
    lo, hi = _entry_bounds(offsets)
    return match_prefix(offsets, bytes_, pat) & ((hi - lo) == len(pat))


# ---------------------------------------------------------------------------
# LIKE compilation (reference GpuLike via cudf regex; RegexParser.scala
# rejects untranslatable patterns — same pattern here: simple shapes run as
# device byte kernels, the general case evaluates host-side per dictionary)
# ---------------------------------------------------------------------------

class LikePlan:
    """Compiled LIKE pattern: either a device kernel composition or None
    (host fallback)."""

    def __init__(self, kind: str, parts: List[bytes]):
        self.kind = kind      # equals|prefix|suffix|contains|prefix_suffix
        self.parts = parts

    def eval_device(self, offsets, bytes_) -> jax.Array:
        if self.kind == "equals":
            return match_equals(offsets, bytes_, self.parts[0])
        if self.kind == "prefix":
            return match_prefix(offsets, bytes_, self.parts[0])
        if self.kind == "suffix":
            return match_suffix(offsets, bytes_, self.parts[0])
        if self.kind == "contains":
            return match_contains(offsets, bytes_, self.parts[0])
        if self.kind == "prefix_suffix":
            pre, suf = self.parts
            lo, hi = _entry_bounds(offsets)
            return (match_prefix(offsets, bytes_, pre) &
                    match_suffix(offsets, bytes_, suf) &
                    ((hi - lo) >= (len(pre) + len(suf))))
        raise AssertionError(self.kind)


def compile_like(pattern: str, escape: str = "\\") -> Optional[LikePlan]:
    """Device plan for simple LIKE shapes; None -> host regex fallback."""
    # tokenize honoring the escape character
    literal: List[str] = []
    tokens: List[object] = []      # str literal chunks | "%" | "_"
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            literal.append(pattern[i + 1])
            i += 2
            continue
        if c in ("%", "_"):
            if literal:
                tokens.append("".join(literal))
                literal = []
            tokens.append("%" if c == "%" else "_")
            i += 1
            continue
        literal.append(c)
        i += 1
    if literal:
        tokens.append("".join(literal))
    if any(tk == "_" for tk in tokens):
        return None
    # collapse runs of %
    coll: List[object] = []
    for tk in tokens:
        if tk == "%" and coll and coll[-1] == "%":
            continue
        coll.append(tk)
    lits = [tk for tk in coll if tk != "%"]
    enc = [s.encode("utf-8") for s in lits]
    if not coll:
        return LikePlan("equals", [b""])
    if len(lits) == 0:      # only %
        return LikePlan("contains", [b""])
    if len(lits) == 1:
        s = enc[0]
        starts = coll[0] == "%"
        ends = coll[-1] == "%"
        if not starts and not ends:
            return LikePlan("equals", [s])
        if not starts and ends:
            return LikePlan("prefix", [s])
        if starts and not ends:
            return LikePlan("suffix", [s])
        return LikePlan("contains", [s])
    if len(lits) == 2 and coll[0] != "%" and coll[-1] != "%" \
            and len(coll) == 3:
        return LikePlan("prefix_suffix", enc)
    return None


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """Full-match regex equivalent of a LIKE pattern (host fallback path)."""
    import re as _re
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# Device byte TRANSFORMS (round 3): upper/lower/trim/substring rewrite the
# byte tensors ON DEVICE, so high-cardinality columns (near-unique ids,
# comments) no longer serialize through a per-entry python loop
# (plan/strings.py DictTransform routes here above a size threshold).
# Entries containing non-ASCII bytes are flagged and fixed host-side
# (exact python semantics for the rare multilingual tail); substring is
# char-aware and needs no fix-up.
# ---------------------------------------------------------------------------

_TRANSFORM_CACHE: dict = {}


def _seg_ids(offsets: jax.Array, cap_b: int, n: int) -> jax.Array:
    pos = jnp.arange(cap_b, dtype=jnp.int32)
    from .search import searchsorted
    return jnp.clip(searchsorted(offsets[:n + 1], pos, side="right")
                    - 1, 0, n - 1).astype(jnp.int32)


def _pack_ranges(bytes_: jax.Array, lo: jax.Array, hi: jax.Array,
                 out_cap: int):
    """Pack per-entry byte ranges [lo, hi) into dense (offsets, bytes)."""
    lens = jnp.maximum(hi - lo, 0).astype(jnp.int32)
    out_offs = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(lens).astype(jnp.int32)])
    j = jnp.arange(out_cap, dtype=jnp.int32)
    from .search import searchsorted
    ent = jnp.clip(searchsorted(out_offs, j, side="right") - 1,
                   0, lens.shape[0] - 1)
    src = jnp.take(lo, ent) + (j - jnp.take(out_offs, ent))
    live = j < out_offs[-1]
    out_bytes = jnp.where(
        live, jnp.take(bytes_, jnp.clip(src, 0, bytes_.shape[0] - 1)),
        jnp.uint8(0))
    return out_offs, out_bytes


def _case_trace(n: int, cap_b: int, upper: bool):
    def run(offsets, bytes_):
        b = bytes_
        if upper:
            out = jnp.where((b >= 97) & (b <= 122), b - 32, b)
        else:
            out = jnp.where((b >= 65) & (b <= 90), b + 32, b)
        non_ascii = _entry_any(offsets, b >= 0x80, cap_b, n)
        return offsets, out, non_ascii
    return run


def _entry_any(offsets, flag: jax.Array, cap_b: int, n: int) -> jax.Array:
    seg = _seg_ids(offsets, cap_b, n)
    live = jnp.arange(cap_b, dtype=jnp.int32) < offsets[n]
    return jax.ops.segment_max((flag & live).astype(jnp.int32), seg,
                               num_segments=n) > 0


_ASCII_WS = (32, 9, 10, 13, 11, 12)


def _trim_trace(n: int, cap_b: int, left: bool, right: bool):
    def run(offsets, bytes_):
        cap = cap_b
        pos = jnp.arange(cap, dtype=jnp.int32)
        seg = _seg_ids(offsets, cap, n)
        ws = jnp.zeros((cap,), bool)
        for c in _ASCII_WS:
            ws = ws | (bytes_ == c)
        live = pos < offsets[n]
        lo0 = jnp.take(offsets[:n], jnp.arange(n))
        hi0 = offsets[1:n + 1]
        big = jnp.int32(cap + 1)
        # first non-ws byte position per entry
        first_nw = jax.ops.segment_min(
            jnp.where(live & ~ws, pos, big), seg, num_segments=n)
        last_nw = jax.ops.segment_max(
            jnp.where(live & ~ws, pos, jnp.int32(-1)), seg, num_segments=n)
        lo = jnp.where(jnp.asarray(left), jnp.minimum(first_nw, hi0), lo0)
        hi = jnp.where(jnp.asarray(right), last_nw + 1, hi0)
        hi = jnp.maximum(hi, lo)
        out_offs, out_bytes = _pack_ranges(bytes_, lo, hi, cap)
        non_ascii = _entry_any(offsets, bytes_ >= 0x80, cap, n)
        return out_offs, out_bytes, non_ascii
    return run


def _substr_trace(n: int, cap_b: int, pos_arg: int, length):
    def run(offsets, bytes_):
        cap = cap_b
        lead = ((bytes_ & 0xC0) != 0x80)
        idx = jnp.arange(cap, dtype=jnp.int32)
        live = idx < offsets[n]
        lead_live = lead & live
        # chars before each entry + per-entry char count (char_lengths)
        lead32 = lead_live.astype(jnp.int32)
        csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(lead32)])
        chars_before = csum[jnp.clip(offsets[:n], 0, cap)]
        nchars = csum[jnp.clip(offsets[1:n + 1], 0, cap)] - chars_before
        # byte position of the r-th char (global rank): stable compaction
        char_pos = jnp.argsort(jnp.where(lead_live, idx, jnp.int32(cap)),
                               stable=True).astype(jnp.int32)
        total_chars = csum[-1]

        if pos_arg > 0:
            start = jnp.minimum(jnp.int32(pos_arg - 1), nchars)
        elif pos_arg == 0:
            start = jnp.zeros((n,), jnp.int32)
        else:
            start = jnp.maximum(nchars + jnp.int32(pos_arg), 0)
        if length is None:
            end = nchars
        elif length <= 0:
            end = start
        else:
            end = jnp.minimum(start + jnp.int32(length), nchars)
        end = jnp.maximum(end, start)

        def char_byte(rank):
            # byte offset of global char rank; ranks at the end map to
            # the bytes' end
            r = jnp.clip(rank, 0, cap - 1)
            p = jnp.take(char_pos, r)
            return jnp.where(rank >= total_chars, offsets[n], p)

        lo = char_byte(chars_before + start)
        hi = char_byte(chars_before + end)
        # chars of the NEXT entry start exactly at this entry's byte end,
        # so an end-rank inside the next entry clamps to this entry's hi
        hi = jnp.minimum(hi, offsets[1:n + 1])
        lo = jnp.minimum(lo, offsets[1:n + 1])
        out_offs, out_bytes = _pack_ranges(bytes_, lo, hi, cap)
        return out_offs, out_bytes, jnp.zeros((n,), bool)
    return run


def transform_dict_device(dictionary: pa.Array, kind: str, args: tuple,
                          conf: TpuConf = DEFAULT_CONF) -> pa.Array:
    """Transform every dictionary entry on device; ONE fetch builds the
    output pa.StringArray from the packed buffers.  `kind`:
    upper|lower|trim|ltrim|rtrim|substr(pos, len)."""
    offs_np, bytes_np = dict_byte_tensors(dictionary, conf)
    n = len(dictionary)
    cap_b = bytes_np.shape[0]
    sig = (kind, args, offs_np.shape[0], cap_b, n)
    fn = _TRANSFORM_CACHE.get(sig)
    if fn is None:
        if kind in ("upper", "lower"):
            fn = jax.jit(_case_trace(n, cap_b, kind == "upper"))
        elif kind in ("trim", "ltrim", "rtrim"):
            fn = jax.jit(_trim_trace(n, cap_b, kind != "rtrim",
                                     kind != "ltrim"))
        elif kind == "substr":
            fn = jax.jit(_substr_trace(n, cap_b, args[0], args[1]))
        else:
            raise ValueError(kind)
        if len(_TRANSFORM_CACHE) > 512:
            _TRANSFORM_CACHE.clear()
        _TRANSFORM_CACHE[sig] = fn
    out_offs, out_bytes, fixup = jax.device_get(
        fn(jnp.asarray(offs_np), jnp.asarray(bytes_np)))
    out_offs = np.asarray(out_offs)[:n + 1]
    total = int(out_offs[-1])
    data = np.asarray(out_bytes)[:total].tobytes()
    arr = pa.Array.from_buffers(
        pa.utf8(), n,
        [None, pa.py_buffer(out_offs.astype(np.int32).tobytes()),
         pa.py_buffer(data)])
    fix = np.asarray(fixup)[:n]
    if fix.any():
        # exact python semantics for entries with non-ASCII bytes
        vals = arr.to_pylist()
        src = dictionary.cast(pa.string())
        for i in np.nonzero(fix)[0].tolist():
            s = src[i].as_py()
            if s is None:
                vals[i] = None
                continue
            if kind == "upper":
                vals[i] = s.upper()
            elif kind == "lower":
                vals[i] = s.lower()
            elif kind == "trim":
                vals[i] = s.strip()
            elif kind == "ltrim":
                vals[i] = s.lstrip()
            elif kind == "rtrim":
                vals[i] = s.rstrip()
        arr = pa.array(vals, pa.string())
    # null entries: reuse the SOURCE validity bitmap directly (nulls were
    # encoded as empty strings in the byte tensors) — no pylist loop
    if dictionary.null_count:
        src = dictionary.cast(pa.string())
        if isinstance(src, pa.ChunkedArray):
            src = src.combine_chunks()
        if src.offset == 0:
            bufs = arr.buffers()
            arr = pa.Array.from_buffers(
                pa.utf8(), n, [src.buffers()[0], bufs[1], bufs[2]],
                null_count=src.null_count)
        else:                 # sliced source: bit-shifted bitmap; rebuild
            import pyarrow.compute as pc
            arr = pc.if_else(pc.is_valid(src), arr,
                             pa.scalar(None, pa.string()))
    return arr
