"""Compressed device-resident execution: the encoding-aware layer.

"GPU Acceleration of SQL Analytics on Compressed Data" (PAPERS.md) shows
operators evaluated IN the encoded domain beat decode-then-compute by
large factors; Flare motivates keeping the whole encoded pipeline inside
one fused program.  This engine already stores strings as dictionary
codes and (since PR 6) keeps encodings alive across joins — but filters,
comparisons and ORDER BY still paid a per-row DECODE gather (a
dictionary-sized remap/rank table read at row capacity), and integer
lanes always rode at full logical width.  This module is the
encoded-execution layer behind ``spark.rapids.tpu.sql.encoded.*``:

  * **Code-space dictionary predicates.**  A literal predicate over a
    dictionary column translates the LITERAL through the dictionary once
    at prepare time (host, dictionary-sized, cached per dictionary
    identity) instead of remapping every row: equality/IN become
    ``code == c`` comparisons, ``<``/``<=`` ranges become one scalar
    rank-bound comparison when the dictionary is ORDER-PRESERVING and
    fall back to a per-dictionary rank-table gather (the decode rung,
    still on device) when it is not.

  * **Order-preserving scan dictionaries.**  The host->device boundary
    sorts each dictionary (columnar/device.py) so codes ARE ranks:
    ORDER BY on dictionary columns skips its rank-table gather
    (ops/sort.py) and range predicates take the scalar-bound path.
    A pure representation change — decoded values are identical.

  * **FOR-narrowed integer lanes.**  Integer/date scan columns whose
    live range fits a smaller signed dtype upload VALUE-PRESERVING
    narrow lanes (no bias: every consumer that widens via a plain
    dtype promotion still computes exact values, so decode is a fused
    ``convert`` sunk to the first consumer that truly needs width).
    Comparisons evaluate in the narrow dtype with runtime range guards
    (plan/expressions.py), and two-narrow-lane arithmetic promotes only
    to the exact width the result needs.

  * **RLE run-domain predicates.**  A run-length-encoded lane evaluates
    a predicate per RUN (run count, not row count) and expands the
    verdict mask by rank search — the bench.py --encodings A/B
    quantifies it against decode-first.

Fallback-safety mirrors the Pallas tier (ops/pallas/): every encoded
dispatch NEGOTIATES, fires the existing `kernel` chaos site, and an
injected OOM sheds the dispatch onto the decoded path bit-identically
(`tpu_encoded_dispatch_total{outcome=oom_shed}`).  With
``encoded.execution.enabled=false`` no encoded path is consulted at all
and plans/results are bit-identical to the pre-encoding engine.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..config import (ENCODED_DICT_PREDICATES, ENCODED_DICT_SORT_SCAN,
                      ENCODED_EXECUTION, ENCODED_IN_MAX_CODES,
                      ENCODED_NARROW_LANES, TpuConf)


@dataclasses.dataclass(frozen=True)
class EncodingPolicy:
    """Resolved per-conf encoded-execution decisions (static per query)."""
    enabled: bool
    dict_predicates: bool
    dict_sort_scan: bool
    narrow_lanes: bool
    in_max_codes: int

    @property
    def any_enabled(self) -> bool:
        return self.enabled and (self.dict_predicates or
                                 self.dict_sort_scan or self.narrow_lanes)


NO_ENCODING = EncodingPolicy(False, False, False, False, 0)


def encoding_policy(conf: TpuConf) -> EncodingPolicy:
    """The resolved policy for this conf, cached on the conf instance
    (the disabled path is one dict hit)."""
    pol = conf._cache.get("__encoding_policy")
    if pol is not None:
        return pol
    if not conf.get(ENCODED_EXECUTION):
        pol = NO_ENCODING
    else:
        def mode(entry, auto: bool) -> bool:
            v = str(conf.get(entry)).upper()
            return auto if v == "AUTO" else v == "ON"
        pol = EncodingPolicy(
            enabled=True,
            dict_predicates=mode(ENCODED_DICT_PREDICATES, True),
            dict_sort_scan=bool(conf.get(ENCODED_DICT_SORT_SCAN)),
            narrow_lanes=mode(ENCODED_NARROW_LANES, True),
            in_max_codes=int(conf.get(ENCODED_IN_MAX_CODES)))
    conf._cache["__encoding_policy"] = pol
    return pol


def encoding_discriminant(conf: TpuConf) -> Optional[tuple]:
    """Encoded-execution discriminant for compiled-program / upload cache
    keys: two confs whose RESOLVED policies differ must never share an
    executable or a device upload (the encoded representation changes
    lane dtypes and dictionary order).  None when fully off — the key
    stays byte-identical to pre-encoding builds."""
    p = encoding_policy(conf)
    if not p.any_enabled:
        return None
    return ("enc", p.dict_predicates, p.dict_sort_scan, p.narrow_lanes,
            p.in_max_codes)


# ---------------------------------------------------------------------------
# Dispatch bookkeeping: metrics + the `kernel` chaos site (fallback rung)
# ---------------------------------------------------------------------------

def count_dispatch(site: str, outcome: str = "encoded") -> None:
    from ..obs.registry import ENCODED_DISPATCH
    ENCODED_DISPATCH.inc(site=site, outcome=outcome)


def count_decode(site: str, nbytes: int) -> None:
    """One emitted decode pass (rank/remap gather, full-width widen)."""
    from ..obs.registry import DECODE_BYTES, ENCODED_DISPATCH
    DECODE_BYTES.inc(int(nbytes), site=site)
    ENCODED_DISPATCH.inc(site=site, outcome="decode")


def elect_encoded(conf: TpuConf, site: str) -> bool:
    """Final election for one encoded dispatch: fires the existing
    `kernel` chaos site (kernel=<site> names the encoded dispatch in the
    injected-fault record).  An injected OOM there is the shed signal —
    the dispatch falls back to the DECODED tier bit-identically
    (outcome=oom_shed) instead of failing the query; fatal/error kinds
    propagate to their usual recovery ladders."""
    from ..runtime.faults import get_active_injector, get_injector
    inj = get_injector(conf)
    if not inj.enabled:
        inj = get_active_injector()
    if inj.enabled:
        from ..runtime.memory import TpuRetryOOM
        try:
            inj.fire("kernel", kernel=site, mode="encoded")
        except TpuRetryOOM:
            count_dispatch(site, "oom_shed")
            from ..obs.tracer import get_active
            get_active().instant("kernel_fallback", "runtime", kernel=site,
                                 reason="oom")
            return False
    count_dispatch(site)
    return True


# ---------------------------------------------------------------------------
# Dictionary utilities (host side, cached per dictionary identity)
# ---------------------------------------------------------------------------
# The SAME pa.Array dictionary object flows through every batch of a
# scan, so O(dictionary) host work — orderedness checks, literal code
# lookups, rank tables — is computed once per dictionary.  Entries pin
# the dictionary so id() reuse cannot alias a stale hit.  All caches
# share one lock: the serving plane prepares plans concurrently, and a
# half-built entry must never be observable (the remap_codes_into race).

_DICT_META_LOCK = threading.RLock()
_ORDERED_CACHE: dict = {}
_UNIQUE_CACHE: dict = {}
_LITERAL_CODE_CACHE: dict = {}
_RANK_BOUND_CACHE: dict = {}
_RANK_TABLE_CACHE: dict = {}


def clear_dict_caches() -> None:
    with _DICT_META_LOCK:
        for c in (_ORDERED_CACHE, _UNIQUE_CACHE, _LITERAL_CODE_CACHE,
                  _RANK_BOUND_CACHE, _RANK_TABLE_CACHE):
            c.clear()


def _cache_get(cache: dict, key, pin):
    hit = cache.get(key)
    if hit is not None and hit[0] is pin:
        return hit
    return None


def _cache_put(cache: dict, key, pin, value) -> None:
    if len(cache) > 4096:
        cache.clear()
    cache[key] = (pin, value)


def is_ordered_dict(d: Optional[pa.Array]) -> bool:
    """True when the dictionary is STRICTLY increasing in Spark string
    order (unicode code points == UTF-8 byte order): codes are then
    rank-equivalent, so code comparisons ARE value comparisons."""
    if d is None:
        return False
    if len(d) <= 1:
        return True
    with _DICT_META_LOCK:
        hit = _cache_get(_ORDERED_CACHE, id(d), d)
        if hit is not None:
            return hit[1]
        s = d.cast(pa.string())
        ordered = bool(pc.all(
            pc.less(s.slice(0, len(s) - 1), s.slice(1))).as_py())
        _cache_put(_ORDERED_CACHE, id(d), d, ordered)
        return ordered


def is_unique_dict(d: Optional[pa.Array]) -> bool:
    """Duplicate-free dictionary: value equality == code equality, the
    legality gate for code-space equality/IN (a COMPUTED dictionary —
    e.g. a substring projection's — may repeat values, and a single
    translated code would miss the duplicates' rows)."""
    if d is None:
        return False
    if len(d) <= 1:
        return True
    with _DICT_META_LOCK:
        hit = _cache_get(_UNIQUE_CACHE, id(d), d)
        if hit is not None:
            return hit[1]
        u = len(pc.unique(d.cast(pa.string()))) == len(d)
        _cache_put(_UNIQUE_CACHE, id(d), d, u)
        return u


#: literal-absent sentinel: never equals a valid code (>= 0) and never
#: equals the -1 "string absent from target dictionary" remap marker
ABSENT_CODE = -2


def literal_code(d: Optional[pa.Array], value: str) -> int:
    """Code of `value` in the dictionary, or ABSENT_CODE.  One host
    lookup per (dictionary identity, value) — the prepare-time literal
    translation code-space equality predicates ride on."""
    if d is None or len(d) == 0:
        return ABSENT_CODE
    key = (id(d), value)
    with _DICT_META_LOCK:
        hit = _cache_get(_LITERAL_CODE_CACHE, key, d)
        if hit is not None:
            return hit[1]
        idx = pc.index(d.cast(pa.string()), pa.scalar(value)).as_py()
        code = ABSENT_CODE if idx is None or idx < 0 else int(idx)
        _cache_put(_LITERAL_CODE_CACHE, key, d, code)
        return code


def rank_bounds(d: Optional[pa.Array], value: str):
    """(count_less, count_less_eq) of `value` against the dictionary's
    entries in Spark string order — the scalar bounds range predicates
    compare ranks (or, for an ordered dictionary, codes) against:
        col <  value  <=>  rank(col) <  count_less
        col <= value  <=>  rank(col) <  count_less_eq
    """
    if d is None or len(d) == 0:
        return 0, 0
    key = (id(d), value)
    with _DICT_META_LOCK:
        hit = _cache_get(_RANK_BOUND_CACHE, key, d)
        if hit is not None:
            return hit[1]
        s = d.cast(pa.string())
        less = int(pc.sum(pc.less(s, pa.scalar(value)),
                          min_count=0).as_py() or 0)
        leq = int(pc.sum(pc.less_equal(s, pa.scalar(value)),
                         min_count=0).as_py() or 0)
        _cache_put(_RANK_BOUND_CACHE, key, d, (less, leq))
        return less, leq


def rank_table(d: Optional[pa.Array]) -> np.ndarray:
    """ranks[code] -> rank of the code's string in the sorted dictionary
    (ops/sort.dictionary_ranks), cached per identity — the decode rung
    for range predicates over UNORDERED dictionaries."""
    if d is None or len(d) == 0:
        return np.zeros(1, np.int32)
    with _DICT_META_LOCK:
        hit = _cache_get(_RANK_TABLE_CACHE, id(d), d)
        if hit is not None:
            return hit[1]
        from .sort import dictionary_ranks
        ranks = dictionary_ranks(d)
        _cache_put(_RANK_TABLE_CACHE, id(d), d, ranks)
        return ranks


def sort_dictionary_encode(arr: pa.Array):
    """Dictionary-encode an arrow string array with an ORDER-PRESERVING
    (sorted, duplicate-free) dictionary: -> (codes int32 np array with
    nulls as 0, dictionary pa.StringArray, null mask np bool).  The
    host->device boundary's encoded upload (columnar/device.py)."""
    if not pa.types.is_dictionary(arr.type):
        arr = pc.dictionary_encode(arr)
    d = arr.dictionary.cast(pa.string())
    codes_arr = arr.indices.fill_null(0) if arr.null_count else arr.indices
    codes = codes_arr.to_numpy(zero_copy_only=False).astype(np.int32)
    if len(d) == 0:
        return codes, d, None
    order = pc.sort_indices(d).to_numpy(zero_copy_only=False)
    sorted_d = d.take(pa.array(order, pa.int64()))
    # arrow dictionary_encode already dedupes, so sorted == strictly
    # increasing; remap codes through the inverse permutation
    remap = np.empty(len(d), np.int32)
    remap[order] = np.arange(len(d), dtype=np.int32)
    return remap[codes], sorted_d, None


# ---------------------------------------------------------------------------
# FOR-narrowed integer lanes (value-preserving dtype demotion)
# ---------------------------------------------------------------------------
# No bias: the narrow lane holds the exact values, so ANY consumer that
# widens via a plain dtype promotion (expression casts, concat dtype
# promotion, astype in hashing/sort/host-fetch) computes exact results —
# correctness never depends on the encoding metadata, which is why the
# legality pass can stay an optimization, not a safety requirement.

_NARROW_STEPS = {8: (np.int8, np.int16, np.int32),
                 4: (np.int8, np.int16),
                 2: (np.int8,)}


def narrow_np_dtype(lo: int, hi: int, base: np.dtype):
    """Smallest signed dtype (< base width) exactly holding [lo, hi],
    or None when no narrowing applies."""
    base = np.dtype(base)
    if base.kind != "i" or base.itemsize not in _NARROW_STEPS:
        return None
    for cand in _NARROW_STEPS[base.itemsize]:
        info = np.iinfo(cand)
        if info.min <= lo and hi <= info.max:
            return np.dtype(cand)
    return None


def narrow_widths(itemsize_a: int, itemsize_b: int, op: str) -> int:
    """Itemsize (bytes) that EXACTLY represents op over two signed
    integer lanes: add/sub need one extra bit (double the wider side),
    mul needs the sum of the widths.  The overflow-checked promotion
    rule narrow arithmetic uses — dtype-only, so compiled programs keyed
    on lane dtypes stay value-agnostic."""
    if op == "mul":
        need = itemsize_a + itemsize_b
    else:
        need = 2 * max(itemsize_a, itemsize_b)
    w = 1
    while w < need:
        w *= 2
    return w


_SIGNED_BY_SIZE = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32, 8: jnp.int64}


def exact_arith_dtype(a_dtype, b_dtype, op: str, logical_dtype):
    """jnp dtype for exact narrow arithmetic, or None when the exact
    width is not narrower than the logical compute dtype (promote as
    usual — the 'only when the live range requires it' rule)."""
    a, b = np.dtype(a_dtype), np.dtype(b_dtype)
    if a.kind != "i" or b.kind != "i":
        return None
    logical = np.dtype(logical_dtype)
    if logical.kind != "i":
        return None
    w = narrow_widths(a.itemsize, b.itemsize, op)
    if w >= logical.itemsize or w > 8:
        return None
    return _SIGNED_BY_SIZE[w]


# ---------------------------------------------------------------------------
# RLE run-domain predicates (the bench --encodings A/B primitive)
# ---------------------------------------------------------------------------

def rle_predicate_mask(values: jnp.ndarray, lengths: jnp.ndarray,
                       n: int, pred) -> jnp.ndarray:
    """Row mask of `pred` over an RLE lane WITHOUT decoding: the
    predicate evaluates per RUN (run count, not row count) and the
    verdict expands to rows by rank search against the run ends —
    gathers a bool per row from a runs-sized table instead of
    materializing the decoded value lane first."""
    verdict = pred(values)
    ends = jnp.cumsum(lengths.astype(jnp.int32))
    rows = jnp.arange(n, dtype=jnp.int32)
    run_of_row = jnp.searchsorted(ends, rows, side="right")
    run_of_row = jnp.clip(run_of_row, 0, values.shape[0] - 1)
    in_range = rows < ends[-1]
    return jnp.take(verdict, run_of_row) & in_range


# ---------------------------------------------------------------------------
# Narrow-domain comparison (runtime range guards)
# ---------------------------------------------------------------------------

def narrow_compare(symbol: str, narrow_lane: jnp.ndarray,
                   wide_other: jnp.ndarray) -> jnp.ndarray:
    """Compare a FOR-narrowed lane against a full-width lane WITHOUT
    widening the rows: the wide side (a literal broadcast — possibly a
    lifted runtime scalar, so the guards must be data, not trace-time
    branches) casts DOWN into the narrow dtype, with range guards
    supplying the answer wherever the cast would wrap.  Exact for every
    int64 value of the wide side."""
    info = np.iinfo(np.dtype(narrow_lane.dtype))
    lo = jnp.asarray(info.min, wide_other.dtype)
    hi = jnp.asarray(info.max, wide_other.dtype)
    below = wide_other < lo          # other smaller than every lane value
    above = wide_other > hi          # other larger than every lane value
    dn = jnp.clip(wide_other, lo, hi).astype(narrow_lane.dtype)
    if symbol == "=":
        core, if_below, if_above = narrow_lane == dn, False, False
    elif symbol == "!=":
        core, if_below, if_above = narrow_lane != dn, True, True
    elif symbol == "<":
        core, if_below, if_above = narrow_lane < dn, False, True
    elif symbol == "<=":
        core, if_below, if_above = narrow_lane <= dn, False, True
    elif symbol == ">":
        core, if_below, if_above = narrow_lane > dn, True, False
    elif symbol == ">=":
        core, if_below, if_above = narrow_lane >= dn, True, False
    else:
        raise ValueError(f"narrow_compare: unknown symbol {symbol!r}")
    out = jnp.where(below, jnp.asarray(if_below, bool),
                    jnp.where(above, jnp.asarray(if_above, bool), core))
    return out


def common_narrow_dtype(a_dtype, b_dtype):
    """Widest of two signed narrow dtypes (value-preserving common
    compare dtype), or None when either side is not a narrow int."""
    a, b = np.dtype(a_dtype), np.dtype(b_dtype)
    if a.kind != "i" or b.kind != "i":
        return None
    return _SIGNED_BY_SIZE[max(a.itemsize, b.itemsize)]
