"""Device calendar kernels over DATE (int32 days) / TIMESTAMP (int64 us UTC).

Reference: datetimeExpressions.scala + GpuTimeZoneDB JNI (device timezone
transition tables).  This engine keeps Spark's internal representations
(days since epoch / micros since epoch UTC), so every calendar field is
pure integer arithmetic — branchless civil-calendar conversion (the
Gregorian era decomposition) vectorizes perfectly on the VPU; no lookup
tables, no host trips.  Non-UTC session timezones are not yet supported
(the reference gates non-UTC behind GpuTimeZoneDB the same way).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _floordiv(a, b):
    return a // b     # jnp int division floors for int inputs (numpy rules)


def civil_from_days(days: jax.Array):
    """(year, month, day) from days since 1970-01-01 (proleptic Gregorian).

    Branchless era decomposition; exact for the whole int32 day range.
    """
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)                       # [1, 12]
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    """days since epoch from (year, month, day); inverse of civil_from_days."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400                                      # [0, 399]
    mp = (m.astype(jnp.int64) + 9) % 12                      # [0, 11]
    doy = (153 * mp + 2) // 5 + d.astype(jnp.int64) - 1      # [0, 365]
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def is_leap(y: jax.Array) -> jax.Array:
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


def days_in_month(y: jax.Array, m: jax.Array) -> jax.Array:
    base = jnp.asarray([0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                       jnp.int32)
    d = base[jnp.clip(m, 0, 12)]
    return jnp.where((m == 2) & is_leap(y), 29, d)


def day_of_year(days: jax.Array) -> jax.Array:
    y, _, _ = civil_from_days(days)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (days.astype(jnp.int32) - jan1 + 1)


def day_of_week_sunday1(days: jax.Array) -> jax.Array:
    """Spark dayofweek(): 1 = Sunday ... 7 = Saturday.
    1970-01-01 was a Thursday."""
    dow0 = (days.astype(jnp.int64) + 4) % 7        # 0 = Sunday
    dow0 = jnp.where(dow0 < 0, dow0 + 7, dow0)
    return (dow0 + 1).astype(jnp.int32)


def weekday_monday0(days: jax.Array) -> jax.Array:
    """Spark weekday(): 0 = Monday ... 6 = Sunday."""
    w = (days.astype(jnp.int64) + 3) % 7
    w = jnp.where(w < 0, w + 7, w)
    return w.astype(jnp.int32)


def iso_week(days: jax.Array) -> jax.Array:
    """ISO-8601 week number (Spark weekofyear)."""
    wd = weekday_monday0(days)                     # 0=Mon..6=Sun
    nearest_thursday = days.astype(jnp.int32) + (3 - wd)
    y, _, _ = civil_from_days(nearest_thursday)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return ((nearest_thursday - jan1) // 7 + 1).astype(jnp.int32)


def add_months(days: jax.Array, months: jax.Array) -> jax.Array:
    """Spark add_months: day clamped to the target month's last day."""
    y, m, d = civil_from_days(days)
    total = y.astype(jnp.int64) * 12 + (m.astype(jnp.int64) - 1) \
        + months.astype(jnp.int64)
    ny = jnp.where(total >= 0, total, total - 11) // 12
    nm = (total - ny * 12 + 1).astype(jnp.int32)
    ny = ny.astype(jnp.int32)
    nd = jnp.minimum(d, days_in_month(ny, nm))
    return days_from_civil(ny, nm, nd)


def last_day(days: jax.Array) -> jax.Array:
    y, m, _ = civil_from_days(days)
    return days_from_civil(y, m, days_in_month(y, m))


def trunc_date(days: jax.Array, unit: str) -> jax.Array:
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(y)
    if unit in ("year", "yyyy", "yy"):
        return days_from_civil(y, one, one)
    if unit in ("quarter",):
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, one)
    if unit in ("month", "mon", "mm"):
        return days_from_civil(y, m, one)
    if unit in ("week",):
        return (days.astype(jnp.int32) - weekday_monday0(days))
    raise ValueError(f"unsupported trunc unit {unit}")


_US_PER_DAY = 86400_000_000


def ts_to_days(us: jax.Array) -> jax.Array:
    """micros since epoch -> days since epoch (floor, UTC)."""
    us = us.astype(jnp.int64)
    return jnp.where(us >= 0, us // _US_PER_DAY,
                     -((-us + _US_PER_DAY - 1) // _US_PER_DAY)
                     ).astype(jnp.int32)


def ts_time_of_day_us(us: jax.Array) -> jax.Array:
    us = us.astype(jnp.int64)
    rem = us % _US_PER_DAY
    return jnp.where(rem < 0, rem + _US_PER_DAY, rem)
