"""Ragged (ARRAY<primitive>) device kernels over values+offsets lanes.

Reference: cuDF LIST columns are first-class device types consumed by
collectionOperations.scala, higherOrderFunctions.scala and
GpuGenerateExec.scala:829.  XLA has no ragged tensors, so the TPU-native
layout is the SURVEY §7c dual-tensor design: a flat VALUES lane (own
static bucket) plus an int32 offsets lane per row; every kernel below is
a composition of segment primitives (searchsorted row-ids, segment
min/max/sum, masked compaction) that XLA fuses — no per-row loops, no
host round trips.

The segment workhorse: `row_ids(offsets, vcap)` maps each value-lane slot
to its owning row via one vectorized searchsorted; everything else rides
`jax.ops.segment_*` over that id lane.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceColumn


def row_ids(offsets: jax.Array, vcap: int) -> jax.Array:
    """Owning row index per values-lane slot; slots past the last live
    value map to the (invalid) final row id."""
    pos = jnp.arange(vcap, dtype=jnp.int32)
    from .search import searchsorted
    return (searchsorted(offsets, pos, side="right") - 1) \
        .astype(jnp.int32)


def value_live(offsets: jax.Array, vcap: int, num_rows) -> jax.Array:
    """True for value slots belonging to live rows (< num_rows)."""
    end = offsets[jnp.int32(num_rows)]
    return jnp.arange(vcap, dtype=jnp.int32) < end


def sizes(col: DeviceColumn) -> Tuple[jax.Array, jax.Array]:
    """Per-row element count (int32) + row validity."""
    off = col.offsets
    n = off[1:] - off[:-1]
    return n.astype(jnp.int32), col.validity


def get_item(col: DeviceColumn, index: int) -> Tuple[jax.Array, jax.Array]:
    """array[index] per row: (values gathered, validity)."""
    off = col.offsets
    lens = off[1:] - off[:-1]
    idx = off[:-1] + jnp.int32(index)
    ok = col.validity & (jnp.int32(index) >= 0) & (jnp.int32(index) < lens)
    safe = jnp.clip(idx, 0, col.value_capacity - 1)
    data = jnp.take(col.data, safe)
    valid = ok & jnp.take(col.elem_valid, safe)
    return data, valid


def contains(col: DeviceColumn, needle, num_rows) -> Tuple[jax.Array,
                                                           jax.Array]:
    """array_contains(arr, v) — Spark: null array -> null; true if any
    element equals v; else null if the array has null elements, false
    otherwise."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    live = value_live(col.offsets, vcap, num_rows)
    cap = col.capacity
    hit = (col.data == needle) & col.elem_valid & live
    has_hit = jax.ops.segment_max(hit.astype(jnp.int32), rid,
                                  num_segments=cap) > 0
    has_null = jax.ops.segment_max(
        ((~col.elem_valid) & live).astype(jnp.int32), rid,
        num_segments=cap) > 0
    data = has_hit
    valid = col.validity & (has_hit | ~has_null)
    return data, valid


def _segment_minmax(col: DeviceColumn, num_rows, is_min: bool
                    ) -> Tuple[jax.Array, jax.Array]:
    """array_min/array_max ignoring null elements; empty/all-null -> null."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    live = value_live(col.offsets, vcap, num_rows) & col.elem_valid
    cap = col.capacity
    info_dtype = col.data.dtype
    if jnp.issubdtype(info_dtype, jnp.floating):
        ident = jnp.array(jnp.inf if is_min else -jnp.inf, info_dtype)
    else:
        ii = jnp.iinfo(info_dtype)
        ident = jnp.array(ii.max if is_min else ii.min, info_dtype)
    vals = jnp.where(live, col.data, ident)
    seg = jax.ops.segment_min if is_min else jax.ops.segment_max
    out = seg(vals, rid, num_segments=cap)
    any_val = jax.ops.segment_max(live.astype(jnp.int32), rid,
                                  num_segments=cap) > 0
    return out, col.validity & any_val


def array_min(col, num_rows):
    return _segment_minmax(col, num_rows, True)


def array_max(col, num_rows):
    return _segment_minmax(col, num_rows, False)


def sort_within(col: DeviceColumn, num_rows, asc: bool = True
                ) -> DeviceColumn:
    """sort_array: order elements within each row (nulls first for asc,
    last for desc — Spark SortArray semantics)."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    live = value_live(col.offsets, vcap, num_rows)
    from .sort import _to_unsigned_comparable
    lane = _to_unsigned_comparable(col.data)
    if not asc:
        lane = ~lane
    null_lane = jnp.where(col.elem_valid, jnp.uint8(1), jnp.uint8(0)) \
        if asc else jnp.where(col.elem_valid, jnp.uint8(0), jnp.uint8(1))
    # segment-local stable sort: [value, nulls, row, liveness] minor->major
    perm = jnp.lexsort([lane, null_lane, rid,
                        (~live).astype(jnp.int8)])
    return DeviceColumn(jnp.take(col.data, perm), col.validity, col.dtype,
                        col.dictionary,
                        None if col.data_hi is None
                        else jnp.take(col.data_hi, perm),
                        offsets=col.offsets,
                        elem_valid=jnp.take(col.elem_valid, perm))


def filter_values(col: DeviceColumn, keep_vals: jax.Array, num_rows
                  ) -> DeviceColumn:
    """Higher-order filter: keep values where the (values-lane) predicate
    holds; offsets recompute from per-row surviving counts."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    live = value_live(col.offsets, vcap, num_rows)
    keep = keep_vals & live
    # stable compaction ordered by (row, original position)
    order = jnp.lexsort([jnp.arange(vcap, dtype=jnp.int32),
                         (~keep).astype(jnp.int8)])
    new_counts = jax.ops.segment_sum(keep.astype(jnp.int32), rid,
                                     num_segments=col.capacity)
    new_off = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(new_counts).astype(jnp.int32)])
    return DeviceColumn(jnp.take(col.data, order), col.validity,
                        col.dtype, col.dictionary,
                        None if col.data_hi is None
                        else jnp.take(col.data_hi, order),
                        offsets=new_off,
                        elem_valid=jnp.take(col.elem_valid, order) & (
                            jnp.arange(vcap, dtype=jnp.int32)
                            < new_off[-1]))

def map_element_at(keys: DeviceColumn, values: DeviceColumn, needle,
                  num_rows) -> Tuple[jax.Array, jax.Array]:
    """element_at(map, key) over a shattered map (two ragged lanes with
    identical offsets — plan/structs.py): per row, the value whose key
    slot equals `needle`, null when absent or the map is null.  Spark
    map construction keeps the LAST duplicate key, so ties resolve to
    the highest matching slot (segment_max over slot index)."""
    vcap = keys.value_capacity
    rid = row_ids(keys.offsets, vcap)
    live = value_live(keys.offsets, vcap, num_rows)
    hit = (keys.data == needle) & keys.elem_valid & live
    slot = jnp.where(hit, jnp.arange(vcap, dtype=jnp.int32),
                     jnp.int32(-1))
    best = jax.ops.segment_max(slot, rid, num_segments=keys.capacity)
    found = best >= 0
    safe = jnp.clip(best, 0, values.value_capacity - 1)
    data = jnp.take(values.data, safe)
    valid = keys.validity & found & jnp.take(values.elem_valid, safe)
    return data, valid


def element_at(col: DeviceColumn, index: int) -> Tuple[jax.Array,
                                                       jax.Array]:
    """element_at(arr, i): 1-based; negative indexes from the end
    (Spark ElementAt over arrays).  Out-of-range -> null."""
    off = col.offsets
    lens = off[1:] - off[:-1]
    if index >= 0:
        pos = jnp.int32(index - 1)
        idx = off[:-1] + pos
        ok = col.validity & (jnp.int32(index) >= 1) & (pos < lens)
    else:
        pos = lens + jnp.int32(index)
        idx = off[:-1] + pos
        ok = col.validity & (pos >= 0)
    safe = jnp.clip(idx, 0, col.value_capacity - 1)
    return jnp.take(col.data, safe), ok & jnp.take(col.elem_valid, safe)


def position(col: DeviceColumn, needle, num_rows) -> Tuple[jax.Array,
                                                           jax.Array]:
    """array_position(arr, v): 1-based first match, 0 if absent, null
    for null arrays (Spark)."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    live = value_live(col.offsets, vcap, num_rows)
    hit = (col.data == needle) & col.elem_valid & live
    within = jnp.arange(vcap, dtype=jnp.int32) - jnp.take(col.offsets, rid)
    big = jnp.int32(vcap)
    first = jax.ops.segment_min(jnp.where(hit, within, big), rid,
                                num_segments=col.capacity)
    data = jnp.where(first < big, first + 1, 0).astype(jnp.int64)
    return data, col.validity


def slice_rows(col: DeviceColumn, start: int, length: int, num_rows
               ) -> DeviceColumn:
    """slice(arr, start, length): 1-based start; negative start counts
    from the end (Spark Slice).  Keeps per-value order."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    lens = col.offsets[1:] - col.offsets[:-1]
    within = jnp.arange(vcap, dtype=jnp.int32) - jnp.take(col.offsets, rid)
    if start >= 0:
        lo = jnp.full(col.capacity, start - 1, jnp.int32)
        oob = jnp.zeros(col.capacity, bool)
    else:
        raw_lo = lens + jnp.int32(start)
        oob = raw_lo < 0          # Spark: start before the array -> empty
        lo = jnp.maximum(raw_lo, 0)
    lo_v = jnp.take(lo, rid)
    keep = (within >= lo_v) & (within < lo_v + jnp.int32(length)) & \
        ~jnp.take(oob, rid)
    return filter_values(col, keep, num_rows)


def reverse_rows(col: DeviceColumn, num_rows) -> DeviceColumn:
    """reverse(arr): per-row element reversal — one gather, offsets
    unchanged."""
    vcap = col.value_capacity
    rid = row_ids(col.offsets, vcap)
    lens = col.offsets[1:] - col.offsets[:-1]
    within = jnp.arange(vcap, dtype=jnp.int32) - jnp.take(col.offsets, rid)
    src = jnp.take(col.offsets, rid) + jnp.take(lens, rid) - 1 - within
    safe = jnp.clip(src, 0, vcap - 1)
    return DeviceColumn(jnp.take(col.data, safe), col.validity,
                        col.dtype, col.dictionary,
                        None if col.data_hi is None
                        else jnp.take(col.data_hi, safe),
                        offsets=col.offsets,
                        elem_valid=jnp.take(col.elem_valid, safe))
