"""Bounded-domain segmented aggregation: block-local accumulate +
single-pass combine, in Pallas.

The portable group-by tiers either sort (packed single-lane sort +
segmented scans) or scatter (the dense no-sort bucket path).  When the
packed key domain is small — dictionary codes, booleans, range-packed
integer tuples — neither is the TPU-native shape: the cuDF/libcudf
answer is a block-local accumulator combined once, and on TPU that
accumulator IS the MXU: a (domain x block) one-hot contraction
accumulates every sum/count lane of a block in one matmul, and
MIN/MAX/FIRST/LAST/ANY/EVERY ride masked VPU reductions over the same
one-hot.  No sort, no scatter, no row permutation at all — aggregate
inputs are read in place, so dictionary codes and FOR-narrowed lanes
aggregate without decoding.

Exactness: int64 sums cannot ride a single f64 matmul (53-bit
mantissa), so integer lanes contract as two exact f64 matmuls over
their unsigned-low/signed-high 32-bit halves — each half's block sum
stays < 2^53 for any block <= 2^21 rows — and recombine in int64,
where wraparound matches jax.ops.segment_sum semantics.  f64 sums
combine block-parallel (different association than the sorted-run
scan, the variableFloatAgg contract the election gate enforces).

Output contract mirrors ops/groupby.packed_groupby_trace /
dense_groupby_trace: occupied buckets compact to the front in
ascending packed-key order (null slot 0 first), keys decode
arithmetically from the bucket id, (domain,)-sized outputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import groupby as G
from ... import types as t
from ..kernels import compute_view

_SEGAGG_CACHE = {}


def _block_rows(capacity: int, domain: int) -> int:
    """Accumulate-block sizing: the (domain x block) one-hot is the
    working set, budgeted at ~2^21 elements; the block must divide
    capacity (interpreter padding would otherwise feed junk rows into
    the accumulator) and stays <= 2^21 so 32-bit-half sums are exact
    in f64."""
    capacity = max(capacity, 1)
    blk = max(512, min(capacity, (1 << 21) // max(domain, 1)))
    p = 1 << (blk.bit_length() - 1)
    while p > 1 and capacity % p:
        p >>= 1
    return p if capacity // p <= 256 else capacity


def _seg_matmul_sums(seg, int_lanes, f64_lanes, domain: int,
                     capacity: int, interpret: bool):
    """(domain, Ki) exact int64 sums + (domain, Kf) f64 sums per bucket
    in ONE kernel pass: one-hot built once per block, integer lanes
    contracted as exact split-f64 half matmuls."""
    ki, kf = len(int_lanes), len(f64_lanes)
    blk = _block_rows(capacity, domain)
    grid = max(1, capacity // blk)
    sig = ("sums", domain, capacity, ki, kf, blk, interpret)
    fn = _SEGAGG_CACHE.get(sig)
    if fn is None:
        def kernel(seg_ref, ints_ref, f64s_ref, iacc_ref, facc_ref):
            @pl.when(pl.program_id(0) == 0)
            def _():
                iacc_ref[...] = jnp.zeros((domain, max(ki, 1)),
                                          jnp.int64)
                facc_ref[...] = jnp.zeros((domain, max(kf, 1)),
                                          jnp.float64)
            onehot = (seg_ref[...][None, :] == jax.lax.broadcasted_iota(
                jnp.int32, (domain, blk), 0)).astype(jnp.float64)
            if ki:
                v = ints_ref[...]
                lo = (v & jnp.int64(0xFFFFFFFF)).astype(jnp.float64)
                hi = (v >> 32).astype(jnp.float64)
                slo = jax.lax.dot_general(
                    onehot, lo, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float64)
                shi = jax.lax.dot_general(
                    onehot, hi, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float64)
                iacc_ref[...] += shi.astype(jnp.int64) * jnp.int64(
                    1 << 32) + slo.astype(jnp.int64)
            if kf:
                facc_ref[...] += jax.lax.dot_general(
                    onehot, f64s_ref[...], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float64)

        def run(seg, ints, f64s):
            return pl.pallas_call(
                kernel,
                grid=(grid,),
                in_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                          pl.BlockSpec((blk, max(ki, 1)),
                                       lambda i: (i, 0)),
                          pl.BlockSpec((blk, max(kf, 1)),
                                       lambda i: (i, 0))],
                out_specs=[pl.BlockSpec((domain, max(ki, 1)),
                                        lambda i: (0, 0)),
                           pl.BlockSpec((domain, max(kf, 1)),
                                        lambda i: (0, 0))],
                out_shape=[jax.ShapeDtypeStruct((domain, max(ki, 1)),
                                                jnp.int64),
                           jax.ShapeDtypeStruct((domain, max(kf, 1)),
                                                jnp.float64)],
                interpret=interpret,
            )(seg, ints, f64s)
        fn = jax.jit(run)
        _SEGAGG_CACHE[sig] = fn
    zi = jnp.zeros((capacity, 1), jnp.int64)
    zf = jnp.zeros((capacity, 1), jnp.float64)
    ints = jnp.stack(int_lanes, axis=1) if ki else zi
    f64s = jnp.stack(f64_lanes, axis=1) if kf else zf
    iacc, facc = fn(seg, ints, f64s)
    return iacc, facc


def _seg_reduce(seg, lane, domain: int, capacity: int, is_min: bool,
                ident, interpret: bool):
    """(domain,) per-bucket min/max of one lane via the masked one-hot
    reduction (the VPU leg of the block accumulator)."""
    blk = _block_rows(capacity, domain)
    grid = max(1, capacity // blk)
    dts = str(lane.dtype)
    sig = ("reduce", domain, capacity, dts, is_min, blk, interpret)
    fn = _SEGAGG_CACHE.get(sig)
    if fn is None:
        def kernel(seg_ref, lane_ref, id_ref, acc_ref):
            iv = id_ref[0]

            @pl.when(pl.program_id(0) == 0)
            def _():
                acc_ref[...] = jnp.full((domain,), iv, lane_ref.dtype)
            onehot = seg_ref[...][None, :] == jax.lax.broadcasted_iota(
                jnp.int32, (domain, blk), 0)
            masked = jnp.where(onehot, lane_ref[...][None, :], iv)
            red = (jnp.min if is_min else jnp.max)(masked, axis=1)
            acc_ref[...] = (jnp.minimum if is_min else jnp.maximum)(
                acc_ref[...], red)

        def run(seg, lane, iv):
            return pl.pallas_call(
                kernel,
                grid=(grid,),
                in_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                          pl.BlockSpec((blk,), lambda i: (i,)),
                          pl.BlockSpec((1,), lambda i: (0,))],
                out_specs=pl.BlockSpec((domain,), lambda i: (0,)),
                out_shape=jax.ShapeDtypeStruct((domain,), lane.dtype),
                interpret=interpret,
            )(seg, lane, iv)
        fn = jax.jit(run)
        _SEGAGG_CACHE[sig] = fn
    iv = jnp.asarray(ident, lane.dtype).reshape((1,))
    return fn(seg, lane, iv)


def pallas_groupby_trace(pack_spec, key_lanes_info, agg_specs,
                         num_segments: int, capacity: int,
                         interpret: bool):
    """The block-accumulate group-by: same call contract AND output
    shape as ops/groupby.packed_groupby_trace — (num_segments,)-sized
    outputs, group order = ascending packed key (null first).  Shape
    parity with the sort path it replaces matters beyond tidiness: the
    adaptive join picks build sides by materialized BYTES, so a
    differently-sized aggregate output would flip join plans (and with
    them whole-plan traceability) when the tier toggles."""
    spans = [s[1] for s in pack_spec]
    los = [s[0] for s in pack_spec]
    strides = []
    tot = 1
    for s in reversed(spans):
        strides.append(tot)
        tot *= s
    strides.reverse()
    D = tot

    def run(keys, keys_valid, agg_data, agg_valid, live):
        packed = G._packed_key_lane(keys, keys_valid, pack_spec)
        seg = jnp.where(live, packed, jnp.int64(D)).astype(jnp.int32)

        iota = jnp.arange(capacity, dtype=jnp.int32)
        big = jnp.int32(capacity)

        # ---- queue every reduction over the original (unsorted) rows
        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                d = agg_data[spec.input_idx]
                v = agg_valid[spec.input_idx]
                v = jnp.ones((capacity,), bool) if v is None else v
                spec_vls.append((d, v & live))
            else:
                spec_vls.append((None, live))

        int_lanes, int_slots = [], {}
        f64_lanes, f64_slots = [], {}

        def queue_sum(key, lane, is_float):
            lanes_, slots = (f64_lanes, f64_slots) if is_float \
                else (int_lanes, int_slots)
            if key not in slots:
                slots[key] = len(lanes_)
                lanes_.append(lane)

        queue_sum(("rows",), live.astype(jnp.int64), False)
        for si, spec in enumerate(agg_specs):
            d, vl = spec_vls[si]
            dt = spec.dtype
            if spec.kind == G.COUNT_ALL:
                queue_sum(("cnt", si), live.astype(jnp.int64), False)
            elif spec.kind == G.COUNT:
                queue_sum(("cnt", si), vl.astype(jnp.int64), False)
            elif spec.kind == G.SUM:
                cd = compute_view(d, dt)
                if t.is_floating(dt):
                    queue_sum(("sum", si),
                              jnp.where(vl, cd.astype(jnp.float64), 0.0),
                              True)
                else:
                    queue_sum(("sum", si),
                              jnp.where(vl, cd.astype(jnp.int64), 0),
                              False)
            if spec.kind not in (G.COUNT, G.COUNT_ALL):
                queue_sum(("vc", spec.input_idx),
                          vl.astype(jnp.int64), False)

        iacc, facc = _seg_matmul_sums(seg, int_lanes, f64_lanes, D,
                                      capacity, interpret)

        def sum_of(key, is_float):
            return (facc[:, f64_slots[key]] if is_float
                    else iacc[:, int_slots[key]])

        occupied = sum_of(("rows",), False) > 0
        num_groups = jnp.sum(occupied, dtype=jnp.int32)
        order = jnp.argsort(jnp.where(occupied, jnp.int8(0),
                                      jnp.int8(1)), stable=True)
        group_live = jnp.arange(D, dtype=jnp.int32) < num_groups

        out_keys = []
        for (dt, _hv, lane_dt), lo, span, stride in zip(
                key_lanes_info, los, spans, strides):
            slot = (order.astype(jnp.int64) // jnp.int64(stride)) % \
                jnp.int64(span)
            data = (slot - 1 + jnp.int64(lo)).astype(jnp.dtype(lane_dt))
            out_keys.append((data, (slot > 0) & group_live))

        def reduce_of(lane, is_min, ident):
            return _seg_reduce(seg, lane, D, capacity, is_min, ident,
                               interpret)[order]

        def nan_counts(si):
            # per-bucket NaN counts for the float-min contract (min is
            # NaN only when every valid value is NaN); a second small
            # matmul pass rather than churning the main sum signature
            d, vl = spec_vls[si]
            isnan = jnp.isnan(compute_view(d, agg_specs[si].dtype)) & vl
            return _seg_matmul_sums(
                seg, [isnan.astype(jnp.int64)], [], D, capacity,
                interpret)[0][:, 0][order]

        outs = []
        for si, spec in enumerate(agg_specs):
            d, vl = spec_vls[si]
            dt = spec.dtype
            if spec.kind in (G.COUNT, G.COUNT_ALL):
                outs.append((sum_of(("cnt", si), False)[order],
                             group_live))
                continue
            valid_count = sum_of(("vc", spec.input_idx), False)[order]
            out_valid = (valid_count > 0) & group_live
            cd = compute_view(d, dt)
            if spec.kind == G.SUM:
                data = sum_of(("sum", si), t.is_floating(dt))[order]
            elif spec.kind in (G.MIN, G.MAX):
                is_min = spec.kind == G.MIN
                if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                    o = G._bits_total_order(d)
                    ident = G._ORDER_MAX if is_min else G._ORDER_MIN
                    o = jnp.where(vl, o, jnp.int64(ident))
                    data = G._bits_from_order(
                        reduce_of(o, is_min, ident))
                elif t.is_floating(dt):
                    isnan = jnp.isnan(cd) & vl
                    has_nan = reduce_of(isnan.astype(jnp.int8), False,
                                        np.int8(0)) > 0
                    ident = np.float64(np.inf if is_min else -np.inf)
                    clean = jnp.where(vl & ~isnan, cd, ident)
                    red = reduce_of(clean, is_min, ident)
                    if is_min:
                        non_nan = valid_count - nan_counts(si)
                        data = jnp.where(has_nan & (non_nan == 0),
                                         jnp.float64(np.nan), red)
                    else:
                        data = jnp.where(has_nan, jnp.float64(np.nan),
                                         red)
                else:
                    if isinstance(dt, t.BooleanType):
                        ident = bool(is_min)
                    else:
                        info = np.iinfo(np.dtype(cd.dtype))
                        ident = info.max if is_min else info.min
                    data = reduce_of(jnp.where(vl, cd, jnp.asarray(
                        ident, cd.dtype)), is_min, ident)
            elif spec.kind in (G.FIRST, G.LAST):
                is_first = spec.kind == G.FIRST
                masked = jnp.where(live, iota, big if is_first else -1)
                pick = jnp.clip(reduce_of(masked, is_first,
                                          capacity if is_first else -1),
                                0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind in (G.FIRST_NN, G.LAST_NN):
                is_first = spec.kind == G.FIRST_NN
                masked = jnp.where(vl, iota, big if is_first else -1)
                pick = jnp.clip(reduce_of(masked, is_first,
                                          capacity if is_first else -1),
                                0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind == G.ANY:
                data = reduce_of(jnp.where(vl, cd, False).astype(
                    jnp.int8), False, np.int8(0)) > 0
            elif spec.kind == G.EVERY:
                data = reduce_of(jnp.where(vl, cd, True).astype(
                    jnp.int8), True, np.int8(1)) > 0
            else:
                raise ValueError(f"unknown agg kind {spec.kind}")
            outs.append((data, out_valid))

        def fit(arr):
            # (D,) bucket lane -> (num_segments,) output lane, matching
            # the packed sort path's shapes (concat, never scatter)
            if D == num_segments:
                return arr
            if D > num_segments:
                return arr[:num_segments]
            pad = jnp.zeros((num_segments - D,), arr.dtype)
            return jnp.concatenate([arr, pad])

        out_keys = [(fit(kd), fit(kv)) for kd, kv in out_keys]
        outs = [(fit(data), fit(valid)) for data, valid in outs]
        return out_keys, outs, num_groups

    return run
