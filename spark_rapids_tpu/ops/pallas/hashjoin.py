"""Hash-probe equi-join kernels: murmur3 open addressing in Pallas.

The portable tier probes a SORTED build lane with `_merge_rank` — two
2-operand sorts over build+probe rows per probe op — because binary
search's log2(n) dependent gathers are the slowest access pattern on
TPU.  This kernel family replaces the search with a real open-
addressing hash table in the layout TPUs (and the interpreter) like:

  * slot = top bits of the murmur3 (fmix64) finalizer of the canonical
    int64 key lane.  fmix64 is a BIJECTION on 64 bits, so two lanes are
    equal iff their hashes are equal — no collision verification pass.
  * The table is built in HASH ORDER: build rows sort once by hash
    (dead/null-key rows last), each row's final slot is
    `i + prefix_max(ideal_slot - i)` — the classic linear-probing
    invariant materialized by one blocked prefix max, no insertion
    loop, no contention.  Equal keys land in CONSECUTIVE slots in
    ascending build-row order (stable sort), so duplicate handling is
    run-length arithmetic, never chain walking: a probe row's matches
    are table positions [first, first+count) and pair expansion is a
    pure gather.
  * Probes grid over probe blocks: each block walks `slot, slot+1, ...`
    with vectorized gathers until every lane hit its key or an empty
    slot (row == -1).  The linear-probing invariant guarantees no gap
    between a key's ideal slot and its run.

Table sizing: S = 2^ceil(log2(2*cap)) home slots (load factor <= 0.5)
plus a cap-row overflow tail so pushed runs never wrap — probes only
ever walk forward.  Contracts mirror ops/join exactly: `probe_first`
is the unique-build aligned probe, `probe_matched` the semi/anti flag,
`probe_counts`/`expand_pairs` the sized gather-map path; all outputs
are bit-identical to the sorted tier (same pair order: probe-major,
build rows ascending within a key).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..kernels import blocked_cummax, blocked_cumsum

_HASH_CACHE = {}


def mix64(x: jax.Array) -> jax.Array:
    """murmur3 fmix64 finalizer over uint64 lanes (a 64-bit bijection:
    equal hashes <=> equal lanes, so probes never verify twice)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 33)) * jnp.uint64(0xFF51AFD7ED558CCD)
    x = (x ^ (x >> 33)) * jnp.uint64(0xC4CEB9FE1A85EC53)
    return x ^ (x >> 33)


class HashTable(NamedTuple):
    """Hash-ordered open-addressing table over one canonical int64 lane.

    keys[s] is the lane value at slot s (meaningful only where
    rows[s] >= 0); rows[s] is the build row id, -1 = empty.  nbits is
    the home-slot width (S = 1 << nbits), span the physical slot count
    (S + build capacity overflow tail)."""
    keys: jax.Array          # int64[span]
    rows: jax.Array          # int32[span]
    nbits: int
    span: int
    interpret: bool


def _probe_block(capacity: int) -> int:
    """Probe grid block: the largest power-of-two divisor of capacity
    up to 512k rows — big blocks amortize the interpreter's
    per-grid-step carry copies, and an exact divisor means the
    interpreter never pads blocks with uninitialized rows (the build
    kernel stores at computed positions, so junk rows must not exist).
    Falls back to one whole-capacity block for odd capacities."""
    capacity = max(capacity, 1)
    blk = min(capacity, 1 << 19)
    while blk > 1 and capacity % blk:
        blk >>= 1
    # pathological (odd) capacities would degrade to a huge grid of
    # tiny blocks; one whole-capacity block beats that everywhere
    return blk if capacity // blk <= 64 else capacity


def _grid_blocks(capacity: int, blk: int) -> int:
    return max(1, (capacity + blk - 1) // blk)


def build_table(lane: jax.Array, valid: jax.Array,
                interpret: bool) -> HashTable:
    """Build the table for one canonical build lane: one hash-order
    sort chain (two 2-operand stable sorts — hash, then liveness) and
    ONE Pallas layout kernel computing final slots by blocked prefix
    max and storing (key, row) pairs.  Dead rows ride the sort to the
    end and store row = -1 in the overflow tail, indistinguishable
    from empty slots."""
    cap = int(lane.shape[0])
    nbits = max(4, (2 * max(cap, 1) - 1).bit_length())
    span = (1 << nbits) + cap
    sig = ("build", cap, nbits, interpret)
    fn = _HASH_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_build_trace(cap, nbits, span, interpret))
        _HASH_CACHE[sig] = fn
    keys, rows = fn(lane, valid)
    return HashTable(keys, rows, nbits, span, interpret)


def _build_trace(cap: int, nbits: int, span: int, interpret: bool):
    S = 1 << nbits
    shift = np.uint64(64 - nbits)
    blk = _probe_block(cap)
    grid = _grid_blocks(cap, blk)

    def kernel(ideal_ref, lane_ref, rid_ref, keys_ref, rows_ref,
               carry_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            keys_ref[...] = jnp.zeros((span,), jnp.int64)
            rows_ref[...] = jnp.full((span,), -1, jnp.int32)
            carry_ref[0] = jnp.int32(-(2 ** 31) + 1)
        i = pl.program_id(0) * blk + \
            jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)[:, 0]
        # linear-probing layout: final = i + running_max(ideal - i);
        # strictly increasing, == ideal when unpushed, contiguous when
        # pushed — the invariant probes rely on (module docstring)
        rel = ideal_ref[...] - i
        prefix = jnp.maximum(blocked_cummax(rel), carry_ref[0])
        carry_ref[0] = prefix[-1]
        final = jnp.clip(i + prefix, 0, span - 1)
        keys_ref[final] = lane_ref[...]
        rows_ref[final] = rid_ref[...]

    def run(lane, valid):
        from ..segments import lexsort_capped
        h = mix64(lane)
        dead = ~valid
        perm = lexsort_capped([h, dead.astype(jnp.int8)], 2)
        hs = jnp.take(h, perm)
        dead_s = jnp.take(dead, perm)
        lane_s = jnp.take(lane, perm)
        ideal = jnp.where(dead_s, jnp.int32(S),
                          (hs >> shift).astype(jnp.int32))
        rid = jnp.where(dead_s, jnp.int32(-1), perm.astype(jnp.int32))
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                      pl.BlockSpec((blk,), lambda i: (i,)),
                      pl.BlockSpec((blk,), lambda i: (i,))],
            out_specs=[pl.BlockSpec((span,), lambda i: (0,)),
                       pl.BlockSpec((span,), lambda i: (0,))],
            out_shape=[jax.ShapeDtypeStruct((span,), jnp.int64),
                       jax.ShapeDtypeStruct((span,), jnp.int32)],
            scratch_shapes=[_smem_scratch((1,), jnp.int32)],
            interpret=interpret,
        )(ideal, lane_s, rid)
    return run


def _smem_scratch(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.SMEM(shape, dtype)


def probe_first(table: HashTable, lane: jax.Array, valid: jax.Array):
    """(build_row, found) per probe row: the FIRST build row (ascending
    row id) whose key equals the probe lane — the unique-build aligned
    probe (ops/join.probe_aligned contract)."""
    cap = int(lane.shape[0])
    sig = ("first", table.span, table.nbits, cap, table.interpret)
    fn = _HASH_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_probe_first_trace(cap, table.nbits, table.span,
                                        table.interpret))
        _HASH_CACHE[sig] = fn
    return fn(table.keys, table.rows, lane, valid)


def _probe_first_trace(cap: int, nbits: int, span: int, interpret: bool):
    shift = np.uint64(64 - nbits)
    blk = _probe_block(cap)
    grid = _grid_blocks(cap, blk)

    def kernel(tk_ref, tr_ref, lane_ref, valid_ref, row_ref, ok_ref):
        keys = lane_ref[...]
        slot0 = (mix64(keys) >> shift).astype(jnp.int32)

        def cond(c):
            _, _, pending, steps = c
            return jnp.logical_and(jnp.any(pending), steps < span)

        def body(c):
            slot, out, pending, steps = c
            r = tr_ref[slot]
            k = tk_ref[slot]
            occupied = r >= 0
            hit = pending & occupied & (k == keys)
            out = jnp.where(hit, r, out)
            pending = pending & occupied & ~hit
            slot = jnp.where(pending, jnp.minimum(slot + 1, span - 1),
                             slot)
            return slot, out, pending, steps + 1

        _, out, _, _ = jax.lax.while_loop(
            cond, body, (slot0, jnp.full((blk,), -1, jnp.int32),
                         valid_ref[...], 0))
        row_ref[...] = out
        ok_ref[...] = out >= 0

    def run(tk, tr, lane, valid):
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((span,), lambda i: (0,)),
                      pl.BlockSpec((span,), lambda i: (0,)),
                      pl.BlockSpec((blk,), lambda i: (i,)),
                      pl.BlockSpec((blk,), lambda i: (i,))],
            out_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                       pl.BlockSpec((blk,), lambda i: (i,))],
            out_shape=[jax.ShapeDtypeStruct((cap,), jnp.int32),
                       jax.ShapeDtypeStruct((cap,), jnp.bool_)],
            interpret=interpret,
        )(tk, tr, lane, valid)
    return run


def probe_matched(table: HashTable, lane: jax.Array, valid: jax.Array):
    """Per-probe-row matched flag (semi/anti joins) — probe_first's ok
    lane without the row output."""
    _row, ok = probe_first(table, lane, valid)
    return ok


def probe_counts(table: HashTable, lane: jax.Array, valid: jax.Array):
    """(first_pos, counts, cum) per probe row: first TABLE position and
    run length of the probe key's matches (duplicates are consecutive
    by construction).  counts is 0 for invalid/unmatched rows; cum is
    the inclusive blocked prefix sum the expansion searches."""
    cap = int(lane.shape[0])
    sig = ("counts", table.span, table.nbits, cap, table.interpret)
    fn = _HASH_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_probe_counts_trace(cap, table.nbits, table.span,
                                         table.interpret))
        _HASH_CACHE[sig] = fn
    first, counts = fn(table.keys, table.rows, lane, valid)
    return first, counts, blocked_cumsum(counts)


def _probe_counts_trace(cap: int, nbits: int, span: int,
                        interpret: bool):
    shift = np.uint64(64 - nbits)
    blk = _probe_block(cap)
    grid = _grid_blocks(cap, blk)

    def kernel(tk_ref, tr_ref, lane_ref, valid_ref, first_ref, cnt_ref):
        keys = lane_ref[...]
        slot0 = (mix64(keys) >> shift).astype(jnp.int32)

        def cond(c):
            _, _, _, pending, steps = c
            return jnp.logical_and(jnp.any(pending), steps < span)

        def body(c):
            slot, first, cnt, pending, steps = c
            r = tr_ref[slot]
            k = tk_ref[slot]
            occupied = r >= 0
            hit = pending & occupied & (k == keys)
            first = jnp.where(hit & (cnt == 0), slot, first)
            cnt = cnt + hit.astype(jnp.int32)
            # stop at the first empty slot OR the first non-matching
            # slot after the run started (equal keys are consecutive)
            pending = pending & occupied & (hit | (cnt == 0))
            slot = jnp.where(pending, jnp.minimum(slot + 1, span - 1),
                             slot)
            return slot, first, cnt, pending, steps + 1

        _, first, cnt, _, _ = jax.lax.while_loop(
            cond, body, (slot0, jnp.zeros((blk,), jnp.int32),
                         jnp.zeros((blk,), jnp.int32), valid_ref[...], 0))
        first_ref[...] = first
        cnt_ref[...] = cnt

    def run(tk, tr, lane, valid):
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((span,), lambda i: (0,)),
                      pl.BlockSpec((span,), lambda i: (0,)),
                      pl.BlockSpec((blk,), lambda i: (i,)),
                      pl.BlockSpec((blk,), lambda i: (i,))],
            out_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                       pl.BlockSpec((blk,), lambda i: (i,))],
            out_shape=[jax.ShapeDtypeStruct((cap,), jnp.int32),
                       jax.ShapeDtypeStruct((cap,), jnp.int32)],
            interpret=interpret,
        )(tk, tr, lane, valid)
    return run


def expand_pairs(table: HashTable, first: jax.Array, counts: jax.Array,
                 cum: jax.Array, out_cap: int, total):
    """(probe_idx, build_idx, ok) for the sized pair expansion: output
    slot j's owning probe row falls out of a vectorized rank search
    over `cum` (log2 rounds of gathers — cheap because cum is ONE
    monotone int32 lane), its build row is a pure gather at
    first[p] + (j - start(p)) since duplicate matches are consecutive
    table slots.  Pair order is identical to the sorted tier:
    probe-major, build rows ascending within a key."""
    pcap = int(first.shape[0])
    sig = ("expand", table.span, pcap, out_cap, table.interpret)
    fn = _HASH_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_expand_trace(pcap, out_cap, table.span,
                                   table.interpret))
        _HASH_CACHE[sig] = fn
    return fn(table.rows, first, counts, cum, jnp.int32(total))


def _expand_trace(pcap: int, out_cap: int, span: int, interpret: bool):
    blk = _probe_block(out_cap)
    grid = _grid_blocks(out_cap, blk)
    rounds = max(1, (max(pcap, 1) - 1).bit_length() + 1)

    def kernel(tr_ref, first_ref, cnt_ref, cum_ref, total_ref,
               p_ref, b_ref, ok_ref):
        j = pl.program_id(0) * blk + \
            jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)[:, 0]
        tgt = j + 1
        lo = jnp.zeros((blk,), jnp.int32)
        hi = jnp.full((blk,), pcap, jnp.int32)

        def body(_, c):
            lo, hi = c
            mid = jnp.minimum((lo + hi) // 2, pcap - 1)
            go_hi = cum_ref[mid] < tgt
            return (jnp.where(go_hi, mid + 1, lo),
                    jnp.where(go_hi, hi, mid))

        lo, _ = jax.lax.fori_loop(0, rounds, body, (lo, hi))
        p = jnp.minimum(lo, pcap - 1)
        start = cum_ref[p] - cnt_ref[p]
        pos = jnp.clip(first_ref[p] + (j - start), 0, span - 1)
        live = j < total_ref[0]
        p_ref[...] = jnp.where(live, p, 0)
        b_ref[...] = jnp.where(live, jnp.maximum(tr_ref[pos], 0), 0)
        ok_ref[...] = live

    def run(tr, first, counts, cum, total):
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((span,), lambda i: (0,)),
                      pl.BlockSpec((pcap,), lambda i: (0,)),
                      pl.BlockSpec((pcap,), lambda i: (0,)),
                      pl.BlockSpec((pcap,), lambda i: (0,)),
                      pl.BlockSpec((1,), lambda i: (0,))],
            out_specs=[pl.BlockSpec((blk,), lambda i: (i,)),
                       pl.BlockSpec((blk,), lambda i: (i,)),
                       pl.BlockSpec((blk,), lambda i: (i,))],
            out_shape=[jax.ShapeDtypeStruct((out_cap,), jnp.int32),
                       jax.ShapeDtypeStruct((out_cap,), jnp.int32),
                       jax.ShapeDtypeStruct((out_cap,), jnp.bool_)],
            interpret=interpret,
        )(tr, first, counts, cum, total.reshape((1,)))
    return run


def build_matched_flags(table: HashTable, first: jax.Array,
                        counts: jax.Array,
                        build_capacity: int) -> jax.Array:
    """Per-BUILD-row matched flags (right/full outer) from the counted
    probe runs, expansion-free: each probe row's matches are the table
    interval [first, first+count), so interval-difference marking (+1
    at starts, -1 past ends, blocked cumsum > 0) yields per-SLOT
    matched flags, carried back to rows through the table's row lane —
    two small scatters + one scan instead of a segment reduction over
    the expanded pair set."""
    span = table.span
    has = counts > 0
    delta = jnp.zeros((span + 1,), jnp.int32)
    delta = delta.at[jnp.where(has, first, span + 1)].add(1, mode="drop")
    delta = delta.at[jnp.where(has, first + counts, span + 1)].add(
        -1, mode="drop")
    occ = blocked_cumsum(delta[:span]) > 0
    tgt = jnp.where(occ & (table.rows >= 0), table.rows,
                    jnp.int32(build_capacity))
    return jnp.zeros((build_capacity,), bool).at[tgt].set(
        True, mode="drop")
