"""Hand-written Pallas kernel tier — the libcudf-equivalent layer.

The reference engine leans on libcudf device kernels (hash joins, hash
group-by, stream compaction); this engine's portable tier re-expresses
those as sorts + segmented scans because XLA's TPU lowering favors
them.  PR 9's attribution plane showed where that trade loses: the
join/shuffle-heavy tail (q3/q9/q15-class) spends its device time in the
sort-based probe (`ops/join._merge_rank` — two 2-operand sorts of
build+probe rows per probe op) and in keep-mask argsorts.  This package
is the hand-written kernel tier for exactly those segments
(`spark.rapids.tpu.sql.kernels.pallas.enabled` + per-kernel modes):

  * `hashjoin`  — murmur3 open-addressing hash table (hash-ordered
    layout, duplicate keys consecutive) + probe kernels gridded over
    probe blocks; emits the same gather-map/match-flag contract as the
    sorted probe, so late materialization, semi/anti/outer variants and
    dictionary-code keys ride through unchanged.
  * `segagg`    — bounded-domain segmented aggregation: block-local
    accumulate (one-hot MXU matmuls for sums/counts, masked VPU
    reductions for MIN/MAX/FIRST/LAST/ANY/EVERY) + one combine, no sort
    and no scatter, operating directly on dictionary codes and
    FOR-narrowed integer lanes.
  * `compact`   — selection compaction: blocked prefix sum + per-slot
    rank search replaces the stable keep-mask argsort.

Dispatch philosophy (fallback-safe): the sort-based tier stays intact
and every dispatch point NEGOTIATES — single exact key lane, domain and
build-size bounds, backend support, float-exactness — then counts the
decision in `tpu_kernel_dispatch_total` / `tpu_kernel_fallback_total`.
On backends without native Pallas lowering the kernels run under
`interpret=True`: the kernel bodies execute as discharged XLA ops
inside the same traced program, so tier-1 and the CPU container
exercise the REAL probe/accumulate/compact logic.  The `kernel` chaos
site fires at each election; an injected OOM there sheds the operator
onto the sort tier bit-identically (the fallback rung), a fatal
surfaces as a classified dump whose injected-fault record names the
kernel.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from ...config import (PALLAS_COMPACT, PALLAS_ENABLED, PALLAS_INTERPRET,
                       PALLAS_JOIN, PALLAS_JOIN_DENSE_REPLACE,
                       PALLAS_JOIN_MAX_BUILD, PALLAS_SEGAGG,
                       PALLAS_SEGAGG_MAX_DOMAIN, TpuConf)


@dataclasses.dataclass(frozen=True)
class KernelTier:
    """Resolved per-conf kernel-tier decisions (static for a query)."""
    join: bool
    segagg: bool
    compact: bool
    interpret: bool
    segagg_max_domain: int
    join_max_build: int
    join_dense_replace: str = "AUTO"   # AUTO | ON | OFF

    @property
    def mode(self) -> str:
        return "interpret" if self.interpret else "compiled"

    @property
    def any_enabled(self) -> bool:
        return self.join or self.segagg or self.compact


NO_TIER = KernelTier(False, False, False, False, 0, 0, "OFF")


def _native_backend() -> bool:
    """Whether pl.pallas_call lowers natively here (Mosaic: TPU only)."""
    return jax.default_backend() == "tpu"


def kernel_tier(conf: TpuConf) -> KernelTier:
    """The resolved tier for this conf, cached on the conf instance (one
    resolution per query plan; the disabled path is one dict hit)."""
    tier = conf._cache.get("__pallas_tier")
    if tier is not None:
        return tier
    tier = _resolve_tier(conf)
    conf._cache["__pallas_tier"] = tier
    return tier


def _resolve_tier(conf: TpuConf) -> KernelTier:
    if not conf.get(PALLAS_ENABLED):
        return NO_TIER
    native = _native_backend()
    imode = str(conf.get(PALLAS_INTERPRET)).upper()
    interpret = (not native) if imode == "AUTO" else imode == "ON"
    if not native and not interpret:
        # no native lowering and interpretation forbidden: the tier
        # cannot run anywhere on this backend
        from ...obs.registry import KERNEL_FALLBACK
        KERNEL_FALLBACK.inc(kernel="tier", reason="backend")
        return NO_TIER

    def mode(entry, auto: bool) -> bool:
        v = str(conf.get(entry)).upper()
        return auto if v == "AUTO" else v == "ON"

    # join/compact win on every backend (the interpreted kernels beat
    # the sort path on XLA-CPU too — measured in bench.py --kernels);
    # segagg's block accumulators only pay off where Pallas compiles
    # natively (XLA-CPU scatters are fast, docs/PERF.md §8)
    return KernelTier(
        join=mode(PALLAS_JOIN, True),
        segagg=mode(PALLAS_SEGAGG, native and not interpret),
        compact=mode(PALLAS_COMPACT, True),
        interpret=interpret,
        segagg_max_domain=int(conf.get(PALLAS_SEGAGG_MAX_DOMAIN)),
        join_max_build=int(conf.get(PALLAS_JOIN_MAX_BUILD)),
        join_dense_replace=str(conf.get(PALLAS_JOIN_DENSE_REPLACE))
        .upper())


def tier_discriminant(conf: TpuConf) -> Optional[tuple]:
    """Kernel-tier discriminant for compiled-program cache keys
    (exec/compiled.py plan_structure_key): two confs whose RESOLVED
    tiers differ must never share an executable — in particular a
    persistent-cache entry compiled with kernels on must not cross-load
    into a kernels-off session or vice versa.  None when the tier is
    fully off (the key stays byte-identical to pre-tier builds)."""
    t = kernel_tier(conf)
    if not t.any_enabled:
        return None
    return ("pallas", t.join, t.segagg, t.compact, t.interpret,
            t.segagg_max_domain, t.join_max_build, t.join_dense_replace)


# ---------------------------------------------------------------------------
# Dispatch bookkeeping: metrics + the `kernel` chaos site
# ---------------------------------------------------------------------------

def _count_dispatch(kernel: str, tier: KernelTier) -> None:
    from ...obs.registry import KERNEL_DISPATCH
    KERNEL_DISPATCH.inc(kernel=kernel, mode=tier.mode)


def count_fallback(kernel: str, reason: str) -> None:
    from ...obs.registry import KERNEL_FALLBACK
    KERNEL_FALLBACK.inc(kernel=kernel, reason=reason)


def elect(conf: TpuConf, tier: KernelTier, kernel: str) -> bool:
    """Final election step for one operator dispatch onto `kernel`:
    fires the `kernel` chaos site (the injected-fault record names the
    kernel) and counts the dispatch.  An injected OOM at the site is
    the shed signal: the operator falls back to the sort-based tier
    bit-identically — returns False, counted as reason='oom' — instead
    of failing the query (the fallback rung the chaos suite asserts).
    Fatal/error/ioerror kinds propagate to their usual recovery
    ladders (a fatal becomes a classified dump naming the kernel)."""
    from ...runtime.faults import get_active_injector, get_injector
    from ...runtime.memory import TpuRetryOOM
    inj = get_injector(conf)
    if not inj.enabled:
        inj = get_active_injector()
    try:
        inj.fire("kernel", kernel=kernel, mode=tier.mode)
    except TpuRetryOOM:
        count_fallback(kernel, "oom")
        from ...obs.tracer import get_active
        get_active().instant("kernel_fallback", "runtime", kernel=kernel,
                             reason="oom")
        return False
    _count_dispatch(kernel, tier)
    return True


# ---------------------------------------------------------------------------
# Per-family election gates (the legality negotiations)
# ---------------------------------------------------------------------------

def elect_join(conf: TpuConf, build_capacity: int,
               dense_span: Optional[int] = None) -> Optional[KernelTier]:
    """The hash-probe join election visible at exec level: tier on,
    join family on, build side small enough to table, and — when the
    join ALSO qualifies for a dense direct-address table over
    `dense_span` keys — the denseReplace policy: AUTO replaces the
    dense table only when span > 4x build capacity (where the dense
    build's span-sized sorts dominate; below it the dense one-gather
    probes win).  Lane-count legality finishes inside
    ops.join.BuildTable (the canonical lane set is only known there)."""
    tier = kernel_tier(conf)
    if not tier.join:
        return None
    if build_capacity > tier.join_max_build:
        count_fallback("hash_probe_join", "build_too_large")
        return None
    if dense_span is not None:
        mode = tier.join_dense_replace
        replace = (mode == "ON") or (
            mode == "AUTO" and dense_span > 4 * build_capacity)
        if not replace:
            count_fallback("hash_probe_join", "dense_domain")
            return None
    if not elect(conf, tier, "hash_probe_join"):
        return None
    return tier


def elect_segagg(conf: TpuConf, total_domain: int,
                 has_float_sum: bool) -> Optional[KernelTier]:
    """Segmented-aggregation election: tier on, segagg family on, the
    packed key domain fits the block accumulator, and float sums are
    allowed to re-associate (variableFloatAgg — block-parallel partial
    sums combine in a different order than the sorted-run scan)."""
    tier = kernel_tier(conf)
    if not tier.segagg:
        return None
    if total_domain > tier.segagg_max_domain:
        count_fallback("segagg", "domain_too_large")
        return None
    if has_float_sum:
        from ...config import IMPROVED_FLOAT_OPS
        if not conf.get(IMPROVED_FLOAT_OPS):
            count_fallback("segagg", "float_exact")
            return None
    if not elect(conf, tier, "segagg"):
        return None
    return tier


def elect_compact(conf: TpuConf, capacity: int) -> Optional[KernelTier]:
    """Compaction election: tier on, compact family on, capacity large
    enough that the rank-search beats the argsort's fixed cost."""
    tier = kernel_tier(conf)
    if not tier.compact or capacity < 1024:
        return None
    if not elect(conf, tier, "compact"):
        return None
    return tier
