"""Selection compaction: prefix-sum + rank-search order, in Pallas.

The portable compaction is ONE stable int8-key argsort of the inverted
keep-mask (ops/filter.compaction_order) — sort-shaped because XLA
lowers it well, but still an O(n log n) full-lane sort for what is
logically a prefix sum.  This kernel replaces it for selective
predicates: the keep-mask's blocked inclusive prefix sum assigns every
kept row its output rank; the kernel grids over OUTPUT blocks and
finds, for each output slot j < count, the source row via a vectorized
binary search over the monotone rank lane — log2(capacity) rounds of
gathers on ONE int32 lane, instead of sorting every row of every lane
class.  Slots past the kept count keep identity order (their validity
dies under the live mask downstream, exactly like the argsort tail).

The fused shape the filter path gets: mask evaluate (already traced
into the same program) -> blocked_cumsum -> this kernel -> the shared
grouped_take gather — no sort equation in the emitted program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..kernels import blocked_cumsum

_COMPACT_CACHE = {}


def _out_block(capacity: int) -> int:
    capacity = max(capacity, 1)
    blk = min(capacity, 1 << 20)
    while blk > 1 and capacity % blk:
        blk >>= 1
    return blk if capacity // blk <= 64 else capacity


def compaction_order(keep: jax.Array, interpret: bool) -> jax.Array:
    """Indices bringing keep=True rows to the front, stably — the
    Pallas analogue of ops/filter.compaction_order.  The tail (slots
    >= count) is identity, not the dropped rows: every consumer masks
    validity beyond the kept count, so only the front order is
    contractual."""
    cap = int(keep.shape[0])
    sig = ("order", cap, interpret)
    fn = _COMPACT_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_order_trace(cap, interpret))
        _COMPACT_CACHE[sig] = fn
    return fn(keep)


def _order_trace(cap: int, interpret: bool):
    blk = _out_block(cap)
    grid = max(1, cap // blk)
    rounds = max(1, (max(cap, 1) - 1).bit_length() + 1)

    def kernel(cum_ref, ord_ref):
        j = pl.program_id(0) * blk + \
            jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)[:, 0]
        tgt = j + 1
        lo = jnp.zeros((blk,), jnp.int32)
        hi = jnp.full((blk,), cap, jnp.int32)

        def body(_, c):
            lo, hi = c
            mid = jnp.minimum((lo + hi) // 2, cap - 1)
            go_hi = cum_ref[mid] < tgt
            return (jnp.where(go_hi, mid + 1, lo),
                    jnp.where(go_hi, hi, mid))

        lo, _ = jax.lax.fori_loop(0, rounds, body, (lo, hi))
        total = cum_ref[cap - 1]
        ord_ref[...] = jnp.where(j < total,
                                 jnp.minimum(lo, cap - 1), j)

    def run(keep):
        cum = blocked_cumsum(keep.astype(jnp.int32))
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((cap,), lambda i: (0,))],
            out_specs=pl.BlockSpec((blk,), lambda i: (i,)),
            out_shape=jax.ShapeDtypeStruct((cap,), jnp.int32),
            interpret=interpret,
        )(cum)
    return run
