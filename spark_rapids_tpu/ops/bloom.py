"""Bloom-filter runtime join filtering.

Role of the reference's BloomFilter JNI kernel + bloom_filter_agg /
bloom_filter_might_contain (SURVEY §2.9 spark-rapids-jni surface; Spark's
InjectRuntimeFilter inserts them around large shuffled joins on 3.3+).
Here the natural insertion point is the adaptive join
(exec/adaptive.py): the build side is already fully materialized when
the probe side replays, so the filter costs one scatter pass over build
keys and one gather pass per probe batch, both fused device programs.

TPU-first representation: the bitset is a plain bool vector (m slots)
rather than packed words — scatter-set and gather are single XLA ops,
there is no bit-packing ALU work on the critical path, and at the
default sizing (<= 2^22 slots = 4 MiB) HBM cost is noise next to the
build side it summarizes.  Hashing reuses the engine's lane-normalized
row hash (exec/plan._agg_partition_ids — equal keys hash equal across
batches and spills) with double hashing h1 + i*h2 for k probes.

False positives only ever ADMIT probe rows the join then drops; rows
whose key IS in the build side always pass (every live build row sets
its bits).  Padding lanes in build batches may set spurious bits —
harmless by the same argument.
"""
from __future__ import annotations

import math
from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn

DEFAULT_FPP = 0.03
_MIN_SLOTS = 1 << 10
_MAX_SLOTS = 1 << 22


def optimal_slots(n_items: int, fpp: float = DEFAULT_FPP) -> int:
    """Bloom sizing (standard -n*ln(p)/ln2^2), clamped to a power of
    two in [2^10, 2^22]."""
    n = max(1, n_items)
    m = int(-n * math.log(fpp) / (math.log(2) ** 2))
    return max(_MIN_SLOTS, min(_MAX_SLOTS, 1 << max(1, m - 1).bit_length()))


def optimal_hashes(n_items: int, m_slots: int) -> int:
    k = int(round(m_slots / max(1, n_items) * math.log(2)))
    return max(1, min(6, k))


def _double_hashes(key_cols: Sequence[DeviceColumn], db: DeviceBatch,
                   m_slots: int):
    """(h1, h2) in [0, m): two decorrelated lane-normalized row hashes."""
    from ..exec.plan import _agg_partition_ids
    kb = DeviceBatch(list(key_cols), db.num_rows,
                     [f"_k{i}" for i in range(len(key_cols))])
    h1 = _agg_partition_ids(kb, len(key_cols), m_slots, salt=11)
    h2 = _agg_partition_ids(kb, len(key_cols), m_slots - 1, salt=23)
    return jnp.asarray(h1), jnp.asarray(h2) + 1   # h2 in [1, m)


def bloom_build(key_cols: Sequence[DeviceColumn], db: DeviceBatch,
                m_slots: int, k: int,
                bits: jax.Array = None,
                live: jax.Array = None) -> jax.Array:
    """Set the k slots of every row's key; pass `bits` to accumulate
    over multiple build batches.  `live` masks rows out of insertion
    (fused build-side filters): without it their keys would only widen
    the filter (false positives stay sound), but the bloom loses exactly
    the selectivity the build filter was supposed to give it."""
    if bits is None:
        bits = jnp.zeros((m_slots,), bool)
    h1, h2 = _double_hashes(key_cols, db, m_slots)
    for i in range(k):
        idx = (h1 + i * h2) % m_slots
        if live is not None:
            idx = jnp.where(live, idx, m_slots)
        bits = bits.at[idx].set(True, mode="drop")
    return bits


def bloom_might_contain(bits: jax.Array,
                        key_cols: Sequence[DeviceColumn],
                        db: DeviceBatch, k: int) -> jax.Array:
    """Bool mask per lane: False only when the key is DEFINITELY absent
    from the build side."""
    m_slots = bits.shape[0]
    h1, h2 = _double_hashes(key_cols, db, m_slots)
    out = jnp.ones((db.capacity,), bool)
    for i in range(k):
        idx = (h1 + i * h2) % m_slots
        out = out & bits[idx]
    return out
