"""Sort-based percentile kernels on the sort-segment machinery.

Role of the reference's GpuPercentile / Histogram JNI kernel
(GpuPercentile.scala, SURVEY §2.5 aggregate set) and of
GpuApproximatePercentile's t-digest: this engine computes EXACT
percentiles on device — the values sort as an extra minor lexsort lane
under the group keys, so every group's values are contiguous ascending
runs and each requested percentile is two gathers + a lerp.  Exact
results trivially satisfy approx_percentile's rank-error contract.

Ordering follows Spark's double sort: values ascending with NaN
greatest; null values sort after everything inside their group and are
excluded from the count.  A group with zero non-null values yields
null.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .groupby import _eq_prev, _null_first_key_lanes
from .kernels import blocked_cumsum, compute_view


def sorted_segments(key_lanes_info, keys, keys_valid, live,
                    minor_lanes, capacity: int, num_segments: int,
                    pack_spec=None):
    """Shared sort-segment core for holistic aggregates (percentile,
    count-distinct, collect): lexsort rows by (dead-last, group keys,
    minor_lanes most-minor-first), find group boundaries, return

      (perm, s_live, s_keys, s_keys_valid, seg_ids, start_idx,
       out_keys, num_groups, group_live)

    `minor_lanes` order rows WITHIN a group (value lanes, null flags);
    they do not contribute to boundaries.

    pack_spec: per-key (lo, span) covering EVERY key (exec layer: plan
    range stats, dictionary sizes, bools) folds the whole key tuple plus
    liveness into ONE sort lane — TPU sort compile time scales with
    operand count (a 9-operand lexsort at 1M is minutes; the packed form
    is seconds), group keys decode arithmetically (zero key gathers),
    and the boundary compare touches one lane."""
    from .filter import take_keys_valid
    packed_all = pack_spec is not None and len(pack_spec) == \
        len(key_lanes_info) and all(s is not None for s in pack_spec)
    if packed_all:
        from .groupby import _packed_key_lane
        spans = [s[1] for s in pack_spec]
        total = 1
        for sp in spans:
            total *= sp
        packed = _packed_key_lane(keys, keys_valid, pack_spec)
        key_lane = jnp.where(live, packed, jnp.int64(total))
        if total < (1 << 31) - 1:
            key_lane = key_lane.astype(jnp.int32)
        sort_keys = list(minor_lanes) + [key_lane]
        perm = jnp.lexsort(sort_keys)
        s_key = key_lane[perm]
        s_live = s_key < jnp.asarray(total, s_key.dtype)
        boundary = _eq_prev(s_key)
        seg_ids = blocked_cumsum(boundary.astype(jnp.int32)) - 1
        count = jnp.sum(live, dtype=jnp.int32)
        num_groups = jnp.where(count > 0,
                               seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)
        group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
        start_idx = jnp.sort(jnp.where(
            boundary & s_live, jnp.arange(capacity, dtype=jnp.int32),
            jnp.int32(capacity)))[:num_segments]
        start_idx = jnp.clip(start_idx, 0, capacity - 1)
        # keys decode from the packed value at segment starts
        strides = []
        tot = 1
        for sp in reversed(spans):
            strides.append(tot)
            tot *= sp
        strides.reverse()
        pk = s_key[start_idx].astype(jnp.int64)
        out_keys = []
        for (dt, _hv, lane_dt), (lo, span), stride in zip(
                key_lanes_info, pack_spec, strides):
            slot = (pk // jnp.int64(stride)) % jnp.int64(span)
            okd = (slot - 1 + jnp.int64(lo)).astype(jnp.dtype(lane_dt))
            out_keys.append((okd, (slot > 0) & group_live))
        return (perm, s_live, None, None, seg_ids, start_idx,
                out_keys, num_groups, group_live)

    lanes = []
    for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, keys, keys_valid):
        sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
        lanes.extend([l for l in sub if l is not None])
    # lexsort: LAST key is primary
    sort_keys = list(minor_lanes) + list(reversed(lanes)) + \
        [(~live).astype(jnp.int8)]
    perm = jnp.lexsort(sort_keys)
    # one stacked gather pass per dtype class (TPU gathers pay per row,
    # ~20ms per 1M-row pass — per-lane takes multiply that)
    s_keys, s_keys_valid, (s_live,) = take_keys_valid(
        keys, keys_valid, [live], perm)

    boundary = jnp.zeros((capacity,), bool).at[0].set(True)
    for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, s_keys,
                                      s_keys_valid):
        sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
        for lane in sub:
            if lane is not None:
                boundary = boundary | _eq_prev(lane)
    pad_start = jnp.concatenate([jnp.ones((1,), bool),
                                 s_live[1:] != s_live[:-1]])
    boundary = boundary | pad_start
    seg_ids = blocked_cumsum(boundary.astype(jnp.int32)) - 1
    count = jnp.sum(live, dtype=jnp.int32)
    num_groups = jnp.where(count > 0,
                           seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)
    group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups

    # seg ids rise with position, so the g-th boundary IS segment g's
    # start: a single-lane sort compacts them (no segment_min scatter —
    # scatter outputs land in slow S(1) buffers on this platform)
    start_idx = jnp.sort(jnp.where(
        boundary, jnp.arange(capacity, dtype=jnp.int32),
        jnp.int32(capacity)))[:num_segments]
    start_idx = jnp.clip(start_idx, 0, capacity - 1)
    okds, okvs, _ = take_keys_valid(s_keys, s_keys_valid, [], start_idx)
    out_keys = []
    for okd, okv in zip(okds, okvs):
        okv = jnp.ones((capacity,), bool) if okv is None else okv
        out_keys.append((okd, okv & group_live))
    return (perm, s_live, s_keys, s_keys_valid, seg_ids, start_idx,
            out_keys, num_groups, group_live)


def sketch_trace(key_lanes_info, k: int, num_segments: int,
                 capacity: int, pack_spec=None):
    """Traced PARTIAL of the mergeable approx_percentile: per group, the
    non-null count and k equi-rank order statistics
    (ops/quantile_sketch.py; reference GpuApproximatePercentile.scala
    builds cuDF t-digests in partial mode).  Returns
    (out_keys, cnt, points[num_segments, k], num_groups)."""
    from .quantile_sketch import sketch_gather

    def run(keys, keys_valid, val, val_valid, live):
        vlive = live & val_valid
        isnan = jnp.isnan(val)
        clean = jnp.where(isnan, 0.0, val)
        minor = [clean, isnan.astype(jnp.int8), (~vlive).astype(jnp.int8)]
        (perm, _s_live, _sk, _skv, seg_ids, start_idx, out_keys,
         num_groups, _group_live) = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec)
        s_vlive = vlive[perm]
        s_val = val[perm]
        cnt = jax.ops.segment_sum(s_vlive.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments)
        pts = sketch_gather(s_val, start_idx, cnt, k, num_segments,
                            capacity)
        return out_keys, cnt, pts, num_groups

    return run


def percentile_trace(key_lanes_info, qs: Sequence[float],
                     num_segments: int, capacity: int, pack_spec=None):
    """Traced fn: (keys, keys_valid, val_f64, val_valid, live) ->
    (out_keys [(data, valid)...], [(vals, valid) per q], num_groups).
    With zero keys this is the global single-group reduction."""
    qs = [float(q) for q in qs]

    def run(keys, keys_valid, val, val_valid, live):
        vlive = live & val_valid
        isnan = jnp.isnan(val)
        # neutralize NaN for the comparator; a separate flag lane orders
        # them greatest-within-group (Spark double ordering)
        clean = jnp.where(isnan, 0.0, val)
        # minor order within group: values asc, NaN after, nulls last
        minor = [clean, isnan.astype(jnp.int8),
                 (~vlive).astype(jnp.int8)]
        (perm, s_live, _sk, _skv, seg_ids, start_idx, out_keys,
         num_groups, group_live) = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec)
        s_vlive = vlive[perm]
        s_val = val[perm]

        # non-null values per group sit at [start, start + cnt)
        cnt = jax.ops.segment_sum(s_vlive.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments)
        out = []
        for q in qs:
            pos = (cnt - 1).astype(jnp.float64) * jnp.float64(q)
            lo = jnp.floor(pos).astype(jnp.int32)
            hi = jnp.ceil(pos).astype(jnp.int32)
            frac = pos - lo.astype(jnp.float64)
            gi_lo = jnp.clip(start_idx + jnp.maximum(lo, 0),
                             0, capacity - 1)
            gi_hi = jnp.clip(start_idx + jnp.maximum(hi, 0),
                             0, capacity - 1)
            v_lo = s_val[gi_lo]
            v_hi = s_val[gi_hi]
            # integral rank returns v_lo exactly: a NaN at the unused
            # hi endpoint must not contaminate (NaN * 0 is NaN)
            res = jnp.where(frac == 0.0, v_lo,
                            v_lo + (v_hi - v_lo) * frac)
            out.append((res, (cnt > 0) & group_live))
        return out_keys, out, num_groups

    return run


def collect_trace(key_lanes_info, num_segments: int, capacity: int,
                  distinct: bool, val_dtype, pack_spec=None):
    """Traced collect_list / collect_set as a group-by emitting a RAGGED
    column (reference GpuAggregateExec.scala collect ops over cuDF
    lists).  Sort-by-(key[, value], position) makes every group's kept
    values a contiguous run; a single-lane sort compacts the keep-mask
    into gather indices — no scatters.

    collect_list keeps non-null values in input order (stable sort on
    the position payload); collect_set additionally keeps only the first
    of each distinct value within a group (order unspecified by Spark —
    here value-sorted).  Returns (out_keys, values, elem_offsets,
    num_groups); values lane capacity == row capacity."""
    from .distinct import _value_eq_lanes

    def run(keys, keys_valid, val, val_valid, live):
        vlive = live & val_valid
        idx = jnp.arange(capacity, dtype=jnp.int32)
        if distinct:
            vlanes = _value_eq_lanes(val, val_dtype)
            minor = [idx] + list(vlanes) + [(~vlive).astype(jnp.int8)]
        else:
            minor = [idx, (~vlive).astype(jnp.int8)]
        (perm, _s_live, _sk, _skv, seg_ids, _start, out_keys,
         num_groups, group_live) = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec)
        s_vlive = vlive[perm]
        s_val = val[perm]
        keep = s_vlive
        if distinct:
            changed = _eq_prev(seg_ids)
            for lane in _value_eq_lanes(s_val, val_dtype):
                changed = changed | _eq_prev(lane)
            keep = keep & changed
        # kept positions compact to the front, in (group, order) order
        kept = jnp.sort(jnp.where(keep, idx, jnp.int32(capacity)))
        kept_c = jnp.clip(kept, 0, capacity - 1)
        n_kept = jnp.sum(keep, dtype=jnp.int32)
        values = s_val[kept_c]
        # per-group counts -> element offsets (scatter-free: counts are
        # ends-starts in the kept ordering).  kept slots are grouped by
        # seg id, so each group's count = (# kept with seg < g+1) -
        # (# kept with seg < g): one cumulative histogram via merge rank
        kept_seg = seg_ids[kept_c]
        kept_seg = jnp.where(jnp.arange(capacity) < n_kept, kept_seg,
                             jnp.int32(num_segments))
        # rank of each group boundary in the kept_seg (sorted) lane:
        # offsets[g] = count of kept with seg < g — merge-rank (two lean
        # 2-operand sorts), not binary search (log-step dependent
        # gathers are the slowest access pattern on this chip)
        from .join import _merge_rank
        offs = _merge_rank(
            kept_seg.astype(jnp.uint64),
            jnp.arange(num_segments + 1, dtype=jnp.uint64),
            side="left").astype(jnp.int32)
        elem_valid = jnp.arange(capacity, dtype=jnp.int32) < n_kept
        return out_keys, values, offs, elem_valid, num_groups, group_live

    return run
