"""Sort-based percentile kernels on the sort-segment machinery.

Role of the reference's GpuPercentile / Histogram JNI kernel
(GpuPercentile.scala, SURVEY §2.5 aggregate set) and of
GpuApproximatePercentile's t-digest: this engine computes EXACT
percentiles on device — the values sort as an extra minor lane under
the group keys, so every group's values are contiguous ascending runs
and each requested percentile is two gathers + a lerp.  Exact results
trivially satisfy approx_percentile's rank-error contract.

Ordering follows Spark's double sort: values ascending with NaN
greatest; null values sort after everything inside their group and are
excluded from the count.  A group with zero non-null values yields
null.

The shared sort-segment core (`sorted_segments`) lives in
ops/segments.py; this module keeps a re-export for older callers.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .groupby import _eq_prev
from .kernels import blocked_cumsum, compute_view
from .segments import (SegRuns, seg_sums_sorted,            # noqa: F401
                       sorted_segments)


def _value_order_lanes(val, val_valid, live):
    """(vlive, minor lanes, minor spec) ordering a group's values the
    Spark way: values ascending, NaN after all values, nulls last.  The
    NaN and null flags FOLD into one small int lane (z), so the minor
    order is two lanes — one fewer emitted sort on the chained path."""
    vlive = live & val_valid
    isnan = jnp.isnan(val)
    clean = jnp.where(isnan, 0.0, val)
    z = isnan.astype(jnp.int8) + 2 * (~vlive).astype(jnp.int8)
    return vlive, [clean, z], [None, (0, 4)]


def _seg_valid_counts(s_vlive, runs: SegRuns, num_segments: int,
                      scatter_free: bool):
    """Per-segment non-null count: stacked-cumsum boundary diff when
    scatter-free, legacy segment_sum scatter otherwise."""
    if scatter_free:
        return seg_sums_sorted([s_vlive.astype(jnp.int32)],
                               runs.start_idx, runs.end_idx)[:, 0]
    return jax.ops.segment_sum(s_vlive.astype(jnp.int32), runs.seg_ids,
                               num_segments=num_segments)


def sketch_trace(key_lanes_info, k: int, num_segments: int,
                 capacity: int, pack_spec=None, scatter_free=True,
                 max_sort_operands=2):
    """Traced PARTIAL of the mergeable approx_percentile: per group, the
    non-null count and k equi-rank order statistics
    (ops/quantile_sketch.py; reference GpuApproximatePercentile.scala
    builds cuDF t-digests in partial mode).  Returns
    (out_keys, cnt, points[num_segments, k], num_groups)."""
    from .quantile_sketch import sketch_gather

    def run(keys, keys_valid, val, val_valid, live):
        vlive, minor, minor_spec = _value_order_lanes(val, val_valid,
                                                      live)
        runs = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec, minor_spec=minor_spec,
            max_sort_operands=max_sort_operands)
        s_vlive = vlive[runs.perm]
        s_val = val[runs.perm]
        cnt = _seg_valid_counts(s_vlive, runs, num_segments,
                                scatter_free)
        pts = sketch_gather(s_val, runs.start_idx, cnt, k, num_segments,
                            capacity)
        return runs.out_keys, cnt, pts, runs.num_groups

    return run


def percentile_trace(key_lanes_info, qs: Sequence[float],
                     num_segments: int, capacity: int, pack_spec=None,
                     scatter_free=True, max_sort_operands=2):
    """Traced fn: (keys, keys_valid, val_f64, val_valid, live) ->
    (out_keys [(data, valid)...], [(vals, valid) per q], num_groups).
    With zero keys this is the global single-group reduction."""
    qs = [float(q) for q in qs]

    def run(keys, keys_valid, val, val_valid, live):
        vlive, minor, minor_spec = _value_order_lanes(val, val_valid,
                                                      live)
        runs = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec, minor_spec=minor_spec,
            max_sort_operands=max_sort_operands)
        s_vlive = vlive[runs.perm]
        s_val = val[runs.perm]

        # non-null values per group sit at [start, start + cnt)
        cnt = _seg_valid_counts(s_vlive, runs, num_segments,
                                scatter_free)
        start_idx = runs.start_idx
        out = []
        for q in qs:
            pos = (cnt - 1).astype(jnp.float64) * jnp.float64(q)
            lo = jnp.floor(pos).astype(jnp.int32)
            hi = jnp.ceil(pos).astype(jnp.int32)
            frac = pos - lo.astype(jnp.float64)
            gi_lo = jnp.clip(start_idx + jnp.maximum(lo, 0),
                             0, capacity - 1)
            gi_hi = jnp.clip(start_idx + jnp.maximum(hi, 0),
                             0, capacity - 1)
            v_lo = s_val[gi_lo]
            v_hi = s_val[gi_hi]
            # integral rank returns v_lo exactly: a NaN at the unused
            # hi endpoint must not contaminate (NaN * 0 is NaN)
            res = jnp.where(frac == 0.0, v_lo,
                            v_lo + (v_hi - v_lo) * frac)
            out.append((res, (cnt > 0) & runs.group_live))
        return runs.out_keys, out, runs.num_groups

    return run


def collect_trace(key_lanes_info, num_segments: int, capacity: int,
                  distinct: bool, val_dtype, pack_spec=None,
                  max_sort_operands=2):
    """Traced collect_list / collect_set as a group-by emitting a RAGGED
    column (reference GpuAggregateExec.scala collect ops over cuDF
    lists).  Sort-by-(key[, value], position) makes every group's kept
    values a contiguous run; a single-lane sort compacts the keep-mask
    into gather indices — no scatters.

    collect_list keeps non-null values in input order (stable sort on
    the position payload); collect_set additionally keeps only the first
    of each distinct value within a group (order unspecified by Spark —
    here value-sorted).  Returns (out_keys, values, elem_offsets,
    num_groups); values lane capacity == row capacity."""
    from .distinct import _value_eq_lanes

    def run(keys, keys_valid, val, val_valid, live):
        vlive = live & val_valid
        idx = jnp.arange(capacity, dtype=jnp.int32)
        if distinct:
            vlanes = _value_eq_lanes(val, val_dtype)
            minor = [idx] + list(vlanes) + [(~vlive).astype(jnp.int8)]
        else:
            minor = [idx, (~vlive).astype(jnp.int8)]
        runs = sorted_segments(
            key_lanes_info, keys, keys_valid, live, minor, capacity,
            num_segments, pack_spec=pack_spec,
            max_sort_operands=max_sort_operands)
        perm, seg_ids = runs.perm, runs.seg_ids
        group_live = runs.group_live
        s_vlive = vlive[perm]
        s_val = val[perm]
        keep = s_vlive
        if distinct:
            changed = _eq_prev(seg_ids)
            for lane in _value_eq_lanes(s_val, val_dtype):
                changed = changed | _eq_prev(lane)
            keep = keep & changed
        # kept positions compact to the front, in (group, order) order
        kept = jnp.sort(jnp.where(keep, idx, jnp.int32(capacity)))
        kept_c = jnp.clip(kept, 0, capacity - 1)
        n_kept = jnp.sum(keep, dtype=jnp.int32)
        values = s_val[kept_c]
        # per-group counts -> element offsets (scatter-free: counts are
        # ends-starts in the kept ordering).  kept slots are grouped by
        # seg id, so each group's count = (# kept with seg < g+1) -
        # (# kept with seg < g): one cumulative histogram via merge rank
        kept_seg = seg_ids[kept_c]
        kept_seg = jnp.where(jnp.arange(capacity) < n_kept, kept_seg,
                             jnp.int32(num_segments))
        # rank of each group boundary in the kept_seg (sorted) lane:
        # offsets[g] = count of kept with seg < g — merge-rank (two lean
        # 2-operand sorts), not binary search (log-step dependent
        # gathers are the slowest access pattern on this chip)
        from .join import _merge_rank
        offs = _merge_rank(
            kept_seg.astype(jnp.uint64),
            jnp.arange(num_segments + 1, dtype=jnp.uint64),
            side="left").astype(jnp.int32)
        elem_valid = jnp.arange(capacity, dtype=jnp.int32) < n_kept
        return (runs.out_keys, values, offs, elem_valid,
                runs.num_groups, group_live)

    return run
