"""Unified segmented-reduction / packed-sort kernel layer.

Every holistic operator in this engine (group-by, count-distinct,
percentile, collect, window frames, sort-merge joins) reduces over
CONTIGUOUS RUNS of a sorted batch.  This module is the one home for the
primitives those operators share, shaped by the two platform costs that
dominate this chip (docs/PERF.md §1):

  * **Scatters are the enemy at runtime** (~70 ms per 1M rows, and their
    outputs land in S(1)-space buffers whose consumers run ~200 MB/s).
    Wherever an order exists, a segment reduction is a *blocked
    segmented scan* (the `blocked_cumsum` pattern: fixed 512-row blocks,
    compiles in seconds where one long scan costs minutes) followed by a
    gather at each run's END row — scan + gather, never scatter.

  * **Sort operand count is the enemy at compile time** (2-operand sort
    31 s, 3×i64 lexsort 164 s, 10-operand ≈ 10 min at 1M on v5e).
    `lexsort_capped` emits a chain of stable ≤N-operand sorts instead of
    one wide variadic sort, and `sorted_segments` folds statically
    bounded group keys — and, new here, bounded minor/value lanes — into
    ONE packed integer lane so the whole (keys, values) order is a
    single 2-operand sort.

`sorted_segments` (previously in ops/percentile.py; ops/distinct.py used
to import it cross-module from there) is the shared sort-segment core
for the holistic aggregates.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import blocked_cumsum

_SEG_BLOCK = 512
_SEG_MIN = 4096


def op_identity(op, dtype):
    """Identity element of a scan combiner over `dtype` lanes: the value
    e with op(e, v) == v for every v the lane can carry."""
    dt = np.dtype(dtype)
    if op is jnp.add:
        return np.zeros((), dt)
    if dt == np.bool_:
        # minimum == logical and (ident True), maximum == or (ident False)
        return np.bool_(op is jnp.minimum)
    if np.issubdtype(dt, np.inexact):
        return dt.type(np.inf if op is jnp.minimum else -np.inf)
    info = np.iinfo(dt)
    return dt.type(info.max if op is jnp.minimum else info.min)


def _doubling_seg_scan(v, f, length, op, ident, axis: int):
    """Hillis-Steele inclusive segmented scan along `axis` via log2(length)
    shift+combine steps over the (value, boundary-flag) monoid — every
    step is elementwise VPU work, so COMPILE time stays flat where XLA's
    native log-depth scan lowering of one long axis runs minutes."""
    step = 1
    while step < length:
        pad_shape = list(v.shape)
        pad_shape[axis] = step
        sl = [slice(None)] * v.ndim
        sl[axis] = slice(None, -step)
        pv = jnp.concatenate(
            [jnp.full(pad_shape, ident, v.dtype), v[tuple(sl)]], axis=axis)
        fpad = list(f.shape)
        fpad[axis] = step
        fsl = [slice(None)] * f.ndim
        fsl[axis] = slice(None, -step)
        pf = jnp.concatenate(
            [jnp.zeros(fpad, bool), f[tuple(fsl)]], axis=axis)
        fb = f if f.ndim == v.ndim else f[..., None]
        v = jnp.where(fb, v, op(pv, v))
        f = f | pf
        step <<= 1
    return v, f


def blocked_seg_scan(vals: jax.Array, boundary: jax.Array, op,
                     ident=None) -> jax.Array:
    """Segmented INCLUSIVE scan along axis 0: the running op-combine that
    resets at rows where `boundary` is True.  `vals` is (n,) or (n, k)
    with one boundary lane shared by all k columns.

    Identical semantics to a `lax.associative_scan` over the standard
    (value, start-flag) segmented monoid, but compiled as fixed 512-row
    blocks + a cross-block carry (the `blocked_cumsum` shape): an 80 s
    associative_scan compile at 1M becomes ~2 s of elementwise passes.
    """
    n = vals.shape[0]
    if ident is None:
        ident = op_identity(op, vals.dtype)
    ident = jnp.asarray(ident, vals.dtype)
    if n < _SEG_MIN or n % _SEG_BLOCK != 0:
        v, _f = _doubling_seg_scan(vals, boundary, n, op, ident, axis=0)
        return v
    nb = n // _SEG_BLOCK
    v = vals.reshape((nb, _SEG_BLOCK) + vals.shape[1:])
    f = boundary.reshape(nb, _SEG_BLOCK)
    v, f = _doubling_seg_scan(v, f, _SEG_BLOCK, op, ident, axis=1)
    # cross-block carry: exclusive segmented scan of per-block totals;
    # a block's carry only reaches rows before its first boundary, which
    # is exactly where the scanned in-block flag is still False
    tv, tf = v[:, -1], f[:, -1]
    cv, _cf = _doubling_seg_scan(tv, tf, nb, op, ident, axis=0)
    carry = jnp.expand_dims(jnp.concatenate(
        [jnp.full((1,) + tv.shape[1:], ident, v.dtype), cv[:-1]]), 1)
    fb = f if f.ndim == v.ndim else f[..., None]
    out = jnp.where(fb, v, op(carry, v))
    return out.reshape(vals.shape)


def seg_reduce_sorted(vals: jax.Array, boundary: jax.Array,
                      ends_c: jax.Array, op, ident=None) -> jax.Array:
    """Per-segment reduction over sorted runs, scatter-free: the
    segmented scan's value at each run's last row IS the run's
    reduction — one gather at `ends_c` (segment-slot -> last row index)
    replaces a jax.ops.segment_* scatter whose output would land in a
    slow S(1) buffer."""
    return blocked_seg_scan(vals, boundary, op, ident)[ends_c]


def seg_sums_sorted(lanes: Sequence[jax.Array], starts_c: jax.Array,
                    ends_c: jax.Array) -> jax.Array:
    """(num_segments, k) per-segment sums of int lanes over sorted runs:
    ONE stacked blocked cumsum + two boundary gathers.  int64
    wraparound cancels in the diff, so this is exact whenever the
    segment sum fits int64 — segment_sum's own contract."""
    cs = blocked_cumsum(jnp.stack(list(lanes), axis=1))
    hi = cs[ends_c]
    lo = jnp.where((starts_c > 0)[:, None],
                   cs[jnp.maximum(starts_c - 1, 0)], 0)
    return hi - lo


def row0_true(capacity: int) -> jax.Array:
    """Boundary-lane seed: True at row 0.  Built by concatenation, not
    `.at[0].set` — the scatter that set would lower to is exactly the op
    class this layer exists to avoid (and the jaxpr scatter lint counts
    it)."""
    return jnp.concatenate([jnp.ones((1,), bool),
                            jnp.zeros((capacity - 1,), bool)])


# ---------------------------------------------------------------------------
# Operand-capped lexsort
# ---------------------------------------------------------------------------

def lexsort_capped(lanes: Sequence[jax.Array],
                   max_operands: int = 2) -> jax.Array:
    """`jnp.lexsort` semantics (LAST lane is the primary key) emitting
    only sorts of <= max_operands operands (keys + payload lane).

    One variadic lexsort compiles in time that grows brutally with
    operand count on TPU (3×i64 at 1M: 164 s; 10 operands: ~10 min); a
    chain of stable (key..., perm) sorts — most-minor lane first, each
    later key gathered through the running permutation — costs one
    ~20 ms/1M gather per extra lane at runtime but keeps every emitted
    sort within the compile-friendly budget."""
    lanes = list(lanes)
    assert lanes, "lexsort of zero lanes"
    keys_per_sort = max(1, max_operands - 1)
    if len(lanes) + 1 <= max_operands:
        return jnp.lexsort(lanes)
    perm = None
    i = 0
    while i < len(lanes):
        chunk = lanes[i:i + keys_per_sort]
        i += keys_per_sort
        if perm is None:
            n = chunk[0].shape[0]
            perm = jnp.arange(n, dtype=jnp.int32)
        else:
            chunk = [c[perm] for c in chunk]
        # lax.sort key order is primary-first; chunk arrives minor-first
        ops = tuple(reversed(chunk)) + (perm,)
        out = jax.lax.sort(ops, num_keys=len(chunk), is_stable=True)
        perm = out[-1]
    return perm


# ---------------------------------------------------------------------------
# Merge-rank matched flags (the scatter-free segment_max over indices)
# ---------------------------------------------------------------------------

def matched_flags(idx: jax.Array, ok: jax.Array, n: int) -> jax.Array:
    """(n,) flags: row r is True iff some position has ok & idx == r.

    The scatter formulation (`zeros.at[idx].max(ok)`) pays the ~70 ms/1M
    serialization cost and parks its output in an S(1) buffer; here the
    ok-masked indices sort into one lane (1-operand sort) and each row's
    hit count falls out of a merge-rank difference (two lean 2-operand
    sorts, ops/join._merge_rank)."""
    from .join import _merge_rank
    s = jnp.sort(jnp.where(ok, idx, n).astype(jnp.uint32))
    hi = _merge_rank(s.astype(jnp.uint64),
                     jnp.arange(n, dtype=jnp.uint64), side="right")
    prev = jnp.concatenate([jnp.zeros((1,), hi.dtype), hi[:-1]])
    return hi > prev


# ---------------------------------------------------------------------------
# sorted_segments: the shared sort-segment core for holistic aggregates
# ---------------------------------------------------------------------------

class SegRuns(NamedTuple):
    """Sorted-run structure shared by the holistic aggregates.

    perm: row permutation into (group, minor) order; s_live: liveness in
    sorted order; s_keys/s_keys_valid: sorted key lanes (None on the
    packed path — keys decode arithmetically); seg_ids: per-row segment
    id; start_idx/end_idx: per segment-slot first/last row (clipped,
    garbage beyond num_groups); out_keys: [(data, valid)] per key;
    num_groups: live-group count scalar; group_live: segment-slot mask.
    """
    perm: jax.Array
    s_live: jax.Array
    s_keys: Optional[list]
    s_keys_valid: Optional[list]
    seg_ids: jax.Array
    start_idx: jax.Array
    end_idx: jax.Array
    out_keys: list
    num_groups: jax.Array
    group_live: jax.Array


def segment_ends(start_raw, count, capacity: int):
    """Per segment-slot last-row index from the slot-ordered UNCLIPPED
    starts (dead slots carry the `capacity` sentinel): the next slot's
    start - 1, clipped into the live prefix."""
    nexts = jnp.concatenate(
        [start_raw[1:], jnp.full((1,), capacity, jnp.int32)])
    return jnp.clip(jnp.minimum(nexts - 1, count - 1), 0, capacity - 1)


def pack_minor_spec(minor_lanes, minor_spec):
    """Fold statically bounded minor lanes into (packed lane, span), or
    (None, 1) when any lane is unbounded.  minor_spec entries are
    (lo, span) with every lane value in [lo, lo+span)."""
    if minor_spec is None or len(minor_spec) != len(minor_lanes) or \
            any(s is None for s in minor_spec):
        return None, 1
    total = 1
    for _lo, span in minor_spec:
        total *= int(span)
    if total >= (1 << 31):
        return None, 1
    # minor_lanes arrive most-minor FIRST: lane i's stride is the span
    # product of the lanes minor to it, so the most-major lane weighs
    # highest and the packed integer order IS the lexsort order
    packed = None
    stride = 1
    for lane, (lo, span) in zip(minor_lanes, minor_spec):
        slot = jnp.clip(lane.astype(jnp.int64) - jnp.int64(int(lo)),
                        0, int(span) - 1)
        packed = slot * jnp.int64(stride) if packed is None \
            else packed + slot * jnp.int64(stride)
        stride *= int(span)
    return packed, total


def sorted_segments(key_lanes_info, keys, keys_valid, live,
                    minor_lanes, capacity: int, num_segments: int,
                    pack_spec=None, minor_spec=None,
                    max_sort_operands: int = 2) -> SegRuns:
    """Shared sort-segment core for holistic aggregates (percentile,
    count-distinct, collect): order rows by (dead-last, group keys,
    minor_lanes most-minor-first), find group boundaries, return a
    SegRuns.

    `minor_lanes` order rows WITHIN a group (value lanes, null flags);
    they do not contribute to boundaries.

    pack_spec: per-key (lo, span) covering EVERY key (exec layer: plan
    range stats, dictionary sizes, bools) folds the whole key tuple plus
    liveness into ONE sort lane; group keys decode arithmetically (zero
    key gathers) and the boundary compare touches one lane.

    minor_spec: optional per-minor-lane (lo, span) bounds.  When both
    specs cover everything and the combined span fits, keys AND minor
    lanes fold into ONE lane and the whole ordering is a single
    2-operand (lane, iota) sort — the count-distinct / approx-percentile
    analogue of ops/groupby.packed_groupby_trace, killing the
    q16-class multi-operand-lexsort cold-compile cost.  Unpacked lanes
    fall back to a lexsort_capped chain, so no emitted sort ever
    exceeds `max_sort_operands` operands either way."""
    from .filter import take_keys_valid
    from .groupby import _eq_prev, _null_first_key_lanes, _packed_key_lane
    from .kernels import compute_view

    count = jnp.sum(live, dtype=jnp.int32)
    iota = jnp.arange(capacity, dtype=jnp.int32)

    packed_all = pack_spec is not None and len(pack_spec) == \
        len(key_lanes_info) and all(s is not None for s in pack_spec)
    if packed_all:
        spans = [s[1] for s in pack_spec]
        total = 1
        for sp in spans:
            total *= sp
        packed = _packed_key_lane(keys, keys_valid, pack_spec)
        key_lane = jnp.where(live, packed, jnp.int64(total))

        minor_packed, minor_total = pack_minor_spec(minor_lanes,
                                                    minor_spec)
        if minor_packed is not None and \
                (total + 1) * minor_total < (1 << 62):
            # ONE fused (key, minor) lane -> ONE 2-operand stable sort
            fused = key_lane * jnp.int64(minor_total) + minor_packed
            fused_s, perm = jax.lax.sort((fused, iota), num_keys=1,
                                         is_stable=True)
            s_key = fused_s // jnp.int64(minor_total)
        else:
            if total < (1 << 31) - 1:
                key_lane = key_lane.astype(jnp.int32)
            perm = lexsort_capped(list(minor_lanes) + [key_lane],
                                  max_sort_operands)
            s_key = key_lane[perm]
        s_live = s_key < jnp.asarray(total, s_key.dtype)
        boundary = _eq_prev(s_key)
        seg_ids = blocked_cumsum(boundary.astype(jnp.int32)) - 1
        num_groups = jnp.where(count > 0,
                               seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)
        group_live = jnp.arange(num_segments,
                                dtype=jnp.int32) < num_groups
        start_raw = jnp.sort(jnp.where(
            boundary & s_live, iota, jnp.int32(capacity)))[:num_segments]
        end_idx = segment_ends(start_raw, count, capacity)
        start_idx = jnp.clip(start_raw, 0, capacity - 1)
        # keys decode from the packed value at segment starts
        strides = []
        tot = 1
        for sp in reversed(spans):
            strides.append(tot)
            tot *= sp
        strides.reverse()
        pk = s_key[start_idx].astype(jnp.int64)
        out_keys = []
        for (dt, _hv, lane_dt), (lo, span), stride in zip(
                key_lanes_info, pack_spec, strides):
            slot = (pk // jnp.int64(stride)) % jnp.int64(span)
            okd = (slot - 1 + jnp.int64(lo)).astype(jnp.dtype(lane_dt))
            out_keys.append((okd, (slot > 0) & group_live))
        return SegRuns(perm, s_live, None, None, seg_ids, start_idx,
                       end_idx, out_keys, num_groups, group_live)

    lanes = []
    for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, keys, keys_valid):
        sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
        lanes.extend([l for l in sub if l is not None])
    # lexsort semantics: LAST lane is primary
    sort_keys = list(minor_lanes) + list(reversed(lanes)) + \
        [(~live).astype(jnp.int8)]
    perm = lexsort_capped(sort_keys, max_sort_operands)
    # one stacked gather pass per dtype class (TPU gathers pay per row,
    # ~20ms per 1M-row pass — per-lane takes multiply that)
    s_keys, s_keys_valid, (s_live,) = take_keys_valid(
        keys, keys_valid, [live], perm)

    boundary = row0_true(capacity)
    for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, s_keys,
                                      s_keys_valid):
        sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
        for lane in sub:
            if lane is not None:
                boundary = boundary | _eq_prev(lane)
    pad_start = jnp.concatenate([jnp.ones((1,), bool),
                                 s_live[1:] != s_live[:-1]])
    boundary = boundary | pad_start
    seg_ids = blocked_cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = jnp.where(count > 0,
                           seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)
    group_live = jnp.arange(num_segments, dtype=jnp.int32) < num_groups

    # seg ids rise with position, so the g-th boundary IS segment g's
    # start: a single-lane sort compacts them (no segment_min scatter —
    # scatter outputs land in slow S(1) buffers on this platform)
    start_raw = jnp.sort(jnp.where(
        boundary, iota, jnp.int32(capacity)))[:num_segments]
    end_idx = segment_ends(start_raw, count, capacity)
    start_idx = jnp.clip(start_raw, 0, capacity - 1)
    okds, okvs, _ = take_keys_valid(s_keys, s_keys_valid, [], start_idx)
    out_keys = []
    for okd, okv in zip(okds, okvs):
        okv = jnp.ones((num_segments,), bool) if okv is None else okv
        out_keys.append((okd, okv & group_live))
    return SegRuns(perm, s_live, s_keys, s_keys_valid, seg_ids,
                   start_idx, end_idx, out_keys, num_groups, group_live)
