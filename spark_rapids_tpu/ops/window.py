"""Window kernels: segmented scans/reductions over partition-sorted rows.

Role of cuDF RollingAggregation / scan-based running windows in the
reference (window/GpuWindowExec.scala:146, GpuRunningWindowExec.scala:220,
GpuBatchedBoundedWindowExec.scala:220) — re-designed for XLA:

  * the input batch arrives sorted by (partition keys, order keys)
    (ops/sort.py operand-capped lexsort); partition and peer boundaries
    are equality flags on adjacent rows (same trick as the sort-segment
    groupby);
  * running frames  = blocked segmented inclusive scans (ops/segments.py
    — compiles in seconds where associative_scan ran minutes at 1M);
  * unbounded frames = the forward scan gathered at each row's segment
    end (scatter-free segment reduction over sorted runs);
  * bounded ROWS sums/counts = global prefix-sum differences with the
    window clamped to the partition span (exact: clamping keeps both
    gathers inside the current partition);
  * bounded ROWS min/max = static shift-stack reduction when both bounds
    are finite, forward/backward segmented scans gathered at the moving
    bound when one side is unbounded;
  * RANGE frames (UNBOUNDED/CURRENT shapes) = the running result gathered
    at each row's peer-group end / start.

Everything for one operator runs as ONE jit program per
(specs, bucket, layout) key.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..plan.window import WindowFrame
from .groupby import (_bits_from_order, _bits_total_order,
                      _null_first_key_lanes, _ORDER_MAX, _ORDER_MIN)
from .kernels import blocked_cumsum, compute_view
from .segments import blocked_seg_scan, row0_true


def _seg_scan(vals: jax.Array, boundary: jax.Array, op) -> jax.Array:
    """Segmented inclusive scan: resets at rows where boundary is True.

    Runs as the blocked two-level segmented scan (ops/segments.py) — the
    `lax.associative_scan` formulation it replaces compiled in ~80 s at
    1M rows on this platform; the blocked form is seconds."""
    return blocked_seg_scan(vals, boundary, op)


def _seg_scan_rev(vals: jax.Array, boundary: jax.Array, op) -> jax.Array:
    """Segmented inclusive scan running from each segment's END backwards.
    `boundary` marks segment STARTS; reversed, segment ends become starts."""
    end_b = jnp.concatenate([boundary[1:], jnp.ones((1,), bool)])
    out = _seg_scan(vals[::-1], end_b[::-1], op)
    return out[::-1]


def _boundary_from_lanes(lanes: List[jax.Array], capacity: int) -> jax.Array:
    """True where any lane differs from the previous row (row 0 True)."""
    b = row0_true(capacity)
    for lane in lanes:
        if lane is None:
            continue
        b = b | jnp.concatenate([jnp.ones((1,), bool),
                                 lane[1:] != lane[:-1]])
    return b


def _key_eq_lanes(cols_info, datas, valids) -> List[jax.Array]:
    lanes: List[jax.Array] = []
    for (dt,), d, v in zip(cols_info, datas, valids):
        lanes.extend(l for l in _null_first_key_lanes(compute_view(d, dt), v, dt)
                     if l is not None)
    return lanes


def _gather(vals: jax.Array, idx: jax.Array, capacity: int) -> jax.Array:
    return vals[jnp.clip(idx, 0, capacity - 1)]


def _minmax_ident(dtype, is_min: bool):
    if dtype in (jnp.float64, jnp.float32):
        return np.inf if is_min else -np.inf
    if np.dtype(dtype) == np.bool_:
        return is_min          # True is min-identity, False is max-identity
    info = np.iinfo(np.dtype(dtype))
    return info.max if is_min else info.min


def _minmax_lanes(cd, vl, dt, raw_data, is_min):
    """(order lane with invalid rows at identity, identity scalar, decoder,
    nan lane or None).

    DOUBLE int64-bits columns compare in Java total-order bit space (exact,
    NaN greatest).  Computed float lanes order by value with NaN mapped to
    +inf; callers restore NaN results from per-frame NaN counts (Spark:
    max is NaN when any valid value is NaN, min only when ALL are)."""
    if isinstance(dt, t.DoubleType) and raw_data is not None \
            and raw_data.dtype == jnp.int64:
        ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
        o = jnp.where(vl, _bits_total_order(raw_data), ident)
        return o, ident, _bits_from_order, None
    if t.is_floating(dt):
        f = cd.astype(jnp.float64)
        nan_lane = (jnp.isnan(f) & vl).astype(jnp.int64)
        o = jnp.where(jnp.isnan(f), jnp.float64(np.inf), f)
        ident = jnp.float64(np.inf if is_min else -np.inf)
        o = jnp.where(vl, o, ident)
        return o, ident, (lambda x: x), nan_lane
    if isinstance(dt, t.BooleanType):
        ident = jnp.int8(1 if is_min else 0)
        o = jnp.where(vl, cd.astype(jnp.int8), ident)
        return o, ident, (lambda x: x > 0), None
    ident = jnp.asarray(_minmax_ident(cd.dtype, is_min), cd.dtype)
    o = jnp.where(vl, cd, ident)
    return o, ident, (lambda x: x), None


def _round_half_up_div(num: jax.Array, den: jax.Array) -> jax.Array:
    """Spark decimal division rounding: HALF_UP (away from zero), den > 0."""
    mag = jnp.abs(num)
    q = (mag + den // 2) // den
    return jnp.where(num < 0, -q, q)


def _nan_restore(red, frame_cnt, frame_nan, is_min):
    """Spark float semantics over the NaN->+inf order lane: max is NaN when
    any valid value in the frame is NaN; min only when ALL are."""
    if frame_nan is None:
        return red
    non_nan = frame_cnt - frame_nan
    nan = jnp.float64(np.nan)
    if is_min:
        return jnp.where((frame_cnt > 0) & (non_nan == 0), nan, red)
    return jnp.where(frame_nan > 0, nan, red)


def _merge_rank_counts(seg, u, query, query_first: bool, part_start,
                       capacity: int, max_sort_operands: int = 2):
    """Per-row count of in-segment key values < query (query_first) or
    <= query (not query_first), computed without binary search: a sort
    merges the key lane with the query lane per segment (reference
    GpuBatchedBoundedWindowExec.scala:220 sizes value-offset frames with
    per-row searches; log-step searchsorted is the slowest access
    pattern on TPU, a merge sort rides the fast sort network)."""
    # tie order rides STABILITY (query-before-key = concat queries
    # first), not a tag lane, and the inversion back to row order is a
    # 2-operand sort — TPU sort compile scales with operand count, and
    # scatter outputs land in slow S(1) buffers
    if query_first:
        segs = jnp.concatenate([seg, seg])
        vals = jnp.concatenate([query, u])
        qlo = 0
    else:
        segs = jnp.concatenate([seg, seg])
        vals = jnp.concatenate([u, query])
        qlo = capacity
    ids = jnp.arange(2 * capacity, dtype=jnp.int32)
    if max_sort_operands >= 3:
        _sg, _vl, s_ids = jax.lax.sort((segs, vals, ids), num_keys=2,
                                       is_stable=True)
    else:
        # chained 2-operand form of the same (seg, val) order: sort by
        # value with the id payload, then stably by segment — the id
        # payload of the second sort IS the merged order
        _v1, p1 = jax.lax.sort((vals, ids), num_keys=1, is_stable=True)
        _s2, s_ids = jax.lax.sort((segs[p1], p1), num_keys=1,
                                  is_stable=True)
    is_key = (s_ids < qlo) | (s_ids >= qlo + capacity)
    cum = blocked_cumsum(is_key.astype(jnp.int32))
    # every batch row is a key, so keys in earlier segments == the
    # segment's starting row index
    _i, counts = jax.lax.sort((s_ids, cum), num_keys=1, is_stable=True)
    counts = counts[qlo:qlo + capacity]
    return counts - part_start


def _range_value_bounds(order_lane, order_valid, asc: bool,
                        nulls_first: bool, frame, seg, part_start,
                        part_end, peer_start, peer_end, capacity: int,
                        max_sort_operands: int = 2):
    """Per-row inclusive [lo, hi] row bounds of a value-offset RANGE
    frame over the single (int-lane) order key.  frame.lower/upper are
    SIGNED value offsets (None = unbounded, 0 = current peer group).
    Null order keys frame their null peer group (Spark)."""
    u = order_lane.astype(jnp.int64)
    if not asc:
        u = -u                      # normalize to ascending value space
    # keep real values off the int64 extremes: the extremes are the null
    # sentinels below, and u + offset must not wrap (saturating query)
    u = jnp.clip(u, jnp.int64(int(_ORDER_MIN) + 1),
                 jnp.int64(int(_ORDER_MAX) - 1))
    if order_valid is not None:
        # null-key rows sit at the segment's head or tail (sort nf);
        # pin their u to that extreme so non-null rows' merge counts
        # step over them correctly (their own bounds are masked below)
        null_u = jnp.int64(_ORDER_MIN if nulls_first else _ORDER_MAX)
        u = jnp.where(order_valid, u, null_u)

    def query(offset: int):
        # saturate u+offset inside the sentinel-free value band; the
        # clip bounds are exact python ints, so no intermediate wrap
        lo_b, hi_b = int(_ORDER_MIN) + 1, int(_ORDER_MAX) - 1
        lo_c = max(lo_b, lo_b - offset)
        hi_c = min(hi_b, hi_b - offset)
        return jnp.clip(u, jnp.int64(lo_c), jnp.int64(hi_c)) + \
            jnp.int64(offset)

    if frame.lower is None:
        lo = part_start
    elif frame.lower == 0:
        lo = peer_start
    else:
        # offsets are direction-free in the normalized (ascending-u)
        # space: for DESC, "x preceding" = key+x = u-x = u+lower
        cnt = _merge_rank_counts(seg, u, query(int(frame.lower)),
                                 query_first=True,
                                 part_start=part_start,
                                 capacity=capacity,
                                 max_sort_operands=max_sort_operands)
        lo = part_start + cnt
    if frame.upper is None:
        hi = part_end
    elif frame.upper == 0:
        hi = peer_end
    else:
        cnt = _merge_rank_counts(seg, u, query(int(frame.upper)),
                                 query_first=False,
                                 part_start=part_start,
                                 capacity=capacity,
                                 max_sort_operands=max_sort_operands)
        hi = part_start + cnt - 1
    if order_valid is not None:
        lo = jnp.where(order_valid, lo, peer_start)
        hi = jnp.where(order_valid, hi, peer_end)
    return lo, hi


def _ilog2(length: jax.Array, capacity: int) -> jax.Array:
    """floor(log2(length)) for 1 <= length <= capacity, exactly (no
    float round-off)."""
    k = jnp.zeros(length.shape, jnp.int32)
    j = 2
    while j <= capacity:
        k = k + (length >= j).astype(jnp.int32)
        j <<= 1
    return k


def _sparse_minmax(o, ident, lo, hi, op, capacity: int):
    """min/max over arbitrary per-row [lo, hi] spans via a sparse table
    (log2(cap) doubled-shift levels, two gathers per query) — the
    variable-width analogue of the static shift-stack used for bounded
    ROWS frames."""
    levels = [o]
    step = 1
    while step < capacity:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[step:], jnp.full((step,), ident, prev.dtype)])
        levels.append(op(prev, shifted))
        step <<= 1
    table = jnp.stack(levels).reshape(-1)
    length = jnp.maximum(hi - lo + 1, 1).astype(jnp.int32)
    k = _ilog2(length, capacity)
    pow2 = (jnp.int32(1) << k)
    left = jnp.clip(lo, 0, capacity - 1)
    right = jnp.clip(hi - pow2 + 1, 0, capacity - 1)
    a = table[k * capacity + left]
    b = table[k * capacity + right]
    return op(a, b)


def window_trace(part_info, order_info, val_info, specs_frames,
                 capacity: int, order_dirs=(), scatter_free=True,
                 max_sort_operands=2):
    """Build the traced window program.

    part_info/order_info/val_info: tuples of (dtype,) per column (static).
    specs_frames: list of (spec, resolved WindowFrame, input_idx); input_idx
    indexes the value columns, -1 for input-less functions.
    scatter_free: partition/peer extents and whole-frame reductions ride
    segmented scans (+ per-row end gathers) instead of segment_* scatters.

    Returns fn(part_data, part_valid, order_data, order_valid,
               val_data, val_valid, live) -> [(data, valid)] per spec,
    where all lanes belong to the partition-sorted batch.
    """
    def run(part_data, part_valid, order_data, order_valid,
            val_data, val_valid, live):
        idx = jnp.arange(capacity, dtype=jnp.int32)

        # --- partition / peer structure ---
        part_lanes = _key_eq_lanes(part_info, part_data, part_valid)
        live_lane = (~live).astype(jnp.int8)
        part_b = _boundary_from_lanes(part_lanes + [live_lane], capacity)
        seg = blocked_cumsum(part_b.astype(jnp.int32)) - 1

        order_lanes = _key_eq_lanes(order_info, order_data, order_valid)
        peer_b = (part_b | _boundary_from_lanes(order_lanes, capacity)) \
            if order_lanes else part_b

        part_start = _seg_scan(idx, part_b, jnp.minimum)
        pg = blocked_cumsum(peer_b.astype(jnp.int32)) - 1
        peer_start = _seg_scan(idx, peer_b, jnp.minimum)
        if scatter_free:
            # a reverse max-scan IS the per-row segment end — no
            # segment_max scatter, no broadcast gather
            part_end = _seg_scan_rev(idx, part_b, jnp.maximum)
            peer_end = _seg_scan_rev(idx, peer_b, jnp.maximum)
        else:
            part_end = _gather(jax.ops.segment_max(idx, seg,
                                                   num_segments=capacity),
                               seg, capacity)
            peer_end = _gather(jax.ops.segment_max(idx, pg,
                                                   num_segments=capacity),
                               pg, capacity)
        part_rows = (part_end - part_start + 1).astype(jnp.int64)

        rn0 = idx - part_start                     # 0-based row number

        def frame_bounds(frame: WindowFrame):
            """Per-row inclusive [lo, hi] row-index bounds."""
            if frame.kind == "range":
                if frame.is_value_offset:
                    # value-offset RANGE: single int-lane order key
                    # (placement guarantees this)
                    asc, nf = order_dirs[0] if order_dirs else (True, True)
                    ov = order_valid[0]
                    ov = None if ov is None else (ov & live)
                    lo, hi = _range_value_bounds(
                        compute_view(order_data[0], order_info[0][0]),
                        ov, asc, nf, frame, seg, part_start, part_end,
                        peer_start, peer_end, capacity,
                        max_sort_operands=max_sort_operands)
                    return (jnp.clip(lo, part_start, part_end + 1),
                            jnp.clip(hi, part_start - 1, part_end))
                lo = part_start if frame.lower is None else peer_start
                hi = part_end if frame.upper is None else peer_end
                return lo, hi
            lo = part_start if frame.lower is None \
                else jnp.maximum(part_start, idx + frame.lower)
            hi = part_end if frame.upper is None \
                else jnp.minimum(part_end, idx + frame.upper)
            return lo, hi

        outs: List[Tuple[jax.Array, jax.Array]] = []
        for spec, frame, input_idx in specs_frames:
            kind = spec.kind
            if input_idx >= 0:
                d = val_data[input_idx]
                v = val_valid[input_idx]
                v = jnp.ones((capacity,), bool) if v is None else v
                dt = spec.child.dtype
                cd = compute_view(d, dt)
                vl = v & live
            else:
                d = cd = dt = None
                vl = live

            if kind == "row_number":
                outs.append(((rn0 + 1).astype(jnp.int32), live))
            elif kind == "rank":
                outs.append(((peer_start - part_start + 1).astype(jnp.int32),
                             live))
            elif kind == "dense_rank":
                dr = _seg_scan(peer_b.astype(jnp.int32), part_b, jnp.add)
                outs.append((dr, live))
            elif kind == "percent_rank":
                rank0 = (peer_start - part_start).astype(jnp.float64)
                denom = (part_rows - 1).astype(jnp.float64)
                pr = jnp.where(denom > 0, rank0 / jnp.maximum(denom, 1.0),
                               0.0)
                outs.append((pr, live))
            elif kind == "cume_dist":
                cume = (peer_end - part_start + 1).astype(jnp.float64) \
                    / part_rows.astype(jnp.float64)
                outs.append((cume, live))
            elif kind == "ntile":
                n = jnp.int64(spec.n)
                k = part_rows // n
                rem = part_rows % n
                i0 = rn0.astype(jnp.int64)
                cut = rem * (k + 1)
                bucket = jnp.where(
                    i0 < cut, i0 // jnp.maximum(k + 1, 1),
                    rem + (i0 - cut) // jnp.maximum(k, 1))
                bucket = jnp.where(part_rows < n, i0, bucket)
                outs.append(((bucket + 1).astype(jnp.int32), live))
            elif kind in ("lead", "lag"):
                shift = spec.offset * (1 if kind == "lead" else -1)
                src = idx + shift
                in_part = (src >= part_start) & (src <= part_end) & live
                sd = _gather(cd, src, capacity)
                sv = _gather(vl, src, capacity)
                if spec.default is not None:
                    # Spark: default only when the offset row does not
                    # exist; an existing null value stays null
                    dflt = jnp.asarray(spec.default, sd.dtype)
                    data = jnp.where(in_part, sd, dflt)
                    valid = jnp.where(in_part, sv, True) & live
                else:
                    data = jnp.where(in_part, sd, jnp.zeros((), sd.dtype))
                    valid = in_part & sv
                outs.append((data, valid))
            elif kind in ("first_value", "last_value"):
                lo, hi = frame_bounds(frame)
                pick = lo if kind == "first_value" else hi
                nonempty = hi >= lo
                data = _gather(cd, pick, capacity)
                valid = _gather(vl, pick, capacity) & nonempty & live
                outs.append((data, valid))
            elif kind in ("agg_sum", "agg_count", "agg_avg",
                          "agg_min", "agg_max"):
                outs.append(_framed_agg(
                    kind, spec, frame, cd, vl, dt, d, idx, part_b,
                    frame_bounds, seg, pg, peer_end, peer_start, live,
                    capacity, peer_b, part_end, scatter_free))
            else:
                raise ValueError(f"unknown window kind {kind}")
        return outs

    return run


def _framed_agg(kind, spec, frame, cd, vl, dt, raw_data, idx, part_b,
                frame_bounds, seg, pg, peer_end, peer_start, live,
                capacity, peer_b=None, part_end=None,
                scatter_free=True):
    """sum/count/min/max/avg over a frame; returns (data, valid)."""
    is_min = kind == "agg_min"
    count_all = kind == "agg_count" and spec.child is None
    cnt_lane = (live if count_all else vl).astype(jnp.int64)

    if kind in ("agg_sum", "agg_avg"):
        decimal = isinstance(dt, t.DecimalType)
        if decimal:
            acc = jnp.where(vl, cd.astype(jnp.int64), 0)
        elif kind == "agg_avg" or t.is_floating(dt):
            acc = jnp.where(vl, cd.astype(jnp.float64), 0.0)
        else:
            acc = jnp.where(vl, cd.astype(jnp.int64), 0)

    def finish(s, c):
        if kind == "agg_count":
            return c, live
        if kind == "agg_sum":
            return s, (c > 0) & live
        if isinstance(dt, t.DecimalType):
            # avg(decimal(p,s)) -> decimal(p+4, s+4): unscaled*10^4/count
            q = _round_half_up_div(s * jnp.int64(10 ** 4), jnp.maximum(c, 1))
            return q, (c > 0) & live
        return (s / jnp.maximum(c, 1).astype(jnp.float64), (c > 0) & live)

    # --- whole-partition / whole-peer-group frames: reduce + broadcast ---
    peers_only = frame.kind == "range" and frame.lower == 0 \
        and frame.upper == 0
    if frame.is_unbounded_both or peers_only:
        ids = pg if peers_only else seg
        b = peer_b if peers_only else part_b
        end = peer_end if peers_only else part_end

        def red_bcast(lane, op):
            """Whole-segment reduce broadcast to every member row."""
            if scatter_free:
                # the forward scan's value at the segment END is the
                # full reduction; `end` is already per-row — scan + one
                # gather, no segment_* scatter
                return _gather(_seg_scan(lane, b, op), end, capacity)
            red = {jnp.add: jax.ops.segment_sum,
                   jnp.minimum: jax.ops.segment_min,
                   jnp.maximum: jax.ops.segment_max}[op](
                lane, ids, num_segments=capacity)
            return _gather(red, ids, capacity)

        c = red_bcast(cnt_lane, jnp.add)
        if kind == "agg_count":
            return c, live
        if kind in ("agg_sum", "agg_avg"):
            return finish(red_bcast(acc, jnp.add), c)
        o, _ident, back, nan_lane = _minmax_lanes(cd, vl, dt, raw_data,
                                                  is_min)
        red = red_bcast(o, jnp.minimum if is_min else jnp.maximum)
        fnan = None if nan_lane is None else red_bcast(nan_lane, jnp.add)
        return _nan_restore(back(red), c, fnan, is_min), (c > 0) & live

    # --- running frames (incl. RANGE ..CURRENT ROW via peer-end gather) ---
    running_rows = frame.kind == "rows" and frame.is_running
    running_range = frame.kind == "range" and frame.lower is None \
        and frame.upper == 0
    if running_rows or running_range:
        def at_peers(x):
            return _gather(x, peer_end, capacity) if running_range else x
        c = at_peers(_seg_scan(cnt_lane, part_b, jnp.add))
        if kind == "agg_count":
            return c, live
        if kind in ("agg_sum", "agg_avg"):
            s = at_peers(_seg_scan(acc, part_b, jnp.add))
            return finish(s, c)
        o, _ident, back, nan_lane = _minmax_lanes(cd, vl, dt, raw_data,
                                                  is_min)
        red = at_peers(_seg_scan(
            o, part_b, jnp.minimum if is_min else jnp.maximum))
        fnan = None if nan_lane is None else at_peers(
            _seg_scan(nan_lane, part_b, jnp.add))
        return _nan_restore(back(red), c, fnan, is_min), (c > 0) & live

    # --- RANGE CURRENT ROW .. UNBOUNDED FOLLOWING: reverse running ---
    if frame.kind == "range" and frame.lower == 0 and frame.upper is None:
        def at_peer_start(x):
            return _gather(x, peer_start, capacity)
        c = at_peer_start(_seg_scan_rev(cnt_lane, part_b, jnp.add))
        if kind == "agg_count":
            return c, live
        if kind in ("agg_sum", "agg_avg"):
            s = at_peer_start(_seg_scan_rev(acc, part_b, jnp.add))
            return finish(s, c)
        o, _ident, back, nan_lane = _minmax_lanes(cd, vl, dt, raw_data,
                                                  is_min)
        red = at_peer_start(_seg_scan_rev(
            o, part_b, jnp.minimum if is_min else jnp.maximum))
        fnan = None if nan_lane is None else at_peer_start(
            _seg_scan_rev(nan_lane, part_b, jnp.add))
        return _nan_restore(back(red), c, fnan, is_min), (c > 0) & live

    # --- bounded ROWS frames ---
    lo, hi = frame_bounds(frame)
    nonempty = (hi >= lo) & live

    if kind in ("agg_sum", "agg_count", "agg_avg"):
        def pref_window(lane):
            p = blocked_cumsum(lane)
            hi_v = _gather(p, hi, capacity)
            lo_v = jnp.where(lo > 0, _gather(p, lo - 1, capacity),
                             jnp.zeros((), p.dtype))
            return jnp.where(nonempty, hi_v - lo_v, jnp.zeros((), p.dtype))
        c = pref_window(cnt_lane)
        if kind == "agg_count":
            return c, live
        return finish(pref_window(acc), c)

    # bounded min/max
    o, ident, back, nan_lane = _minmax_lanes(cd, vl, dt, raw_data, is_min)
    op = jnp.minimum if is_min else jnp.maximum
    c_cnt = None
    if frame.kind == "range":
        # value-offset RANGE: variable frame widths -> sparse table
        red = jnp.where(nonempty, _sparse_minmax(o, ident, lo, hi, op,
                                                 capacity), ident)
    elif frame.lower is None:
        # UNBOUNDED PRECEDING .. k FOLLOWING: forward scan gathered at hi
        fwd = _seg_scan(o, part_b, op)
        red = jnp.where(nonempty, _gather(fwd, hi, capacity), ident)
    elif frame.upper is None:
        # k PRECEDING .. UNBOUNDED FOLLOWING: backward scan gathered at lo
        bwd = _seg_scan_rev(o, part_b, op)
        red = jnp.where(nonempty, _gather(bwd, lo, capacity), ident)
    else:
        best = jnp.full((capacity,), 0, o.dtype) + ident
        c_cnt = jnp.zeros((capacity,), jnp.int64)
        for off in range(frame.lower, frame.upper + 1):
            src = idx + off
            ok = (src >= lo) & (src <= hi)
            cand_v = ok & _gather(vl, src, capacity)
            cand = jnp.where(cand_v, _gather(o, src, capacity), ident)
            best = op(best, cand)
            c_cnt = c_cnt + cand_v.astype(jnp.int64)
        red = best
    def pref_cnt(lane):
        p = blocked_cumsum(lane)
        hi_v = _gather(p, hi, capacity)
        lo_v = jnp.where(lo > 0, _gather(p, lo - 1, capacity), jnp.int64(0))
        return jnp.where(nonempty, hi_v - lo_v, jnp.int64(0))
    if c_cnt is None:
        c_cnt = pref_cnt(vl.astype(jnp.int64))
    fnan = None if nan_lane is None else pref_cnt(nan_lane)
    return _nan_restore(back(red), c_cnt, fnan, is_min), (c_cnt > 0) & live
