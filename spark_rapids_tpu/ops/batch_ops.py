"""Device batch utilities: concat, coalesce, slice (GpuCoalesceBatches role).

Concat is the workhorse under aggregation-merge, sort and join build sides
(reference Table.concatenate / GpuCoalesceBatches.scala:697).  String columns
carry per-batch dictionaries, so concat first unifies dictionaries on host
(dictionaries are small) and remaps codes on device.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..config import TpuConf, DEFAULT_CONF


def unify_dictionaries(dicts: Sequence[Optional[pa.Array]]):
    """-> (unified dict, [np remap array per input dict])."""
    arrs = [d.cast(pa.string()) if d is not None else pa.array([], pa.string())
            for d in dicts]
    combined = pa.concat_arrays(arrs)
    enc = pc.dictionary_encode(combined)
    codes = enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)
    remaps = []
    off = 0
    for a in arrs:
        n = len(a)
        remaps.append(codes[off:off + n] if n else np.zeros(1, np.int32))
        off += n
    return enc.dictionary, remaps


def remap_string_column(col: DeviceColumn, remap: np.ndarray,
                        unified: pa.Array) -> DeviceColumn:
    table = jnp.asarray(remap)
    data = table[jnp.clip(col.data, 0, table.shape[0] - 1)]
    return DeviceColumn(data, col.validity, col.dtype, unified)


# Dictionary-identity caches: the SAME pa.Array dictionary object flows
# through every batch of a scan (and every sub-partition bucket of a
# materialized build side), so the O(dictionary) host work — uniqueness
# unification, cross-dictionary remap tables — is computed once per
# dictionary (pair), not once per probe batch.  Entries pin the
# dictionaries so id() reuse cannot alias a stale hit; tracers are never
# cached (whole-plan tracing).  The ``dict_remaps`` registry counter
# counts actual host computations, so a regression back to per-batch
# remapping is visible in the metrics plane.
#
# LOOKUP AND PUBLISH hold one lock: serving prepares plans concurrently,
# and without it two tenants preparing the same scan could interleave a
# miss-path compute with the eviction clear() (or each observe the other
# mid-publish) — the compute must be decided and the finished table
# published under a single critical section.

import threading

_DICT_CACHE_LOCK = threading.Lock()
_UNIQUE_DICT_CACHE: dict = {}
_REMAP_TABLE_CACHE: dict = {}


def _count_dict_remap() -> None:
    from ..obs.registry import DICT_REMAPS
    DICT_REMAPS.inc()


def ensure_unique_dict(col: DeviceColumn) -> DeviceColumn:
    """Code-equality == string-equality requires a duplicate-free dict."""
    d = col.dictionary
    if d is None:
        return col
    with _DICT_CACHE_LOCK:
        hit = _UNIQUE_DICT_CACHE.get(id(d))
        if hit is not None and hit[0] is d:
            unified, remap = hit[1], hit[2]
        else:
            _count_dict_remap()
            unified, remaps = unify_dictionaries([d])
            remap = None if len(unified) == len(d) else remaps[0]
            if len(_UNIQUE_DICT_CACHE) > 1024:
                _UNIQUE_DICT_CACHE.clear()
            _UNIQUE_DICT_CACHE[id(d)] = (d, unified, remap)
    if remap is None:
        return col
    return remap_string_column(col, remap, unified)


def remap_codes_into(col: DeviceColumn, target_dict: pa.Array) -> DeviceColumn:
    """Remap a string column's codes into `target_dict`'s code space; codes
    whose string is absent from the target map to -1 (equal to no valid
    code).  Lets a join probe stream remap against a build-side dictionary
    unified ONCE instead of re-unifying build+probe per batch; the remap
    table itself is cached per (source, target) dictionary pair so
    repeated probe batches (and sub-partition buckets) sharing
    dictionaries never recompute the host index_in."""
    src = col.dictionary
    if src is None:
        raise ValueError("remap_codes_into needs a dictionary column")
    if src is target_dict:
        # same dictionary object: codes are ALREADY in target space —
        # the common same-scan self-join / shared-upload case needs
        # neither table nor per-row gather
        return col
    key = (id(src), id(target_dict))
    with _DICT_CACHE_LOCK:
        hit = _REMAP_TABLE_CACHE.get(key)
        dev = hit[2] if hit is not None and hit[0] is src and \
            hit[1] is target_dict else None
    if dev is None:
        _count_dict_remap()
        idx = pc.index_in(src.cast(pa.string()), value_set=target_dict)
        table = np.asarray(idx.fill_null(-1).to_numpy(zero_copy_only=False),
                           dtype=np.int32)
        if not len(table):
            table = np.full(1, -1, np.int32)
        dev = jnp.asarray(table)
        if not isinstance(dev, jax.core.Tracer):
            with _DICT_CACHE_LOCK:
                if len(_REMAP_TABLE_CACHE) > 1024:
                    _REMAP_TABLE_CACHE.clear()
                _REMAP_TABLE_CACHE[key] = (src, target_dict, dev)
    data = dev[jnp.clip(col.data, 0, dev.shape[0] - 1)]
    return DeviceColumn(data, col.validity, col.dtype, target_dict)




def _hi_lane_of(col: DeviceColumn, upto=None) -> "jax.Array":
    """The column's hi int64 lane, synthesizing the sign-extension for
    single-lane (device-computed) wide values so mixed streams concat
    correctly."""
    if col.data_hi is not None:
        return col.data_hi if upto is None else col.data_hi[:upto]
    d = col.data if upto is None else col.data[:upto]
    d = d.astype(jnp.int64)
    return jnp.where(d < 0, jnp.int64(-1), jnp.int64(0))


def ensure_prefix(db: DeviceBatch, conf: TpuConf = DEFAULT_CONF
                  ) -> DeviceBatch:
    """Materialize a lazy selection vector (DeviceBatch.sel) and any
    deferred columns (DeviceBatch.thin) into the dense front-prefix form
    every slicing/concat/fetch path assumes."""
    if db.sel is None:
        if db.thin is None:
            return db
        from ..columnar.lanes import materialize_batch
        return materialize_batch(db, conf)
    from .filter import compact_batch
    # compact_batch resolves thin state in the same pass (compact_thin)
    return compact_batch(db, db.sel, conf)


def concat_batches(batches: List[DeviceBatch],
                   conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    """Concatenate device batches (same schema) into one bucketed batch.

    Batches with host-known counts concatenate tightly (layout decisions
    on host).  If ANY count is lazy (a device scalar / tracer), the lazy
    path concatenates full-capacity lanes and compacts live rows to the
    front on device — zero host syncs, at the cost of padding up to the
    capacity sum."""
    assert batches, "concat of zero batches"
    batches = [ensure_prefix(b, conf) for b in batches]
    if len(batches) == 1:
        return batches[0]
    if any(not isinstance(b.num_rows, int) for b in batches):
        return _concat_batches_lazy(batches, conf)
    batches = [DeviceBatch(b.columns, int(b.num_rows), b.names,
                           b.origin_file) for b in batches]
    total = sum(b.num_rows for b in batches)
    cap = bucket_capacity(max(total, 1), conf)
    names = list(batches[0].names)
    ncols = batches[0].num_columns
    out_cols = []
    for ci in range(ncols):
        cols = [b.column(ci) for b in batches]
        dt = cols[0].dtype
        unified = None
        if isinstance(dt, t.StringType):
            unified, remaps = unify_dictionaries([c.dictionary for c in cols])
            cols = [remap_string_column(c, r, unified)
                    for c, r in zip(cols, remaps)]
        data_parts = [c.data[:b.num_rows] for c, b in zip(cols, batches)]
        if isinstance(dt, t.DoubleType) and \
                len({str(p.dtype) for p in data_parts}) > 1:
            # DOUBLE has two storage lanes (int64 bit patterns from host
            # uploads, native f64 from device compute; see columnar/device):
            # concatenating them raw would convert bit patterns NUMERICALLY.
            # Unify on f64 via the bitcast view.
            from .kernels import compute_view
            data_parts = [compute_view(p, dt) for p in data_parts]
        valid_parts = [c.validity[:b.num_rows] for c, b in zip(cols, batches)]
        pad = cap - total
        if pad:
            data_parts.append(jnp.zeros((pad,), cols[0].data.dtype))
            valid_parts.append(jnp.zeros((pad,), bool))
        hi = None
        if any(c.data_hi is not None for c in cols):
            hi_parts = [_hi_lane_of(c, b.num_rows)
                        for c, b in zip(cols, batches)]
            if pad:
                hi_parts.append(jnp.zeros((pad,), jnp.int64))
            hi = jnp.concatenate(hi_parts)
        out_cols.append(DeviceColumn(jnp.concatenate(data_parts),
                                     jnp.concatenate(valid_parts),
                                     dt, unified, hi))
    from ..columnar.device import merge_origin
    return DeviceBatch(out_cols, total, names,
                       merge_origin(b.origin_file for b in batches))


def _concat_batches_lazy(batches: List[DeviceBatch],
                         conf: TpuConf) -> DeviceBatch:
    """Sync-free concat: stack full-capacity lanes, then compact live rows
    to the front on device (ops/filter.py).  Capacities are host facts, so
    the output shape is static; the row count stays a device scalar."""
    from ..columnar.device import merge_origin
    from .filter import compact_batch
    cap_total = sum(b.capacity for b in batches)
    cap = bucket_capacity(max(cap_total, 1), conf)
    pad = cap - cap_total
    names = list(batches[0].names)
    live_parts = [b.row_mask() for b in batches]
    if pad:
        live_parts.append(jnp.zeros((pad,), bool))
    keep = jnp.concatenate(live_parts)
    out_cols = []
    for ci in range(batches[0].num_columns):
        cols = [b.column(ci) for b in batches]
        dt = cols[0].dtype
        unified = None
        if isinstance(dt, t.StringType):
            unified, remaps = unify_dictionaries(
                [c.dictionary for c in cols])
            cols = [remap_string_column(c, r, unified)
                    for c, r in zip(cols, remaps)]
        data_parts = [c.data for c in cols]
        if isinstance(dt, t.DoubleType) and \
                len({str(p.dtype) for p in data_parts}) > 1:
            from .kernels import compute_view
            data_parts = [compute_view(p, dt) for p in data_parts]
        valid_parts = [c.validity for c in cols]
        if pad:
            data_parts = data_parts + [jnp.zeros((pad,),
                                                 data_parts[0].dtype)]
            valid_parts = valid_parts + [jnp.zeros((pad,), bool)]
        hi = None
        if any(c.data_hi is not None for c in cols):
            hi_parts = [_hi_lane_of(c) for c in cols]
            if pad:
                hi_parts.append(jnp.zeros((pad,), jnp.int64))
            hi = jnp.concatenate(hi_parts)
        out_cols.append(DeviceColumn(jnp.concatenate(data_parts),
                                     jnp.concatenate(valid_parts),
                                     dt, unified, hi))
    total = sum(jnp.int32(b.num_rows) for b in batches)
    db = DeviceBatch(out_cols, total, names,
                     merge_origin(b.origin_file for b in batches))
    return compact_batch(db, keep, conf)


def shrink_to_capacity(db: DeviceBatch, row_bound: int,
                       conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    """Slice lanes down to the bucket fitting `row_bound` WITHOUT reading
    the (possibly lazy) num_rows.  Sound when the caller can statically
    bound the live row count (e.g. LIMIT N): live rows are a prefix, so
    rows past the bound are guaranteed padding.  Keeps collect()/to_host
    from shipping a full-capacity batch over the link for a tiny limit."""
    db = ensure_prefix(db, conf)
    cap = bucket_capacity(max(row_bound, 1), conf)
    if cap >= db.capacity:
        return db
    cols = [DeviceColumn(c.data[:cap], c.validity[:cap], c.dtype,
                         c.dictionary,
                         None if c.data_hi is None else c.data_hi[:cap])
            for c in db.columns]
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file)


def shrink_to_rows(db: DeviceBatch, num_rows: int,
                   conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    """Re-bucket a padded batch down to the bucket fitting `num_rows`
    (used after groupby/filter when occupancy dropped a bucket or more)."""
    db = ensure_prefix(db, conf)
    cap = bucket_capacity(max(num_rows, 1), conf)
    if cap >= db.capacity:
        return DeviceBatch(db.columns, num_rows, db.names, db.origin_file)
    cols = [DeviceColumn(c.data[:cap], c.validity[:cap], c.dtype,
                         c.dictionary,
                         None if c.data_hi is None else c.data_hi[:cap])
            for c in db.columns]
    return DeviceBatch(cols, num_rows, db.names, db.origin_file)
