"""Row-selection kernels: mask compaction and index gather.

The reference filters by cuDF `Table.filter` / applies gather maps produced
by joins (GpuFilterExec basicPhysicalOperators.scala:795, JoinGatherer).
TPU-first realization on static shapes:

  * compaction = one stable argsort of the inverted keep-mask (int8 keys);
    kept rows move to the front preserving order, padding/dropped rows sink.
    XLA lowers the sort onto the device; no host round-trip besides the
    selected-row count, which must come back anyway because `num_rows` is
    host metadata (same host sync the reference performs to size outputs).

  * gather = plain take along axis with clipped indices; out-of-range
    semantics are handled by an explicit validity lane, mirroring cuDF's
    OutOfBoundsPolicy.NULLIFY.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..config import TpuConf, DEFAULT_CONF
from ..ops.kernels import live_mask

_COMPACT_CACHE = {}


def compaction_order(keep: jax.Array) -> jax.Array:
    """Indices that bring keep=True rows to the front, stably."""
    return jnp.argsort(jnp.where(keep, jnp.int8(0), jnp.int8(1)),
                       stable=True)


def _compact_trace(ncols: int, has_hi: Tuple[bool, ...]):
    def run(datas, valids, his, keep):
        order = compaction_order(keep)
        count = jnp.sum(keep, dtype=jnp.int32)
        out = []
        for i in range(ncols):
            d = jnp.take(datas[i], order, axis=0)
            v = jnp.take(valids[i], order, axis=0) & (
                jnp.arange(d.shape[0], dtype=jnp.int32) < count)
            h = jnp.take(his[i], order, axis=0) if has_hi[i] else None
            out.append((d, v, h))
        return out, count
    return run


def compact_batch(db: DeviceBatch, keep: jax.Array,
                  conf: TpuConf = DEFAULT_CONF,
                  sync: bool = False) -> DeviceBatch:
    """Keep rows where `keep` is True (padding rows must already be False).

    By default the surviving row count stays on device (`num_rows` becomes a
    0-d jax scalar) so a filter feeding another device operator costs zero
    host round-trips — the device↔host sync latency (70ms over a tunneled
    chip) dwarfs any saving from shrinking the bucket.  Pass `sync=True` to
    fetch the count and re-bucket down (worth it before expensive downstream
    work when selectivity is high).
    """
    from .batch_ops import shrink_to_rows
    has_hi = tuple(c.data_hi is not None for c in db.columns)
    sig = (db.num_columns, has_hi, db.capacity,
           tuple(str(c.data.dtype) for c in db.columns))
    fn = _COMPACT_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_compact_trace(db.num_columns, has_hi))
        _COMPACT_CACHE[sig] = fn
    if any(has_hi):
        zeros = jnp.zeros((db.capacity,), jnp.int64)
        his = tuple(c.data_hi if h else zeros
                    for c, h in zip(db.columns, has_hi))
    else:
        his = tuple(c.data for c in db.columns)  # ignored by the trace
    outs, count = fn(tuple(c.data for c in db.columns),
                     tuple(c.validity for c in db.columns), his, keep)
    cols = [DeviceColumn(d, v, c.dtype, c.dictionary, h)
            for (d, v, h), c in zip(outs, db.columns)]
    if not sync:
        return DeviceBatch(cols, count, db.names, db.origin_file)
    return shrink_to_rows(
        DeviceBatch(cols, int(count), db.names, db.origin_file),
        int(count), conf)


def gather_batch(db: DeviceBatch, indices: jax.Array, out_rows: int,
                 names: List[str] = None,
                 null_out_of_bounds: bool = False) -> DeviceBatch:
    """Gather rows of `db` at `indices` (shape (out_capacity,)).

    Rows with index < 0 or >= num_rows become null when
    `null_out_of_bounds` (cuDF NULLIFY), used by outer joins; rows past
    `out_rows` are padding.
    """
    cap_out = indices.shape[0]
    in_bounds = (indices >= 0) & (indices < jnp.int32(db.num_rows))
    safe = jnp.clip(indices, 0, max(db.capacity - 1, 0)).astype(jnp.int32)
    live = live_mask(cap_out, jnp.int32(out_rows))
    cols = []
    for c in db.columns:
        d = jnp.take(c.data, safe, axis=0)
        v = jnp.take(c.validity, safe, axis=0) & live
        if null_out_of_bounds:
            v = v & in_bounds
        h = None if c.data_hi is None else jnp.take(c.data_hi, safe, axis=0)
        cols.append(DeviceColumn(d, v, c.dtype, c.dictionary, h))
    return DeviceBatch(cols, out_rows, names or list(db.names))
