"""Row-selection kernels: mask compaction and index gather.

The reference filters by cuDF `Table.filter` / applies gather maps produced
by joins (GpuFilterExec basicPhysicalOperators.scala:795, JoinGatherer).
TPU-first realization on static shapes:

  * compaction = one stable argsort of the inverted keep-mask (int8 keys);
    kept rows move to the front preserving order, padding/dropped rows sink.
    XLA lowers the sort onto the device; no host round-trip besides the
    selected-row count, which must come back anyway because `num_rows` is
    host metadata (same host sync the reference performs to size outputs).

  * gather = plain take along axis with clipped indices; out-of-range
    semantics are handled by an explicit validity lane, mirroring cuDF's
    OutOfBoundsPolicy.NULLIFY.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..config import TpuConf, DEFAULT_CONF
from ..ops.kernels import live_mask

_COMPACT_CACHE = {}


def compaction_order(keep: jax.Array) -> jax.Array:
    """Indices that bring keep=True rows to the front, stably."""
    return jnp.argsort(jnp.where(keep, jnp.int8(0), jnp.int8(1)),
                       stable=True)


def grouped_take(lanes, idx: jax.Array):
    """Gather many (capacity,) lanes at the same indices, same-dtype
    lanes stacked into one (capacity, k) matrix per dtype.

    TPU gathers pay per gathered ROW (descriptor-driven DMA), so k lanes
    gathered as one matrix cost ~1 lane's descriptors instead of k —
    measured 3x for 8 int64 lanes at 8M on v5e.  (A variadic payload
    sort would be faster still at runtime but TPU sort COMPILE time
    scales ~linearly with operand count — 7.5 min for 17 operands at 8M
    — so gathers win end to end.)  Returns gathered lanes in order."""
    groups: dict = {}
    for slot, arr in enumerate(lanes):
        groups.setdefault(str(arr.dtype), []).append((slot, arr))
    out: dict = {}
    for _dt, members in groups.items():
        if len(members) == 1:
            slot, arr = members[0]
            out[slot] = jnp.take(arr, idx, axis=0)
        else:
            mat = jnp.stack([arr for _s, arr in members], axis=1)
            g = jnp.take(mat, idx, axis=0)
            for k, (slot, _arr) in enumerate(members):
                out[slot] = g[:, k]
    return [out[i] for i in range(len(lanes))]


def take_keys_valid(keys, keys_valid, extra, idx):
    """grouped_take of key lanes + their (possibly None) validity lanes
    + extra lanes at `idx`, preserving None validity slots.

    Returns (keys_out, keys_valid_out, extra_out).  One stacked gather
    pass per dtype class — the shared permute idiom of the sort-segment
    kernels (groupby/percentile), kept in one place so the lane
    bookkeeping cannot drift between copies."""
    kv = [v for v in keys_valid if v is not None]
    moved = grouped_take(list(keys) + kv + list(extra), idx)
    nk = len(keys)
    it = iter(moved[nk:nk + len(kv)])
    out_kv = [None if v is None else next(it) for v in keys_valid]
    return moved[:nk], out_kv, moved[nk + len(kv):]


def pallas_compact_order(keep: jax.Array, conf: TpuConf):
    """Elected Pallas compaction order for this keep-mask, or None on
    the sorted tier (ops/pallas/compact.py — prefix sum + rank search
    instead of the keep-mask argsort)."""
    from .pallas import elect_compact
    tier = elect_compact(conf, int(keep.shape[0]))
    if tier is None:
        return None
    from .pallas.compact import compaction_order as pallas_order
    return pallas_order(keep, tier.interpret)


def _compact_trace(ncols: int, has_hi: Tuple[bool, ...],
                   pallas_interpret=None):
    def run(datas, valids, his, keep):
        if pallas_interpret is not None:
            from .pallas.compact import compaction_order as pallas_order
            order = pallas_order(keep, pallas_interpret)
        else:
            order = compaction_order(keep)
        count = jnp.sum(keep, dtype=jnp.int32)
        lanes = []
        for i in range(ncols):
            lanes.append(datas[i])
            lanes.append(valids[i])
            if has_hi[i]:
                lanes.append(his[i])
        moved = grouped_take(lanes, order)
        live = jnp.arange(keep.shape[0], dtype=jnp.int32) < count
        out = []
        j = 0
        for i in range(ncols):
            d = moved[j]
            v = moved[j + 1] & live
            j += 2
            h = None
            if has_hi[i]:
                h = moved[j]
                j += 1
            out.append((d, v, h))
        return out, count
    return run


def compact_batch(db: DeviceBatch, keep: jax.Array,
                  conf: TpuConf = DEFAULT_CONF,
                  sync: bool = False) -> DeviceBatch:
    """Keep rows where `keep` is True (padding rows must already be False).

    By default the surviving row count stays on device (`num_rows` becomes a
    0-d jax scalar) so a filter feeding another device operator costs zero
    host round-trips — the device↔host sync latency (70ms over a tunneled
    chip) dwarfs any saving from shrinking the bucket.  Pass `sync=True` to
    fetch the count and re-bucket down (worth it before expensive downstream
    work when selectivity is high).
    """
    from .batch_ops import shrink_to_rows
    if db.thin is not None:
        # thin batch: deferred columns gather straight from their lane
        # sources into compacted position — one pass, no
        # materialize-then-compact double gather
        from ..columnar.lanes import compact_thin
        db = compact_thin(db, keep, conf)
        if not sync:
            return db
        return shrink_to_rows(db, int(db.num_rows), conf)
    has_hi = tuple(c.data_hi is not None for c in db.columns)
    from .pallas import elect_compact
    tier = elect_compact(conf, db.capacity)
    pallas_interpret = None if tier is None else tier.interpret
    sig = (db.num_columns, has_hi, db.capacity,
           tuple(str(c.data.dtype) for c in db.columns),
           pallas_interpret)
    fn = _COMPACT_CACHE.get(sig)
    if fn is None:
        fn = jax.jit(_compact_trace(db.num_columns, has_hi,
                                    pallas_interpret))
        _COMPACT_CACHE[sig] = fn
    if any(has_hi):
        zeros = jnp.zeros((db.capacity,), jnp.int64)
        his = tuple(c.data_hi if h else zeros
                    for c, h in zip(db.columns, has_hi))
    else:
        his = tuple(c.data for c in db.columns)  # ignored by the trace
    outs, count = fn(tuple(c.data for c in db.columns),
                     tuple(c.validity for c in db.columns), his, keep)
    cols = [DeviceColumn(d, v, c.dtype, c.dictionary, h)
            for (d, v, h), c in zip(outs, db.columns)]
    if not sync:
        return DeviceBatch(cols, count, db.names, db.origin_file)
    return shrink_to_rows(
        DeviceBatch(cols, int(count), db.names, db.origin_file),
        int(count), conf)


def gather_batch(db: DeviceBatch, indices: jax.Array, out_rows: int,
                 names: List[str] = None,
                 null_out_of_bounds: bool = False) -> DeviceBatch:
    """Gather rows of `db` at `indices` (shape (out_capacity,)).

    Rows with index < 0 or >= num_rows become null when
    `null_out_of_bounds` (cuDF NULLIFY), used by outer joins; rows past
    `out_rows` are padding.
    """
    cap_out = indices.shape[0]
    in_bounds = (indices >= 0) & (indices < jnp.int32(db.num_rows))
    safe = jnp.clip(indices, 0, max(db.capacity - 1, 0)).astype(jnp.int32)
    live = live_mask(cap_out, jnp.int32(out_rows))
    vmask = live & in_bounds if null_out_of_bounds else live

    lanes = []
    slots = []          # (col index, lane kind) per lane
    for ci, c in enumerate(db.columns):
        lanes.append(c.data)
        slots.append((ci, "d"))
        lanes.append(c.validity)
        slots.append((ci, "v"))
        if c.data_hi is not None:
            lanes.append(c.data_hi)
            slots.append((ci, "h"))
    moved = grouped_take(lanes, safe)
    gathered = {slot: arr for slot, arr in zip(slots, moved)}
    cols = []
    for ci, c in enumerate(db.columns):
        d = gathered[(ci, "d")]
        v = gathered[(ci, "v")] & vmask
        h = gathered.get((ci, "h"))
        cols.append(DeviceColumn(d, v, c.dtype, c.dictionary, h))
    return DeviceBatch(cols, out_rows, names or list(db.names))
