"""Session-timezone conversion via precomputed transition tables.

Reference: GpuTimeZoneDB (spark-rapids-jni) loads the IANA tz database
into a GPU-resident transition table so non-UTC timestamp operations run
device-side (SURVEY §2.9; datetimeExpressions.scala + TimeZoneDB.scala).
TPU-native equivalent: the table is two small int64 lanes
(UTC transition instants, offsets) built once per zone from the OS tzdata
(zoneinfo) and shipped to the device through the aux-upload cache; the
conversion is one vectorized `searchsorted` + gather — branchless, fully
traceable inside whole-plan programs.

Wall->UTC (the DST-gap/overlap minefield) follows Spark/java.time
semantics: ambiguous local times take the EARLIER offset; skipped local
times shift forward by the gap.
"""
from __future__ import annotations

import datetime as _dt
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_US = 1_000_000


@functools.lru_cache(maxsize=64)
def transition_table(tz_name: str) -> Tuple[np.ndarray, np.ndarray]:
    """-> (utc_instants_us ascending, offsets_us) with a leading sentinel
    so searchsorted-1 always lands on a valid row.

    Built by probing zoneinfo at UTC year boundaries and bisecting down
    to exact transition instants — exact for every zone the OS tzdata
    knows, without reaching into private tzfile internals."""
    from zoneinfo import ZoneInfo
    tz = ZoneInfo(tz_name)

    def off_us(utc_us: int) -> int:
        ts = _dt.datetime.fromtimestamp(utc_us / _US, _dt.timezone.utc)
        return int(ts.astimezone(tz).utcoffset().total_seconds() * _US)

    lo_year, hi_year = 1900, 2100
    instants = [int(_dt.datetime(lo_year, 1, 1,
                                 tzinfo=_dt.timezone.utc).timestamp()) * _US]
    offsets = [off_us(instants[0])]
    probe = instants[0]
    # 4-day probe window: fine enough that no real zone transitions twice
    # inside one window (Morocco's paired Ramadan transitions are weeks
    # apart; a 6-month window cancels them out entirely)
    step = 4 * 86400 * _US
    cur_off = offsets[0]
    end = int(_dt.datetime(hi_year, 1, 1,
                           tzinfo=_dt.timezone.utc).timestamp()) * _US
    while probe < end:
        nxt = probe + step
        o = off_us(nxt)
        if o != cur_off:
            # bisect the exact transition instant in [probe, nxt]
            lo, hi = probe, nxt
            while hi - lo > _US:
                mid = (lo + hi) // 2
                if off_us(mid) == cur_off:
                    lo = mid
                else:
                    hi = mid
            instants.append(hi)
            offsets.append(o)
            cur_off = o
        probe = nxt
    return (np.asarray(instants, np.int64), np.asarray(offsets, np.int64))


@functools.lru_cache(maxsize=64)
def wall_table(tz_name: str) -> Tuple[np.ndarray, np.ndarray]:
    """Transition table keyed by LOCAL wall instants for wall->UTC:
    (wall_points_us ascending, offsets_us).  Points are each transition's
    pre-gap wall time; ambiguous ranges resolve to the EARLIER offset by
    taking the last point <= wall (Spark/java.time's default)."""
    utc_pts, offs = transition_table(tz_name)
    wall_pts = [utc_pts[0] + offs[0]]
    w_offs = [offs[0]]
    for i in range(1, len(utc_pts)):
        prev_off, new_off = int(offs[i - 1]), int(offs[i])
        t_utc = int(utc_pts[i])
        # Switch at wall = t + max(prev, new):
        #  * spring-forward gap [t+prev, t+new): walls below t+new keep
        #    the PREVIOUS offset, so a skipped wall shifts FORWARD by the
        #    gap (java.time/Spark: 02:30 EST-gap -> 07:30 UTC);
        #  * fall-back overlap [t+new, t+prev): the EARLIER offset wins
        #    inside the overlap, switching only at the overlap end.
        wall_pts.append(t_utc + max(prev_off, new_off))
        w_offs.append(new_off)
    return (np.asarray(wall_pts, np.int64), np.asarray(w_offs, np.int64))


def utc_to_local(ts_us: jax.Array, points: jax.Array,
                 offsets: jax.Array) -> jax.Array:
    """Local wall-clock micros for UTC instants (vectorized)."""
    from .search import searchsorted
    idx = jnp.clip(searchsorted(points, ts_us, side="right") - 1,
                   0, points.shape[0] - 1)
    return ts_us + jnp.take(offsets, idx)


def local_to_utc(wall_us: jax.Array, wall_points: jax.Array,
                 offsets: jax.Array) -> jax.Array:
    """UTC instants for local wall-clock micros (earlier-offset rule for
    ambiguous walls; skipped walls shift forward by the gap)."""
    from .search import searchsorted
    idx = jnp.clip(searchsorted(wall_points, wall_us, side="right") - 1,
                   0, wall_points.shape[0] - 1)
    return wall_us - jnp.take(offsets, idx)
