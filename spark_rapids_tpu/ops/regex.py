"""Java-regex → byte-DFA transpiler + device prefix-automaton runner.

The reference's CudfRegexTranspiler (RegexParser.scala:687, 2162 LoC)
parses Java regex and re-emits it in the cuDF dialect, REJECTING patterns
whose semantics don't map — the pattern for any engine whose regex dialect
differs from Java's.  The TPU has no regex engine at all, so the transpile
target here is further down: a byte-level DFA executed as a *prefix
automaton* —

  * parse a Java-regex subset (literals, escapes, char classes, '.',
    top-level anchors, groups, alternation, greedy quantifiers) to an AST,
    rejecting constructs whose semantics can't compile to a DFA
    (backreferences, lookaround, lazy/possessive quantifiers, interior
    anchors, word boundaries) with a RegexUnsupported the caller turns
    into a fallback — the same reject contract as the reference;
  * Thompson-construct an NFA over the BYTE alphabet (non-ASCII literals
    expand to their UTF-8 byte sequences; '.' and negated classes accept
    well-formed multi-byte sequences, so character semantics survive the
    byte-level compilation);
  * subset-construct a DFA with a state cap (blowup ⇒ reject);
  * run it on device: each byte of the dictionary's flat byte tensor
    becomes a state-mapping vector, composed by a segmented
    `associative_scan` (function composition is associative — the classic
    parallel DFA evaluation), with resets at string starts.  One log-depth
    pass matches EVERY dictionary entry simultaneously; per-row verdicts
    gather by dictionary code.

Search (RLIKE) semantics come from automaton shape, not scanning: an
unanchored head becomes a start-state self-loop, an unanchored tail makes
accepting states absorbing.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

MAX_NFA_STATES = 256
MAX_DFA_STATES = 96
MAX_REPEAT = 64


class RegexUnsupported(Exception):
    """Pattern uses a construct outside the DFA-compilable subset."""


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class _Node:
    pass


class _Bytes(_Node):              # literal byte sequence (one char)
    def __init__(self, bs: bytes):
        self.bs = bs


class _Class(_Node):              # set of single BYTES (ASCII subset)
    def __init__(self, bytes_set: FrozenSet[int], with_multibyte: bool):
        self.bytes_set = bytes_set
        self.with_multibyte = with_multibyte   # also match any non-ASCII char


class _Concat(_Node):
    def __init__(self, parts: List[_Node]):
        self.parts = parts


class _Alt(_Node):
    def __init__(self, opts: List[_Node]):
        self.opts = opts


class _Repeat(_Node):
    def __init__(self, node: _Node, lo: int, hi: Optional[int]):
        self.node = node
        self.lo = lo
        self.hi = hi             # None = unbounded


_ASCII = frozenset(range(0x00, 0x80))
_DIGITS = frozenset(range(ord("0"), ord("9") + 1))
_WORD = frozenset(ord(c) for c in
                  "abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(b" \t\n\x0b\f\r")


class _Parser:
    """Recursive-descent Java-regex parser for the DFA subset."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self) -> Tuple[_Node, bool, bool]:
        """Returns (ast, start_anchored, end_anchored)."""
        start = False
        if self.peek() == "^":
            self.take()
            start = True
        node = self._alternation(top=True)
        end = getattr(self, "_end_anchor", False)
        if self.i < len(self.p):
            raise RegexUnsupported(f"unbalanced pattern at {self.i}")
        return node, start, end

    def _alternation(self, top=False) -> _Node:
        opts = [self._concat(top)]
        while self.peek() == "|":
            self.take()
            opts.append(self._concat(top))
        if len(opts) > 1 and top and getattr(self, "_end_anchor", False):
            # '$' consumed inside one branch of a top-level alternation
            raise RegexUnsupported(
                "'$' inside an alternation branch (interior anchor)")
        return opts[0] if len(opts) == 1 else _Alt(opts)

    def _concat(self, top=False) -> _Node:
        parts: List[_Node] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            if c == "$":
                self.take()
                if top and self.peek() is None:
                    self._end_anchor = True
                    break
                raise RegexUnsupported("interior '$' anchor")
            if c == "^":
                raise RegexUnsupported("interior '^' anchor")
            atom = self._atom()
            atom = self._quantified(atom)
            parts.append(atom)
        return _Concat(parts)

    def _quantified(self, atom: _Node) -> _Node:
        c = self.peek()
        if c not in ("*", "+", "?", "{"):
            return atom
        if c == "{":
            save = self.i
            self.take()
            lo, hi = self._brace()
            if lo is None:                   # not a quantifier: literal '{'
                self.i = save
                return atom
        else:
            self.take()
            lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[c]
        nxt = self.peek()
        if nxt == "?":
            raise RegexUnsupported("lazy quantifier (no leftmost spans "
                                   "in a DFA)")
        if nxt == "+":
            raise RegexUnsupported("possessive quantifier")
        return _Repeat(atom, lo, hi)

    def _brace(self):
        digits = ""
        while self.peek() is not None and self.peek().isdigit():
            digits += self.take()
        if not digits:
            return None, None
        lo = int(digits)
        hi = lo
        if self.peek() == ",":
            self.take()
            d2 = ""
            while self.peek() is not None and self.peek().isdigit():
                d2 += self.take()
            hi = int(d2) if d2 else None
        if self.peek() != "}":
            return None, None
        self.take()
        if hi is not None and (hi < lo or hi > MAX_REPEAT) or lo > MAX_REPEAT:
            raise RegexUnsupported(f"repeat bound beyond {MAX_REPEAT}")
        return lo, hi

    def _atom(self) -> _Node:
        c = self.take()
        if c == "(":
            if self.peek() == "?":
                self.take()
                n = self.peek()
                if n == ":":
                    self.take()
                else:
                    raise RegexUnsupported(
                        "lookaround / named group / inline flags")
            inner = self._alternation()
            if self.peek() != ")":
                raise RegexUnsupported("unbalanced group")
            self.take()
            return inner           # capturing == non-capturing for matching
        if c == "[":
            return self._char_class()
        if c == ".":
            # Java default: any char except line terminators
            return _Class(_ASCII - {0x0A, 0x0D}, with_multibyte=True)
        if c == "\\":
            return self._escape()
        if c in "*+?":
            raise RegexUnsupported(f"dangling quantifier '{c}'")
        return _Bytes(c.encode("utf-8"))

    def _escape(self) -> _Node:
        if self.peek() is None:
            raise RegexUnsupported("trailing backslash")
        c = self.take()
        simple = {"n": b"\n", "t": b"\t", "r": b"\r", "f": b"\f",
                  "a": b"\x07", "e": b"\x1b", "0": b"\x00"}
        if c in simple:
            return _Bytes(simple[c])
        if c == "d":
            return _Class(_DIGITS, False)
        if c == "D":
            return _Class(_ASCII - _DIGITS, True)
        if c == "w":
            return _Class(_WORD, False)
        if c == "W":
            return _Class(_ASCII - _WORD, True)
        if c == "s":
            return _Class(_SPACE, False)
        if c == "S":
            return _Class(_ASCII - _SPACE, True)
        if c == "x":
            h = ""
            for _ in range(2):
                if self.peek() is None:
                    raise RegexUnsupported("bad \\x escape")
                h += self.take()
            return _Bytes(bytes([int(h, 16)]))
        if c in "123456789":
            raise RegexUnsupported("backreference")
        if c in ("b", "B"):
            raise RegexUnsupported("word boundary")
        if c in ("A",):
            raise RegexUnsupported("\\A anchor (use leading ^)")
        if c in ("z", "Z", "G"):
            raise RegexUnsupported(f"\\{c} anchor")
        if c in ("p", "P", "u", "N", "k", "Q"):
            raise RegexUnsupported(f"\\{c} construct")
        # escaped metacharacter or punctuation: literal
        return _Bytes(c.encode("utf-8"))

    def _char_class(self) -> _Node:
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        items: set = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexUnsupported("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            c = self.take()
            if c == "[" and self.peek() == ":":
                raise RegexUnsupported("POSIX class")
            if c == "\\":
                e = self.take()
                cls = {"d": _DIGITS, "w": _WORD, "s": _SPACE}.get(e)
                if cls is not None:
                    items |= cls
                    continue
                if e in ("D", "W", "S"):
                    raise RegexUnsupported(
                        "negated predefined class inside a class")
                simple = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C}
                lo_b = simple.get(e, ord(e) if ord(e) < 128 else None)
                if lo_b is None:
                    raise RegexUnsupported("non-ASCII escape in class")
            else:
                if ord(c) > 127:
                    raise RegexUnsupported("non-ASCII char in class")
                lo_b = ord(c)
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hi_c = self.take()
                if hi_c == "\\":
                    hi_c = self.take()
                if ord(hi_c) > 127:
                    raise RegexUnsupported("non-ASCII range in class")
                items |= set(range(lo_b, ord(hi_c) + 1))
            else:
                items.add(lo_b)
        if negated:
            return _Class(_ASCII - items, with_multibyte=True)
        return _Class(frozenset(items), with_multibyte=False)


# ---------------------------------------------------------------------------
# NFA (Thompson) over the byte alphabet
# ---------------------------------------------------------------------------

_MB_LEAD2 = frozenset(range(0xC2, 0xE0))
_MB_LEAD3 = frozenset(range(0xE0, 0xF0))
_MB_LEAD4 = frozenset(range(0xF0, 0xF5))
_MB_CONT = frozenset(range(0x80, 0xC0))


class _NFA:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[FrozenSet[int], int]]] = []

    def new_state(self) -> int:
        if len(self.eps) >= MAX_NFA_STATES:
            raise RegexUnsupported("pattern too large (NFA cap)")
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add_eps(self, a, b):
        self.eps[a].append(b)

    def add(self, a, byteset: FrozenSet[int], b):
        self.trans[a].append((byteset, b))

    def _multibyte(self, a, b):
        """Accept one well-formed non-ASCII UTF-8 char from a to b."""
        m2 = self.new_state()
        self.add(a, _MB_LEAD2, m2)
        self.add(m2, _MB_CONT, b)
        m3a, m3b = self.new_state(), self.new_state()
        self.add(a, _MB_LEAD3, m3a)
        self.add(m3a, _MB_CONT, m3b)
        self.add(m3b, _MB_CONT, b)
        m4a, m4b, m4c = (self.new_state(), self.new_state(),
                         self.new_state())
        self.add(a, _MB_LEAD4, m4a)
        self.add(m4a, _MB_CONT, m4b)
        self.add(m4b, _MB_CONT, m4c)
        self.add(m4c, _MB_CONT, b)

    def build(self, node: _Node, a: int, b: int):
        """Wire `node` to accept between states a..b."""
        if isinstance(node, _Bytes):
            cur = a
            for i, byte in enumerate(node.bs):
                nxt = b if i == len(node.bs) - 1 else self.new_state()
                self.add(cur, frozenset([byte]), nxt)
                cur = nxt
        elif isinstance(node, _Class):
            if node.bytes_set:
                self.add(a, node.bytes_set, b)
            if node.with_multibyte:
                self._multibyte(a, b)
        elif isinstance(node, _Concat):
            cur = a
            for i, part in enumerate(node.parts):
                nxt = b if i == len(node.parts) - 1 else self.new_state()
                self.build(part, cur, nxt)
                cur = nxt
            if not node.parts:
                self.add_eps(a, b)
        elif isinstance(node, _Alt):
            for opt in node.opts:
                s, e = self.new_state(), self.new_state()
                self.add_eps(a, s)
                self.build(opt, s, e)
                self.add_eps(e, b)
        elif isinstance(node, _Repeat):
            lo, hi = node.lo, node.hi
            cur = a
            for _ in range(lo):
                nxt = self.new_state()
                self.build(node.node, cur, nxt)
                cur = nxt
            if hi is None:
                # loop: cur -> cur, then out
                s, e = self.new_state(), self.new_state()
                self.add_eps(cur, s)
                self.build(node.node, s, e)
                self.add_eps(e, s)
                self.add_eps(cur, b)
                self.add_eps(e, b)
            else:
                self.add_eps(cur, b)
                for _ in range(hi - lo):
                    nxt = self.new_state()
                    self.build(node.node, cur, nxt)
                    self.add_eps(nxt, b)
                    cur = nxt
        else:
            raise RegexUnsupported(f"unknown node {node!r}")


# ---------------------------------------------------------------------------
# DFA via subset construction
# ---------------------------------------------------------------------------

class Dfa:
    """table: (S, 256) int16 next-state; accepting: (S,) bool; start: 0."""

    def __init__(self, table: np.ndarray, accepting: np.ndarray):
        self.table = table
        self.accepting = accepting

    @property
    def num_states(self):
        return self.table.shape[0]


def _eps_closure(nfa: _NFA, states: FrozenSet[int]) -> FrozenSet[int]:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for n in nfa.eps[s]:
            if n not in seen:
                seen.add(n)
                stack.append(n)
    return frozenset(seen)


def compile_dfa(pattern: str, search: bool = True,
                max_states: int = MAX_DFA_STATES) -> Dfa:
    """Compile a Java regex to a byte DFA.

    search=True gives RLIKE find-anywhere semantics via automaton shape:
    unanchored head = start loops on every byte; unanchored tail =
    accepting states absorb.  Raises RegexUnsupported outside the subset.
    """
    ast, anchored_start, anchored_end = _Parser(pattern).parse()
    nfa = _NFA()
    start = nfa.new_state()
    accept = nfa.new_state()
    nfa.build(ast, start, accept)

    all_bytes = frozenset(range(256))
    if search and not anchored_start:
        nfa.add(start, all_bytes, start)
    if search and not anchored_end:
        nfa.add(accept, all_bytes, accept)

    d0 = _eps_closure(nfa, frozenset([start]))
    states: Dict[FrozenSet[int], int] = {d0: 0}
    order = [d0]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        row = np.zeros(256, np.int16)
        # group target sets per byte
        for b in range(256):
            tgt = set()
            for s in cur:
                for byteset, to in nfa.trans[s]:
                    if b in byteset:
                        tgt.add(to)
            if tgt:
                closed = _eps_closure(nfa, frozenset(tgt))
            else:
                closed = frozenset()
            idx = states.get(closed)
            if idx is None:
                if len(states) >= max_states:
                    raise RegexUnsupported("DFA state blowup")
                idx = len(states)
                states[closed] = idx
                order.append(closed)
            row[b] = idx
        rows.append(row)
        i += 1
    table = np.stack(rows)
    accepting = np.array([accept in st for st in order], bool)
    return Dfa(table, accepting)


# ---------------------------------------------------------------------------
# Device runner: segmented prefix-automaton
# ---------------------------------------------------------------------------

def dfa_matches(dfa: Dfa, offsets, bytes_):
    """Convenience wrapper over dfa_matches_lanes (uploads the tables)."""
    import jax.numpy as jnp
    return dfa_matches_lanes(jnp.asarray(dfa.table.T.astype(np.int16)),
                             jnp.asarray(dfa.accepting), offsets, bytes_)


def dfa_matches_lanes(table_t, accepting, offsets, bytes_):
    """Run the DFA over every dictionary entry at once.

    table_t: (256, S) int16 transposed transition table; accepting: (S,)
    bool; offsets: (n_entries+1,) int32; bytes_: (n_bytes,) uint8 — all
    device arrays.  Returns (n_entries,) bool device — entry matches.

    Each byte maps to its column of the transition table (a state-mapping
    vector); a segmented associative scan composes the mappings with
    resets at entry starts, and the verdict gathers the end-of-entry
    state.  O(n_bytes * S) work at log depth — every entry in parallel.
    """
    import jax.numpy as jnp
    import jax

    S = table_t.shape[1]
    n = bytes_.shape[0]
    n_entries = offsets.shape[0] - 1

    if n == 0:
        # all entries empty: start state decides
        return jnp.broadcast_to(accepting[0], (n_entries,))

    fmap = table_t[bytes_.astype(jnp.int32)]              # (n, S)
    # empty entries have start == next start (or == n, dropped): clipping
    # would alias them onto the PREVIOUS entry's last byte
    starts = jnp.zeros((n,), bool).at[offsets[:-1]].set(True, mode="drop")

    def combine(a, b):
        av, af = a
        bv, bf = b
        composed = jnp.take_along_axis(bv, av.astype(jnp.int32), axis=-1)
        return jnp.where(bf[..., None], bv, composed), af | bf

    pref, _ = jax.lax.associative_scan(combine, (fmap, starts))
    # state at entry end = pref[last_byte][start=0]; empty entry -> state 0
    last = jnp.clip(offsets[1:] - 1, 0, n - 1)
    end_state = pref[last, 0]
    empty = offsets[1:] == offsets[:-1]
    end_state = jnp.where(empty, 0, end_state)
    return accepting[jnp.clip(end_state, 0, S - 1)]
