"""Mergeable quantile sketch: device build, host merge.

Role of the reference's t-digest approx_percentile
(/root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
aggregate/GpuApproximatePercentile.scala — cuDF t-digest build/merge with
fixed `delta` centroids): a FIXED-SIZE summary per group that partial
aggregation can build on device and a final aggregation can merge across
an exchange, so distributed approx_percentile has the same partial/final
shape as every other aggregate instead of silently degrading to an
exact-sort single-node algorithm.

TPU-first formulation — an equi-rank summary rather than a centroid
tree: the partial sorts its rows once (the sort-segment machinery the
exact percentile already rides) and keeps, per group, the row count and
K order statistics at evenly spaced ranks.  Merging summaries is a
weighted-percentile resample (tiny: K points per input, numpy on host).
Rank error is <= 1/(2(K-1)) per level and levels only add — two levels
(partial -> final) stay well inside the reference t-digest's own
delta=100 centroid resolution at the default K.

NaN ordering follows Spark doubles (NaN greatest); nulls never enter a
sketch (count excludes them, matching ApproximatePercentile semantics).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# number of stored order statistics per group summary — matches the
# reference t-digest's default resolution class (delta=100 centroids)
# with margin; 129 f64 lanes per group keeps the partial buffer small
DEFAULT_K = 129


def sketch_gather(s_val: jax.Array, start_idx: jax.Array,
                  cnt: jax.Array, k: int, num_segments: int,
                  capacity: int):
    """Per-group equi-rank samples from value-sorted rows.

    s_val: value lane sorted by (group, value) — the sorted_segments
    layout; start_idx/cnt: per-group first row and non-null count.
    Returns points (num_segments, k): for group g, point j sits at rank
    round(j*(cnt-1)/(k-1)).  Empty groups produce zeros (masked by the
    caller via cnt == 0)."""
    j = jnp.arange(k, dtype=jnp.float64)
    n1 = jnp.maximum(cnt.astype(jnp.float64) - 1.0, 0.0)
    ranks = jnp.round(j[None, :] * (n1[:, None] / (k - 1))).astype(jnp.int32)
    pos = jnp.clip(start_idx[:, None] + ranks, 0, capacity - 1)
    return s_val[pos]


def merge_sketches(parts: Sequence[Tuple[int, np.ndarray]],
                   k: int = DEFAULT_K) -> Tuple[int, np.ndarray]:
    """Merge (count, points[k]) summaries into one — host side, numpy.

    Each input point represents count/k rows (endpoints half-weight, the
    standard trapezoid weighting for equi-rank samples).  The merged
    summary resamples the weighted union at k even ranks.  The operation
    is associative up to the summary's own rank error (tested)."""
    parts = [(int(n), np.asarray(p, np.float64)) for n, p in parts
             if int(n) > 0]
    if not parts:
        return 0, np.zeros(k, np.float64)
    if len(parts) == 1:
        return parts[0]
    vals = []
    wts = []
    for n, pts in parts:
        m = len(pts)
        w = np.full(m, n / max(m - 1, 1), np.float64)
        w[0] *= 0.5
        w[-1] *= 0.5
        vals.append(pts)
        wts.append(w)
    v = np.concatenate(vals)
    w = np.concatenate(wts)
    # NaN sorts greatest (Spark double order)
    order = np.argsort(np.where(np.isnan(v), np.inf, v), kind="stable")
    nan_last = np.argsort(np.isnan(v[order]), kind="stable")
    order = order[nan_last]
    v = v[order]
    cw = np.cumsum(w[order])
    total = cw[-1]
    n_out = sum(n for n, _ in parts)
    target = np.linspace(0.0, total, k)
    idx = np.searchsorted(cw, target, side="left")
    idx = np.clip(idx, 0, len(v) - 1)
    return n_out, v[idx]


def query_sketch(n: int, pts: np.ndarray, q: float) -> float:
    """Quantile estimate with linear interpolation between stored ranks
    (Spark percentile interpolation applied to the summary)."""
    if n <= 0:
        return None
    k = len(pts)
    pos = q * (k - 1)
    lo = int(np.floor(pos))
    hi = min(lo + 1, k - 1)
    frac = pos - lo
    if frac == 0.0:
        return float(pts[lo])
    return float(pts[lo] * (1 - frac) + pts[hi] * frac)
