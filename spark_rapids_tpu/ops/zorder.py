"""Z-order (Morton) clustering keys on device.

Role of the reference's ZOrder JNI kernel (SURVEY §2.9: interleave bits,
used by Delta OPTIMIZE ZORDER and Databricks interleave_bits; zorder/
dir ~323 LoC).  TPU formulation: per column, min-max scale values to
uint32 in ONE fused program (the scan for min/max and the scale both
vectorize), then interleave the top `63 // n_cols` bits of every column
into a single int64 sort key — bit i of the key cycles through the
columns, so sorting by the key gives the space-filling-curve order that
keeps per-file min/max ranges tight on every z-ordered column.

On TPUs with emulated f64 (double-double) the min-max scaling can land
one ulp away from a host float64 computation, so device keys match a
numpy oracle only to ±1 in each column's scaled value — identical
clustering, not identical bits (the engine's general computed-f64
deviation policy; exact-bit tests belong on the CPU backend).

The 63-bit key truncates each column to 63/n bits of resolution (vs the
reference's full byte-array keys): for file clustering this is ample —
resolution only needs to exceed the file count by a few bits — and it
keeps the key a single sortable lane instead of a variable-width byte
string XLA cannot sort natively.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _to_unit_u32(x: jax.Array, valid: jax.Array, bits: int) -> jax.Array:
    """Min-max scale a numeric lane to [0, 2^bits) uint32; nulls map to
    0 (clustered first, like NULLS FIRST)."""
    f = x.astype(jnp.float64)
    big = jnp.float64(1e300)
    lo = jnp.min(jnp.where(valid, f, big))
    hi = jnp.max(jnp.where(valid, f, -big))
    span = jnp.maximum(hi - lo, 1e-300)
    top = jnp.float64((1 << bits) - 1)
    u = jnp.clip((f - lo) / span * top, 0.0, top)
    return jnp.where(valid, u.astype(jnp.uint32), jnp.uint32(0))


def zorder_key(lanes: Sequence[jax.Array],
               valids: Sequence[jax.Array]) -> jax.Array:
    """Interleaved int64 sort key from N numeric lanes (N <= 8).
    Column 0 owns the most significant bit of each round."""
    n = len(lanes)
    if not 1 <= n <= 8:
        raise ValueError(f"zorder over {n} columns (1..8 supported)")
    bits = min(32, 63 // n)      # 63 total: keys stay positive as int64
    us = [_to_unit_u32(x, v, bits) for x, v in zip(lanes, valids)]
    key = jnp.zeros(lanes[0].shape, jnp.uint64)
    for b in range(bits - 1, -1, -1):
        for u in us:
            key = (key << jnp.uint64(1)) | \
                ((u >> jnp.uint32(b)) & jnp.uint32(1)).astype(jnp.uint64)
    return key.astype(jnp.int64)   # <= 63 bits used: always positive


def zorder_key_np(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Numpy reference implementation (tests oracle)."""
    n = len(cols)
    bits = min(32, 63 // n)
    us = []
    for c in cols:
        f = c.astype(np.float64)
        lo, hi = f.min(), f.max()
        span = max(hi - lo, 1e-300)
        top = float((1 << bits) - 1)
        us.append(np.clip((f - lo) / span * top, 0, top).astype(np.uint64))
    key = np.zeros(len(cols[0]), np.uint64)
    for b in range(bits - 1, -1, -1):
        for u in us:
            key = (key << np.uint64(1)) | ((u >> np.uint64(b))
                                           & np.uint64(1))
    return key.astype(np.int64)


def zorder_sort_indices(table_cols: List[np.ndarray],
                        use_device: bool = True) -> np.ndarray:
    """Row order that clusters by z-value; device path when available."""
    if use_device:
        try:
            lanes = [jnp.asarray(c.astype(np.float64)) for c in table_cols]
            valids = [jnp.ones(len(table_cols[0]), bool)] * len(table_cols)
            key = np.asarray(zorder_key(lanes, valids))
            return np.argsort(key, kind="stable")
        except Exception as e:                   # noqa: BLE001
            # A real kernel regression must not masquerade as a quiet CPU
            # fallback: surface it (jax backend errors are RuntimeError;
            # anything else is a bug in zorder_key itself).
            import logging
            logging.getLogger(__name__).warning(
                "zorder device path failed, using numpy oracle: %s", e)
            if not isinstance(e, (RuntimeError, jax.errors.JaxRuntimeError)):
                raise
    return np.argsort(zorder_key_np(table_cols), kind="stable")
