"""Sort-based segmented group-by: the cuDF `Table.groupBy().aggregate()` role.

cuDF uses a device hash table; on TPU the idiomatic shape is sort + segment
reduction (static shapes, no scatter contention, MXU/VPU-friendly):

  1. lexsort rows by (liveness, key lanes with validity)  — padding rows and
     null keys each group cleanly (Spark groups nulls as equal)
  2. boundary flags where any key lane differs from the previous row
  3. segment_ids = cumsum(flags); group count = one scalar D2H
  4. jax.ops.segment_{sum,min,max} per aggregate with null/live masking
  5. group keys gathered from each segment's first row

Everything is one jit per (shape-bucket, agg signature); outputs stay padded
to capacity so downstream operators reuse the same bucket.

Min/max float ordering follows Java's Double.compare (NaN greatest,
-0.0 < 0.0) by running the comparison in bit-space when the column carries
the int64-bits storage lane, else a NaN-tracked value-space fallback.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .kernels import blocked_cumsum, compute_view
from .segments import (blocked_seg_scan, lexsort_capped, row0_true,
                       seg_reduce_sorted, seg_sums_sorted, segment_ends)


# Aggregate kernel op kinds understood by the kernel.
# (update vs merge distinction lives in plan/aggregates.py; by kernel time
# everything is one of these.)
SUM = "sum"
COUNT = "count"          # counts valid rows
COUNT_ALL = "count_all"  # counts live rows (count(*) / count(1))
MIN = "min"
MAX = "max"
FIRST = "first"          # first live row's value (Spark ignoreNulls=false)
LAST = "last"
FIRST_NN = "first_nn"    # first non-null (ignoreNulls=true)
LAST_NN = "last_nn"
ANY = "any"              # boolean or
EVERY = "every"          # boolean and


@dataclasses.dataclass(frozen=True)
class AggSpec:
    kind: str
    input_idx: int                 # index into the agg-input column list
    dtype: object                  # logical result type (t.DataType)


def _null_first_key_lanes(data, valid, dt):
    """Lanes making (valid, data) lexsort-comparable; nulls group together."""
    if valid is None:
        valid_lane = None
    else:
        valid_lane = (~valid).astype(jnp.int8)   # nulls first among live rows
        # canonicalize null rows' payload so they compare equal regardless of
        # what the producing kernel left in the data lane
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
    if dt is not None and isinstance(dt, t.DoubleType) and data.dtype == jnp.float64:
        # computed f64: order by value; NaN needs a consistent slot — push to
        # the top via isnan lane handled by caller. Grouping only needs
        # consistency, and NaN != NaN would split groups: map NaN to a
        # canonical key by replacing with +inf and adding an isnan lane.
        isnan = jnp.isnan(data)
        canon = jnp.where(isnan, jnp.float64(np.inf), data)
        canon = jnp.where(canon == 0.0, jnp.float64(0.0), canon)  # -0.0==0.0
        return [valid_lane, isnan.astype(jnp.int8), canon]
    return [valid_lane, data]


def _eq_prev(lane):
    """Boundary lane: True where row differs from previous sorted row."""
    return jnp.concatenate([jnp.ones((1,), bool), lane[1:] != lane[:-1]])


def _segment_minmax_float(vals, valid_live, seg_ids, num_segments, is_min):
    """Java-ordering min/max for float values (NaN greatest).

    Value-space with NaN tracking; the exact bit-space path for int64-bits
    DOUBLE lanes lives inline in groupby_trace via _bits_total_order."""
    isnan = jnp.isnan(vals) & valid_live
    has_nan = jax.ops.segment_max(isnan.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments) > 0
    all_nan_ident = jnp.float64(np.inf) if is_min else jnp.float64(-np.inf)
    clean = jnp.where(valid_live & ~isnan, vals, all_nan_ident)
    red = (jax.ops.segment_min if is_min else jax.ops.segment_max)(
        clean, seg_ids, num_segments=num_segments)
    non_nan_count = jax.ops.segment_sum(
        (valid_live & ~isnan).astype(jnp.int32), seg_ids,
        num_segments=num_segments)
    if is_min:
        # min is NaN only when every valid value is NaN
        return jnp.where(has_nan & (non_nan_count == 0), jnp.float64(np.nan),
                         red)
    return jnp.where(has_nan, jnp.float64(np.nan), red)


_EXP_MASK = np.int64(0x7FF0000000000000)
_MANT_MASK = np.int64(0x000FFFFFFFFFFFFF)
_CANON_NAN = np.int64(0x7FF8000000000000)


def _bits_total_order(b):
    """Monotone int64 mapping of f64 bit patterns (Java Double.compare).

    -inf < ... < -0.0 < 0.0 < ... < +inf < NaN.  NaN bits are canonicalized
    first so the int64 extremes stay free for masking identities."""
    is_nan = ((b & _EXP_MASK) == _EXP_MASK) & ((b & _MANT_MASK) != 0)
    b = jnp.where(is_nan, jnp.int64(_CANON_NAN), b)
    # int64 wraparound makes -2^63-1-b correct mod 2^64 for all negative b
    return jnp.where(b >= 0, b, jnp.int64(-2**63) - jnp.int64(1) - b)


def _bits_from_order(o):
    return jnp.where(o >= 0, o, jnp.int64(-2**63) - jnp.int64(1) - o)


_ORDER_MAX = np.int64(2**63 - 1)   # unreachable after NaN canonicalization
_ORDER_MIN = np.int64(-2**63)




def _queue_sum_lanes(agg_specs, spec_vls, live_all):
    """Collect every sum-like lane (SUM buffers, COUNT/COUNT_ALL,
    per-input valid counts) into two dtype-class stacks.  Shared by all
    group-by variants so the lane/dtype rules cannot drift.

    Returns (int_lanes, int_slots, f64_lanes, f64_slots)."""
    int_lanes, int_slots = [], {}
    f64_lanes, f64_slots = [], {}

    def queue(key, lane, is_float):
        lanes_, slots = (f64_lanes, f64_slots) if is_float \
            else (int_lanes, int_slots)
        if key not in slots:
            slots[key] = len(lanes_)
            lanes_.append(lane)

    for si, spec in enumerate(agg_specs):
        d, vl = spec_vls[si]
        dt = spec.dtype
        if spec.kind == COUNT_ALL:
            queue(("cnt", si), live_all.astype(jnp.int64), False)
        elif spec.kind == COUNT:
            queue(("cnt", si), vl.astype(jnp.int64), False)
        elif spec.kind == SUM:
            cd = compute_view(d, dt)
            if t.is_floating(dt):
                queue(("sum", si),
                      jnp.where(vl, cd.astype(jnp.float64), 0.0), True)
            else:
                queue(("sum", si),
                      jnp.where(vl, cd.astype(jnp.int64), 0), False)
        if spec.kind not in (COUNT, COUNT_ALL):
            queue(("vc", spec.input_idx), vl.astype(jnp.int64), False)
    return int_lanes, int_slots, f64_lanes, f64_slots


def _batched_sums(agg_specs, spec_vls, live_all, seg, num_segments,
                  reindex):
    """ONE wide (N, K) segment_sum for every sum-like lane — TPU scatters
    pay a fixed serialization cost per pass, so K-wide rows amortize it
    (measured 4.5x for 10 aggregates at 8M rows).

    spec_vls: per-spec (data, valid&live) with any permutation already
    applied; live_all: the COUNT(*) lane; reindex: maps the (S, K)
    segment output onto the caller's group order.
    Returns sum_of(key, is_float) -> (G,) lane."""
    int_lanes, int_slots, f64_lanes, f64_slots = _queue_sum_lanes(
        agg_specs, spec_vls, live_all)

    int_out = f64_out = None
    if int_lanes:
        int_out = reindex(jax.ops.segment_sum(
            jnp.stack(int_lanes, axis=1), seg, num_segments=num_segments))
    if f64_lanes:
        f64_out = reindex(jax.ops.segment_sum(
            jnp.stack(f64_lanes, axis=1), seg, num_segments=num_segments))

    def sum_of(key, is_float):
        return (f64_out[:, f64_slots[key]] if is_float
                else int_out[:, int_slots[key]])
    return sum_of


def _segment_minmax_float_sorted(vals, valid_live, boundary, ends_c,
                                 is_min):
    """Java-ordering float min/max over SORTED runs, scatter-free: the
    NaN flag, the clean reduction and the non-NaN count all ride
    segmented scans gathered at run ends (ops/segments.py) instead of
    three segment_* scatters."""
    isnan = jnp.isnan(vals) & valid_live
    has_nan = seg_reduce_sorted(isnan.astype(jnp.int8), boundary, ends_c,
                                jnp.maximum) > 0
    all_nan_ident = jnp.float64(np.inf) if is_min else jnp.float64(-np.inf)
    clean = jnp.where(valid_live & ~isnan, vals, all_nan_ident)
    red = seg_reduce_sorted(clean, boundary, ends_c,
                            jnp.minimum if is_min else jnp.maximum)
    if is_min:
        non_nan = seg_reduce_sorted(
            (valid_live & ~isnan).astype(jnp.int32), boundary, ends_c,
            jnp.add)
        # min is NaN only when every valid value is NaN
        return jnp.where(has_nan & (non_nan == 0), jnp.float64(np.nan),
                         red)
    return jnp.where(has_nan, jnp.float64(np.nan), red)


def sorted_agg_outputs(agg_specs, spec_vls, s_live, boundary, starts_c,
                       ends_c, group_live, num_segments: int,
                       capacity: int, scatter_free: bool):
    """Aggregate outputs over SORTED runs — the one implementation both
    the packed and the generic sort-segment group-bys share.

    spec_vls: per-spec (data, valid&live) lanes already in sorted order;
    boundary: live-run starts; starts_c/ends_c: per segment-slot
    first/last row (clipped).  With `scatter_free` every reduction is a
    blocked segmented scan + boundary gather / stacked-cumsum diff
    (ops/segments.py) — zero jax.ops.segment_* scatters in the emitted
    program; without it the legacy segment (scatter) reductions run, so
    the two modes are flip-comparable under one knob."""
    iota = jnp.arange(capacity, dtype=jnp.int32)
    big = jnp.int32(capacity)
    seg_ids = None

    def seg():
        nonlocal seg_ids
        if seg_ids is None:
            # dead rows continue the last segment; their vl is False
            seg_ids = jnp.clip(
                blocked_cumsum(boundary.astype(jnp.int32)) - 1,
                0, num_segments - 1)
        return seg_ids

    def reduce_lane(lane, is_min):
        if scatter_free:
            return seg_reduce_sorted(
                lane, boundary, ends_c,
                jnp.minimum if is_min else jnp.maximum)
        return (jax.ops.segment_min if is_min else jax.ops.segment_max)(
            lane, seg(), num_segments=num_segments)

    # ---- the sum/count family: ONE stacked pass each dtype class ----
    int_lanes, int_slots, f64_lanes, f64_slots = _queue_sum_lanes(
        agg_specs, spec_vls, s_live)
    int_out = f64_out = None
    if int_lanes:
        if scatter_free:
            # stacked cumsum + two boundary gathers; int64 wraparound
            # cancels in the diff (exact whenever the group sum fits
            # int64 — segment_sum's own contract)
            int_out = seg_sums_sorted(int_lanes, starts_c, ends_c)
        else:
            int_out = jax.ops.segment_sum(
                jnp.stack(int_lanes, axis=1), seg(),
                num_segments=num_segments)
    if f64_lanes:
        if scatter_free:
            # SEGMENTED scan, not cumsum-diff: the per-run reset keeps
            # each group's accumulation independent, so one group's sum
            # is never absorbed by preceding groups' magnitudes
            f64_out = blocked_seg_scan(
                jnp.stack(f64_lanes, axis=1), boundary, jnp.add)[ends_c]
        else:
            f64_out = jax.ops.segment_sum(
                jnp.stack(f64_lanes, axis=1), seg(),
                num_segments=num_segments)

    def sum_of(key, is_float):
        return (f64_out[:, f64_slots[key]] if is_float
                else int_out[:, int_slots[key]])

    outs = []
    for si, spec in enumerate(agg_specs):
        d, vl = spec_vls[si]
        dt = spec.dtype
        if spec.kind in (COUNT, COUNT_ALL):
            outs.append((sum_of(("cnt", si), False), group_live))
            continue
        valid_count = sum_of(("vc", spec.input_idx), False)
        out_valid = (valid_count > 0) & group_live
        cd = compute_view(d, dt)
        if spec.kind == SUM:
            data = sum_of(("sum", si), t.is_floating(dt))
        elif spec.kind == FIRST:
            # runs hold only live rows (liveness is the primary sort
            # lane), so first/last are pure boundary gathers
            data = cd[starts_c]
            out_valid = vl[starts_c] & group_live
        elif spec.kind == LAST:
            data = cd[ends_c]
            out_valid = vl[ends_c] & group_live
        elif spec.kind in (MIN, MAX):
            is_min = spec.kind == MIN
            if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                o = _bits_total_order(d)
                ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                o = jnp.where(vl, o, ident)
                data = _bits_from_order(reduce_lane(o, is_min))
            elif t.is_floating(dt):
                if scatter_free:
                    data = _segment_minmax_float_sorted(
                        cd, vl, boundary, ends_c, is_min)
                else:
                    data = _segment_minmax_float(cd, vl, seg(),
                                                 num_segments, is_min)
            else:
                if isinstance(dt, t.BooleanType):
                    ident = jnp.asarray(is_min)
                else:
                    info = np.iinfo(np.dtype(cd.dtype))
                    ident = jnp.asarray(info.max if is_min else info.min,
                                        cd.dtype)
                data = reduce_lane(jnp.where(vl, cd, ident), is_min)
        elif spec.kind in (FIRST_NN, LAST_NN):
            is_first = spec.kind == FIRST_NN
            masked = jnp.where(vl, iota, big if is_first else -1)
            pick = jnp.clip(reduce_lane(masked, is_first), 0,
                            capacity - 1)
            data = cd[pick]
            out_valid = vl[pick] & group_live
        elif spec.kind == ANY:
            data = reduce_lane(
                jnp.where(vl, cd, False).astype(jnp.int8), False) > 0
        elif spec.kind == EVERY:
            data = reduce_lane(
                jnp.where(vl, cd, True).astype(jnp.int8), True) > 0
        else:
            raise ValueError(f"unknown agg kind {spec.kind}")
        outs.append((data, out_valid))
    return outs


def _packed_key_lane(keys, keys_valid, pack_spec):
    """Fold the statically-bounded keys into ONE int64 lane (slot 0 per
    key = null; values offset by -lo+1).  TPU sort compile time AND run
    time scale with operand count (~15-30s compile per extra 8M operand
    on v5e), so a k-key group-by sorting one packed lane instead of 2k
    (validity+data per key) lanes is the difference between a 1-minute
    and a 20-minute query compile."""
    packed = None
    for i, spec in enumerate(pack_spec):
        if spec is None:
            continue
        lo, span = spec
        kd = keys[i].astype(jnp.int64)
        kv = keys_valid[i]
        slot = jnp.clip(kd - jnp.int64(lo) + 1, 0, span - 1)
        if kv is not None:
            slot = jnp.where(kv, slot, jnp.int64(0))
        packed = slot if packed is None \
            else packed * jnp.int64(span) + slot
    return packed


def packed_groupby_trace(pack_spec, key_lanes_info, agg_specs,
                         num_segments, capacity, scatter_free=True):
    """All-keys-packed group-by: ONE sort lane, NO scatters for the
    sum/count family, group keys decoded arithmetically.

    When every key has a static (lo, span) bound the whole key tuple —
    including liveness — folds into one integer sort lane.  This changes
    the cost shape on both axes that dominate this platform:

      * compile: a 2-operand (key, iota) sort compiles in ~30s where a
        k-key lexsort is minutes (TPU sort compile scales with operand
        count — measured 164s for 3 int64 lanes at 1M vs 31s for
        key+payload);
      * run: per-lane permutation gathers collapse into grouped_take
        stacks (~one gather pass per dtype class instead of per lane;
        TPU gathers pay per-row descriptor latency, ~20ms per pass at
        1M), sums/counts become ONE stacked cumsum + two small gathers
        at segment boundaries instead of scatter passes (~70ms each at
        1M, and scatter outputs land in slow S(1)-space buffers), and
        segment starts come from a single-lane sort instead of a
        segment_min scatter.

    int64 cumsum-diff is exact for any group sum that fits int64
    (two's-complement wraparound cancels in the subtraction), matching
    segment_sum semantics.  MIN/MAX, ignore-null FIRST/LAST, ANY/EVERY
    and f64 sums run through the same scatter-free sorted-run layer
    (sorted_agg_outputs): segmented scans gathered at run ends, so the
    whole program emits ZERO scatters when `scatter_free` holds."""
    spans = [s[1] for s in pack_spec]
    los = [s[0] for s in pack_spec]
    strides = []
    tot = 1
    for s in reversed(spans):
        strides.append(tot)
        tot *= s
    strides.reverse()
    total = tot
    key_dt = jnp.int32 if total < (1 << 31) - 1 else jnp.int64

    def run(keys, keys_valid, agg_data, agg_valid, live):
        packed = _packed_key_lane(keys, keys_valid, pack_spec)
        skey = jnp.where(live, packed, jnp.int64(total)).astype(key_dt)
        iota = jnp.arange(capacity, dtype=jnp.int32)
        skey_s, perm = jax.lax.sort((skey, iota), num_keys=1,
                                    is_stable=True)
        s_live = skey_s < jnp.asarray(total, key_dt)
        count = jnp.sum(live, dtype=jnp.int32)
        boundary = jnp.concatenate(
            [jnp.ones((1,), bool), skey_s[1:] != skey_s[:-1]]) & s_live
        num_groups = jnp.sum(boundary, dtype=jnp.int32)

        # group start positions, compacted to the front by a SINGLE-lane
        # sort (scatter-free segment_min)
        starts = jnp.sort(jnp.where(boundary, iota, jnp.int32(capacity)))
        starts = starts[:num_segments]
        group_live = jnp.arange(num_segments, dtype=jnp.int32) < num_groups
        starts_c = jnp.clip(starts, 0, capacity - 1)
        nexts = jnp.concatenate(
            [starts[1:], jnp.full((1,), capacity, jnp.int32)])
        ends_c = jnp.clip(jnp.minimum(nexts - 1, count - 1), 0,
                          capacity - 1)

        # keys decode from the packed value — zero key gathers
        pk = skey_s[starts_c].astype(jnp.int64)
        out_keys = []
        for (dt, _hv, lane_dt), lo, span, stride in zip(
                key_lanes_info, los, spans, strides):
            slot = (pk // jnp.int64(stride)) % jnp.int64(span)
            data = (slot - 1 + jnp.int64(lo)).astype(jnp.dtype(lane_dt))
            out_keys.append((data, (slot > 0) & group_live))

        # permute agg inputs once, stacked by dtype class
        from .filter import grouped_take
        need = sorted({s.input_idx for s in agg_specs if s.input_idx >= 0})
        lanes = []
        for i in need:
            v = agg_valid[i]
            lanes.append(agg_data[i])
            lanes.append(jnp.ones((capacity,), bool) if v is None else v)
        moved = grouped_take(lanes, perm) if lanes else []
        s_in = {}
        for j, i in enumerate(need):
            s_in[i] = (moved[2 * j], moved[2 * j + 1] & s_live)

        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                spec_vls.append(s_in[spec.input_idx])
            else:
                spec_vls.append((None, s_live))

        # ---- every aggregate kind through the shared sorted-run layer
        # (scatter-free segmented scans + boundary gathers by default;
        # the knob flips back to segment scatters for A/B comparison)
        outs = sorted_agg_outputs(agg_specs, spec_vls, s_live, boundary,
                                  starts_c, ends_c, group_live,
                                  num_segments, capacity, scatter_free)
        return out_keys, outs, num_groups

    return run


def groupby_trace(key_lanes_info, agg_specs, num_segments, capacity,
                  pack_spec=None, scatter_free=True,
                  max_sort_operands=2):
    """Build the traced groupby fn for jit.

    key_lanes_info: list of (dtype, has_validity, lane_dtype_str) — static.
    pack_spec: optional per-key (lo, span) or None — keys with exact
    static bounds fold into one packed sort lane (_packed_key_lane).
    scatter_free: route every segment reduction through the sorted-run
    scan layer (sorted_agg_outputs) — no jax.ops.segment_* scatters.
    max_sort_operands: cap on emitted sort width; the unpacked key sort
    chains stable 2-operand sorts instead of one variadic lexsort
    (segments.lexsort_capped — TPU sort compile scales with operands).
    Returns fn(keys_data, keys_valid, agg_data, agg_valid, live) ->
      (perm_keys (data, valid) per key, agg outs (data, valid) per spec,
       num_groups scalar)

    `live` is an arbitrary row mask, NOT a prefix count: a filter feeding an
    aggregation passes its keep-mask directly, so filtered rows die inside
    the (sorted) segment reduce and no gather/compaction ever runs — row
    gathers are the expensive op on TPU, masked VPU work is nearly free.
    """
    packed_idx = {i for i, s in enumerate(pack_spec or []) if s is not None}
    if pack_spec is not None and len(packed_idx) == len(key_lanes_info):
        tot = 1
        for _lo, span in pack_spec:
            tot *= span
        if tot <= (1 << 62):
            return packed_groupby_trace(pack_spec, key_lanes_info,
                                        agg_specs, num_segments, capacity,
                                        scatter_free=scatter_free)

    def key_sort_lanes(keys, keys_valid):
        """[(lanes...)] for sorting/boundaries: packed keys collapse into
        one lane, the rest keep their (validity, data) pairs."""
        lanes = []
        if packed_idx:
            lanes.append(_packed_key_lane(keys, keys_valid, pack_spec))
        for i, ((dt, _hv, _ld), kd, kv) in enumerate(
                zip(key_lanes_info, keys, keys_valid)):
            if i in packed_idx:
                continue
            sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
            lanes.extend([l for l in sub if l is not None])
        return lanes

    def run(keys, keys_valid, agg_data, agg_valid, live):
        from .filter import grouped_take, take_keys_valid
        # --- 1. sort ---
        lanes = key_sort_lanes(keys, keys_valid)
        # lexsort: LAST key is primary -> order [secondary..., primary];
        # emitted as a chain of <=max_sort_operands stable sorts
        sort_keys = list(reversed(lanes)) + [(~live).astype(jnp.int8)]
        perm = lexsort_capped(sort_keys, max_sort_operands)
        # ONE stacked gather pass per dtype class for every permuted lane
        # (keys, key validity, liveness) — TPU gathers pay per row, not
        # per byte, so per-lane takes multiply a ~20ms/1M latency cost
        s_keys, s_keys_valid, (s_live,) = take_keys_valid(
            keys, keys_valid, [live], perm)

        # --- 2. boundaries ---
        boundary = row0_true(capacity)
        for lane in key_sort_lanes(s_keys, s_keys_valid):
            boundary = boundary | _eq_prev(lane)
        # first padding row opens its own (dead) segment
        pad_start = jnp.concatenate([jnp.ones((1,), bool),
                                     s_live[1:] != s_live[:-1]])
        boundary = boundary | pad_start

        seg_ids = blocked_cumsum(boundary.astype(jnp.int32)) - 1
        count = jnp.sum(live, dtype=jnp.int32)
        num_groups = jnp.where(count > 0,
                               seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)

        # --- 3. group keys: first row of each segment ---
        # seg ids rise with position, so the g-th boundary (position
        # order) IS segment g's start: ONE single-lane sort compacts the
        # boundary positions — no segment_min scatter
        big = jnp.int32(capacity)
        iota = jnp.arange(capacity, dtype=jnp.int32)
        start_raw = jnp.sort(jnp.where(boundary, iota, big))[:num_segments]
        end_idx = segment_ends(start_raw, count, capacity)
        start_idx = jnp.clip(start_raw, 0, capacity - 1)
        group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
        okds, okvs, _ = take_keys_valid(s_keys, s_keys_valid, [],
                                        start_idx)
        out_keys = []
        for okd, okv in zip(okds, okvs):
            okv = jnp.ones((capacity,), bool) if okv is None else okv
            out_keys.append((okd, okv & group_live))

        # --- 4. aggregates ---
        need = sorted({s.input_idx for s in agg_specs if s.input_idx >= 0})
        in_lanes = []
        for i in need:
            v = agg_valid[i]
            in_lanes.append(agg_data[i])
            in_lanes.append(jnp.ones((capacity,), bool) if v is None else v)
        moved_in = grouped_take(in_lanes, perm) if in_lanes else []
        s_in = {i: (moved_in[2 * j], moved_in[2 * j + 1] & s_live)
                for j, i in enumerate(need)}
        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                spec_vls.append(s_in[spec.input_idx])
            else:
                spec_vls.append((None, s_live))
        outs = sorted_agg_outputs(agg_specs, spec_vls, s_live, boundary,
                                  start_idx, end_idx, group_live,
                                  num_segments, capacity, scatter_free)
        return out_keys, outs, num_groups

    return run


def reduce_trace(agg_specs, capacity):
    """No-key aggregation (single output row at index 0).

    `live` is an arbitrary row mask (see groupby_trace)."""
    def run(agg_data, agg_valid, live):
        outs = []
        for spec in agg_specs:
            d = agg_data[spec.input_idx] if spec.input_idx >= 0 else None
            v = agg_valid[spec.input_idx] if spec.input_idx >= 0 else None
            v = jnp.ones((capacity,), bool) if v is None else v
            vl = (v & live) if d is not None else live
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                val = jnp.sum(vl, dtype=jnp.int64)
                data, valid = val, jnp.asarray(True)
            else:
                nvalid = jnp.sum(vl, dtype=jnp.int32)
                valid = nvalid > 0
                cd = compute_view(d, dt)
                if spec.kind == SUM:
                    acc = cd.astype(jnp.float64 if t.is_floating(dt)
                                    else jnp.int64)
                    data = jnp.sum(jnp.where(vl, acc, 0))
                elif spec.kind in (MIN, MAX):
                    is_min = spec.kind == MIN
                    if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                        o = _bits_total_order(d)
                        ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                        o = jnp.where(vl, o, ident)
                        red = jnp.min(o) if is_min else jnp.max(o)
                        data = _bits_from_order(red)
                    elif t.is_floating(dt):
                        isnan = jnp.isnan(cd) & vl
                        has_nan = jnp.any(isnan)
                        ident = jnp.float64(np.inf) if is_min \
                            else jnp.float64(-np.inf)
                        clean = jnp.where(vl & ~isnan, cd, ident)
                        red = jnp.min(clean) if is_min else jnp.max(clean)
                        n_clean = jnp.sum(vl & ~isnan)
                        if is_min:
                            data = jnp.where(has_nan & (n_clean == 0),
                                             jnp.float64(np.nan), red)
                        else:
                            data = jnp.where(has_nan, jnp.float64(np.nan), red)
                    else:
                        if isinstance(dt, t.BooleanType):
                            ident = jnp.asarray(is_min)
                        else:
                            info = np.iinfo(np.dtype(cd.dtype))
                            ident = jnp.asarray(info.max if is_min else info.min,
                                                cd.dtype)
                        acc = jnp.where(vl, cd, ident)
                        data = jnp.min(acc) if is_min else jnp.max(acc)
                elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                    idx = jnp.arange(capacity, dtype=jnp.int32)
                    is_first = spec.kind in (FIRST, FIRST_NN)
                    sel = vl if spec.kind in (FIRST_NN, LAST_NN) else live
                    masked = jnp.where(sel, idx, capacity if is_first else -1)
                    pick = jnp.min(masked) if is_first else jnp.max(masked)
                    pick = jnp.clip(pick, 0, capacity - 1)
                    data = compute_view(d, dt)[pick]
                    valid = vl[pick]
                elif spec.kind == ANY:
                    data = jnp.any(jnp.where(vl, cd, False))
                elif spec.kind == EVERY:
                    data = jnp.all(jnp.where(vl, cd, True))
                else:
                    raise ValueError(spec.kind)
            outs.append((data, valid))
        return outs

    return run


def dense_groupby_trace(domain_sizes, agg_specs, capacity):
    """Bounded-domain groupby: NO SORT, NO ROW GATHERS.

    When every group key has a small static domain (dictionary codes,
    booleans), rows map to a dense bucket id (base-mixed radix over the
    key slots, one extra slot per key for null) and every aggregate is a
    single segment reduction into D buckets.  For the classic low-
    cardinality shapes (TPC-H q1's returnflag x linestatus) this replaces
    an O(C log C) multi-lane lexsort + per-column gathers with one
    masked pass — the difference between seconds and milliseconds at
    8M-row capacities.

    domain_sizes: static per-key domain size (codes in [0, size)).
    Returns fn(keys, keys_valid, agg_data, agg_valid, live) with the same
    contract as groupby_trace: occupied buckets compact to the front,
    group keys decode from the bucket id.
    """
    strides = []
    d_total = 1
    for size in reversed(domain_sizes):
        strides.append(d_total)
        d_total *= size + 1                       # +1: the null slot
    strides.reverse()
    D = d_total

    def run(keys, keys_valid, agg_data, agg_valid, live):
        comb = jnp.zeros((capacity,), jnp.int32)
        for size, stride, kd, kv in zip(domain_sizes, strides, keys,
                                        keys_valid):
            slot = jnp.clip(kd.astype(jnp.int32), 0, size - 1)
            if kv is not None:
                slot = jnp.where(kv, slot, jnp.int32(size))
            comb = comb + slot * jnp.int32(stride)
        seg = jnp.where(live, comb, jnp.int32(D))   # dead rows -> bucket D
        ns = D + 1

        occupied = jax.ops.segment_max(live.astype(jnp.int32), seg,
                                       num_segments=ns)[:D] > 0
        num_groups = jnp.sum(occupied, dtype=jnp.int32)
        # compact occupied buckets to the front, stably (bucket order)
        order = jnp.argsort(jnp.where(occupied, jnp.int32(0),
                                      jnp.int32(1)), stable=True)
        group_live = jnp.arange(D, dtype=jnp.int32) < num_groups

        out_keys = []
        for size, stride, kd in zip(domain_sizes, strides, keys):
            slot = (order // jnp.int32(stride)) % jnp.int32(size + 1)
            okd = slot.astype(kd.dtype)
            okv = (slot < size) & group_live
            out_keys.append((okd, okv))

        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                d = agg_data[spec.input_idx]
                v = agg_valid[spec.input_idx]
                v = jnp.ones((capacity,), bool) if v is None else v
            else:
                d, v = None, live
            vl = (v & live) if d is not None else live
            spec_vls.append((d, vl))
        sum_of = _batched_sums(agg_specs, spec_vls, live, seg, ns,
                               lambda a: a[:D][order])

        outs = []
        for si, spec in enumerate(agg_specs):
            d, vl = spec_vls[si]
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                outs.append((sum_of(("cnt", si), False), group_live))
                continue
            valid_count = sum_of(("vc", spec.input_idx), False)
            out_valid = (valid_count > 0) & group_live
            cd = compute_view(d, dt)
            if spec.kind == SUM:
                data = sum_of(("sum", si), t.is_floating(dt))
            elif spec.kind in (MIN, MAX):
                is_min = spec.kind == MIN
                if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                    o = _bits_total_order(d)
                    ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                    o = jnp.where(vl, o, ident)
                    red = (jax.ops.segment_min if is_min
                           else jax.ops.segment_max)(
                        o, seg, num_segments=ns)[:D][order]
                    data = _bits_from_order(red)
                elif t.is_floating(dt):
                    data = _segment_minmax_float(cd, vl, seg, ns,
                                                 is_min)[:D][order]
                else:
                    if isinstance(dt, t.BooleanType):
                        ident = jnp.asarray(is_min)
                        acc = cd
                    else:
                        info = np.iinfo(np.dtype(cd.dtype))
                        ident = jnp.asarray(info.max if is_min
                                            else info.min, cd.dtype)
                        acc = cd
                    acc = jnp.where(vl, acc, ident)
                    data = (jax.ops.segment_min if is_min
                            else jax.ops.segment_max)(
                        acc, seg, num_segments=ns)[:D][order]
            elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                idx = jnp.arange(capacity, dtype=jnp.int32)
                is_first = spec.kind in (FIRST, FIRST_NN)
                sel = vl if spec.kind in (FIRST_NN, LAST_NN) else live
                masked = jnp.where(sel, idx,
                                   jnp.int32(capacity) if is_first
                                   else jnp.int32(-1))
                pick = (jax.ops.segment_min if is_first
                        else jax.ops.segment_max)(
                    masked, seg, num_segments=ns)[:D][order]
                pick = jnp.clip(pick, 0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind == ANY:
                data = jax.ops.segment_max(
                    jnp.where(vl, cd, False).astype(jnp.int8), seg,
                    num_segments=ns)[:D][order] > 0
            elif spec.kind == EVERY:
                data = jax.ops.segment_min(
                    jnp.where(vl, cd, True).astype(jnp.int8), seg,
                    num_segments=ns)[:D][order] > 0
            else:
                raise ValueError(f"unknown agg kind {spec.kind}")
            outs.append((data, out_valid))
        return out_keys, outs, num_groups

    return run
