"""Sort-based segmented group-by: the cuDF `Table.groupBy().aggregate()` role.

cuDF uses a device hash table; on TPU the idiomatic shape is sort + segment
reduction (static shapes, no scatter contention, MXU/VPU-friendly):

  1. lexsort rows by (liveness, key lanes with validity)  — padding rows and
     null keys each group cleanly (Spark groups nulls as equal)
  2. boundary flags where any key lane differs from the previous row
  3. segment_ids = cumsum(flags); group count = one scalar D2H
  4. jax.ops.segment_{sum,min,max} per aggregate with null/live masking
  5. group keys gathered from each segment's first row

Everything is one jit per (shape-bucket, agg signature); outputs stay padded
to capacity so downstream operators reuse the same bucket.

Min/max float ordering follows Java's Double.compare (NaN greatest,
-0.0 < 0.0) by running the comparison in bit-space when the column carries
the int64-bits storage lane, else a NaN-tracked value-space fallback.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .kernels import compute_view


# Aggregate kernel op kinds understood by the kernel.
# (update vs merge distinction lives in plan/aggregates.py; by kernel time
# everything is one of these.)
SUM = "sum"
COUNT = "count"          # counts valid rows
COUNT_ALL = "count_all"  # counts live rows (count(*) / count(1))
MIN = "min"
MAX = "max"
FIRST = "first"          # first live row's value (Spark ignoreNulls=false)
LAST = "last"
FIRST_NN = "first_nn"    # first non-null (ignoreNulls=true)
LAST_NN = "last_nn"
ANY = "any"              # boolean or
EVERY = "every"          # boolean and


@dataclasses.dataclass(frozen=True)
class AggSpec:
    kind: str
    input_idx: int                 # index into the agg-input column list
    dtype: object                  # logical result type (t.DataType)


def _null_first_key_lanes(data, valid, dt):
    """Lanes making (valid, data) lexsort-comparable; nulls group together."""
    if valid is None:
        valid_lane = None
    else:
        valid_lane = (~valid).astype(jnp.int8)   # nulls first among live rows
        # canonicalize null rows' payload so they compare equal regardless of
        # what the producing kernel left in the data lane
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
    if dt is not None and isinstance(dt, t.DoubleType) and data.dtype == jnp.float64:
        # computed f64: order by value; NaN needs a consistent slot — push to
        # the top via isnan lane handled by caller. Grouping only needs
        # consistency, and NaN != NaN would split groups: map NaN to a
        # canonical key by replacing with +inf and adding an isnan lane.
        isnan = jnp.isnan(data)
        canon = jnp.where(isnan, jnp.float64(np.inf), data)
        canon = jnp.where(canon == 0.0, jnp.float64(0.0), canon)  # -0.0==0.0
        return [valid_lane, isnan.astype(jnp.int8), canon]
    return [valid_lane, data]


def _eq_prev(lane):
    """Boundary lane: True where row differs from previous sorted row."""
    return jnp.concatenate([jnp.ones((1,), bool), lane[1:] != lane[:-1]])


def _segment_minmax_float(vals, valid_live, seg_ids, num_segments, is_min):
    """Java-ordering min/max for float values (NaN greatest).

    Value-space with NaN tracking; the exact bit-space path for int64-bits
    DOUBLE lanes lives inline in groupby_trace via _bits_total_order."""
    isnan = jnp.isnan(vals) & valid_live
    has_nan = jax.ops.segment_max(isnan.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments) > 0
    all_nan_ident = jnp.float64(np.inf) if is_min else jnp.float64(-np.inf)
    clean = jnp.where(valid_live & ~isnan, vals, all_nan_ident)
    red = (jax.ops.segment_min if is_min else jax.ops.segment_max)(
        clean, seg_ids, num_segments=num_segments)
    non_nan_count = jax.ops.segment_sum(
        (valid_live & ~isnan).astype(jnp.int32), seg_ids,
        num_segments=num_segments)
    if is_min:
        # min is NaN only when every valid value is NaN
        return jnp.where(has_nan & (non_nan_count == 0), jnp.float64(np.nan),
                         red)
    return jnp.where(has_nan, jnp.float64(np.nan), red)


_EXP_MASK = np.int64(0x7FF0000000000000)
_MANT_MASK = np.int64(0x000FFFFFFFFFFFFF)
_CANON_NAN = np.int64(0x7FF8000000000000)


def _bits_total_order(b):
    """Monotone int64 mapping of f64 bit patterns (Java Double.compare).

    -inf < ... < -0.0 < 0.0 < ... < +inf < NaN.  NaN bits are canonicalized
    first so the int64 extremes stay free for masking identities."""
    is_nan = ((b & _EXP_MASK) == _EXP_MASK) & ((b & _MANT_MASK) != 0)
    b = jnp.where(is_nan, jnp.int64(_CANON_NAN), b)
    # int64 wraparound makes -2^63-1-b correct mod 2^64 for all negative b
    return jnp.where(b >= 0, b, jnp.int64(-2**63) - jnp.int64(1) - b)


def _bits_from_order(o):
    return jnp.where(o >= 0, o, jnp.int64(-2**63) - jnp.int64(1) - o)


_ORDER_MAX = np.int64(2**63 - 1)   # unreachable after NaN canonicalization
_ORDER_MIN = np.int64(-2**63)




def _batched_sums(agg_specs, spec_vls, live_all, seg, num_segments,
                  reindex):
    """ONE wide (N, K) segment_sum for every sum-like lane (SUM buffers,
    COUNT/COUNT_ALL, per-input valid counts) — TPU scatters pay a fixed
    serialization cost per pass, so K-wide rows amortize it (measured
    4.5x for 10 aggregates at 8M rows).  Shared by groupby_trace and
    dense_groupby_trace so the lane/dtype rules cannot drift.

    spec_vls: per-spec (data, valid&live) with any permutation already
    applied; live_all: the COUNT(*) lane; reindex: maps the (S, K)
    segment output onto the caller's group order.
    Returns sum_of(key, is_float) -> (G,) lane."""
    int_lanes, int_slots = [], {}
    f64_lanes, f64_slots = [], {}

    def queue(key, lane, is_float):
        lanes_, slots = (f64_lanes, f64_slots) if is_float \
            else (int_lanes, int_slots)
        if key not in slots:
            slots[key] = len(lanes_)
            lanes_.append(lane)

    for si, spec in enumerate(agg_specs):
        d, vl = spec_vls[si]
        dt = spec.dtype
        if spec.kind == COUNT_ALL:
            queue(("cnt", si), live_all.astype(jnp.int64), False)
        elif spec.kind == COUNT:
            queue(("cnt", si), vl.astype(jnp.int64), False)
        elif spec.kind == SUM:
            cd = compute_view(d, dt)
            if t.is_floating(dt):
                queue(("sum", si),
                      jnp.where(vl, cd.astype(jnp.float64), 0.0), True)
            else:
                queue(("sum", si),
                      jnp.where(vl, cd.astype(jnp.int64), 0), False)
        if spec.kind not in (COUNT, COUNT_ALL):
            queue(("vc", spec.input_idx), vl.astype(jnp.int64), False)

    int_out = f64_out = None
    if int_lanes:
        int_out = reindex(jax.ops.segment_sum(
            jnp.stack(int_lanes, axis=1), seg, num_segments=num_segments))
    if f64_lanes:
        f64_out = reindex(jax.ops.segment_sum(
            jnp.stack(f64_lanes, axis=1), seg, num_segments=num_segments))

    def sum_of(key, is_float):
        return (f64_out[:, f64_slots[key]] if is_float
                else int_out[:, int_slots[key]])
    return sum_of


def _packed_key_lane(keys, keys_valid, pack_spec):
    """Fold the statically-bounded keys into ONE int64 lane (slot 0 per
    key = null; values offset by -lo+1).  TPU sort compile time AND run
    time scale with operand count (~15-30s compile per extra 8M operand
    on v5e), so a k-key group-by sorting one packed lane instead of 2k
    (validity+data per key) lanes is the difference between a 1-minute
    and a 20-minute query compile."""
    packed = None
    for i, spec in enumerate(pack_spec):
        if spec is None:
            continue
        lo, span = spec
        kd = keys[i].astype(jnp.int64)
        kv = keys_valid[i]
        slot = jnp.clip(kd - jnp.int64(lo) + 1, 0, span - 1)
        if kv is not None:
            slot = jnp.where(kv, slot, jnp.int64(0))
        packed = slot if packed is None \
            else packed * jnp.int64(span) + slot
    return packed


def groupby_trace(key_lanes_info, agg_specs, num_segments, capacity,
                  pack_spec=None):
    """Build the traced groupby fn for jit.

    key_lanes_info: list of (dtype, has_validity, lane_dtype_str) — static.
    pack_spec: optional per-key (lo, span) or None — keys with exact
    static bounds fold into one packed sort lane (_packed_key_lane).
    Returns fn(keys_data, keys_valid, agg_data, agg_valid, live) ->
      (perm_keys (data, valid) per key, agg outs (data, valid) per spec,
       num_groups scalar)

    `live` is an arbitrary row mask, NOT a prefix count: a filter feeding an
    aggregation passes its keep-mask directly, so filtered rows die inside
    the (sorted) segment reduce and no gather/compaction ever runs — row
    gathers are the expensive op on TPU, masked VPU work is nearly free.
    """
    packed_idx = {i for i, s in enumerate(pack_spec or []) if s is not None}

    def key_sort_lanes(keys, keys_valid):
        """[(lanes...)] for sorting/boundaries: packed keys collapse into
        one lane, the rest keep their (validity, data) pairs."""
        lanes = []
        if packed_idx:
            lanes.append(_packed_key_lane(keys, keys_valid, pack_spec))
        for i, ((dt, _hv, _ld), kd, kv) in enumerate(
                zip(key_lanes_info, keys, keys_valid)):
            if i in packed_idx:
                continue
            sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
            lanes.extend([l for l in sub if l is not None])
        return lanes

    def run(keys, keys_valid, agg_data, agg_valid, live):
        # --- 1. sort ---
        lanes = key_sort_lanes(keys, keys_valid)
        # lexsort: LAST key is primary -> order [secondary..., primary]
        sort_keys = list(reversed(lanes)) + [(~live).astype(jnp.int8)]
        perm = jnp.lexsort(sort_keys)
        s_live = live[perm]
        s_keys = [k[perm] for k in keys]
        s_keys_valid = [None if v is None else v[perm] for v in keys_valid]

        # --- 2. boundaries ---
        boundary = jnp.zeros((capacity,), bool)
        boundary = boundary.at[0].set(True)
        for lane in key_sort_lanes(s_keys, s_keys_valid):
            boundary = boundary | _eq_prev(lane)
        # first padding row opens its own (dead) segment
        pad_start = jnp.concatenate([jnp.ones((1,), bool),
                                     s_live[1:] != s_live[:-1]])
        boundary = boundary | pad_start

        seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        count = jnp.sum(live, dtype=jnp.int32)
        num_groups = jnp.where(count > 0,
                               seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)

        # --- 3. group keys: first row of each segment ---
        big = jnp.int32(capacity)
        start_idx = jax.ops.segment_min(
            jnp.arange(capacity, dtype=jnp.int32), seg_ids,
            num_segments=num_segments)
        start_idx = jnp.clip(start_idx, 0, capacity - 1)
        out_keys = []
        for kd, kv in zip(s_keys, s_keys_valid):
            okd = kd[start_idx]
            okv = (jnp.ones((capacity,), bool) if kv is None else kv[start_idx])
            group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
            out_keys.append((okd, okv & group_live))

        # --- 4. aggregates ---
        group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                d = agg_data[spec.input_idx][perm]
                v = agg_valid[spec.input_idx]
                v = (jnp.ones((capacity,), bool) if v is None else v)[perm]
            else:
                d, v = None, s_live
            vl = (v & s_live) if d is not None else s_live
            spec_vls.append((d, vl))
        sum_of = _batched_sums(agg_specs, spec_vls, s_live, seg_ids,
                               num_segments, lambda a: a)

        outs = []
        for si, spec in enumerate(agg_specs):
            d, vl = spec_vls[si]
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                outs.append((sum_of(("cnt", si), False), group_live))
                continue
            valid_count = sum_of(("vc", spec.input_idx), False)
            out_valid = (valid_count > 0) & group_live
            cd = compute_view(d, dt)
            if spec.kind == SUM:
                data = sum_of(("sum", si), t.is_floating(dt))
            elif spec.kind in (MIN, MAX):
                is_min = spec.kind == MIN
                if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                    o = _bits_total_order(d)
                    ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                    o = jnp.where(vl, o, ident)
                    red = (jax.ops.segment_min if is_min
                           else jax.ops.segment_max)(
                        o, seg_ids, num_segments=num_segments)
                    data = _bits_from_order(red)
                elif t.is_floating(dt):
                    data = _segment_minmax_float(cd, vl, seg_ids,
                                                 num_segments, is_min)
                else:
                    info = np.iinfo(np.dtype(cd.dtype)) if not \
                        isinstance(dt, t.BooleanType) else None
                    if isinstance(dt, t.BooleanType):
                        ident = jnp.asarray(True if is_min else False)
                        acc = cd
                    else:
                        ident = jnp.asarray(info.max if is_min else info.min,
                                            cd.dtype)
                        acc = cd
                    acc = jnp.where(vl, acc, ident)
                    data = (jax.ops.segment_min if is_min
                            else jax.ops.segment_max)(
                        acc, seg_ids, num_segments=num_segments)
            elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                idx = jnp.arange(capacity, dtype=jnp.int32)
                is_first = spec.kind in (FIRST, FIRST_NN)
                sel = vl if spec.kind in (FIRST_NN, LAST_NN) else s_live
                masked = jnp.where(sel, idx, big if is_first else -1)
                pick = (jax.ops.segment_min if is_first
                        else jax.ops.segment_max)(
                    masked, seg_ids, num_segments=num_segments)
                pick = jnp.clip(pick, 0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind == ANY:
                data = jax.ops.segment_max(
                    jnp.where(vl, cd, False).astype(jnp.int8), seg_ids,
                    num_segments=num_segments) > 0
            elif spec.kind == EVERY:
                data = jax.ops.segment_min(
                    jnp.where(vl, cd, True).astype(jnp.int8), seg_ids,
                    num_segments=num_segments) > 0
            else:
                raise ValueError(f"unknown agg kind {spec.kind}")
            outs.append((data, out_valid))
        return out_keys, outs, num_groups

    return run


def reduce_trace(agg_specs, capacity):
    """No-key aggregation (single output row at index 0).

    `live` is an arbitrary row mask (see groupby_trace)."""
    def run(agg_data, agg_valid, live):
        outs = []
        for spec in agg_specs:
            d = agg_data[spec.input_idx] if spec.input_idx >= 0 else None
            v = agg_valid[spec.input_idx] if spec.input_idx >= 0 else None
            v = jnp.ones((capacity,), bool) if v is None else v
            vl = (v & live) if d is not None else live
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                val = jnp.sum(vl, dtype=jnp.int64)
                data, valid = val, jnp.asarray(True)
            else:
                nvalid = jnp.sum(vl, dtype=jnp.int32)
                valid = nvalid > 0
                cd = compute_view(d, dt)
                if spec.kind == SUM:
                    acc = cd.astype(jnp.float64 if t.is_floating(dt)
                                    else jnp.int64)
                    data = jnp.sum(jnp.where(vl, acc, 0))
                elif spec.kind in (MIN, MAX):
                    is_min = spec.kind == MIN
                    if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                        o = _bits_total_order(d)
                        ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                        o = jnp.where(vl, o, ident)
                        red = jnp.min(o) if is_min else jnp.max(o)
                        data = _bits_from_order(red)
                    elif t.is_floating(dt):
                        isnan = jnp.isnan(cd) & vl
                        has_nan = jnp.any(isnan)
                        ident = jnp.float64(np.inf) if is_min \
                            else jnp.float64(-np.inf)
                        clean = jnp.where(vl & ~isnan, cd, ident)
                        red = jnp.min(clean) if is_min else jnp.max(clean)
                        n_clean = jnp.sum(vl & ~isnan)
                        if is_min:
                            data = jnp.where(has_nan & (n_clean == 0),
                                             jnp.float64(np.nan), red)
                        else:
                            data = jnp.where(has_nan, jnp.float64(np.nan), red)
                    else:
                        if isinstance(dt, t.BooleanType):
                            ident = jnp.asarray(is_min)
                        else:
                            info = np.iinfo(np.dtype(cd.dtype))
                            ident = jnp.asarray(info.max if is_min else info.min,
                                                cd.dtype)
                        acc = jnp.where(vl, cd, ident)
                        data = jnp.min(acc) if is_min else jnp.max(acc)
                elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                    idx = jnp.arange(capacity, dtype=jnp.int32)
                    is_first = spec.kind in (FIRST, FIRST_NN)
                    sel = vl if spec.kind in (FIRST_NN, LAST_NN) else live
                    masked = jnp.where(sel, idx, capacity if is_first else -1)
                    pick = jnp.min(masked) if is_first else jnp.max(masked)
                    pick = jnp.clip(pick, 0, capacity - 1)
                    data = compute_view(d, dt)[pick]
                    valid = vl[pick]
                elif spec.kind == ANY:
                    data = jnp.any(jnp.where(vl, cd, False))
                elif spec.kind == EVERY:
                    data = jnp.all(jnp.where(vl, cd, True))
                else:
                    raise ValueError(spec.kind)
            outs.append((data, valid))
        return outs

    return run


def dense_groupby_trace(domain_sizes, agg_specs, capacity):
    """Bounded-domain groupby: NO SORT, NO ROW GATHERS.

    When every group key has a small static domain (dictionary codes,
    booleans), rows map to a dense bucket id (base-mixed radix over the
    key slots, one extra slot per key for null) and every aggregate is a
    single segment reduction into D buckets.  For the classic low-
    cardinality shapes (TPC-H q1's returnflag x linestatus) this replaces
    an O(C log C) multi-lane lexsort + per-column gathers with one
    masked pass — the difference between seconds and milliseconds at
    8M-row capacities.

    domain_sizes: static per-key domain size (codes in [0, size)).
    Returns fn(keys, keys_valid, agg_data, agg_valid, live) with the same
    contract as groupby_trace: occupied buckets compact to the front,
    group keys decode from the bucket id.
    """
    strides = []
    d_total = 1
    for size in reversed(domain_sizes):
        strides.append(d_total)
        d_total *= size + 1                       # +1: the null slot
    strides.reverse()
    D = d_total

    def run(keys, keys_valid, agg_data, agg_valid, live):
        comb = jnp.zeros((capacity,), jnp.int32)
        for size, stride, kd, kv in zip(domain_sizes, strides, keys,
                                        keys_valid):
            slot = jnp.clip(kd.astype(jnp.int32), 0, size - 1)
            if kv is not None:
                slot = jnp.where(kv, slot, jnp.int32(size))
            comb = comb + slot * jnp.int32(stride)
        seg = jnp.where(live, comb, jnp.int32(D))   # dead rows -> bucket D
        ns = D + 1

        occupied = jax.ops.segment_max(live.astype(jnp.int32), seg,
                                       num_segments=ns)[:D] > 0
        num_groups = jnp.sum(occupied, dtype=jnp.int32)
        # compact occupied buckets to the front, stably (bucket order)
        order = jnp.argsort(jnp.where(occupied, jnp.int32(0),
                                      jnp.int32(1)), stable=True)
        group_live = jnp.arange(D, dtype=jnp.int32) < num_groups

        out_keys = []
        for size, stride, kd in zip(domain_sizes, strides, keys):
            slot = (order // jnp.int32(stride)) % jnp.int32(size + 1)
            okd = slot.astype(kd.dtype)
            okv = (slot < size) & group_live
            out_keys.append((okd, okv))

        spec_vls = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                d = agg_data[spec.input_idx]
                v = agg_valid[spec.input_idx]
                v = jnp.ones((capacity,), bool) if v is None else v
            else:
                d, v = None, live
            vl = (v & live) if d is not None else live
            spec_vls.append((d, vl))
        sum_of = _batched_sums(agg_specs, spec_vls, live, seg, ns,
                               lambda a: a[:D][order])

        outs = []
        for si, spec in enumerate(agg_specs):
            d, vl = spec_vls[si]
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                outs.append((sum_of(("cnt", si), False), group_live))
                continue
            valid_count = sum_of(("vc", spec.input_idx), False)
            out_valid = (valid_count > 0) & group_live
            cd = compute_view(d, dt)
            if spec.kind == SUM:
                data = sum_of(("sum", si), t.is_floating(dt))
            elif spec.kind in (MIN, MAX):
                is_min = spec.kind == MIN
                if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                    o = _bits_total_order(d)
                    ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                    o = jnp.where(vl, o, ident)
                    red = (jax.ops.segment_min if is_min
                           else jax.ops.segment_max)(
                        o, seg, num_segments=ns)[:D][order]
                    data = _bits_from_order(red)
                elif t.is_floating(dt):
                    data = _segment_minmax_float(cd, vl, seg, ns,
                                                 is_min)[:D][order]
                else:
                    if isinstance(dt, t.BooleanType):
                        ident = jnp.asarray(is_min)
                        acc = cd
                    else:
                        info = np.iinfo(np.dtype(cd.dtype))
                        ident = jnp.asarray(info.max if is_min
                                            else info.min, cd.dtype)
                        acc = cd
                    acc = jnp.where(vl, acc, ident)
                    data = (jax.ops.segment_min if is_min
                            else jax.ops.segment_max)(
                        acc, seg, num_segments=ns)[:D][order]
            elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                idx = jnp.arange(capacity, dtype=jnp.int32)
                is_first = spec.kind in (FIRST, FIRST_NN)
                sel = vl if spec.kind in (FIRST_NN, LAST_NN) else live
                masked = jnp.where(sel, idx,
                                   jnp.int32(capacity) if is_first
                                   else jnp.int32(-1))
                pick = (jax.ops.segment_min if is_first
                        else jax.ops.segment_max)(
                    masked, seg, num_segments=ns)[:D][order]
                pick = jnp.clip(pick, 0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind == ANY:
                data = jax.ops.segment_max(
                    jnp.where(vl, cd, False).astype(jnp.int8), seg,
                    num_segments=ns)[:D][order] > 0
            elif spec.kind == EVERY:
                data = jax.ops.segment_min(
                    jnp.where(vl, cd, True).astype(jnp.int8), seg,
                    num_segments=ns)[:D][order] > 0
            else:
                raise ValueError(f"unknown agg kind {spec.kind}")
            outs.append((data, out_valid))
        return out_keys, outs, num_groups

    return run
