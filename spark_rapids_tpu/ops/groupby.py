"""Sort-based segmented group-by: the cuDF `Table.groupBy().aggregate()` role.

cuDF uses a device hash table; on TPU the idiomatic shape is sort + segment
reduction (static shapes, no scatter contention, MXU/VPU-friendly):

  1. lexsort rows by (liveness, key lanes with validity)  — padding rows and
     null keys each group cleanly (Spark groups nulls as equal)
  2. boundary flags where any key lane differs from the previous row
  3. segment_ids = cumsum(flags); group count = one scalar D2H
  4. jax.ops.segment_{sum,min,max} per aggregate with null/live masking
  5. group keys gathered from each segment's first row

Everything is one jit per (shape-bucket, agg signature); outputs stay padded
to capacity so downstream operators reuse the same bucket.

Min/max float ordering follows Java's Double.compare (NaN greatest,
-0.0 < 0.0) by running the comparison in bit-space when the column carries
the int64-bits storage lane, else a NaN-tracked value-space fallback.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .kernels import compute_view


# Aggregate kernel op kinds understood by the kernel.
# (update vs merge distinction lives in plan/aggregates.py; by kernel time
# everything is one of these.)
SUM = "sum"
COUNT = "count"          # counts valid rows
COUNT_ALL = "count_all"  # counts live rows (count(*) / count(1))
MIN = "min"
MAX = "max"
FIRST = "first"          # first live row's value (Spark ignoreNulls=false)
LAST = "last"
FIRST_NN = "first_nn"    # first non-null (ignoreNulls=true)
LAST_NN = "last_nn"
ANY = "any"              # boolean or
EVERY = "every"          # boolean and


@dataclasses.dataclass(frozen=True)
class AggSpec:
    kind: str
    input_idx: int                 # index into the agg-input column list
    dtype: object                  # logical result type (t.DataType)


def _null_first_key_lanes(data, valid, dt):
    """Lanes making (valid, data) lexsort-comparable; nulls group together."""
    if valid is None:
        valid_lane = None
    else:
        valid_lane = (~valid).astype(jnp.int8)   # nulls first among live rows
        # canonicalize null rows' payload so they compare equal regardless of
        # what the producing kernel left in the data lane
        data = jnp.where(valid, data, jnp.zeros((), data.dtype))
    if dt is not None and isinstance(dt, t.DoubleType) and data.dtype == jnp.float64:
        # computed f64: order by value; NaN needs a consistent slot — push to
        # the top via isnan lane handled by caller. Grouping only needs
        # consistency, and NaN != NaN would split groups: map NaN to a
        # canonical key by replacing with +inf and adding an isnan lane.
        isnan = jnp.isnan(data)
        canon = jnp.where(isnan, jnp.float64(np.inf), data)
        canon = jnp.where(canon == 0.0, jnp.float64(0.0), canon)  # -0.0==0.0
        return [valid_lane, isnan.astype(jnp.int8), canon]
    return [valid_lane, data]


def _eq_prev(lane):
    """Boundary lane: True where row differs from previous sorted row."""
    return jnp.concatenate([jnp.ones((1,), bool), lane[1:] != lane[:-1]])


def _segment_minmax_float(vals, valid_live, seg_ids, num_segments, is_min):
    """Java-ordering min/max for float values (NaN greatest).

    Value-space with NaN tracking; the exact bit-space path for int64-bits
    DOUBLE lanes lives inline in groupby_trace via _bits_total_order."""
    isnan = jnp.isnan(vals) & valid_live
    has_nan = jax.ops.segment_max(isnan.astype(jnp.int32), seg_ids,
                                  num_segments=num_segments) > 0
    all_nan_ident = jnp.float64(np.inf) if is_min else jnp.float64(-np.inf)
    clean = jnp.where(valid_live & ~isnan, vals, all_nan_ident)
    red = (jax.ops.segment_min if is_min else jax.ops.segment_max)(
        clean, seg_ids, num_segments=num_segments)
    non_nan_count = jax.ops.segment_sum(
        (valid_live & ~isnan).astype(jnp.int32), seg_ids,
        num_segments=num_segments)
    if is_min:
        # min is NaN only when every valid value is NaN
        return jnp.where(has_nan & (non_nan_count == 0), jnp.float64(np.nan),
                         red)
    return jnp.where(has_nan, jnp.float64(np.nan), red)


_EXP_MASK = np.int64(0x7FF0000000000000)
_MANT_MASK = np.int64(0x000FFFFFFFFFFFFF)
_CANON_NAN = np.int64(0x7FF8000000000000)


def _bits_total_order(b):
    """Monotone int64 mapping of f64 bit patterns (Java Double.compare).

    -inf < ... < -0.0 < 0.0 < ... < +inf < NaN.  NaN bits are canonicalized
    first so the int64 extremes stay free for masking identities."""
    is_nan = ((b & _EXP_MASK) == _EXP_MASK) & ((b & _MANT_MASK) != 0)
    b = jnp.where(is_nan, jnp.int64(_CANON_NAN), b)
    # int64 wraparound makes -2^63-1-b correct mod 2^64 for all negative b
    return jnp.where(b >= 0, b, jnp.int64(-2**63) - jnp.int64(1) - b)


def _bits_from_order(o):
    return jnp.where(o >= 0, o, jnp.int64(-2**63) - jnp.int64(1) - o)


_ORDER_MAX = np.int64(2**63 - 1)   # unreachable after NaN canonicalization
_ORDER_MIN = np.int64(-2**63)


def groupby_trace(key_lanes_info, agg_specs, num_segments, capacity):
    """Build the traced groupby fn for jit.

    key_lanes_info: list of (dtype, has_validity, lane_dtype_str) — static.
    Returns fn(keys_data, keys_valid, agg_data, agg_valid, live) ->
      (perm_keys (data, valid) per key, agg outs (data, valid) per spec,
       num_groups scalar)

    `live` is an arbitrary row mask, NOT a prefix count: a filter feeding an
    aggregation passes its keep-mask directly, so filtered rows die inside
    the (sorted) segment reduce and no gather/compaction ever runs — row
    gathers are the expensive op on TPU, masked VPU work is nearly free.
    """
    def run(keys, keys_valid, agg_data, agg_valid, live):
        # --- 1. sort ---
        lanes = []
        for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, keys, keys_valid):
            sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
            lanes.extend([l for l in sub if l is not None])
        # lexsort: LAST key is primary -> order [secondary..., primary]
        sort_keys = list(reversed(lanes)) + [(~live).astype(jnp.int8)]
        perm = jnp.lexsort(sort_keys)
        s_live = live[perm]
        s_keys = [k[perm] for k in keys]
        s_keys_valid = [None if v is None else v[perm] for v in keys_valid]

        # --- 2. boundaries ---
        boundary = jnp.zeros((capacity,), bool)
        boundary = boundary.at[0].set(True)
        for (dt, _hv, _ld), kd, kv in zip(key_lanes_info, s_keys, s_keys_valid):
            sub = _null_first_key_lanes(compute_view(kd, dt), kv, dt)
            for lane in sub:
                if lane is not None:
                    boundary = boundary | _eq_prev(lane)
        # first padding row opens its own (dead) segment
        pad_start = jnp.concatenate([jnp.ones((1,), bool),
                                     s_live[1:] != s_live[:-1]])
        boundary = boundary | pad_start

        seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        count = jnp.sum(live, dtype=jnp.int32)
        num_groups = jnp.where(count > 0,
                               seg_ids[jnp.maximum(count - 1, 0)] + 1, 0)

        # --- 3. group keys: first row of each segment ---
        big = jnp.int32(capacity)
        start_idx = jax.ops.segment_min(
            jnp.arange(capacity, dtype=jnp.int32), seg_ids,
            num_segments=num_segments)
        start_idx = jnp.clip(start_idx, 0, capacity - 1)
        out_keys = []
        for kd, kv in zip(s_keys, s_keys_valid):
            okd = kd[start_idx]
            okv = (jnp.ones((capacity,), bool) if kv is None else kv[start_idx])
            group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
            out_keys.append((okd, okv & group_live))

        # --- 4. aggregates ---
        group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
        outs = []
        for spec in agg_specs:
            if spec.input_idx >= 0:
                d = agg_data[spec.input_idx][perm]
                v = agg_valid[spec.input_idx]
                v = (jnp.ones((capacity,), bool) if v is None else v)[perm]
            else:
                d, v = None, s_live
            vl = (v & s_live) if d is not None else s_live
            dt = spec.dtype
            if spec.kind == COUNT_ALL:
                data = jax.ops.segment_sum(s_live.astype(jnp.int64), seg_ids,
                                           num_segments=num_segments)
                outs.append((data, group_live))
                continue
            if spec.kind == COUNT:
                data = jax.ops.segment_sum(vl.astype(jnp.int64), seg_ids,
                                           num_segments=num_segments)
                outs.append((data, group_live))
                continue
            valid_count = jax.ops.segment_sum(vl.astype(jnp.int32), seg_ids,
                                              num_segments=num_segments)
            out_valid = (valid_count > 0) & group_live
            cd = compute_view(d, dt)
            if spec.kind == SUM:
                acc = cd.astype(jnp.float64 if t.is_floating(dt) else jnp.int64)
                data = jax.ops.segment_sum(jnp.where(vl, acc, 0), seg_ids,
                                           num_segments=num_segments)
            elif spec.kind in (MIN, MAX):
                is_min = spec.kind == MIN
                if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                    o = _bits_total_order(d)
                    ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                    o = jnp.where(vl, o, ident)
                    red = (jax.ops.segment_min if is_min
                           else jax.ops.segment_max)(
                        o, seg_ids, num_segments=num_segments)
                    data = _bits_from_order(red)
                elif t.is_floating(dt):
                    data = _segment_minmax_float(cd, vl, seg_ids,
                                                 num_segments, is_min)
                else:
                    info = np.iinfo(np.dtype(cd.dtype)) if not \
                        isinstance(dt, t.BooleanType) else None
                    if isinstance(dt, t.BooleanType):
                        ident = jnp.asarray(True if is_min else False)
                        acc = cd
                    else:
                        ident = jnp.asarray(info.max if is_min else info.min,
                                            cd.dtype)
                        acc = cd
                    acc = jnp.where(vl, acc, ident)
                    data = (jax.ops.segment_min if is_min
                            else jax.ops.segment_max)(
                        acc, seg_ids, num_segments=num_segments)
            elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                idx = jnp.arange(capacity, dtype=jnp.int32)
                is_first = spec.kind in (FIRST, FIRST_NN)
                sel = vl if spec.kind in (FIRST_NN, LAST_NN) else s_live
                masked = jnp.where(sel, idx, big if is_first else -1)
                pick = (jax.ops.segment_min if is_first
                        else jax.ops.segment_max)(
                    masked, seg_ids, num_segments=num_segments)
                pick = jnp.clip(pick, 0, capacity - 1)
                data = cd[pick]
                out_valid = vl[pick] & group_live
            elif spec.kind == ANY:
                data = jax.ops.segment_max(
                    jnp.where(vl, cd, False).astype(jnp.int8), seg_ids,
                    num_segments=num_segments) > 0
            elif spec.kind == EVERY:
                data = jax.ops.segment_min(
                    jnp.where(vl, cd, True).astype(jnp.int8), seg_ids,
                    num_segments=num_segments) > 0
            else:
                raise ValueError(f"unknown agg kind {spec.kind}")
            outs.append((data, out_valid))
        return out_keys, outs, num_groups

    return run


def reduce_trace(agg_specs, capacity):
    """No-key aggregation (single output row at index 0).

    `live` is an arbitrary row mask (see groupby_trace)."""
    def run(agg_data, agg_valid, live):
        outs = []
        for spec in agg_specs:
            d = agg_data[spec.input_idx] if spec.input_idx >= 0 else None
            v = agg_valid[spec.input_idx] if spec.input_idx >= 0 else None
            v = jnp.ones((capacity,), bool) if v is None else v
            vl = (v & live) if d is not None else live
            dt = spec.dtype
            if spec.kind in (COUNT, COUNT_ALL):
                val = jnp.sum(vl, dtype=jnp.int64)
                data, valid = val, jnp.asarray(True)
            else:
                nvalid = jnp.sum(vl, dtype=jnp.int32)
                valid = nvalid > 0
                cd = compute_view(d, dt)
                if spec.kind == SUM:
                    acc = cd.astype(jnp.float64 if t.is_floating(dt)
                                    else jnp.int64)
                    data = jnp.sum(jnp.where(vl, acc, 0))
                elif spec.kind in (MIN, MAX):
                    is_min = spec.kind == MIN
                    if isinstance(dt, t.DoubleType) and d.dtype == jnp.int64:
                        o = _bits_total_order(d)
                        ident = jnp.int64(_ORDER_MAX if is_min else _ORDER_MIN)
                        o = jnp.where(vl, o, ident)
                        red = jnp.min(o) if is_min else jnp.max(o)
                        data = _bits_from_order(red)
                    elif t.is_floating(dt):
                        isnan = jnp.isnan(cd) & vl
                        has_nan = jnp.any(isnan)
                        ident = jnp.float64(np.inf) if is_min \
                            else jnp.float64(-np.inf)
                        clean = jnp.where(vl & ~isnan, cd, ident)
                        red = jnp.min(clean) if is_min else jnp.max(clean)
                        n_clean = jnp.sum(vl & ~isnan)
                        if is_min:
                            data = jnp.where(has_nan & (n_clean == 0),
                                             jnp.float64(np.nan), red)
                        else:
                            data = jnp.where(has_nan, jnp.float64(np.nan), red)
                    else:
                        if isinstance(dt, t.BooleanType):
                            ident = jnp.asarray(is_min)
                        else:
                            info = np.iinfo(np.dtype(cd.dtype))
                            ident = jnp.asarray(info.max if is_min else info.min,
                                                cd.dtype)
                        acc = jnp.where(vl, cd, ident)
                        data = jnp.min(acc) if is_min else jnp.max(acc)
                elif spec.kind in (FIRST, LAST, FIRST_NN, LAST_NN):
                    idx = jnp.arange(capacity, dtype=jnp.int32)
                    is_first = spec.kind in (FIRST, FIRST_NN)
                    sel = vl if spec.kind in (FIRST_NN, LAST_NN) else live
                    masked = jnp.where(sel, idx, capacity if is_first else -1)
                    pick = jnp.min(masked) if is_first else jnp.max(masked)
                    pick = jnp.clip(pick, 0, capacity - 1)
                    data = compute_view(d, dt)[pick]
                    valid = vl[pick]
                elif spec.kind == ANY:
                    data = jnp.any(jnp.where(vl, cd, False))
                elif spec.kind == EVERY:
                    data = jnp.all(jnp.where(vl, cd, True))
                else:
                    raise ValueError(spec.kind)
            outs.append((data, valid))
        return outs

    return run
