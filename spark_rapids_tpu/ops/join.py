"""Equi-join kernels: sorted-hash probe on canonical key lanes.

Reference: GpuShuffledHashJoinExec / GpuHashJoin (GpuHashJoin.scala:104)
builds a cuDF hash table and gathers via GatherMaps.  Hash tables are a
poor fit for the MXU/VPU (serial probing, dynamic shapes), so the
TPU-native join is sort-based with static shapes end to end:

  1. every key column maps to a *canonical int64 lane* where Spark join
     equality == integer equality (NaN canonicalized to one bit pattern,
     -0.0 -> +0.0, strings -> codes in a dictionary unified across both
     sides, narrow ints sign-extended);
  2. multi-key rows fold their lanes into a 64-bit mixed hash; the build
     side is sorted by it once (single key: the lane itself, exact);
  3. probes binary-search the sorted lane (`searchsorted`) for candidate
     ranges — O(log n) vectorized, no data-dependent loops;
  4. candidate pairs expand into a static output bucket and are *verified*
     lane-by-lane, so hash collisions cannot produce wrong results, they
     only cost a masked-out row;
  5. outer/semi/anti variants derive from verified-match flags via
     segment/scatter max — never from the (overcounted) candidate ranges.

One host sync per probe batch fetches the candidate-pair count (the
reference syncs identically to size its gather maps).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..config import TpuConf, DEFAULT_CONF


INNER = "inner"
LEFT_OUTER = "left_outer"
RIGHT_OUTER = "right_outer"
FULL_OUTER = "full_outer"
LEFT_SEMI = "left_semi"
LEFT_ANTI = "left_anti"
CROSS = "cross"

_GOLDEN = 0x9E3779B97F4A7C15


def _mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer over uint64 lanes."""
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def _computed_f64_lanes(x: jax.Array) -> List[jax.Array]:
    """Exact injective int64 lane(s) for a *computed* (native-repr) f64 lane.

    The f64->i64 bitcast is unavailable on-TPU, so the encoding is built
    from conversions that exist on each backend:

      * TPU: the emulated f64 IS an (f32 hi, f32 lo) double-double pair, so
        `x.astype(f32)` recovers hi exactly and `x - hi` IS lo — two f32
        bitcasts packed into one int64 capture the full device value with
        zero loss.
      * CPU (real f64, used by the test mesh): the f32 pair keeps only ~48
        of 53 mantissa bits and overflows f32's exponent range, so distinct
        doubles would collide (the round-1 defect, ADVICE.md).  frexp gives
        an exact (53-bit scaled mantissa, exponent) pair instead — two
        int64 lanes, injective for every finite double.

    NaN (any payload) and -0.0 are canonicalized first: Spark equates them.
    """
    x = jnp.where(x == 0.0, jnp.float64(0.0), x)
    isnan = jnp.isnan(x)
    if jax.default_backend() == "tpu":
        hi = x.astype(jnp.float32)
        lo = jnp.where(jnp.isfinite(hi),
                       (x - hi.astype(jnp.float64)).astype(jnp.float32),
                       jnp.float32(0.0))
        hb = jax.lax.bitcast_convert_type(hi, jnp.int32)
        hb = jnp.where(isnan, jnp.int32(0x7FC00000), hb)
        lb = jax.lax.bitcast_convert_type(
            jnp.where(lo == 0.0, jnp.float32(0.0), lo), jnp.int32)
        lb = jnp.where(isnan, jnp.int32(0), lb)
        return [(hb.astype(jnp.int64) << 32) |
                (lb.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))]
    # XLA CPU flushes subnormals to zero in every op INCLUDING == (verified:
    # jnp.float64(2**-1060) == 0.0 is True), so subnormal inputs are
    # indistinguishable from 0 under the backend's own equality — encode
    # them as 0 explicitly rather than trusting frexp's inconsistent
    # subnormal handling.
    sub = jnp.abs(x) < jnp.float64(2.0 ** -1022)
    m, e = jnp.frexp(jnp.where(sub, 0.0, x))  # m in +-[0.5,1), exact
    mi = (m * jnp.float64(2.0 ** 53)).astype(jnp.int64)
    el = e.astype(jnp.int64)
    isinf = jnp.isinf(x)
    mi = jnp.where(isinf, jnp.where(x > 0, jnp.int64(1), jnp.int64(-1)), mi)
    el = jnp.where(isinf, jnp.int64(1 << 30), el)
    mi = jnp.where(isnan, jnp.int64(0x7FF8000000000000), mi)
    el = jnp.where(isnan, jnp.int64(1 << 30), el)
    return [mi, el]


def canonical_lanes(col: DeviceColumn) -> List[jax.Array]:
    """int64 lane(s) with Spark join-equality semantics (see module doc):
    value equality on the column == elementwise equality of every lane.
    Strings must already carry a side-unified dictionary.

    Most types yield one lane; computed DOUBLE yields one or two depending
    on backend (_computed_f64_lanes).  Build and probe sides must derive
    their lanes through the same column representation (exec/join.py keeps
    plain-column keys on the storage lane for both sides)."""
    dt = col.dtype
    data = col.data
    if isinstance(dt, t.StringType):
        return [data.astype(jnp.int64)]
    if isinstance(dt, t.DoubleType):
        if data.dtype != jnp.int64:
            return _computed_f64_lanes(data)
        # int64-bits storage lane (host pass-through): canonicalize NaN
        # (any payload) and -0.0 on the BITS — exact for all 64 bits, no
        # round trip through the (emulated) f64 representation
        exp_mask = jnp.int64(0x7FF0000000000000)
        mant_mask = jnp.int64(0x000FFFFFFFFFFFFF)
        isnan = ((data & exp_mask) == exp_mask) & ((data & mant_mask) != 0)
        bits = jnp.where(isnan, jnp.int64(0x7FF8000000000000), data)
        neg_zero = jnp.int64(np.int64(np.uint64(0x8000000000000000)))
        return [jnp.where(bits == neg_zero, jnp.int64(0), bits)]
    if isinstance(dt, t.FloatType):
        isnan = jnp.isnan(data)
        canon = jnp.where(isnan, jnp.float32(np.nan), data)
        canon = jnp.where(canon == 0.0, jnp.float32(0.0), canon)
        b = jax.lax.bitcast_convert_type(canon, jnp.int32)
        b = jnp.where(isnan, jnp.int32(0x7FC00000), b)
        return [b.astype(jnp.int64)]
    if isinstance(dt, t.DecimalType) and dt.is_wide:
        raise NotImplementedError("wide decimal join keys")
    return [data.astype(jnp.int64)]


def key_cols_lanes(key_cols: Sequence[DeviceColumn]) -> List[jax.Array]:
    """Flat canonical lane list for a key column set."""
    lanes: List[jax.Array] = []
    for c in key_cols:
        lanes.extend(canonical_lanes(c))
    return lanes


def composite_hash(lanes: Sequence[jax.Array]) -> jax.Array:
    """Fold canonical lanes into one uint64 hash lane (single lane: the
    lane itself -> exact ranges, zero collisions)."""
    if len(lanes) == 1:
        # any order-consistent injective transform works; searchsorted only
        # needs build and probe to agree
        return lanes[0].astype(jnp.uint64)
    h = jnp.zeros(lanes[0].shape, jnp.uint64)
    for i, lane in enumerate(lanes):
        u = lane.astype(jnp.uint64)
        h = _mix64(h ^ _mix64(u + jnp.uint64(_GOLDEN * (i + 1) & (2**64 - 1))))
    return h


class BuildTable:
    """Sorted build side of a join (the hash-table analogue).

    `lanes_override` replaces the per-column canonical lanes (e.g. a
    range-packed single lane for composite keys — exec/join.py
    _range_pack_spec); key validity still derives from `key_cols`."""

    def __init__(self, batch: DeviceBatch, key_cols: Sequence[DeviceColumn],
                 lanes_override: Optional[List[jax.Array]] = None):
        self.batch = batch
        lanes = lanes_override if lanes_override is not None \
            else key_cols_lanes(key_cols)
        valid = batch.row_mask()
        for c in key_cols:
            valid = valid & c.validity      # null keys never match
        h = composite_hash(lanes)
        # dead/null-key rows get MAX and liveness-primary lexsort, so the
        # array is globally non-decreasing (searchsorted-safe) and the
        # searchable region is exactly [0, valid_count)
        sort_h = jnp.where(valid, h, jnp.uint64(2**64 - 1))
        perm = jnp.lexsort([sort_h, (~valid).astype(jnp.int8)])
        self.perm = perm
        self.sorted_hash = jnp.take(sort_h, perm)
        self.valid_count = jnp.sum(valid, dtype=jnp.int32)
        self.lanes = lanes
        self.key_valid = valid

    @property
    def capacity(self) -> int:
        return self.batch.capacity


_PROBE_CACHE = {}


def probe_aligned(build: BuildTable, probe_lanes: List[jax.Array],
                  probe_valid: jax.Array):
    """Probe a build side whose keys are UNIQUE: each probe row has at
    most one match, so the output is probe-aligned — (build_idx, ok) with
    shape (probe_capacity,) and NO host sync (output capacity is the
    probe's own capacity, known statically).

    SINGLE-LANE ONLY: with one canonical lane the sorted "hash" IS the
    lane (exact, zero collisions), so the slot at searchsorted-left is
    the unique candidate.  With multiple lanes the composite hash can
    collide between distinct build keys and the single verified slot
    could miss a real match that sits one slot over — multi-lane joins
    must use probe_counts/expand_pairs, which scan the full candidate
    range.

    This is the TPU-native fast path for the dominant join shape
    (fact⋈dimension, join-against-group-by): the reference syncs to size
    its gather maps (GpuHashJoin.scala:104); a unique build side makes
    the size a static fact instead."""
    assert len(probe_lanes) == 1 and len(build.lanes) == 1, \
        "probe_aligned requires exact single-lane keys"
    sig = ("aligned", build.capacity, probe_valid.shape[0],
           len(probe_lanes))
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        bcap = build.capacity

        def run(perm, sorted_hash, valid_count, b_lanes, b_key_valid,
                p_lanes, p_valid):
            h = composite_hash(p_lanes)
            lo = jnp.searchsorted(sorted_hash, h, side="left")
            in_range = lo < valid_count
            pos = jnp.clip(lo, 0, bcap - 1)
            build_idx = jnp.take(perm, pos).astype(jnp.int32)
            ok = p_valid & in_range & \
                (jnp.take(sorted_hash, pos) == h)
            for bl, pl in zip(b_lanes, p_lanes):
                ok = ok & (jnp.take(bl, build_idx) == pl)
            ok = ok & jnp.take(b_key_valid, build_idx)
            return build_idx, ok
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    return fn(build.perm, build.sorted_hash, build.valid_count,
              tuple(build.lanes), build.key_valid,
              tuple(probe_lanes), probe_valid)


def probe_matched_lazy(build: BuildTable, probe_lanes: List[jax.Array],
                       probe_valid: jax.Array) -> jax.Array:
    """Per-probe-row matched flag with NO host sync — sound only for a
    SINGLE canonical lane, where the "hash" is the lane itself and a
    non-empty candidate range proves a true match (semi/anti joins need
    only this flag, never the pairs)."""
    assert len(probe_lanes) == 1, "exact ranges require a single lane"
    sig = ("matched_lazy", build.capacity, probe_valid.shape[0])
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        def run(sorted_hash, valid_count, lanes, pvalid):
            h = composite_hash(lanes)
            lo = jnp.searchsorted(sorted_hash, h, side="left")
            hi = jnp.searchsorted(sorted_hash, h, side="right")
            lo = jnp.minimum(lo, valid_count)
            hi = jnp.minimum(hi, valid_count)
            return pvalid & (hi > lo)
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    return fn(build.sorted_hash, build.valid_count, tuple(probe_lanes),
              probe_valid)


def probe_counts(build: BuildTable, probe_lanes: List[jax.Array],
                 probe_valid: jax.Array):
    """-> (lo, hi, counts, total) ; total is a host int (one sync)."""
    sig = ("probe_counts", build.capacity, probe_valid.shape[0],
           len(probe_lanes))
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        def run(sorted_hash, valid_count, lanes, pvalid):
            h = composite_hash(lanes)
            # restrict the search to the valid prefix
            lo = jnp.searchsorted(sorted_hash, h, side="left")
            hi = jnp.searchsorted(sorted_hash, h, side="right")
            lo = jnp.minimum(lo, valid_count)
            hi = jnp.minimum(hi, valid_count)
            counts = jnp.where(pvalid, hi - lo, 0).astype(jnp.int32)
            cum = jnp.cumsum(counts)
            return lo.astype(jnp.int32), counts, cum
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    lo, counts, cum = fn(build.sorted_hash, build.valid_count,
                         tuple(probe_lanes), probe_valid)
    total = int(cum[-1]) if cum.shape[0] else 0
    return lo, counts, cum, total


def expand_pairs(build: BuildTable, probe_lanes: List[jax.Array],
                 probe_valid: jax.Array, lo, cum, out_cap: int,
                 total: Optional[int] = None):
    """-> (probe_idx, build_idx, verified, probe_matched, build_matched)

    probe_idx/build_idx: (out_cap,) gather indices for candidate pairs;
    verified: lane-equality check per pair; probe_matched: per probe row;
    build_matched: per build row (for right/full outer)."""
    sig = ("expand", build.capacity, probe_valid.shape[0], out_cap,
           len(probe_lanes))
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        pcap = probe_valid.shape[0]
        bcap = build.capacity

        def run(perm, b_lanes, b_key_valid, p_lanes, p_valid, lo_, cum_,
                total):
            i = jnp.arange(out_cap, dtype=jnp.int32)
            pair_live = i < total
            probe_idx = jnp.searchsorted(cum_, i, side="right"
                                         ).astype(jnp.int32)
            probe_idx = jnp.minimum(probe_idx, pcap - 1)
            base = jnp.where(probe_idx > 0,
                             jnp.take(cum_, jnp.maximum(probe_idx - 1, 0)), 0)
            off = i - base.astype(jnp.int32)
            pos = jnp.take(lo_, probe_idx) + off
            pos = jnp.clip(pos, 0, bcap - 1)
            build_idx = jnp.take(perm, pos)
            # verify true key equality (kills hash collisions)
            ok = pair_live
            for bl, pl in zip(b_lanes, p_lanes):
                ok = ok & (jnp.take(bl, build_idx) ==
                           jnp.take(pl, probe_idx))
            ok = ok & jnp.take(p_valid, probe_idx) & \
                jnp.take(b_key_valid, build_idx)
            probe_matched = jax.ops.segment_max(
                ok.astype(jnp.int32), probe_idx, num_segments=pcap) > 0
            build_matched = jax.ops.segment_max(
                ok.astype(jnp.int32), build_idx, num_segments=bcap) > 0
            return probe_idx, build_idx, ok, probe_matched, build_matched
        fn = jax.jit(run, static_argnames=())
        _PROBE_CACHE[sig] = fn
    # callers pass probe_counts' total to avoid a second D2H sync
    true_total = total if total is not None \
        else (int(cum[-1]) if cum.shape[0] else 0)
    if true_total > out_cap:
        # callers size out_cap from probe_counts' total; a smaller cap would
        # silently drop matching rows — fail loudly instead
        raise ValueError(f"join candidate pairs {true_total} exceed output "
                         f"capacity {out_cap}")
    total = jnp.int32(true_total)
    return fn(build.perm, tuple(build.lanes), build.key_valid,
              tuple(probe_lanes), probe_valid, lo, cum, total)
