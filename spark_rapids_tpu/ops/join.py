"""Equi-join kernels: dense-domain direct addressing with a sorted-lane
fallback.

Reference: GpuShuffledHashJoinExec / GpuHashJoin (GpuHashJoin.scala:104)
builds a cuDF hash table and gathers via GatherMaps.  Hash tables are a
poor fit for the MXU/VPU (serial probing, dynamic shapes); binary search
is equally hostile (log2(n) dependent gathers — profiled at >50% of
TPC-H join time on v5e).  The TPU-native design is therefore a
*direct-address table over the key domain* whenever exact range
statistics bound it (scan min/max propagated through the plan;
dictionary size for strings; packed-lane span for composite keys):

  1. every key column maps to a *canonical int64 lane* where Spark join
     equality == integer equality (NaN canonicalized to one bit pattern,
     -0.0 -> +0.0, strings -> codes in a dictionary unified across both
     sides, narrow ints sign-extended);
  2. with a known domain [lo, hi] of bounded span, the build side
     scatters row ids (unique keys) or per-key counts+offsets (duplicate
     keys) into a span-sized table — probes are then pure gathers, no
     search, no sort for the unique case, O(1) per probe row;
  3. without a domain, multi-key rows fold their lanes into a 64-bit
     mixed hash, the build side is sorted by it once, and probes binary-
     search the sorted lane (`searchsorted`) for candidate ranges;
  4. candidate pairs expand into a static output bucket (pair ownership
     recovered by scatter + cummax, not search) and are *verified*
     lane-by-lane, so hash collisions cannot produce wrong results, they
     only cost a masked-out row;
  5. outer/semi/anti variants derive from verified-match flags — a
     sorted index lane + merge-rank difference (segments.matched_flags;
     scatter reductions only behind the legacy knob) — never from the
     (overcounted) candidate ranges.

One host sync per probe batch fetches the candidate-pair count (the
reference syncs identically to size its gather maps); unique-build and
semi/anti probes are sync-free.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..config import TpuConf, DEFAULT_CONF
from .kernels import blocked_cummax, blocked_cumsum
from .search import searchsorted


INNER = "inner"
LEFT_OUTER = "left_outer"
RIGHT_OUTER = "right_outer"
FULL_OUTER = "full_outer"
LEFT_SEMI = "left_semi"
LEFT_ANTI = "left_anti"
CROSS = "cross"

_GOLDEN = 0x9E3779B97F4A7C15


def _mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer over uint64 lanes."""
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def _computed_f64_lanes(x: jax.Array) -> List[jax.Array]:
    """Exact injective int64 lane(s) for a *computed* (native-repr) f64 lane.

    The f64->i64 bitcast is unavailable on-TPU, so the encoding is built
    from conversions that exist on each backend:

      * TPU: the emulated f64 IS an (f32 hi, f32 lo) double-double pair, so
        `x.astype(f32)` recovers hi exactly and `x - hi` IS lo — two f32
        bitcasts packed into one int64 capture the full device value with
        zero loss.
      * CPU (real f64, used by the test mesh): the f32 pair keeps only ~48
        of 53 mantissa bits and overflows f32's exponent range, so distinct
        doubles would collide (the round-1 defect, ADVICE.md).  frexp gives
        an exact (53-bit scaled mantissa, exponent) pair instead — two
        int64 lanes, injective for every finite double.

    NaN (any payload) and -0.0 are canonicalized first: Spark equates them.
    """
    x = jnp.where(x == 0.0, jnp.float64(0.0), x)
    isnan = jnp.isnan(x)
    if jax.default_backend() == "tpu":
        hi = x.astype(jnp.float32)
        lo = jnp.where(jnp.isfinite(hi),
                       (x - hi.astype(jnp.float64)).astype(jnp.float32),
                       jnp.float32(0.0))
        hb = jax.lax.bitcast_convert_type(hi, jnp.int32)
        hb = jnp.where(isnan, jnp.int32(0x7FC00000), hb)
        lb = jax.lax.bitcast_convert_type(
            jnp.where(lo == 0.0, jnp.float32(0.0), lo), jnp.int32)
        lb = jnp.where(isnan, jnp.int32(0), lb)
        return [(hb.astype(jnp.int64) << 32) |
                (lb.astype(jnp.int64) & jnp.int64(0xFFFFFFFF))]
    # XLA CPU flushes subnormals to zero in every op INCLUDING == (verified:
    # jnp.float64(2**-1060) == 0.0 is True), so subnormal inputs are
    # indistinguishable from 0 under the backend's own equality — encode
    # them as 0 explicitly rather than trusting frexp's inconsistent
    # subnormal handling.
    sub = jnp.abs(x) < jnp.float64(2.0 ** -1022)
    m, e = jnp.frexp(jnp.where(sub, 0.0, x))  # m in +-[0.5,1), exact
    mi = (m * jnp.float64(2.0 ** 53)).astype(jnp.int64)
    el = e.astype(jnp.int64)
    isinf = jnp.isinf(x)
    mi = jnp.where(isinf, jnp.where(x > 0, jnp.int64(1), jnp.int64(-1)), mi)
    el = jnp.where(isinf, jnp.int64(1 << 30), el)
    mi = jnp.where(isnan, jnp.int64(0x7FF8000000000000), mi)
    el = jnp.where(isnan, jnp.int64(1 << 30), el)
    return [mi, el]


def canonical_lanes(col: DeviceColumn) -> List[jax.Array]:
    """int64 lane(s) with Spark join-equality semantics (see module doc):
    value equality on the column == elementwise equality of every lane.
    Strings must already carry a side-unified dictionary.

    Most types yield one lane; computed DOUBLE yields one or two depending
    on backend (_computed_f64_lanes).  Build and probe sides must derive
    their lanes through the same column representation (exec/join.py keeps
    plain-column keys on the storage lane for both sides)."""
    dt = col.dtype
    data = col.data
    if isinstance(dt, t.StringType):
        return [data.astype(jnp.int64)]
    if isinstance(dt, t.DoubleType):
        if data.dtype != jnp.int64:
            return _computed_f64_lanes(data)
        # int64-bits storage lane (host pass-through): canonicalize NaN
        # (any payload) and -0.0 on the BITS — exact for all 64 bits, no
        # round trip through the (emulated) f64 representation
        exp_mask = jnp.int64(0x7FF0000000000000)
        mant_mask = jnp.int64(0x000FFFFFFFFFFFFF)
        isnan = ((data & exp_mask) == exp_mask) & ((data & mant_mask) != 0)
        bits = jnp.where(isnan, jnp.int64(0x7FF8000000000000), data)
        neg_zero = jnp.int64(np.int64(np.uint64(0x8000000000000000)))
        return [jnp.where(bits == neg_zero, jnp.int64(0), bits)]
    if isinstance(dt, t.FloatType):
        isnan = jnp.isnan(data)
        canon = jnp.where(isnan, jnp.float32(np.nan), data)
        canon = jnp.where(canon == 0.0, jnp.float32(0.0), canon)
        b = jax.lax.bitcast_convert_type(canon, jnp.int32)
        b = jnp.where(isnan, jnp.int32(0x7FC00000), b)
        return [b.astype(jnp.int64)]
    if isinstance(dt, t.DecimalType) and dt.is_wide:
        raise NotImplementedError("wide decimal join keys")
    return [data.astype(jnp.int64)]


def key_cols_lanes(key_cols: Sequence[DeviceColumn]) -> List[jax.Array]:
    """Flat canonical lane list for a key column set."""
    lanes: List[jax.Array] = []
    for c in key_cols:
        lanes.extend(canonical_lanes(c))
    return lanes


def composite_hash(lanes: Sequence[jax.Array]) -> jax.Array:
    """Fold canonical lanes into one uint64 hash lane (single lane: the
    lane itself -> exact ranges, zero collisions)."""
    if len(lanes) == 1:
        # any order-consistent injective transform works; searchsorted only
        # needs build and probe to agree
        return lanes[0].astype(jnp.uint64)
    h = jnp.zeros(lanes[0].shape, jnp.uint64)
    for i, lane in enumerate(lanes):
        u = lane.astype(jnp.uint64)
        h = _mix64(h ^ _mix64(u + jnp.uint64(_GOLDEN * (i + 1) & (2**64 - 1))))
    return h


class BuildTable:
    """Build side of a join (the hash-table analogue): a dense
    direct-address table over the key domain when `domain` is given,
    else a sorted canonical lane.

    `lanes_override` replaces the per-column canonical lanes (e.g. a
    range-packed single lane for composite keys — exec/join.py
    _range_pack_spec); key validity still derives from `key_cols`.

    `domain=(lo, hi)` asserts every VALID build key lies in [lo, hi]
    (exact plan statistics); requires a single lane.  `unique` asserts
    build keys are distinct (plan uniqueness statistics) — with a domain
    this removes the build sort entirely (one scatter builds the table).

    Sort-dependent members (`perm`, `sorted_hash`) and the dense tables
    (`slot`, `offs`) are built lazily so eager-mode probes only pay for
    the structures their join type touches (XLA DCE does the same for
    traced whole-plan programs)."""

    def __init__(self, batch: DeviceBatch, key_cols: Sequence[DeviceColumn],
                 lanes_override: Optional[List[jax.Array]] = None,
                 domain: Optional[Tuple[int, int]] = None,
                 unique: bool = False,
                 extra_valid: Optional[jax.Array] = None,
                 dense_via_sort: bool = True,
                 matched_via_merge: bool = True,
                 matched_via_presence: bool = True,
                 pallas_tier=None):
        self.batch = batch
        lanes = lanes_override if lanes_override is not None \
            else key_cols_lanes(key_cols)
        valid = batch.row_mask() if extra_valid is None else extra_valid
        for c in key_cols:
            valid = valid & c.validity      # null keys never match
        self.lanes = lanes
        self.key_valid = valid
        self.unique = unique
        # Pallas hash-probe tier (ops/pallas/hashjoin.py): an elected
        # tier arms the open-addressing murmur3 table for SINGLE exact
        # lanes — replacing both the sorted-build + merge-rank probe
        # and the dense direct-address tables (the hash build costs two
        # row-sized sorts where dense offs/slot cost span-sized ones).
        # Multi-lane composite hashes keep the sorted fallback: their
        # candidate ranges need collision verification the run-length
        # table contract cannot express.
        self.pallas_tier = None
        self._hash_table = None
        if pallas_tier is not None:
            if len(lanes) == 1:
                self.pallas_tier = pallas_tier
            else:
                from .pallas import count_fallback
                count_fallback("hash_probe_join", "multi_lane")
        # scatter-avoidance knobs (config.py JOIN_DENSE_BUILD_VIA_SORT /
        # JOIN_MATCHED_VIA_MERGE): dense tables from a sorted lane +
        # merge-rank, matched flags from merge-rank differences
        self.dense_via_sort = dense_via_sort
        self.matched_via_merge = matched_via_merge
        self.matched_via_presence = matched_via_presence
        if domain is not None and len(lanes) == 1:
            self.domain = (int(domain[0]), int(domain[1]))
        else:
            self.domain = None
        self._perm = None
        self._sorted_hash = None
        self._valid_count = None
        self._slot = None
        self._offs = None
        self._present = None

    @property
    def span(self) -> int:
        lo, hi = self.domain
        return hi - lo + 1

    def _dense_pos(self):
        """(pos, in_bounds): clipped domain position + validity per build
        row."""
        lo, hi = self.domain
        lane = self.lanes[0].astype(jnp.int64)
        inb = self.key_valid & (lane >= lo) & (lane <= hi)
        pos = jnp.clip(lane - lo, 0, self.span - 1).astype(jnp.int32)
        return jnp.where(inb, pos, self.span), inb

    @property
    def slot(self) -> Optional[jax.Array]:
        """Dense-unique direct table: slot[k-lo] = build row of key k,
        -1 for absent keys.  None unless (domain and unique).

        Sort-built by default: the row id at each key's offset in the
        pos-sorted order (dense_via_sort) — the scatter-built table
        lands in an S(1)-space buffer whose per-probe gathers then run
        ~200 MB/s."""
        if self.domain is None or not self.unique:
            return None
        if self._slot is None:
            if self.dense_via_sort:
                offs = self.offs
                first = jnp.take(self.perm,
                                 jnp.clip(offs[:-1], 0,
                                          self.capacity - 1))
                occupied = offs[1:] > offs[:-1]
                self._slot = jnp.where(occupied,
                                       first.astype(jnp.int32), -1)
            else:
                tgt, _inb = self._dense_pos()
                self._slot = jnp.full(
                    (self.span,), -1, jnp.int32).at[tgt].set(
                    jnp.arange(self.capacity, dtype=jnp.int32),
                    mode="drop")
        return self._slot

    @property
    def offs(self) -> Optional[jax.Array]:
        """Dense per-key start offsets into the key-sorted order
        (span+1,); key k's build rows are perm[offs[k-lo]:offs[k-lo+1]].
        None without a domain.

        Sort-built by default: offs[k] = rank of k among the sorted
        domain positions — ONE single-lane sort + a merge-rank (two
        2-operand sorts) instead of a count scatter + cumsum."""
        if self.domain is None:
            return None
        if self._offs is None:
            tgt, _inb = self._dense_pos()
            if self.dense_via_sort:
                sorted_pos = jnp.sort(tgt)
                self._offs = _merge_rank(
                    sorted_pos.astype(jnp.uint64),
                    jnp.arange(self.span + 1, dtype=jnp.uint64),
                    side="left").astype(jnp.int32)
            else:
                counts = jnp.zeros((self.span,), jnp.int32).at[tgt].add(
                    jnp.int32(1), mode="drop")
                self._offs = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int32),
                     blocked_cumsum(counts.astype(jnp.int32))])
        return self._offs

    @property
    def present(self) -> Optional[jax.Array]:
        """Dense-domain PRESENCE bitmap: present[k-lo] = some valid
        build row carries key k.  Matched-only probes (semi/anti) need
        exactly this — one bool scatter over build rows and a 1-byte
        gather per probe row, instead of the sorted offs table (a
        build-sized sort + merge-rank the flag never uses).  None
        without a domain."""
        if self.domain is None:
            return None
        if self._present is None:
            tgt, _inb = self._dense_pos()
            self._present = jnp.zeros((self.span,), bool).at[tgt].set(
                True, mode="drop")
        return self._present

    @property
    def perm(self) -> jax.Array:
        if self._perm is None:
            self._sort()
        return self._perm

    @property
    def sorted_hash(self) -> jax.Array:
        if self._sorted_hash is None:
            self._sort()
        return self._sorted_hash

    @property
    def valid_count(self) -> jax.Array:
        if self._valid_count is None:
            self._valid_count = jnp.sum(self.key_valid, dtype=jnp.int32)
        return self._valid_count

    def _sort(self):
        if self.domain is not None:
            # sort on the domain POSITION (int order), consistent with
            # the offs histogram — the uint64 hash order would disagree
            # for negative lanes
            tgt, _inb = self._dense_pos()
            self._perm = jnp.argsort(tgt, stable=True)
            self._sorted_hash = None    # dense probes never search
            return
        h = composite_hash(self.lanes)
        # dead/null-key rows get MAX and liveness-primary order, so the
        # array is globally non-decreasing (searchsorted-safe) and the
        # searchable region is exactly [0, valid_count); emitted as two
        # chained 2-operand stable sorts (TPU sort compile scales with
        # operand count — segments.lexsort_capped)
        from .segments import lexsort_capped
        sort_h = jnp.where(self.key_valid, h, jnp.uint64(2**64 - 1))
        perm = lexsort_capped(
            [sort_h, (~self.key_valid).astype(jnp.int8)], 2)
        self._perm = perm
        self._sorted_hash = jnp.take(sort_h, perm)

    @property
    def capacity(self) -> int:
        return self.batch.capacity

    @property
    def hash_table(self):
        """The armed Pallas hash table (built lazily, once per build
        side), or None on the sorted tier."""
        if self.pallas_tier is None:
            return None
        if self._hash_table is None:
            from .pallas import hashjoin as HK
            self._hash_table = HK.build_table(
                self.lanes[0].astype(jnp.int64), self.key_valid,
                self.pallas_tier.interpret)
        return self._hash_table


_PROBE_CACHE = {}


def _merge_rank(sorted_vals: jax.Array, queries: jax.Array,
                side: str) -> jax.Array:
    """np.searchsorted(sorted_vals, queries, side) without binary search:
    a stable sort merges both lanes and ranks fall out of a cumsum
    (log-step searchsorted gathers are the slowest access pattern on
    TPU — ~2.1s at 2M/4M vs ~0.2s for the merge on v5e).

    Tie order rides STABILITY, not a tag lane ('left' concatenates
    queries first so equal keys land after them; 'right' the reverse),
    and the rank inversion back to query order is a second stable sort
    on the id payload — both are 2-operand (key, payload) sorts.  TPU
    sort compile time scales with operand count (a 3-operand variadic
    sort costs minutes at 1M) and scatter outputs land in slow S(1)
    buffers, so two lean sorts beat one wide sort plus a scatter on
    both axes."""
    n = sorted_vals.shape[0]
    m = queries.shape[0]
    if side == "left":
        vals = jnp.concatenate([queries, sorted_vals])
        qlo = 0                         # query ids occupy [0, m)
    else:
        vals = jnp.concatenate([sorted_vals, queries])
        qlo = n                         # query ids occupy [n, n+m)
    ids = jnp.arange(n + m, dtype=jnp.int32)
    _v, s_ids = jax.lax.sort((vals, ids), num_keys=1, is_stable=True)
    is_key = (s_ids < qlo) | (s_ids >= qlo + m)
    cum = blocked_cumsum(is_key.astype(jnp.int32))
    # ranks back in query order: id-sort and slice the query span
    _i, ranks = jax.lax.sort((s_ids, cum), num_keys=1, is_stable=True)
    return ranks[qlo:qlo + m]


def _dense_probe_pos(lane: jax.Array, probe_valid: jax.Array,
                     lo: int, hi: int):
    """(pos, in_bounds) of probe keys in a build domain."""
    lane = lane.astype(jnp.int64)
    inb = probe_valid & (lane >= lo) & (lane <= hi)
    pos = jnp.clip(lane - lo, 0, hi - lo).astype(jnp.int32)
    return pos, inb


def probe_aligned(build: BuildTable, probe_lanes: List[jax.Array],
                  probe_valid: jax.Array):
    """Probe a build side whose keys are UNIQUE: each probe row has at
    most one match, so the output is probe-aligned — (build_idx, ok) with
    shape (probe_capacity,) and NO host sync (output capacity is the
    probe's own capacity, known statically).

    With a dense domain this is ONE gather from the direct-address
    table — no search, and the build needed no sort.  Otherwise the slot
    at searchsorted-left is the unique candidate.

    SINGLE-LANE ONLY: with one canonical lane the lane is exact (zero
    collisions).  With multiple lanes the composite hash can collide
    between distinct build keys and the single verified slot could miss
    a real match that sits one slot over — multi-lane joins must use
    probe_counts/expand_pairs, which scan the full candidate range.

    This is the TPU-native fast path for the dominant join shape
    (fact⋈dimension, join-against-group-by): the reference syncs to size
    its gather maps (GpuHashJoin.scala:104); a unique build side makes
    the size a static fact instead."""
    assert len(probe_lanes) == 1 and len(build.lanes) == 1, \
        "probe_aligned requires exact single-lane keys"
    if build.hash_table is not None:
        from .pallas import hashjoin as HK
        row, ok = HK.probe_first(build.hash_table,
                                 probe_lanes[0].astype(jnp.int64),
                                 probe_valid)
        return jnp.maximum(row, 0), ok
    if build.slot is not None:
        lo, hi = build.domain
        sig = ("aligned_dense", build.span, probe_valid.shape[0], lo, hi)
        fn = _PROBE_CACHE.get(sig)
        if fn is None:
            def run(slot, p_lane, p_valid):
                pos, inb = _dense_probe_pos(p_lane, p_valid, lo, hi)
                build_idx = jnp.take(slot, pos)
                ok = inb & (build_idx >= 0)
                return jnp.where(ok, build_idx, 0), ok
            fn = jax.jit(run)
            _PROBE_CACHE[sig] = fn
        return fn(build.slot, probe_lanes[0], probe_valid)
    sig = ("aligned", build.capacity, probe_valid.shape[0],
           len(probe_lanes))
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        bcap = build.capacity

        def run(perm, sorted_hash, valid_count, b_lanes, b_key_valid,
                p_lanes, p_valid):
            h = composite_hash(p_lanes)
            lo = _merge_rank(sorted_hash, h, side="left")
            in_range = lo < valid_count
            pos = jnp.clip(lo, 0, bcap - 1)
            build_idx = jnp.take(perm, pos).astype(jnp.int32)
            ok = p_valid & in_range & \
                (jnp.take(sorted_hash, pos) == h)
            for bl, pl in zip(b_lanes, p_lanes):
                ok = ok & (jnp.take(bl, build_idx) == pl)
            ok = ok & jnp.take(b_key_valid, build_idx)
            return build_idx, ok
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    return fn(build.perm, build.sorted_hash, build.valid_count,
              tuple(build.lanes), build.key_valid,
              tuple(probe_lanes), probe_valid)


def probe_matched_lazy(build: BuildTable, probe_lanes: List[jax.Array],
                       probe_valid: jax.Array) -> jax.Array:
    """Per-probe-row matched flag with NO host sync — sound only for a
    SINGLE canonical lane, where the "hash" is the lane itself and a
    non-empty candidate range proves a true match (semi/anti joins need
    only this flag, never the pairs).  Dense domains answer from the
    per-key counts (two gathers), no search and no build sort."""
    assert len(probe_lanes) == 1, "exact ranges require a single lane"
    if build.hash_table is not None:
        from .pallas import hashjoin as HK
        return HK.probe_matched(build.hash_table,
                                probe_lanes[0].astype(jnp.int64),
                                probe_valid)
    if build.domain is not None and build.matched_via_presence:
        # presence bitmap, not the offs table: the flag needs key
        # EXISTENCE only, so the build-sized sort + merge-rank behind
        # `offs` never pays for itself here (q21/q22-class anti joins:
        # a 2M-row build answered by one span-sized bool scatter)
        lo, hi = build.domain
        sig = ("matched_present", build.span, probe_valid.shape[0], lo,
               hi)
        fn = _PROBE_CACHE.get(sig)
        if fn is None:
            def run(present, p_lane, p_valid):
                pos, inb = _dense_probe_pos(p_lane, p_valid, lo, hi)
                return inb & jnp.take(present, pos)
            fn = jax.jit(run)
            _PROBE_CACHE[sig] = fn
        return fn(build.present, probe_lanes[0], probe_valid)
    if build.domain is not None:
        lo, hi = build.domain
        sig = ("matched_dense", build.span, probe_valid.shape[0], lo, hi)
        fn = _PROBE_CACHE.get(sig)
        if fn is None:
            def run(offs, p_lane, p_valid):
                pos, inb = _dense_probe_pos(p_lane, p_valid, lo, hi)
                return inb & (jnp.take(offs, pos + 1) >
                              jnp.take(offs, pos))
            fn = jax.jit(run)
            _PROBE_CACHE[sig] = fn
        return fn(build.offs, probe_lanes[0], probe_valid)
    sig = ("matched_lazy", build.capacity, probe_valid.shape[0])
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        def run(sorted_hash, valid_count, lanes, pvalid):
            h = composite_hash(lanes)
            lo = _merge_rank(sorted_hash, h, side="left")
            hi = _merge_rank(sorted_hash, h, side="right")
            lo = jnp.minimum(lo, valid_count)
            hi = jnp.minimum(hi, valid_count)
            return pvalid & (hi > lo)
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    return fn(build.sorted_hash, build.valid_count, tuple(probe_lanes),
              probe_valid)


def probe_counts(build: BuildTable, probe_lanes: List[jax.Array],
                 probe_valid: jax.Array):
    """-> (lo, counts, cum, total) ; total is a host int (one sync).
    `lo` values are candidate-range starts in build.perm order (or
    hash-table positions on the Pallas tier — expand_pairs resolves
    whichever representation probe_counts produced)."""
    if build.hash_table is not None and len(probe_lanes) == 1:
        from .pallas import hashjoin as HK
        first, counts, cum = HK.probe_counts(
            build.hash_table, probe_lanes[0].astype(jnp.int64),
            probe_valid)
        total = int(cum[-1]) if cum.shape[0] else 0
        return first, counts, cum, total
    if build.domain is not None and len(probe_lanes) == 1:
        dlo, dhi = build.domain
        sig = ("counts_dense", build.span, probe_valid.shape[0], dlo, dhi)
        fn = _PROBE_CACHE.get(sig)
        if fn is None:
            def run(offs, p_lane, p_valid):
                pos, inb = _dense_probe_pos(p_lane, p_valid, dlo, dhi)
                lo = jnp.take(offs, pos)
                hi = jnp.take(offs, pos + 1)
                counts = jnp.where(inb, hi - lo, 0).astype(jnp.int32)
                return lo, counts, blocked_cumsum(counts)
            fn = jax.jit(run)
            _PROBE_CACHE[sig] = fn
        lo, counts, cum = fn(build.offs, probe_lanes[0], probe_valid)
        total = int(cum[-1]) if cum.shape[0] else 0
        return lo, counts, cum, total
    sig = ("probe_counts", build.capacity, probe_valid.shape[0],
           len(probe_lanes))
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        def run(sorted_hash, valid_count, lanes, pvalid):
            h = composite_hash(lanes)
            # restrict the search to the valid prefix
            lo = _merge_rank(sorted_hash, h, side="left")
            hi = _merge_rank(sorted_hash, h, side="right")
            lo = jnp.minimum(lo, valid_count)
            hi = jnp.minimum(hi, valid_count)
            counts = jnp.where(pvalid, hi - lo, 0).astype(jnp.int32)
            cum = blocked_cumsum(counts)
            return lo.astype(jnp.int32), counts, cum
        fn = jax.jit(run)
        _PROBE_CACHE[sig] = fn
    lo, counts, cum = fn(build.sorted_hash, build.valid_count,
                         tuple(probe_lanes), probe_valid)
    total = int(cum[-1]) if cum.shape[0] else 0
    return lo, counts, cum, total


def expand_pairs(build: BuildTable, probe_lanes: List[jax.Array],
                 probe_valid: jax.Array, lo, counts, cum, out_cap: int,
                 total: Optional[int] = None):
    """-> (probe_idx, build_idx, verified, probe_matched, build_matched)

    probe_idx/build_idx: (out_cap,) gather indices for candidate pairs;
    verified: lane-equality check per pair; probe_matched: per probe row;
    build_matched: per build row (for right/full outer).

    Pair ownership (which probe row owns output slot i) is recovered by
    scattering each live probe row's index at its range start and
    cummax-ing forward — O(n) scatter+scan instead of a binary search
    per output slot (the log2(n) dependent gathers of searchsorted are
    the slowest access pattern on TPU)."""
    if build.hash_table is not None and len(probe_lanes) == 1:
        # Pallas tier: `lo` is the per-probe first TABLE position and a
        # key's matches occupy consecutive slots, so expansion is a
        # rank search + pure gathers (no ownership sorts); matched
        # flags fall out of counts and interval marking
        from .pallas import hashjoin as HK
        true_total = total if total is not None \
            else (int(cum[-1]) if cum.shape[0] else 0)
        if true_total > out_cap:
            raise ValueError(
                f"join candidate pairs {true_total} exceed output "
                f"capacity {out_cap}")
        probe_idx, build_idx, ok = HK.expand_pairs(
            build.hash_table, lo, counts, cum, out_cap,
            jnp.int32(true_total))
        probe_matched = probe_valid & (counts > 0)
        build_matched = HK.build_matched_flags(
            build.hash_table, lo, counts, build.capacity)
        return probe_idx, build_idx, ok, probe_matched, build_matched
    # exact candidate ranges (single lane or dense domain) need no
    # per-pair verification against collisions, and probe_matched is just
    # counts>0 — skip one of the two segment reductions
    exact = len(build.lanes) == 1
    via_merge = build.matched_via_merge
    sig = ("expand", build.capacity, probe_valid.shape[0], out_cap,
           len(probe_lanes), exact, via_merge)
    fn = _PROBE_CACHE.get(sig)
    if fn is None:
        pcap = probe_valid.shape[0]
        bcap = build.capacity

        def run(perm, b_lanes, b_key_valid, p_lanes, p_valid, lo_,
                counts_, cum_, total):
            i = jnp.arange(out_cap, dtype=jnp.int32)
            pair_live = i < total
            starts = (cum_ - counts_).astype(jnp.int32)
            # pair ownership by MERGE, not scatter: sort probe range
            # starts together with the output slots (starts win ties so a
            # start owns its own slot), cummax the owning probe row
            # forward in merged order, then invert by the id payload —
            # two 2-operand sorts; scatter outputs land in slow S(1)
            # buffers and the variadic alternative is compile-hostile
            tgt = jnp.where(counts_ > 0, starts, out_cap)
            vals = jnp.concatenate([tgt, i])
            ids = jnp.arange(pcap + out_cap, dtype=jnp.int32)
            _v, s_ids = jax.lax.sort((vals, ids), num_keys=1,
                                     is_stable=True)
            is_start = s_ids < pcap
            mark = jnp.where(is_start, s_ids, -1)
            owner = blocked_cummax(mark)
            _i, owner_by_id = jax.lax.sort((s_ids, owner), num_keys=1,
                                           is_stable=True)
            probe_idx = jnp.maximum(owner_by_id[pcap:], 0).astype(jnp.int32)
            off = i - jnp.take(starts, probe_idx)
            pos = jnp.take(lo_, probe_idx) + off
            pos = jnp.clip(pos, 0, bcap - 1)
            build_idx = jnp.take(perm, pos)
            ok = pair_live
            if exact:
                ok = ok & jnp.take(p_valid, probe_idx)
                probe_matched = p_valid & (counts_ > 0)
            else:
                # verify true key equality (kills hash collisions)
                for bl, pl in zip(b_lanes, p_lanes):
                    ok = ok & (jnp.take(bl, build_idx) ==
                               jnp.take(pl, probe_idx))
                ok = ok & jnp.take(p_valid, probe_idx) & \
                    jnp.take(b_key_valid, build_idx)
                if via_merge:
                    from .segments import matched_flags
                    probe_matched = matched_flags(probe_idx, ok, pcap)
                else:
                    probe_matched = jax.ops.segment_max(
                        ok.astype(jnp.int32), probe_idx,
                        num_segments=pcap, indices_are_sorted=True) > 0
            if via_merge:
                from .segments import matched_flags
                build_matched = matched_flags(build_idx, ok, bcap)
            else:
                build_matched = jax.ops.segment_max(
                    ok.astype(jnp.int32), build_idx,
                    num_segments=bcap) > 0
            return probe_idx, build_idx, ok, probe_matched, build_matched
        fn = jax.jit(run, static_argnames=())
        _PROBE_CACHE[sig] = fn
    # callers pass probe_counts' total to avoid a second D2H sync
    true_total = total if total is not None \
        else (int(cum[-1]) if cum.shape[0] else 0)
    if true_total > out_cap:
        # callers size out_cap from probe_counts' total; a smaller cap would
        # silently drop matching rows — fail loudly instead
        raise ValueError(f"join candidate pairs {true_total} exceed output "
                         f"capacity {out_cap}")
    total = jnp.int32(true_total)
    return fn(build.perm, tuple(build.lanes), build.key_valid,
              tuple(probe_lanes), probe_valid, lo, counts, cum, total)
