"""Vectorized binary-search with a single switch point for the method.

TPU cost model (measured on v5e, 2M table / 4M queries):
  * `scan` (the default): log2(n) while-steps, each a dynamic gather at
    one index per query — ~2.1s at that shape (gather-bound), but
    compiles in O(1s).
  * `sort`: one (n+m) variadic sort + rank recovery — ~0.2s to RUN but
    ~60s to COMPILE per instance (TPU sort compile scales with length
    and operand count), which multiplies across a whole-plan program.

Hot join paths avoid this primitive entirely (dense-domain direct
addressing in ops/join.py — scatter+gather only); the remaining users
(ragged row-ids, string segment maps, timezone tables, range bounds)
keep the scan method, whose compile cost is negligible and whose run
cost is acceptable at their shapes.  This wrapper exists so the choice
is made in exactly one place as the cost model evolves.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def searchsorted(a: jax.Array, v: jax.Array, side: str = "left"
                 ) -> jax.Array:
    """np.searchsorted semantics with a TPU-friendly method choice."""
    return jnp.searchsorted(a, v, side=side, method="scan")
