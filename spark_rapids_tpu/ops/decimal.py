"""Decimal kernels over int64 unscaled lanes.

Reference: decimalExpressions.scala + DecimalUtils JNI (128-bit).  TPU has
no int128; this engine's decimal story (columnar/device.py):

  * decimal(p<=18) — one int64 unscaled lane, exact.
  * wider results  — still ONE int64 lane on device: arithmetic whose
    *result type* exceeds precision 18 stays on device when the operand
    types fit int64, with overflow-to-null detection; the host boundary
    widens to arrow decimal128.  Values beyond int64's ~9.2e18 unscaled
    range null out where Spark's 128-bit math would succeed — a documented
    deviation (docs/compatibility.md analogue) the same spirit as the
    reference's float-ordering notes.  Host columns that *arrive* wider
    than int64 (true 128-bit data) are not computed on device (tagged,
    CPU fallback).

Spark result-type rules (DecimalPrecision, allowPrecisionLoss=true):
  add/sub: s = max(s1,s2);          p = max(p1-s1, p2-s2) + s + 1
  mul:     s = s1+s2;               p = p1 + p2 + 1
  div:     s = max(6, s1+p2+1);     p = p1 - s1 + s2 + s
capped at 38 with scale reduction (min scale 6) on overflow.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t

MAX_PRECISION = 38
MIN_ADJUSTED_SCALE = 6

POW10 = np.array([10 ** i for i in range(19)], dtype=np.int64)

#: largest int64-exact unscaled magnitude per precision (p <= 18)
def max_unscaled(p: int) -> int:
    return 10 ** min(p, 18) - 1


def _adjust(p: int, s: int) -> t.DecimalType:
    """Spark DecimalType.adjustPrecisionScale (allowPrecisionLoss)."""
    if p <= MAX_PRECISION:
        return t.DecimalType(p, s)
    int_digits = p - s
    min_scale = min(s, MIN_ADJUSTED_SCALE)
    adj_scale = max(MAX_PRECISION - int_digits, min_scale)
    return t.DecimalType(MAX_PRECISION, adj_scale)


def add_result(a: t.DecimalType, b: t.DecimalType) -> t.DecimalType:
    s = max(a.scale, b.scale)
    p = max(a.precision - a.scale, b.precision - b.scale) + s + 1
    return _adjust(p, s)


def mul_result(a: t.DecimalType, b: t.DecimalType) -> t.DecimalType:
    return _adjust(a.precision + b.precision + 1, a.scale + b.scale)


def div_result(a: t.DecimalType, b: t.DecimalType) -> t.DecimalType:
    s = max(6, a.scale + b.precision + 1)
    p = a.precision - a.scale + b.scale + s
    return _adjust(p, s)


def integral_as_decimal(dt: t.DataType) -> t.DecimalType:
    return {t.ByteType: t.DecimalType(3, 0), t.ShortType: t.DecimalType(5, 0),
            t.IntegerType: t.DecimalType(10, 0),
            t.LongType: t.DecimalType(20, 0)}[type(dt)]


# ---------------------------------------------------------------------------
# Device kernels (traced)
# ---------------------------------------------------------------------------

def upscale(u: jax.Array, ds: int) -> Tuple[jax.Array, jax.Array]:
    """u * 10^ds with int64-overflow detection -> (value, ok)."""
    if ds == 0:
        return u, jnp.ones(u.shape, bool)
    f = POW10[ds]
    out = u * jnp.int64(f)
    ok = jnp.abs(u) <= (jnp.int64(2 ** 63 - 1) // jnp.int64(f))
    return out, ok


def downscale_half_up(u: jax.Array, ds: int) -> jax.Array:
    """u / 10^ds rounding half away from zero (Spark HALF_UP)."""
    if ds == 0:
        return u
    f = jnp.int64(POW10[ds])
    half = f // 2
    mag = (jnp.abs(u) + half) // f
    return jnp.where(u < 0, -mag, mag)


def rescale(u: jax.Array, from_scale: int, to_scale: int
            ) -> Tuple[jax.Array, jax.Array]:
    """(value, ok): change of scale with overflow/rounding per Spark."""
    if to_scale >= from_scale:
        return upscale(u, to_scale - from_scale)
    return downscale_half_up(u, from_scale - to_scale), \
        jnp.ones(u.shape, bool)


def fits_precision(u: jax.Array, p: int) -> jax.Array:
    """ok mask: |u| representable in precision p (int64-capped)."""
    if p >= 19:
        return jnp.ones(u.shape, bool)
    return jnp.abs(u) <= jnp.int64(max_unscaled(p))


def add_dev(ua, sa, ub, sb, out: t.DecimalType):
    """Aligned add -> (unscaled, ok)."""
    va, ok_a = rescale(ua, sa, out.scale)
    vb, ok_b = rescale(ub, sb, out.scale)
    r = va + vb
    # int64 add overflow: same sign in, different sign out
    ovf = ((va >= 0) == (vb >= 0)) & ((r >= 0) != (va >= 0))
    ok = ok_a & ok_b & ~ovf & fits_precision(r, out.precision)
    return r, ok


def sub_dev(ua, sa, ub, sb, out: t.DecimalType):
    return add_dev(ua, sa, -ub, sb, out)


def mul_dev(ua, sa, ub, sb, out: t.DecimalType):
    """Product at scale sa+sb, then rescale to out.scale."""
    prod = ua * ub
    # overflow estimate via f64 magnitudes (exact int64 check is awkward;
    # 2^62 guard leaves a safety margin over f64's 53-bit mantissa error)
    est = jnp.abs(ua.astype(jnp.float64)) * jnp.abs(ub.astype(jnp.float64))
    ok = est < jnp.float64(2 ** 62)
    r, ok2 = rescale(prod, sa + sb, out.scale)
    return r, ok & ok2 & fits_precision(r, out.precision)


def cast_to_integral(u: jax.Array, scale: int) -> jax.Array:
    """decimal -> integral: truncate toward zero."""
    if scale == 0:
        return u
    f = jnp.int64(POW10[scale])
    mag = jnp.abs(u) // f
    return jnp.where(u < 0, -mag, mag)


def to_double(u: jax.Array, scale: int) -> jax.Array:
    return u.astype(jnp.float64) / jnp.float64(10 ** scale)


def from_double(x: jax.Array, out: t.DecimalType):
    """double -> decimal(p, s) with HALF_UP, null on overflow/NaN."""
    scaled = x.astype(jnp.float64) * jnp.float64(10 ** out.scale)
    finite = jnp.isfinite(scaled)
    bounded = jnp.abs(scaled) < jnp.float64(2 ** 62)
    safe = jnp.where(finite & bounded, scaled, 0.0)
    mag = jnp.floor(jnp.abs(safe) + 0.5)
    u = jnp.where(safe < 0, -mag, mag).astype(jnp.int64)
    ok = finite & bounded & fits_precision(u, out.precision)
    return u, ok


def from_integral(v: jax.Array, out: t.DecimalType):
    u, ok = upscale(v.astype(jnp.int64), out.scale)
    return u, ok & fits_precision(u, out.precision)
