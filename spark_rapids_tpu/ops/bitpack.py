"""On-device compression kernels for the exchange wire format.

The reference's shuffle compresses partition payloads on-GPU (nvcomp)
before they hit UCX; Theseus (PAPERS.md) argues the whole distributed
tier wins or loses on exactly this.  TPU-native, the wire is an XLA
collective, so the codecs must be jit-traceable tensor programs:

  * bit packing     — bool/validity lanes ride 1 bit per row instead of
                      the 1-byte `int8` lanes the exchange used to ship;
  * frame-of-reference (FOR) width narrowing — an integer lane whose
    global [min, max] span fits a narrower word ships as `value - min`
    in uint8/16/32 (the cascaded-codec primitive nvcomp applies first);
  * run-length encoding — sorted or low-cardinality lanes collapse into
    (value, run_length) pairs at a static capacity.

All kernels are static-shape (capacity in, capacity out) so they can
live inside `shard_map` collective programs (parallel/exchange.py).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_BIT_WEIGHTS = 1 << np.arange(8, dtype=np.uint8)


def pack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """Pack a bool array's LAST axis (length divisible by 8) into uint8
    bytes: (..., N) bool -> (..., N // 8) uint8, bit b of byte i holding
    row 8*i + b."""
    n = x.shape[-1]
    assert n % 8 == 0, f"pack_bits needs a multiple of 8 rows, got {n}"
    g = x.reshape(x.shape[:-1] + (n // 8, 8)).astype(jnp.uint8)
    return (g * jnp.asarray(_BIT_WEIGHTS)).sum(-1).astype(jnp.uint8)


def unpack_bits(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of `pack_bits`: (..., M) uint8 -> (..., 8 * M) bool."""
    bits = (packed[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return bits.reshape(packed.shape[:-1] + (packed.shape[-1] * 8,)) \
        .astype(bool)


def wire_dtype_for(lo: int, hi: int, logical: np.dtype) -> np.dtype:
    """Narrowest unsigned frame-of-reference wire dtype for an integer
    lane with global live range [lo, hi] — the host-side plan step (the
    range rides the same fetch as the exchange's count matrix).  Returns
    the LOGICAL dtype when narrowing does not save bytes (already
    narrow, empty lane handled by caller passing lo > hi)."""
    logical = np.dtype(logical)
    if lo > hi:                    # no live rows: cheapest legal width
        return np.dtype(np.uint8) if logical.itemsize > 1 else logical
    span = int(hi) - int(lo)
    for cand in (np.uint8, np.uint16, np.uint32):
        c = np.dtype(cand)
        if c.itemsize < logical.itemsize and span <= np.iinfo(c).max:
            return c
    return logical


def for_encode(x: jnp.ndarray, bias, wire_dtype) -> jnp.ndarray:
    """Frame-of-reference encode: `(x - bias)` cast to the planned wire
    dtype.  Masked (dead) slots may wrap — receivers drop them."""
    if np.dtype(wire_dtype) == np.dtype(x.dtype):
        return x
    return (x - bias).astype(wire_dtype)


def for_decode(w: jnp.ndarray, bias, logical_dtype) -> jnp.ndarray:
    """Inverse of `for_encode` back to the logical dtype."""
    if np.dtype(w.dtype) == np.dtype(logical_dtype):
        return w
    return (w.astype(logical_dtype) + jnp.asarray(bias).astype(
        logical_dtype))


def bytes_to_words(x: jnp.ndarray) -> jnp.ndarray:
    """View any fixed-width lane slab (..., Q) as wire bytes
    (..., Q, itemsize) so heterogeneous lanes concatenate into ONE wide
    word per slot — one collective dispatch instead of one per lane."""
    if x.dtype == jnp.uint8:
        return x[..., None]
    return jax.lax.bitcast_convert_type(x, jnp.uint8)


def words_to_lane(w: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of `bytes_to_words` for one lane's byte slice."""
    dtype = np.dtype(dtype)
    if dtype == np.dtype(np.uint8):
        return w[..., 0]
    return jax.lax.bitcast_convert_type(w, dtype)


def _exclusive_cumsum(x):
    return jnp.concatenate([jnp.zeros((1,), x.dtype), jnp.cumsum(x)[:-1]])


def rle_encode(x: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run-length encode a lane at static capacity: returns
    (run_values (C,), run_lengths (C,) int32, n_runs () int32) where
    only the first `n_runs` entries are meaningful.  Sorted or
    low-cardinality lanes (dictionary-run keys after the exchange's
    dest-lexsort) collapse to n_runs << C."""
    cap = x.shape[0]
    b = jnp.concatenate([jnp.ones((1,), bool), x[1:] != x[:-1]])
    run_id = jnp.cumsum(b.astype(jnp.int32)) - 1
    n_runs = jnp.sum(b, dtype=jnp.int32)
    lengths = jax.ops.segment_sum(jnp.ones_like(run_id), run_id,
                                  num_segments=cap)
    starts = jnp.sort(jnp.where(b, jnp.arange(cap, dtype=jnp.int32),
                                jnp.int32(cap)))
    values = x[jnp.clip(starts, 0, cap - 1)]
    return values, lengths.astype(jnp.int32), n_runs


def rle_decode(values: jnp.ndarray, lengths: jnp.ndarray,
               cap: int) -> jnp.ndarray:
    """Expand (run_values, run_lengths) back to a (cap,) lane.  Rows
    past the encoded total replicate the final run's value (callers
    carry a live mask, same convention as every exchange lane)."""
    starts = _exclusive_cumsum(lengths)
    idx = jnp.searchsorted(starts, jnp.arange(cap, dtype=lengths.dtype),
                           side="right") - 1
    return values[jnp.clip(idx, 0, values.shape[0] - 1)]
