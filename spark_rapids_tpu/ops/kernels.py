"""Low-level device kernel helpers shared by the expression/operator layers.

The cuDF ColumnVector elementwise-op role (reference §2.9) is played by jnp
inside jit-traced expression functions; this module holds the representation
plumbing those traces share:

  * storage<->compute views (DOUBLE rides as int64 bit patterns, see
    columnar/device.py module docs)
  * validity lane algebra (Spark three-valued logic)
  * row-liveness masking for reductions over padded buckets
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t


def compute_dtype(dt: t.DataType):
    """jnp dtype used for arithmetic on this logical type."""
    if isinstance(dt, t.DoubleType):
        return jnp.float64
    return t.physical_np_dtype(dt)


def compute_view(data: jax.Array, dt: t.DataType) -> jax.Array:
    """Storage lane -> compute representation.

    DOUBLE has two possible storage lanes: int64 f64-bit-patterns for columns
    that came from the host (bit-exact pass-through; see columnar/device.py)
    and native (emulated) f64 for computed results — XLA on this TPU supports
    the s64->f64 bitcast but NOT the reverse, so computed doubles stay f64.
    """
    if isinstance(dt, t.DoubleType) and data.dtype == jnp.int64:
        return jax.lax.bitcast_convert_type(data, jnp.float64)
    return data


def storage_view(data: jax.Array, dt: t.DataType) -> jax.Array:
    """Compute representation -> storage lane.

    Computed DOUBLEs keep their native f64 lane (f64->s64 bitcast is
    unimplemented on-TPU; nothing is lost — the value is already
    device-precision).  to_host handles both lane kinds.
    """
    if isinstance(dt, t.DoubleType):
        return data.astype(jnp.float64)
    return data.astype(t.physical_np_dtype(dt))


def merge_validity(*vs: Optional[jax.Array]) -> Optional[jax.Array]:
    """AND of validity lanes; None means all-valid."""
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = jnp.logical_and(out, v)
    return out


def valid_or_true(v: Optional[jax.Array], capacity: int) -> jax.Array:
    return jnp.ones((capacity,), dtype=bool) if v is None else v


def live_mask(capacity: int, num_rows: jax.Array) -> jax.Array:
    """Mask of logically-live rows in a padded bucket."""
    return jnp.arange(capacity, dtype=jnp.int32) < num_rows.astype(jnp.int32)


def zeros_like_storage(dt: t.DataType, capacity: int) -> jax.Array:
    return jnp.zeros((capacity,), dtype=t.physical_np_dtype(dt))
