"""Low-level device kernel helpers shared by the expression/operator layers.

The cuDF ColumnVector elementwise-op role (reference §2.9) is played by jnp
inside jit-traced expression functions; this module holds the representation
plumbing those traces share:

  * storage<->compute views (DOUBLE rides as int64 bit patterns, see
    columnar/device.py module docs)
  * validity lane algebra (Spark three-valued logic)
  * row-liveness masking for reductions over padded buckets
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t


def compute_dtype(dt: t.DataType):
    """jnp dtype used for arithmetic on this logical type."""
    if isinstance(dt, t.DoubleType):
        return jnp.float64
    return t.physical_np_dtype(dt)


def compute_view(data: jax.Array, dt: t.DataType) -> jax.Array:
    """Storage lane -> compute representation.

    DOUBLE has two possible storage lanes: int64 f64-bit-patterns for columns
    that came from the host (bit-exact pass-through; see columnar/device.py)
    and native (emulated) f64 for computed results — XLA on this TPU supports
    the s64->f64 bitcast but NOT the reverse, so computed doubles stay f64.
    """
    if isinstance(dt, t.DoubleType) and data.dtype == jnp.int64:
        return jax.lax.bitcast_convert_type(data, jnp.float64)
    return data


def storage_view(data: jax.Array, dt: t.DataType) -> jax.Array:
    """Compute representation -> storage lane.

    Computed DOUBLEs keep their native f64 lane (f64->s64 bitcast is
    unimplemented on-TPU; nothing is lost — the value is already
    device-precision).  to_host handles both lane kinds.
    """
    if isinstance(dt, t.DoubleType):
        return data.astype(jnp.float64)
    return data.astype(t.physical_np_dtype(dt))


def merge_validity(*vs: Optional[jax.Array]) -> Optional[jax.Array]:
    """AND of validity lanes; None means all-valid."""
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = jnp.logical_and(out, v)
    return out


def valid_or_true(v: Optional[jax.Array], capacity: int) -> jax.Array:
    return jnp.ones((capacity,), dtype=bool) if v is None else v


def live_mask(capacity: int, num_rows: jax.Array) -> jax.Array:
    """Mask of logically-live rows in a padded bucket."""
    return jnp.arange(capacity, dtype=jnp.int32) < num_rows.astype(jnp.int32)


def zeros_like_storage(dt: t.DataType, capacity: int) -> jax.Array:
    return jnp.zeros((capacity,), dtype=t.physical_np_dtype(dt))


# ---------------------------------------------------------------------------
# Blocked cumulative scans
# ---------------------------------------------------------------------------
# XLA:TPU lowers a long 1-D cumsum/cummax into a log-depth associative
# scan whose COMPILE time is brutal on this platform (measured: 44-50s
# for one 1M-row int64 cumsum; 2s for the blocked form).  Splitting into
# fixed 512-row blocks keeps every scan window small (compiles in
# seconds) and runs as three cheap elementwise/reduce passes.

_SCAN_BLOCK = 512
_SCAN_MIN = 4096            # below this the native scan compiles fine


def blocked_cumsum(a: jax.Array, axis: int = 0) -> jax.Array:
    """jnp.cumsum along axis 0 (1-D or 2-D input), TPU-compile-friendly."""
    assert axis == 0
    n = a.shape[0]
    if n < _SCAN_MIN or n % _SCAN_BLOCK != 0:
        return jnp.cumsum(a, axis=0)
    nb = n // _SCAN_BLOCK
    blocks = a.reshape((nb, _SCAN_BLOCK) + a.shape[1:])
    within = jnp.cumsum(blocks, axis=1)
    totals = within[:, -1]
    offs = jnp.cumsum(totals, axis=0) - totals
    return (within + offs[:, None]).reshape(a.shape)


def blocked_cummax(a: jax.Array) -> jax.Array:
    """lax.cummax along axis 0 (1-D input), TPU-compile-friendly."""
    n = a.shape[0]
    if n < _SCAN_MIN or n % _SCAN_BLOCK != 0:
        return jax.lax.cummax(a, axis=0)
    nb = n // _SCAN_BLOCK
    blocks = a.reshape(nb, _SCAN_BLOCK)
    within = jax.lax.cummax(blocks, axis=1)
    totals = within[:, -1]
    offs = jax.lax.cummax(totals, axis=0)
    ident = (jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.inexact)
             else jnp.iinfo(a.dtype).min)
    shifted = jnp.concatenate(
        [jnp.full((1,), ident, a.dtype), offs[:-1]])
    return jnp.maximum(within, shifted[:, None]).reshape(n)
