"""ServingRuntime: many queries, many tenants, one engine.

The single-query path (`DataFrame.collect`) runs plan -> compile ->
upload -> execute strictly in sequence and one query at a time; under
interactive traffic the device idles through every host phase.  This
runtime is the concurrency layer the ROADMAP's "millions of users" item
asks for, built ON TOP of the existing substrate rather than beside it:

  * ADMISSION — a bounded queue (`serving.queueDepth`) with a blocking
    timeout (`serving.admitTimeoutMs`): at the bound, `submit()` applies
    backpressure and then raises `AdmissionTimeout` — load sheds with a
    clean, retryable signal at the door instead of a device OOM halfway
    through a query.  Device-phase overlap is additionally gated by an
    HBM working-set estimate against the memory budget
    (`runtime/memory.py` sizing), so concurrent queries queue for HBM
    instead of betting on the OOM retry ladder.
  * CONF SNAPSHOT — every query's `TpuConf` is captured at admission; a
    mid-flight `TpuSession.set_conf` affects only queries admitted
    after it (TpuConf instances are immutable, `set_conf` swaps them).
  * PHASE OVERLAP — each admitted query runs its pipeline (plan ->
    result-cache probe -> compile -> scan upload -> device execute) on
    a worker thread; compilation routes through the background compile
    service (`runtime/compile_service.py`, keyed by canonical plan
    structure so identical-shape tenants' queries compile once) and XLA
    compiles release the GIL — one query compiles while another holds
    the device, which is where the `bench.py --serving` QPS-over-serial
    win comes from.
  * FAIR SHARE — device-execute grants go through a weighted
    virtual-time scheduler: each tenant accumulates measured device
    microseconds divided by its weight, and the runnable tenant with
    the LEAST virtual time runs next, with a hard starvation bound
    (`serving.starvationBound` consecutive pass-overs forces a grant).
    Per-tenant device time feeds `tpu_serving_tenant_device_us_total`
    from the same integer measurement the ticket records, so registry
    totals and per-ticket sums agree exactly.
  * RESULT CACHE — see serving/cache.py.
  * FAULT ISOLATION (`serving.pool.processes` > 0) — queries execute in
    a SUPERVISED POOL of worker processes (serving/workers.py): each
    worker owns its own TpuSession / MemoryBudget / device slice while
    sharing the persistent compile cache and history store; a crash,
    hang or fatal device error in one worker loses only its in-flight
    queries, which REDRIVE on survivors (serving.redrive.maxAttempts)
    bit-identically.  Admission stays here, in the supervisor, and the
    HBM gate reconciles its estimates against the pool's heartbeat-
    reported DeviceCensus totals — a truthful cross-process picture.
  * DEADLINES (`serving.deadlineMs`, or per-submit) — cooperative
    cancellation at the engine's natural brackets (seam / batch / OOC
    pass / exchange round / spill sweep, ExecContext.checkpoint):
    an expired query raises QueryDeadlineExceeded at the next
    checkpoint and its FULL device reservation releases.
  * GRACEFUL DRAIN — `drain()` stops admitting, lets in-flight queries
    finish (or redrive), checkpoints the history store, and reaps every
    worker process: empty queue, no orphans.

Surfaces: `TpuSession.serving()` -> ServingRuntime;
`runtime.tenant("bi", weight=2.0)` -> TenantSession with
`submit()`/`collect()`; `runtime.stats()` for the live picture; the
`tpu_serving_*` metric families for Prometheus.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

import pyarrow as pa

from ..config import (HBM_BUDGET_BYTES, HBM_BUDGET_FRACTION,
                      SERVING_ADMIT_TIMEOUT_MS,
                      SERVING_ADMIT_WORKING_SET_FACTOR,
                      SERVING_DEADLINE_MS, SERVING_DEVICE_SLOTS,
                      SERVING_POOL_PROCS, SERVING_QUEUE_DEPTH,
                      SERVING_RESULT_CACHE_BYTES, SERVING_STARVATION_BOUND,
                      SERVING_WORKERS, TpuConf)
from ..obs.registry import (SERVING_ADMIT_WAIT_MS, SERVING_DEADLINE_CANCELS,
                            SERVING_DEVICE_BUSY_US, SERVING_QUERIES,
                            SERVING_TENANT_DEVICE_US,
                            SERVING_TENANT_PREDICTED_US)
from ..obs.registry import SERVING_QUEUE_DEPTH as QUEUE_DEPTH_GAUGE
from .cache import ResultCache, result_cache_key


class AdmissionTimeout(RuntimeError):
    """The admission queue stayed at queueDepth past admitTimeoutMs —
    the backpressure signal.  Retryable by construction: nothing was
    admitted, nothing ran."""


class InjectedAdmissionTimeout(AdmissionTimeout):
    """Chaos-harness form (`serving:timeout:...`,  runtime/faults.py)."""


class QueryTicket:
    """One admitted query's handle: state, timings, result."""

    _SEQ_LOCK = threading.Lock()
    _SEQ = 0

    def __init__(self, plan, conf: TpuConf, tenant: str):
        with QueryTicket._SEQ_LOCK:
            QueryTicket._SEQ += 1
            self.id = QueryTicket._SEQ
        self.plan = plan                  # logical plan (DataFrame._plan)
        self.conf = conf                  # admission-time snapshot
        self.tenant = tenant
        self.cache = "bypass"             # hit | miss | store | bypass
        self.plan_kind = None             # "device" | "host" once planned
        #: admission-time cost prediction (obs/estimator.py), or None
        #: when the history plane is off: {device_us, working_set_bytes,
        #: compile_ms, confidence, basis, ...}
        self.predicted: Optional[dict] = None
        #: admitted in OUT-OF-CORE mode: the working-set estimate
        #: exceeded the HBM budget, so instead of running solo (and
        #: serializing the queue) the query executes with the OOC tier
        #: forced and a grant sized to the OOC resident window
        self.ooc = False
        #: per-query deadline (serving.deadlineMs or the submit
        #: override); 0 = none.  Cooperative: enforced at the engine's
        #: checkpoint brackets, not by thread preemption.
        self.deadline_ms = 0.0
        self.redrives = 0                 # worker losses survived (MP mode)
        self.worker = None                # worker id that answered (MP mode)
        #: compact profile summary from the answering worker's
        #: completion frame (MP mode): wall breakdown, hbm, serving
        #: context — folded into the stitched event-log record
        self.worker_profile: Optional[dict] = None
        self.device_us = 0                # measured device-execute micros
        self.skips = 0                    # scheduler pass-overs at grant
        self.admit_wait_ms = 0.0
        self.phases: Dict[str, float] = {}     # phase -> wall seconds
        self.error: Optional[BaseException] = None
        self._table: Optional[pa.Table] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = 600.0) -> pa.Table:
        """Block for the result; re-raises the query's failure here."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"serving query #{self.id} (tenant {self.tenant!r}) did "
                f"not finish within {timeout}s")
        if self.error is not None:
            raise self.error
        return self._table

    def _complete(self, table: pa.Table) -> None:
        self._table = table
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self.error = exc
        self._done.set()


class _TenantState:
    __slots__ = ("name", "weight", "vtime_us", "skips", "queue",
                 "queries", "device_us")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = float(weight)
        self.vtime_us = 0.0          # device_us / weight, accumulated
        self.skips = 0               # consecutive pass-overs while runnable
        self.queue: List[QueryTicket] = []
        self.queries = 0
        self.device_us = 0


class TenantSession:
    """Per-tenant handle: the unit client code holds.

    `collect()` retries ONE AdmissionTimeout (genuine backpressure and
    the chaos `serving:timeout` site both surface there) — a dashboard
    refresh should survive a momentary full queue without caller retry
    loops; sustained overload still raises."""

    def __init__(self, runtime: "ServingRuntime", name: str):
        self._runtime = runtime
        self.name = name

    def submit(self, df,
               deadline_ms: Optional[float] = None) -> QueryTicket:
        return self._runtime.submit(df, tenant=self.name,
                                    deadline_ms=deadline_ms)

    def collect(self, df, timeout: Optional[float] = 600.0,
                deadline_ms: Optional[float] = None) -> pa.Table:
        try:
            ticket = self.submit(df, deadline_ms=deadline_ms)
        except AdmissionTimeout:
            # one bounded re-admission
            ticket = self.submit(df, deadline_ms=deadline_ms)
        return ticket.result(timeout)


class ServingRuntime:
    def __init__(self, session, conf_overrides: Optional[dict] = None):
        self._session = session
        rconf = session.conf
        if conf_overrides:
            rconf = TpuConf({**rconf._raw, **conf_overrides})
        self._rconf = rconf
        self._overrides = dict(conf_overrides or {})
        # merged-conf cache: ONE TpuConf per session-conf instance, so
        # the fault injector / typed-value caches riding the conf keep
        # stable counters across submits (a fresh merge per submit
        # would reset deterministic nth= chaos triggers)
        self._merged = (None, None)
        self._queue_depth = rconf.get(SERVING_QUEUE_DEPTH)
        self._admit_timeout_s = rconf.get(SERVING_ADMIT_TIMEOUT_MS) / 1e3
        self._deadline_ms = float(rconf.get(SERVING_DEADLINE_MS))
        #: serving.pool.processes > 0 = MULTI-PROCESS mode: queries
        #: execute in the supervised worker pool (serving/workers.py);
        #: the pool itself starts lazily, on the first submit
        self._pool_procs = int(rconf.get(SERVING_POOL_PROCS))
        self._worker_pool = None
        self._device_slots = rconf.get(SERVING_DEVICE_SLOTS)
        if self._pool_procs > 0:
            # each worker process owns its own device slice + budget:
            # device phases genuinely run in parallel across processes,
            # so the grant width IS the pool width
            self._device_slots = self._pool_procs
        elif self._device_slots == 0:
            # auto: on an accelerator, the GpuSemaphore sizing
            # (concurrentTpuTasks) — the chip pipelines dispatches and
            # one query's host tail overlaps another's compute.  On the
            # CPU backend "device compute" IS host compute: concurrent
            # XLA CPU programs each size their intra-op pool to all
            # cores and thrash (measured 5x throughput collapse), so
            # device phases serialize and only host phases overlap.
            import jax
            if jax.default_backend() == "cpu":
                self._device_slots = 1
            else:
                from ..config import CONCURRENT_TPU_TASKS
                self._device_slots = rconf.get(CONCURRENT_TPU_TASKS)
        self._starvation_bound = rconf.get(SERVING_STARVATION_BOUND)
        self._ws_factor = rconf.get(SERVING_ADMIT_WORKING_SET_FACTOR)
        self.cache = ResultCache(rconf.get(SERVING_RESULT_CACHE_BYTES))
        self._hbm_limit = self._device_budget_bytes(rconf)
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=rconf.get(SERVING_WORKERS),
            thread_name_prefix="tpu-serving")
        self._cond = threading.Condition()
        self._tenants: Dict[str, _TenantState] = {}
        self._inflight = 0               # admitted, not yet finished
        self._device_active = 0
        self._device_bytes = 0           # working-set estimates admitted
        self._closed = False
        self._draining = False           # drain(): admission closed, in-
                                         # flight queries still finishing
        # -- stats (under _cond) -------------------------------------
        self._t0 = time.perf_counter()
        self._busy_us = 0
        self._max_skips = 0
        self._max_depth = 0
        self._completed = 0
        self._admission_timeouts = 0
        self._ooc_admissions = 0         # oversized queries admitted OOC
        self._deadline_cancels = 0       # deadline/injected cancellations
        #: recent (phase, ticket id, t0, t1) intervals — the overlap
        #: proof stats()["overlap_observed"] is computed from
        self._intervals: List[tuple] = []

    # -- construction helpers ---------------------------------------------
    @staticmethod
    def _device_budget_bytes(conf: TpuConf) -> int:
        """The HBM byte budget device-phase admission schedules against
        (0 = undiscoverable = unlimited) — same sizing rule as
        runtime/memory.py MemoryBudget."""
        limit = conf.get(HBM_BUDGET_BYTES)
        if limit == 0:
            from ..runtime.memory import device_hbm_bytes
            hbm = device_hbm_bytes()
            limit = int(hbm * conf.get(HBM_BUDGET_FRACTION)) if hbm else 0
        return limit

    def tenant(self, name: str, weight: float = 1.0) -> TenantSession:
        """The tenant handle (registers the tenant; weight sticks —
        re-calling with a new weight updates it)."""
        with self._cond:
            st = self._tenants.get(name)
            if st is None:
                self._tenants[name] = _TenantState(name, weight)
            else:
                st.weight = float(weight)
        return TenantSession(self, name)

    # -- admission ---------------------------------------------------------
    def submit(self, df, tenant: str = "default",
               conf: Optional[TpuConf] = None,
               deadline_ms: Optional[float] = None) -> QueryTicket:
        """Admit one query (blocking up to admitTimeoutMs when the queue
        is full) and start its pipeline.  `df` is a DataFrame or a
        logical plan; the session conf is SNAPSHOT here, at admission.
        `deadline_ms` overrides serving.deadlineMs for this query."""
        if self._closed:
            raise RuntimeError("ServingRuntime is closed")
        if self._draining:
            raise RuntimeError("ServingRuntime is draining: admission "
                               "is closed, in-flight queries finishing")
        # the snapshot: TpuConf instances are immutable — grabbing the
        # reference pins this query's behavior against later set_conf
        snap = conf or self._session.conf
        if conf is None and self._overrides:
            with self._cond:
                if self._merged[0] is not snap:
                    self._merged = (
                        snap, TpuConf({**snap._raw, **self._overrides}))
                snap = self._merged[1]
        from ..runtime.faults import get_injector
        injector = get_injector(snap)
        injector.fire("serving", tenant=tenant)
        plan = getattr(df, "_plan", df)
        ticket = QueryTicket(plan, snap, tenant)
        ticket.deadline_ms = float(self._deadline_ms
                                   if deadline_ms is None else deadline_ms)
        if self._pool_procs > 0:
            self._ensure_pool()
        t0 = time.perf_counter()
        deadline = t0 + self._admit_timeout_s
        with self._cond:
            while self._inflight >= self._queue_depth:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self._admission_timeouts += 1
                    SERVING_QUERIES.inc(tenant=tenant,
                                        status="admission_timeout")
                    raise AdmissionTimeout(
                        f"serving queue at depth {self._queue_depth} for "
                        f"{self._admit_timeout_s:.1f}s (tenant "
                        f"{tenant!r}) — shed load or raise "
                        f"spark.rapids.tpu.serving.queueDepth")
                self._cond.wait(remaining)
            if self._closed:
                raise RuntimeError("ServingRuntime is closed")
            self._inflight += 1
            self._max_depth = max(self._max_depth, self._inflight)
            if tenant not in self._tenants:
                self._tenants[tenant] = _TenantState(tenant, 1.0)
        waited_ms = (time.perf_counter() - t0) * 1e3
        ticket.admit_wait_ms = waited_ms
        SERVING_ADMIT_WAIT_MS.observe(waited_ms)
        QUEUE_DEPTH_GAUGE.set(self._inflight)
        self._pool.submit(self._run, ticket, injector)
        return ticket

    def _ensure_pool(self):
        """The supervised worker pool, started on first demand (worker
        processes each build a full TpuSession — seconds, paid once)."""
        with self._cond:
            pool = self._worker_pool
            if pool is not None:
                return pool
        from .workers import WorkerPool
        pool = WorkerPool(self._rconf, dict(self._rconf._raw),
                          self._pool_procs).start()
        with self._cond:
            if self._worker_pool is None:
                self._worker_pool = pool
                return pool
        pool.close()                     # lost the race: keep the first
        return self._worker_pool

    # -- the per-query pipeline (one worker thread) ------------------------
    def _run(self, ticket: QueryTicket, injector) -> None:
        try:
            out = self._pipeline(ticket, injector)
            ticket._complete(out)
            SERVING_QUERIES.inc(
                tenant=ticket.tenant,
                status="cache_hit" if ticket.cache == "hit" else "ok")
        except BaseException as e:                   # noqa: BLE001
            ticket._fail(e)
            from ..exec.plan import (InjectedDeadlineExceeded,
                                     QueryCancelled, QueryDeadlineExceeded)
            if isinstance(e, QueryDeadlineExceeded):
                reason = ("injected"
                          if isinstance(e, InjectedDeadlineExceeded)
                          else "drain" if isinstance(e, QueryCancelled)
                          else "deadline")
                SERVING_DEADLINE_CANCELS.inc(reason=reason)
                with self._cond:
                    self._deadline_cancels += 1
            SERVING_QUERIES.inc(tenant=ticket.tenant, status="error")
        finally:
            with self._cond:
                self._inflight -= 1
                self._completed += 1
                self._cond.notify_all()
            QUEUE_DEPTH_GAUGE.set(self._inflight)

    def _phase(self, name: str, ticket: QueryTicket):
        runtime = self

        @contextmanager
        def scope():
            t0 = time.perf_counter()
            try:
                yield
            finally:
                t1 = time.perf_counter()
                ticket.phases[name] = ticket.phases.get(name, 0.0) \
                    + (t1 - t0)
                with runtime._cond:
                    runtime._intervals.append((name, ticket.id, t0, t1))
                    if len(runtime._intervals) > 4096:
                        del runtime._intervals[:2048]
        return scope()

    def _pipeline(self, ticket: QueryTicket, injector) -> pa.Table:
        from ..plan.overrides import apply_overrides
        with self._phase("plan", ticket):
            q = apply_overrides(ticket.plan, ticket.conf)
        ticket.plan_kind = q.kind
        # admission-time cost prediction: the structure-keyed history
        # oracle (obs/estimator.py) answers BEFORE anything runs; the
        # prediction rides the ticket, the per-tenant predicted-us
        # counter, and (below) the query's tracer/event log — the
        # execution record closes the calibration loop
        try:
            from ..obs.estimator import estimate_query
            ticket.predicted = estimate_query(q)
        except Exception:                            # noqa: BLE001
            ticket.predicted = None      # the oracle must never fail a query
        pred = ticket.predicted
        if pred:
            SERVING_TENANT_PREDICTED_US.inc(int(pred["device_us"]),
                                            tenant=ticket.tenant)
        if self._pool_procs > 0:
            # MULTI-PROCESS mode: the query executes in the supervised
            # worker pool.  The result cache is bypassed (the mp tier
            # trades it for fault isolation — the persistent compile
            # cache still dedupes across workers); admission, fair
            # share and the HBM gate stay here in the supervisor.
            return self._pipeline_mp(ticket, injector, q, pred)
        keyed = None
        if self.cache.cap_bytes and q.kind == "device":
            keyed = result_cache_key(q.root, ticket.conf)
        if keyed is not None:
            hit = self.cache.get(keyed[0], injector)
            if hit is not None:
                ticket.cache = "hit"
                return hit
            ticket.cache = "miss"
        with self._phase("compile", ticket):
            self._compile(q, ticket)
        with self._phase("upload", ticket):
            est_bytes = self._upload(q, ticket)
        est_bytes = self._admit_working_set(ticket, est_bytes, pred)
        with self._device_grant(ticket, est_bytes):
            with self._phase("execute", ticket):
                from ..exec.plan import ExecContext, cancel_scope
                ctx = ExecContext(ticket.conf)
                # cooperative deadline: checked at every checkpoint
                # bracket (seam/batch/OOC/exchange/spill); the clock
                # starts HERE, at the device grant — queue wait does
                # not consume the budget
                ctx.arm_deadline(ticket.deadline_ms)
                if ticket.ooc:
                    ctx.ooc_force = True
                ctx.metrics["serving.tenant"] = ticket.tenant
                # the GLOBAL ticket id: the tracer adopts it
                # (plan/overrides.py), so event-log filenames are
                # keyed the same way in-process and across the pool
                ctx.metrics["serving.query_id"] = ticket.id
                if pred:
                    # stamped pre-collect so the instrumented scope
                    # embeds the prediction in the trace + event log
                    # and the history record calibrates against it
                    ctx.metrics["predicted.device_us"] = \
                        int(pred["device_us"])
                    ctx.metrics["predicted.basis"] = pred["basis"]
                    ctx.metrics["predicted.working_set_bytes"] = \
                        int(pred.get("working_set_bytes") or 0)
                    ctx.metrics["predicted.ws_basis"] = \
                        str(pred.get("ws_basis") or "?")
                    ctx.metrics["predicted.confidence"] = \
                        pred.get("confidence")
                t0 = time.perf_counter()
                with cancel_scope(ctx):
                    out = q.collect(ctx)
                ticket.device_us = int(
                    (time.perf_counter() - t0) * 1e6)
        if keyed is not None and ticket.error is None:
            if self.cache.put(keyed[0], out, keyed[1]):
                ticket.cache = "store"
        return out

    def _admit_working_set(self, ticket: QueryTicket, est_bytes: int,
                           pred: Optional[dict]) -> int:
        """Tighten the heuristic working-set estimate against the
        history oracle, then make the OVERSIZED call (shared by the
        thread and multi-process pipelines)."""
        if pred and pred.get("ws_basis") == "measured" and \
                int(pred.get("working_set_bytes") or 0) > 0:
            # MEASURED-basis working set (memattr query peaks / XLA
            # memory_analysis floors folded through the history plane):
            # it REPLACES the admitWorkingSetFactor x source-bytes
            # heuristic — the gate tightens to what the structure
            # actually held, so more queries overlap without betting
            # on the OOM ladder
            est_bytes = int(pred["working_set_bytes"])
        elif pred and pred["basis"] == "exact_history":
            # reserved-basis history: schedule against the LARGER of
            # the heuristic and the recorded peak (no measured data
            # yet — over-reserve rather than over-commit)
            est_bytes = max(est_bytes,
                            int(pred.get("working_set_bytes") or 0))
        # OVERSIZED working set: instead of waiting for a solo slot
        # (the `_runnable` escape hatch — one big query serializing the
        # whole queue), admit in OUT-OF-CORE mode (ROADMAP 4's last
        # clause): the query runs with the OOC tier forced, its actual
        # resident footprint is the OOC window, and the grant is sized
        # to that window so small-tenant queries keep overlapping it
        if self._hbm_limit > 0 and est_bytes > self._hbm_limit:
            from ..config import OOC_ENABLED, OOC_RESIDENT_FRACTION
            if ticket.conf.get(OOC_ENABLED):
                ticket.ooc = True
                est_bytes = max(
                    int(self._hbm_limit *
                        float(ticket.conf.get(OOC_RESIDENT_FRACTION))), 1)
                with self._cond:
                    self._ooc_admissions += 1
                from ..obs.registry import OOC_ELECTIONS
                OOC_ELECTIONS.inc(op="query", mode="admission")
        return est_bytes

    def _pipeline_mp(self, ticket: QueryTicket, injector, q,
                     pred: Optional[dict]) -> pa.Table:
        """The multi-process tail of the pipeline: size the grant from
        the LOGICAL plan (uploads happen inside whichever worker wins
        the dispatch, against that worker's own budget), then dispatch
        through the pool's redrive loop under a supervisor grant."""
        src_bytes = 0
        if q.kind == "device":
            from ..exec.plan import HostScanExec
            stack, seen = [q.root], set()
            while stack:
                n = stack.pop()
                if id(n) in seen:
                    continue
                seen.add(id(n))
                if isinstance(n, HostScanExec) and \
                        n._source_table is not None:
                    src_bytes += int(n._source_table.nbytes)
                stack.extend(getattr(n, "children", ()))
        est_bytes = self._admit_working_set(
            ticket, int(src_bytes * self._ws_factor), pred)
        pool = self._ensure_pool()
        tracer = self._stitch_tracer(ticket)
        status = "ok"
        try:
            t_g0 = time.perf_counter()
            with self._device_grant(ticket, est_bytes):
                if tracer is not None:
                    tracer.add_span("grant", "serving", t_g0,
                                    time.perf_counter(),
                                    skips=ticket.skips,
                                    est_bytes=est_bytes)
                with self._phase("execute", ticket):
                    out, device_us = pool.execute(ticket, injector,
                                                  ticket.deadline_ms,
                                                  tracer=tracer)
                    ticket.device_us = int(device_us)
            return out
        except BaseException:
            status = "error"
            raise
        finally:
            self._finish_stitch(tracer, ticket, status)

    def _stitch_tracer(self, ticket: QueryTicket):
        """The supervisor-side STITCHED trace: one event-log record per
        pool query, keyed by the global ticket id, spanning admission ->
        grant -> worker execution (-> loss -> redrive) -> completion.
        The answering worker writes its own deep per-query log under
        the SAME id; this record is the cross-process head that names
        every worker the query touched."""
        from ..config import EVENT_LOG_DIR, TRACE_ENABLED
        if not (ticket.conf.get(TRACE_ENABLED)
                or ticket.conf.get(EVENT_LOG_DIR)):
            return None
        from ..obs.tracer import QueryTracer
        tracer = QueryTracer(ticket.id)
        tracer.meta["stitched"] = True
        tracer.meta["tenant"] = ticket.tenant
        if ticket.predicted:
            tracer.meta["prediction"] = {
                k: ticket.predicted.get(k)
                for k in ("device_us", "basis")}
        # admission already happened: replay it as a span so the record
        # covers submit -> grant
        now = time.perf_counter()
        tracer.add_span("admission", "serving",
                        now - ticket.admit_wait_ms / 1e3, now,
                        wait_ms=round(ticket.admit_wait_ms, 3))
        return tracer

    def _finish_stitch(self, tracer, ticket: QueryTicket,
                       status: str) -> None:
        if tracer is None:
            return
        from ..config import EVENT_LOG_DIR
        try:
            tracer.meta["status"] = status
            tracer.meta["redrives"] = ticket.redrives
            tracer.meta["worker"] = ticket.worker
            tracer.meta["workers"] = [
                s.attrs.get("worker") for s in tracer.spans
                if s.cat == "execute"]
            if ticket.worker_profile:
                tracer.meta["worker_profile"] = ticket.worker_profile
            # a root query span over the whole stitched window so
            # QueryProfile/profile_report render it like any trace
            ts = [s.t0 for s in tracer.spans] or [time.perf_counter()]
            with tracer.span("query", "query"):
                pass
            root = tracer.spans[-1]
            root.t0 = min(ts)
            tracer.finish({"serving.tenant": ticket.tenant,
                           "serving.query_id": ticket.id,
                           "serving.redrives": ticket.redrives,
                           "device_us": ticket.device_us})
            log_dir = str(ticket.conf.get(EVENT_LOG_DIR) or "")
            if log_dir:
                tracer.write(log_dir)
        except Exception:                            # noqa: BLE001
            pass          # stitching must never fail a served query

    def _compile(self, q, ticket: QueryTicket) -> None:
        """AOT-compile the whole-plan program through the background
        compile service: dedupe-keyed by canonical plan structure, so N
        tenants submitting the same dashboard shape pay ONE compile;
        injected `compile` chaos faults re-raise here, on the consuming
        thread, where the existing recovery ladders live."""
        if q.kind != "device" or not q._whole_plan_enabled():
            return
        from ..exec.compiled import plan_structure_key
        from ..runtime.compile_service import get_service
        skey = plan_structure_key(q.root, ticket.conf)
        key = ("serving-compile", skey if skey is not None else ticket.id)
        task = get_service(ticket.conf).submit(key, q.prewarm)
        task.wait()
        get_service(ticket.conf).take(key)

    def _upload(self, q, ticket: QueryTicket) -> int:
        """Host-IO phase: push every scan's source table through the
        shared upload cache NOW, outside the device grant, so uploads
        overlap other queries' device execution.  Returns the HBM
        working-set estimate admission schedules with."""
        src_bytes = 0
        if q.kind == "device":
            from ..exec.compiled import _shared_scan_upload
            from ..exec.plan import HostScanExec
            stack, seen = [q.root], set()
            while stack:
                n = stack.pop()
                if id(n) in seen:
                    continue
                seen.add(id(n))
                if isinstance(n, HostScanExec) and \
                        n._source_table is not None:
                    src_bytes += int(n._source_table.nbytes)
                    try:
                        _shared_scan_upload(n, ticket.conf)
                    except Exception:                # noqa: BLE001
                        pass      # the execute path re-tries with retry
                stack.extend(getattr(n, "children", ()))
        return int(src_bytes * self._ws_factor)

    # -- fair-share device scheduling --------------------------------------
    def _runnable(self, st: _TenantState) -> bool:
        """A tenant's head ticket can run now: a device slot argument is
        checked by the caller; here only the HBM-fit gate (a query that
        can never fit runs alone — progress over perfection)."""
        if not st.queue:
            return False
        est = st.queue[0]._grant_est
        if self._hbm_limit <= 0:
            return True
        used = self._device_bytes
        if self._worker_pool is not None:
            # the truthful cross-process picture: the pool's heartbeat-
            # reported DeviceCensus live bytes, reconciled against the
            # supervisor's own reservations — gate on whichever says
            # MORE (estimates can undershoot; the census can lag)
            used = max(used, self._worker_pool.live_bytes())
        if used + est <= self._hbm_limit:
            return True
        return self._device_active == 0      # too big: run it solo

    def _try_grant(self, ticket: QueryTicket) -> bool:
        """Under _cond: grant `ticket` the next device slot iff the
        weighted virtual-time scheduler (with the starvation override)
        picks it right now.  Mutates skip counters exactly once per
        actual grant."""
        if self._device_active >= self._device_slots:
            return False
        runnable = [st for st in self._tenants.values()
                    if self._runnable(st)]
        if not runnable:
            return False
        starving = [st for st in runnable
                    if st.skips >= self._starvation_bound]
        if starving:
            pick = max(starving, key=lambda s: (s.skips, -s.vtime_us))
        else:
            pick = min(runnable, key=lambda s: (s.vtime_us, s.name))
        if pick.queue[0] is not ticket:
            return False
        # commit: this ticket runs — exactly one skip bump per grant
        pick.queue.pop(0)
        ticket.skips = pick.skips
        self._max_skips = max(self._max_skips, pick.skips)
        pick.skips = 0
        for st in runnable:
            if st is not pick and st.queue:
                st.skips += 1
        self._device_active += 1
        self._device_bytes += ticket._grant_est
        return True

    @contextmanager
    def _device_grant(self, ticket: QueryTicket, est_bytes: int):
        ticket._grant_est = int(est_bytes)
        with self._cond:
            st = self._tenants[ticket.tenant]
            st.queue.append(ticket)
            # state changed: a waiter whose tenant just became the
            # scheduler's pick must re-evaluate
            self._cond.notify_all()
            while not self._try_grant(ticket):
                if self._closed:
                    st.queue.remove(ticket)
                    raise RuntimeError("ServingRuntime closed while "
                                       "waiting for a device grant")
                self._cond.wait(0.5)
        try:
            yield
        finally:
            with self._cond:
                st.vtime_us += ticket.device_us / st.weight
                st.queries += 1
                st.device_us += ticket.device_us
                self._busy_us += ticket.device_us
                self._device_active -= 1
                self._device_bytes -= ticket._grant_est
                self._cond.notify_all()
            SERVING_TENANT_DEVICE_US.inc(ticket.device_us,
                                         tenant=ticket.tenant)
            SERVING_DEVICE_BUSY_US.inc(ticket.device_us)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._cond:
            wall_s = time.perf_counter() - self._t0
            tenants = {st.name: {"weight": st.weight,
                                 "queries": st.queries,
                                 "device_us": st.device_us,
                                 "vtime_us": round(st.vtime_us, 1),
                                 "waiting": len(st.queue)}
                       for st in self._tenants.values()}
            intervals = list(self._intervals)
            busy_us = self._busy_us
            pool = self._worker_pool
            out = {"inflight": self._inflight,
                   "completed": self._completed,
                   "max_queue_depth": self._max_depth,
                   "max_skips": self._max_skips,
                   "admission_timeouts": self._admission_timeouts,
                   "ooc_admissions": self._ooc_admissions,
                   "deadline_cancellations": self._deadline_cancels,
                   "draining": self._draining,
                   "device_slots": self._device_slots,
                   "hbm_limit_bytes": self._hbm_limit,
                   "wall_s": round(wall_s, 3),
                   "device_busy_us": busy_us,
                   "device_utilization": round(
                       busy_us / 1e6 / (wall_s * self._device_slots), 4)
                   if wall_s > 0 else 0.0,
                   "tenants": tenants,
                   "result_cache": self.cache.stats()}
        from ..obs.export import bound_metrics_port
        out["metrics_port"] = bound_metrics_port()
        if pool is not None:
            out["pool"] = pool.stats()
            out["census"] = pool.census()
            # the federated fleet view: per-worker-labeled tpu_fleet_*
            # series folded from worker heartbeats (obs/registry.py)
            from ..obs.registry import FLEET
            fleet = FLEET.flat()
            if fleet:
                out["fleet"] = fleet
        out["overlap_observed"] = _overlap_observed(intervals)
        # oracle trustworthiness: per-basis estimate counts + the
        # prediction-error summary (obs/estimator.py / history plane)
        try:
            from ..obs.estimator import prediction_stats
            out["prediction"] = prediction_stats()
        except Exception:                            # noqa: BLE001
            pass
        return out

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout: float = 120.0) -> None:
        """GRACEFUL shutdown: stop admitting (new submits raise), let
        every in-flight query finish — in multi-process mode a query on
        a dying worker still REDRIVES during drain — then checkpoint
        the history store (atomic aggregate rewrite) and reap every
        worker process.  On return: empty queue, no orphans, runtime
        closed.  Unlike close(), grant waiters are NOT aborted."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.perf_counter() + timeout
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(
                        f"drain: {self._inflight} queries still in "
                        f"flight after {timeout}s")
                self._cond.wait(min(remaining, 0.5))
        from ..obs.history import get_store
        store = get_store(self._rconf)
        if store is not None:
            store.checkpoint()
        pool = self._worker_pool
        if pool is not None:
            pool.drain()                 # workers checkpoint + exit 0
            self._worker_pool = None
        self.close()

    def close(self, wait: bool = True) -> None:
        """Stop accepting queries; `wait` drains in-flight ones."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._pool.shutdown(wait=wait)
        pool = self._worker_pool
        if pool is not None:
            pool.close()
            self._worker_pool = None

    def __enter__(self) -> "ServingRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _overlap_observed(intervals: List[tuple]) -> bool:
    """True when any host-side phase (plan/compile/upload) of one query
    ran concurrently with another query's device execute — the
    structural proof the pipeline actually overlaps phases."""
    execs = [(t0, t1, tid) for name, tid, t0, t1 in intervals
             if name == "execute"]
    for name, tid, t0, t1 in intervals:
        if name == "execute":
            continue
        for e0, e1, etid in execs:
            if etid != tid and t0 < e1 and e0 < t1:
                return True
    return False
