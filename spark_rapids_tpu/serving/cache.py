"""Plan+result cache for repeated dashboard-style traffic.

Reference shape: interactive BI fleets re-issue the same parameterized
queries against slowly-changing tables ("Accelerating Presto with GPUs",
PAPERS.md); serving them from a result cache is the cheapest query the
device never runs.  The cache key reuses the compile plane's canonical
machinery (PR 7, exec/compiled.py):

  * `plan_structure_key` — the canonical constant-lifted plan structure
    (node classes, canonical expression fingerprints, conf signature,
    backend) with LIFTED literal values erased;
  * the lifted literal VALUES in canonical preorder (erased from the
    structure key, but results obviously depend on them);
  * source-table identity — the key carries `id()` of every scan's
    source table, and weakref ANCHORS invalidate the entry the moment
    any of those tables is garbage collected, so a structurally
    identical query over new data can never see stale rows.

Entries are Arrow IPC stream payloads with a CRC32: a hit deserializes a
fresh table (bit-identical to the cold run — the IPC round trip is
exact, and returning a new table means no caller can mutate the cached
copy), and checksum verification rejects damaged payloads (the
`result_cache` chaos site corrupts them deliberately) by recomputing.
Byte-capped LRU; every outcome lands in the always-on
`tpu_serving_result_cache_total` family.
"""
from __future__ import annotations

import io
import threading
import weakref
import zlib
from collections import OrderedDict
from typing import List, Optional, Tuple

import pyarrow as pa

from ..obs.registry import SERVING_RESULT_CACHE


class _Entry:
    __slots__ = ("data", "crc", "nbytes", "refs")

    def __init__(self, data: bytearray, crc: int, refs: list):
        self.data = data
        self.crc = crc
        self.nbytes = len(data)
        self.refs = refs


def result_cache_key(root, conf) -> Optional[Tuple[tuple, list]]:
    """(key, anchor objects) for a device plan, or None when the plan is
    not canonically coverable (unknown node classes, un-liftable
    shapes) — those queries simply bypass the cache."""
    from ..exec.compiled import collect_plan_literals, plan_structure_key
    skey = plan_structure_key(root, conf)
    if skey is None:
        return None
    lits = collect_plan_literals(root)
    if lits is None:
        return None
    lit_vals = tuple((type(e.value).__name__, repr(e.value), repr(e.dtype))
                     for e in lits)
    anchors = _source_tables(root)
    key = (skey, lit_vals, tuple(id(a) for a in anchors))
    return key, anchors


def _source_tables(root) -> list:
    from ..exec.plan import HostScanExec
    out, stack, seen = [], [root], set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if isinstance(n, HostScanExec) and n._source_table is not None:
            out.append(n._source_table)
        stack.extend(getattr(n, "children", ()))
    return out


class ResultCache:
    """Byte-capped LRU of serialized query results (cap 0 disables)."""

    def __init__(self, cap_bytes: int):
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0

    # -- stats -------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "cap_bytes": self.cap_bytes}

    # -- read --------------------------------------------------------------
    def get(self, key, injector=None) -> Optional[pa.Table]:
        """The cached result table, or None (miss / anchor died /
        checksum mismatch — each with its own outcome count)."""
        if key is None or self.cap_bytes == 0:
            return None
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._entries[key] = entry          # re-insert: now MRU
        if entry is None:
            SERVING_RESULT_CACHE.inc(outcome="miss")
            return None
        if any(r() is None for r in entry.refs):
            # an anchor died between the weakref callback queueing and
            # now — treat as invalidated, never serve stale data
            self._drop(key, entry, "invalidate")
            return None
        if injector is not None:
            # chaos `result_cache` site: kind corrupt flips a byte in
            # THIS entry's payload so the verification below is real
            injector.fire("result_cache", payload=entry.data)
        if zlib.crc32(bytes(entry.data)) != entry.crc:
            self._drop(key, entry, "corrupt")
            return None
        table = pa.ipc.open_stream(io.BytesIO(bytes(entry.data))).read_all()
        SERVING_RESULT_CACHE.inc(outcome="hit")
        return table

    # -- write -------------------------------------------------------------
    def put(self, key, table: pa.Table, anchors: List[object]) -> bool:
        if key is None or self.cap_bytes == 0:
            return False
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, table.schema) as w:
            w.write_table(table)
        data = bytearray(sink.getvalue())
        if len(data) > self.cap_bytes:
            return False                 # bigger than the whole cache
        try:
            refs = [weakref.ref(a, lambda _r, k=key: self.invalidate(k))
                    for a in anchors]
        except TypeError:
            return False                 # un-weakref-able anchor
        entry = _Entry(data, zlib.crc32(bytes(data)), refs)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += entry.nbytes
            while self._bytes > self.cap_bytes and len(self._entries) > 1:
                k, e = next(iter(self._entries.items()))
                if k == key:             # never evict the fresh entry
                    break
                del self._entries[k]
                self._bytes -= e.nbytes
                SERVING_RESULT_CACHE.inc(outcome="evict")
        SERVING_RESULT_CACHE.inc(outcome="store")
        return True

    # -- invalidation ------------------------------------------------------
    def invalidate(self, key) -> None:
        """Drop one entry (weakref death callback / explicit)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
        if entry is not None:
            SERVING_RESULT_CACHE.inc(outcome="invalidate")

    def _drop(self, key, entry: _Entry, outcome: str) -> None:
        with self._lock:
            cur = self._entries.pop(key, None)
            if cur is entry:
                self._bytes -= entry.nbytes
            elif cur is not None:        # replaced concurrently: keep it
                self._entries[key] = cur
        SERVING_RESULT_CACHE.inc(outcome=outcome)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
