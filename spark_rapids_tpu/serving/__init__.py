"""Concurrent serving plane: multi-tenant admission, fair-share device
scheduling and a plan+result cache over the single-query engine.

Entry point: `TpuSession.serving()` -> ServingRuntime;
`runtime.tenant(name, weight)` -> TenantSession handles.  See
docs/SERVING.md for the architecture walkthrough.
"""
from .cache import ResultCache
from .runtime import (AdmissionTimeout, InjectedAdmissionTimeout,
                      QueryTicket, ServingRuntime, TenantSession)

__all__ = ["AdmissionTimeout", "InjectedAdmissionTimeout", "QueryTicket",
           "ResultCache", "ServingRuntime", "TenantSession"]
