"""Fault-isolated multi-process serving: the supervised worker pool.

One Python process is one fault domain: a fatal XLA error, a native
crash or a SIGKILL takes down every tenant it hosts, and throughput
cannot scale past one GIL.  The reference engine survives this class of
failure *structurally* — Spark retries tasks and replaces dead
executors; Theseus (PAPERS.md) runs a distributed GPU query platform
whose worker processes are replaceable units behind one admission
plane.  Our queries are read-only and deterministic with oracle-checked
results, so REDRIVE-ON-CRASH is safe by construction.

This module is both sides of that boundary:

  * `WorkerPool` — the SUPERVISOR, embedded in the ServingRuntime when
    `serving.pool.processes` > 0: spawns N worker processes, dispatches
    admitted queries to the least-loaded live worker over an
    authenticated local socket (the plugin/worker.py framing), consumes
    heartbeats (pid, in-flight query, DeviceCensus totals, bound
    metrics port), detects death three ways (connection EOF, process
    exit, heartbeat-miss window) and REDRIVES the dead worker's
    in-flight queries on survivors up to `serving.redrive.maxAttempts`.
    With `serving.pool.restart` it spawns a replacement so the pool
    holds its size.
  * `main()` — the WORKER: builds its own TpuSession (own MemoryBudget,
    own device slice, own metrics plane) from the conf the supervisor
    ships, shares the PERSISTENT compile cache and history store with
    its siblings (topology-keyed dirs; JSONL appends and the aggregate
    summary rewrite are multi-process safe), executes one query per
    request under the full single-query substrate (crash_capture, retry
    ladders, OOC tier), and SELF-TERMINATES after a classified
    FATAL_DEVICE dump — the Plugin.scala executor-self-termination
    contract, with the supervisor as the cluster manager that replaces
    it.

Chaos (`worker:{kill,hang,fatal}:trigger`, runtime/faults.py) fires
SUPERVISOR-side at dispatch so nth= triggers stay deterministic across
the pool; `kill` SIGKILLs the victim the moment its `started` frame
confirms the query is mid-flight, `hang` wedges it (the heartbeat-miss
window detects it), `fatal` arms the in-worker fatal injector.  All
three lose only the victim's in-flight queries, which redrive
bit-identically while other tenants' queries complete uninterrupted.

Graceful drain: the runtime stops admitting, in-flight queries finish
or redrive, then every worker checkpoints the history store (atomic
aggregate rewrite) and exits 0 — no orphan processes, nothing lost.
"""
from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..config import (SERVING_POOL_HEARTBEAT_MISSES,
                      SERVING_POOL_HEARTBEAT_MS, SERVING_POOL_RESTART,
                      SERVING_REDRIVE_MAX, TpuConf)
from ..plugin.worker import recv_frame, send_frame

_ENV_ID = "SPARK_RAPIDS_TPU_WORKER_ID"
_ENV_ADDR = "SPARK_RAPIDS_TPU_WORKER_ADDR"
_ENV_TOKEN = "SPARK_RAPIDS_TPU_WORKER_TOKEN"

#: worker exit codes: the supervisor's restart accounting reads these
EXIT_DRAINED = 0
EXIT_FATAL = 13


class WorkerLost(RuntimeError):
    """A dispatched query's worker process died before answering
    (crash / SIGKILL / hang-kill / fatal self-termination).  Caught by
    the redrive loop, never by client code."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


class ServingWorkerError(RuntimeError):
    """A query exhausted `serving.redrive.maxAttempts` worker losses —
    the terminal form the ticket fails with."""


def _frame(obj: dict) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def _unframe(data: bytes) -> dict:
    return pickle.loads(data)


# ===========================================================================
# Supervisor side
# ===========================================================================

class _Dispatch:
    """One in-flight query on one worker (supervisor bookkeeping)."""

    __slots__ = ("qid", "event", "reply", "lost", "kill_on_start",
                 "started", "ticket_info")

    def __init__(self, qid: int, kill_on_start: bool = False,
                 ticket_info: Optional[dict] = None):
        self.qid = qid
        self.event = threading.Event()
        self.reply: Optional[dict] = None
        self.lost: Optional[WorkerLost] = None
        self.kill_on_start = kill_on_start
        self.started = threading.Event()
        # the supervisor's view of the dispatched ticket (tenant,
        # attempt, deadline, prediction) — embedded into the WorkerLost
        # forensics dump when the worker dies holding this query
        self.ticket_info = dict(ticket_info or {})


class _WorkerHandle:
    """Supervisor-side state for one worker process."""

    def __init__(self, wid: str, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc
        self.conn: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.pid: Optional[int] = None
        self.metrics_port: Optional[int] = None
        self.ready = threading.Event()       # hello received, conf sent
        self.alive = False                   # ready and not declared dead
        self.last_hb = time.monotonic()
        self.census: Dict[str, int] = {"live_bytes": 0, "peak_bytes": 0}
        self.inflight: Dict[int, _Dispatch] = {}     # qid -> dispatch
        self.draining = False
        # the worker's last heartbeat-carried flight-recorder snapshot
        # (black box): embedded into the WorkerLost dump on kill/hang —
        # the cases where the victim cannot write its own dump
        self.flight: List[dict] = []

    def send(self, obj: dict) -> None:
        with self.send_lock:
            send_frame(self.conn, _frame(obj))


class WorkerPool:
    """Supervises `procs` worker processes behind the admission front
    (serving/runtime.py owns admission, conf snapshots, fair-share
    grants and tickets; the pool owns dispatch, health, redrive, and
    the cross-process census picture)."""

    def __init__(self, rconf: TpuConf, conf_raw: dict, procs: int):
        self._rconf = rconf
        self._conf_raw = dict(conf_raw)
        self.procs = int(procs)
        self._hb_s = float(rconf.get(SERVING_POOL_HEARTBEAT_MS)) / 1e3
        self._hb_misses = int(rconf.get(SERVING_POOL_HEARTBEAT_MISSES))
        self._restart = bool(rconf.get(SERVING_POOL_RESTART))
        self._redrive_max = int(rconf.get(SERVING_REDRIVE_MAX))
        self._cond = threading.Condition()
        self._workers: Dict[str, _WorkerHandle] = {}
        self._wid_seq = 0
        self._srv: Optional[socket.socket] = None
        self._token = b""
        self._closed = False
        self._draining = False
        self._restarts: Dict[str, int] = {}          # reason -> count
        self._redrives = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, timeout: float = 120.0) -> "WorkerPool":
        import secrets
        self._token = secrets.token_hex(16).encode()
        self._srv = socket.create_server(("127.0.0.1", 0))
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="tpu-pool-accept").start()
        threading.Thread(target=self._monitor_loop, daemon=True,
                         name="tpu-pool-monitor").start()
        for _ in range(self.procs):
            self._spawn()
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._live_count() < self.procs:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.close()
                    raise RuntimeError(
                        f"serving worker pool: only {self._live_count()}"
                        f"/{self.procs} workers came up in {timeout}s")
                self._cond.wait(min(remaining, 0.5))
        return self

    def _spawn(self) -> _WorkerHandle:
        with self._cond:
            self._wid_seq += 1
            wid = f"w{self._wid_seq}"
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env[_ENV_ID] = wid
        env[_ENV_ADDR] = "%s:%d" % self._srv.getsockname()
        env[_ENV_TOKEN] = self._token.decode()
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.serving.workers"],
            env=env, stdin=subprocess.DEVNULL)
        h = _WorkerHandle(wid, proc)
        with self._cond:
            self._workers[wid] = h
        return h

    def _accept_loop(self) -> None:
        import hmac
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            try:
                hello = recv_frame(conn)
                if hello is None:
                    conn.close()
                    continue
                msg = _unframe(hello)
                if not hmac.compare_digest(
                        msg.get("token", "").encode(), self._token):
                    conn.close()
                    continue
                wid = msg["worker_id"]
                with self._cond:
                    h = self._workers.get(wid)
                if h is None:
                    conn.close()
                    continue
                h.conn = conn
                h.pid = msg.get("pid")
                h.metrics_port = msg.get("metrics_port")
                h.send({"op": "conf", "conf": self._conf_raw,
                        "hb_ms": self._hb_s * 1e3})
                h.last_hb = time.monotonic()
                with self._cond:
                    h.alive = True
                    h.ready.set()
                    self._cond.notify_all()
                self._set_live_gauge()
                threading.Thread(target=self._reader_loop, args=(h,),
                                 daemon=True,
                                 name=f"tpu-pool-read-{wid}").start()
            except Exception:                        # noqa: BLE001
                try:
                    conn.close()
                except OSError:
                    pass

    def _reader_loop(self, h: _WorkerHandle) -> None:
        from ..obs.registry import SERVING_WORKER_HEARTBEATS
        while True:
            try:
                data = recv_frame(h.conn)
            except OSError:
                data = None
            if data is None:
                self._declare_dead(h, "crash")
                return
            try:
                msg = _unframe(data)
            except Exception:                        # noqa: BLE001
                self._declare_dead(h, "crash")
                return
            op = msg.get("op")
            if op == "hb":
                h.last_hb = time.monotonic()
                h.census = dict(msg.get("census") or {})
                if msg.get("metrics_port") is not None:
                    h.metrics_port = msg["metrics_port"]
                SERVING_WORKER_HEARTBEATS.inc()
                self._fold_telemetry(h, msg)
            elif op == "started":
                if msg.get("flight"):
                    h.flight = list(msg["flight"])
                d = h.inflight.get(msg.get("qid"))
                if d is not None:
                    d.started.set()
                    if d.kill_on_start:
                        # worker:kill — the victim is now PROVABLY
                        # mid-query; lose the whole process
                        try:
                            h.proc.kill()
                        except OSError:
                            pass
            elif op in ("result", "error"):
                qid = msg.get("qid")
                d = h.inflight.pop(qid, None)
                if op == "error" and \
                        msg.get("classification") == "fatal_device":
                    # the worker wrote its classified dump and is
                    # self-terminating: its query REDRIVES (the dump
                    # names the pid; the redrive conf carries no
                    # injected fatal), exactly like a plain crash
                    if d is not None:
                        d.lost = WorkerLost(
                            f"worker {h.wid} hit a fatal device error "
                            f"(dump: {msg.get('dump_path')})", "fatal")
                        d.event.set()
                    self._declare_dead(h, "fatal")
                    return
                if d is not None:
                    d.reply = msg
                    d.event.set()
                with self._cond:
                    self._cond.notify_all()
            elif op == "drained":
                h.draining = True
                with self._cond:
                    self._cond.notify_all()

    def _monitor_loop(self) -> None:
        while not self._closed:
            time.sleep(self._hb_s)
            now = time.monotonic()
            with self._cond:
                handles = list(self._workers.values())
            for h in handles:
                if not h.alive:
                    continue
                if h.proc.poll() is not None:
                    self._declare_dead(h, "crash")
                elif not h.draining and \
                        now - h.last_hb > self._hb_s * self._hb_misses:
                    # hung: heartbeats stopped but the process lives —
                    # SIGKILL it and treat exactly like a crash
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
                    self._declare_dead(h, "hang")

    def _fold_telemetry(self, h: _WorkerHandle, msg: dict) -> None:
        """Metrics federation + black-box fold of one heartbeat frame.
        The `fleet` chaos site fires here, SUPERVISOR-side, once per
        telemetry-carrying frame: ioerror drops THIS frame whole
        (cumulative-set federation converges on the next beat, the
        in-flight query untouched); fatal writes a classified dump
        naming the site and drops the frame — the supervisor (and the
        pool) survive, telemetry never takes serving down."""
        if msg.get("registry") is None and msg.get("flight") is None:
            return
        from ..obs.registry import (FLEET_FRAMES, fold_fleet_snapshot)
        from ..runtime.faults import get_injector
        try:
            get_injector(self._rconf).fire("fleet", worker=h.wid)
        except OSError:
            FLEET_FRAMES.inc(outcome="dropped")
            return
        except Exception as exc:                     # noqa: BLE001
            from ..runtime.failure import (FATAL_DEVICE, classify,
                                           write_crash_dump)
            if classify(exc) == FATAL_DEVICE:
                try:
                    write_crash_dump(self._rconf, exc)
                except Exception:                    # noqa: BLE001
                    pass
                FLEET_FRAMES.inc(outcome="dropped")
                return
            FLEET_FRAMES.inc(outcome="error")
            return
        try:
            if msg.get("registry") is not None:
                fold_fleet_snapshot(h.wid, msg["registry"])
            if msg.get("flight") is not None:
                h.flight = list(msg["flight"])
            FLEET_FRAMES.inc(outcome="folded")
        except Exception:                            # noqa: BLE001
            # a malformed frame must never kill the reader loop (the
            # worker would be declared dead over telemetry)
            FLEET_FRAMES.inc(outcome="error")

    def _declare_dead(self, h: _WorkerHandle, reason: str) -> None:
        from ..obs.registry import SERVING_WORKER_RESTARTS
        with self._cond:
            if not h.alive and h.ready.is_set():
                return                   # already handled
            h.alive = False
            self._workers.pop(h.wid, None)
            pending = list(h.inflight.values())
            h.inflight.clear()
            # A worker exiting while the pool drains/closes is a CLEAN
            # shutdown racing the reaper, not a loss: no restart count,
            # no black-box dump.
            shutdown = self._draining or self._closed
            if not shutdown:
                self._restarts[reason] = self._restarts.get(reason, 0) + 1
            self._cond.notify_all()
        if not shutdown:
            SERVING_WORKER_RESTARTS.inc(reason=reason)
        self._set_live_gauge()
        # fleet federation: the dead worker's GAUGE series (point-in-
        # time state) die with the process; its counters — cumulative
        # work the fleet did — stay.  A restarted replacement publishes
        # under a fresh worker id.
        try:
            from ..obs.registry import drop_fleet_worker
            drop_fleet_worker(h.wid)
        except Exception:                            # noqa: BLE001
            pass
        # BLACK-BOX forensics: on kill/hang the victim could not write
        # its own dump — embed its last heartbeat-carried flight
        # snapshot + the in-flight ticket state supervisor-side
        if not shutdown:
            try:
                from ..runtime.failure import write_worker_lost_dump
                write_worker_lost_dump(
                    self._rconf, h.wid, h.pid, reason,
                    flight=list(h.flight), census=dict(h.census),
                    inflight=[dict(d.ticket_info, qid=d.qid,
                                   started=d.started.is_set())
                              for d in pending])
            except Exception:                        # noqa: BLE001
                pass              # forensics must never break redrive
        try:
            if h.conn is not None:
                h.conn.close()
        except OSError:
            pass
        for d in pending:
            if d.lost is None:
                d.lost = WorkerLost(
                    f"worker {h.wid} (pid {h.pid}) died mid-query "
                    f"({reason})", reason)
            d.event.set()
        if self._restart and not self._draining and not self._closed:
            self._spawn()

    def _set_live_gauge(self) -> None:
        from ..obs.registry import SERVING_WORKERS_LIVE
        SERVING_WORKERS_LIVE.set(self._live_count())

    def _live_count(self) -> int:
        return sum(1 for h in list(self._workers.values()) if h.alive)

    # -- dispatch ----------------------------------------------------------
    def _pick(self, timeout: float = 60.0) -> _WorkerHandle:
        """The least-loaded live worker (blocks for a restart when the
        whole pool is momentarily down)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                live = [h for h in self._workers.values()
                        if h.alive and not h.draining]
                if live:
                    return min(live, key=lambda h: (len(h.inflight),
                                                    h.wid))
                if self._closed or self._draining:
                    raise ServingWorkerError("worker pool is closed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ServingWorkerError(
                        f"no live serving worker within {timeout}s")
                self._cond.wait(min(remaining, 0.5))

    def execute(self, ticket, injector, deadline_ms: float = 0.0,
                tracer=None):
        """Run one admitted query on the pool: dispatch, await, REDRIVE
        on worker loss up to serving.redrive.maxAttempts.  Returns
        (pa.Table, device_us).  Chaos `worker` fires here, supervisor-
        side, once per dispatch.  With a (stitched) tracer, each
        attempt is one `execute@<wid>` span and each loss a
        `worker_lost` instant — the redrive chain renders as retry
        spans naming both workers."""
        from ..obs.registry import SERVING_REDRIVES
        from ..runtime.faults import InjectedWorkerFault
        losses = 0
        pred = dict(ticket.predicted or {})
        while True:
            attempt = losses
            fault_kind = None
            try:
                injector.fire("worker", query=ticket.id,
                              tenant=ticket.tenant)
            except InjectedWorkerFault as f:
                fault_kind = f.kind
            h = self._pick()
            d = _Dispatch(ticket.id,
                          kill_on_start=(fault_kind == "kill"),
                          ticket_info={
                              "tenant": ticket.tenant,
                              "attempt": attempt,
                              "deadline_ms": float(deadline_ms or 0.0),
                              "ooc": bool(ticket.ooc),
                              "predicted_us": int(
                                  pred.get("device_us") or 0)})
            extra = {}
            if fault_kind == "fatal":
                # arm the in-worker fatal injector for THIS dispatch
                # only — the redrive conf is clean
                extra["spark.rapids.tpu.test.injectFatalError"] = "1"
            h.inflight[ticket.id] = d
            t0 = time.perf_counter()
            try:
                h.send({"op": "query", "qid": ticket.id,
                        "plan": ticket.plan, "extra": extra,
                        "deadline_ms": float(deadline_ms or 0.0),
                        "ooc": bool(ticket.ooc),
                        "hang": fault_kind == "hang",
                        # the supervisor's GLOBAL ticket context: the
                        # worker tracer adopts the id (key-exact event
                        # logs) and stamps the serving.* metrics
                        "ctx": {"query_id": ticket.id,
                                "tenant": ticket.tenant,
                                "attempt": attempt,
                                "admit_wait_ms": round(
                                    ticket.admit_wait_ms, 3),
                                "predicted": {
                                    "device_us": pred.get("device_us"),
                                    "basis": pred.get("basis")}}})
            except (OSError, pickle.PicklingError) as e:
                h.inflight.pop(ticket.id, None)
                if isinstance(e, pickle.PicklingError):
                    raise
                d.lost = WorkerLost(f"worker {h.wid} unreachable "
                                    f"at dispatch: {e}", "crash")
                d.event.set()
            while not d.event.wait(0.5):
                pass
            t1 = time.perf_counter()
            if d.lost is None:
                msg = d.reply
                if msg["op"] == "result":
                    ticket.worker = h.wid
                    ticket.worker_profile = msg.get("profile")
                    if tracer is not None and \
                            getattr(tracer, "enabled", False):
                        tracer.add_span(f"execute@{h.wid}", "execute",
                                        t0, t1, worker=h.wid,
                                        attempt=attempt,
                                        device_us=int(
                                            msg.get("device_us") or 0))
                    return msg["table"], int(msg.get("device_us") or 0)
                exc = msg.get("exc")
                if exc is None:
                    exc = RuntimeError(
                        f"[worker {h.wid}] {msg.get('error_class')}: "
                        f"{msg.get('message')}")
                raise exc
            # worker loss: redrive on a survivor, bit-identically —
            # queries are read-only and deterministic
            losses += 1
            ticket.redrives = losses
            SERVING_REDRIVES.inc(reason=d.lost.reason)
            if tracer is not None and getattr(tracer, "enabled", False):
                tracer.add_span(f"execute@{h.wid}", "execute", t0, t1,
                                worker=h.wid, attempt=attempt,
                                lost=d.lost.reason)
                tracer.instant("worker_lost", "serving", worker=h.wid,
                               reason=d.lost.reason, attempt=attempt)
            with self._cond:
                self._redrives += 1
            if losses > self._redrive_max:
                raise ServingWorkerError(
                    f"query #{ticket.id} lost its worker {losses} times "
                    f"(> serving.redrive.maxAttempts="
                    f"{self._redrive_max}); last: {d.lost}") \
                    from d.lost

    # -- the cross-process HBM picture ------------------------------------
    def live_bytes(self) -> int:
        with self._cond:
            return sum(int(h.census.get("live_bytes") or 0)
                       for h in self._workers.values() if h.alive)

    def census(self) -> dict:
        with self._cond:
            per = {h.wid: {"pid": h.pid,
                           "live_bytes": int(
                               h.census.get("live_bytes") or 0),
                           "peak_bytes": int(
                               h.census.get("peak_bytes") or 0)}
                   for h in self._workers.values() if h.alive}
        return {"live_bytes": sum(w["live_bytes"] for w in per.values()),
                "peak_bytes": sum(w["peak_bytes"] for w in per.values()),
                "workers": per}

    def stats(self) -> dict:
        with self._cond:
            now = time.monotonic()
            workers = {h.wid: {"pid": h.pid,
                               "inflight": len(h.inflight),
                               "metrics_port": h.metrics_port,
                               "last_heartbeat_ms": round(
                                   (now - h.last_hb) * 1e3, 1)}
                       for h in self._workers.values() if h.alive}
            return {"processes": self.procs,
                    "live": len(workers),
                    "restarts": dict(self._restarts),
                    "redrives": self._redrives,
                    "workers": workers}

    # -- drain / close -----------------------------------------------------
    def drain(self, timeout: float = 60.0) -> None:
        """Graceful: every worker checkpoints the history store (atomic
        aggregate rewrite) and exits 0; the supervisor reaps them all —
        no orphan processes."""
        with self._cond:
            self._draining = True
            handles = [h for h in self._workers.values() if h.alive]
        for h in handles:
            try:
                h.send({"op": "drain"})
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        for h in handles:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                h.proc.wait(remaining)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(5.0)
            with self._cond:
                h.alive = False
                self._workers.pop(h.wid, None)
        self._set_live_gauge()
        self.close()

    def close(self) -> None:
        self._closed = True
        with self._cond:
            handles = list(self._workers.values())
            self._workers.clear()
            self._cond.notify_all()
        for h in handles:
            try:
                h.proc.kill()
            except OSError:
                pass
            try:
                h.proc.wait(5.0)
            except Exception:                        # noqa: BLE001
                pass
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        self._set_live_gauge()


# ===========================================================================
# Worker side
# ===========================================================================

def _worker_heartbeat(conn, send_lock: threading.Lock, hb_s: float,
                      stop: threading.Event, state: dict) -> None:
    from ..obs.export import bound_metrics_port
    from ..obs.memattr import CENSUS
    from ..obs.recorder import FLIGHT_RECORDER, tail_bounded
    from ..obs.registry import REGISTRY
    # First beat goes out IMMEDIATELY: a worker killed early in its
    # first query must already have shipped a black-box snapshot.
    while True:
        msg = {"op": "hb", "pid": os.getpid(),
               "census": CENSUS.totals(),
               "metrics_port": bound_metrics_port(),
               "inflight": state.get("qid")}
        tel = state.get("telemetry")
        if tel:
            k_events, max_bytes = tel
            # federation piggyback: the FULL cumulative registry
            # snapshot (set semantics supervisor-side make a dropped
            # frame self-heal) + the rolling black-box flight tail.
            # Liveness first: trim the flight, then drop it, then drop
            # the registry — the bare heartbeat always goes out.
            msg["registry"] = REGISTRY.snapshot()
            msg["flight"] = tail_bounded(FLIGHT_RECORDER, k_events,
                                         max(max_bytes // 4, 1024))
            if len(_frame(msg)) > max_bytes:
                msg["flight"] = []
                if len(_frame(msg)) > max_bytes:
                    msg.pop("registry")
        try:
            with send_lock:
                send_frame(conn, _frame(msg))
        except OSError:
            # supervisor is gone: a worker must never outlive it
            os._exit(EXIT_DRAINED)
        if stop.wait(hb_s):
            return


def _profile_summary(ctx, device_us: int, wid: str) -> dict:
    """Compact, jsonable span-tree/profile summary the completion frame
    carries home: wall breakdown (overhead.*), memory attribution
    (memory.*), serving/prediction context and the worker's event-log
    path — the supervisor folds it into the stitched record's meta."""
    from ..obs.memattr import CENSUS
    out = {"worker": wid, "pid": os.getpid(), "device_us": device_us,
           "hbm": CENSUS.totals()}
    keep = {}
    for k, v in (ctx.metrics or {}).items():
        if not isinstance(v, (int, float, str, bool)) and v is not None:
            continue
        if k.startswith(("overhead.", "memory.", "serving.",
                         "predicted.", "seg.")):
            keep[k] = v
    out["metrics"] = keep
    logf = ctx.metrics.get("event_log_files")
    if isinstance(logf, dict):
        out["event_log"] = logf.get("jsonl")
    return out


def _run_one(session, base_raw: dict, req: dict) -> dict:
    """Execute one dispatched query under the full single-query
    substrate (crash_capture, retry ladders, OOC tier, history feed)."""
    from ..exec.plan import ExecContext, cancel_scope
    from ..plan.overrides import apply_overrides
    extra = req.get("extra") or {}
    conf = TpuConf({**base_raw, **extra}) if extra else session.conf
    q = apply_overrides(req["plan"], conf)
    ctx = ExecContext(conf)
    ctx.arm_deadline(float(req.get("deadline_ms") or 0.0))
    if req.get("ooc"):
        ctx.ooc_force = True
    wid = os.environ.get(_ENV_ID, "w?")
    dctx = req.get("ctx") or {}
    if dctx:
        # the supervisor's ticket context rides ctx.metrics into the
        # instrumented scope: the tracer adopts the GLOBAL query id
        # (plan/overrides.py — the event log becomes query_<gid>.jsonl,
        # key-exact for stitching) and the serving.* keys land in the
        # trace meta + history record
        if dctx.get("query_id") is not None:
            ctx.metrics["serving.query_id"] = int(dctx["query_id"])
        if dctx.get("tenant"):
            ctx.metrics["serving.tenant"] = str(dctx["tenant"])
        ctx.metrics["serving.worker"] = wid
        ctx.metrics["serving.attempt"] = int(dctx.get("attempt") or 0)
        if dctx.get("admit_wait_ms") is not None:
            ctx.metrics["serving.admit_wait_ms"] = dctx["admit_wait_ms"]
        pred = dctx.get("predicted") or {}
        if pred.get("device_us") is not None:
            ctx.metrics["predicted.device_us"] = int(pred["device_us"])
            ctx.metrics["predicted.basis"] = str(pred.get("basis")
                                                 or "?")
    t0 = time.perf_counter()
    with cancel_scope(ctx):
        out = q.collect(ctx)
    device_us = int((time.perf_counter() - t0) * 1e6)
    tenant = dctx.get("tenant")
    if tenant:
        # publish the SAME integer the supervisor's grant publishes for
        # this ticket, so the fleet's per-worker tenant device-us sums
        # to the supervisor's per-tenant counter EXACTLY (the PR 10
        # hammer invariant, now across the socket)
        from ..obs.registry import SERVING_TENANT_DEVICE_US
        SERVING_TENANT_DEVICE_US.inc(device_us, tenant=str(tenant))
    return {"op": "result", "qid": req["qid"], "table": out,
            "device_us": device_us,
            "profile": _profile_summary(ctx, device_us, wid)}


def main() -> int:
    wid = os.environ.get(_ENV_ID, "w?")
    host, port = os.environ[_ENV_ADDR].rsplit(":", 1)
    token = os.environ.get(_ENV_TOKEN, "")
    conn = socket.create_connection((host, int(port)))
    send_lock = threading.Lock()
    from ..obs.export import bound_metrics_port
    send_frame(conn, _frame({"op": "hello", "token": token,
                             "worker_id": wid, "pid": os.getpid(),
                             "metrics_port": bound_metrics_port()}))
    cfg = _unframe(recv_frame(conn))
    base_raw = dict(cfg["conf"])
    # failure.py registers its conf keys (coredump.path, the fatal
    # injector) at import — they must exist before the shipped conf
    # (already validated supervisor-side) is re-validated here
    from ..runtime.failure import classify          # noqa: F401
    # this worker owns its own session: budget, device slice, metrics
    # plane, and the SHARED persistent compile cache + history store
    from ..session import TpuSession
    session = TpuSession(base_raw)
    state: dict = {"qid": None}
    from ..config import (SERVING_POOL_TELEMETRY_ENABLED,
                          SERVING_POOL_TELEMETRY_FLIGHT_EVENTS,
                          SERVING_POOL_TELEMETRY_MAX_FRAME_BYTES)
    if bool(session.conf.get(SERVING_POOL_TELEMETRY_ENABLED)):
        state["telemetry"] = (
            int(session.conf.get(SERVING_POOL_TELEMETRY_FLIGHT_EVENTS)),
            int(session.conf.get(SERVING_POOL_TELEMETRY_MAX_FRAME_BYTES)))
    stop_hb = threading.Event()
    threading.Thread(target=_worker_heartbeat,
                     args=(conn, send_lock, float(cfg["hb_ms"]) / 1e3,
                           stop_hb, state),
                     daemon=True, name="tpu-worker-hb").start()
    while True:
        try:
            data = recv_frame(conn)
        except OSError:
            # supervisor died mid-frame (SIGKILL'd, crashed): same exit
            # as a clean EOF — a worker never outlives its supervisor
            return EXIT_DRAINED
        if data is None:
            return EXIT_DRAINED            # supervisor closed the pool
        req = _unframe(data)
        op = req.get("op")
        if op == "drain":
            # checkpoint the shared history store (atomic aggregate
            # rewrite) so a restart/deploy loses no folded history
            from ..obs.history import get_store
            store = get_store(session.conf)
            if store is not None:
                store.checkpoint()
            session.close()
            with send_lock:
                send_frame(conn, _frame({"op": "drained"}))
            return EXIT_DRAINED
        if op != "query":
            continue
        state["qid"] = req["qid"]
        started = {"op": "started", "qid": req["qid"],
                   "pid": os.getpid()}
        tel = state.get("telemetry")
        if tel:
            # black-box determinism: a dispatch instant + the current
            # flight tail ride the started frame itself, so a worker
            # killed mid-query — even its FIRST, milliseconds in —
            # always leaves a snapshot naming the query it died on
            from ..obs.recorder import FLIGHT_RECORDER, tail_bounded
            FLIGHT_RECORDER.record(
                "instant", "serving_dispatch", "serving",
                attrs={"qid": req["qid"],
                       "tenant": (req.get("ctx") or {}).get("tenant")},
                query=req["qid"])
            k_events, max_bytes = tel
            started["flight"] = tail_bounded(
                FLIGHT_RECORDER, k_events, max(max_bytes // 4, 1024))
        with send_lock:
            send_frame(conn, _frame(started))
        if req.get("hang"):
            # chaos worker:hang — wedge: heartbeats stop, requests
            # stop; the supervisor's miss window kills this process
            stop_hb.set()
            while True:
                time.sleep(60.0)
        try:
            reply = _run_one(session, base_raw, req)
        except BaseException as exc:                 # noqa: BLE001
            cls = classify(exc)
            reply = {"op": "error", "qid": req["qid"],
                     "classification": cls,
                     "error_class": type(exc).__name__,
                     "message": str(exc),
                     "dump_path": getattr(exc, "dump_path", None)}
            try:
                pickle.dumps(exc)
                reply["exc"] = exc
            except Exception:                        # noqa: BLE001
                pass                  # supervisor rebuilds from message
            with send_lock:
                send_frame(conn, _frame(reply))
            if cls == "fatal_device":
                # executor self-termination (Plugin.scala contract):
                # the dump is written, the error frame is out — exit so
                # the supervisor replaces this process
                conn.close()
                os._exit(EXIT_FATAL)
            state["qid"] = None
            continue
        with send_lock:
            send_frame(conn, _frame(reply))
        state["qid"] = None


if __name__ == "__main__":
    raise SystemExit(main())
