"""Fault-isolated multi-process serving: the supervised worker pool.

One Python process is one fault domain: a fatal XLA error, a native
crash or a SIGKILL takes down every tenant it hosts, and throughput
cannot scale past one GIL.  The reference engine survives this class of
failure *structurally* — Spark retries tasks and replaces dead
executors; Theseus (PAPERS.md) runs a distributed GPU query platform
whose worker processes are replaceable units behind one admission
plane.  Our queries are read-only and deterministic with oracle-checked
results, so REDRIVE-ON-CRASH is safe by construction.

This module is both sides of that boundary:

  * `WorkerPool` — the SUPERVISOR, embedded in the ServingRuntime when
    `serving.pool.processes` > 0: spawns N worker processes, dispatches
    admitted queries to the least-loaded live worker over an
    authenticated local socket (the plugin/worker.py framing), consumes
    heartbeats (pid, in-flight query, DeviceCensus totals, bound
    metrics port), detects death three ways (connection EOF, process
    exit, heartbeat-miss window) and REDRIVES the dead worker's
    in-flight queries on survivors up to `serving.redrive.maxAttempts`.
    With `serving.pool.restart` it spawns a replacement so the pool
    holds its size.
  * `main()` — the WORKER: builds its own TpuSession (own MemoryBudget,
    own device slice, own metrics plane) from the conf the supervisor
    ships, shares the PERSISTENT compile cache and history store with
    its siblings (topology-keyed dirs; JSONL appends and the aggregate
    summary rewrite are multi-process safe), executes one query per
    request under the full single-query substrate (crash_capture, retry
    ladders, OOC tier), and SELF-TERMINATES after a classified
    FATAL_DEVICE dump — the Plugin.scala executor-self-termination
    contract, with the supervisor as the cluster manager that replaces
    it.

Chaos (`worker:{kill,hang,fatal}:trigger`, runtime/faults.py) fires
SUPERVISOR-side at dispatch so nth= triggers stay deterministic across
the pool; `kill` SIGKILLs the victim the moment its `started` frame
confirms the query is mid-flight, `hang` wedges it (the heartbeat-miss
window detects it), `fatal` arms the in-worker fatal injector.  All
three lose only the victim's in-flight queries, which redrive
bit-identically while other tenants' queries complete uninterrupted.

Graceful drain: the runtime stops admitting, in-flight queries finish
or redrive, then every worker checkpoints the history store (atomic
aggregate rewrite) and exits 0 — no orphan processes, nothing lost.
"""
from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..config import (SERVING_POOL_HEARTBEAT_MISSES,
                      SERVING_POOL_HEARTBEAT_MS, SERVING_POOL_RESTART,
                      SERVING_REDRIVE_MAX, TpuConf)
from ..plugin.worker import recv_frame, send_frame

_ENV_ID = "SPARK_RAPIDS_TPU_WORKER_ID"
_ENV_ADDR = "SPARK_RAPIDS_TPU_WORKER_ADDR"
_ENV_TOKEN = "SPARK_RAPIDS_TPU_WORKER_TOKEN"

#: worker exit codes: the supervisor's restart accounting reads these
EXIT_DRAINED = 0
EXIT_FATAL = 13


class WorkerLost(RuntimeError):
    """A dispatched query's worker process died before answering
    (crash / SIGKILL / hang-kill / fatal self-termination).  Caught by
    the redrive loop, never by client code."""

    def __init__(self, msg: str, reason: str):
        super().__init__(msg)
        self.reason = reason


class ServingWorkerError(RuntimeError):
    """A query exhausted `serving.redrive.maxAttempts` worker losses —
    the terminal form the ticket fails with."""


def _frame(obj: dict) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def _unframe(data: bytes) -> dict:
    return pickle.loads(data)


# ===========================================================================
# Supervisor side
# ===========================================================================

class _Dispatch:
    """One in-flight query on one worker (supervisor bookkeeping)."""

    __slots__ = ("qid", "event", "reply", "lost", "kill_on_start",
                 "started")

    def __init__(self, qid: int, kill_on_start: bool = False):
        self.qid = qid
        self.event = threading.Event()
        self.reply: Optional[dict] = None
        self.lost: Optional[WorkerLost] = None
        self.kill_on_start = kill_on_start
        self.started = threading.Event()


class _WorkerHandle:
    """Supervisor-side state for one worker process."""

    def __init__(self, wid: str, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc
        self.conn: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.pid: Optional[int] = None
        self.metrics_port: Optional[int] = None
        self.ready = threading.Event()       # hello received, conf sent
        self.alive = False                   # ready and not declared dead
        self.last_hb = time.monotonic()
        self.census: Dict[str, int] = {"live_bytes": 0, "peak_bytes": 0}
        self.inflight: Dict[int, _Dispatch] = {}     # qid -> dispatch
        self.draining = False

    def send(self, obj: dict) -> None:
        with self.send_lock:
            send_frame(self.conn, _frame(obj))


class WorkerPool:
    """Supervises `procs` worker processes behind the admission front
    (serving/runtime.py owns admission, conf snapshots, fair-share
    grants and tickets; the pool owns dispatch, health, redrive, and
    the cross-process census picture)."""

    def __init__(self, rconf: TpuConf, conf_raw: dict, procs: int):
        self._rconf = rconf
        self._conf_raw = dict(conf_raw)
        self.procs = int(procs)
        self._hb_s = float(rconf.get(SERVING_POOL_HEARTBEAT_MS)) / 1e3
        self._hb_misses = int(rconf.get(SERVING_POOL_HEARTBEAT_MISSES))
        self._restart = bool(rconf.get(SERVING_POOL_RESTART))
        self._redrive_max = int(rconf.get(SERVING_REDRIVE_MAX))
        self._cond = threading.Condition()
        self._workers: Dict[str, _WorkerHandle] = {}
        self._wid_seq = 0
        self._srv: Optional[socket.socket] = None
        self._token = b""
        self._closed = False
        self._draining = False
        self._restarts: Dict[str, int] = {}          # reason -> count
        self._redrives = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, timeout: float = 120.0) -> "WorkerPool":
        import secrets
        self._token = secrets.token_hex(16).encode()
        self._srv = socket.create_server(("127.0.0.1", 0))
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="tpu-pool-accept").start()
        threading.Thread(target=self._monitor_loop, daemon=True,
                         name="tpu-pool-monitor").start()
        for _ in range(self.procs):
            self._spawn()
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._live_count() < self.procs:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.close()
                    raise RuntimeError(
                        f"serving worker pool: only {self._live_count()}"
                        f"/{self.procs} workers came up in {timeout}s")
                self._cond.wait(min(remaining, 0.5))
        return self

    def _spawn(self) -> _WorkerHandle:
        with self._cond:
            self._wid_seq += 1
            wid = f"w{self._wid_seq}"
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env[_ENV_ID] = wid
        env[_ENV_ADDR] = "%s:%d" % self._srv.getsockname()
        env[_ENV_TOKEN] = self._token.decode()
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.serving.workers"],
            env=env, stdin=subprocess.DEVNULL)
        h = _WorkerHandle(wid, proc)
        with self._cond:
            self._workers[wid] = h
        return h

    def _accept_loop(self) -> None:
        import hmac
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            try:
                hello = recv_frame(conn)
                if hello is None:
                    conn.close()
                    continue
                msg = _unframe(hello)
                if not hmac.compare_digest(
                        msg.get("token", "").encode(), self._token):
                    conn.close()
                    continue
                wid = msg["worker_id"]
                with self._cond:
                    h = self._workers.get(wid)
                if h is None:
                    conn.close()
                    continue
                h.conn = conn
                h.pid = msg.get("pid")
                h.metrics_port = msg.get("metrics_port")
                h.send({"op": "conf", "conf": self._conf_raw,
                        "hb_ms": self._hb_s * 1e3})
                h.last_hb = time.monotonic()
                with self._cond:
                    h.alive = True
                    h.ready.set()
                    self._cond.notify_all()
                self._set_live_gauge()
                threading.Thread(target=self._reader_loop, args=(h,),
                                 daemon=True,
                                 name=f"tpu-pool-read-{wid}").start()
            except Exception:                        # noqa: BLE001
                try:
                    conn.close()
                except OSError:
                    pass

    def _reader_loop(self, h: _WorkerHandle) -> None:
        from ..obs.registry import SERVING_WORKER_HEARTBEATS
        while True:
            try:
                data = recv_frame(h.conn)
            except OSError:
                data = None
            if data is None:
                self._declare_dead(h, "crash")
                return
            try:
                msg = _unframe(data)
            except Exception:                        # noqa: BLE001
                self._declare_dead(h, "crash")
                return
            op = msg.get("op")
            if op == "hb":
                h.last_hb = time.monotonic()
                h.census = dict(msg.get("census") or {})
                if msg.get("metrics_port") is not None:
                    h.metrics_port = msg["metrics_port"]
                SERVING_WORKER_HEARTBEATS.inc()
            elif op == "started":
                d = h.inflight.get(msg.get("qid"))
                if d is not None:
                    d.started.set()
                    if d.kill_on_start:
                        # worker:kill — the victim is now PROVABLY
                        # mid-query; lose the whole process
                        try:
                            h.proc.kill()
                        except OSError:
                            pass
            elif op in ("result", "error"):
                qid = msg.get("qid")
                d = h.inflight.pop(qid, None)
                if op == "error" and \
                        msg.get("classification") == "fatal_device":
                    # the worker wrote its classified dump and is
                    # self-terminating: its query REDRIVES (the dump
                    # names the pid; the redrive conf carries no
                    # injected fatal), exactly like a plain crash
                    if d is not None:
                        d.lost = WorkerLost(
                            f"worker {h.wid} hit a fatal device error "
                            f"(dump: {msg.get('dump_path')})", "fatal")
                        d.event.set()
                    self._declare_dead(h, "fatal")
                    return
                if d is not None:
                    d.reply = msg
                    d.event.set()
                with self._cond:
                    self._cond.notify_all()
            elif op == "drained":
                h.draining = True
                with self._cond:
                    self._cond.notify_all()

    def _monitor_loop(self) -> None:
        while not self._closed:
            time.sleep(self._hb_s)
            now = time.monotonic()
            with self._cond:
                handles = list(self._workers.values())
            for h in handles:
                if not h.alive:
                    continue
                if h.proc.poll() is not None:
                    self._declare_dead(h, "crash")
                elif not h.draining and \
                        now - h.last_hb > self._hb_s * self._hb_misses:
                    # hung: heartbeats stopped but the process lives —
                    # SIGKILL it and treat exactly like a crash
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
                    self._declare_dead(h, "hang")

    def _declare_dead(self, h: _WorkerHandle, reason: str) -> None:
        from ..obs.registry import SERVING_WORKER_RESTARTS
        with self._cond:
            if not h.alive and h.ready.is_set():
                return                   # already handled
            h.alive = False
            self._workers.pop(h.wid, None)
            pending = list(h.inflight.values())
            h.inflight.clear()
            self._restarts[reason] = self._restarts.get(reason, 0) + 1
            self._cond.notify_all()
        SERVING_WORKER_RESTARTS.inc(reason=reason)
        self._set_live_gauge()
        try:
            if h.conn is not None:
                h.conn.close()
        except OSError:
            pass
        for d in pending:
            if d.lost is None:
                d.lost = WorkerLost(
                    f"worker {h.wid} (pid {h.pid}) died mid-query "
                    f"({reason})", reason)
            d.event.set()
        if self._restart and not self._draining and not self._closed:
            self._spawn()

    def _set_live_gauge(self) -> None:
        from ..obs.registry import SERVING_WORKERS_LIVE
        SERVING_WORKERS_LIVE.set(self._live_count())

    def _live_count(self) -> int:
        return sum(1 for h in list(self._workers.values()) if h.alive)

    # -- dispatch ----------------------------------------------------------
    def _pick(self, timeout: float = 60.0) -> _WorkerHandle:
        """The least-loaded live worker (blocks for a restart when the
        whole pool is momentarily down)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                live = [h for h in self._workers.values()
                        if h.alive and not h.draining]
                if live:
                    return min(live, key=lambda h: (len(h.inflight),
                                                    h.wid))
                if self._closed or self._draining:
                    raise ServingWorkerError("worker pool is closed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ServingWorkerError(
                        f"no live serving worker within {timeout}s")
                self._cond.wait(min(remaining, 0.5))

    def execute(self, ticket, injector, deadline_ms: float = 0.0):
        """Run one admitted query on the pool: dispatch, await, REDRIVE
        on worker loss up to serving.redrive.maxAttempts.  Returns
        (pa.Table, device_us).  Chaos `worker` fires here, supervisor-
        side, once per dispatch."""
        from ..obs.registry import SERVING_REDRIVES
        from ..runtime.faults import InjectedWorkerFault
        losses = 0
        while True:
            fault_kind = None
            try:
                injector.fire("worker", query=ticket.id,
                              tenant=ticket.tenant)
            except InjectedWorkerFault as f:
                fault_kind = f.kind
            h = self._pick()
            d = _Dispatch(ticket.id,
                          kill_on_start=(fault_kind == "kill"))
            extra = {}
            if fault_kind == "fatal":
                # arm the in-worker fatal injector for THIS dispatch
                # only — the redrive conf is clean
                extra["spark.rapids.tpu.test.injectFatalError"] = "1"
            h.inflight[ticket.id] = d
            try:
                h.send({"op": "query", "qid": ticket.id,
                        "plan": ticket.plan, "extra": extra,
                        "deadline_ms": float(deadline_ms or 0.0),
                        "ooc": bool(ticket.ooc),
                        "hang": fault_kind == "hang"})
            except (OSError, pickle.PicklingError) as e:
                h.inflight.pop(ticket.id, None)
                if isinstance(e, pickle.PicklingError):
                    raise
                d.lost = WorkerLost(f"worker {h.wid} unreachable "
                                    f"at dispatch: {e}", "crash")
                d.event.set()
            while not d.event.wait(0.5):
                pass
            if d.lost is None:
                msg = d.reply
                if msg["op"] == "result":
                    ticket.worker = h.wid
                    return msg["table"], int(msg.get("device_us") or 0)
                exc = msg.get("exc")
                if exc is None:
                    exc = RuntimeError(
                        f"[worker {h.wid}] {msg.get('error_class')}: "
                        f"{msg.get('message')}")
                raise exc
            # worker loss: redrive on a survivor, bit-identically —
            # queries are read-only and deterministic
            losses += 1
            ticket.redrives = losses
            SERVING_REDRIVES.inc(reason=d.lost.reason)
            with self._cond:
                self._redrives += 1
            if losses > self._redrive_max:
                raise ServingWorkerError(
                    f"query #{ticket.id} lost its worker {losses} times "
                    f"(> serving.redrive.maxAttempts="
                    f"{self._redrive_max}); last: {d.lost}") \
                    from d.lost

    # -- the cross-process HBM picture ------------------------------------
    def live_bytes(self) -> int:
        with self._cond:
            return sum(int(h.census.get("live_bytes") or 0)
                       for h in self._workers.values() if h.alive)

    def census(self) -> dict:
        with self._cond:
            per = {h.wid: {"pid": h.pid,
                           "live_bytes": int(
                               h.census.get("live_bytes") or 0),
                           "peak_bytes": int(
                               h.census.get("peak_bytes") or 0)}
                   for h in self._workers.values() if h.alive}
        return {"live_bytes": sum(w["live_bytes"] for w in per.values()),
                "peak_bytes": sum(w["peak_bytes"] for w in per.values()),
                "workers": per}

    def stats(self) -> dict:
        with self._cond:
            now = time.monotonic()
            workers = {h.wid: {"pid": h.pid,
                               "inflight": len(h.inflight),
                               "metrics_port": h.metrics_port,
                               "last_heartbeat_ms": round(
                                   (now - h.last_hb) * 1e3, 1)}
                       for h in self._workers.values() if h.alive}
            return {"processes": self.procs,
                    "live": len(workers),
                    "restarts": dict(self._restarts),
                    "redrives": self._redrives,
                    "workers": workers}

    # -- drain / close -----------------------------------------------------
    def drain(self, timeout: float = 60.0) -> None:
        """Graceful: every worker checkpoints the history store (atomic
        aggregate rewrite) and exits 0; the supervisor reaps them all —
        no orphan processes."""
        with self._cond:
            self._draining = True
            handles = [h for h in self._workers.values() if h.alive]
        for h in handles:
            try:
                h.send({"op": "drain"})
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        for h in handles:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                h.proc.wait(remaining)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(5.0)
            with self._cond:
                h.alive = False
                self._workers.pop(h.wid, None)
        self._set_live_gauge()
        self.close()

    def close(self) -> None:
        self._closed = True
        with self._cond:
            handles = list(self._workers.values())
            self._workers.clear()
            self._cond.notify_all()
        for h in handles:
            try:
                h.proc.kill()
            except OSError:
                pass
            try:
                h.proc.wait(5.0)
            except Exception:                        # noqa: BLE001
                pass
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        self._set_live_gauge()


# ===========================================================================
# Worker side
# ===========================================================================

def _worker_heartbeat(conn, send_lock: threading.Lock, hb_s: float,
                      stop: threading.Event, state: dict) -> None:
    from ..obs.export import bound_metrics_port
    from ..obs.memattr import CENSUS
    while not stop.wait(hb_s):
        try:
            with send_lock:
                send_frame(conn, _frame({
                    "op": "hb", "pid": os.getpid(),
                    "census": CENSUS.totals(),
                    "metrics_port": bound_metrics_port(),
                    "inflight": state.get("qid")}))
        except OSError:
            # supervisor is gone: a worker must never outlive it
            os._exit(EXIT_DRAINED)


def _run_one(session, base_raw: dict, req: dict) -> dict:
    """Execute one dispatched query under the full single-query
    substrate (crash_capture, retry ladders, OOC tier, history feed)."""
    from ..exec.plan import ExecContext, cancel_scope
    from ..plan.overrides import apply_overrides
    extra = req.get("extra") or {}
    conf = TpuConf({**base_raw, **extra}) if extra else session.conf
    q = apply_overrides(req["plan"], conf)
    ctx = ExecContext(conf)
    ctx.arm_deadline(float(req.get("deadline_ms") or 0.0))
    if req.get("ooc"):
        ctx.ooc_force = True
    t0 = time.perf_counter()
    with cancel_scope(ctx):
        out = q.collect(ctx)
    device_us = int((time.perf_counter() - t0) * 1e6)
    return {"op": "result", "qid": req["qid"], "table": out,
            "device_us": device_us}


def main() -> int:
    wid = os.environ.get(_ENV_ID, "w?")
    host, port = os.environ[_ENV_ADDR].rsplit(":", 1)
    token = os.environ.get(_ENV_TOKEN, "")
    conn = socket.create_connection((host, int(port)))
    send_lock = threading.Lock()
    from ..obs.export import bound_metrics_port
    send_frame(conn, _frame({"op": "hello", "token": token,
                             "worker_id": wid, "pid": os.getpid(),
                             "metrics_port": bound_metrics_port()}))
    cfg = _unframe(recv_frame(conn))
    base_raw = dict(cfg["conf"])
    # failure.py registers its conf keys (coredump.path, the fatal
    # injector) at import — they must exist before the shipped conf
    # (already validated supervisor-side) is re-validated here
    from ..runtime.failure import classify          # noqa: F401
    # this worker owns its own session: budget, device slice, metrics
    # plane, and the SHARED persistent compile cache + history store
    from ..session import TpuSession
    session = TpuSession(base_raw)
    state: dict = {"qid": None}
    stop_hb = threading.Event()
    threading.Thread(target=_worker_heartbeat,
                     args=(conn, send_lock, float(cfg["hb_ms"]) / 1e3,
                           stop_hb, state),
                     daemon=True, name="tpu-worker-hb").start()
    while True:
        try:
            data = recv_frame(conn)
        except OSError:
            # supervisor died mid-frame (SIGKILL'd, crashed): same exit
            # as a clean EOF — a worker never outlives its supervisor
            return EXIT_DRAINED
        if data is None:
            return EXIT_DRAINED            # supervisor closed the pool
        req = _unframe(data)
        op = req.get("op")
        if op == "drain":
            # checkpoint the shared history store (atomic aggregate
            # rewrite) so a restart/deploy loses no folded history
            from ..obs.history import get_store
            store = get_store(session.conf)
            if store is not None:
                store.checkpoint()
            session.close()
            with send_lock:
                send_frame(conn, _frame({"op": "drained"}))
            return EXIT_DRAINED
        if op != "query":
            continue
        state["qid"] = req["qid"]
        with send_lock:
            send_frame(conn, _frame({"op": "started", "qid": req["qid"],
                                     "pid": os.getpid()}))
        if req.get("hang"):
            # chaos worker:hang — wedge: heartbeats stop, requests
            # stop; the supervisor's miss window kills this process
            stop_hb.set()
            while True:
                time.sleep(60.0)
        try:
            reply = _run_one(session, base_raw, req)
        except BaseException as exc:                 # noqa: BLE001
            cls = classify(exc)
            reply = {"op": "error", "qid": req["qid"],
                     "classification": cls,
                     "error_class": type(exc).__name__,
                     "message": str(exc),
                     "dump_path": getattr(exc, "dump_path", None)}
            try:
                pickle.dumps(exc)
                reply["exc"] = exc
            except Exception:                        # noqa: BLE001
                pass                  # supervisor rebuilds from message
            with send_lock:
                send_frame(conn, _frame(reply))
            if cls == "fatal_device":
                # executor self-termination (Plugin.scala contract):
                # the dump is written, the error frame is out — exit so
                # the supervisor replaces this process
                conn.close()
                os._exit(EXIT_FATAL)
            state["qid"] = None
            continue
        with send_lock:
            send_frame(conn, _frame(reply))
        state["qid"] = None


if __name__ == "__main__":
    raise SystemExit(main())
