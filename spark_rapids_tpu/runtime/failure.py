"""Failure detection + device crash capture — the GpuCoreDumpHandler /
executor-self-termination role.

Reference (SURVEY §5): the executor plugin classifies CUDA errors and
self-terminates on fatal ones so Spark replaces the executor
(Plugin.scala:566-575, logGpuDebugInfoAndExit); GpuCoreDumpHandler
(GpuCoreDumpHandler.scala:38) streams GPU core dumps to a distributed FS
and notifies the driver; `CudaFatalException` gets distinct retry
handling (RmmRapidsRetryIterator).

TPU translation:
- `classify(exc)`: RETRYABLE (RESOURCE_EXHAUSTED / budget OOM — the
  retry ladder owns these), FATAL_DEVICE (XLA internal errors, device
  halt, data loss — the chip or its runtime is wedged; the hosting
  process must exit so the cluster manager replaces it), QUERY (plain
  python/user errors — fail the query, keep the executor).
- `crash_capture(conf, ctx)`: context manager that, on FATAL_DEVICE,
  writes a crash-dump JSON (exception, device info, memory budget
  counters, query metrics, backend platform/version) to
  `spark.rapids.tpu.coredump.path` before re-raising wrapped in
  FatalDeviceError — the analogue of streaming the core dump out before
  the executor dies.  PhysicalQuery.collect installs it when the conf
  is set.
- fault injection: `spark.rapids.tpu.test.injectFatalError` (internal)
  raises a synthetic fatal error after N device batches, testing the
  capture path the way injectRetryOOM tests the retry path.
"""
from __future__ import annotations

import itertools
import json
import os
import time
import traceback
from contextlib import contextmanager
from typing import Optional

from ..config import TpuConf, conf as _conf, _positive
from .memory import CorruptBlockError, is_oom_error

COREDUMP_PATH = _conf(
    "spark.rapids.tpu.coredump.path", "",
    "Directory for device crash dumps (GpuCoreDumpHandler role). Empty "
    "disables capture.")

INJECT_FATAL = _conf(
    "spark.rapids.tpu.test.injectFatalError", 0,
    "Test-only: raise a synthetic fatal device error after this many "
    "device batches (0 = off).", internal=True,
    checker=lambda v: None if v >= 0 else "must be >= 0")

RETRYABLE = "retryable"
FATAL_DEVICE = "fatal_device"
QUERY = "query"
IO = "io"                      # transient host IO — the retry.io ladder
CORRUPTION = "corruption"      # checksummed block failed verification:
                               # data loss, fail the query cleanly

_FATAL_MARKERS = (
    "INTERNAL:", "DATA_LOSS", "device halted", "Device halted",
    "FAILED_PRECONDITION: The program continuator has halted",
    "XLA:TPU compile permanent error", "tpu driver",
)


_DUMP_SEQ = itertools.count()


class FatalDeviceError(RuntimeError):
    """The device/runtime is wedged; the hosting process should exit
    (the CudaFatalException analogue)."""

    def __init__(self, msg: str, dump_path: Optional[str] = None):
        super().__init__(msg)
        self.dump_path = dump_path


class InjectedFatalError(Exception):
    """Synthetic fatal error from the fault-injection conf."""


def classify(exc: BaseException) -> str:
    if isinstance(exc, (FatalDeviceError, InjectedFatalError)):
        return FATAL_DEVICE
    if isinstance(exc, CorruptBlockError):
        return CORRUPTION
    if is_oom_error(exc):
        return RETRYABLE
    s = str(exc)
    mod = type(exc).__module__ or ""
    from_device_runtime = ("jax" in mod
                           or "XlaRuntimeError" in type(exc).__name__)
    if from_device_runtime and any(m in s for m in _FATAL_MARKERS):
        return FATAL_DEVICE
    if isinstance(exc, OSError):
        return IO
    return QUERY


def write_crash_dump(conf: TpuConf, exc: BaseException,
                     ctx=None) -> Optional[str]:
    """Serialize diagnostic state next to the dying executor (the
    core-dump stream-out). Returns the dump path."""
    dump_dir = conf.get(COREDUMP_PATH)
    if not dump_dir:
        return None
    os.makedirs(dump_dir, exist_ok=True)
    # the flight recorder FIRST: its tail must show what the runtime
    # was doing up to the fault — the fault's own instant is the last
    # event, and nothing the dump writer does below may append past it
    from ..obs.recorder import FLIGHT_RECORDER
    from ..obs.registry import CRASH_DUMPS, REGISTRY
    flight_tail = FLIGHT_RECORDER.tail()
    info = {
        "ts": time.time(),
        "pid": os.getpid(),
        # the supervised serving pool stamps each worker process's id
        # into its environment: a post-mortem maps dump -> pool slot
        "worker_id": os.environ.get("SPARK_RAPIDS_TPU_WORKER_ID"),
        "exception": repr(exc),
        "traceback": traceback.format_exception(
            type(exc), exc, exc.__traceback__),
        "classification": classify(exc),
        "flight_recorder": flight_tail,
        "metrics_registry": REGISTRY.flat(),
    }
    CRASH_DUMPS.inc()
    try:
        import jax
        d = jax.devices()[0]
        info["device"] = {"kind": d.device_kind,
                          "platform": d.platform,
                          "id": d.id}
        info["jax_version"] = jax.__version__
        stats = d.memory_stats() or {}
        info["memory_stats"] = {k: v for k, v in stats.items()
                                if isinstance(v, (int, float))}
    except Exception as e:                       # noqa: BLE001
        info["device"] = f"unavailable: {e!r}"
    if ctx is not None:
        info["query_metrics"] = dict(getattr(ctx, "metrics", {}))
        budget = getattr(ctx, "_budget", None)
        if budget is not None:
            info["memory_budget"] = dict(getattr(budget, "metrics", {}))
            info["memory_budget"]["naked_live"] = int(
                getattr(budget, "naked_live", 0) or 0)
    # spill/OOM forensics (obs/memattr.py): the HBM-timeline tail —
    # which node-id ranges owned the memory pressure in the window
    # before the fault — rides the dump when the plane was armed
    from ..obs import memattr
    rec = getattr(ctx, "_memattr", None) if ctx is not None else None
    if rec is None:
        rec = memattr.get_active_recorder()
    if rec is not None:
        info["hbm_timeline"] = rec.timeline(tail=64)
        info["hbm_summary"] = rec.summary()
    info["hbm_census"] = memattr.CENSUS.totals()
    # the injected-fault record: when chaos is armed, a post-mortem must
    # show exactly which synthetic faults fired before the crash
    from .faults import get_active_injector, get_injector
    for inj in (get_active_injector(), get_injector(conf)):
        if getattr(inj, "log", None):
            info["injected_faults"] = list(inj.log)
            break
    # the pid keeps CONCURRENT WORKER PROCESSES sharing one dump dir
    # from colliding; the process-monotonic -<seq> suffix keeps two
    # same-second failures in ONE process from overwriting each other
    path = os.path.join(dump_dir,
                        f"tpu-coredump-{os.getpid()}-{int(time.time())}"
                        f"-{next(_DUMP_SEQ)}.json")
    with open(path, "w") as f:
        json.dump(info, f, indent=2, default=str)
    return path


def write_worker_lost_dump(conf: TpuConf, worker_id: str, pid,
                           reason: str, flight=None, census=None,
                           inflight=None) -> Optional[str]:
    """BLACK-BOX forensics for a worker that died by kill/hang — the
    cases where no in-worker dump is possible.  The supervisor writes
    this from the victim's last heartbeat-carried flight-recorder
    snapshot plus the in-flight ticket state it was holding, so a
    post-mortem sees what the worker was doing right up to its last
    beat even though the process never got to say goodbye."""
    dump_dir = conf.get(COREDUMP_PATH)
    if not dump_dir:
        return None
    os.makedirs(dump_dir, exist_ok=True)
    info = {
        "ts": time.time(),
        "type": "worker_lost",
        "supervisor_pid": os.getpid(),
        "worker_id": worker_id,
        "worker_pid": pid,
        "reason": reason,
        # the victim's black box: its last-known flight-recorder tail
        # (heartbeat telemetry) — NOT this process's recorder
        "flight_recorder": list(flight or ()),
        "hbm_census": dict(census or {}),
        # the tickets that were mid-flight on the victim (they redrive)
        "inflight_tickets": list(inflight or ()),
        "metrics_registry": None,
    }
    try:
        from ..obs.registry import FLEET
        fleet = {k: v for k, v in FLEET.flat().items()
                 if f"worker={worker_id}" in k}
        info["metrics_registry"] = fleet or None
    except Exception:                            # noqa: BLE001
        pass
    from .faults import get_active_injector, get_injector
    for inj in (get_active_injector(), get_injector(conf)):
        if getattr(inj, "log", None):
            info["injected_faults"] = list(inj.log)
            break
    path = os.path.join(dump_dir,
                        f"tpu-workerlost-{worker_id}-{int(time.time())}"
                        f"-{next(_DUMP_SEQ)}.json")
    try:
        with open(path, "w") as f:
            json.dump(info, f, indent=2, default=str)
    except OSError:
        return None                  # forensics must never break redrive
    return path


@contextmanager
def crash_capture(conf: TpuConf, ctx=None):
    """On a fatal device error: capture the dump, re-raise as
    FatalDeviceError so the hosting process can self-terminate (the
    Plugin.scala:569-575 contract: Spark replaces the executor)."""
    try:
        yield
    except BaseException as exc:                 # noqa: BLE001
        if classify(exc) == FATAL_DEVICE and \
                not isinstance(exc, FatalDeviceError):
            path = write_crash_dump(conf, exc, ctx)
            raise FatalDeviceError(
                f"fatal device error: {exc!r}"
                + (f" (crash dump: {path})" if path else ""),
                dump_path=path) from exc
        raise


def install_fault_injection(root, conf: TpuConf) -> None:
    """Wrap a physical root's execute stream with the per-batch fault
    sites: the legacy batch-count fatal injector (injectRetryOOM's
    sibling) and the chaos harness's `execute` site (runtime/faults.py),
    which fires once per device batch the root emits."""
    from .faults import get_injector
    chaos = get_injector(conf)
    thr = int(conf.get(INJECT_FATAL))
    if (not thr and not chaos.has_site("execute")) or \
            getattr(root, "_fatal_injected", False):
        return
    inj = FatalInjector(conf)
    orig = root.execute

    def wrapped(ctx):
        for b in orig(ctx):
            inj.tick()
            chaos.fire("execute")
            yield b

    root.execute = wrapped
    root._fatal_injected = True


class FatalInjector:
    """Counts device batches; raises at the configured threshold."""

    def __init__(self, conf: TpuConf):
        self.threshold = int(conf.get(INJECT_FATAL))
        self.count = 0

    def tick(self):
        if not self.threshold:
            return
        self.count += 1
        if self.count >= self.threshold:
            self.threshold = 0      # fire once
            raise InjectedFatalError(
                "injected fatal device error "
                "(spark.rapids.tpu.test.injectFatalError)")
