"""HBM budget accounting + spill store — the RMM/spill-framework role.

Reference: RapidsBufferCatalog.scala:62 (buffer catalog with device→host→
disk tiers), DeviceMemoryEventHandler.scala:36 (synchronous spill on
allocation failure), SpillableColumnarBatch.scala (operators hold handles,
not pinned batches), GpuDeviceManager.scala:275 (pool sizing).

TPU-first re-design (SURVEY §7 hard part b): XLA manages HBM itself and
cannot call back on allocation failure, so the engine *pre-budgets*: every
long-lived batch an operator holds across blocking points is registered
here as a `Spillable`; admitting a new reservation spills least-recently-
used device batches to host until the budget fits.  Reactive OOMs
(XlaRuntimeError RESOURCE_EXHAUSTED leaking through the budget, e.g. from
transient kernel scratch) are caught by runtime/retry.py, which spills
everything and replays with split batches.

The host tier holds Arrow batches; a byte limit overflows the oldest to a
disk directory of Arrow IPC files (the RapidsDiskStore role).
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Optional

import pyarrow as pa

from ..columnar.device import DeviceBatch, to_device, to_host
from ..columnar.host import HostBatch
from ..config import (HBM_BUDGET_BYTES, HBM_BUDGET_FRACTION,
                      HOST_SPILL_LIMIT_BYTES, TEST_INJECT_RETRY_OOM, TpuConf)
from ..obs.registry import (HOST_SPILL_LIVE_BYTES, RELEASE_UNDERFLOWS,
                            SPILL_BATCHES, SPILL_BYTES, SPILL_MS)


def _device_label() -> str:
    """Index of the chip whose HBM this process budgets (the per-device
    label on the registry's HBM gauges)."""
    try:
        import jax
        return str(jax.devices()[0].id)
    except Exception:                            # noqa: BLE001
        return "0"


class TpuRetryOOM(RuntimeError):
    """Budget exhausted (or injected); the retry framework catches this and
    replays the attempt — the GpuRetryOOM analogue."""


class TpuSplitAndRetryOOM(TpuRetryOOM):
    """Retry after splitting the input — the GpuSplitAndRetryOOM analogue."""


class CorruptBlockError(RuntimeError):
    """A checksummed spill/shuffle block failed verification: the bytes
    the query needs are gone, so retrying cannot help — fail the query
    cleanly with a classified error (runtime.failure CORRUPTION class)
    instead of surfacing the raw native IO error."""

    def __init__(self, msg: str, path: Optional[str] = None):
        super().__init__(msg)
        self.path = path


def is_oom_error(exc: BaseException) -> bool:
    """Budget OOMs plus XLA RESOURCE_EXHAUSTED leaking past the budget."""
    if isinstance(exc, TpuRetryOOM):
        return True
    s = str(exc)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s


class _YieldableRLock:
    """Re-entrant budget lock whose full hold can be temporarily yielded.

    The spill chain (reserve -> _spill_one -> spill -> host_reserve ->
    _disk_one -> to_disk) holds the budget lock re-entrantly, so an
    inner frame cannot drop a plain threading.RLock around an IO
    backoff sleep.  `yielded()` releases the whole re-entrant hold for
    the duration of the sleep and restores it afterwards, so a retried
    disk write (retry_io) never stalls other threads' reserve/release
    traffic behind its backoff."""

    def __init__(self):
        self._block = threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            self._count += 1
            return True
        self._block.acquire()
        self._owner = me
        self._count = 1
        return True

    def release(self):
        if self._owner != threading.get_ident():
            raise RuntimeError("release of un-acquired budget lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._block.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    @contextmanager
    def yielded(self):
        """Fully release this thread's hold for the body, then restore
        it at the same re-entrancy depth (no-op for a non-holder)."""
        me = threading.get_ident()
        if self._owner != me:
            yield
            return
        count, self._count, self._owner = self._count, 0, None
        self._block.release()
        try:
            yield
        finally:
            self._block.acquire()
            self._owner = me
            self._count = count


def device_hbm_bytes() -> Optional[int]:
    """Total bytes of the addressable device's memory, if discoverable."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


class MemoryBudget:
    """Per-query (ExecContext) budget over registered Spillables.

    `limit == 0` means unlimited (accounting still runs, nothing spills).
    Thread-safe: shuffle/scan worker threads register batches too."""

    def __init__(self, conf: TpuConf):
        limit = conf.get(HBM_BUDGET_BYTES)
        if limit == 0:
            hbm = device_hbm_bytes()
            limit = int(hbm * conf.get(HBM_BUDGET_FRACTION)) if hbm else 0
        self.limit = limit
        self.host_limit = conf.get(HOST_SPILL_LIMIT_BYTES)
        self.conf = conf
        self.live = 0                 # bytes of registered device batches
        self.naked_live = 0           # direct (non-Spillable) reservations
                                      # still live — the leak-check basis
        self.host_live = 0
        self._lock = _YieldableRLock()
        self._spillables: "OrderedDict[int, Spillable]" = OrderedDict()
        self._next_id = 0
        self._disk_dir: Optional[str] = None
        # OOM injection: fire TpuRetryOOM on the Nth reservation (1-based),
        # once — the reference's spark.rapids.sql.test.injectRetryOOM
        self._inject_at = conf.get(TEST_INJECT_RETRY_OOM)
        self._reservations = 0
        # chaos harness: the `reserve` fault site fires per admission
        from .faults import get_injector
        self._injector = get_injector(conf)
        # per-thread stack of attempt scopes (retry-ladder rollback)
        self._tls = threading.local()
        # per-query compat view; the process-wide truth lives in the
        # always-on registry (obs/registry.py) this budget also feeds
        self.metrics = {"spilled_batches": 0, "spilled_bytes": 0,
                        "disk_batches": 0, "oom_retries": 0,
                        "batch_splits": 0, "peak_bytes": 0,
                        "release_underflow": 0, "io_retries": 0,
                        "attempt_rollback_bytes": 0}
        self._device = _device_label()
        # memory-attribution plane (obs/memattr.py): the process census
        # sums live bytes across ALL budgets (the global gauges — a
        # serving tenant's bytes never inflate another query's peak),
        # and the active per-query recorder, when profiling armed one,
        # receives watermark events for the HBM timeline
        from ..obs.memattr import CENSUS, get_active_recorder
        self._attr = get_active_recorder()
        self._census_cell = CENSUS.register(self)
        self._census = CENSUS

    # -- registration ------------------------------------------------------
    def register(self, sp: "Spillable") -> int:
        with self._lock:
            self._next_id += 1
            self._spillables[self._next_id] = sp
            return self._next_id

    def unregister(self, sid: int):
        with self._lock:
            self._spillables.pop(sid, None)

    def touch(self, sid: int):
        """LRU bump: most-recently-used spills last."""
        with self._lock:
            if sid in self._spillables:
                self._spillables.move_to_end(sid)

    # -- attempt scopes (retry-ladder rollback) ----------------------------
    def _scopes(self) -> list:
        st = getattr(self._tls, "scopes", None)
        if st is None:
            st = self._tls.scopes = []
        return st

    @contextmanager
    def track_attempt(self):
        """Track this thread's net *naked* reservations (direct reserve/
        release pairs; Spillable-owned bytes are excluded — the spillable
        owns their lifecycle) so the retry ladder can release what a
        failed attempt leaked before replaying or escaping
        (runtime/retry.py)."""
        scope = _AttemptScope()
        st = self._scopes()
        st.append(scope)
        try:
            yield scope
        finally:
            st.pop()

    def rollback_attempt(self, scope: "_AttemptScope"):
        """Release the positive leftover of a failed attempt's naked
        reservations (call after the scope exits)."""
        leftover = scope.naked
        if leftover > 0:
            self.release(leftover, _tracked=False)
            with self._lock:
                # the rolled-back bytes WERE naked (tracked at reserve):
                # the untracked release above did not retire them from
                # the leak-check counter, so do it here
                self.naked_live = max(0, self.naked_live - leftover)
                self.metrics["attempt_rollback_bytes"] += leftover
                # reserve() counted these bytes into every scope on the
                # stack, so the enclosing rungs of a nested ladder must
                # not release them a second time
                for outer in self._scopes():
                    outer.naked -= leftover
        scope.naked = 0

    # -- accounting --------------------------------------------------------
    def reserve(self, nbytes: int, _tracked: bool = True):
        """Admit `nbytes` of new device data, spilling LRU batches first.
        Raises TpuRetryOOM when the budget cannot fit even after spilling
        everything (the DeviceMemoryEventHandler contract)."""
        with self._lock:
            self._reservations += 1
            if self._inject_at and self._reservations == self._inject_at:
                self.metrics["oom_retries"] += 1
                raise TpuRetryOOM("injected OOM "
                                  f"(reservation #{self._reservations})")
            self._injector.fire("reserve")
            if self.limit:
                while self.live + nbytes > self.limit:
                    if not self._spill_one():
                        if self._attr is not None:
                            # forensics: who owned the pressure (the
                            # open segment bracket, if any) and what
                            # the watermark was at the OOM instant
                            self._attr.on_budget_event(
                                "oom", nbytes, self.live, self.naked_live)
                        raise TpuRetryOOM(
                            f"HBM budget exhausted: live={self.live} "
                            f"+ {nbytes} > limit={self.limit} with "
                            "nothing left to spill")
            self.live += nbytes
            if _tracked:
                self.naked_live += nbytes
                for scope in self._scopes():
                    scope.naked += nbytes
            # device-memory high-water (the profile's peak-usage line);
            # PER-QUERY by construction — the process-wide view is the
            # census sum below, kept separate so concurrent tenants
            # never inflate each other's reported peaks
            if self.live > self.metrics["peak_bytes"]:
                self.metrics["peak_bytes"] = self.live
            self._census_cell[0] = self.live
            self._census.adjust(nbytes, self._device)
            if self._attr is not None:
                self._attr.on_budget_event("reserve", nbytes, self.live,
                                           self.naked_live)

    def release(self, nbytes: int, _tracked: bool = True):
        with self._lock:
            prev = self.live
            self.live -= nbytes
            if _tracked:
                self.naked_live -= nbytes
                if self.naked_live < 0:
                    self.naked_live = 0
                for scope in self._scopes():
                    scope.naked -= nbytes
            if self.live < 0:
                # double-release: clamp so the budget doesn't silently
                # widen, and count it — chaos/regression tests assert 0
                self.metrics["release_underflow"] += 1
                RELEASE_UNDERFLOWS.inc()
                self.live = 0
            self._census_cell[0] = self.live
            self._census.adjust(self.live - prev, self._device)
            if self._attr is not None:
                self._attr.on_budget_event("release", nbytes, self.live,
                                           self.naked_live)

    def _spill_one(self) -> bool:
        for sp in self._spillables.values():
            if sp.on_device:
                sp.spill()
                return True
        return False

    def spill_all(self):
        """Reactive path (retry framework): push every held batch off
        device before replaying the failed attempt.  Each spillable is
        a cancellation checkpoint: a deadline-armed query cancels
        BETWEEN spills (every block fully written or not started), so
        a long spill sweep cannot pin a cancelled query's device slot."""
        from ..exec.plan import checkpoint_active
        with self._lock:
            for sp in list(self._spillables.values()):
                checkpoint_active("spill")
                if sp.on_device:
                    sp.spill()

    # -- host tier ---------------------------------------------------------
    def host_reserve(self, nbytes: int):
        with self._lock:
            while self.host_limit and \
                    self.host_live + nbytes > self.host_limit:
                if not self._disk_one():
                    break        # disk tier is unbounded; never refuse
            self.host_live += nbytes
            HOST_SPILL_LIVE_BYTES.set(self.host_live)

    def host_release(self, nbytes: int):
        with self._lock:
            self.host_live -= nbytes
            if self.host_live < 0:
                self.metrics["release_underflow"] += 1
                RELEASE_UNDERFLOWS.inc()
                self.host_live = 0
            HOST_SPILL_LIVE_BYTES.set(self.host_live)

    def _disk_one(self) -> bool:
        for sp in self._spillables.values():
            # skip spillables whose disk write is mid-backoff with the
            # lock yielded: a second to_disk would double-write
            if sp.on_host and not sp._writing:
                sp.to_disk()
                return True
        return False

    def disk_dir(self) -> str:
        if self._disk_dir is None:
            self._disk_dir = tempfile.mkdtemp(prefix="srtpu_spill_")
        return self._disk_dir


class _AttemptScope:
    """Net naked-reservation delta of one retry-ladder attempt on one
    thread (see MemoryBudget.track_attempt)."""

    __slots__ = ("naked",)

    def __init__(self):
        self.naked = 0


class Spillable:
    """A batch an operator holds across blocking points, owned by the
    budget: device ⇄ host Arrow ⇄ disk Arrow-IPC (SpillableColumnarBatch +
    the three RapidsBufferStore tiers)."""

    def __init__(self, db: DeviceBatch, budget: MemoryBudget):
        self._db: Optional[DeviceBatch] = db
        self._hb: Optional[HostBatch] = None
        self._path: Optional[str] = None
        self._budget = budget
        self._nbytes = db.nbytes()
        # lazily coerced: a device-resident row count stays on device
        # until someone actually needs the host value (spill does anyway)
        self._num_rows = db.num_rows
        # untracked: the spillable owns these bytes' lifecycle; attempt
        # scopes roll back only naked reservations (track_attempt)
        budget.reserve(self._nbytes, _tracked=False)
        self._sid = budget.register(self)
        self._writing = False            # disk write in flight (to_disk)
        self._closed = False             # see close(): idempotent contract

    @property
    def num_rows(self) -> int:
        if not isinstance(self._num_rows, int):
            self._num_rows = int(self._num_rows)
        return self._num_rows

    @property
    def on_device(self) -> bool:
        return self._db is not None

    @property
    def on_host(self) -> bool:
        return self._hb is not None

    @property
    def nbytes(self) -> int:
        """Device-resident byte size this spillable reserves when
        materialized — the out-of-core tier sizes partitions from it."""
        return self._nbytes

    @property
    def closed(self) -> bool:
        """Whether close() already released every tier (see close)."""
        return self._closed

    def spill(self):
        """device -> host tier (holds the budget lock: spill can be driven
        by another thread's reserve())."""
        with self._budget._lock:
            if self._db is None:
                return
            t0 = time.perf_counter()
            hb = to_host(self._db)
            self._db = None
            self._budget.release(self._nbytes, _tracked=False)
            self._budget.metrics["spilled_batches"] += 1
            self._budget.metrics["spilled_bytes"] += self._nbytes
            SPILL_BATCHES.inc(tier="host")
            SPILL_BYTES.inc(self._nbytes, tier="host")
            SPILL_MS.observe((time.perf_counter() - t0) * 1e3, op="spill")
            from ..obs.tracer import get_active
            get_active().instant("spill", "runtime", tier="host",
                                 bytes=self._nbytes)
            if self._budget._attr is not None:
                # forensics: the spill instant on the HBM timeline,
                # attributed to the open segment bracket (if any)
                self._budget._attr.on_budget_event(
                    "spill", self._nbytes, self._budget.live,
                    self._budget.naked_live)
            # reserve BEFORE publishing the host tier: host_reserve may
            # drive _disk_one(), and finding THIS batch on_host would
            # release bytes that were never added (host-budget underflow)
            self._budget.host_reserve(hb.rb.nbytes)
            self._hb = hb

    def to_disk(self):
        """host -> disk tier: Arrow IPC payload inside a checksummed
        native block (native/spillio.cpp — the RapidsDiskStore writes;
        the C write path releases the GIL under spill worker threads).
        Holds the budget lock: a concurrent reserve() driving
        _disk_one() must not race the owner's get().  The retried
        write's backoff sleeps yield the lock (retry_io) so the budget
        stays responsive; the _writing flag keeps a concurrent
        _disk_one() off this spillable meanwhile, and the host tier is
        only dropped if it survived the yield unchanged."""
        with self._budget._lock:
            if self._hb is None or self._writing:
                return
            from .. import native
            from .retry import retry_io
            hb = self._hb
            path = os.path.join(self._budget.disk_dir(),
                                f"spill_{self._sid}.blk")
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, hb.rb.schema) as w:
                w.write_batch(hb.rb)
            payload = sink.getvalue()               # zero-copy pa.Buffer
            self._writing = True
            t0 = time.perf_counter()
            try:
                retry_io(self._budget.conf, "spill_write",
                         lambda: native.spill_write(path, payload),
                         budget=self._budget, lock=self._budget._lock)
            finally:
                self._writing = False
            SPILL_MS.observe((time.perf_counter() - t0) * 1e3,
                             op="to_disk")
            if self._hb is not hb:
                # the owner re-uploaded or closed while the lock was
                # yielded: the host tier moved on, the block is stale
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return
            self._budget.host_release(hb.rb.nbytes)
            self._budget.metrics["disk_batches"] += 1
            SPILL_BATCHES.inc(tier="disk")
            SPILL_BYTES.inc(hb.rb.nbytes, tier="disk")
            from ..obs.tracer import get_active
            get_active().instant("spill", "runtime", tier="disk",
                                 bytes=hb.rb.nbytes)
            self._hb = None
            self._path = path

    def get(self) -> DeviceBatch:
        """Materialize on device (re-uploading through the budget).  The
        returned batch reference stays valid even if the spillable is
        spilled again by a concurrent reserve()."""
        with self._budget._lock:
            if self._db is None:
                hb = self._host_batch()
                # recheck: a disk read's backoff yields the lock, so a
                # concurrent get() may have re-uploaded already
                if self._db is None:
                    # untracked like __init__/spill: the spillable owns
                    # these bytes, so a failed attempt's rollback must
                    # not release them while the batch is live on device
                    self._budget.reserve(self._nbytes, _tracked=False)
                    self._db = to_device(hb, self._budget.conf)
                    if self._hb is not None:
                        self._budget.host_release(self._hb.rb.nbytes)
                    self._hb = None
            self._budget.touch(self._sid)
            return self._db

    def get_host(self) -> HostBatch:
        """Materialize as a host batch without a device reservation."""
        with self._budget._lock:
            if self._db is not None:
                return to_host(self._db)
            return self._host_batch()

    def _host_batch(self) -> HostBatch:
        if self._hb is not None:
            return self._hb
        assert self._path is not None, "spillable lost all tiers"
        from .. import native
        from .retry import retry_io
        path = self._path

        def _read():
            try:
                return native.spill_read(path)      # checksum-verified
            except OSError as e:
                if "checksum" in str(e) or "magic" in str(e):
                    # verification failure is data loss, not a transient
                    # fault: classify and fail the query cleanly (the
                    # IO retry ladder must not spin on it)
                    from ..obs.tracer import get_active
                    get_active().instant("corrupt_block", "runtime",
                                         path=path)
                    raise CorruptBlockError(
                        f"spill block failed checksum verification: "
                        f"{path} ({e})", path=path) from e
                raise

        t0 = time.perf_counter()
        payload = retry_io(self._budget.conf, "spill_read", _read,
                           budget=self._budget, info={"path": path},
                           lock=self._budget._lock)
        SPILL_MS.observe((time.perf_counter() - t0) * 1e3, op="read")
        reader = pa.ipc.open_stream(pa.BufferReader(payload))
        rb = reader.read_next_batch()
        return HostBatch(rb)

    def close(self):
        """Release every tier this spillable still holds (device
        reservation, host bytes, disk block file).

        IDEMPOTENT BY CONTRACT: out-of-core operators close handles
        both at consumption time (inside their bucket loops) and again
        in their `finally` cleanup sweeps — early generator abandonment
        (a LIMIT above an OOC join) reaches the sweep with some handles
        already closed.  A second close must release nothing twice:
        every tier is nulled before its release path can re-run, and
        the `closed` flag makes the state observable to tests."""
        with self._budget._lock:
            self._closed = True
            self._budget.unregister(self._sid)
            if self._db is not None:
                # untracked for the same reason __init__/spill are: an
                # attempt scope must not mistake this spillable-owned
                # release for a naked reservation being returned
                self._budget.release(self._nbytes, _tracked=False)
                self._db = None
            if self._hb is not None:
                self._budget.host_release(self._hb.rb.nbytes)
                self._hb = None
            if self._path is not None:
                try:
                    os.unlink(self._path)
                except OSError:
                    pass
                self._path = None
