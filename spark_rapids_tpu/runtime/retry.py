"""OOM retry framework — the RmmRapidsRetryIterator role.

Reference: RmmRapidsRetryIterator.scala:41-107 — operator inner loops run
inside withRetry/withRetryNoSplit/withSplitAndRetry; on GpuRetryOOM the
work replays after spilling, on GpuSplitAndRetryOOM the input batch is
split in half first.  Inputs must be spillable and the attempt idempotent.

TPU shape: the budget (runtime/memory.py) raises TpuRetryOOM proactively;
XLA RESOURCE_EXHAUSTED errors from kernel scratch are caught reactively.
Either way the recovery ladder is identical to the reference's:
  1. spill everything registered with the budget, replay;
  2. halve the input batch and process the halves independently
     (up to conf retry.maxSplits times);
  3. rethrow.
Attempts must be idempotent: they are traced jit programs plus pure
gathers, so replaying is safe by construction.
"""
from __future__ import annotations

from typing import Callable, Iterator, List, TypeVar

import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..config import RETRY_ENABLED, RETRY_MAX_SPLITS, TpuConf
from .memory import MemoryBudget, TpuRetryOOM, is_oom_error

T = TypeVar("T")


def split_batch(db: DeviceBatch, conf: TpuConf) -> List[DeviceBatch]:
    """Halve a batch by row (the splitSpillableInHalfByRows policy)."""
    n = int(db.num_rows)
    if n <= 1:
        raise TpuRetryOOM(f"cannot split a {n}-row batch further")
    cut = n // 2
    return [slice_batch(db, 0, cut, conf), slice_batch(db, cut, n, conf)]


def slice_batch(db: DeviceBatch, start: int, stop: int,
                conf: TpuConf) -> DeviceBatch:
    """Rows [start, stop) as a new right-sized batch (device slice)."""
    rows = stop - start
    cap = bucket_capacity(max(rows, 1), conf)
    idx = jnp.arange(cap, dtype=jnp.int32) + start
    live = jnp.arange(cap, dtype=jnp.int32) < rows
    cols = []
    for c in db.columns:
        sl = jnp.clip(idx, 0, db.capacity - 1)
        d = c.data[sl]
        v = c.validity[sl] & live
        h = None if c.data_hi is None else c.data_hi[sl]
        cols.append(DeviceColumn(d, v, c.dtype, c.dictionary, h))
    return DeviceBatch(cols, rows, list(db.names), db.origin_file)


def with_retry(budget: MemoryBudget, conf: TpuConf,
               attempt: Callable[[], T]) -> T:
    """Replay `attempt` once after a spill-everything on OOM
    (withRetryNoSplit)."""
    if not conf.get(RETRY_ENABLED):
        return attempt()
    try:
        return attempt()
    except Exception as e:                       # noqa: BLE001
        if not is_oom_error(e):
            raise
        budget.metrics["oom_retries"] += 1
        from ..obs.tracer import get_active
        get_active().instant("oom_retry", "runtime",
                             error=type(e).__name__)
        budget.spill_all()
        return attempt()


def with_split_retry(budget: MemoryBudget, conf: TpuConf,
                     batch: DeviceBatch,
                     attempt: Callable[[DeviceBatch], T]
                     ) -> Iterator[T]:
    """Run `attempt(batch)`; on OOM spill + replay, then recursively halve
    the batch (withSplitAndRetry).  Yields one result per final sub-batch
    in row order."""
    if not conf.get(RETRY_ENABLED):
        yield attempt(batch)
        return
    from ..obs.tracer import get_active
    max_splits = conf.get(RETRY_MAX_SPLITS)
    pending: List[tuple] = [(batch, 0)]          # (batch, splits so far)
    while pending:
        b, depth = pending.pop(0)
        try:
            yield attempt(b)
            continue
        except Exception as e:                   # noqa: BLE001
            if not is_oom_error(e):
                raise
        budget.metrics["oom_retries"] += 1
        get_active().instant("oom_retry", "runtime", depth=depth)
        budget.spill_all()
        try:
            yield attempt(b)
            continue
        except Exception as e:                   # noqa: BLE001
            if not is_oom_error(e):
                raise
            if depth >= max_splits:
                raise TpuRetryOOM(
                    f"OOM persists after {depth} splits") from e
        budget.metrics["batch_splits"] += 1
        get_active().instant("batch_split", "runtime", depth=depth + 1)
        halves = split_batch(b, conf)
        pending[:0] = [(h, depth + 1) for h in halves]
