"""OOM retry framework — the RmmRapidsRetryIterator role.

Reference: RmmRapidsRetryIterator.scala:41-107 — operator inner loops run
inside withRetry/withRetryNoSplit/withSplitAndRetry; on GpuRetryOOM the
work replays after spilling, on GpuSplitAndRetryOOM the input batch is
split in half first.  Inputs must be spillable and the attempt idempotent.

TPU shape: the budget (runtime/memory.py) raises TpuRetryOOM proactively;
XLA RESOURCE_EXHAUSTED errors from kernel scratch are caught reactively.
Either way the recovery ladder is identical to the reference's:
  1. spill everything registered with the budget, replay;
  2. halve the input batch and process the halves independently
     (up to conf retry.maxSplits times);
  3. rethrow.
Attempts must be idempotent: they are traced jit programs plus pure
gathers, so replaying is safe by construction.
"""
from __future__ import annotations

import itertools
import os
import time
import zlib
from typing import Callable, Iterator, List, Optional, TypeVar

import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity
from ..config import (RETRY_ENABLED, RETRY_IO_ATTEMPTS,
                      RETRY_IO_BACKOFF_MS, RETRY_IO_BACKOFF_MULT,
                      RETRY_IO_JITTER, RETRY_MAX_ATTEMPTS,
                      RETRY_MAX_SPLITS, TpuConf)
from ..obs.registry import BATCH_SPLITS, IO_RETRIES, OOM_RETRIES
from .memory import (MemoryBudget, TpuRetryOOM, TpuSplitAndRetryOOM,
                     is_oom_error)

T = TypeVar("T")

#: per-process backoff-draw counter: each sleep advances the stream, so
#: one process's jitter sequence is exactly reproducible while distinct
#: processes (distinct pid seeds) desynchronize
_JITTER_SEQ = itertools.count(1)


def _jittered_backoff_s(backoff_s: float, fraction: float, seed: int,
                        draw: int) -> float:
    """`backoff_s` scaled by a deterministic factor in
    [1-fraction, 1+fraction]: the splitmix64 stream (runtime/faults.py
    — NOT python's salted hash) keyed by (seed, draw).  N worker
    processes replaying the SAME injected host-IO fault sleep different
    amounts (pid-distinct seeds) instead of thundering-herding the
    spill disk; re-running one process replays its exact sequence."""
    if fraction <= 0.0:
        return backoff_s
    from .faults import _splitmix_uniform
    u = _splitmix_uniform(seed, draw)
    return backoff_s * (1.0 + fraction * (2.0 * u - 1.0))


def _io_jitter_seed(site: str) -> int:
    return os.getpid() ^ zlib.crc32(site.encode())


def retry_io(conf: TpuConf, site: str, attempt: Callable[[], T],
             budget: Optional[MemoryBudget] = None,
             info: Optional[dict] = None, lock=None) -> T:
    """Bounded retry-with-backoff for transient host IO (spill block
    read/write, shuffle write/fetch, host<->device transfers) — the
    `spark.rapids.tpu.retry.io.*` ladder.

    Each attempt first fires the chaos injector's `site` (so injected
    IO faults land inside the retried unit and the recovery path is the
    one under test), then runs `attempt`.  OSErrors retry up to
    maxAttempts with exponential backoff, emitting an `io_retry` obs
    instant per recovery; anything else (including CorruptBlockError —
    verification failure is data loss, not transience) escapes
    immediately.

    `lock` (the budget's yieldable re-entrant lock) is fully released
    for the duration of each backoff sleep and restored after, so a
    spill read/write that retries inside the budget-locked spill chain
    does not stall every other thread's reserve/release behind its
    backoff (memory.py _YieldableRLock)."""
    from .faults import get_injector
    inj = get_injector(conf)
    attempts = int(conf.get(RETRY_IO_ATTEMPTS))
    backoff = float(conf.get(RETRY_IO_BACKOFF_MS)) / 1000.0
    mult = float(conf.get(RETRY_IO_BACKOFF_MULT))
    kw = info or {}
    for i in range(max(attempts, 1)):
        try:
            inj.fire(site, **kw)
            return attempt()
        except OSError as e:
            if i + 1 >= max(attempts, 1):
                raise
            from ..obs.tracer import get_active
            get_active().instant("io_retry", "runtime", site=site,
                                 attempt=i + 1, error=type(e).__name__)
            IO_RETRIES.inc(site=site)
            if budget is not None:
                budget.metrics["io_retries"] += 1
            if backoff > 0:
                sleep_s = _jittered_backoff_s(
                    backoff, float(conf.get(RETRY_IO_JITTER)),
                    _io_jitter_seed(site), next(_JITTER_SEQ))
                if lock is not None:
                    with lock.yielded():
                        time.sleep(sleep_s)
                else:
                    time.sleep(sleep_s)
            backoff *= mult
    raise AssertionError("unreachable")


def split_batch(db: DeviceBatch, conf: TpuConf) -> List[DeviceBatch]:
    """Halve a batch by row (the splitSpillableInHalfByRows policy)."""
    n = int(db.num_rows)
    if n <= 1:
        raise TpuRetryOOM(f"cannot split a {n}-row batch further")
    cut = n // 2
    return [slice_batch(db, 0, cut, conf), slice_batch(db, cut, n, conf)]


def slice_batch(db: DeviceBatch, start: int, stop: int,
                conf: TpuConf) -> DeviceBatch:
    """Rows [start, stop) as a new right-sized batch (device slice)."""
    rows = stop - start
    cap = bucket_capacity(max(rows, 1), conf)
    idx = jnp.arange(cap, dtype=jnp.int32) + start
    live = jnp.arange(cap, dtype=jnp.int32) < rows
    cols = []
    for c in db.columns:
        sl = jnp.clip(idx, 0, db.capacity - 1)
        d = c.data[sl]
        v = c.validity[sl] & live
        h = None if c.data_hi is None else c.data_hi[sl]
        cols.append(DeviceColumn(d, v, c.dtype, c.dictionary, h))
    return DeviceBatch(cols, rows, list(db.names), db.origin_file)


def with_retry(budget: MemoryBudget, conf: TpuConf,
               attempt: Callable[[], T]) -> T:
    """Replay `attempt` after a spill-everything on OOM, up to the
    configured attempt ladder depth (withRetryNoSplit upgraded:
    spark.rapids.tpu.sql.retry.maxAttempts rungs; a failed attempt's
    partial naked reservations are released before replay or escape)."""
    if not conf.get(RETRY_ENABLED):
        return attempt()
    from ..obs.tracer import get_active
    max_attempts = max(int(conf.get(RETRY_MAX_ATTEMPTS)), 1)
    for i in range(max_attempts):
        with budget.track_attempt() as scope:
            try:
                return attempt()
            except Exception as e:               # noqa: BLE001
                err, oom = e, is_oom_error(e)
        budget.rollback_attempt(scope)
        if not oom or i + 1 >= max_attempts:
            raise err
        budget.metrics["oom_retries"] += 1
        OOM_RETRIES.inc()
        get_active().instant("oom_retry", "runtime",
                             error=type(err).__name__, attempt=i + 1)
        budget.spill_all()
    raise AssertionError("unreachable")


def with_split_retry(budget: MemoryBudget, conf: TpuConf,
                     batch: DeviceBatch,
                     attempt: Callable[[DeviceBatch], T]
                     ) -> Iterator[T]:
    """Run `attempt(batch)`; on OOM spill + replay, then recursively halve
    the batch (withSplitAndRetry).  Yields one result per final sub-batch
    in row order."""
    if not conf.get(RETRY_ENABLED):
        yield attempt(batch)
        return
    from ..obs.tracer import get_active
    max_splits = conf.get(RETRY_MAX_SPLITS)
    max_attempts = max(int(conf.get(RETRY_MAX_ATTEMPTS)), 1)
    pending: List[tuple] = [(batch, 0)]          # (batch, splits so far)
    while pending:
        b, depth = pending.pop(0)
        done = False
        last_oom = None
        for i in range(max_attempts):
            with budget.track_attempt() as scope:
                try:
                    result = attempt(b)
                    done = True
                except Exception as e:           # noqa: BLE001
                    err, oom = e, is_oom_error(e)
            if done:
                yield result
                break
            budget.rollback_attempt(scope)
            if not oom:
                raise err
            last_oom = err
            if i + 1 < max_attempts:
                budget.metrics["oom_retries"] += 1
                OOM_RETRIES.inc()
                get_active().instant("oom_retry", "runtime", depth=depth,
                                     attempt=i + 1)
                budget.spill_all()
        if done:
            continue
        if depth >= max_splits:
            # the split ladder is exhausted: escalate as the SPLIT
            # variant so the query-level ladder (plan/overrides.py)
            # knows splitting cannot help and replays through the
            # out-of-core rung before the final whole-query replay
            raise TpuSplitAndRetryOOM(
                f"OOM persists after {depth} splits") from last_oom
        budget.metrics["batch_splits"] += 1
        BATCH_SPLITS.inc()
        get_active().instant("batch_split", "runtime", depth=depth + 1)
        halves = split_batch(b, conf)
        pending[:0] = [(h, depth + 1) for h in halves]
