"""Concurrency throttle — the GpuSemaphore role.

Reference: GpuSemaphore.scala:51 — `spark.rapids.sql.concurrentGpuTasks`
(default 2) tasks hold permits before touching the device, so concurrent
tasks cannot collectively exceed device memory; permits release around
host-only phases.

TPU shape: one process-wide semaphore sized by
`spark.rapids.tpu.sql.concurrentTpuTasks`; each running query (and each
shuffle/scan worker doing device uploads) holds a permit for the duration
of its device work.  The memory budget (runtime/memory.py) bounds bytes;
the semaphore bounds concurrent *holders*, which is what keeps worst-case
transient allocations (K concurrent programs' scratch) in check."""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from ..config import CONCURRENT_TPU_TASKS, TpuConf

_LOCK = threading.Lock()
_SEMS: dict = {}        # size -> semaphore: stable per configured size


def _semaphore(conf: TpuConf) -> threading.BoundedSemaphore:
    """One stable semaphore per configured size — rebuilding on a size
    change would hand fresh unblocked permits to in-flight holders."""
    n = conf.get(CONCURRENT_TPU_TASKS)
    with _LOCK:
        sem = _SEMS.get(n)
        if sem is None:
            sem = _SEMS[n] = threading.BoundedSemaphore(n)
        return sem


@contextmanager
def device_permit(conf: TpuConf, metrics: Optional[dict] = None):
    """Hold one device permit; blocks when concurrentTpuTasks are active.
    Time spent blocked is surfaced as the semaphore-wait metric
    (GpuTaskMetrics semaphore-wait analogue).

    `metrics` defaults to the active query's metrics dict (the tracer
    binds ExecContext.metrics for its scope), so call sites that cannot
    reach an ExecContext — shuffle/scan worker threads — still populate
    the wait accumulator instead of silently dropping it."""
    import time
    from ..obs.registry import SEMAPHORE_WAIT_MS
    from ..obs.tracer import get_active
    tracer = get_active()
    if metrics is None:
        metrics = getattr(tracer, "metrics", None)
    sem = _semaphore(conf)
    t0 = time.perf_counter()
    sem.acquire()
    waited = time.perf_counter() - t0
    # always-on wait distribution: one observation per acquisition, so
    # count == acquisitions and contention shows up in the tail buckets
    SEMAPHORE_WAIT_MS.observe(waited * 1000.0)
    if metrics is not None:
        metrics["semaphore_wait_ms"] = metrics.get(
            "semaphore_wait_ms", 0.0) + waited * 1000.0
    if waited >= 0.001:
        tracer.instant("semaphore_wait", "runtime",
                       wait_ms=round(waited * 1000.0, 3))
    try:
        yield
    finally:
        sem.release()
