"""Background compile service: AOT compilation off the query thread.

ROADMAP item 3's last leg: whole-plan compiles are the cold-start wall,
and a split plan (exec/compiled.py SplitCompiledPlan) compiles its
segments strictly in sequence — segment i+1's compile starts only after
segment i's seam sync.  XLA compilation releases the GIL, so a small
thread pool can overlap that work with device execution (and with other
compiles: bench.py --compile-only drives the whole suite's cold
compiles through this pool concurrently to pre-populate the persistent
cache).

Contract:

  * `submit(key, fn)` runs `fn` on the pool exactly once per live key
    (duplicate submissions return the in-flight task).  `fn` returns
    the compiled object; any exception — including injected `compile`
    chaos faults, which fire inside `fn` on the service thread via the
    submitting query's own injector — is captured and re-raised on the
    CONSUMING thread by `task.wait()`, so the existing recovery ladders
    (OOM -> eager fallback, fatal -> crash capture) see background
    failures exactly where they would see inline ones.
  * `take(key)` pops the task for consumption; mispredicted speculative
    tasks that nobody takes age out of the bounded task map (their
    threads still finish; the results are just dropped).
  * Every task's wall time lands in the `tpu_compile_background_ms`
    histogram (obs/registry.py).

The pool is process-wide and lazily sized from the FIRST conf that
touches it (spark.rapids.tpu.compile.background.threads).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..config import (COMPILE_BG_ENABLED, COMPILE_BG_THREADS, TpuConf)

#: dropped-oldest bound on the task map: speculative keys nobody
#: consumes must not accumulate across a long session
_MAX_TASKS = 128


class CompileTask:
    """One background compile: an Event-guarded (result | exception)."""

    __slots__ = ("key", "done", "result", "exc", "ms")

    def __init__(self, key):
        self.key = key
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.ms = 0.0

    def wait(self, timeout: Optional[float] = 600.0):
        """Block for the compile; re-raise its exception on THIS thread
        (the chaos-threading seam: an injected fault crosses the pool
        boundary here)."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"background compile {self.key!r} did not finish "
                f"within {timeout}s")
        if self.exc is not None:
            raise self.exc
        return self.result


class CompileService:
    def __init__(self, threads: int):
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix="tpu-compile")
        self._tasks: Dict[object, CompileTask] = {}
        self._lock = threading.Lock()

    def submit(self, key, fn: Callable[[], object]) -> CompileTask:
        """Schedule `fn` under `key` (idempotent per live key)."""
        with self._lock:
            task = self._tasks.get(key)
            if task is not None:
                return task
            task = CompileTask(key)
            self._tasks[key] = task
            while len(self._tasks) > _MAX_TASKS:
                self._tasks.pop(next(iter(self._tasks)))

        def run():
            t0 = time.perf_counter()
            try:
                task.result = fn()
            except BaseException as e:              # noqa: BLE001
                task.exc = e
            finally:
                task.ms = (time.perf_counter() - t0) * 1000.0
                try:
                    from ..obs.registry import COMPILE_BG_MS
                    COMPILE_BG_MS.observe(task.ms)
                finally:
                    task.done.set()

        self._pool.submit(run)
        return task

    def take(self, key) -> Optional[CompileTask]:
        """Pop the task for `key` — the consumer owns its result (and
        its exception).  None when never submitted / already aged out."""
        with self._lock:
            return self._tasks.pop(key, None)

    def pending(self) -> int:
        with self._lock:
            return sum(1 for t in self._tasks.values()
                       if not t.done.is_set())


_SERVICE: Optional[CompileService] = None
_SERVICE_LOCK = threading.Lock()


def get_service(conf: TpuConf) -> CompileService:
    """The process-wide compile service (pool sized by the first conf)."""
    global _SERVICE
    if _SERVICE is None:
        with _SERVICE_LOCK:
            if _SERVICE is None:
                _SERVICE = CompileService(int(conf.get(COMPILE_BG_THREADS)))
    return _SERVICE


def background_enabled(conf: TpuConf) -> bool:
    return bool(conf.get(COMPILE_BG_ENABLED))
