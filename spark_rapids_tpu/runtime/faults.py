"""Site-addressable deterministic fault injection — the chaos harness.

Reference: the RAPIDS plugin proves its recovery paths by injecting
faults (`spark.rapids.sql.test.injectRetryOOM` via RmmSpark,
GpuCoreDumpHandler drills, SURVEY §5).  This module generalizes that
idea from "one synthetic OOM knob" to a harness where ANY layer that can
fail in production carries a *named injection site*, and a conf spec
(`spark.rapids.tpu.test.faults`) arms deterministic faults at those
sites:

    site:kind:trigger[;site:kind:trigger...]

    spill_read:corrupt:nth=2          # corrupt the 2nd spill block read
    reserve:oom:every=3               # OOM every 3rd budget reservation
    shuffle_fetch:ioerror:p=0.1,seed=7  # 10% of fetches fail (seeded)
    execute:fatal:nth=5               # wedge the device on batch 5

Sites (the layers that can actually fail — see `SITES`):
  reserve, compile, execute, h2d, d2h, spill_write, spill_read,
  shuffle_write, shuffle_fetch, exchange, serving, result_cache,
  history, memattr, ooc, kernel, worker, deadline.

Kinds:
  oom     -> TpuRetryOOM       (the OOM retry ladder owns recovery)
  ioerror -> InjectedIOError   (OSError: the bounded IO retry ladder,
                                runtime/retry.py retry_io, owns recovery)
  corrupt -> flips a payload byte in the on-disk block so the REAL
             checksum verification path detects it (spill_read only)
  fatal   -> InjectedFatalError (classified FATAL_DEVICE: crash dump +
                                 FatalDeviceError, runtime/failure.py);
             at the worker site: the victim worker process dies with a
             classified dump and its queries redrive
  error   -> InjectedQueryError (a plain query error, class QUERY)
  timeout -> serving: the AdmissionTimeout backpressure signal;
             deadline: a synthetic per-query deadline expiry at a
             cancellation checkpoint
  kill    -> (worker only) SIGKILL the victim worker process mid-query
  hang    -> (worker only) wedge the victim worker (heartbeats stop;
             the health monitor kills it past the miss window)

Triggers fire deterministically: `nth=N` fires exactly once on the Nth
hit of the site; `every=N` on every Nth hit; `p=F[,seed=N]` per-hit with
a counter-seeded splitmix64 (NOT python's salted hash — runs reproduce);
`always` on every hit.  Each firing emits a `fault_injected` obs instant
and is appended to the injector's `log`, which crash dumps embed so a
post-mortem shows exactly what chaos did (the injected-fault record).

The disabled path is a no-op: `get_injector(conf)` returns the shared
`NULL_INJECTOR` when the conf has no fault spec, and `fire()` on it does
nothing — call sites never branch.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional

from ..config import TEST_FAULTS, TpuConf
from .memory import TpuRetryOOM

#: site name -> which layer it interrupts (the registry the coverage
#: lint `scripts/check_fault_sites.py` asserts chaos tests exercise)
SITES: Dict[str, str] = {
    "reserve": "MemoryBudget.reserve admission (runtime/memory.py)",
    "compile": "whole-plan XLA compile (exec/compiled.py) — fires on "
               "the compiling thread, including background segment "
               "compiles on the compile service "
               "(runtime/compile_service.py), whose faults re-raise on "
               "the consuming query thread",
    "execute": "per-batch physical root stream (runtime/failure.py "
               "install_fault_injection)",
    "h2d": "host->device upload transitions",
    "d2h": "device->host fetch transitions",
    "spill_write": "Spillable host->disk block write (runtime/memory.py)",
    "spill_read": "Spillable disk block read-back (runtime/memory.py)",
    "shuffle_write": "shuffle map-output write (exec/exchange.py)",
    "shuffle_fetch": "shuffle reduce-side fetch (exec/exchange.py)",
    "exchange": "mesh/multihost collective exchange (parallel/)",
    "serving": "ServingRuntime admission (serving/runtime.py) — fires "
               "per submit; kind 'timeout' raises the admission-timeout "
               "backpressure signal (TenantSession.collect retries it "
               "once, the bounded-admission recovery rung)",
    "result_cache": "serving plan+result cache read (serving/cache.py) "
                    "— kind 'corrupt' flips a byte in the cached IPC "
                    "payload so the REAL checksum verification detects "
                    "it, drops the entry and recomputes",
    "history": "performance-history store write (obs/history.py) — "
               "fires once per recorded query on the JSONL append "
               "path. Kind 'ioerror' is absorbed by the store itself: "
               "the entry is SKIPPED "
               "(tpu_history_records_total{outcome=io_error}) and the "
               "query's result is untouched — telemetry must never "
               "fail work. 'fatal' propagates through the query's "
               "crash-capture scope as a classified FATAL_DEVICE dump "
               "naming the site",
    "memattr": "memory-attribution census read (obs/memattr.py via "
               "exec/compiled.py) — fires once per profiled segment "
               "dispatch when the plane is armed "
               "(profile.segments + profile.memory). Kind 'ioerror' "
               "is absorbed at the bracket: that dispatch's HBM "
               "sample is SKIPPED (memattr_census_skipped metric) and "
               "the query result is bit-identical — sampling must "
               "never cost work. 'fatal' propagates through the "
               "query's crash-capture scope as a classified "
               "FATAL_DEVICE dump embedding the PARTIAL HBM timeline "
               "collected up to the fault",
    "ooc": "out-of-core tier boundaries (exec/ooc.py via exec/join.py "
           "hash-join spill partitioning, exec/ooc_agg.py spill-"
           "partitioned aggregation, exec/ooc_sort.py merge passes) — "
           "fires once per partition pass / merge pass with the "
           "operator, bucket and depth in the injected-fault record, "
           "AFTER the matching `ooc_state` instant hit the flight "
           "recorder.  Kind 'oom' rides the normal OOM ladder (the "
           "query replays bit-identically — the OOC context is already "
           "forced on the replay); 'fatal' surfaces as a classified "
           "FATAL_DEVICE crash dump whose flight-recorder tail embeds "
           "the OOC bucket state the pass was in",
    "worker": "serving worker-process dispatch (serving/workers.py) — "
              "fires SUPERVISOR-side, once per query dispatched to a "
              "worker process (redrives fire it again), so nth= "
              "triggers stay deterministic across the pool. Kind "
              "'kill' SIGKILLs the victim worker the moment its "
              "'started' frame confirms the query is mid-flight; "
              "'hang' wedges the victim (heartbeats and request "
              "processing stop — the health monitor detects the "
              "missed-heartbeat window and kills it); 'fatal' arms "
              "the in-worker fatal injector so the query dies with a "
              "classified FATAL_DEVICE crash dump and the worker "
              "self-terminates. All three lose only the victim's "
              "in-flight queries, which REDRIVE on a surviving worker "
              "(serving.redrive.maxAttempts) bit-identically",
    "fleet": "observability federation fold (serving/workers.py "
             "_reader_loop): fires SUPERVISOR-side once per heartbeat "
             "frame that carries telemetry (registry snapshot / flight "
             "tail). Kind 'ioerror' drops that ONE frame whole — "
             "cumulative-set federation converges on the next beat and "
             "the in-flight query stays bit-identical; 'fatal' writes "
             "a classified crash dump naming the site and drops the "
             "frame, with the supervisor (and the pool) surviving — "
             "telemetry must never take serving down",
    "deadline": "cooperative cancellation checkpoints (exec/plan.py "
                "ExecContext.checkpoint): the compiled-plan seam "
                "brackets, the per-batch result stream, out-of-core "
                "partition/merge passes, exchange rounds and spill-all "
                "sweeps. Kind 'timeout' injects a synthetic deadline "
                "expiry at the Nth checkpoint — the query cancels "
                "exactly as if serving.deadlineMs had elapsed there, "
                "and the ticket's whole device reservation is released "
                "(DeviceCensus shows zero residual)",
    "kernel": "Pallas kernel-tier dispatch (ops/pallas/) and encoded-"
              "execution dispatch (ops/encodings.py) — fires each "
              "time an operator elects a hand-written kernel or a "
              "code-space/narrow-lane path, with the kernel family / "
              "encoded site in the injected-fault record. Kind 'oom' "
              "is caught by the dispatch gate itself: the operator "
              "sheds to the sort-based portable tier (or the encoded "
              "dispatch to the decoded tier) bit-identically "
              "(tpu_kernel_fallback_total{reason=oom} / "
              "tpu_encoded_dispatch_total{outcome=oom_shed}); 'fatal' "
              "surfaces as a classified FATAL_DEVICE crash dump whose "
              "injected-fault record names the kernel",
}

KINDS = ("oom", "ioerror", "corrupt", "fatal", "error", "timeout",
         "kill", "hang")

#: kinds the corrupt action makes sense for: it needs an on-disk block
#: path (spill_read) or an in-memory payload bytearray (result_cache)
#: in the fire() info to flip bytes in
_CORRUPT_SITES = ("spill_read", "result_cache")

#: the timeout kind models admission backpressure (serving) and
#: deadline expiry (the cancellation checkpoints)
_TIMEOUT_SITES = ("serving", "deadline")

#: process-level faults: only the supervised worker pool can SIGKILL or
#: wedge a process, so kill/hang arm only at the worker site — and the
#: worker site accepts only process-level kinds
_WORKER_KINDS = ("kill", "hang", "fatal")

#: the federation fold can lose a frame (ioerror) or dump-and-survive
#: (fatal); nothing else is meaningful for pure telemetry
_FLEET_KINDS = ("ioerror", "fatal")


class InjectedIOError(OSError):
    """Synthetic transient host-IO failure (classified 'io'; the bounded
    IO retry ladder recovers it)."""


class InjectedQueryError(RuntimeError):
    """Synthetic plain query error (classified 'query')."""


class InjectedWorkerFault(Exception):
    """Control-flow signal for `worker:{kill,hang,fatal}` rules: raised
    by fire('worker') SUPERVISOR-side at dispatch; the WorkerPool
    catches it and acts on the victim process (SIGKILL after the
    started frame / wedge the worker / arm the in-worker fatal
    injector).  Never escapes the pool."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


@dataclasses.dataclass
class FaultRule:
    site: str
    kind: str
    nth: Optional[int] = None        # fire once, on the Nth hit
    every: Optional[int] = None      # fire on every Nth hit
    p: Optional[float] = None        # per-hit probability (seeded)
    seed: int = 0
    always: bool = False
    hits: int = 0
    fired: int = 0

    def should_fire(self) -> bool:
        self.hits += 1
        if self.nth is not None:
            return self.hits == self.nth
        if self.every is not None:
            return self.hits % self.every == 0
        if self.p is not None:
            return _splitmix_uniform(self.seed, self.hits) < self.p
        return self.always


def _splitmix_uniform(seed: int, counter: int) -> float:
    """Deterministic per-(seed, counter) uniform in [0, 1) — python's
    `hash` is process-salted and would make p= rules unreproducible."""
    x = (seed * 0x9E3779B97F4A7C15 + counter * 0xBF58476D1CE4E5B9) \
        & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    return x / 2.0 ** 64


def parse_spec(spec: str) -> List[FaultRule]:
    """`site:kind:trigger[;...]` -> rules.  Raises ValueError on any
    unknown site/kind or malformed trigger (the conf checker surfaces
    this at set time, not at the injection site)."""
    rules: List[FaultRule] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        pieces = part.split(":")
        if len(pieces) != 3:
            raise ValueError(f"fault rule {part!r}: want site:kind:trigger")
        site, kind, trigger = (p.strip() for p in pieces)
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(known: {sorted(SITES)})")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(known: {list(KINDS)})")
        if kind == "corrupt" and site not in _CORRUPT_SITES:
            raise ValueError(f"kind 'corrupt' only applies to sites "
                             f"{list(_CORRUPT_SITES)}, got {site!r}")
        if kind == "timeout" and site not in _TIMEOUT_SITES:
            raise ValueError(f"kind 'timeout' only applies to sites "
                             f"{list(_TIMEOUT_SITES)}, got {site!r}")
        if kind in ("kill", "hang") and site != "worker":
            raise ValueError(f"kind {kind!r} only applies to site "
                             f"'worker', got {site!r}")
        if site == "worker" and kind not in _WORKER_KINDS:
            raise ValueError(f"site 'worker' only takes process-level "
                             f"kinds {list(_WORKER_KINDS)}, got {kind!r}")
        if site == "fleet" and kind not in _FLEET_KINDS:
            raise ValueError(f"site 'fleet' only takes telemetry kinds "
                             f"{list(_FLEET_KINDS)}, got {kind!r}")
        rule = FaultRule(site, kind)
        if trigger == "always":
            rule.always = True
        else:
            for kv in trigger.split(","):
                if "=" not in kv:
                    raise ValueError(f"fault trigger {trigger!r}: "
                                     f"want key=value[,key=value]")
                k, v = (x.strip() for x in kv.split("=", 1))
                try:
                    if k == "nth":
                        rule.nth = int(v)
                    elif k == "every":
                        rule.every = int(v)
                    elif k == "p":
                        rule.p = float(v)
                    elif k == "seed":
                        rule.seed = int(v)
                    else:
                        raise ValueError(f"unknown trigger key {k!r}")
                except ValueError as e:
                    raise ValueError(f"fault trigger {trigger!r}: {e}")
            if rule.nth is None and rule.every is None and rule.p is None:
                raise ValueError(f"fault trigger {trigger!r}: need one of "
                                 f"nth=/every=/p=/always")
            if (rule.nth is not None and rule.nth < 1) or \
                    (rule.every is not None and rule.every < 1):
                raise ValueError(f"fault trigger {trigger!r}: counts are "
                                 f"1-based (must be >= 1)")
            if rule.p is not None and not 0.0 <= rule.p <= 1.0:
                raise ValueError(f"fault trigger {trigger!r}: p must be "
                                 f"in [0, 1]")
        rules.append(rule)
    return rules


def check_spec(spec: str) -> Optional[str]:
    """Conf-checker form of parse_spec: error string or None."""
    try:
        parse_spec(spec)
        return None
    except ValueError as e:
        return str(e)


class FaultInjector:
    """Armed injector for one conf's fault spec.  Thread-safe: shuffle
    and spill worker threads hit sites concurrently; hit counters are
    global per rule so `nth=` means the Nth hit process-wide for this
    conf, whichever thread lands it."""

    enabled = True

    def __init__(self, spec: str):
        self.rules = parse_spec(spec)
        self._by_site: Dict[str, List[FaultRule]] = {}
        for r in self.rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._lock = threading.Lock()
        self.log: List[dict] = []        # the injected-fault record

    def has_site(self, site: str) -> bool:
        return site in self._by_site

    def fire(self, site: str, **info) -> None:
        """Evaluate every rule armed at `site`; the first that triggers
        acts (raise / corrupt).  Each firing is logged and emits a
        `fault_injected` obs instant before the fault surfaces."""
        rules = self._by_site.get(site)
        if not rules:
            return
        with self._lock:
            fired = None
            for r in rules:
                if r.should_fire():
                    r.fired += 1
                    fired = r
                    break
            if fired is None:
                return
            rec = {"site": site, "kind": fired.kind, "hit": fired.hits,
                   "ts": time.time()}
            rec.update({k: str(v) for k, v in info.items()
                        if k != "payload"})   # bulk bytes stay out of logs
            if len(self.log) < 256:
                self.log.append(rec)
        from ..obs.registry import FAULTS_INJECTED
        FAULTS_INJECTED.inc(site=site, kind=fired.kind)
        from ..obs.tracer import get_active
        get_active().instant("fault_injected", "chaos", site=site,
                             kind=fired.kind, hit=fired.hits)
        self._act(fired, site, info)

    @staticmethod
    def _act(rule: FaultRule, site: str, info: dict) -> None:
        kind = rule.kind
        msg = (f"injected {kind} at fault site {site!r} "
               f"(hit #{rule.hits}, spark.rapids.tpu.test.faults)")
        if site == "worker":
            # process-level faults (kill/hang/fatal) act on the VICTIM
            # process, not the firing thread: the supervisor catches
            # this and kills/wedges/arms the dispatched worker
            raise InjectedWorkerFault(kind, msg)
        if kind == "timeout" and site == "deadline":
            from ..exec.plan import InjectedDeadlineExceeded
            raise InjectedDeadlineExceeded(msg)
        if kind == "oom":
            raise TpuRetryOOM(msg)
        if kind == "ioerror":
            raise InjectedIOError(msg)
        if kind == "fatal":
            from .failure import InjectedFatalError
            raise InjectedFatalError(msg)
        if kind == "error":
            raise InjectedQueryError(msg)
        if kind == "timeout":
            from ..serving.runtime import InjectedAdmissionTimeout
            raise InjectedAdmissionTimeout(msg)
        if kind == "corrupt":
            payload = info.get("payload")
            if isinstance(payload, bytearray) and payload:
                # in-memory block (serving result cache): flip a payload
                # byte past the Arrow IPC stream header so the REAL
                # checksum verification path detects the damage
                off = min(64, len(payload) - 1)
                payload[off] ^= 0xFF
                return
            path = info.get("path")
            if path and os.path.exists(path):
                _corrupt_block(path)
            return
        raise AssertionError(f"unhandled fault kind {kind}")


def _corrupt_block(path: str) -> None:
    """Flip one payload byte past the 24-byte block header so the REAL
    checksum verification (native/spillio) detects the damage — the
    chaos suite exercises detection, not a simulation of it."""
    size = os.path.getsize(path)
    off = 24 + 8 if size > 32 else max(size - 1, 0)
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


class NullInjector:
    """Disabled path: every call is a no-op (the NULL_TRACER pattern)."""

    enabled = False
    log: tuple = ()

    def has_site(self, site: str) -> bool:
        return False

    def fire(self, site: str, **info) -> None:
        return None


NULL_INJECTOR = NullInjector()


def get_injector(conf: TpuConf):
    """The injector armed for this conf (cached on the conf instance so
    hit counters are stable for the conf's lifetime), or NULL_INJECTOR
    when no fault spec is set."""
    inj = getattr(conf, "_fault_injector", None)
    if inj is None:
        spec = str(conf.get(TEST_FAULTS) or "")
        inj = FaultInjector(spec) if spec.strip() else NULL_INJECTOR
        conf._fault_injector = inj
    return inj


# The ACTIVE injector: sites with no conf in reach (the mesh/multihost
# exchange collectives) report here.  Installed for the duration of a
# query's instrumented scope (plan/overrides.py), mirroring the active
# tracer — and like it, the binding is THREAD-LOCAL with a
# single-active-scope process fallback, so concurrent queries (the
# serving plane) cannot arm each other's chaos rules or disarm a still-
# running query's injector at scope exit.
_TLS_ACTIVE = threading.local()
_ACTIVE_LOCK = threading.Lock()
_ACTIVE_SET: dict = {}           # id(injector) -> injector, in scope
_FALLBACK: object = NULL_INJECTOR


def set_active(injector) -> None:
    global _FALLBACK
    prev = getattr(_TLS_ACTIVE, "injector", None)
    _TLS_ACTIVE.injector = injector
    with _ACTIVE_LOCK:
        if prev is not None and getattr(prev, "enabled", False):
            _ACTIVE_SET.pop(id(prev), None)
        if getattr(injector, "enabled", False):
            _ACTIVE_SET[id(injector)] = injector
        _FALLBACK = (next(iter(_ACTIVE_SET.values()))
                     if len(_ACTIVE_SET) == 1 else NULL_INJECTOR)


def get_active_injector():
    inj = getattr(_TLS_ACTIVE, "injector", None)
    if inj is not None and inj is not NULL_INJECTOR:
        return inj
    return _FALLBACK


def fire_active(site: str, **info) -> None:
    """Fire `site` on the active injector (conf-less call sites)."""
    get_active_injector().fire(site, **info)
