"""Spark-compatible logical type system for the TPU-native engine.

Mirrors the role of Spark's DataType hierarchy plus the reference's TypeSig
algebra (reference: sql-plugin/.../TypeChecks.scala:168-757) which declares,
per operator/expression, which input/output types are supported on the
accelerator.  Unsupported types cause a per-operator CPU fallback with a
recorded reason instead of a query failure.

TPU mapping notes:
  - Integral/floating types map 1:1 to jnp dtypes.
  - DATE   -> int32 days since epoch (Spark internal representation).
  - TIMESTAMP -> int64 microseconds since epoch UTC (Spark internal).
  - STRING -> dictionary-encoded on device (int32 codes + host dictionary) or
    raw (offsets,bytes) tensors for byte-level kernels; see columnar/device.py.
  - DECIMAL(p<=18) -> int64 unscaled value; DECIMAL(p>18) -> dual-int64 lanes
    (hi/lo) since TPU has no native int128.
  - NULL literal type -> carried logically; materializes as all-null int32.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class DataType:
    """Base logical type. Instances are value objects: equality by fields."""

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))

    def __repr__(self):
        return self.simple_string

    @property
    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    pass


class ByteType(IntegralType):
    pass


class ShortType(IntegralType):
    pass


class IntegerType(IntegralType):
    @property
    def simple_string(self):
        return "int"


class LongType(IntegralType):
    @property
    def simple_string(self):
        return "bigint"


class FloatType(FractionalType):
    pass


class DoubleType(FractionalType):
    pass


class StringType(DataType):
    pass


class BinaryType(DataType):
    pass


class DateType(DataType):
    pass


class TimestampType(DataType):
    pass


class NullType(DataType):
    @property
    def simple_string(self):
        return "void"


class DecimalType(FractionalType):
    MAX_PRECISION = 38

    def __init__(self, precision: int = 10, scale: int = 0):
        if not (0 < precision <= self.MAX_PRECISION):
            raise ValueError(f"decimal precision {precision} out of range")
        if not (0 <= scale <= precision):
            raise ValueError(f"decimal scale {scale} invalid for precision {precision}")
        self.precision = precision
        self.scale = scale

    @property
    def simple_string(self):
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_wide(self) -> bool:
        """True when the unscaled value does not fit an int64 (precision > 18)."""
        return self.precision > 18


class ArrayType(DataType):
    def __init__(self, element_type: DataType, contains_null: bool = True):
        self.element_type = element_type
        self.contains_null = contains_null

    @property
    def simple_string(self):
        return f"array<{self.element_type.simple_string}>"


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


class StructType(DataType):
    def __init__(self, fields):
        self.fields = tuple(fields)

    @property
    def simple_string(self):
        inner = ",".join(f"{f.name}:{f.data_type.simple_string}" for f in self.fields)
        return f"struct<{inner}>"

    @property
    def names(self):
        return [f.name for f in self.fields]

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __getitem__(self, name: str) -> StructField:
        return self.fields[self.field_index(name)]

    def __iter__(self):
        return iter(self.fields)

    def __len__(self):
        return len(self.fields)


class MapType(DataType):
    def __init__(self, key_type: DataType, value_type: DataType,
                 value_contains_null: bool = True):
        self.key_type = key_type
        self.value_type = value_type
        self.value_contains_null = value_contains_null

    @property
    def simple_string(self):
        return f"map<{self.key_type.simple_string},{self.value_type.simple_string}>"


# Singletons for the simple types (Spark-style convenience).
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()


_NP_DTYPES = {
    BooleanType: np.bool_,
    ByteType: np.int8,
    ShortType: np.int16,
    IntegerType: np.int32,
    LongType: np.int64,
    FloatType: np.float32,
    DoubleType: np.float64,
    DateType: np.int32,        # days since epoch
    TimestampType: np.int64,   # micros since epoch
    NullType: np.int32,
}


def physical_np_dtype(dt: DataType):
    """numpy dtype of the on-device *storage* representation of `dt`.

    Strings are dictionary codes (int32); narrow decimals are int64 unscaled;
    DOUBLE is stored as int64 f64-bit-patterns because this TPU's f64 is a
    lossy float32-pair emulation (kernels bitcast to f64 only for compute —
    see columnar/device.py module docs).  Wide decimals (p>18) use two int64
    lanes and have no single np dtype — callers handle them explicitly.
    """
    if isinstance(dt, StringType):
        return np.int32
    if isinstance(dt, DoubleType):
        return np.int64
    if isinstance(dt, DecimalType):
        # narrow: int64 unscaled.  wide (p>18): the PRIMARY lane is still
        # int64 — host columns carry a data_hi lane alongside; device-
        # computed wide results are single-lane int64 with overflow-to-null
        # (ops/decimal.py module docs).
        return np.int64
    try:
        return _NP_DTYPES[type(dt)]
    except KeyError:
        raise TypeError(f"no physical dtype for {dt}") from None


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


# Numeric widening order for implicit binary-op promotion (Spark semantics).
_NUMERIC_RANK = {ByteType: 0, ShortType: 1, IntegerType: 2, LongType: 3,
                 FloatType: 4, DoubleType: 5}


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Spark's binary arithmetic common type for non-decimal numerics."""
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise TypeError("decimal promotion handled by DecimalPrecision rules")
    ra, rb = _NUMERIC_RANK[type(a)], _NUMERIC_RANK[type(b)]
    winner = a if ra >= rb else b
    return winner


# ---------------------------------------------------------------------------
# TypeSig: declarative per-operator type support (reference TypeChecks.scala).
# ---------------------------------------------------------------------------

_ALL_TYPE_TAGS = (
    "BOOLEAN BYTE SHORT INT LONG FLOAT DOUBLE STRING BINARY DATE TIMESTAMP "
    "NULL DECIMAL64 DECIMAL128 ARRAY STRUCT MAP"
).split()


def _tag_of(dt: DataType) -> str:
    if isinstance(dt, DecimalType):
        return "DECIMAL128" if dt.is_wide else "DECIMAL64"
    if isinstance(dt, ArrayType):
        return "ARRAY"
    if isinstance(dt, StructType):
        return "STRUCT"
    if isinstance(dt, MapType):
        return "MAP"
    return {
        BooleanType: "BOOLEAN", ByteType: "BYTE", ShortType: "SHORT",
        IntegerType: "INT", LongType: "LONG", FloatType: "FLOAT",
        DoubleType: "DOUBLE", StringType: "STRING", BinaryType: "BINARY",
        DateType: "DATE", TimestampType: "TIMESTAMP", NullType: "NULL",
    }[type(dt)]


class TypeSig:
    """A set of supported type tags, with optional nested-type signature.

    Combinators mirror the reference's algebra: `+` union, `-` removal.
    """

    def __init__(self, tags=frozenset(), nested: Optional["TypeSig"] = None):
        self.tags = frozenset(tags)
        self.nested = nested

    def __add__(self, other: "TypeSig") -> "TypeSig":
        nested = self.nested or other.nested
        if self.nested and other.nested:
            nested = self.nested + other.nested
        return TypeSig(self.tags | other.tags, nested)

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.tags - other.tags, self.nested)

    def with_nested(self, nested: "TypeSig") -> "TypeSig":
        return TypeSig(self.tags, nested)

    def supports(self, dt: DataType) -> bool:
        tag = _tag_of(dt)
        if tag not in self.tags:
            return False
        inner = self.nested or self
        if isinstance(dt, ArrayType):
            return inner.supports(dt.element_type)
        if isinstance(dt, StructType):
            return all(inner.supports(f.data_type) for f in dt.fields)
        if isinstance(dt, MapType):
            return inner.supports(dt.key_type) and inner.supports(dt.value_type)
        return True

    def reason_not_supported(self, dt: DataType) -> Optional[str]:
        if self.supports(dt):
            return None
        return f"type {dt.simple_string} is not supported"

    def __repr__(self):
        return f"TypeSig({sorted(self.tags)})"


def _sig(*tags) -> TypeSig:
    return TypeSig(frozenset(tags))


class T:
    """Namespace of common TypeSigs (reference TypeSig object:543)."""
    BOOLEAN = _sig("BOOLEAN")
    INTEGRAL = _sig("BYTE", "SHORT", "INT", "LONG")
    FP = _sig("FLOAT", "DOUBLE")
    DECIMAL_64 = _sig("DECIMAL64")
    DECIMAL_128 = _sig("DECIMAL64", "DECIMAL128")
    NUMERIC = INTEGRAL + FP + DECIMAL_128
    STRING = _sig("STRING")
    BINARY = _sig("BINARY")
    DATE = _sig("DATE")
    TIMESTAMP = _sig("TIMESTAMP")
    DATETIME = DATE + TIMESTAMP
    NULL = _sig("NULL")
    ARRAY = _sig("ARRAY")
    STRUCT = _sig("STRUCT")
    MAP = _sig("MAP")
    NESTED = ARRAY + STRUCT + MAP
    ORDERABLE = NUMERIC + STRING + BOOLEAN + DATETIME + NULL
    COMPARABLE = ORDERABLE
    ALL_SIMPLE = NUMERIC + STRING + BINARY + BOOLEAN + DATETIME + NULL
    ALL = (ALL_SIMPLE + NESTED).with_nested(ALL_SIMPLE + NESTED)
    # What the device kernels handle today (grows as kernels are added).
    DEVICE_COMMON = NUMERIC + STRING + BOOLEAN + DATETIME + NULL
