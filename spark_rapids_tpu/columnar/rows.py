"""Row interop: Spark UnsafeRow-compatible row <-> columnar conversion.

Role of the reference's CudfUnsafeRow.java (bit-exact UnsafeRow layout
over device-produced row buffers), InternalRowToColumnarBatchIterator
(row->columnar building for the R2C transition) and the JNI RowConversion
kernels (SURVEY §2.4).  The JVM⇄TPU-worker bridge will speak either
Arrow IPC (wide tables) or this row format (the narrow-table fast path
Spark itself uses for shuffle rows), so both directions are implemented
here, vectorized with numpy over a packed row block.

UnsafeRow binary layout (Spark's UnsafeRow.java contract):
  [null bitset: ceil(nFields/64) * 8 bytes, little-endian words]
  [fixed region: 8 bytes per field —
     numeric/bool inline; decimal(p<=18) as unscaled long;
     string/binary as (offset << 32) | length, offset from row start]
  [variable region: var-len payloads, each 8-byte aligned]

Only types with a defined UnsafeRow encoding are supported; nested types
go through Arrow IPC instead (the same split the reference makes:
GpuColumnarToRowExec's accelerated path is fixed-width-only).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np
import pyarrow as pa

from .. import types as t


def _bitset_words(n_fields: int) -> int:
    return (n_fields + 63) // 64


def _is_varlen(dt: pa.DataType) -> bool:
    return (pa.types.is_string(dt) or pa.types.is_large_string(dt)
            or pa.types.is_binary(dt))


def _check_supported(schema: pa.Schema) -> None:
    for f in schema:
        dt = f.type
        ok = (pa.types.is_integer(dt) or pa.types.is_floating(dt)
              or pa.types.is_boolean(dt) or pa.types.is_date32(dt)
              or pa.types.is_timestamp(dt) or _is_varlen(dt)
              or (pa.types.is_decimal(dt) and dt.precision <= 18))
        if not ok:
            raise TypeError(f"no UnsafeRow encoding for column "
                            f"{f.name}: {dt} (use Arrow IPC)")


def batch_to_rows(rb: pa.RecordBatch) -> List[bytes]:
    """Columnar -> UnsafeRow bytes per row (GpuColumnarToRowExec role)."""
    _check_supported(rb.schema)
    n_fields = rb.num_columns
    nw = _bitset_words(n_fields)
    fixed_off = nw * 8

    cols = []
    for i in range(n_fields):
        arr = rb.column(i)
        dt = arr.type
        if pa.types.is_timestamp(dt):
            vals = arr.cast(pa.int64()).to_pylist()
        elif pa.types.is_date32(dt):
            vals = arr.cast(pa.int32()).to_pylist()
        else:
            vals = arr.to_pylist()
        cols.append((dt, vals))

    rows: List[bytes] = []
    for r in range(rb.num_rows):
        bitset = np.zeros(nw, np.uint64)
        fixed = np.zeros(n_fields, np.int64)
        var_parts: List[bytes] = []
        var_off = fixed_off + 8 * n_fields
        for i, (dt, vals) in enumerate(cols):
            v = vals[r]
            if v is None:
                bitset[i // 64] |= np.uint64(1) << np.uint64(i % 64)
                continue
            if _is_varlen(dt):
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                fixed[i] = (var_off << 32) | len(b)
                pad = (-len(b)) % 8
                var_parts.append(b + b"\x00" * pad)
                var_off += len(b) + pad
            elif pa.types.is_boolean(dt):
                fixed[i] = int(v)
            elif pa.types.is_floating(dt):
                if pa.types.is_float32(dt):
                    fixed[i] = np.frombuffer(
                        np.float32(v).tobytes() + b"\x00" * 4, np.int64)[0]
                else:
                    fixed[i] = np.frombuffer(
                        np.float64(v).tobytes(), np.int64)[0]
            elif pa.types.is_decimal(dt):
                fixed[i] = int(v.scaleb(dt.scale))
            else:   # ints, date32 (days), timestamp (micros) — all ints
                fixed[i] = int(v)
        rows.append(bitset.tobytes() + fixed.tobytes()
                    + b"".join(var_parts))
    return rows


def rows_to_batch(rows: Sequence[bytes],
                  schema: pa.Schema) -> pa.RecordBatch:
    """UnsafeRow bytes -> columnar batch (GpuRowToColumnarExec role).
    Fixed-width columns decode vectorized over a packed block."""
    _check_supported(schema)
    n_fields = len(schema)
    nw = _bitset_words(n_fields)
    fixed_off = nw * 8
    n = len(rows)
    if n == 0:
        return pa.RecordBatch.from_pydict(
            {f.name: [] for f in schema}, schema=schema)

    head_len = fixed_off + 8 * n_fields
    # packed head block: (n, head_len) uint8 -> vectorized field views
    head = np.empty((n, head_len), np.uint8)
    for r, row in enumerate(rows):
        if len(row) < head_len:
            raise ValueError(f"row {r}: {len(row)} bytes < header "
                             f"{head_len}")
        head[r] = np.frombuffer(row[:head_len], np.uint8)
    bitset = head[:, :fixed_off].copy().view(np.uint64).reshape(n, nw)
    fixed = head[:, fixed_off:].copy().view(np.int64).reshape(n, n_fields)

    arrays = []
    for i, f in enumerate(schema):
        dt = f.type
        nulls = (bitset[:, i // 64] >> np.uint64(i % 64)
                 ) & np.uint64(1) > 0
        raw = fixed[:, i]
        if _is_varlen(dt):
            vals = []
            for r in range(n):
                if nulls[r]:
                    vals.append(None)
                    continue
                packed = int(raw[r])
                off, ln = packed >> 32, packed & 0xFFFFFFFF
                b = rows[r][off:off + ln]
                vals.append(b.decode("utf-8")
                            if pa.types.is_string(dt) else b)
            arrays.append(pa.array(vals, dt))
            continue
        mask = nulls
        if pa.types.is_boolean(dt):
            vals = raw != 0
            arrays.append(pa.array(
                [None if m else bool(v) for m, v in zip(mask, vals)], dt)
                if mask.any() else pa.array(vals, dt))
        elif pa.types.is_float32(dt):
            vals = raw.view(np.uint64).astype(np.uint32).view(np.float32)
            arrays.append(pa.array(
                np.ma.masked_array(vals, mask=mask), dt, from_pandas=True))
        elif pa.types.is_float64(dt):
            vals = raw.view(np.float64)
            arrays.append(pa.array(
                np.ma.masked_array(vals, mask=mask), dt, from_pandas=True))
        elif pa.types.is_decimal(dt):
            import decimal as pydec
            arrays.append(pa.array(
                [None if m else pydec.Decimal(int(v)).scaleb(-dt.scale)
                 for m, v in zip(mask, raw)], dt))
        elif pa.types.is_date32(dt):
            arrays.append(pa.array(
                np.ma.masked_array(raw.astype(np.int32), mask=mask),
                pa.int32(), from_pandas=True).cast(dt))
        elif pa.types.is_timestamp(dt):
            arrays.append(pa.array(
                np.ma.masked_array(raw, mask=mask), pa.int64(),
                from_pandas=True).cast(dt))
        else:
            width = dt.bit_width // 8
            np_t = {1: np.int8, 2: np.int16, 4: np.int32,
                    8: np.int64}[width]
            if not pa.types.is_signed_integer(dt):
                np_t = {1: np.uint8, 2: np.uint16, 4: np.uint32,
                        8: np.uint64}[width]
            arrays.append(pa.array(
                np.ma.masked_array(raw.astype(np_t), mask=mask), dt,
                from_pandas=True))
    return pa.RecordBatch.from_arrays(arrays, schema=schema)
