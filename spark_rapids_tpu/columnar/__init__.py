from .host import (HostBatch, arrow_to_dtype, dtype_to_arrow, schema_to_struct,
                   struct_to_schema)
from .device import (DeviceBatch, DeviceColumn, bucket_capacity, to_device,
                     to_host, empty_device_batch)

__all__ = [
    "HostBatch", "arrow_to_dtype", "dtype_to_arrow", "schema_to_struct",
    "struct_to_schema", "DeviceBatch", "DeviceColumn", "bucket_capacity",
    "to_device", "to_host", "empty_device_batch",
]
