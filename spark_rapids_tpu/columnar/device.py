"""Device-side columnar batches for TPU execution.

Role of GpuColumnVector/ColumnarBatch in the reference (GpuColumnVector.java),
re-designed for XLA's compilation model instead of translated:

  * **Static-shape row bucketing.** XLA compiles one program per shape, so a
    per-batch dynamic row count would blow up the jit cache (SURVEY §7 hard
    part (f)).  Every device column is padded to a *capacity* drawn from a
    small geometric set of buckets; the logical `num_rows` travels alongside
    as data (a scalar passed into kernels), never as a shape.  Kernels mask
    rows `>= num_rows` out of every reduction/aggregation.

  * **Validity as a bool lane.** Spark's three-valued null semantics are
    carried as a dense bool array per column (True = valid).  Padding rows are
    invalid.  This fuses freely with elementwise compute on the VPU.

  * **Strings as dictionary codes.** TPUs have no ragged tensors; string
    columns are dictionary-encoded at the host boundary (int32 codes on
    device + a host-side pyarrow dictionary).  Equality/ordering/hash/groupby
    run on codes (order via a host-computed rank permutation of the sorted
    dictionary); byte-level kernels get (offsets, bytes) tensors on demand
    (ops/strings.py).

  * **Decimal(≤18,s) as int64 unscaled lanes**; wide decimal (>18) is a
    (hi, lo) int64 pair (TPU has no int128) — see ops/decimal.py.

  * **DOUBLE stored as int64 bit patterns.** TPUs emulate f64 as a
    float32-pair (double-double, ~48-bit mantissa, f32 exponent range), so
    device transfers of raw f64 are lossy (measured: 1e300 -> inf).  Columns
    that merely pass through the device must survive bit-exactly, so DOUBLE's
    physical lane is the int64 bitcast; kernels bitcast to f64 only when
    actually computing (ops/kernels.py compute_view).  Compute results carry
    the emulation's reduced precision — a documented deviation, same spirit
    as the reference's float notes in docs/compatibility.md.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from ..config import TpuConf, DEFAULT_CONF
from .host import HostBatch, dtype_to_arrow


def merge_origin(origins) -> str:
    """Provenance of data merged from several batches/files: the single
    shared file, or "" for mixed/unknown (input_file_name contract)."""
    s = {o or "" for o in origins}
    return s.pop() if len(s) == 1 else ""


def bucket_capacity(n: int, conf: TpuConf = DEFAULT_CONF) -> int:
    """Smallest static-shape bucket >= n.

    An explicit `spark.rapids.tpu.sql.shape.buckets` set wins when
    configured: capacities quantize onto exactly that list (doubling
    past its largest entry), so one compiled program serves every input
    size inside a bucket and cross-scale-factor runs land on the same
    shapes — the compile-cache hit the persistent cache needs.

    Otherwise buckets grow geometrically (x growth) up to batchSizeRows,
    then x2 above it to halve worst-case padding waste: batches above
    the target size are expected to be split upstream (coalesce/retry
    machinery), so the >target regime only exists transiently.
    """
    explicit = conf.bucket_set
    if explicit:
        for cap in explicit:
            if cap >= n:
                return cap
        cap = explicit[-1]
        while cap < n:
            cap *= 2
        return cap
    cap = conf.bucket_min_rows
    growth = conf.bucket_growth
    target = conf.batch_size_rows
    while cap < n:
        cap *= growth if cap < target else 2
    return cap


@dataclasses.dataclass
class DeviceColumn:
    """One column on device: padded data lane + validity lane.

    data      : jnp array, shape (capacity,) in the physical dtype
                (types.physical_np_dtype); strings are int32 dictionary codes.
    validity  : jnp bool array, shape (capacity,); padding rows are False.
    dtype     : logical Spark type.
    dictionary: host pyarrow array of unique values for STRING columns
                (codes index into it); None otherwise.
    data_hi   : high int64 lane for wide decimals; None otherwise.

    RAGGED (ARRAY<primitive>) columns — the SURVEY §7c values+offsets
    dual-tensor design (reference nested cuDF LIST columns,
    GpuColumnVector.java type mapping):
    offsets   : int32, shape (row_capacity + 1,); row i's elements are
                data[offsets[i]:offsets[i+1]].  Null/padding rows carry
                empty spans.  When set, `data` is the flat VALUES lane
                (its own value-capacity bucket) and `validity` stays the
                per-ROW null mask with shape (row_capacity,).
    elem_valid: bool per VALUE (null elements); same shape as data.
    """
    data: jax.Array
    validity: jax.Array
    dtype: t.DataType
    dictionary: Optional[pa.Array] = None
    data_hi: Optional[jax.Array] = None
    offsets: Optional[jax.Array] = None
    elem_valid: Optional[jax.Array] = None
    # ENCODED-lane metadata (ops/encodings.py, informational only —
    # correctness NEVER depends on it): ("for", lo, hi) marks a
    # VALUE-PRESERVING narrowed integer lane (data dtype smaller than
    # the logical physical dtype, values exact, live range [lo, hi]);
    # ("dict_sorted",) marks an order-preserving dictionary upload.
    # Paths that rebuild columns may drop it freely: every consumer
    # either understands narrow lanes or widens via plain dtype
    # promotion, which is exact.
    enc: Optional[tuple] = None

    @property
    def capacity(self) -> int:
        if self.offsets is not None:
            return self.offsets.shape[0] - 1
        return self.data.shape[0]

    @property
    def value_capacity(self) -> int:
        """Flat values-lane capacity of a ragged column."""
        return self.data.shape[0]

    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.data_hi is not None:
            n += self.data_hi.size * 8
        if self.offsets is not None:
            n += self.offsets.size * 4
        if self.elem_valid is not None:
            n += self.elem_valid.size
        return n

    def with_dtype(self, dtype: t.DataType) -> "DeviceColumn":
        return dataclasses.replace(self, dtype=dtype)


@dataclasses.dataclass
class DeviceBatch:
    """A batch of device columns sharing one capacity and logical row count.

    `num_rows` is either a host int or a 0-d jax int scalar: operators whose
    output count is data-dependent (filter, join) leave it on device so
    chained device work never stalls on a D2H sync; host-side consumers
    coerce with `int(db.num_rows)` (one sync) when they truly need the value
    (coalesce sizing, limits, final collect)."""
    columns: List[DeviceColumn]
    num_rows: object   # int | jax.Array 0-d
    names: List[str]
    # scan provenance for input_file_name (GpuInputFileBlock role):
    # "" = unknown / non-file source / mixed files
    origin_file: str = ""
    # LAZY SELECTION VECTOR (the cuDF gather-map-deferred idea,
    # JoinGatherer.scala role): when set, live rows are `sel`-True rows,
    # NOT a front prefix, and num_rows is their (device) count.  Row
    # gathers are the dominant device cost on TPU (~20ms per pass at
    # 1M), so a join feeding a mask-aware consumer (aggregation live
    # mask, another join's probe liveness) skips its output compaction
    # entirely.  Prefix-assuming operators (fetch, concat, slicing)
    # compact on entry via ops.batch_ops.ensure_prefix.
    sel: object = None   # Optional[jax.Array]
    # LATE-MATERIALIZATION state (columnar/lanes.py ThinState): when
    # set, columns listed in thin.pending are ZERO-capacity placeholders
    # backed by (source batch, row-id lane) pairs; sinks resolve them
    # with one composed gather per source via lanes.materialize_batch.
    thin: object = None  # Optional[lanes.ThinState]

    @property
    def capacity(self) -> int:
        if self.thin is not None:
            return self.thin.capacity
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def schema(self) -> t.StructType:
        return t.StructType([t.StructField(n, c.dtype)
                             for n, c in zip(self.names, self.columns)])

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def column_by_name(self, name: str) -> DeviceColumn:
        return self.columns[self.names.index(name)]

    def select(self, indices: Sequence[int]) -> "DeviceBatch":
        return DeviceBatch([self.columns[i] for i in indices], self.num_rows,
                           [self.names[i] for i in indices],
                           self.origin_file, sel=self.sel,
                           thin=None if self.thin is None
                           else self.thin.select(indices))

    def nbytes(self) -> int:
        n = sum(c.nbytes() for c in self.columns)
        if self.thin is not None:
            n += self.thin.nbytes()
        return n

    def row_mask(self) -> jax.Array:
        """Bool mask of logically-live rows: the selection vector when
        present, else True for row < num_rows (prefix liveness)."""
        if self.sel is not None:
            return self.sel
        return jnp.arange(self.capacity, dtype=jnp.int32) < jnp.int32(self.num_rows)

    def __repr__(self):
        return (f"DeviceBatch(rows={self.num_rows}/cap={self.capacity}, "
                f"{self.schema.simple_string})")


# ---------------------------------------------------------------------------
# Decimal128 buffer plumbing (narrow decimals ride as int64 unscaled values)
# ---------------------------------------------------------------------------

def _decimal128_lanes(arr: pa.Array) -> np.ndarray:
    """(n, 2) uint64 [lo, hi] little-endian lanes of a decimal128 array."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    buf = arr.buffers()[1]
    words = np.frombuffer(buf, dtype=np.uint64)
    words = words[arr.offset * 2: (arr.offset + len(arr)) * 2]
    return words.reshape(-1, 2)


def _decimal128_from_unscaled(unscaled: np.ndarray, validity: np.ndarray,
                              dt: t.DecimalType) -> pa.Array:
    lo = unscaled.astype(np.int64).view(np.uint64)
    hi = np.where(unscaled < 0, np.uint64(0xFFFFFFFFFFFFFFFF), np.uint64(0))
    lanes = np.empty((len(unscaled), 2), dtype=np.uint64)
    lanes[:, 0] = lo
    lanes[:, 1] = hi
    validity_buf = pa.py_buffer(np.packbits(validity, bitorder="little").tobytes())
    data_buf = pa.py_buffer(lanes.tobytes())
    return pa.Array.from_buffers(pa.decimal128(dt.precision, dt.scale),
                                 len(unscaled), [validity_buf, data_buf])


# ---------------------------------------------------------------------------
# Host -> device (the RowToColumnar / HostColumnarToGpu analogue)
# ---------------------------------------------------------------------------

def _pad(np_arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if len(np_arr) == capacity:
        return np_arr
    out = np.full(capacity, fill, dtype=np_arr.dtype)
    out[: len(np_arr)] = np_arr
    return out


def _arrow_column_to_device(arr: pa.Array, dt: t.DataType, capacity: int,
                            device=None, policy=None,
                            narrow_ok: bool = False) -> DeviceColumn:
    """`policy` (ops/encodings.EncodingPolicy) turns on the ENCODED
    upload forms: order-preserving (sorted) dictionaries for strings and
    — when `narrow_ok` (negotiated per scan column by
    plan/overrides._negotiate_encoded) — value-preserving FOR-narrowed
    integer lanes.  None keeps the pre-encoding representation
    bit-identically."""
    import pyarrow.compute as pc
    n = len(arr)
    validity_np = np.zeros(capacity, dtype=bool)
    if n:
        validity_np[:n] = pc.is_valid(arr).to_numpy(zero_copy_only=False)

    if isinstance(dt, t.ArrayType):
        return _arrow_list_to_device(arr, dt, capacity, validity_np, device,
                                     policy)

    dictionary = None
    hi = None
    enc = None
    if isinstance(dt, t.StringType):
        if policy is not None and policy.dict_sort_scan:
            from ..ops.encodings import (count_dispatch, is_ordered_dict,
                                         sort_dictionary_encode)
            codes_np, dictionary, _m = sort_dictionary_encode(arr)
            data_np = _pad(codes_np, capacity)
            if len(dictionary):
                # publish orderedness under the identity pin so later
                # prepare-time checks are one dict hit
                is_ordered_dict(dictionary)
            enc = ("dict_sorted",)
            count_dispatch("dict_sort_upload")
        else:
            if not pa.types.is_dictionary(arr.type):
                arr = pc.dictionary_encode(arr)
            codes_arr = arr.indices.fill_null(0) if arr.null_count \
                else arr.indices
            data_np = _pad(
                codes_arr.to_numpy(zero_copy_only=False).astype(np.int32),
                capacity)
            dictionary = arr.dictionary.cast(pa.string())
    elif isinstance(dt, t.DecimalType):
        if dt.is_wide:
            lanes = _decimal128_lanes(arr)
            data_np = _pad(lanes[:, 0].view(np.int64), capacity)
            hi_np = _pad(lanes[:, 1].view(np.int64), capacity)
            # hi lane needs sign-correct padding of 0 which is fine (value 0)
            hi = jnp.asarray(hi_np)
        else:
            lanes = _decimal128_lanes(arr)
            data_np = _pad(lanes[:, 0].view(np.int64), capacity)
    elif isinstance(dt, t.TimestampType):
        a = arr.cast(pa.timestamp("us", tz="UTC")).cast(pa.int64())
        a = a.fill_null(0) if a.null_count else a
        data_np = _pad(a.to_numpy(zero_copy_only=False), capacity)
    elif isinstance(dt, t.DateType):
        a = arr.cast(pa.int32())
        a = a.fill_null(0) if a.null_count else a
        data_np = _pad(a.to_numpy(zero_copy_only=False), capacity)
    elif isinstance(dt, t.NullType):
        data_np = np.zeros(capacity, dtype=np.int32)
    elif isinstance(dt, t.DoubleType):
        a = arr.fill_null(0) if arr.null_count else arr
        f64 = a.to_numpy(zero_copy_only=False).astype(np.float64, copy=False)
        data_np = _pad(f64.view(np.int64), capacity)
    else:
        np_dt = t.physical_np_dtype(dt)
        a = arr.fill_null(False if np_dt == np.bool_ else 0) if arr.null_count else arr
        data_np = _pad(a.to_numpy(zero_copy_only=False).astype(np_dt, copy=False),
                       capacity)

    # FOR-narrowing (value-preserving): integer-family lanes whose live
    # range fits a smaller signed dtype upload narrow — fewer H2D bytes,
    # narrow-domain predicates/arithmetic — and widen exactly via plain
    # dtype promotion wherever full width is needed.  DOUBLE's int64
    # lane is a BITCAST (never narrowed); string codes stay int32.
    if (policy is not None and policy.narrow_lanes and narrow_ok and
            enc is None and hi is None and n and
            data_np.dtype.kind == "i" and
            not isinstance(dt, (t.DoubleType, t.StringType, t.NullType))):
        live = data_np[:n][validity_np[:n]]
        if live.size:
            from ..ops.encodings import count_dispatch, narrow_np_dtype
            lo_v, hi_v = int(live.min()), int(live.max())
            ndt = narrow_np_dtype(min(lo_v, 0), max(hi_v, 0),
                                  data_np.dtype)
            if ndt is not None:
                data_np = data_np.astype(ndt)
                enc = ("for", lo_v, hi_v)
                count_dispatch("narrow_upload")

    put = (lambda x: jax.device_put(x, device)) if device is not None else jnp.asarray
    return DeviceColumn(put(data_np), put(validity_np), dt, dictionary, hi,
                        enc=enc)


def _arrow_list_to_device(arr: pa.Array, dt: t.ArrayType, capacity: int,
                          validity_np: np.ndarray, device=None,
                          policy=None) -> DeviceColumn:
    """ListArray -> ragged device column: int32 offsets (row capacity+1)
    + flat values lane in its own bucket.  Null rows get empty spans so
    kernels never need the row validity to bound a segment."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    n = len(arr)
    if n:
        arr = arr.cast(pa.list_(arr.type.value_type))
        raw_off = np.asarray(arr.offsets.to_numpy(zero_copy_only=False),
                             np.int64)
        values = arr.values[raw_off[0]:raw_off[-1]]
        raw_off = raw_off - raw_off[0]
        # null rows -> empty spans (rebuild offsets monotonically)
        lens = np.diff(raw_off)
        lens[~validity_np[:n]] = 0
        # rebuild a compacted values array when null rows carried values
        if lens.sum() != len(values):
            keep = np.zeros(len(values), bool)
            for i in range(n):
                if validity_np[i]:
                    keep[raw_off[i]:raw_off[i + 1]] = True
            values = values.filter(pa.array(keep))
        off = np.zeros(capacity + 1, np.int32)
        off[1:n + 1] = np.cumsum(lens).astype(np.int32)
        off[n + 1:] = off[n]
    else:
        values = pa.array([], dtype_to_arrow(dt.element_type))
        off = np.zeros(capacity + 1, np.int32)

    vcap = bucket_capacity(max(len(values), 1))
    # ragged value lanes keep sorted-dict encoding but never narrow
    # (offset/value-lane plumbing assumes physical dtypes)
    vcol = _arrow_column_to_device(values, dt.element_type, vcap, device,
                                   policy=policy, narrow_ok=False)
    put = (lambda x: jax.device_put(x, device)) if device is not None \
        else jnp.asarray
    return DeviceColumn(vcol.data, put(validity_np), dt,
                        vcol.dictionary, vcol.data_hi,
                        offsets=put(off), elem_valid=vcol.validity)


def to_device(hb: HostBatch, conf: TpuConf = DEFAULT_CONF,
              capacity: Optional[int] = None, device=None,
              encoded_cols=None) -> DeviceBatch:
    """`encoded_cols`: column names approved for FOR-narrowed lanes by
    the _negotiate_encoded legality pass (plan/overrides.py); None means
    no narrowing (un-negotiated uploads stay full width).  Sorted-
    dictionary encoding applies to every upload when the policy is on —
    a pure representation change, safe for any consumer."""
    cap = capacity or bucket_capacity(max(hb.num_rows, 1), conf)
    if cap > hb.num_rows:
        # always-on pad accounting at bucket time: the rows the capacity
        # bucket adds over the live count (the overhead plane's upload
        # site; profiled segment dispatches price this padding in ms)
        from ..obs.registry import PAD_ROWS
        PAD_ROWS.inc(cap - hb.num_rows, site="upload")
    from ..ops.encodings import encoding_policy
    pol = encoding_policy(conf)
    if not pol.any_enabled:
        pol = None
    cols = []
    for i, f in enumerate(hb.schema.fields):
        cols.append(_arrow_column_to_device(
            hb.rb.column(i), f.data_type, cap, device, policy=pol,
            narrow_ok=encoded_cols is not None and f.name in encoded_cols))
    return DeviceBatch(cols, hb.num_rows, list(hb.schema.names))


# ---------------------------------------------------------------------------
# Device -> host (the ColumnarToRow / BringBackToHost analogue)
# ---------------------------------------------------------------------------

def _device_column_to_arrow(col: DeviceColumn, num_rows: int,
                            fetched=None) -> pa.Array:
    if fetched is not None:
        data_np, valid_np, hi_np, off_np, ev_np = fetched
    else:
        data_np, valid_np, hi_np, off_np, ev_np = jax.device_get(
            (col.data, col.validity, col.data_hi, col.offsets,
             col.elem_valid))
    dt = col.dtype
    if isinstance(dt, t.ArrayType):
        off = np.asarray(off_np)[:num_rows + 1].astype(np.int32)
        nvals = int(off[-1]) if len(off) else 0
        vcol = DeviceColumn(col.data, col.elem_valid, dt.element_type,
                            col.dictionary)
        values = _device_column_to_arrow(
            vcol, nvals, (data_np, ev_np, None, None, None))
        valid = np.asarray(valid_np)[:num_rows].astype(bool)
        return pa.ListArray.from_arrays(
            pa.array(off, pa.int32()), values,
            mask=pa.array(~valid) if not valid.all() else None)
    data = np.asarray(data_np)[:num_rows]
    valid = np.asarray(valid_np)[:num_rows].astype(bool)
    if isinstance(dt, t.StringType):
        codes = np.where(valid, data, -1).astype(np.int32)
        dict_arr = col.dictionary if col.dictionary is not None else pa.array([], pa.string())
        indices = pa.array(codes, pa.int32(), mask=~valid)
        return pa.DictionaryArray.from_arrays(indices, dict_arr).cast(pa.string())
    if isinstance(dt, t.DecimalType):
        if dt.is_wide:
            lo = data.astype(np.int64).view(np.uint64)
            if hi_np is None:
                # device-computed wide result: single int64 lane, sign-extend
                hi_np = np.where(data.astype(np.int64) < 0,
                                 np.int64(-1), np.int64(0))
                hi_lane = hi_np.view(np.uint64)
            else:
                hi_lane = np.asarray(hi_np)[:num_rows].view(np.uint64)
            lanes = np.empty((num_rows, 2), dtype=np.uint64)
            lanes[:, 0] = lo
            lanes[:, 1] = hi_lane
            validity_buf = pa.py_buffer(np.packbits(valid, bitorder="little").tobytes())
            return pa.Array.from_buffers(pa.decimal128(dt.precision, dt.scale),
                                         num_rows,
                                         [validity_buf, pa.py_buffer(lanes.tobytes())])
        return _decimal128_from_unscaled(data, valid, dt)
    if isinstance(dt, t.NullType):
        return pa.nulls(num_rows)
    if isinstance(dt, t.DoubleType):
        # Two storage lanes exist: int64 f64-bit-patterns (host pass-through)
        # and native f64 (computed on device) — see ops/kernels.py views.
        if data.dtype == np.float64:
            return pa.array(data, pa.float64(), mask=~valid)
        f64 = data.astype(np.int64).view(np.float64)
        return pa.array(f64, pa.float64(), mask=~valid)
    arrow_type = dtype_to_arrow(dt)
    if isinstance(dt, t.TimestampType):
        return pa.array(data.astype(np.int64), pa.int64(), mask=~valid).cast(arrow_type)
    if isinstance(dt, t.DateType):
        return pa.array(data.astype(np.int32), pa.int32(), mask=~valid).cast(arrow_type)
    return pa.array(data, arrow_type, mask=~valid)


def to_host(db: DeviceBatch, fetch_rows: Optional[int] = None) -> HostBatch:
    """Bring a batch to host.

    ONE D2H round trip for the row count and every lane of every column
    (a separate int(num_rows) fetch would double the tunnel RTTs).

    fetch_rows: upper bound on live rows KNOWN BY THE CALLER (a static
    limit, an already-synced count).  Lanes are device-sliced to it before
    the transfer, so the tunnel ships live rows instead of the padded
    bucket capacity — on a high-latency link the padding bytes, not the
    device compute, dominate a naive fetch (measured: a 1M-row bucket
    carrying 1,760 live rows shipped 25 MB in 9.2 s; its live prefix is
    42 KB).  Ragged value lanes are sliced via the (host-known) offsets
    bound only when the whole column is fetched, because the value count
    of a row prefix is itself device data."""
    n, fetched = _fetch_lanes(db, fetch_rows)
    if fetch_rows is not None:
        n = min(n, fetch_rows)
    return _build_host_batch(db, n, fetched)


def _fetch_lanes(db: DeviceBatch, fetch_rows: Optional[int]):
    """device_get count + lanes in one round trip; lanes prefix-sliced to
    fetch_rows when given.  Returns (clamped live count, fetched lists)."""
    if db.sel is not None or db.thin is not None:
        from ..ops.batch_ops import ensure_prefix
        db = ensure_prefix(db)
    cols = db.columns
    if fetch_rows is not None and fetch_rows < db.capacity:
        h = fetch_rows
        sl = []
        for c in cols:
            if c.offsets is not None:
                # offsets prefix is enough for rebuild; values lanes keep
                # full length (their live length is offsets[h], on device)
                sl.append(dataclasses.replace(
                    c, offsets=c.offsets[:h + 1]))
            else:
                sl.append(dataclasses.replace(
                    c, data=c.data[:h], validity=c.validity[:h],
                    data_hi=None if c.data_hi is None else c.data_hi[:h]))
        cols = sl
    n_f, fetched = jax.device_get(
        (db.num_rows, [(c.data, c.validity, c.data_hi, c.offsets,
                        c.elem_valid) for c in cols]))
    return int(n_f), fetched        # TRUE count (may exceed fetch_rows)


def _build_host_batch(db: DeviceBatch, n: int, fetched) -> HostBatch:
    arrays = [_device_column_to_arrow(c, n, f)
              for c, f in zip(db.columns, fetched)]
    schema = pa.schema([pa.field(n, a.type) for n, a in zip(db.names, arrays)])
    if not arrays:
        return HostBatch(pa.RecordBatch.from_pydict({}))
    return HostBatch(pa.RecordBatch.from_arrays(arrays, schema=schema))


# Result-fetch head default: one speculative round trip ships the count
# plus this many rows (~40 KB/column at 4096 — under one RTT's worth of
# bytes on the ~2 MB/s tunnel, covering every TPC-H final result).  The
# SOURCE OF TRUTH is the config entry; conf=None callers read it from
# DEFAULT_CONF so tuning the default cannot fork the two.


def fetch_result_batch(db: DeviceBatch, bound: Optional[int] = None,
                       conf: Optional[TpuConf] = None) -> HostBatch:
    """Bring a RESULT batch to host with minimum tunnel traffic.

    The live rows of every operator output are a front prefix of the
    padded bucket (filters compact, aggregates emit groups first, sorts
    order dead rows last), so the fetch never needs the padding:

      * static row count           -> one trip, exactly n rows
      * static bound (limit/top-N) -> one trip, bound rows
      * unknown count              -> ONE speculative trip fetching the
        count + a RESULT_HEAD_ROWS prefix together; a second trip only
        when the result is genuinely bigger than the head.

    Measured on the axon tunnel (~125 ms RTT, ~2 MB/s D2H): a 1M-row
    bucket with 1,760 live rows cost 9.2 s as a full-capacity fetch and
    ~0.15 s via the head protocol."""
    from ..config import (DEFAULT_CONF, RESULT_BOUND_FETCH_FACTOR,
                          RESULT_HEAD_ROWS)
    conf = conf or DEFAULT_CONF
    head_rows = conf.get(RESULT_HEAD_ROWS)
    bound_factor = conf.get(RESULT_BOUND_FETCH_FACTOR)
    cap = db.capacity
    if isinstance(db.num_rows, int):
        return to_host(db, fetch_rows=min(db.num_rows, cap))
    if any(c.offsets is not None for c in db.columns):
        # ragged value lanes aren't prefix-sliceable by a row bound (the
        # value count of a prefix is device data).  A small static bound
        # fetches exactly-sized in one trip; otherwise the cheap scalar
        # count goes first so an all-padding bucket never ships lanes
        if bound is not None and bound < cap:
            return to_host(db, fetch_rows=bound)
        n = int(jax.device_get(db.num_rows))
        return to_host(db, fetch_rows=max(n, 0) if n < cap else None)
    # a small static bound buys an exact one-trip fetch; a loose bound
    # (dense-domain group counts can reach 4M) must not defeat the head
    # protocol, so past boundFactor x the head size we speculate instead
    if bound is not None and bound <= bound_factor * head_rows:
        head = min(cap, bound)
    else:
        head = min(cap, head_rows)
    if head >= cap:
        return to_host(db)
    n, fetched = _fetch_lanes(db, head)
    if n <= head:
        return _build_host_batch(db, n, fetched)
    # result larger than the head: pay the second, exactly-sized trip
    return to_host(db, fetch_rows=n)


def empty_device_batch(schema: t.StructType, conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    hb = HostBatch(pa.RecordBatch.from_pydict(
        {f.name: pa.array([], dtype_to_arrow(f.data_type)) for f in schema.fields}))
    return to_device(hb, conf)
