"""Host-side columnar batches backed by Apache Arrow.

Plays the role of the reference's RapidsHostColumnVector / host-side
ColumnarBatch (sql-plugin/src/main/java/.../RapidsHostColumnVector.java) and
of JCudfSerialization's host table format (GpuColumnarBatchSerializer.scala:127)
— here the host format IS Arrow: pyarrow RecordBatch in memory, Arrow IPC
stream on the wire (shuffle, spill, broadcast).
"""
from __future__ import annotations

import io
from typing import Iterator, List, Optional

import pyarrow as pa

from .. import types as t


# ---------------------------------------------------------------------------
# Arrow <-> logical type mapping
# ---------------------------------------------------------------------------

def arrow_to_dtype(at: pa.DataType) -> t.DataType:
    if pa.types.is_boolean(at):
        return t.BOOLEAN
    if pa.types.is_int8(at):
        return t.BYTE
    if pa.types.is_int16(at):
        return t.SHORT
    if pa.types.is_int32(at):
        return t.INT
    if pa.types.is_int64(at):
        return t.LONG
    if pa.types.is_float32(at):
        return t.FLOAT
    if pa.types.is_float64(at):
        return t.DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return t.STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return t.BINARY
    if pa.types.is_date32(at):
        return t.DATE
    if pa.types.is_timestamp(at):
        return t.TIMESTAMP
    if pa.types.is_null(at):
        return t.NULL
    if pa.types.is_decimal(at):
        return t.DecimalType(at.precision, at.scale)
    if pa.types.is_dictionary(at):
        return arrow_to_dtype(at.value_type)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return t.ArrayType(arrow_to_dtype(at.value_type))
    if pa.types.is_struct(at):
        return t.StructType([t.StructField(f.name, arrow_to_dtype(f.type), f.nullable)
                             for f in at])
    if pa.types.is_map(at):
        return t.MapType(arrow_to_dtype(at.key_type), arrow_to_dtype(at.item_type))
    raise TypeError(f"unsupported arrow type {at}")


def dtype_to_arrow(dt: t.DataType) -> pa.DataType:
    if isinstance(dt, t.BooleanType):
        return pa.bool_()
    if isinstance(dt, t.ByteType):
        return pa.int8()
    if isinstance(dt, t.ShortType):
        return pa.int16()
    if isinstance(dt, t.IntegerType):
        return pa.int32()
    if isinstance(dt, t.LongType):
        return pa.int64()
    if isinstance(dt, t.FloatType):
        return pa.float32()
    if isinstance(dt, t.DoubleType):
        return pa.float64()
    if isinstance(dt, t.StringType):
        return pa.string()
    if isinstance(dt, t.BinaryType):
        return pa.binary()
    if isinstance(dt, t.DateType):
        return pa.date32()
    if isinstance(dt, t.TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, t.NullType):
        return pa.null()
    if isinstance(dt, t.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, t.ArrayType):
        return pa.list_(dtype_to_arrow(dt.element_type))
    if isinstance(dt, t.StructType):
        return pa.struct([pa.field(f.name, dtype_to_arrow(f.data_type), f.nullable)
                          for f in dt.fields])
    if isinstance(dt, t.MapType):
        return pa.map_(dtype_to_arrow(dt.key_type), dtype_to_arrow(dt.value_type))
    raise TypeError(f"unsupported logical type {dt}")


def schema_to_struct(schema: pa.Schema) -> t.StructType:
    return t.StructType([t.StructField(f.name, arrow_to_dtype(f.type), f.nullable)
                         for f in schema])


def struct_to_schema(st: t.StructType) -> pa.Schema:
    return pa.schema([pa.field(f.name, dtype_to_arrow(f.data_type), f.nullable)
                      for f in st.fields])


class HostBatch:
    """Thin wrapper over a pyarrow RecordBatch with the engine's schema view."""

    def __init__(self, rb: pa.RecordBatch):
        self.rb = rb
        self.schema = schema_to_struct(rb.schema)

    @property
    def num_rows(self) -> int:
        return self.rb.num_rows

    @property
    def num_columns(self) -> int:
        return self.rb.num_columns

    def column(self, i: int) -> pa.Array:
        return self.rb.column(i)

    @staticmethod
    def from_pydict(data: dict, schema: Optional[pa.Schema] = None) -> "HostBatch":
        return HostBatch(pa.RecordBatch.from_pydict(data, schema=schema))

    @staticmethod
    def from_table(tbl: pa.Table) -> "HostBatch":
        return HostBatch(tbl.combine_chunks().to_batches(max_chunksize=tbl.num_rows or 1)[0]
                         if tbl.num_rows else pa.RecordBatch.from_pydict(
                             {n: [] for n in tbl.schema.names}, schema=tbl.schema))

    def to_table(self) -> pa.Table:
        return pa.Table.from_batches([self.rb])

    @staticmethod
    def concat(batches: List["HostBatch"]) -> "HostBatch":
        if not batches:
            raise ValueError("concat of zero batches")
        tbl = pa.Table.from_batches([b.rb for b in batches])
        return HostBatch.from_table(tbl.combine_chunks())

    def slice(self, offset: int, length: int) -> "HostBatch":
        return HostBatch(self.rb.slice(offset, length))

    # ------------------------------------------------------------------
    # Arrow IPC wire format — the JCudfSerialization analogue used by the
    # shuffle writer/reader and the host/disk spill stores.
    # ------------------------------------------------------------------
    def serialize(self, compression: Optional[str] = "zstd") -> bytes:
        sink = io.BytesIO()
        codec = None if compression is None else str(compression).lower()
        opts = pa.ipc.IpcWriteOptions(
            compression=None if codec in (None, "none") else codec)
        with pa.ipc.new_stream(sink, self.rb.schema, options=opts) as w:
            w.write_batch(self.rb)
        return sink.getvalue()

    @staticmethod
    def deserialize(buf: bytes) -> "HostBatch":
        with pa.ipc.open_stream(pa.py_buffer(buf)) as r:
            return HostBatch.from_table(r.read_all())

    @staticmethod
    def deserialize_stream(buf: bytes) -> Iterator["HostBatch"]:
        with pa.ipc.open_stream(pa.py_buffer(buf)) as r:
            for rb in r:
                yield HostBatch(rb)

    def nbytes(self) -> int:
        return self.rb.nbytes

    def __repr__(self):
        return f"HostBatch({self.num_rows} rows, {self.schema.simple_string})"
