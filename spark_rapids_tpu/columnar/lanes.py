"""Late-materialization lanes: THIN device batches for join pipelines.

Row gathers are the dominant device cost on TPU (~1.6 GB/s descriptor-
driven DMA per gathered lane), and a join chain classically re-gathers
every payload column of both sides at full batch capacity per join.  The
reference defers this with gather maps (JoinGatherer.scala — a join
yields gather maps, materialization happens when a downstream operator
actually needs columns); "GPU Acceleration of SQL Analytics on
Compressed Data" (PAPERS.md) shows executing *through* encodings rather
than materializing decoded columns is the dominant accelerator win.

The TPU-native realization: a join emits a **thin batch** — its
materialized key/condition columns plus, per deferred payload column, a
pointer into a *lane source*:

  * ``LaneSource``: a fully materialized source batch (a join's build
    side, or a probe batch whose columns pass through) together with an
    int32 **row-id lane** of the output's capacity — the gather indices
    the join computed anyway.  Index < 0 marks a null-extended row
    (outer-join semantics, cuDF OutOfBoundsPolicy.NULLIFY).
  * ``ThinState.pending``: output column position -> (source ordinal,
    column index in the source).

Downstream joins COMPOSE lanes (one int32 take per source per join)
instead of gathering payloads; filters compose their mask into the
batch's selection vector (``DeviceBatch.sel``) instead of compacting; a
pipeline *sink* (aggregate build, sort, exchange, collect — anything
that calls ``materialize_batch``/``ensure_prefix``/``compact_batch``)
resolves each still-needed column with ONE gather through the composed
lane.  Columns nobody references are never gathered at all.

Encodings stay live through the chain: a deferred dictionary-coded
string column materializes as CODES (the dictionary pointer rides on the
placeholder), so strings cross an entire join pipeline without a decode
and with the build-side dictionary remap done once per build
(ops/batch_ops.py remap caches).

Deferred placeholders are ZERO-capacity columns: any path that forgot to
materialize fails loudly on a shape mismatch instead of silently
computing over garbage.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..config import TpuConf, DEFAULT_CONF
from .device import DeviceBatch, DeviceColumn


@dataclasses.dataclass
class LaneSource:
    """A materialized source batch + the row-id lane selecting from it."""
    batch: DeviceBatch
    lane: jax.Array          # (out_capacity,) int32; < 0 => null row

    def nbytes(self) -> int:
        return self.lane.size * 4


@dataclasses.dataclass
class ThinState:
    """Deferred-column bookkeeping attached to a DeviceBatch."""
    capacity: int
    sources: List[LaneSource]
    # output column position -> (source ordinal, source column index)
    pending: Dict[int, Tuple[int, int]]

    def nbytes(self) -> int:
        return sum(s.nbytes() for s in self.sources)

    def select(self, indices: Sequence[int]) -> "Optional[ThinState]":
        """Thin state for DeviceBatch.select(indices): pending positions
        remap to the new column order; sources nobody references drop."""
        new_pending: Dict[int, Tuple[int, int]] = {}
        used: List[LaneSource] = []
        src_map: Dict[int, int] = {}
        for out_i, old_i in enumerate(indices):
            ref = self.pending.get(old_i)
            if ref is None:
                continue
            s, c = ref
            if s not in src_map:
                src_map[s] = len(used)
                used.append(self.sources[s])
            new_pending[out_i] = (src_map[s], c)
        if not new_pending:
            return None
        return ThinState(self.capacity, used, new_pending)


def deferred_column(src_col: DeviceColumn) -> DeviceColumn:
    """Zero-capacity placeholder for a deferred column.  It carries the
    logical dtype AND the source dictionary (schema/encoding fidelity —
    string columns stay code-addressed through the chain) but no data:
    consuming it without materialization is a loud shape error."""
    return DeviceColumn(
        jnp.zeros((0,), src_col.data.dtype),
        jnp.zeros((0,), bool),
        src_col.dtype,
        src_col.dictionary,
        None if src_col.data_hi is None else jnp.zeros((0,), jnp.int64))


def _count_gather(site: str, rows: int, cols: List[DeviceColumn]) -> None:
    """Publish one payload-gather pass into the always-on registry."""
    from ..obs.registry import GATHER_BYTES, GATHER_ROWS
    nbytes = sum(rows * (c.data.dtype.itemsize + 1 +
                         (8 if c.data_hi is not None else 0))
                 for c in cols)
    GATHER_ROWS.inc(rows * len(cols), site=site)
    GATHER_BYTES.inc(nbytes, site=site)


def gather_deferred(src: LaneSource, col_indices: Sequence[int],
                    live: Optional[jax.Array], lane=None
                    ) -> List[DeviceColumn]:
    """Materialize source columns through a row-id lane: one stacked
    gather pass per dtype class (ops/filter.py grouped_take).  Rows with
    lane < 0 / >= source rows come back null; `live` (the output batch's
    row mask) additionally nulls dead output rows."""
    from ..ops.filter import grouped_take
    idx = src.lane if lane is None else lane
    src_rows = jnp.asarray(src.batch.num_rows, jnp.int32)
    in_bounds = (idx >= 0) & (idx < src_rows)
    vmask = in_bounds if live is None else in_bounds & live
    cap = max(src.batch.capacity - 1, 0)
    safe = jnp.clip(idx, 0, cap).astype(jnp.int32)
    cols = [src.batch.columns[i] for i in col_indices]
    lanes, slots = [], []
    for ci, c in enumerate(cols):
        lanes.append(c.data)
        slots.append((ci, "d"))
        lanes.append(c.validity)
        slots.append((ci, "v"))
        if c.data_hi is not None:
            lanes.append(c.data_hi)
            slots.append((ci, "h"))
    moved = grouped_take(lanes, safe)
    got = {slot: arr for slot, arr in zip(slots, moved)}
    out = []
    for ci, c in enumerate(cols):
        out.append(DeviceColumn(got[(ci, "d")], got[(ci, "v")] & vmask,
                                c.dtype, c.dictionary, got.get((ci, "h"))))
    _count_gather("late", idx.shape[0], cols)
    return out


def materialize_batch(db: DeviceBatch, conf: TpuConf = DEFAULT_CONF,
                      positions: Optional[Sequence[int]] = None
                      ) -> DeviceBatch:
    """Resolve deferred columns: one composed gather per lane source.

    positions=None resolves everything (the thin state drops); a subset
    resolves only those columns (mid-pipeline early materialization —
    e.g. a filter referencing a deferred column) and keeps the rest
    thin."""
    ts = db.thin
    if ts is None:
        return db
    want = set(ts.pending) if positions is None \
        else set(positions) & set(ts.pending)
    remaining = {p: r for p, r in ts.pending.items() if p not in want}
    if not want:
        if remaining:
            return db
        return DeviceBatch(list(db.columns), db.num_rows, db.names,
                           db.origin_file, sel=db.sel)
    live = db.row_mask()
    cols = list(db.columns)
    by_src: Dict[int, List[Tuple[int, int]]] = {}
    for pos in want:
        s, c = ts.pending[pos]
        by_src.setdefault(s, []).append((pos, c))
    for s, items in sorted(by_src.items()):
        src = ts.sources[s]
        gathered = gather_deferred(src, [c for _p, c in items], live)
        for (pos, _c), col in zip(items, gathered):
            cols[pos] = col
    new_ts = None
    if remaining:
        # re-pack sources still referenced
        keep_src = sorted({s for s, _c in remaining.values()})
        src_map = {s: i for i, s in enumerate(keep_src)}
        new_ts = ThinState(ts.capacity,
                           [ts.sources[s] for s in keep_src],
                           {p: (src_map[s], c)
                            for p, (s, c) in remaining.items()})
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file,
                       sel=db.sel, thin=new_ts)


def expr_column_refs(exprs) -> set:
    """Column names referenced anywhere in a set of bound expressions
    (including lambda bodies)."""
    from ..plan import expressions as E
    out: set = set()

    def walk(e):
        if isinstance(e, E.ColumnRef):
            out.add(e.name)
        for c in getattr(e, "children", ()) or ():
            if isinstance(c, E.Expression):
                walk(c)
        body = getattr(e, "body", None)
        if isinstance(body, E.Expression):
            walk(body)
    for e in exprs:
        if isinstance(e, E.Expression):
            walk(e)
    return out


def passthrough_positions(db: DeviceBatch, exprs) -> Dict[int, int]:
    """Output position -> input position for projection expressions that
    are plain (possibly aliased) references to STILL-DEFERRED columns: a
    thin-aware projection passes those through as placeholders with
    remapped lane bookkeeping instead of materializing them.  Duplicate
    input names are ambiguous (column_by_name semantics) and never pass
    through."""
    from ..plan import expressions as E
    ts = db.thin
    out: Dict[int, int] = {}
    if ts is None:
        return out
    counts: Dict[str, int] = {}
    for n in db.names:
        counts[n] = counts.get(n, 0) + 1
    pending_by_name = {db.names[p]: p for p in ts.pending
                       if counts[db.names[p]] == 1}
    for oi, e in enumerate(exprs):
        inner = e.children[0] if isinstance(e, E.Alias) else e
        if isinstance(inner, E.ColumnRef):
            p = pending_by_name.get(inner.name)
            if p is not None:
                out[oi] = p
    return out


def materialize_refs(db: DeviceBatch, exprs, conf: TpuConf = DEFAULT_CONF
                     ) -> DeviceBatch:
    """Materialize exactly the deferred columns the expressions
    reference (forced early materialization of just those columns);
    unreferenced deferred columns stay thin."""
    if db.thin is None:
        return db
    refs = expr_column_refs(exprs)
    positions = [p for p in db.thin.pending if db.names[p] in refs]
    if not positions:
        return db
    return materialize_batch(db, conf, positions)


def materialize_needed(db: DeviceBatch, exprs, conf: TpuConf = DEFAULT_CONF
                       ) -> DeviceBatch:
    """Sink-side materialization that also DROPS dead columns: deferred
    columns the expressions reference materialize through their lanes;
    the rest become all-null dense columns (never gathered) so
    prefix/concat machinery downstream sees a plain dense batch."""
    if db.thin is None:
        return db
    db = materialize_refs(db, exprs, conf)
    ts = db.thin
    if ts is None:
        return db
    cols = list(db.columns)
    for pos, (s, c) in ts.pending.items():
        src_col = ts.sources[s].batch.columns[c]
        cap = ts.capacity
        cols[pos] = DeviceColumn(
            jnp.zeros((cap,), src_col.data.dtype),
            jnp.zeros((cap,), bool), src_col.dtype, src_col.dictionary,
            None if src_col.data_hi is None
            else jnp.zeros((cap,), jnp.int64))
    return DeviceBatch(cols, db.num_rows, db.names, db.origin_file,
                       sel=db.sel)


def compact_thin(db: DeviceBatch, keep: jax.Array,
                 conf: TpuConf = DEFAULT_CONF) -> DeviceBatch:
    """Compact a THIN batch: materialized columns move through the
    compaction order as usual; each deferred column is gathered ONCE,
    straight from its source into compacted position (the lane composes
    with the order — no materialize-then-compact double pass)."""
    from ..ops.filter import (compaction_order, grouped_take,
                              pallas_compact_order)
    ts = db.thin
    assert ts is not None
    order = pallas_compact_order(keep, conf)
    if order is None:
        order = compaction_order(keep)
    count = jnp.sum(keep, dtype=jnp.int32)
    live_out = jnp.arange(db.capacity, dtype=jnp.int32) < count
    out_cols: List[Optional[DeviceColumn]] = [None] * len(db.columns)
    # materialized columns: the ordinary stacked compact gather
    mat = [i for i in range(len(db.columns)) if i not in ts.pending]
    if mat:
        lanes, slots = [], []
        for i in mat:
            c = db.columns[i]
            lanes.append(c.data)
            slots.append((i, "d"))
            lanes.append(c.validity)
            slots.append((i, "v"))
            if c.data_hi is not None:
                lanes.append(c.data_hi)
                slots.append((i, "h"))
        moved = grouped_take(lanes, order)
        got = {slot: arr for slot, arr in zip(slots, moved)}
        for i in mat:
            c = db.columns[i]
            out_cols[i] = DeviceColumn(got[(i, "d")],
                                       got[(i, "v")] & live_out,
                                       c.dtype, c.dictionary,
                                       got.get((i, "h")))
    # deferred columns: compose lane through the order, gather once
    by_src: Dict[int, List[Tuple[int, int]]] = {}
    for pos, (s, c) in ts.pending.items():
        by_src.setdefault(s, []).append((pos, c))
    for s, items in sorted(by_src.items()):
        src = ts.sources[s]
        composed = jnp.where(live_out,
                             jnp.take(src.lane, order), jnp.int32(-1))
        gathered = gather_deferred(src, [c for _p, c in items], live_out,
                                   lane=composed)
        for (pos, _c), col in zip(items, gathered):
            out_cols[pos] = col
    return DeviceBatch(out_cols, count, db.names, db.origin_file)
